#!/usr/bin/env python
"""Driver benchmark: one JSON line on stdout.

On a single real TPU chip the distributed overlap cannot be exercised, so the
headline single-chip metric is the framework's MXU matmul pipeline (the inner
loop of AG-GEMM / GEMM-RS, tutorial-07 shapes: hidden=7168 bf16) measured as
TFLOP/s against the XLA ``jnp.matmul`` baseline.  ``vs_baseline`` is the
throughput ratio (>= 1.0 means our Pallas pipeline matches XLA's own GEMM).

With more than one device available, the fused AG-GEMM benchmark runs
instead: overlapped AllGather+GEMM wall-time vs the non-overlapped
``jax.lax.all_gather`` + ``jnp.matmul`` baseline (BASELINE.json target:
>= 90% of compute throughput with the collective fully hidden).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp


def _bench(fn, iters=16, warmup=3):
    """Per-iteration seconds (slope timing — see core.utils.perf_func)."""
    from triton_distributed_tpu.core.utils import perf_func

    _, ms = perf_func(fn, iters=iters, warmup_iters=warmup)
    return ms / 1e3


def bench_single_chip():
    from triton_distributed_tpu.ops.matmul import matmul

    m = n = k = 7168  # tutorial-07 hidden size, square problem
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), dtype=jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (k, n), dtype=jnp.bfloat16)

    flops = 2.0 * m * n * k
    t_ours = _bench(lambda: matmul(a, b))
    t_xla = _bench(lambda: jnp.matmul(a, b))
    tflops = flops / t_ours / 1e12
    return {
        "metric": "single_chip_gemm_7168_bf16",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        "vs_baseline": round(t_xla / t_ours, 4),
    }


def bench_multi_chip():
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.ops.ag_gemm import ag_gemm

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    m, k, n = 4096, 7168, 7168  # e2e_dense.md MLP M=4096 shape
    key = jax.random.PRNGKey(0)
    a = mesh_lib.shard(
        mesh, jax.random.normal(key, (m, k), dtype=jnp.bfloat16), "tp", None
    )
    b = mesh_lib.shard(
        mesh,
        jax.random.normal(jax.random.fold_in(key, 1), (k, n), dtype=jnp.bfloat16),
        None,
        "tp",
    )

    t_fused = _bench(lambda: ag_gemm(a, b, mesh))

    @jax.jit
    def baseline(a, b):
        ag = jax.lax.with_sharding_constraint(
            a, mesh_lib.replicated(mesh)
        )
        return jnp.matmul(ag, b, preferred_element_type=jnp.float32).astype(a.dtype)

    t_base = _bench(lambda: baseline(a, b))
    tflops = 2.0 * m * n * k / ntp / t_fused / 1e12
    return {
        "metric": f"ag_gemm_m{m}_k{k}_n{n}_tp{ntp}",
        "value": round(tflops, 2),
        "unit": "TFLOP/s/chip",
        "vs_baseline": round(t_base / t_fused, 4),
    }


def main():
    if jax.device_count() > 1:
        result = bench_multi_chip()
    else:
        result = bench_single_chip()
    print(json.dumps(result))


if __name__ == "__main__":
    main()
