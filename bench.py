#!/usr/bin/env python
"""Driver benchmark: one JSON line per metric on stdout.

``auto`` sweeps the whole single-chip perf surface — GEMM at three shape
classes, flash attention, split-KV decode, the TP MLP layer, and the grouped
(MoE) matmul — emitting one JSON line each, headline GEMM first.  The
headline single-chip metric is the framework's MXU matmul pipeline (the inner
loop of AG-GEMM / GEMM-RS, tutorial-07 shapes: hidden=7168 bf16) measured as
TFLOP/s against the XLA ``jnp.matmul`` baseline.  ``vs_baseline`` is the
throughput ratio (>= 1.0 means our Pallas pipeline matches XLA's own GEMM).

With more than one device available, the fused AG-GEMM benchmark runs
instead: overlapped AllGather+GEMM wall-time vs the non-overlapped
``jax.lax.all_gather`` + ``jnp.matmul`` baseline (BASELINE.json target:
>= 90% of compute throughput with the collective fully hidden).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp


def _bench_pair(ours_fn, base_fn, aliased: bool, **kw) -> dict:
    """Two-engine interleaved measurement, except for ALIASED pairs
    (baseline = the same executable): there a second engine re-measures
    the identical program for nothing — the ratio is definitional and
    baseline_value is the same measurement — so one engine runs and its
    samples serve both keys."""
    if aliased:
        times = _bench_interleaved({"ours": ours_fn}, **kw)
        times["xla"] = times["ours"]
        return times
    return _bench_interleaved({"ours": ours_fn, "xla": base_fn}, **kw)


def _bench_interleaved(engines: dict, iters: int = 64, rounds: int = 9,
                       window_s: float = 0.15) -> dict:
    """Per-engine per-round seconds/iter, measured in interleaved rounds.

    Returns ``{name: [(slope_sec, raw_sec), ...]}`` per post-ramp round
    (NaN slope for rounds where sync noise swamped it).  The tunneled
    chip's absolute throughput drifts by up to 3x between process
    invocations (throttling/contention), so engine-vs-engine ratios are
    only meaningful when the engines are timed alternately within one
    process.  ABSOLUTE numbers use the slope estimator (unbiased;
    cancels the fixed sync/tunnel cost); RATIOS use the raw long-window
    estimator (the shared sync cost is common mode, so near-tie ratios
    read 1.0 instead of the slope's +-3% self-noise — see
    core.utils.interleaved_time_samples).  The first round lands on the
    post-compile thermal ramp and is discarded.
    """
    from triton_distributed_tpu.core.utils import (
        interleaved_time_samples, sync, timed_run,
    )

    for fn in engines.values():  # warmup/compile
        sync(fn())
    # auto-raise each engine's trip count to the target timing window: a
    # fixed iter count leaves fast kernels with jitter-sized windows when
    # the chip is in a slow state (measured: the attention kernel read 20
    # TFLOP/s on a 50 ms window and 90+ on calibrated windows, same code)
    raw = interleaved_time_samples(engines, iters, rounds,
                                   target_window_s=window_s)
    times = {
        name: [(s if s > 0 else float("nan"), r) for s, r in xs]
        for name, xs in raw.items()
    }
    for name in engines:
        if len(times[name]) > 1:
            times[name] = times[name][1:]  # drop the ramp round
    for name, fn in engines.items():
        if not any(s == s for s, _ in times[name]):
            # pathological noise: fall back to amortized timing, one big run
            t = timed_run(fn, iters) / iters
            times[name] = [(t, t)]
    return times


def _median(xs) -> float:
    """Median of the SLOPE samples (absolute per-iter seconds)."""
    xs = sorted(s for s, _ in xs if s == s and s > 0)
    return xs[len(xs) // 2] if xs else float("nan")


def _median_ratio(times: dict, num: str, den: str) -> float:
    """Median of per-round num/den RAW-window time ratios —
    round-adjacent measurements share the chip's thermal state, and the
    raw estimator's shared fixed cost cancels in the ratio (the slope
    estimator's independent calibration noise gave identical engines a
    +-3% captured spread)."""
    pairs = [(a[1], b[1]) for a, b in zip(times[num], times[den])
             if a[1] > 0 and b[1] > 0]
    rs = sorted(a / b for a, b in pairs)
    return rs[len(rs) // 2] if rs else float("nan")


def _pair_fields(times: dict, ours: str, base: str, work: float,
                 unit_scale: float, aliased: bool, crowned) -> dict:
    """The shared tail fields of every ours-vs-baseline metric line.

    ``vs_baseline`` is the RAW-window ratio; ``baseline_value`` is the
    baseline's SLOPE-median absolute (``work`` units of work per second,
    divided by ``unit_scale`` — 1e12 for TFLOP/s, 1e9 for GB/s).  The two
    estimators answer different questions (unbiased absolute vs
    common-mode-cancelled comparison) and MUST NOT be combined:
    ``value / vs_baseline`` is NOT the baseline's throughput — the r04
    record's "1,062 GB/s implied decode baseline" was exactly that
    cross-estimator arithmetic.  ``baseline_value`` is the number the
    claims gate sanity-checks against physical ceilings instead.
    ``crowned`` records which backend the fresh tune picked;
    ``baseline_aliased`` whether the baseline is literally the same
    executable (ratio = definitional parity, not a measured win)."""
    if aliased:
        # same executable on both sides: the ratio is DEFINITIONALLY 1.0.
        # Timing it instead reports window asymmetry — an aliased pair
        # has read 0.85-1.05 "self-ratios" in oscillating chip states,
        # which is measurement artifact, not information.
        ratio = 1.0
    else:
        ratio = round(_median_ratio(times, base, ours), 4)
    return {
        "vs_baseline": ratio,
        "baseline_value": round(work / _median(times[base]) / unit_scale, 2),
        "baseline_aliased": bool(aliased),
        "crowned": str(crowned),
    }


def bench_single_chip(m: int = 7168, n: int = 7168, k: int = 7168,
                      rounds: int = 15):
    # default: tutorial-07 hidden size, square problem
    from triton_distributed_tpu.tune import autotuner as tune

    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (m, k), dtype=jnp.bfloat16)
    b = jax.random.normal(jax.random.fold_in(key, 1), (k, n), dtype=jnp.bfloat16)

    # crown the backend IN THIS PROCESS before the timed rounds: which
    # backend wins is partly a chip-state property, and a winner inherited
    # from another invocation's state is what regressed the round-3 record
    from triton_distributed_tpu.ops.matmul import _xla_matmul_fn, matmul_callable

    crowned = tune.fresh_tune_matmul(a, b)
    ours = matmul_callable(a, b)   # the resolved executable, no per-call
    flops = 2.0 * m * n * k        # Python (it skews sub-ms windows)
    xla = jax.jit(lambda a, b: jnp.matmul(a, b))
    aliased = ours is _xla_matmul_fn(0, jnp.dtype(a.dtype))
    # aliased = the crowned backend IS the plain XLA dot: one executable,
    # measured once, serving value AND baseline_value; the ratio is the
    # definitional 1.0 (see _bench_pair/_pair_fields)
    # 15 rounds: the tunneled chip's round-to-round drift makes the
    # 9-round median swing ~±10%; extra rounds tighten the headline number
    times = _bench_pair(lambda: ours(a, b), lambda: xla(a, b), aliased,
                        rounds=rounds, window_s=0.4)
    tflops = flops / _median(times["ours"]) / 1e12
    name = ("single_chip_gemm_7168_bf16" if m == n == k == 7168
            else f"single_chip_gemm_m{m}_n{n}_k{k}_bf16")
    return {
        "metric": name,
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        **_pair_fields(times, "ours", "xla", flops, 1e12, aliased, crowned),
    }


def _interpret_capture() -> bool:
    """Whether this capture runs under CPU interpret mode (functional
    smoke, not timing): the record carries the flag so the claims gate
    never hard-gates simulated numbers (scripts/check_perf_claims.py)."""
    try:
        from triton_distributed_tpu.core import compilation

        return bool(compilation.interpret_mode())
    except Exception:
        return False


def _ag_gemm_operands(mesh, m, k, n):
    """The shared (a sharded, b sharded, a replicated) operand set of the
    multi-chip AG-GEMM benches — one definition so both metrics measure
    the same problem."""
    from triton_distributed_tpu.core import mesh as mesh_lib

    key = jax.random.PRNGKey(0)
    a_host = jax.random.normal(key, (m, k), dtype=jnp.bfloat16)
    a = mesh_lib.shard(mesh, a_host, "tp", None)
    b = mesh_lib.shard(
        mesh,
        jax.random.normal(jax.random.fold_in(key, 1), (k, n), dtype=jnp.bfloat16),
        None,
        "tp",
    )
    a_full = mesh_lib.shard(mesh, a_host, None, None)
    return a, b, a_full


def bench_multi_chip():
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.ops.ag_gemm import ag_gemm

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    m, k, n = 4096, 7168, 7168  # e2e_dense.md MLP M=4096 shape
    a, b, _ = _ag_gemm_operands(mesh, m, k, n)

    @jax.jit
    def baseline(a, b):
        ag = jax.lax.with_sharding_constraint(
            a, mesh_lib.replicated(mesh)
        )
        return jnp.matmul(ag, b, preferred_element_type=jnp.float32).astype(a.dtype)

    times = _bench_interleaved({
        "fused": lambda: ag_gemm(a, b, mesh),
        "base": lambda: baseline(a, b),
    })
    tflops = 2.0 * m * n * k / ntp / _median(times["fused"]) / 1e12
    return {
        "metric": f"ag_gemm_m{m}_k{k}_n{n}_tp{ntp}",
        "value": round(tflops, 2),
        "unit": "TFLOP/s/chip",
        "vs_baseline": round(_median_ratio(times, "base", "fused"), 4),
        "devices": jax.device_count(),
        "interpret": _interpret_capture(),
    }


def bench_attention():
    """Flash-attention kernel vs XLA's dot-product attention, prefill
    shapes (B=1, H=32, S=4096, D=128 — an 8B-class layer)."""
    from triton_distributed_tpu.ops.attention import flash_attention

    b, h, s, d = 1, 32, 4096, 128
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)

    @jax.jit
    def xla_attn(q, k, v):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
        s_ = s_ * (d ** -0.5)
        mask = jnp.tril(jnp.ones((s, s), bool))
        s_ = jnp.where(mask, s_, -jnp.inf)
        p = jax.nn.softmax(s_, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    from triton_distributed_tpu.tune import autotuner as tune

    crowned = tune.fresh_tune_flash_attention(q, k, v, causal=True)
    # jitted wrapper: resolves the tuned blocks from the winner cache
    # under tracing; the timed loop pays one jit dispatch per call
    ours = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    times = _bench_interleaved({
        "ours": lambda: ours(q, k, v),
        "xla": lambda: xla_attn(q, k, v),
    }, iters=32)
    # causal flash does ~half the full-matrix FLOPs; count the real work
    flops = 4.0 * b * h * s * s * d / 2
    tflops = flops / _median(times["ours"]) / 1e12
    return {
        "metric": f"flash_attn_b{b}_h{h}_s{s}_d{d}",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        # the baseline materializes the S x S score matrix (different
        # work/byte profile than the flash kernel): its TFLOP/s absolute
        # uses the SAME flop count, i.e. useful-work throughput
        **_pair_fields(times, "ours", "xla", flops, 1e12, False, crowned),
    }


def bench_tp_mlp():
    """TP MLP layer forward (AG-GEMM -> SwiGLU -> GEMM-RS) vs the
    XLA-collective layer (all_gather + matmul + psum_scatter).  With one
    real chip the mesh degenerates to tp=1 (both paths local); on a slice
    it exercises the fused overlap end to end."""
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.layers import TPMLP

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    m, k, i = 4096, 7168, 7168  # e2e_dense MLP shapes
    layer = TPMLP(mesh)
    params = layer.init(jax.random.key(0), k, i, dtype=jnp.bfloat16)
    x = mesh_lib.shard(
        mesh, jax.random.normal(jax.random.key(1), (m, k), jnp.bfloat16),
        "tp", None,
    )

    gate_up, down = params.gate_up, params.down

    @jax.jit
    def baseline(x, gu, dn):
        xg = jax.lax.with_sharding_constraint(x, mesh_lib.replicated(mesh))
        hkt = jnp.matmul(xg, gu, preferred_element_type=jnp.float32)
        # gate_up is rank-blocked [gate_r | up_r] per rank: split per block,
        # not down the global middle (same layout _act_combine consumes)
        t = hkt.astype(x.dtype).reshape(m, ntp, 2, i // ntp)
        h = (jax.nn.silu(t[:, :, 0]) * t[:, :, 1]).reshape(m, i)
        out = jnp.matmul(h, dn, preferred_element_type=jnp.float32)
        return jax.lax.with_sharding_constraint(
            out.astype(x.dtype), mesh_lib.sharding(mesh, "tp", None)
        )

    fused = jax.jit(lambda p, x: layer.forward(p, x))
    times = _bench_interleaved({
        "fused": lambda: fused(params, x),
        "base": lambda: baseline(x, gate_up, down),
    }, iters=8)
    flops = 2.0 * m * k * i * 3 / ntp   # gate + up + down per chip
    tflops = flops / _median(times["fused"]) / 1e12
    return {
        "metric": f"tp_mlp_m{m}_k{k}_i{i}_tp{ntp}",
        "value": round(tflops, 2),
        "unit": "TFLOP/s/chip",
        **_pair_fields(times, "fused", "base", flops, 1e12, False,
                       "layer.forward"),
    }


def bench_group_gemm():
    """Tile-scheduled Pallas grouped matmul vs XLA's ``lax.ragged_dot``
    (MoE up-projection shapes: T=8192 routed rows, 8 local experts,
    7168 -> 2048 bf16, uneven splits)."""
    from triton_distributed_tpu.ops.group_gemm import grouped_matmul

    t, k, n, e = 8192, 7168, 2048, 8
    kx, kw = jax.random.split(jax.random.key(0))
    x = jax.random.normal(kx, (t, k), jnp.bfloat16)
    w = jax.random.normal(kw, (e, k, n), jnp.bfloat16)
    splits = jnp.asarray([2048, 512, 1536, 0, 1024, 1408, 640, 1024],
                         jnp.int32)

    # crown the backend in this process (see bench_single_chip), then hold
    # the resolved jitted callable: a crowned XLA backend runs as its own
    # computation carrying its compile options, and the timed loop pays no
    # per-call Python
    from triton_distributed_tpu.ops.group_gemm import (
        _xla_ragged_fn, grouped_matmul_callable,
    )
    from triton_distributed_tpu.tune import autotuner as tune

    crowned = tune.fresh_tune_grouped_matmul(x, w, splits)
    ours = grouped_matmul_callable(x, w, splits)
    ragged = jax.jit(lambda x, w, s: jax.lax.ragged_dot(x, w, s))
    aliased = ours is _xla_ragged_fn(0, jnp.dtype(x.dtype))
    # aliased: same-HLO single-engine measurement, see bench_single_chip
    times = _bench_pair(lambda: ours(x, w, splits),
                        lambda: ragged(x, w, splits), aliased,
                        iters=16, window_s=0.4)
    flops = 2.0 * t * k * n
    tflops = flops / _median(times["ours"]) / 1e12
    return {
        "metric": f"group_gemm_t{t}_k{k}_n{n}_e{e}",
        "value": round(tflops, 2),
        "unit": "TFLOP/s",
        **_pair_fields(times, "ours", "xla", flops, 1e12, aliased, crowned),
    }


def bench_decode():
    """Split-KV decode attention vs XLA's unfused GQA decode (B=8 tokens
    against an 8k cache, 32/8 heads, d=128 — a serving decode step).

    Both engines are KV-bandwidth bound, so both absolutes (``value`` and
    ``baseline_value``) are achieved GB/s of cache read and BOTH must sit
    below the chip's HBM ceiling — the claims gate enforces that, which
    is what catches an estimator-mixing or cache artifact in the capture
    (the r04 record implied a 1,062 GB/s baseline on an 819 GB/s part by
    dividing a slope absolute by a raw-window ratio)."""
    from triton_distributed_tpu.ops.attention import (
        _xla_decode_fn, decode_attention,
    )
    from triton_distributed_tpu.tune import autotuner as tune

    b, h, hk, s, d = 8, 32, 8, 8192, 128
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.bfloat16)

    # the op's own never-lose XLA dispatch target doubles as the bench
    # baseline (kv_len = s: the mask is all-valid, same program shape the
    # reference baseline uses)
    xla_fn = _xla_decode_fn(b, h, hk, s, d, d ** -0.5, 0.0,
                            jnp.dtype(q.dtype))
    crowned = tune.fresh_tune_decode(q, k, v, s)
    aliased = isinstance(crowned, tune.XlaBackend)
    ours = jax.jit(lambda q, k, v: decode_attention(q, k, v, s))
    # aliased: the crowned backend IS the unfused XLA decode — same-HLO
    # single-engine measurement, see bench_single_chip
    times = _bench_pair(lambda: ours(q, k, v),
                        lambda: xla_fn(q, k, v, s), aliased,
                        iters=48, window_s=0.4)
    # decode is KV-bandwidth bound; report achieved GB/s of cache read
    nbytes = 2 * b * hk * s * d * 2
    gbps = nbytes / _median(times["ours"]) / 1e9
    return {
        "metric": f"decode_attn_b{b}_h{h}_hk{hk}_s{s}_d{d}",
        "value": round(gbps, 1),
        "unit": "GB/s",
        **_pair_fields(times, "ours", "xla", nbytes, 1e9, aliased, crowned),
    }


_EMIT_FAILED = False
# metric names the sweep actually printed: the sentinel carries these so
# the claims gate can distinguish a tail-truncated head line from a
# crashed bench mode (scripts/check_perf_claims.py completeness check)
_EMITTED: list = []

# on-disk tee of the full `auto` JSONL stream (VERDICT r5 next #1): the
# driver envelope keeps only the last N bytes of stdout, so head lines
# can be truncated away; the LOCAL record is complete by construction
# and the claims gate prefers it over the envelope tail when committed
_LOCAL_SINK = None

# the round this capture will become (newest committed BENCH_r*.json +
# 1): stamped into every emitted record line so the perf-trajectory
# sentinel (scripts/bench_history.py) can place a stray/renamed record
# file without trusting its filename
_ROUND = None


def _next_round() -> int:
    """Round numbering by plain glob over the committed envelopes —
    deliberately NOT via the claims module, whose bugs must not break a
    capture (same rationale as _open_local_record)."""
    import glob
    import os
    import re

    root = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append(int(m.group(1)))
    return max(rounds) + 1 if rounds else 1


def _record_line(line: str) -> None:
    """Emit one JSONL record line to stdout (the driver captures its
    tail) AND to the on-disk local record when one is open."""
    print(line, flush=True)
    if _LOCAL_SINK is not None:
        _LOCAL_SINK.write(line + "\n")
        _LOCAL_SINK.flush()


def _open_local_record() -> None:
    """Open ``BENCH_LOCAL_rNN.jsonl`` next to the committed records,
    NN = the round this capture will become (newest committed
    ``BENCH_r*.json`` + 1, zero-padded, by a plain glob — deliberately
    NOT via the claims module, whose bugs must not break a capture).
    ``TDT_BENCH_LOCAL`` overrides the path; ``0``/``off`` disables the
    tee.  Any failure here is non-fatal — stdout (the envelope path)
    still carries the stream."""
    import os
    import sys
    import traceback

    global _LOCAL_SINK, _ROUND
    try:
        _ROUND = _next_round()
        env = os.environ.get("TDT_BENCH_LOCAL", "")
        if env.lower() in ("0", "off", "false", "no"):
            return
        root = os.path.dirname(os.path.abspath(__file__))
        if env:
            path = env
        else:
            path = os.path.join(root, f"BENCH_LOCAL_r{_ROUND:02d}.jsonl")
        _LOCAL_SINK = open(path, "w")
    except Exception:
        traceback.print_exc(file=sys.stderr)
        _LOCAL_SINK = None


_CLAIMS_MODULE = None


def _load_claims_module():
    global _CLAIMS_MODULE
    if _CLAIMS_MODULE is None:
        import importlib.util
        import os

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "check_perf_claims.py")
        spec = importlib.util.spec_from_file_location("_cpc_bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _CLAIMS_MODULE = mod
    return _CLAIMS_MODULE


def _emit(fn, *args, **kw):
    """Run one bench and print its JSON line immediately (partial results
    survive a later mode crashing / the driver timing out).

    A capture that would VIOLATE its primary claim (floor/ceiling in the
    claims registry) gets ONE retry: the chip throttles transiently
    (observed: a mid-sweep dip pulled even the crowned backend to 131
    TF/s while the same sweep's dense GEMM read 189), and a floor claim
    asserts the kernel's capability, not the thermal luck of one draw.
    The retry is SYMMETRIC (ADVICE r5 low #3): the published ``value``
    is always the first draw, the retry lands as ``retry_value``, and
    the claims gate — not the bench — decides whether a dip-with-
    passing-retry is acceptable; a genuine regression fails both draws
    and the gate stays red."""
    import sys
    import traceback

    global _EMIT_FAILED
    try:
        rec = fn(*args, **kw)
        # the registry consult is guarded NARROWLY: a claims-script bug
        # must not break the capture, but a crash of the retry bench run
        # itself propagates to the outer handler like any mode crash
        claim = cpc = None
        try:
            cpc = _load_claims_module()
            claim = next(
                (c for prefix, c in cpc.CLAIMS.items()
                 if rec.get("metric", "").startswith(prefix)), None,
            )
            fails = (cpc._check_metric(rec, claim)[0]
                     if claim is not None else [])
            # retry ONLY pure floor violations (the thermal-dip class);
            # a ceiling/impossible-baseline failure is a measurement
            # ARTIFACT the gate exists to surface — re-rolling until it
            # passes would hide it, so those records print as-is and
            # the gate goes red
            needs_retry = bool(fails) and all(
                "below the claimed floor" in f for f in fails
            )
        except Exception:
            traceback.print_exc(file=sys.stderr)
            needs_retry = False
        if needs_retry:
            try:
                retry = fn(*args, **kw)
            except Exception:
                # the first attempt is a complete record: partial
                # results must survive a crashed retry
                rec["attempts"] = 2
                rec["retry_crashed"] = True
                if rec.get("metric"):
                    _EMITTED.append(rec["metric"])
                if _ROUND is not None:
                    rec.setdefault("round", _ROUND)
                _record_line(json.dumps(rec))
                raise
            # SYMMETRIC retry (ADVICE r5 low #3): the published value is
            # ALWAYS the first draw — high and low draws get identical
            # treatment, removing the max-of-two bias on floor dips.  The
            # retry rides along as ``retry_value`` and the claims GATE
            # owns the accept/reject decision: a floor dip whose retry
            # clears the floor downgrades to a warning there
            # (scripts/check_perf_claims.py::_check_metric).
            rec["attempts"] = 2
            rec["retry_value"] = retry.get("value")
        if rec.get("metric"):
            _EMITTED.append(rec["metric"])
        if _ROUND is not None:
            # round-id stamp: the trajectory sentinel can place this
            # line without trusting the record file's name
            rec.setdefault("round", _ROUND)
        _record_line(json.dumps(rec))
    except Exception:  # keep the remaining modes alive, but fail the run
        _EMIT_FAILED = True
        traceback.print_exc(file=sys.stderr)


def bench_decode_modes(batch: int = 128):
    """Full-model decode step, psum-reduction mode vs the Pallas fast-AR
    mode (the reference's headline decode win: GEMM + fast AR 1.27-1.37x at
    B=128-4096, ``e2e_dense.md`` "GEMM + AllReduce" table).  On one chip the
    mesh degenerates to tp=1 (both modes local — ratio ~1.0); on a slice the
    ratio measures the fast-AR path end to end.  ``vs_baseline`` =
    psum-mode time / ar-mode time (>1 means the AR kernels win)."""
    import numpy as np

    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    cfg = ModelConfig(
        num_layers=4, hidden=2048, intermediate=4096, num_heads=16,
        num_kv_heads=8, head_dim=128, vocab=8192, max_length=256,
        dtype=jnp.bfloat16,
    )
    engines = {}
    steps = {}
    for mode in ("psum", "ar"):
        eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=batch,
                           decode_mode=mode)
        ids = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab, (batch, 64)),
            jnp.int32,
        )
        eng.prefill(ids)
        tok = jnp.zeros((batch,), jnp.int32)
        engines[mode] = eng
        steps[mode] = lambda eng=eng, tok=tok: eng.decode_step(tok)
    times = _bench_interleaved(steps, iters=16, rounds=9)
    ms = _median(times["ar"]) * 1e3
    return {
        "metric": f"qwen_decode_step_b{batch}_tp{ntp}_psum_vs_ar",
        "value": round(ms, 3),
        "unit": "ms/step (ar mode)",
        "vs_baseline": round(_median_ratio(times, "psum", "ar"), 4),
        # slice-gated claims key on this: at devices>1 the psum/ar ratio
        # is a distributed measurement the gate binds on, at 1 it is
        # definitional parity (scripts/check_perf_claims.py)
        "devices": jax.device_count(),
        "interpret": _interpret_capture(),
        # tp=1 timing is degenerate (both modes local); the wire volume per
        # step is the mode property measurable anywhere — computed from the
        # model shapes for an 8-way tp mesh, per chip, per decode step
        "wire_bytes_per_step": _decode_mode_wire_bytes(cfg, batch, ntp=8),
    }


def bench_fused_decode(batch: int = 128):
    """Full-model decode step, ``decode_mode="fused"`` (the ISSUE-8
    decode megakernel: per-layer attention fused into one kernel on the
    paged cache, MLP/o-proj reductions semaphore-chained) vs the psum
    per-kernel baseline.  ``vs_baseline`` = psum-mode time / fused-mode
    time (>1 means the megakernel wins); ``value`` = ms/step fused.  The
    exposed-wait proof rides the flight timeline
    (``scripts/obs_report.py --timeline fused_mlp_ar``), not this
    record."""
    import numpy as np

    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    cfg = ModelConfig(
        num_layers=4, hidden=2048, intermediate=4096, num_heads=16,
        num_kv_heads=8, head_dim=128, vocab=8192, max_length=256,
        dtype=jnp.bfloat16,
    )
    steps = {}
    for mode in ("psum", "fused"):
        eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=batch,
                           decode_mode=mode, cache_layout="paged")
        ids = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab, (batch, 64)),
            jnp.int32,
        )
        eng.prefill(ids)
        tok = jnp.zeros((batch,), jnp.int32)
        steps[mode] = lambda eng=eng, tok=tok: eng.decode_step(tok)
    times = _bench_interleaved(steps, iters=16, rounds=9)
    ms = _median(times["fused"]) * 1e3
    return {
        "metric": f"decode_ms_per_token_fused_b{batch}_tp{ntp}",
        "value": round(ms, 3),
        "unit": "ms/step (fused mode)",
        "vs_baseline": round(_median_ratio(times, "psum", "fused"), 4),
        "devices": jax.device_count(),
        "interpret": _interpret_capture(),
    }


def bench_decode_dispatches(batch: int = 8):
    """Static per-decode-step kernel-dispatch count, fused vs the
    per-kernel chain (``ops.fused_decode.count_decode_dispatches``):
    pallas launches, MXU GEMMs, cache scatters and cross-rank
    reductions in one traced step.  Deterministic in (shapes, tp) — the
    ISSUE-8 acceptance number (>= 2x reduction on a slice, where the
    per-kernel chain also pays its two reductions per layer), and the
    completeness anchor for the fused family in every round."""
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import (
        Engine, ModelConfig, Qwen3,
    )
    from triton_distributed_tpu.ops import count_decode_dispatches

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    cfg = ModelConfig(
        num_layers=4, hidden=2048, intermediate=4096, num_heads=16,
        num_kv_heads=8, head_dim=128, vocab=8192, max_length=256,
        dtype=jnp.bfloat16,
    )
    eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=batch,
                       cache_layout="paged")
    tok = jnp.zeros((batch,), jnp.int32)
    counts = {}
    for mode in ("psum", "fused"):
        model = Qwen3(cfg, mesh, decode_mode=mode)
        counts[mode] = count_decode_dispatches(
            model, eng.params, eng.cache, tok)
    return {
        "metric": f"decode_step_dispatches_b{batch}_L{cfg.num_layers}"
                  f"_tp{ntp}",
        "value": round(counts["psum"] / max(counts["fused"], 1), 3),
        "unit": "x fewer dispatches (psum chain / fused)",
        "dispatches_fused": counts["fused"],
        "dispatches_unfused": counts["psum"],
        "devices": jax.device_count(),
    }


def bench_persistent_dispatches(batch: int = 8, steps: int = 4):
    """Static dispatch count of one PERSISTENT step bundle
    (``ops.persistent_decode.count_bundle_dispatches``): with
    ``decode_mode="persistent"`` the bundle is ONE megakernel launch +
    the lm_head GEMM per token window — the ISSUE-13 acceptance number
    (<= 2 per step bundle, down from 2/layer), claims-gated on slices
    where the collective megakernel actually builds (tp=1 runs the
    pure-XLA reference whose dot chain is the honest count there).
    ``dispatches_per_token_psum`` carries the per-kernel chain's count
    for the same model as the before number."""
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig, Qwen3
    from triton_distributed_tpu.ops import count_bundle_dispatches

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    cfg = ModelConfig(
        num_layers=4, hidden=2048, intermediate=4096, num_heads=16,
        num_kv_heads=8, head_dim=128, vocab=8192, max_length=256,
        dtype=jnp.bfloat16,
    )
    eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=batch,
                       cache_layout="paged")
    tok = jnp.zeros((batch,), jnp.int32)
    counts = {}
    for mode in ("psum", "persistent"):
        model = Qwen3(cfg, mesh, decode_mode=mode)
        counts[mode] = count_bundle_dispatches(
            model, eng.params, eng.cache, tok, steps)
    return {
        "metric": f"decode_dispatches_per_bundle_b{batch}"
                  f"_L{cfg.num_layers}_s{steps}_tp{ntp}",
        # scan bodies count once, so the traced bundle count IS the
        # per-step-bundle dispatch number the claim binds
        "value": counts["persistent"],
        "unit": "dispatches/bundle (persistent)",
        "dispatches_per_token_psum": counts["psum"],
        "steps_per_dispatch": steps,
        "devices": jax.device_count(),
    }


def bench_persistent_decode(batch: int = 128, steps: int = 8):
    """Persistent multi-step serving decode (ISSUE 13): ONE
    ``decode_multi`` dispatch of ``steps`` tokens through the persistent
    megakernel vs ``steps`` per-token dispatches of the psum per-kernel
    chain — the production before/after.  ``value`` = ms/token
    persistent; ``vs_baseline`` = psum per-token time / persistent
    per-token time (>1 means the device-resident loop wins).  The
    exposed-wait story rides the flight timeline
    (``scripts/obs_report.py --timeline persistent_decode``)."""
    import numpy as np

    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    cfg = ModelConfig(
        num_layers=4, hidden=2048, intermediate=4096, num_heads=16,
        num_kv_heads=8, head_dim=128, vocab=8192, max_length=256,
        dtype=jnp.bfloat16,
    )
    thunks = {}
    for mode in ("psum", "persistent"):
        eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=batch,
                           decode_mode=mode, cache_layout="paged")
        ids = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab, (batch, 64)),
            jnp.int32,
        )
        eng.prefill(ids)
        tok = jnp.zeros((batch,), jnp.int32)
        if mode == "persistent":
            multi = jax.jit(eng.model.decode_multi, static_argnums=3)

            def run_p(eng=eng, tok=tok, multi=multi):
                # STATEFUL like the psum loop below: both modes advance
                # (and clamp at) the same sequence lengths, so neither
                # is measured on less attention work than the other
                toks, eng.cache = multi(eng.params, eng.cache, tok, steps)
                return toks

            thunks[mode] = run_p
        else:
            def run_s(eng=eng, tok=tok):
                out = None
                for _ in range(steps):
                    out = eng.decode_step(tok)
                return out

            thunks[mode] = run_s
    times = _bench_interleaved(thunks, iters=8, rounds=9)
    ms = _median(times["persistent"]) * 1e3 / steps
    return {
        "metric": f"decode_ms_per_token_persistent_b{batch}_s{steps}"
                  f"_tp{ntp}",
        "value": round(ms, 3),
        "unit": "ms/token (persistent bundle)",
        "vs_baseline": round(_median_ratio(times, "psum", "persistent"), 4),
        "devices": jax.device_count(),
        "interpret": _interpret_capture(),
    }


def _decode_mode_wire_bytes(cfg, batch: int, ntp: int) -> dict:
    """Per-chip wire bytes one decode step moves through its row-parallel
    reductions (o-proj + MLP down-proj per layer) in each ``decode_mode``,
    at ``ntp`` tensor-parallel ranks.

    psum: XLA's collective — canonical bandwidth-optimal ring allreduce,
    2(n-1)/n * nbytes.  ar: ``comm.allreduce`` one-shot ((n-1) * nbytes
    pushed per chip, one hop — the latency choice the reference makes at
    decode sizes) vs fused two-shot (2(n-1)/n, ring); BOTH are reported
    because the static ``choose_method`` pick (also recorded, as
    ``ar_auto``) can be overridden by a measured tuner at runtime — and
    the bench shape sits exactly on the one-shot byte threshold.
    gemm_ar: fused GEMM+RS ring then AG ring = 2(n-1)/n.  Verified
    mode-parity (same outputs) on the 8-mesh by
    ``tests/test_qwen_engine.py``; the dryrun exercises all three."""
    from triton_distributed_tpu.comm.allreduce import choose_method

    nbytes = batch * cfg.hidden * 2          # one (B, H) bf16 reduction
    n_red = 2 * cfg.num_layers               # o-proj + down-proj per layer
    ring = 2 * (ntp - 1) / ntp * nbytes
    one_shot = (ntp - 1) * nbytes
    return {
        "ntp": ntp,
        "psum": int(ring * n_red),
        "ar_one_shot": int(one_shot * n_red),
        "ar_two_shot": int(ring * n_red),
        "ar_auto": choose_method(nbytes, ntp).value,
        "gemm_ar": int(ring * n_red),
    }


def _fp8_auto_policy() -> dict:
    """Per-wire-class decisions of the fp8 "auto" policy, evaluated on
    probe meshes through the same wire_class the layer consults."""
    from triton_distributed_tpu.core import mesh as mesh_lib

    ici = mesh_lib.wire_class(mesh_lib.tp_mesh(), "tp") == "dcn"
    dcn = mesh_lib.wire_class(
        mesh_lib.make_mesh({"dcn": 1, "tp": jax.device_count()}), "dcn"
    ) == "dcn"
    return {"ici": ici, "dcn": dcn}


def bench_moe_ep_wire(tokens: int = 4096):
    """EP A2A wire cost with the fp8 (e4m3 + scale sidecar) payload vs the
    bf16 payload (the reference's production low-latency A2A config, README
    137 us case).  ``value`` = fp8 wire bytes per token per hop;
    ``vs_baseline`` = bf16_bytes / fp8_bytes (~2.0 = halved).

    The codec is MEASURED, not assumed: pack and unpack are timed on the
    chip at a serving-batch shape and the JSON line carries their
    throughput (``codec_gbps``, input GB/s through pack+unpack) plus the
    NET per-token time win of shipping fp8 — wire time saved minus codec
    cost — against both wire classes: ``net_us_per_token_hop_ici`` (the
    intra-slice torus, where a halved payload saves little and the codec
    may not pay) and ``net_us_per_token_hop_dcn`` (cross-slice EP, where
    it clearly does).  A 10x-slower-than-wire codec shows up as negative
    numbers here, not hidden behind the byte ratio.  Round-trip accuracy
    is asserted at the same shape."""
    import numpy as np

    from triton_distributed_tpu.layers.moe import (
        _FP8_SIDECAR, _pack_fp8, _unpack_fp8,
    )
    from triton_distributed_tpu.tools import perf_model

    h = 7168                       # reference A2A case: hidden=7168
    fp8_bytes = h + _FP8_SIDECAR
    bf16_bytes = 2 * h

    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((tokens, h)) * 0.3,
        jnp.bfloat16,
    )
    pack = jax.jit(_pack_fp8)
    unpack = jax.jit(lambda u8: _unpack_fp8(u8, h, jnp.bfloat16))
    packed = pack(x)
    assert packed.shape == (tokens, fp8_bytes) and packed.dtype == jnp.uint8
    back = unpack(packed)
    err = jnp.abs(back.astype(jnp.float32) - x.astype(jnp.float32)).max()
    assert float(err) < 0.1, f"fp8 wire codec round-trip error {err}"

    times = _bench_interleaved({
        "pack": lambda: pack(x),
        "unpack": lambda: unpack(packed),
    }, iters=32, rounds=7)
    t_codec_s = _median(times["pack"]) + _median(times["unpack"])
    in_bytes = tokens * h * 2
    codec_gbps = in_bytes / t_codec_s / 1e9

    # net win per token per hop: the wire time the smaller payload
    # saves, minus what the codec costs (pack send-side + unpack
    # recv-side).  Reported against BOTH wire classes, because the
    # answer differs: on the ICI torus (~186 GB/s/chip) a halved payload
    # saves so little time that even a fast codec barely pays — the fp8
    # wire's real economics live on the DCN (cross-slice EP, ~12.5 GB/s
    # per chip), where the saving dwarfs the codec.
    codec_s_per_token = t_codec_s / tokens
    saved_bytes = bf16_bytes - fp8_bytes
    ici_gbps = perf_model.chip_spec().ici_gbps
    net_ici = (saved_bytes / (ici_gbps * 1e9) - codec_s_per_token) * 1e6
    net_dcn = (saved_bytes / (perf_model.DCN_GBPS_PER_CHIP * 1e9)
               - codec_s_per_token) * 1e6
    return {
        "metric": f"moe_ep_a2a_fp8_wire_bytes_h{h}",
        "value": fp8_bytes,
        "unit": "bytes/token/hop",
        "vs_baseline": round(bf16_bytes / fp8_bytes, 4),
        "codec_gbps": round(codec_gbps, 1),
        "net_us_per_token_hop_ici": round(net_ici, 4),
        "net_us_per_token_hop_dcn": round(net_dcn, 4),
        # what MoEMLP(fp8_wire="auto") resolves per wire class (the
        # policy the measured nets above justify: codec on the slow
        # cross-slice wire only) — DERIVED from the live policy code
        # (core.mesh.wire_class feeding fp8_wire_enabled), so a policy
        # change reaches the record automatically
        "fp8_auto_policy": _fp8_auto_policy(),
    }


# -- low-precision wire and KV (ISSUE 9) ------------------------------------


def _obs_wire_total(op: str) -> float:
    """Sum of the ``comm_wire_bytes`` counters for ``op`` across method
    labels (the live obs accounting the quantized entries feed)."""
    from triton_distributed_tpu import obs

    return sum(
        c["value"] for c in obs.REGISTRY.snapshot()
        if c.get("name") == "comm_wire_bytes"
        and c.get("labels", {}).get("op") == op)


def _codec_err_ratios(x) -> dict:
    """Worst PER-ROW round-trip error over the quantized wire dtypes as
    a fraction of each row's documented envelope
    (``lang.quant.abs_error_bound`` at the ROW absmax — the bound the
    property tests pin).  Normalizing by the global absmax would let a
    small-absmax row bust its own envelope unnoticed, so the parity
    sentinel measures the per-row quantity.  One home: both wire benches
    record this."""
    from triton_distributed_tpu.lang import quant

    xf = x.astype(jnp.float32)
    row_absmax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    out = {}
    for wd in quant.QUANTIZED_WIRE_DTYPES:
        back = quant.roundtrip_rows(x, wd, out_dtype=jnp.float32)
        bound = quant.abs_error_bound(row_absmax, wd)
        out[wd] = float(jnp.max(jnp.abs(back - xf) / bound))
    return out


def bench_wire_bytes(m: int = 1024, h: int = 7168):
    """Wire bytes of the quantized collective payloads vs bf16 (ISSUE 9
    tentpole): ``value`` = bf16 bytes / quantized bytes per row ("x
    fewer"), hard-floored at 1.82 (<= 0.55x) by the claims gate.

    Measured TWO ways and both recorded: the static packed-message
    accounting (payload byte per element + the 128-lane scale sidecar —
    deterministic, like the MoE fp8 line), and — when a live mesh can
    run the collectives — the ``comm_wire_bytes`` obs counters around a
    real bf16 vs fp8 ``all_gather`` pair, so the recorded ratio is what
    the wire actually moved (slice captures gate on it; the CPU
    container marks records ``interpret``).  Dequant parity at the same
    shape rides along as ``codec_err_vs_envelope_*`` (measured max
    error / the documented envelope — advisory ``warn_max`` 1.0)."""
    import numpy as np

    from triton_distributed_tpu import obs
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.lang import quant

    static_ratio = (2.0 * h) / quant.packed_width(h, "fp8")
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((m, h)) * 0.3,
        jnp.bfloat16,
    )
    # parity: measured per-row round-trip error vs the documented
    # envelope (shared with bench_wire_parity — one home)
    err_ratio = _codec_err_ratios(x)

    measured_ratio = None
    interpret = _interpret_capture()
    mesh = None
    try:
        mesh = mesh_lib.tp_mesh()
    except Exception:
        pass
    if mesh is not None and mesh.shape["tp"] > 1:
        from triton_distributed_tpu import comm

        prev = obs.enabled()
        obs.enable(True)
        try:
            base = _obs_wire_total("all_gather")
            comm.all_gather(x, mesh, "tp")
            bf16_bytes = _obs_wire_total("all_gather") - base
            base = _obs_wire_total("all_gather")
            comm.all_gather(x, mesh, "tp", wire_dtype="fp8")
            q_bytes = _obs_wire_total("all_gather") - base
            if q_bytes > 0:
                measured_ratio = bf16_bytes / q_bytes
        except Exception:
            import sys
            import traceback

            traceback.print_exc(file=sys.stderr)
            interpret = True
        finally:
            obs.enable(prev)
    else:
        interpret = True
    value = measured_ratio if measured_ratio is not None else static_ratio
    return {
        "metric": f"wire_bytes_ratio_bf16_over_quant_h{h}",
        "value": round(value, 4),
        "unit": "x fewer wire bytes (bf16 / quantized)",
        "static_ratio": round(static_ratio, 4),
        "measured_from_counters": measured_ratio is not None,
        "codec_err_vs_envelope_fp8": round(err_ratio["fp8"], 4),
        "codec_err_vs_envelope_int8": round(err_ratio["int8"], 4),
        "devices": jax.device_count(),
        "interpret": interpret,
    }


def bench_wire_parity(m: int = 1024, h: int = 7168):
    """Dequant parity of the wire codecs at the serving shape: ``value``
    = the worst measured round-trip error over {fp8, int8} as a FRACTION
    of the documented envelope (``lang.quant.abs_error_bound`` — the
    dtype-scaled tolerance the parity gates use).  1.0 = exactly at the
    envelope; the claims gate warns (advisory) above 1.05 — codec drift
    is a trend finding for obs.history, the hard guarantees live in the
    checksum plane and the round-trip property tests."""
    import numpy as np

    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((m, h)) * 0.3,
        jnp.bfloat16,
    )
    ratios = _codec_err_ratios(x)
    return {
        "metric": "wire_dequant_parity_err_ratio",
        "value": round(max(ratios.values()), 4),
        "unit": "x of the documented codec error envelope",
        "fp8": round(ratios["fp8"], 4),
        "int8": round(ratios["int8"], 4),
        "devices": jax.device_count(),
    }


def bench_serve_kv_quant():
    """Max concurrent sequences at the SAME pool byte budget, int8 KV vs
    bf16 (the ISSUE 9 acceptance number: >= 1.8x — halved page bytes
    double the page count, which the continuous-batching scheduler
    converts directly into admitted sequences).  Deterministic: two
    seeded scheduler replays over the real paged-cache plumbing
    (SimBackend) whose pools are sized from one byte budget via
    ``kv_cache.kv_page_bytes`` (scale-sidecar overhead included — the
    honest capacity math), peak concurrency read off the step results."""
    from triton_distributed_tpu import serve
    from triton_distributed_tpu.models.kv_cache import kv_page_bytes

    layers, kv_heads, head_dim, page_size = 1, 1, 64, 16
    bf16_page = kv_page_bytes(layers, kv_heads, page_size, head_dim,
                              jnp.bfloat16, None)
    int8_page = kv_page_bytes(layers, kv_heads, page_size, head_dim,
                              jnp.bfloat16, "int8")
    budget = 32 * bf16_page                 # the shared pool byte budget
    pools = {"bf16": (None, 1 + budget // bf16_page),
             "int8": ("int8", 1 + budget // int8_page)}
    slots = 40
    peak = {}
    for name, (kvd, pages) in pools.items():
        backend = serve.SimBackend(
            slots=slots, page_size=page_size, pool_pages=int(pages),
            max_length=64, head_dim=head_dim, kv_dtype=kvd)
        sched = serve.Scheduler(backend, serve.SchedulerConfig(
            max_queue_depth=2 * slots))
        for i in range(slots):
            sched.submit(serve.Request(
                prompt=tuple((7 * i + j) % 97 + 1 for j in range(17)),
                max_new_tokens=12))
        hi = 0
        for _ in range(10_000):
            res = sched.step()
            hi = max(hi, res.active)
            if res.idle:
                break
        if sched.pool.used_pages != 0:      # not assert: survives -O
            raise RuntimeError(
                f"leaked pages in the {name} replay: "
                f"{sched.pool.used_pages}")
        peak[name] = hi
    return {
        "metric": "serve_kv_quant_concurrency",
        "value": round(peak["int8"] / max(peak["bf16"], 1), 4),
        "unit": "x concurrent sequences (int8 pool / bf16 pool, equal bytes)",
        "peak_active_bf16": peak["bf16"],
        "peak_active_int8": peak["int8"],
        "pool_pages_bf16": int(pools["bf16"][1]),
        "pool_pages_int8": int(pools["int8"][1]),
        "page_bytes_bf16": bf16_page,
        "page_bytes_int8": int8_page,
        "devices": jax.device_count(),
    }


# -- continuous-batching serving (ISSUE 6) ----------------------------------

_SERVE_RUN: dict | None = None


def _serve_run(n_requests: int = 64) -> dict:
    """One shared open-loop serving run behind the two serve metrics:
    a seeded arrival trace that overcommits the KV-page budget ~2x
    through the continuous-batching scheduler, with preemption doing
    the absorbing.  Tries the real engine (paged cache, chunked
    prefill); boxes whose jax cannot run the model's shard_map paths
    (the CPU CI container) fall back to the deterministic SimBackend
    and the records are marked ``interpret`` so the claims gate treats
    them as functional smoke, never timing."""
    global _SERVE_RUN
    if _SERVE_RUN is not None:
        return _SERVE_RUN
    import time

    from triton_distributed_tpu import obs, serve
    from triton_distributed_tpu.core import mesh as mesh_lib

    prev_obs = obs.enabled()
    obs.enable(True)
    obs.serve_stats.STATS.reset()
    simulated = False
    vocab = 512
    try:
        from triton_distributed_tpu.models import Engine, ModelConfig

        cfg = ModelConfig(
            num_layers=2, hidden=256, intermediate=512, num_heads=8,
            num_kv_heads=4, head_dim=64, vocab=vocab, max_length=256,
            dtype=jnp.bfloat16,
        )
        eng = Engine.build(cfg, mesh_lib.tp_mesh(), key=jax.random.key(0),
                           batch=8, cache_layout="paged", page_size=16)
        # pool sized to HALF the slots' worst case: the trace overcommits
        sched = eng.scheduler(pool_pages=8 * (256 // 16) // 2 + 1,
                              chunk_tokens=32, max_queue_depth=128)
        # compile the step functions outside the timed replay (the
        # serve analogue of Engine.serve's warmup).  The warm request
        # must COMPLETE: the scheduler's failure isolation would
        # otherwise absorb a backend whose decode path cannot run on
        # this jax (e.g. no shard_map) and the replay would "succeed"
        # with every request failed
        warm = serve.Request(prompt=(1, 2, 3), max_new_tokens=2)
        with obs.suppress():
            sched.submit(warm)
            while not sched.step().idle:
                pass
        if warm.state is not serve.RequestState.DONE:
            raise RuntimeError(
                f"warm request did not complete: {warm.state} "
                f"({warm.error})")
    except Exception:
        import sys
        import traceback

        traceback.print_exc(file=sys.stderr)
        simulated = True
        backend = serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                                   max_length=256, vocab=vocab)
        sched = serve.Scheduler(backend, serve.SchedulerConfig(
            max_queue_depth=128, prefill_chunk_tokens=32))
    arrivals = serve.synthetic_trace(
        0, n_requests, mean_interarrival_steps=0.25,
        prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
    try:
        t0 = time.perf_counter()
        report = serve.replay(sched, arrivals, max_steps=100_000)
        wall_s = time.perf_counter() - t0
    finally:
        # a crashed replay must not leave telemetry enabled for the
        # rest of the sweep (it would perturb later timed modes)
        obs.enable(prev_obs)
    ttft = report.ttft_ms
    toks = sum(len(r.tokens) for r in report.completed)
    _SERVE_RUN = {
        "simulated": simulated,
        "wall_s": wall_s,
        "ttft_ms": ttft,
        "tokens": toks,
        "completed": len(report.completed),
        "failed": len(report.failed),
        "shed": len(report.shed),
        "preemptions": sched.preemptions,
        "leaked_pages": report.leaked_pages,
        "peak_pool_occupancy": report.peak_pool_occupancy,
        "steps": report.steps,
    }
    return _SERVE_RUN


def _pctl(xs: list, q: float) -> float:
    if not xs:
        return float("nan")
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))]


def bench_serve_ttft():
    """Time-to-first-token under the saturated open-loop trace (queue
    wait included — that IS the saturation signal the SLO binds on)."""
    run = _serve_run()
    return {
        "metric": "serve_ttft_ms_p99",
        "value": round(_pctl(run["ttft_ms"], 0.99), 2),
        "unit": "ms",
        "p50": round(_pctl(run["ttft_ms"], 0.5), 2),
        "requests": run["completed"] + run["failed"] + run["shed"],
        "completed": run["completed"],
        "preemptions": run["preemptions"],
        "leaked_pages": run["leaked_pages"],
        "interpret": run["simulated"] or _interpret_capture(),
    }


def bench_serve_throughput():
    """Aggregate generated tokens/s across the whole saturated replay
    (the trace overcommits the pool ~2x, so the run IS the saturated
    regime; preemption recompute cost is inside the number — that is
    the honest overload throughput)."""
    run = _serve_run()
    return {
        "metric": "serve_tokens_per_s_saturated",
        "value": round(run["tokens"] / max(run["wall_s"], 1e-9), 2),
        "unit": "tok/s",
        "scheduler_steps": run["steps"],
        "peak_pool_occupancy": round(run["peak_pool_occupancy"], 4),
        "preemptions": run["preemptions"],
        "interpret": run["simulated"] or _interpret_capture(),
    }


def _trace_overhead_record(metric: str, run_once, *,
                           rounds: int = 3) -> dict:
    """Traced-vs-untraced wall time of the SAME seeded replay (ISSUE 14
    satellite): ``run_once()`` drives one deterministic serve replay;
    both arms run with obs on (isolating the TDT_TRACE cost alone),
    interleaved, min-of-rounds against CI jitter.  The traced arm runs
    last so the committed record can also attest the acceptance
    criterion: the TTFT p99 exemplar resolves to a retained trace.
    Always a SimBackend replay on this box — marked ``interpret`` so
    the 3% warn ceiling binds on real captures, and the trend sentinel
    (``obs.history.direction_for``: "overhead" -> lower-is-better)
    guards growth everywhere."""
    import time as _time

    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import request_trace

    prev_obs = obs.enabled()
    obs.enable(True)
    prev_trace = request_trace.enable(False)
    walls = {False: [], True: []}
    try:
        run_once()                      # compile warmup, untimed
        for _ in range(rounds):
            for traced in (False, True):
                request_trace.enable(traced)
                if traced:
                    request_trace.RING.clear()
                    obs.serve_stats.STATS.reset()
                t0 = _time.perf_counter()
                run_once()
                walls[traced].append(_time.perf_counter() - t0)
        ex = obs.serve_stats.STATS.ttft_ms.exemplar(0.99)
        exemplar_resolved = (ex is not None
                             and request_trace.RING.get(ex) is not None)
    finally:
        request_trace.enable(prev_trace)
        obs.enable(prev_obs)
    t_off, t_on = min(walls[False]), min(walls[True])
    return {
        "metric": metric,
        "value": round(100.0 * (t_on - t_off) / max(t_off, 1e-9), 2),
        "unit": "% over untraced",
        "untraced_s": round(t_off, 4),
        "traced_s": round(t_on, 4),
        "ttft_p99_exemplar_resolved": exemplar_resolved,
        "traces_retained": len(request_trace.RING),
        "interpret": True,   # SimBackend replay on this box
        "devices": jax.device_count(),
    }


def bench_trace_overhead():
    """TDT_TRACE tax on the single-tier scheduler replay (`bench.py
    serve`): the same seeded 48-request overcommit mix replayed
    untraced vs traced."""
    from triton_distributed_tpu import serve

    vocab = 512

    def run_once():
        backend = serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                                   max_length=256, vocab=vocab)
        sched = serve.Scheduler(backend, serve.SchedulerConfig(
            max_queue_depth=128, prefill_chunk_tokens=32))
        arrivals = serve.synthetic_trace(
            7, 48, mean_interarrival_steps=0.25,
            prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
        serve.replay(sched, arrivals, max_steps=100_000)

    return _trace_overhead_record("trace_overhead_pct", run_once)


def bench_trace_overhead_disagg():
    """TDT_TRACE tax on the two-tier disaggregated replay (`bench.py
    serve_disagg`): the handoff plane's extract/wire/verify spans ride
    this arm, so its overhead is gated separately.  Same harness as
    ``_serve_disagg_run`` (``_disagg_setup``/``_disagg_drive``), fewer
    requests per arm; both arms include setup equally, so the pct
    compares like with like."""
    def run_once():
        router, pending = _disagg_setup(32, seed=7, bulk_bytes_per_step=0)
        _disagg_drive(router, pending)

    return _trace_overhead_record("trace_overhead_pct_disagg", run_once)


def _profile_overhead_record(metric: str, run_once, *,
                             rounds: int = 3) -> dict:
    """Profiled-vs-unprofiled wall time of the SAME seeded replay
    (ISSUE 16 satellite): the continuous profiler's always-on cost —
    flight ring recording plus the per-step incremental drain /
    window rotation (``TDT_PROFILE=1``, persistence off so disk IO is
    not in the number).  Both arms run with obs on, interleaved,
    min-of-rounds against CI jitter — the ``_trace_overhead_record``
    discipline.  Marked ``interpret`` (SimBackend replay on this box)
    so the 2% warn ceiling binds on real captures; the trend sentinel
    ("overhead" -> lower-is-better) guards growth everywhere."""
    import time as _time

    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import continuous, flight

    prev_obs = obs.enabled()
    obs.enable(True)
    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    flight.enable(False)
    continuous.enable(False)
    walls = {False: [], True: []}
    try:
        run_once()                      # compile warmup, untimed
        for _ in range(rounds):
            for profiled in (False, True):
                flight.enable(profiled)
                continuous.enable(profiled)
                if profiled:
                    flight.clear()
                    continuous.install(continuous.ContinuousProfiler(
                        window_steps=32, out_dir=""))
                t0 = _time.perf_counter()
                run_once()
                walls[profiled].append(_time.perf_counter() - t0)
        snap = continuous.profiler().snapshot()
        windows = snap["windows_total"]
    finally:
        continuous.reset()
        flight.clear()
        continuous.enable(prev_prof)
        flight.enable(prev_flight)
        obs.enable(prev_obs)
    t_off, t_on = min(walls[False]), min(walls[True])
    return {
        "metric": metric,
        "value": round(100.0 * (t_on - t_off) / max(t_off, 1e-9), 2),
        "unit": "% over unprofiled",
        "unprofiled_s": round(t_off, 4),
        "profiled_s": round(t_on, 4),
        "windows_rotated": windows,
        "interpret": True,   # SimBackend replay on this box
        "devices": jax.device_count(),
    }


def bench_profile_overhead():
    """TDT_PROFILE tax on the single-tier scheduler replay (`bench.py
    serve`): the same seeded 48-request overcommit mix replayed
    unprofiled vs with the continuous profiler armed."""
    from triton_distributed_tpu import serve

    vocab = 512

    def run_once():
        backend = serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                                   max_length=256, vocab=vocab)
        sched = serve.Scheduler(backend, serve.SchedulerConfig(
            max_queue_depth=128, prefill_chunk_tokens=32))
        arrivals = serve.synthetic_trace(
            7, 48, mean_interarrival_steps=0.25,
            prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
        serve.replay(sched, arrivals, max_steps=100_000)

    return _profile_overhead_record("profile_overhead_pct", run_once)


def bench_profile_overhead_disagg():
    """TDT_PROFILE tax on the two-tier disaggregated replay (`bench.py
    serve_disagg`): the router's three per-step hooks (prefill /
    handoff / decode tiers) ride this arm, so its overhead is gated
    separately."""
    def run_once():
        router, pending = _disagg_setup(32, seed=7, bulk_bytes_per_step=0)
        _disagg_drive(router, pending)

    return _profile_overhead_record("profile_overhead_pct_disagg",
                                    run_once)


def _diff_overhead_record(metric: str, run_once, *,
                          rounds: int = 3) -> dict:
    """Regression-forensics tax on the ARMED profiler (ISSUE 20
    satellite): both arms run with the continuous profiler recording;
    the "diffed" arm additionally computes the window-vs-baseline
    causal decomposition (``obs.diff.diff_windows`` + the
    band-representative baseline pick) on EVERY rotation — the worst
    case, since production only diffs on a band breach.  Healthy
    windows must stay retained as future baselines, so the harness
    detector attributes without raising events.  Interleaved,
    min-of-rounds — the ``_trace_overhead_record`` discipline."""
    import time as _time

    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import anomaly, continuous, flight
    from triton_distributed_tpu.obs import diff as diff_mod

    class _AlwaysDiff(anomaly.AnomalyDetector):
        """Event-free harness detector: full attribution per window,
        no breach (the window stays a future baseline candidate)."""

        def __init__(self):
            super().__init__(bands={}, record=False)
            self.diffs = 0

        def check_window(self, window, baseline=None):
            if baseline is not None:
                diff_mod.diff_windows(baseline, window)
                self.diffs += 1
            return []

    prev_obs = obs.enabled()
    obs.enable(True)
    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    walls = {False: [], True: []}
    diffs = 0
    windows = 0
    try:
        run_once()                      # compile warmup, untimed
        for _ in range(rounds):
            for diffed in (False, True):
                flight.enable(True)
                continuous.enable(True)
                flight.clear()
                continuous.install(continuous.ContinuousProfiler(
                    window_steps=32, out_dir=""))
                det = _AlwaysDiff() if diffed \
                    else anomaly.AnomalyDetector(bands={}, record=False)
                anomaly.set_detector(det)
                t0 = _time.perf_counter()
                run_once()
                walls[diffed].append(_time.perf_counter() - t0)
                windows = continuous.profiler().snapshot()[
                    "windows_total"]
                if diffed:
                    diffs += det.diffs
    finally:
        anomaly.set_detector(None)
        continuous.reset()
        flight.clear()
        continuous.enable(prev_prof)
        flight.enable(prev_flight)
        obs.enable(prev_obs)
    t_off, t_on = min(walls[False]), min(walls[True])
    return {
        "metric": metric,
        "value": round(100.0 * (t_on - t_off) / max(t_off, 1e-9), 2),
        "unit": "% over undiffed profiling",
        "undiffed_s": round(t_off, 4),
        "diffed_s": round(t_on, 4),
        "windows_rotated": windows,
        "diffs_computed": diffs,
        "interpret": True,   # SimBackend replay on this box
        "devices": jax.device_count(),
    }


def bench_diff_overhead():
    """Per-rotation differential-attribution tax on the single-tier
    scheduler replay (`bench.py serve`): the same seeded 48-request
    overcommit mix replayed with the profiler armed, undiffed vs
    diffing every window against its healthy baseline."""
    from triton_distributed_tpu import serve

    vocab = 512

    def run_once():
        backend = serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                                   max_length=256, vocab=vocab)
        sched = serve.Scheduler(backend, serve.SchedulerConfig(
            max_queue_depth=128, prefill_chunk_tokens=32))
        arrivals = serve.synthetic_trace(
            7, 48, mean_interarrival_steps=0.25,
            prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
        serve.replay(sched, arrivals, max_steps=100_000)

    return _diff_overhead_record("diff_overhead_pct", run_once)


_DISAGG_RUN = None


def _disagg_setup(n_requests: int, *, seed: int = 0,
                  bulk_bytes_per_step: int = 1 << 20):
    """ONE home for the bench-scale two-tier harness (shared by
    ``_serve_disagg_run`` and the trace-overhead arm): fresh SimBackend
    tiers + router over the modeled DCN plus the seeded open-loop mix.
    Setup only — ``_disagg_drive`` is the (separately timed) replay, so
    ``wall_s``-derived metrics never absorb pool-allocation cost."""
    from triton_distributed_tpu import resilience, serve

    resilience.reset_breaker(serve.HANDOFF_OP)
    vocab = 512
    pre = serve.Scheduler(
        serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                         max_length=256, vocab=vocab),
        serve.SchedulerConfig(max_queue_depth=128,
                              prefill_chunk_tokens=32,
                              prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                         max_length=256, vocab=vocab),
        serve.SchedulerConfig(max_queue_depth=128))
    router = serve.DisaggRouter(
        pre, dec, plane=serve.HandoffPlane(),
        config=serve.RouterConfig(
            bulk_bytes_per_step=bulk_bytes_per_step))
    arrivals = serve.synthetic_trace(
        seed, n_requests, mean_interarrival_steps=0.25,
        prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
    pending = sorted(arrivals, key=lambda a: (a.step, a.request.req_id))
    return router, pending


def _disagg_drive(router, pending) -> None:
    """Drive the two-tier harness until idle (arrivals scheduled
    against the prefill tier's step count, the open-loop contract)."""
    idx = 0
    for _ in range(100_000):
        while idx < len(pending) and \
                pending[idx].step <= router.prefill.steps:
            router.submit(pending[idx].request)
            idx += 1
        res = router.step()
        if idx >= len(pending) and res.idle:
            break


def _serve_disagg_run(n_requests: int = 48) -> dict:
    """One shared two-tier disaggregated replay (ISSUE 12) behind the
    ``serve_disagg`` metrics: a prefill-only tier streams finished KV
    pages over the modeled priority DCN (with a bulk stream contending
    on the wire — the traffic the LATENCY-class handoffs preempt) to a
    decode tier through ``serve.DisaggRouter``.  On this container the
    tiers are SimBackends and the wire is modeled, so every record is
    marked ``interpret`` (functional smoke the trend sentinel follows);
    the slice-gated hard claims arm on the first real multislice
    capture, the PR-10 pattern."""
    global _DISAGG_RUN
    if _DISAGG_RUN is not None:
        return _DISAGG_RUN
    import time

    from triton_distributed_tpu import obs

    prev_obs = obs.enabled()
    obs.enable(True)
    obs.serve_stats.STATS.reset()
    try:
        router, pending = _disagg_setup(n_requests)
        t0 = time.perf_counter()
        _disagg_drive(router, pending)
        wall_s = time.perf_counter() - t0
    finally:
        obs.enable(prev_obs)
    reqs = [a.request for a in pending]
    from triton_distributed_tpu.serve import RequestState

    done = [r for r in reqs if r.state is RequestState.DONE]
    ttft = sorted(r.ttft_ms() for r in done if r.ttft_ms() is not None)
    plane = router.plane
    _DISAGG_RUN = {
        "simulated": True,   # SimBackend tiers + modeled DCN on this box
        "wall_s": wall_s,
        "ttft_ms": ttft,
        "handoff_ms": sorted(plane.handoff_ms),
        "handoffs": router.handoffs,
        "colocated": router.colocated,
        "reprefills": router.reprefills,
        "retries": plane.retries,
        "pages_moved": plane.pages_moved,
        "completed": len(done),
        "failed": sum(r.state is RequestState.FAILED for r in reqs),
        "shed": sum(r.state is RequestState.SHED for r in reqs),
        "leaked_pages": router.leaked_pages(),
    }
    return _DISAGG_RUN


def bench_serve_disagg_ttft():
    """TTFT under the disaggregated topology: submit -> first token on
    the PREFILL tier (the handoff then overlaps with other requests'
    decode — exactly the step-time isolation the topology buys)."""
    run = _serve_disagg_run()
    return {
        "metric": "serve_disagg_ttft_ms_p99",
        "value": round(_pctl(run["ttft_ms"], 0.99), 2),
        "unit": "ms",
        "p50": round(_pctl(run["ttft_ms"], 0.5), 2),
        "completed": run["completed"],
        "handoffs": run["handoffs"],
        "colocated": run["colocated"],
        "reprefills": run["reprefills"],
        "leaked_pages": run["leaked_pages"],
        "interpret": run["simulated"] or _interpret_capture(),
    }


def bench_handoff_latency():
    """Per-transfer KV-handoff latency (modeled wire on this box): the
    page payload's queue wait + serialization + hop on the shared DCN
    at LATENCY priority."""
    run = _serve_disagg_run()
    return {
        "metric": "handoff_ms_p99",
        "value": round(_pctl(run["handoff_ms"], 0.99), 3),
        "unit": "ms",
        "p50": round(_pctl(run["handoff_ms"], 0.5), 3),
        "transfers": run["handoffs"],
        "interpret": run["simulated"] or _interpret_capture(),
    }


def bench_handoff_throughput():
    """KV pages shipped per second across the replay (re-prefilled
    transfers excluded — they never delivered pages)."""
    run = _serve_disagg_run()
    return {
        "metric": "handoff_pages_per_s",
        "value": round(run["pages_moved"] / max(run["wall_s"], 1e-9), 2),
        "unit": "pages/s",
        "pages_moved": run["pages_moved"],
        "wall_s": round(run["wall_s"], 4),
        "interpret": run["simulated"] or _interpret_capture(),
    }


def bench_handoff_retries():
    """Burned transfer-ladder rungs across the replay: every retry is
    wire pressure (obs.history trends it lower-is-better; a clean wire
    reads 0)."""
    run = _serve_disagg_run()
    return {
        "metric": "handoff_retries",
        "value": float(run["retries"]),
        "unit": "count",
        "reprefills": run["reprefills"],
        "interpret": run["simulated"] or _interpret_capture(),
    }


_FLEET_RUN: dict | None = None
_FLEET_REBALANCE_RUN: dict | None = None


def _fleet_run(n_requests: int = 64) -> dict:
    """One shared fleet-tier replay (ISSUE 18) behind the fleet
    metrics: a diurnal + bursty open-loop mix over 2 prefill + 2 decode
    SimBackend replicas through ``serve.FleetRouter``, with a decode
    REPLICA LOST mid-replay — the p99 TTFT under loss is the headline
    (failover re-prefills residents on survivors; the claims gate
    bounds the tail).  SimBackend replicas + modeled DCN on this box,
    so the record is interpret-marked; the hard bound binds on real
    multi-replica captures."""
    global _FLEET_RUN
    if _FLEET_RUN is not None:
        return _FLEET_RUN
    import time

    from triton_distributed_tpu import obs, resilience, serve

    for rid in ("p0", "p1", "d0", "d1"):
        resilience.reset_breaker(serve.replica_breaker_name(rid))
    resilience.reset_breaker(serve.HANDOFF_OP)
    vocab = 512
    replicas = []
    for rid in ("p0", "p1"):
        replicas.append(serve.Replica(
            rid,
            serve.Scheduler(
                serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                                 max_length=256, vocab=vocab),
                serve.SchedulerConfig(max_queue_depth=128,
                                      prefill_chunk_tokens=32,
                                      prefill_only=True)),
            "prefill"))
    for rid in ("d0", "d1"):
        replicas.append(serve.Replica(
            rid,
            serve.Scheduler(
                serve.SimBackend(slots=8, page_size=16, pool_pages=65,
                                 max_length=256, vocab=vocab),
                serve.SchedulerConfig(max_queue_depth=128)),
            "decode"))
    router = serve.FleetRouter(
        replicas, plane=serve.HandoffPlane(),
        config=serve.FleetConfig(max_failovers_per_request=4,
                                 probe_interval_steps=1 << 30))
    # diurnal + bursty: a dense "peak" phase, a sparse "trough", then a
    # burst wave (interarrival 0 gaps are the point) — stitched from
    # three seeded open-loop traces with offset clocks
    peak = serve.synthetic_trace(
        11, n_requests // 2, mean_interarrival_steps=0.25,
        prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
    trough_off = max(a.step for a in peak) + 8
    trough = serve.synthetic_trace(
        12, n_requests // 4, mean_interarrival_steps=4.0,
        prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
    burst_off = trough_off + max(a.step for a in trough) + 8
    burst = serve.synthetic_trace(
        13, n_requests - n_requests // 2 - n_requests // 4,
        mean_interarrival_steps=0.0,
        prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
    arrivals = (list(peak)
                + [serve.Arrival(step=a.step + trough_off,
                                 request=a.request) for a in trough]
                + [serve.Arrival(step=a.step + burst_off,
                                 request=a.request) for a in burst])
    pending = sorted(arrivals, key=lambda a: (a.step, a.request.req_id))
    lose_at = trough_off  # the loss lands between peak and burst
    prev_obs = obs.enabled()
    obs.enable(True)
    obs.serve_stats.STATS.reset()
    lost = []
    try:
        t0 = time.perf_counter()
        idx = 0
        for _ in range(200_000):
            while idx < len(pending) and \
                    pending[idx].step <= router.steps:
                router.submit(pending[idx].request)
                idx += 1
            res = router.step()
            if not lost and router.steps >= lose_at:
                lost = router.lose_replica(
                    "d0", reason="bench-injected replica loss")
                lost = ["d0"]
            if idx >= len(pending) and res.idle:
                break
        wall_s = time.perf_counter() - t0
    finally:
        obs.enable(prev_obs)
    reqs = [a.request for a in pending]
    done = [r for r in reqs if r.state is serve.RequestState.DONE]
    ttft = sorted(r.ttft_ms() for r in done if r.ttft_ms() is not None)
    _FLEET_RUN = {
        "simulated": True,  # SimBackend replicas + modeled DCN here
        "wall_s": wall_s,
        "ttft_ms": ttft,
        "lost": lost,
        "completed": len(done),
        "failed": sum(r.state is serve.RequestState.FAILED
                      for r in reqs),
        "shed": sum(r.state is serve.RequestState.SHED for r in reqs),
        "failovers": router.failovers,
        "reprefills": router.reprefills,
        "handoffs": router.handoffs,
        "colocated": router.colocated,
        "leaked_pages": router.leaked_pages(),
    }
    return _FLEET_RUN


def bench_fleet_ttft_under_loss():
    """p99 TTFT across the diurnal+bursty fleet replay WITH a decode
    replica lost mid-replay: the robustness headline — failover
    re-prefills must keep the tail bounded, not just eventually
    complete (claims gate: ``fleet_ttft_ms_p99_under_loss``)."""
    run = _fleet_run()
    return {
        "metric": "fleet_ttft_ms_p99_under_loss",
        "value": round(_pctl(run["ttft_ms"], 0.99), 2),
        "unit": "ms",
        "p50": round(_pctl(run["ttft_ms"], 0.5), 2),
        "completed": run["completed"],
        "failed": run["failed"],
        "shed": run["shed"],
        "lost_replicas": run["lost"],
        "failovers": run["failovers"],
        "reprefills": run["reprefills"],
        "leaked_pages": run["leaked_pages"],
        "interpret": run["simulated"] or _interpret_capture(),
    }


def bench_fleet_rebalance():
    """Steps from first sustained decode-dominant demand reading to the
    membership conversion (prefill replica recruited into the decode
    role) in the fleet rebalance drill — the SLO-driven rebalance loop's
    convergence latency (claims gate:
    ``fleet_rebalance_convergence_steps``, lower is better)."""
    global _FLEET_REBALANCE_RUN
    if _FLEET_REBALANCE_RUN is None:
        import random

        from triton_distributed_tpu.resilience import matrix as rmatrix

        row = rmatrix._fleet_rebalance_cell(random.Random(0))
        _FLEET_REBALANCE_RUN = row
    row = _FLEET_REBALANCE_RUN
    conv = row.get("convergence_steps")
    return {
        "metric": "fleet_rebalance_convergence_steps",
        # a drill that never converged reads as the gate's ceiling —
        # red, not silently absent
        "value": float(conv) if conv is not None else 1e9,
        "unit": "steps",
        "outcome": row["outcome"],
        "recruited": row.get("replica"),
        "rebalances": row["rebalances"],
        "leaked_pages": row["pages_leaked"],
        "interpret": True,  # SimBackend drill on this box
    }


def bench_fleet_obs_overhead():
    """The TDT_FLEET_OBS tax (ISSUE 19 satellite): the SAME seeded
    N=4 fleet replay bare vs with the observability plane armed — the
    per-replica tee federation, the decision ledger on every router
    actuation, and the per-step fleet-window rotation.  Both arms run
    with base obs on (only the fleet plane toggles), interleaved,
    min-of-rounds — the ``_profile_overhead_record`` discipline;
    ledger persistence is off so disk IO is not in the number.
    Marked ``interpret`` (SimBackend replicas on this box) so the 2%
    warn ceiling (claims gate: ``fleet_obs_overhead_pct``) binds on
    real multi-replica captures; the trend sentinel guards growth
    everywhere."""
    import time as _time

    from triton_distributed_tpu import obs, resilience, serve
    from triton_distributed_tpu.obs import decisions, fleet_stats

    vocab = 512

    def reset_breakers():
        for rid in ("p0", "p1", "d0", "d1"):
            resilience.reset_breaker(serve.replica_breaker_name(rid))
        resilience.reset_breaker(serve.HANDOFF_OP)

    def run_once():
        reset_breakers()
        replicas = []
        for rid in ("p0", "p1"):
            replicas.append(serve.Replica(
                rid,
                serve.Scheduler(
                    serve.SimBackend(slots=8, page_size=16,
                                     pool_pages=65, max_length=256,
                                     vocab=vocab),
                    serve.SchedulerConfig(max_queue_depth=128,
                                          prefill_chunk_tokens=32,
                                          prefill_only=True)),
                "prefill"))
        for rid in ("d0", "d1"):
            replicas.append(serve.Replica(
                rid,
                serve.Scheduler(
                    serve.SimBackend(slots=8, page_size=16,
                                     pool_pages=65, max_length=256,
                                     vocab=vocab),
                    serve.SchedulerConfig(max_queue_depth=128)),
                "decode"))
        router = serve.FleetRouter(
            replicas, plane=serve.HandoffPlane(),
            config=serve.FleetConfig(probe_interval_steps=1 << 30))
        arrivals = serve.synthetic_trace(
            17, 32, mean_interarrival_steps=0.25,
            prompt_len=(8, 48), max_new=(8, 48), vocab=vocab)
        pending = sorted(arrivals,
                         key=lambda a: (a.step, a.request.req_id))
        idx = 0
        for _ in range(100_000):
            while idx < len(pending) and \
                    pending[idx].step <= router.steps:
                router.submit(pending[idx].request)
                idx += 1
            if router.step().idle and idx >= len(pending):
                break

    prev_obs = obs.enabled()
    obs.enable(True)
    prev_dec = decisions.enabled()
    prev_fs = fleet_stats.enabled()
    prev_ledger = decisions.ledger()
    prev_fleet = fleet_stats.current()
    decisions.enable(False)
    fleet_stats.enable(False)
    walls = {False: [], True: []}
    decided = 0
    try:
        run_once()                      # warmup, untimed
        for _ in range(3):
            for armed in (False, True):
                decisions.enable(armed)
                fleet_stats.enable(armed)
                if armed:
                    decisions.install(decisions.DecisionLedger(
                        cap=512, out_dir=""))
                obs.serve_stats.STATS.reset()
                t0 = _time.perf_counter()
                run_once()
                walls[armed].append(_time.perf_counter() - t0)
        led = decisions.ledger()
        decided = 0 if led is None else led.total
    finally:
        decisions.install(prev_ledger)
        decisions.enable(prev_dec)
        fleet_stats.install(prev_fleet)
        fleet_stats.enable(prev_fs)
        obs.serve_stats.STATS.reset()
        obs.enable(prev_obs)
        reset_breakers()
    t_off, t_on = min(walls[False]), min(walls[True])
    return {
        "metric": "fleet_obs_overhead_pct",
        "value": round(100.0 * (t_on - t_off) / max(t_off, 1e-9), 2),
        "unit": "% over bare",
        "bare_s": round(t_off, 4),
        "armed_s": round(t_on, 4),
        "decisions_ledgered": decided,
        "interpret": True,   # SimBackend replicas on this box
        "devices": jax.device_count(),
    }


def bench_integrity_overhead():
    """The TDT_INTEGRITY tax: checksummed vs plain AG/RS at the tuned
    configs, as a percent of the plain eager op (ISSUE 7 satellite —
    the trend sentinel guards it; the claims gate warns above 5%).

    On a real slice (>= 2 devices, compiled kernels) both public eager
    entries are timed with the verification layer off vs on and the
    WORST of the two ratios is the record.  The CPU CI container cannot
    run a collective kernel at all, so there the record is a HOST-
    MODELED functional smoke, marked ``interpret`` (never hard-gated):
    the measured consumer-side verification cost over the tuned payload
    relative to one host copy of the same bytes — a machine-relative
    number that stays comparable round over round on the same box."""
    import time as _time

    from triton_distributed_tpu.core import compilation, mesh as mesh_lib
    from triton_distributed_tpu.resilience import integrity

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    real = ntp >= 2 and not compilation.interpret_mode()
    m, r = 4096, 7168
    prev = integrity._ENABLED
    details: dict = {}
    try:
        if real:
            from triton_distributed_tpu.comm.allgather import all_gather
            from triton_distributed_tpu.comm.reduce_scatter import (
                reduce_scatter,
            )

            x = jax.random.normal(jax.random.key(0), (m, r), jnp.bfloat16)
            worst = 0.0
            for name, op in (
                ("all_gather", lambda: all_gather(x, mesh)),
                ("reduce_scatter", lambda: reduce_scatter(x, mesh)),
            ):
                def run_off(op=op):
                    integrity.enable(False)
                    return jax.block_until_ready(op())

                def run_on(op=op):
                    integrity.enable(True)
                    return jax.block_until_ready(op())

                times = _bench_interleaved(
                    {"off": run_off, "on": run_on},
                    iters=8, rounds=7, window_s=0.3)
                t_off, t_on = _median(times["off"]), _median(times["on"])
                pct = 100.0 * (t_on - t_off) / max(t_off, 1e-12)
                details[f"{name}_plain_us"] = round(t_off * 1e6, 1)
                details[f"{name}_checked_us"] = round(t_on * 1e6, 1)
                worst = max(worst, pct)
            value = worst
        else:
            # host-modeled: verify_gather over the tuned payload vs one
            # copy of the gathered bytes (marked interpret below)
            import numpy as np

            rng = np.random.default_rng(0)
            x = rng.standard_normal((m, r)).astype(np.float32)
            reps = 3
            t0 = _time.perf_counter()
            for _ in range(reps):
                diag = integrity.verify_gather("all_gather", x, x, 4)
                if diag is not None:   # self-check, never timed away
                    raise RuntimeError(f"clean payload flagged: {diag}")
            t_verify = (_time.perf_counter() - t0) / reps
            t0 = _time.perf_counter()
            for _ in range(reps):
                x.copy()
            t_copy = (_time.perf_counter() - t0) / reps
            value = 100.0 * t_verify / max(t_copy, 1e-12)
            details["modeled"] = ("verify_gather vs one host copy of "
                                  "the gathered payload")
            details["verify_us"] = round(t_verify * 1e6, 1)
            details["copy_us"] = round(t_copy * 1e6, 1)
    finally:
        integrity.enable(prev)
    return {
        "metric": "integrity_overhead_pct",
        "value": round(value, 2),
        "unit": "% over plain",
        "shape": f"({m}, {r})",
        "devices": jax.device_count(),
        "interpret": (not real) or _interpret_capture(),
        **details,
    }


# -- hierarchical multi-slice collectives (ISSUE 10) ------------------------


def bench_hier_ar_dcn_bytes(m: int = 4096, r: int = 7168, n_in: int = 4,
                            n_out: int = 2):
    """DCN bytes-on-wire of the hierarchical AllReduce at the RS∘AG
    bound (ISSUE 10 acceptance): ``value`` = per-chip DCN bytes / the
    1/n_in-of-payload bound — claims-gated <= 1.02 (the "+ tolerance"),
    deterministic static accounting from the SAME byte math the obs
    counters and the watchdog pricing read
    (``comm.hierarchical.hier_ar_wire_bytes``).  The record carries the
    bf16 ``psum`` form (exactly 1.0 at n_out=2) and the resolved default
    policy's form (the quantized one-shot exchange where
    ``codec_pays("dcn")`` — ~0.51 cold-start), plus the 2x4 chunk
    schedule so the emission order is pinned in the round history.
    ``vs_baseline`` = slow-wire bytes a FLAT two-shot ring over the
    combined axis would pace through the DCN cut, over ours — the
    hierarchy's reason to exist.  Sim-marked ``interpret`` on CPU
    containers (no wire ran; the arithmetic is the claim)."""
    from triton_distributed_tpu.comm.hierarchical import (
        chunk_schedule,
        dcn_ar_wire,
        hier_ar_wire_bytes,
    )

    n = n_in * n_out
    payload = m * r * 2                       # bf16 per-chip partial
    bound = payload // n_in                   # the RS∘AG DCN bound
    _, dcn_bf16 = hier_ar_wire_bytes(m, r, jnp.bfloat16, n_in, n_out,
                                     "bf16")
    wire = dcn_ar_wire("auto", r, n_out)      # the shipped default policy
    ici, dcn_auto = hier_ar_wire_bytes(m, r, jnp.bfloat16, n_in, n_out,
                                       wire)
    flat_wire = 2 * (n - 1) * payload // n    # flat ring: every link paced
    return {
        "metric": f"hier_ar_dcn_bytes_ratio_m{m}_r{r}_{n_out}x{n_in}",
        "value": round(dcn_auto / bound, 4),
        "unit": "x of the 1/slice_ranks payload bound (DCN bytes/chip)",
        "vs_baseline": round(flat_wire / dcn_auto, 4),
        "ratio_bf16_psum": round(dcn_bf16 / bound, 4),
        "dcn_wire": wire,
        "dcn_bytes": int(dcn_auto),
        "ici_bytes": int(ici),
        "payload_bytes": payload,
        "bound_bytes": bound,
        "schedule_2x4": [list(g) for g in chunk_schedule(2, 4)],
        "devices": jax.device_count(),
        # sim-marked on CPU containers (platform probe, not the
        # interpret-params probe — this box's jax predates
        # InterpretParams, which would read as "not interpret")
        "interpret": _interpret_capture() or _bench_on_cpu(),
    }


def _bench_on_cpu() -> bool:
    try:
        from triton_distributed_tpu.core import platform

        return bool(platform.on_cpu())
    except Exception:
        return False


def bench_overlap():
    """Measured DMA/MXU overlap of the tile pipeline (the compute core of
    the fused collective GEMMs) via the three-kernel decomposition in
    ``tools/overlap.py`` — fused vs dma-only vs mxu-only wall times,
    reporting what fraction of the smaller phase the pipeline hides.
    Converts ``tests/test_overlap_structure.py``'s program-order argument
    into a measured claim; on a slice :func:`bench_overlap_collective`
    applies the same decomposition to the fused AG-GEMM ring itself (the
    v5p >= 90%-hidden BASELINE target)."""
    from triton_distributed_tpu.tools.overlap import hidden_pct, overlap_kernels

    m = n = k = 4096
    fused, dma, mxu = overlap_kernels(m, n, k)
    ka, kb = jax.random.split(jax.random.key(0))
    a = jax.random.normal(ka, (m, k), jnp.bfloat16)
    b = jax.random.normal(kb, (k, n), jnp.bfloat16)
    times = _bench_interleaved({
        "fused": lambda: fused(a, b),
        "dma": lambda: dma(a, b),
        "mxu": lambda: mxu(a, b),
    }, iters=16, rounds=9, window_s=0.3)
    tf_, td, tm = (_median(times[x]) for x in ("fused", "dma", "mxu"))
    pct = hidden_pct(tf_, td, tm)
    return {
        "metric": f"overlap_hidden_pct_m{m}",
        "value": round(pct, 4),
        "unit": "fraction of smaller phase hidden",
        "fused_us": round(tf_ * 1e6, 1),
        "dma_only_us": round(td * 1e6, 1),
        "mxu_only_us": round(tm * 1e6, 1),
    }


def bench_overlap_collective():
    """Multi-chip: the same phase decomposition applied to the fused
    AG-GEMM itself — t_fused (the ring kernel) vs t_comm (the bare
    AllGather moving the same bytes) vs t_gemm (the gathered local
    matmul), all through the public ops.  hidden = fraction of the
    smaller phase (usually the wire) the fused kernel hides; this IS the
    v5p >= 90%-hidden BASELINE target, measured.  Requires a slice
    (>= 2 devices); ``auto`` emits it only there."""
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.comm.allgather import (
        AllGatherMethod, all_gather,
    )
    from triton_distributed_tpu.core import compilation, mesh as mesh_lib
    from triton_distributed_tpu.ops.ag_gemm import ag_gemm
    from triton_distributed_tpu.tools.overlap import hidden_pct

    mesh = mesh_lib.tp_mesh()
    ntp = mesh.shape["tp"]
    if ntp < 2:
        raise SystemExit(
            "overlap_collective needs a slice (>= 2 devices): at tp=1 the "
            "gather is identity and the hidden fraction would be noise "
            "dressed as measurement"
        )
    if compilation.interpret_mode():
        m, k, n = 8 * ntp, 256, 16 * ntp   # structure smoke, not timing
    else:
        m, k, n = 4096, 7168, 7168  # e2e_dense.md MLP shape
    a, b, af = _ag_gemm_operands(mesh, m, k, n)
    ag = jax.jit(lambda a: all_gather(a, mesh, method=AllGatherMethod.RING_BIDIR))
    gemm = compilation.jit_shard_map(
        lambda a, b: jnp.matmul(a, b, preferred_element_type=jnp.float32
                                ).astype(a.dtype),
        mesh, in_specs=(P(None, None), P(None, "tp")),
        out_specs=P(None, "tp"),
    )
    iters = 4 if compilation.interpret_mode() else 16
    times = _bench_interleaved({
        "fused": lambda: ag_gemm(a, b, mesh),
        "comm": lambda: ag(a),
        "gemm": lambda: gemm(af, b),
    }, iters=iters, rounds=7, window_s=0.3)
    tf_, tc, tg = (_median(times[x]) for x in ("fused", "comm", "gemm"))
    pct = hidden_pct(tf_, tc, tg)
    return {
        "metric": f"overlap_hidden_pct_ag_gemm_m{m}_tp{ntp}",
        "value": round(pct, 4),
        "unit": "fraction of smaller phase hidden",
        "fused_us": round(tf_ * 1e6, 1),
        "comm_only_us": round(tc * 1e6, 1),
        "gemm_only_us": round(tg * 1e6, 1),
        # the >= 90%-hidden BASELINE claim binds only on real slices —
        # the gate keys on this field (min_devices); an interpret-mode
        # capture ("structure smoke, not timing" above) is never
        # hard-gated
        "devices": jax.device_count(),
        "interpret": _interpret_capture(),
    }


def bench_latency():
    """Latency-class collectives at 8-256 KiB payloads, in MICROSECONDS
    (reference ``test_ag_small_msg.py`` / ``test_ring_put.py`` — the
    regime the one-shot/push variants exist for).

    With >1 device the AG (push vs ring) and AR (one-shot vs two-shot)
    entries are measured for real.  On ONE chip the collectives early-out
    (nothing to wire), so the honest measurable quantity is the LATENCY
    FLOOR those paths pay before any wire byte moves: the wall cost of a
    small Pallas kernel round-tripping the payload HBM->VMEM->HBM
    (kernel launch + DMA issue + sync — the fixed term of the one-shot
    path), against the same-payload XLA elementwise baseline.  A slice
    run's small-message latency is this floor + hop latency + B/bw with
    the ``tools.calibrate`` link numbers; the record labels which case it
    measured via ``single_chip_floor``."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from triton_distributed_tpu.core import compilation
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.core.utils import perf_func

    payloads_kib = (8, 32, 128, 256)
    multi = jax.device_count() > 1
    # interpret-mode (CPU mesh) runs are functional smoke, not timing:
    # the simulator pays ~100 ms per collective call, so trip counts drop
    iters = 8 if compilation.interpret_mode() else 64
    sizes = {}
    if multi:
        from jax.sharding import PartitionSpec as P

        from triton_distributed_tpu.comm.allgather import (
            AllGatherMethod, all_gather,
        )
        from triton_distributed_tpu.comm.allreduce import (
            AllReduceMethod, all_reduce,
        )
        mesh = mesh_lib.tp_mesh()
        n = mesh.shape["tp"]
        for kib in payloads_kib:
            rows = max(8, (kib * 1024) // (128 * 4) // 8 * 8)
            x = mesh_lib.shard(
                mesh, jnp.ones((n * rows, 128), jnp.float32), "tp", None
            )
            entry = {}
            for name, fn in (
                ("ag_push", functools.partial(
                    all_gather, mesh=mesh, method=AllGatherMethod.PUSH_1SHOT)),
                ("ag_ring", functools.partial(
                    all_gather, mesh=mesh, method=AllGatherMethod.RING_BIDIR)),
                ("ar_one_shot", functools.partial(
                    all_reduce, mesh=mesh, method=AllReduceMethod.ONE_SHOT)),
                ("ar_two_shot", functools.partial(
                    all_reduce, mesh=mesh, method=AllReduceMethod.TWO_SHOT)),
            ):
                jit_fn = jax.jit(lambda x, fn=fn: fn(x))
                _, ms = perf_func(lambda: jit_fn(x), iters=iters)
                entry[name] = round(ms * 1e3, 2)
            sizes[f"{kib}KiB"] = entry
        headline = sizes["8KiB"]["ag_push"]
    else:
        def roundtrip_kernel(x_ref, o_ref, scratch, sem):
            from triton_distributed_tpu import lang

            lang.local_copy(x_ref, scratch, sem).wait()
            lang.local_copy(scratch, o_ref, sem).wait()

        for kib in payloads_kib:
            rows = max(8, (kib * 1024) // (128 * 4) // 8 * 8)
            x = jnp.ones((rows, 128), jnp.float32)
            call = pl.pallas_call(
                roundtrip_kernel,
                out_shape=jax.ShapeDtypeStruct((rows, 128), jnp.float32),
                in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
                out_specs=pl.BlockSpec(memory_space=pl.ANY),
                scratch_shapes=[pltpu.VMEM((rows, 128), jnp.float32),
                                pltpu.SemaphoreType.DMA],
                interpret=compilation.interpret_mode(),
            )
            pallas_fn = jax.jit(call)
            xla_fn = jax.jit(lambda x: x + 1.0)
            # latency floors are microseconds against a chip that
            # oscillates on second timescales: ride the interleaved
            # median protocol, not a single slope shot
            times = _bench_interleaved({
                "pallas": lambda: pallas_fn(x),
                "xla": lambda: xla_fn(x),
            }, iters=256, rounds=7, window_s=0.1)
            sizes[f"{kib}KiB"] = {
                "pallas_roundtrip": round(_median(times["pallas"]) * 1e6, 2),
                "xla_elementwise": round(_median(times["xla"]) * 1e6, 2),
            }
        headline = sizes["8KiB"]["pallas_roundtrip"]
    return {
        "metric": "latency_class_us",
        "value": headline,
        "unit": "us (8KiB)",
        "single_chip_floor": not multi,
        "sizes_us": sizes,
    }


def main():
    import os
    import sys

    # persistent XLA compilation cache: the fresh-tune sweeps compile
    # ~7 candidates per op, ~30 s each for the Pallas big tiles via the
    # remote compiler — cached, a repeat bench run pays none of it
    cache_dir = os.path.join(
        os.path.expanduser("~"), ".cache", "triton_distributed_tpu",
        "xla_cache",
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass  # older jax without the knobs: compile uncached

    mode = sys.argv[1] if len(sys.argv) > 1 else "auto"
    if mode == "attn":
        print(json.dumps(bench_attention()))
    elif mode == "mlp":
        print(json.dumps(bench_tp_mlp()))
    elif mode == "gemm":
        print(json.dumps(bench_single_chip()))
    elif mode == "moe":
        print(json.dumps(bench_group_gemm()))
    elif mode == "decode":
        # the decode surface: split-KV attention kernel, the ISSUE-8
        # megakernel dispatch accounting, the fused-mode step time, and
        # the ISSUE-13 persistent bundle (dispatches-per-bundle ratchets
        # the 2/layer chain toward O(1)/step)
        print(json.dumps(bench_decode()))
        print(json.dumps(bench_decode_dispatches()))
        print(json.dumps(bench_fused_decode()))
        print(json.dumps(bench_persistent_dispatches()))
        print(json.dumps(bench_persistent_decode()))
    elif mode == "decode_modes":
        print(json.dumps(bench_decode_modes()))
    elif mode == "moe_ep":
        print(json.dumps(bench_moe_ep_wire()))
    elif mode == "latency":
        print(json.dumps(bench_latency()))
    elif mode == "serve":
        # the continuous-batching scheduler under a seeded open-loop
        # overload trace (two record lines off one shared replay), plus
        # the int8-KV capacity ratio at equal pool bytes (ISSUE 9)
        print(json.dumps(bench_serve_ttft()))
        print(json.dumps(bench_serve_throughput()))
        print(json.dumps(bench_serve_kv_quant()))
        print(json.dumps(bench_trace_overhead()))
        print(json.dumps(bench_profile_overhead()))
        print(json.dumps(bench_diff_overhead()))
    elif mode == "serve_disagg":
        # the disaggregated prefill/decode topology (ISSUE 12): TTFT
        # plus the KV-handoff plane's latency/throughput/retry surface,
        # all off one shared two-tier replay over the modeled DCN
        print(json.dumps(bench_serve_disagg_ttft()))
        print(json.dumps(bench_handoff_latency()))
        print(json.dumps(bench_handoff_throughput()))
        print(json.dumps(bench_handoff_retries()))
        print(json.dumps(bench_trace_overhead_disagg()))
        print(json.dumps(bench_profile_overhead_disagg()))
    elif mode == "fleet":
        # the N-replica fleet tier (ISSUE 18): diurnal+bursty replay
        # with a replica lost mid-stream, the rebalance drill's
        # convergence latency, plus the fleet-observability tax
        # (ISSUE 19)
        print(json.dumps(bench_fleet_ttft_under_loss()))
        print(json.dumps(bench_fleet_rebalance()))
        print(json.dumps(bench_fleet_obs_overhead()))
    elif mode == "wire":
        # quantized collective payload byte accounting + dequant parity
        # (ISSUE 9)
        print(json.dumps(bench_wire_bytes()))
        print(json.dumps(bench_wire_parity()))
    elif mode == "hier":
        # hierarchical multi-slice collectives (ISSUE 10): DCN
        # bytes-on-wire for AR at the RS∘AG bound + the pinned schedule
        print(json.dumps(bench_hier_ar_dcn_bytes()))
    elif mode == "overlap":
        print(json.dumps(bench_overlap()))
    elif mode == "overlap_collective":
        print(json.dumps(bench_overlap_collective()))
    elif mode == "integrity":
        print(json.dumps(bench_integrity_overhead()))
    elif mode == "auto":
        # whole perf surface, one JSON line per mode; headline GEMM
        # first.  The complete stream also lands in BENCH_LOCAL_rNN.jsonl
        # (commit it next to the driver's BENCH_rNN.json: the claims gate
        # prefers the untruncatable local record)
        _open_local_record()
        _emit(bench_single_chip)
        _emit(bench_single_chip, 4096, 4096, 4096, rounds=13)
        _emit(bench_single_chip, 8192, 2048, 7168, rounds=13)
        _emit(bench_attention)
        _emit(bench_decode)
        _emit(bench_tp_mlp)
        _emit(bench_group_gemm)
        _emit(bench_decode_modes)
        _emit(bench_decode_dispatches)
        _emit(bench_fused_decode)
        _emit(bench_persistent_dispatches)
        _emit(bench_persistent_decode)
        _emit(bench_moe_ep_wire)
        _emit(bench_latency)
        _emit(bench_overlap)
        _emit(bench_serve_ttft)
        _emit(bench_serve_throughput)
        _emit(bench_serve_kv_quant)
        _emit(bench_serve_disagg_ttft)
        _emit(bench_handoff_latency)
        _emit(bench_handoff_throughput)
        _emit(bench_handoff_retries)
        _emit(bench_fleet_ttft_under_loss)
        _emit(bench_fleet_rebalance)
        _emit(bench_fleet_obs_overhead)
        _emit(bench_trace_overhead)
        _emit(bench_trace_overhead_disagg)
        _emit(bench_profile_overhead)
        _emit(bench_profile_overhead_disagg)
        _emit(bench_diff_overhead)
        _emit(bench_wire_bytes)
        _emit(bench_wire_parity)
        _emit(bench_hier_ar_dcn_bytes)
        _emit(bench_integrity_overhead)
        if jax.device_count() > 1:
            _emit(bench_multi_chip)
            _emit(bench_overlap_collective)
        # sweep sentinel, ALWAYS last: tells the claims gate this record
        # is a full `auto` capture (completeness enforced — every binding
        # claim must appear) and whether any mode crashed.  A run that
        # dies before even this line leaves no sentinel, which the gate
        # treats as an incomplete record via the driver envelope's rc.
        _record_line(json.dumps({
            "metric": "bench_sweep_complete",
            "value": 1 if not _EMIT_FAILED else 0,
            "unit": "bool",
            # survives tail truncation (the sentinel is the LAST line):
            # lets the gate tell truncated-away head lines from crashes
            "emitted": _EMITTED,
            # the completeness gate requires slice-gated claims only on
            # sweeps that actually ran on a slice
            "devices": jax.device_count(),
            # round-id stamp (see _next_round): lets the trajectory
            # sentinel place the stream without trusting the filename
            "round": _ROUND,
        }))
        if _LOCAL_SINK is not None:
            _LOCAL_SINK.close()
        if _EMIT_FAILED:
            # partial lines already flushed; the exit code must still
            # reflect that some modes crashed
            sys.exit(1)
    else:
        raise SystemExit(
            f"unknown bench mode {mode!r} "
            "(auto|gemm|attn|mlp|moe|decode|decode_modes|moe_ep|latency|"
            "overlap|overlap_collective|serve|serve_disagg|fleet|wire|"
            "hier|integrity)"
        )


if __name__ == "__main__":
    main()
