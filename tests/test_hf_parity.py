"""End-to-end parity vs Hugging Face eager (the reference's
``test_tp_e2e.py --check`` mode, which compares its distributed forward
against the HF implementation on the same weights).

Builds a tiny random-weight HF Qwen3, exports its state dict through this
framework's loader, and compares prefill logits and a greedy decode step
across the TP mesh — validating the RoPE/QK-norm/SwiGLU/GQA/cache
conventions against the canonical implementation, not just against our
own golden.

The importorskip is LOUD (VERDICT weak #6): skipping these tests means
the repo's model conventions are NOT being validated against the
canonical implementation this run, which must not hide inside the
silent 's' column.  When torch/transformers are absent the skip emits a
warning naming the skipped convention check (surfaced again by
``tests/conftest.py::pytest_terminal_summary``), and with
``TDT_REQUIRE_HF_PARITY=1`` — the CI shard that provisions torch sets
it — absence becomes a hard collection failure, asserting the parity
check actually ran."""

import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

HF_SKIP_MSG = (
    "HF-parity convention checks SKIPPED ({missing} not installed): "
    "prefill/decode logits are NOT being validated against the canonical "
    "Hugging Face implementation this run (docs/parity.md).  Install "
    "torch+transformers, or set TDT_REQUIRE_HF_PARITY=1 to make this a "
    "hard failure in the shard that provisions them."
)

_missing = [m for m in ("torch", "transformers")
            if importlib.util.find_spec(m) is None]
if _missing:
    msg = HF_SKIP_MSG.format(missing="+".join(_missing))
    if os.environ.get("TDT_REQUIRE_HF_PARITY", "") not in ("", "0"):
        # the CI shard that installs torch asserts the check RAN: a
        # broken provision step must fail the shard, not skip the test
        raise RuntimeError(
            f"TDT_REQUIRE_HF_PARITY=1 but {'+'.join(_missing)} cannot be "
            f"imported — the HF-parity shard is not actually running the "
            f"parity check"
        )
    import warnings

    warnings.warn(msg)
    pytest.skip(msg, allow_module_level=True)

import torch          # noqa: E402
import transformers   # noqa: E402

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.models import ModelConfig, Qwen3, init_cache
from triton_distributed_tpu.models.loader import load_qwen_state_dict

CFG = ModelConfig(
    num_layers=2, hidden=64, intermediate=128, num_heads=4, num_kv_heads=2,
    head_dim=32, vocab=128, max_length=64, rope_theta=1e6, rms_eps=1e-6,
    dtype=jnp.float32,
)


def _hf_model():
    hf_cfg = transformers.Qwen3Config(
        vocab_size=CFG.vocab,
        hidden_size=CFG.hidden,
        intermediate_size=CFG.intermediate,
        num_hidden_layers=CFG.num_layers,
        num_attention_heads=CFG.num_heads,
        num_key_value_heads=CFG.num_kv_heads,
        head_dim=CFG.head_dim,
        max_position_embeddings=CFG.max_length,
        rope_theta=CFG.rope_theta,
        rms_norm_eps=CFG.rms_eps,
        tie_word_embeddings=False,
        attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = transformers.Qwen3ForCausalLM(hf_cfg)
    model.eval()
    return model


@pytest.mark.parametrize("tp", [1, 2])
def test_prefill_logits_match_hf(tp):
    hf = _hf_model()
    ids_np = np.array([[3, 17, 42, 7, 99, 5, 23, 81]], np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids_np)).logits.float().numpy()

    mesh = make_mesh({TP_AXIS: tp}, devices=jax.devices()[:tp])
    model = Qwen3(CFG, mesh)
    params = load_qwen_state_dict(model, hf.state_dict())
    cache = init_cache(mesh, CFG.num_layers, 1, CFG.num_kv_heads,
                       CFG.max_length, CFG.head_dim, CFG.dtype)
    got, _ = model.prefill(params, cache, jnp.asarray(ids_np, jnp.int32))
    got = np.asarray(jax.device_get(got), np.float32)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)


def test_greedy_decode_matches_hf():
    hf = _hf_model()
    ids_np = np.array([[3, 17, 42, 7]], np.int64)
    gen_len = 6
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids_np), max_new_tokens=gen_len, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, ids_np.shape[1]:]

    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(CFG, mesh)
    params = load_qwen_state_dict(model, hf.state_dict())
    from triton_distributed_tpu.models import Engine

    eng = Engine(model, params, batch=1)
    got = np.asarray(jax.device_get(
        eng.generate(jnp.asarray(ids_np, jnp.int32), gen_len)
    ))
    np.testing.assert_array_equal(got, want)


MOE_CFG = ModelConfig(
    num_layers=2, hidden=64, intermediate=128, num_heads=4, num_kv_heads=2,
    head_dim=32, vocab=128, max_length=64, rope_theta=1e6, rms_eps=1e-6,
    dtype=jnp.float32, num_experts=4, top_k=2, moe_intermediate=32,
    norm_topk=True,
)


def _hf_moe_model():
    hf_cfg = transformers.Qwen3MoeConfig(
        vocab_size=MOE_CFG.vocab,
        hidden_size=MOE_CFG.hidden,
        intermediate_size=MOE_CFG.intermediate,
        num_hidden_layers=MOE_CFG.num_layers,
        num_attention_heads=MOE_CFG.num_heads,
        num_key_value_heads=MOE_CFG.num_kv_heads,
        head_dim=MOE_CFG.head_dim,
        max_position_embeddings=MOE_CFG.max_length,
        rope_theta=MOE_CFG.rope_theta,
        rms_norm_eps=MOE_CFG.rms_eps,
        tie_word_embeddings=False,
        attention_dropout=0.0,
        num_experts=MOE_CFG.num_experts,
        num_experts_per_tok=MOE_CFG.top_k,
        moe_intermediate_size=MOE_CFG.moe_intermediate,
        norm_topk_prob=MOE_CFG.norm_topk,
        decoder_sparse_step=1,
        mlp_only_layers=[],
        output_router_logits=False,
    )
    torch.manual_seed(1)
    model = transformers.Qwen3MoeForCausalLM(hf_cfg)
    model.eval()
    return model


@pytest.mark.parametrize("tp,strategy", [(1, "tp"), (2, "tp"), (2, "ep")])
def test_moe_prefill_logits_match_hf(tp, strategy):
    """Qwen3-MoE: routed SwiGLU experts vs HF, under both parallelism
    strategies (TP: experts F-sharded through AG+group-GEMM+RS; EP:
    experts partitioned through A2A dispatch/combine)."""
    import dataclasses

    hf = _hf_moe_model()
    ids_np = np.array([[3, 17, 42, 7, 99, 5, 23, 81]], np.int64)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids_np)).logits.float().numpy()

    cfg = dataclasses.replace(MOE_CFG, moe_strategy=strategy)
    mesh = make_mesh({TP_AXIS: tp}, devices=jax.devices()[:tp])
    model = Qwen3(cfg, mesh)
    params = load_qwen_state_dict(model, hf.state_dict())
    cache = init_cache(mesh, cfg.num_layers, 1, cfg.num_kv_heads,
                       cfg.max_length, cfg.head_dim, cfg.dtype)
    got, _ = model.prefill(params, cache, jnp.asarray(ids_np, jnp.int32))
    got = np.asarray(jax.device_get(got), np.float32)
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)


@pytest.mark.parametrize("strategy", ["tp", "ep"])
def test_moe_greedy_decode_matches_hf(strategy):
    import dataclasses

    hf = _hf_moe_model()
    ids_np = np.array([[3, 17, 42, 7]], np.int64)
    gen_len = 6
    with torch.no_grad():
        want = hf.generate(
            torch.from_numpy(ids_np), max_new_tokens=gen_len, do_sample=False,
            pad_token_id=0,
        ).numpy()[:, ids_np.shape[1]:]

    cfg = dataclasses.replace(MOE_CFG, moe_strategy=strategy)
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(cfg, mesh)
    params = load_qwen_state_dict(model, hf.state_dict())
    from triton_distributed_tpu.models import Engine

    eng = Engine(model, params, batch=1)
    got = np.asarray(jax.device_get(
        eng.generate(jnp.asarray(ids_np, jnp.int32), gen_len)
    ))
    np.testing.assert_array_equal(got, want)
