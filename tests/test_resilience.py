"""tdt.resilience: bounded collectives, fault injection, graceful
degradation (ISSUE 3).

CPU-only, no interpret mode: faults are injected through the
primitives-layer interception points into recorded executions; the
bounded simulator detects stalls with the offending semaphore/chunk
named; the watchdog bounds live thunks by wall time; the policy ladder
retries, degrades and trips the sticky breaker; the engine isolates
failed requests; calibrate agrees thresholds across hosts.
"""

import os
import random
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu import resilience as rz
from triton_distributed_tpu.analysis.registry import all_cases

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _case(name: str, n: int = 4):
    return next(c for c in all_cases(ranks=(n,)) if c.name == name)


@pytest.fixture(autouse=True)
def _clean_policy_state():
    rz.policy._reset_state_for_tests()
    yield
    rz.policy._reset_state_for_tests()


# ---------------------------------------------------------------------------
# fault scope mechanics


def test_drop_notify_removes_signal_from_trace():
    case = _case("reduce_scatter/ring")
    clean = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.DROP_NOTIFY, rank=1, nth=10 ** 9))
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.DROP_NOTIFY, rank=1, nth=0))
    assert ft.fired
    assert len(ft.traces[1]) == len(clean.traces[1]) - 1
    # untouched ranks record identical traces
    assert ft.traces[0] == clean.traces[0]


def test_rank_abort_truncates_trace():
    case = _case("reduce_scatter/ring")
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.RANK_ABORT, rank=2, nth=3))
    assert ft.aborted == {2}
    clean = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.RANK_ABORT, rank=2, nth=10 ** 9))
    assert len(ft.traces[2]) < len(clean.traces[2])


def test_stale_credit_prepends_and_unbalances():
    case = _case("allgather/push_1shot")
    spec = rz.sample_spec(case, rz.FaultKind.STALE_CREDIT, random.Random(3))
    ft = rz.record_faulty_case(case, spec)
    assert ft.fired
    hazards = rz.check_hazards(ft)
    assert hazards and "stale surplus" in hazards[0]


def test_fault_scope_does_not_nest():
    scope = rz.FaultScope(rz.FaultSpec(rz.FaultKind.DROP_NOTIFY, rank=0))
    with rz.scoped(scope):
        with pytest.raises(RuntimeError, match="nest"):
            with rz.scoped(scope):
                pass


def test_sample_spec_is_seed_deterministic():
    case = _case("gemm_rs/ring")
    for kind in rz.FAULT_KINDS:
        a = rz.sample_spec(case, kind, random.Random(42))
        b = rz.sample_spec(case, kind, random.Random(42))
        assert a == b


# ---------------------------------------------------------------------------
# bounded simulator


def test_clean_traces_complete():
    case = _case("allreduce/two_shot")
    assert rz.clean_ticks(case) > 0


def test_dropped_notify_stalls_with_named_semaphore():
    case = _case("gemm_rs/ring")
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.DROP_NOTIFY, rank=0, nth=0))
    with pytest.raises(rz.CollectiveTimeoutError) as ei:
        rz.run_bounded(ft, deadline_ticks=10_000)
    err = ei.value
    assert err.diagnosis is not None and err.diagnosis.pending
    # the drop hits an ack_sems notify; some rank starves on it
    assert any("ack_sems" in s for s in err.diagnosis.semaphores()), \
        err.diagnosis.semaphores()


def test_rank_abort_names_missing_chunk_and_rank():
    case = _case("allgather/push_1shot")
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.RANK_ABORT, rank=1, nth=2))
    with pytest.raises(rz.CollectiveTimeoutError) as ei:
        rz.run_bounded(ft)
    diag = ei.value.diagnosis
    assert diag.aborted == (1,)
    assert diag.pending
    # survivors starve for the aborted rank's chunk pushes
    assert any(p.chunk is not None or p.sem for p in diag.pending)
    assert "aborted" in str(ei.value)


def test_straggler_delays_completion_but_survives():
    case = _case("reduce_scatter/ring")
    base = rz.clean_ticks(case)
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.STRAGGLER, rank=0, delay=7))
    res = rz.run_bounded(ft, deadline_ticks=base * 10)
    assert res.ticks > base
    assert not rz.check_hazards(ft)


def test_straggler_beyond_deadline_is_detected():
    case = _case("reduce_scatter/ring")
    base = rz.clean_ticks(case)
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.STRAGGLER, rank=0,
                           delay=base * 100))
    with pytest.raises(rz.CollectiveTimeoutError, match="deadline"):
        rz.run_bounded(ft, deadline_ticks=base * 4)


def test_delayed_notify_within_slack_survives():
    case = _case("gemm_ar/ring")
    spec = rz.sample_spec(case, rz.FaultKind.DELAY_NOTIFY, random.Random(5))
    ft = rz.record_faulty_case(case, spec)
    base = rz.clean_ticks(case)
    res = rz.run_bounded(ft, deadline_ticks=base * 4 + 16)
    assert res.ticks >= base
    assert not rz.check_hazards(ft)


# ---------------------------------------------------------------------------
# watchdog


def test_deadline_ms_monotone_and_floored():
    small = rz.deadline_ms("all_gather", payload_bytes=1 << 10, num_ranks=4)
    big = rz.deadline_ms("all_gather", payload_bytes=1 << 28, num_ranks=4)
    assert big > small >= rz.watchdog.floor_ms()


def test_call_with_deadline_passes_value_and_errors():
    assert rz.call_with_deadline("x", lambda: 41 + 1, 5_000) == 42
    with pytest.raises(ValueError, match="boom"):
        rz.call_with_deadline(
            "x", lambda: (_ for _ in ()).throw(ValueError("boom")), 5_000)


def test_call_with_deadline_times_out_with_static_diagnosis():
    started = threading.Event()

    def slow():
        started.set()
        time.sleep(5.0)
        return "late"

    obs.REGISTRY.reset()
    obs.enable(True)
    try:
        with pytest.raises(rz.CollectiveTimeoutError) as ei:
            rz.call_with_deadline("all_gather", slow, 50.0,
                                  family="allgather", ranks=4)
    finally:
        obs.enable(None)
    assert started.is_set()
    err = ei.value
    assert err.deadline_ms == 50.0
    # the static protocol diagnosis names the semaphores the kernel
    # family waits on, even though the live thunk is a black box
    assert err.diagnosis is not None and err.diagnosis.static
    assert err.diagnosis.pending
    counts = {(r["name"], tuple(sorted(r["labels"].items()))): r["value"]
              for r in obs.REGISTRY.snapshot()}
    assert counts.get(("resilience_timeouts",
                       (("op", "all_gather"),))) == 1
    obs.REGISTRY.reset()


def test_call_with_deadline_propagates_fault_scope():
    """Live injection must survive the watchdog's dispatch thread: the
    caller's thread-local FaultScope is inherited, so a scoped guarded
    collective still sees its faults (docs/robustness.md live mode)."""
    from triton_distributed_tpu.lang import primitives as dl

    scope = rz.FaultScope(rz.FaultSpec(rz.FaultKind.DROP_NOTIFY, rank=0))
    seen = {}

    def probe():
        seen["scope"] = dl.active_fault_scope()
        return "done"

    with rz.scoped(scope):
        assert rz.call_with_deadline("x", probe, 5_000) == "done"
    assert seen["scope"] is scope
    # and without a scope, the dispatch thread sees none
    assert rz.call_with_deadline("x", probe, 5_000) == "done"
    assert seen["scope"] is None


def test_suppress_disarms_guards_for_measurement_traffic():
    """Autotune sweeps / warmups must not ride the ladder: both
    resilience.suppress and obs.suppress disarm enabled() on this
    thread (a timed candidate must not burn deadlines, feed fallback
    times to the tuner, or walk the breaker open)."""
    rz.enable(True)
    try:
        assert rz.enabled()
        with rz.suppress():
            assert not rz.enabled()
        with obs.suppress():
            assert not rz.enabled()
        assert rz.enabled()
        seen = []
        g = rz.suppressed_thunk(lambda: seen.append(rz.enabled()))
        g()
        assert seen == [False]
    finally:
        rz.enable(None)   # back to the TDT_RESILIENCE env state


def test_protocol_pending_covers_guarded_families():
    for family in ("allgather", "reduce_scatter", "allreduce",
                   "all_to_all", "ag_gemm", "gemm_rs", "gemm_ar"):
        diag = rz.protocol_pending(family, 4)
        assert diag is not None and diag.pending, family
        assert diag.semaphores(), family


# ---------------------------------------------------------------------------
# policy ladder


def test_retry_then_success():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise rz.CollectiveTimeoutError("op", 1.0)
        return "ok"

    policy = rz.RetryPolicy(max_retries=2, backoff_ms=0.0)
    assert rz.resilient_call("op_a", flaky, policy=policy) == "ok"
    assert calls["n"] == 2
    assert not rz.breaker("op_a").open


def test_fallback_after_retries_exhausted():
    def always_stuck():
        raise rz.CollectiveTimeoutError("op", 1.0)

    policy = rz.RetryPolicy(max_retries=1, backoff_ms=0.0)
    out = rz.resilient_call("op_b", always_stuck,
                            fallback=lambda: "degraded", policy=policy)
    assert out == "degraded"


def test_non_retryable_error_propagates_without_fallback():
    calls = {"n": 0}

    def bad_shapes():
        calls["n"] += 1
        raise ValueError("inner dims mismatch")

    with pytest.raises(ValueError, match="mismatch"):
        rz.resilient_call("op_c", bad_shapes, fallback=lambda: "nope",
                          policy=rz.RetryPolicy(max_retries=3,
                                                backoff_ms=0.0))
    assert calls["n"] == 1   # no retries for caller bugs


def test_breaker_opens_sticky_and_short_circuits():
    calls = {"n": 0}

    def always_stuck():
        calls["n"] += 1
        raise rz.CollectiveTimeoutError("op", 1.0)

    policy = rz.RetryPolicy(max_retries=0, backoff_ms=0.0,
                            breaker_threshold=2)
    for _ in range(2):
        assert rz.resilient_call("op_d", always_stuck,
                                 fallback=lambda: "deg",
                                 policy=policy) == "deg"
    assert rz.breaker("op_d").open
    n_before = calls["n"]
    # open breaker: straight to fallback, the fused thunk never runs
    assert rz.resilient_call("op_d", always_stuck, fallback=lambda: "deg",
                             policy=policy) == "deg"
    assert calls["n"] == n_before
    # sticky: only an explicit reset closes it
    rz.reset_breaker("op_d")
    assert not rz.breaker("op_d").open


def test_open_breaker_without_fallback_raises_circuit_open():
    b = rz.breaker("op_e", threshold=1)
    b.record_failure()
    assert b.open
    with pytest.raises(rz.CircuitOpenError, match="op_e"):
        rz.resilient_call("op_e", lambda: "never")


def test_health_snapshot_reports_breakers_and_counters():
    obs.REGISTRY.reset()
    obs.enable(True)
    try:
        rz.resilient_call(
            "op_f", lambda: (_ for _ in ()).throw(
                rz.CollectiveTimeoutError("op_f", 1.0)),
            fallback=lambda: 1,
            policy=rz.RetryPolicy(max_retries=0, backoff_ms=0.0,
                                  breaker_threshold=1))
    finally:
        obs.enable(None)
    snap = rz.health_snapshot()
    assert snap["status"] == "degraded"
    assert snap["breakers"]["op_f"]["open"]
    assert "op_f" in snap["last_errors"]
    assert any("resilience_degraded_calls" in k for k in snap["counters"])
    obs.REGISTRY.reset()


# ---------------------------------------------------------------------------
# engine integration: per-request deadlines + failed-step isolation
# (needs a jax whose shard_map / interpret APIs exist — the container's
# 0.4.37 lacks them, the seed's pre-existing failure class; skip clean)

from triton_distributed_tpu.core.compilation import interpret_supported

requires_engine = pytest.mark.skipif(
    not interpret_supported(),
    reason="jax lacks shard_map / pallas interpret APIs",
)


def _tiny_engine():
    import jax

    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig

    cfg = ModelConfig(num_layers=1, hidden=64, intermediate=128,
                      num_heads=4, num_kv_heads=2, head_dim=16,
                      vocab=128, max_length=64)
    mesh = mesh_lib.make_mesh({"tp": 1}, devices=jax.devices()[:1])
    return Engine.build(cfg, mesh, key=jax.random.key(0), batch=1)


@requires_engine
def test_engine_serve_within_deadline_and_health_ok():
    import jax.numpy as jnp

    eng = _tiny_engine()
    ids = jnp.zeros((1, 4), jnp.int32)
    tokens, stats = eng.serve(ids, 3, deadline_ms=120_000)
    assert tokens.shape == (1, 3)
    health = eng.health()
    assert health["engine"]["failed_requests"] == 0
    assert health["engine"]["last_failure"] is None


@requires_engine
def test_engine_deadline_breach_isolated_and_recoverable(monkeypatch):
    import jax.numpy as jnp

    eng = _tiny_engine()
    ids = jnp.zeros((1, 4), jnp.int32)
    eng.serve(ids, 2)   # compile everything first

    real_decode = eng.decode_step

    def slow_decode(tok):
        time.sleep(0.4)
        return real_decode(tok)

    monkeypatch.setattr(eng, "decode_step", slow_decode)
    with pytest.raises(rz.CollectiveTimeoutError):
        # warmup is outside the budget; the decode block breaches it
        eng.serve(ids, 4, deadline_ms=100.0)
    health = eng.health()
    assert health["engine"]["failed_requests"] == 1
    assert "CollectiveTimeoutError" in health["engine"]["last_failure"]
    # failed-step isolation: the SAME engine object serves the next
    # request cleanly once the fault is gone
    monkeypatch.setattr(eng, "decode_step", real_decode)
    tokens, _ = eng.serve(ids, 3, deadline_ms=120_000)
    assert tokens.shape == (1, 3)
    assert eng.health()["engine"]["failed_requests"] == 1


# ---------------------------------------------------------------------------
# calibrate: cross-host threshold agreement (ADVICE r5 low #5)


def test_agree_thresholds_single_process_identity():
    from triton_distributed_tpu.tools import calibrate as cal

    assert cal.agree_thresholds(111, 222, n_proc=1) == (111, 222)


def test_agree_thresholds_adopts_mean_on_agreement():
    from triton_distributed_tpu.tools import calibrate as cal

    # simulate 2 hosts with values within tolerance: the "mean" of
    # [v, v2] across hosts — host-symmetric stats injected directly
    hosts = [(256_000.0, 512_000.0), (258_000.0, 516_000.0)]

    def mean_fn(vec):
        per_host = [[p, o, p * p, o * o] for p, o in hosts]
        return [sum(col) / len(col) for col in zip(*per_host)]

    push, one = cal.agree_thresholds(*hosts[0], n_proc=2, mean_fn=mean_fn)
    assert push == 257_000 and one == 514_000


def test_agree_thresholds_cold_defaults_on_disagreement():
    from triton_distributed_tpu.tools import calibrate as cal

    # one host cold (or stale): thresholds differ 4x — every host must
    # fall back to the identical cold defaults
    hosts = [(256_000.0, 512_000.0), (1_024_000.0, 2_048_000.0)]

    def mean_fn(vec):
        per_host = [[p, o, p * p, o * o] for p, o in hosts]
        return [sum(col) / len(col) for col in zip(*per_host)]

    push, one = cal.agree_thresholds(*hosts[0], n_proc=2, mean_fn=mean_fn)
    assert (push, one) == (cal.DEFAULT_PUSH_BYTES,
                           cal.DEFAULT_ONE_SHOT_BYTES)


def test_threshold_agreement_memoized_and_invalidated(monkeypatch):
    from triton_distributed_tpu.tools import calibrate as cal

    cal.invalidate_cache()
    calls = {"n": 0}
    orig = cal.agree_thresholds

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(cal, "agree_thresholds", counting)
    cal.push_bytes_threshold()
    cal.one_shot_bytes_threshold()
    assert calls["n"] == 1          # agreed once per process
    cal.invalidate_cache()
    cal.push_bytes_threshold()
    assert calls["n"] == 2
    cal.invalidate_cache()


# ---------------------------------------------------------------------------
# CLI: the tier-1-visible fault gate


def test_lint_faults_cli():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--faults", "--seed", "2"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "0 problem(s)" in res.stdout
    assert "DETECTED" in res.stdout and "SURVIVED" in res.stdout


def test_resilience_disabled_by_default_keeps_entry_points_unwrapped():
    assert not rz.enabled()
    # the comm entry points consult resilience.enabled() on the eager
    # path; with the gate off the guarded() wrapper must never build
    # (this is the tier-1 "don't change working behavior" contract)
    assert rz.enable(False) is False
    assert rz.enable(None) in (True, False)   # re-reads TDT_RESILIENCE
