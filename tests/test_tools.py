"""Tools: AOT serialize round trip, SOL perf models, profiling helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tools import (
    annotate,
    allreduce_sol_ms,
    allgather_sol_ms,
    aot_compile,
    chip_spec,
    gemm_sol_ms,
    group_profile,
    load,
    overlap_efficiency,
    save,
)


def test_aot_round_trip(tmp_path):
    def f(x, y):
        return jnp.sin(x) @ y

    a = jnp.ones((16, 16), jnp.float32)
    b = jnp.eye(16, dtype=jnp.float32)
    compiled = aot_compile(f, a, b)
    want = np.asarray(compiled(a, b))
    p = str(tmp_path / "f.aotx")
    save(compiled, p)
    re = load(p)
    try:
        got = np.asarray(re(a, b))
    except jax.errors.JaxRuntimeError as exc:
        # XLA:CPU loader quirk (see tools/aot.py docstring): the reloaded
        # executable binds to ALL virtual devices; the serialized artifact
        # itself round-trips — executing it needs matching topology (TPU).
        assert "shards" in str(exc)
        pytest.xfail("XLA:CPU reload rebinds to the full device set")
    np.testing.assert_allclose(got, want)


def test_gemm_sol_monotonic():
    t1 = gemm_sol_ms(1024, 1024, 1024, device_kind="TPU v5e")
    t2 = gemm_sol_ms(2048, 2048, 2048, device_kind="TPU v5e")
    assert 0 < t1 < t2
    # bigger chip is faster
    assert gemm_sol_ms(4096, 4096, 4096, device_kind="TPU v5p") < \
        gemm_sol_ms(4096, 4096, 4096, device_kind="TPU v5e")


def test_collective_sol_scaling():
    # more ranks -> more wire per rank for AG
    assert allgather_sol_ms(1 << 20, 8) > allgather_sol_ms(1 << 20, 2)
    # AR moves ~2x the RS/AG volume at large n
    ar = allreduce_sol_ms(1 << 24, 8)
    ag = allgather_sol_ms((1 << 24) // 8, 8)
    assert ar > ag


def test_chip_spec_fallback():
    assert chip_spec("TPU v5e").name == "TPU v5e"
    assert chip_spec("weird-device").name == "unknown"


def test_overlap_efficiency_bounds():
    assert overlap_efficiency(10.0, 10.0, 5.0) == 1.0   # fully hidden
    assert overlap_efficiency(15.0, 10.0, 5.0) == 0.0   # fully serial
    assert 0.0 < overlap_efficiency(12.0, 10.0, 5.0) < 1.0


def test_profile_and_annotate(tmp_path):
    with group_profile("t", str(tmp_path)) as path:
        with annotate("block"):
            jnp.zeros((8,)).block_until_ready()
    import os
    assert path and os.path.isdir(path)
    with group_profile("t2", str(tmp_path), enabled=False) as path2:
        assert path2 is None


# ---------------------------------------------------------------------------
# native trace merge


def _write_trace(path, pid, n_events):
    import json
    events = [
        {"name": f"op{i}", "ph": "X", "pid": pid, "tid": 1,
         "ts": i * 10, "dur": 5,
         # nested pid + tricky strings: must survive the native scanner
         "args": {"note": 'quote " and ] inside', "pid": 42}}
        for i in range(n_events)
    ]
    with open(path, "w") as f:
        json.dump({"traceEvents": events, "displayTimeUnit": "ns"}, f)


@pytest.mark.parametrize("native", [True, False])
def test_merge_traces_native_and_fallback(tmp_path, native):
    import gzip
    import json

    from triton_distributed_tpu.tools import trace_merge
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    if native:
        # a silent fallback here would fake native coverage
        assert trace_merge._load_native(), "native merger failed to build"

    p0, p1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
    _write_trace(p0, pid=7, n_events=3)
    _write_trace(p1, pid=7, n_events=2)
    out = str(tmp_path / f"merged_{native}.json.gz")
    merge_traces([p0, p1], [0, 1], out, native=native)
    with gzip.open(out) as f:
        merged = json.load(f)
    evs = merged["traceEvents"]
    assert len(evs) == 5
    pids = sorted({e["pid"] for e in evs})
    assert pids == [7, 1000007]  # rank 1 offset by 1e6
    # top-level envelope keys survive the merge
    assert merged["displayTimeUnit"] == "ns"
    # payload strings and NESTED pids pass through untouched
    assert all(e["args"]["note"] == 'quote " and ] inside' for e in evs)
    assert all(e["args"]["pid"] == 42 for e in evs)


def test_merge_traces_float_pid_passthrough(tmp_path):
    import gzip
    import json

    from triton_distributed_tpu.tools.trace_merge import merge_traces

    p = str(tmp_path / "f.json")
    with open(p, "w") as f:
        json.dump({"traceEvents": [{"name": "a", "pid": 1.5, "tid": 0}]}, f)
    for native in (True, False):
        out = str(tmp_path / f"fm_{native}.json.gz")
        merge_traces([p], [1], out, native=native)
        with gzip.open(out) as f:
            merged = json.load(f)
        # non-integer pids are never remapped (matches the int-only policy)
        assert merged["traceEvents"][0]["pid"] == 1.5


def test_merge_traces_native_matches_python(tmp_path):
    import gzip
    import json

    from triton_distributed_tpu.tools.trace_merge import merge_traces

    paths = []
    for r in range(3):
        p = str(tmp_path / f"rank{r}.json")
        _write_trace(p, pid=r + 1, n_events=r + 1)
        paths.append(p)
    out_n = str(tmp_path / "n.json.gz")
    out_p = str(tmp_path / "p.json.gz")
    merge_traces(paths, None, out_n, native=True)
    merge_traces(paths, None, out_p, native=False)
    with gzip.open(out_n) as f:
        a = json.load(f)
    with gzip.open(out_p) as f:
        b = json.load(f)
    assert a == b


def test_overlap_kernels_structure_and_math():
    """tools/overlap.py: the fused probe kernel IS a correct matmul (same
    pipeline it claims to measure), the dma/mxu variants run the same
    grid without error, and hidden_pct's algebra hits the endpoints."""
    import jax
    import jax.numpy as jnp

    from triton_distributed_tpu.tools.overlap import hidden_pct, overlap_kernels

    m = n = k = 256
    fused, dma, mxu = overlap_kernels(m, n, k, bm=128, bn=128, bk=128,
                                      dtype=jnp.float32)
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (k, n), jnp.float32)
    out = fused(a, b)
    assert jnp.allclose(out, a @ b, atol=2e-3, rtol=2e-3)
    # the probes must execute (values are probe artifacts, not matmuls)
    jax.block_until_ready(dma(a, b))
    jax.block_until_ready(mxu(a, b))

    assert hidden_pct(1.25, 0.5, 1.0) == 0.5    # half the DMA hidden
    assert hidden_pct(1.0, 0.6, 1.0) == 1.0     # fused == max: all hidden
    assert hidden_pct(1.6, 0.6, 1.0) == 0.0     # fused == sum: serialized
    assert hidden_pct(2.0, 0.6, 1.0) == 0.0     # noise below zero: clamped
    assert hidden_pct(0.9, 0.6, 1.0) == 1.0     # noise above one: clamped


def test_merge_traces_native_python_byte_identical(tmp_path):
    """On compact inputs with ``traceEvents`` last — the layout
    ``obs.tracing.export`` writes — the native and pure-Python mergers
    must produce BYTE-identical (non-gz) output: the native path splices
    input text, the Python path re-serializes compactly, and any drift
    between them would silently fork the merged-trace format."""
    import json

    from triton_distributed_tpu.tools import trace_merge
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    if not trace_merge._load_native():
        pytest.skip("no C++ toolchain: native merger unavailable")

    paths = []
    for r in range(2):
        events = [
            {"name": f"op{r}_{i}", "cat": "comm", "ph": "X", "pid": 3,
             "tid": r, "ts": 10 * i, "dur": 5,
             "args": {"note": 'tricky "quoted] text', "pid": 9}}
            for i in range(r + 2)
        ]
        p = str(tmp_path / f"rank{r}.json")
        with open(p, "w") as f:
            # compact, traceEvents last — obs.tracing.export's layout
            f.write('{"displayTimeUnit":"ms","traceEvents":'
                    + json.dumps(events, separators=(",", ":")) + "}")
        paths.append(p)

    out_n = str(tmp_path / "native.json")
    out_p = str(tmp_path / "python.json")
    merge_traces(paths, [0, 1], out_n, native=True)
    merge_traces(paths, [0, 1], out_p, native=False)
    a = open(out_n, "rb").read()
    b = open(out_p, "rb").read()
    assert a == b
    merged = json.loads(a)
    assert len(merged["traceEvents"]) == 5
    assert sorted({e["pid"] for e in merged["traceEvents"]}) == [3, 1000003]


def test_obs_export_merge_byte_identical(tmp_path):
    """The real producer path: two ``obs.tracing.export`` files merge
    byte-identically through both merger backends."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.tools import trace_merge
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    if not trace_merge._load_native():
        pytest.skip("no C++ toolchain: native merger unavailable")

    prev = obs.enabled()
    obs.enable(True)
    obs.tracing.clear()
    try:
        paths = []
        for r in range(2):
            with obs.span("decode_step", "step", rank=r):
                pass
            paths.append(obs.tracing.export(str(tmp_path / f"r{r}.json"),
                                            clear_buffer=True))
    finally:
        obs.enable(prev)
    out_n = str(tmp_path / "native.json")
    out_p = str(tmp_path / "python.json")
    merge_traces(paths, [0, 1], out_n, native=True)
    merge_traces(paths, [0, 1], out_p, native=False)
    assert open(out_n, "rb").read() == open(out_p, "rb").read()


def test_group_profile_single_process_path(tmp_path):
    """Single-process: flat ``logdir/name`` (no proc subdir)."""
    import os

    with group_profile("sp", str(tmp_path)) as path:
        jnp.zeros((4,)).block_until_ready()
    assert path == os.path.join(str(tmp_path), "sp")
    assert "proc" not in os.path.basename(path)


def test_group_profile_multi_process_path(tmp_path, monkeypatch):
    """Multi-process: rank-disambiguated ``logdir/name/procN`` subdirs so
    per-host captures on a shared filesystem never clobber each other
    (the docstring's promise; previously the rank was dropped)."""
    import os

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    with group_profile("mp", str(tmp_path)) as path:
        jnp.zeros((4,)).block_until_ready()
    assert path == os.path.join(str(tmp_path), "mp", "proc1")
    assert os.path.isdir(path)


def test_hf_parity_guard_is_loud(tmp_path):
    """tests/test_hf_parity.py's importorskip is LOUD (VERDICT weak #6):
    with TDT_REQUIRE_HF_PARITY=1 (the CI shard that provisions torch),
    missing torch/transformers is a hard error naming the unran parity
    check — a broken provision step cannot silently skip the convention
    validation.  Without the flag the module skips with the warning
    message (unchanged local behavior)."""
    import importlib.util
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import runpy\n"
        "runpy.run_path(%r)\n" % os.path.join(repo, "tests",
                                              "test_hf_parity.py")
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "TDT_REQUIRE_HF_PARITY": "1"}
    proc = subprocess.run([sys.executable, "-c", script], cwd=repo,
                          capture_output=True, text=True, timeout=300,
                          env=env)
    have_hf = all(importlib.util.find_spec(m) is not None
                  for m in ("torch", "transformers"))
    if have_hf:
        assert proc.returncode == 0, proc.stderr
    else:
        assert proc.returncode != 0
        assert "TDT_REQUIRE_HF_PARITY" in proc.stderr
        assert "parity" in proc.stderr
