"""Tools: AOT serialize round trip, SOL perf models, profiling helpers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tools import (
    annotate,
    allreduce_sol_ms,
    allgather_sol_ms,
    aot_compile,
    chip_spec,
    gemm_sol_ms,
    group_profile,
    load,
    overlap_efficiency,
    save,
)


def test_aot_round_trip(tmp_path):
    def f(x, y):
        return jnp.sin(x) @ y

    a = jnp.ones((16, 16), jnp.float32)
    b = jnp.eye(16, dtype=jnp.float32)
    compiled = aot_compile(f, a, b)
    want = np.asarray(compiled(a, b))
    p = str(tmp_path / "f.aotx")
    save(compiled, p)
    re = load(p)
    try:
        got = np.asarray(re(a, b))
    except jax.errors.JaxRuntimeError as exc:
        # XLA:CPU loader quirk (see tools/aot.py docstring): the reloaded
        # executable binds to ALL virtual devices; the serialized artifact
        # itself round-trips — executing it needs matching topology (TPU).
        assert "shards" in str(exc)
        pytest.xfail("XLA:CPU reload rebinds to the full device set")
    np.testing.assert_allclose(got, want)


def test_gemm_sol_monotonic():
    t1 = gemm_sol_ms(1024, 1024, 1024, device_kind="TPU v5e")
    t2 = gemm_sol_ms(2048, 2048, 2048, device_kind="TPU v5e")
    assert 0 < t1 < t2
    # bigger chip is faster
    assert gemm_sol_ms(4096, 4096, 4096, device_kind="TPU v5p") < \
        gemm_sol_ms(4096, 4096, 4096, device_kind="TPU v5e")


def test_collective_sol_scaling():
    # more ranks -> more wire per rank for AG
    assert allgather_sol_ms(1 << 20, 8) > allgather_sol_ms(1 << 20, 2)
    # AR moves ~2x the RS/AG volume at large n
    ar = allreduce_sol_ms(1 << 24, 8)
    ag = allgather_sol_ms((1 << 24) // 8, 8)
    assert ar > ag


def test_chip_spec_fallback():
    assert chip_spec("TPU v5e").name == "TPU v5e"
    assert chip_spec("weird-device").name == "unknown"


def test_overlap_efficiency_bounds():
    assert overlap_efficiency(10.0, 10.0, 5.0) == 1.0   # fully hidden
    assert overlap_efficiency(15.0, 10.0, 5.0) == 0.0   # fully serial
    assert 0.0 < overlap_efficiency(12.0, 10.0, 5.0) < 1.0


def test_profile_and_annotate(tmp_path):
    with group_profile("t", str(tmp_path)) as path:
        with annotate("block"):
            jnp.zeros((8,)).block_until_ready()
    import os
    assert path and os.path.isdir(path)
    with group_profile("t2", str(tmp_path), enabled=False) as path2:
        assert path2 is None
