"""ReduceScatter ring kernel vs stacked-sum golden (reference
``test_reduce_scatter.py``)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import ReduceScatterConfig, reduce_scatter
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh, shard
from triton_distributed_tpu.core.utils import assert_allclose, rand_tensor


def _golden(x, n):
    # device r holds partial rows [r*M:(r+1)*M]; sum the n stacked partials
    m = x.shape[0] // n
    return x.reshape(n, m, x.shape[1]).astype(jnp.float32).sum(0)


@pytest.mark.parametrize("m,r,dtype", [
    (64, 128, jnp.float32),
    (128, 256, jnp.bfloat16),
])
def test_reduce_scatter_matches_golden(mesh8, m, r, dtype):
    n = 8
    x = rand_tensor((n * m, r), dtype, scale=0.1)
    xs = shard(mesh8, x, TP_AXIS)
    out = reduce_scatter(xs, mesh8, TP_AXIS,
                         config=ReduceScatterConfig(bm=8, bn=128))
    assert out.shape == (m, r)
    golden = _golden(x, n).astype(out.dtype)
    tol = 1e-4 if dtype == jnp.float32 else 1e-1
    assert_allclose(out.astype(jnp.float32), golden.astype(jnp.float32),
                    atol=tol, rtol=tol, name="reduce_scatter")


def test_reduce_scatter_repeat(mesh8):
    """Second in-process invocation: semaphore drains must leave no residue."""
    n, m, r = 8, 64, 128
    x = rand_tensor((n * m, r), jnp.float32, scale=0.1)
    xs = shard(mesh8, x, TP_AXIS)
    cfg = ReduceScatterConfig(bm=8, bn=128)
    out1 = reduce_scatter(xs, mesh8, TP_AXIS, config=cfg)
    out2 = reduce_scatter(xs, mesh8, TP_AXIS, config=cfg)
    assert_allclose(out1, out2, atol=0, rtol=0, name="rs-repeat")


def test_reduce_scatter_two_ranks():
    mesh2 = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    x = rand_tensor((2 * 16, 128), jnp.float32, scale=0.1)
    xs = jax.device_put(x, NamedSharding(mesh2, P(TP_AXIS)))
    out = reduce_scatter(xs, mesh2, TP_AXIS)
    assert_allclose(out, _golden(x, 2).astype(out.dtype), atol=1e-4, rtol=1e-4)


def test_reduce_scatter_three_ranks():
    """Odd ring size exercises the n==3 drain path."""
    mesh3 = make_mesh({TP_AXIS: 3}, devices=jax.devices()[:3])
    x = rand_tensor((3 * 24, 128), jnp.float32, scale=0.1)
    xs = jax.device_put(x, NamedSharding(mesh3, P(TP_AXIS)))
    out = reduce_scatter(xs, mesh3, TP_AXIS)
    assert_allclose(out, _golden(x, 3).astype(out.dtype), atol=1e-4, rtol=1e-4)
