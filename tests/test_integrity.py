"""The end-to-end data-integrity plane (ISSUE 7): checksummed
collective payloads, corruption fault classes, KV-page audit, and
quarantine recovery.

Everything here is headless (CPU-only, no kernels): the checksum
protocol is exercised through record-mode traces, the live verifiers
through host arrays, the ladder/quarantine through thunk doubles, and
the KV audit through the deterministic SimBackend over the real
paged-cache plumbing.
"""

import dataclasses
import os
import subprocess
import sys

import numpy as np
import pytest

from triton_distributed_tpu import obs, resilience as rz, serve
from triton_distributed_tpu.resilience import integrity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def integrity_on():
    prev = integrity._ENABLED
    integrity.enable(True)
    rz.policy._reset_state_for_tests()
    yield integrity
    integrity.enable(prev)
    rz.policy._reset_state_for_tests()


@pytest.fixture()
def obs_on():
    prev = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    yield obs
    obs.enable(prev)
    obs.REGISTRY.reset()


# ---------------------------------------------------------------------------
# the fold


def test_fold32_sees_value_position_and_duplicates():
    a = np.arange(32, dtype=np.float32)
    assert integrity.fold32(a) == integrity.fold32(a.copy())
    # value change
    b = a.copy()
    b[7] += 1
    assert integrity.fold32(b) != integrity.fold32(a)
    # position change (same multiset of words — an XOR/sum fold is
    # blind to this)
    assert integrity.fold32(a[::-1].copy()) != integrity.fold32(a)
    # duplicated-word payloads (broadcast KV tiles): flipping one of N
    # identical words must still move the fold
    c = np.full((4, 8), 1000.0, np.float32)
    d = c.copy()
    d[2, 3] = 0.0
    assert integrity.fold32(c) != integrity.fold32(d)
    # dtype-agnostic byte exactness
    assert integrity.fold32(a.view(np.int32)) == integrity.fold32(a)


def test_fold32_sees_high_bit_flips_at_every_position():
    # the position weight A*i+B (odd constants) is EVEN at every odd i,
    # where a ±2^31 word delta (a float32 sign-bit flip — the canonical
    # SDC) would cancel in the surviving low 32 bits; the `| 1` in the
    # weight is what makes this pass
    x = np.arange(64, dtype=np.float32) + 1.0
    base = integrity.fold32(x)
    for pos in range(x.size):
        for bit in (31, 30):
            r = x.copy().view(np.uint32)
            r[pos] ^= np.uint32(1 << bit)
            assert integrity.fold32(r.view(np.float32)) != base, (pos, bit)


def test_verify_reduce_catches_small_magnitude_corruption():
    # the per-element bound must scale with the ACCUMULATED magnitude
    # (sum of |partials|), not the global max of the result: partials
    # ~1 that cancel to ~0 leave an element a global-max bound would
    # let be corrupted by ~rtol*max undetected
    rng = np.random.default_rng(0)
    n, m, r = 4, 8, 8
    parts = rng.normal(size=(n * m, r)).astype(np.float32) * 2.0
    for k in range(n):                       # partials ±1, sum ≈ 0
        parts[k * m + 2, 3] = (-1.0) ** k * 1.0
    parts[2, 3] += -1e-4
    out = parts.reshape(n, m, r).sum(axis=0)
    assert abs(out[2, 3]) < 1e-3
    assert integrity.verify_reduce("ar", parts, out, n) is None
    bad = out.copy()
    bad[2, 3] = 0.074                        # far beyond rounding noise
    d = integrity.verify_reduce("ar", parts, bad, n)
    assert d is not None and d.chunk == "out[2]"


# ---------------------------------------------------------------------------
# record-mode checksum protocol


def _case(name: str, n: int = 4):
    from triton_distributed_tpu.analysis.registry import all_cases

    return next(c for c in all_cases(ranks=(n,)) if c.name == name)


def test_corrupt_payload_trace_names_sem_chunk_peer():
    case = _case("allgather/push_1shot")
    spec = rz.FaultSpec(rz.FaultKind.CORRUPT_PAYLOAD, rank=1, nth=0)
    ft = rz.record_faulty_case(case, spec)
    assert ft.fired and ft.corrupt
    findings = integrity.check_traces(ft)
    assert findings, "in-flight corruption must be caught at consumption"
    d = findings[0]
    assert d.kind == "payload"
    assert d.sem and d.chunk
    assert d.peer == 1            # the victim's own pushes carry its rank
    # liveness is untouched: the protocol still completes cleanly
    rz.run_bounded(ft)
    assert rz.check_hazards(ft) == []


def test_corrupt_kv_page_trace_names_poisoned_region():
    case = _case("allgather/push_1shot")
    spec = rz.FaultSpec(rz.FaultKind.CORRUPT_KV_PAGE, rank=2, nth=1)
    ft = rz.record_faulty_case(case, spec)
    assert ft.fired and ft.poisoned
    findings = integrity.check_traces(ft)
    assert findings
    assert findings[0].kind == "kv_page"
    assert findings[0].sem and findings[0].chunk


def test_clean_traces_have_no_findings():
    case = _case("reduce_scatter/ring")
    # unreachable nth records clean traces (simulate.clean_ticks trick)
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.CORRUPT_PAYLOAD, rank=0,
                           nth=10 ** 9))
    assert integrity.check_traces(ft) == []


def test_matrix_corruption_cells_all_detected():
    rows = rz.run_matrix(seed=0, kinds=rz.CORRUPTION_KINDS)
    # both classes x all 13 kernel cases (fused_mlp_ar since ISSUE 8;
    # quant_allgather/push_1shot + quant_exchange/oneshot since ISSUE 9;
    # hier_allreduce/2x2 + hier_a2a/2x2 since ISSUE 10;
    # persistent_decode/chain since ISSUE 13; ag_gemm/unidir since
    # ISSUE 15 — the completeness lint found it uncovered)
    assert len(rows) == 26
    for row in rows:
        assert row["outcome"] == "detected", row
        assert row["named"], row
    assert rz.verify_matrix(rows, kinds=rz.CORRUPTION_KINDS) == []


# ---------------------------------------------------------------------------
# the fault-matrix SHAPE golden (ISSUE 7 satellite): adding a FaultKind
# without matrix coverage must fail LOUDLY here, not silently shrink
# the guarantee


# delay_notify applies only to kernels with a flat ``notify`` (the ring
# pipelines); the pure-DMA protocols (push AG, A2A zones) have no
# signal whose delivery can be delayed from the host side
MATRIX_GOLDEN = {
    ("allgather/push_1shot", "drop_notify"),
    ("allgather/push_1shot", "stale_credit"),
    ("allgather/push_1shot", "straggler"),
    ("allgather/push_1shot", "rank_abort"),
    ("allgather/push_1shot", "corrupt_payload"),
    ("allgather/push_1shot", "corrupt_kv_page"),
    ("reduce_scatter/ring", "drop_notify"),
    ("reduce_scatter/ring", "delay_notify"),
    ("reduce_scatter/ring", "stale_credit"),
    ("reduce_scatter/ring", "straggler"),
    ("reduce_scatter/ring", "rank_abort"),
    ("reduce_scatter/ring", "corrupt_payload"),
    ("reduce_scatter/ring", "corrupt_kv_page"),
    ("allreduce/two_shot", "drop_notify"),
    ("allreduce/two_shot", "delay_notify"),
    ("allreduce/two_shot", "stale_credit"),
    ("allreduce/two_shot", "straggler"),
    ("allreduce/two_shot", "rank_abort"),
    ("allreduce/two_shot", "corrupt_payload"),
    ("allreduce/two_shot", "corrupt_kv_page"),
    ("all_to_all/dispatch", "drop_notify"),
    ("all_to_all/dispatch", "stale_credit"),
    ("all_to_all/dispatch", "straggler"),
    ("all_to_all/dispatch", "rank_abort"),
    ("all_to_all/dispatch", "corrupt_payload"),
    ("all_to_all/dispatch", "corrupt_kv_page"),
    # ag_gemm: the one family the ISSUE-15 completeness lint found with
    # no fault coverage (pure-DMA protocol: no delay_notify target)
    ("ag_gemm/unidir", "drop_notify"),
    ("ag_gemm/unidir", "stale_credit"),
    ("ag_gemm/unidir", "straggler"),
    ("ag_gemm/unidir", "rank_abort"),
    ("ag_gemm/unidir", "corrupt_payload"),
    ("ag_gemm/unidir", "corrupt_kv_page"),
    ("gemm_rs/ring", "drop_notify"),
    ("gemm_rs/ring", "delay_notify"),
    ("gemm_rs/ring", "stale_credit"),
    ("gemm_rs/ring", "straggler"),
    ("gemm_rs/ring", "rank_abort"),
    ("gemm_rs/ring", "corrupt_payload"),
    ("gemm_rs/ring", "corrupt_kv_page"),
    ("gemm_ar/ring", "drop_notify"),
    ("gemm_ar/ring", "delay_notify"),
    ("gemm_ar/ring", "stale_credit"),
    ("gemm_ar/ring", "straggler"),
    ("gemm_ar/ring", "rank_abort"),
    ("gemm_ar/ring", "corrupt_payload"),
    ("gemm_ar/ring", "corrupt_kv_page"),
    # the decode megakernel's semaphore-chained MLP+AllReduce (ISSUE 8)
    ("fused_mlp_ar/swiglu", "drop_notify"),
    ("fused_mlp_ar/swiglu", "delay_notify"),
    ("fused_mlp_ar/swiglu", "stale_credit"),
    ("fused_mlp_ar/swiglu", "straggler"),
    ("fused_mlp_ar/swiglu", "rank_abort"),
    ("fused_mlp_ar/swiglu", "corrupt_payload"),
    ("fused_mlp_ar/swiglu", "corrupt_kv_page"),
    # the ISSUE-9 quantized wire variants at their packed-u8 shapes
    ("quant_allgather/push_1shot", "drop_notify"),
    ("quant_allgather/push_1shot", "stale_credit"),
    ("quant_allgather/push_1shot", "straggler"),
    ("quant_allgather/push_1shot", "rank_abort"),
    ("quant_allgather/push_1shot", "corrupt_payload"),
    ("quant_allgather/push_1shot", "corrupt_kv_page"),
    ("quant_exchange/oneshot", "drop_notify"),
    ("quant_exchange/oneshot", "stale_credit"),
    ("quant_exchange/oneshot", "straggler"),
    ("quant_exchange/oneshot", "rank_abort"),
    ("quant_exchange/oneshot", "corrupt_payload"),
    ("quant_exchange/oneshot", "corrupt_kv_page"),
    # the ISSUE-10 two-level (ICI x DCN) families at the 2x2 layout —
    # the inter-slice credit protocol in the injection loop (the other
    # layouts ride `tdt_lint --hier`); the AR composition's ring RS
    # carries notifies, so delay_notify applies there but not to the
    # pure-DMA scheduled A2A
    ("hier_allreduce/2x2", "drop_notify"),
    ("hier_allreduce/2x2", "delay_notify"),
    ("hier_allreduce/2x2", "stale_credit"),
    ("hier_allreduce/2x2", "straggler"),
    ("hier_allreduce/2x2", "rank_abort"),
    ("hier_allreduce/2x2", "corrupt_payload"),
    ("hier_allreduce/2x2", "corrupt_kv_page"),
    ("hier_a2a/2x2", "drop_notify"),
    ("hier_a2a/2x2", "stale_credit"),
    ("hier_a2a/2x2", "straggler"),
    ("hier_a2a/2x2", "rank_abort"),
    ("hier_a2a/2x2", "corrupt_payload"),
    ("hier_a2a/2x2", "corrupt_kv_page"),
    # the persistent multi-layer decode chain (ISSUE 13): 2L ring
    # reductions on one re-armed semaphore set — every class must land
    # somewhere in the chain, with the inter-layer semaphores nameable
    ("persistent_decode/chain", "drop_notify"),
    ("persistent_decode/chain", "delay_notify"),
    ("persistent_decode/chain", "stale_credit"),
    ("persistent_decode/chain", "straggler"),
    ("persistent_decode/chain", "rank_abort"),
    ("persistent_decode/chain", "corrupt_payload"),
    ("persistent_decode/chain", "corrupt_kv_page"),
}

SCHEDULER_GOLDEN = {
    ("rank_abort", "abort"),
    ("straggler", "slack"),
    ("straggler", "overrun"),
    ("corrupt_kv_page", "poison"),
}

# the disaggregated-handoff cells (ISSUE 12): one per HandoffFault
# class.  A class added to serve.handoff.HandoffFault without a matrix
# cell fails below with the diff as the message (the PR-7 discipline).
HANDOFF_GOLDEN = {
    ("transfer_drop", "reprefill"),
    ("corrupt_page_in_flight", "retry"),
    ("stale_stamp", "retry"),
    ("prefill_rank_abort", "reprefill"),
    ("decode_saturated", "colocate"),
}


def test_fault_matrix_shape_pinned():
    """A golden listing of every (kernel x fault-class) cell: a new
    ``FaultKind`` that the matrix does not exercise shows up as a
    missing golden entry; a silently-dropped cell shows up as a missing
    run entry.  Either way the diff is the error message."""
    rows = rz.run_matrix(seed=0)
    cells = {(r["kernel"], r["fault"]) for r in rows}
    assert cells == MATRIX_GOLDEN, (
        f"matrix shape drifted: +{sorted(cells - MATRIX_GOLDEN)} "
        f"-{sorted(MATRIX_GOLDEN - cells)}")
    sched = {(r["fault"], r["leg"]) for r in rz.run_scheduler_matrix(0)}
    assert sched == SCHEDULER_GOLDEN, (
        f"scheduler cells drifted: +{sorted(sched - SCHEDULER_GOLDEN)} "
        f"-{sorted(SCHEDULER_GOLDEN - sched)}")
    # every declared fault class appears SOMEWHERE (kernel matrix or
    # scheduler cells): this is the line that fails when a FaultKind is
    # added without coverage
    covered = {f for _, f in cells} | {f for f, _ in sched}
    assert covered == {k.value for k in rz.FAULT_KINDS}, (
        f"fault class(es) without any matrix cell: "
        f"{sorted({k.value for k in rz.FAULT_KINDS} - covered)}")
    # the handoff threat model (ISSUE 12) keeps the same discipline:
    # the cell listing is pinned AND every HandoffFault class must have
    # a cell — adding a class without one fails with the diff
    from triton_distributed_tpu.serve import HANDOFF_FAULT_KINDS

    hand = {(r["fault"], r["leg"]) for r in rz.run_handoff_matrix(0)}
    assert hand == HANDOFF_GOLDEN, (
        f"handoff cells drifted: +{sorted(hand - HANDOFF_GOLDEN)} "
        f"-{sorted(HANDOFF_GOLDEN - hand)}")
    assert {f for f, _ in hand} == \
        {k.value for k in HANDOFF_FAULT_KINDS}, (
        f"handoff fault class(es) without any matrix cell: "
        f"{sorted({k.value for k in HANDOFF_FAULT_KINDS} - {f for f, _ in hand})}")


# ---------------------------------------------------------------------------
# live verifiers + selftest


def test_live_verifier_selftest_battery():
    assert integrity.run_selftest() == []
    rz.policy._reset_state_for_tests()   # the selftest's probe peer


def test_verify_gather_attributes_the_peer():
    x = np.arange(64, dtype=np.float32).reshape(16, 4)
    bad = x.copy()
    bad[9, 1] += 3.0                       # chunk 2 of 4 (rows 8..12)
    d = integrity.verify_gather("all_gather", x, bad, 4)
    assert d is not None and d.peer == 2
    assert "recv_sems[2]" == d.sem


# ---------------------------------------------------------------------------
# the ladder: corruption -> retry -> fallback -> quarantine


def test_corruption_rides_ladder_to_fallback(obs_on, integrity_on):
    """A checked thunk that keeps returning corrupt data burns its
    retry, then the ladder serves the XLA-fallback result; the
    integrity counters reflect the checks."""
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    bad = x.copy()
    bad.reshape(-1)[3] += 5.0

    checked = integrity.checked(
        "all_gather", lambda: bad, ranks=4,
        verify=lambda out: integrity.verify_gather("all_gather", x, out, 4))
    out = rz.resilient_call(
        "all_gather", checked, fallback=lambda: x,
        policy=rz.RetryPolicy(max_retries=1, backoff_ms=0.0))
    np.testing.assert_array_equal(out, x)
    counts = {(r["name"], r["labels"].get("kind")): r["value"]
              for r in obs.REGISTRY.snapshot()}
    assert counts.get(("integrity_checks", None)) == 2    # first + retry
    assert counts.get(("integrity_failures", "payload")) == 2


def test_repeated_corruption_quarantines_the_peer(obs_on, integrity_on):
    """Attributable corruption from one peer walks its quarantine
    breaker open; once open, every guarded call with a fallback routes
    straight to the fallback and /healthz surfaces the peer."""
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    bad = x.copy()
    bad[2, 1] += 9.0                        # chunk 1 -> peer 1

    def one_call():
        checked = integrity.checked(
            "all_gather", lambda: bad, ranks=4,
            verify=lambda out: integrity.verify_gather(
                "all_gather", x, out, 4))
        return rz.resilient_call(
            "all_gather", checked, fallback=lambda: x,
            policy=rz.RetryPolicy(max_retries=0, backoff_ms=0.0,
                                  breaker_threshold=100))

    for _ in range(integrity.quarantine_threshold()):
        one_call()
    assert integrity.quarantined_peers() == [1]
    snap = rz.health_snapshot()
    assert snap["quarantined_peers"] == [1]
    assert snap["status"] == "degraded"     # open breaker => /healthz 503

    # the quarantine rung: calls now route straight to the fallback
    # WITHOUT running the corrupt thunk
    ran = []
    out = rz.resilient_call(
        "all_gather", lambda: ran.append(1) or bad,
        fallback=lambda: x, ranks=4)
    np.testing.assert_array_equal(out, x)
    assert not ran
    degraded = [r for r in obs.REGISTRY.snapshot()
                if r["name"] == "resilience_degraded_calls"
                and r["labels"].get("reason") == "quarantined_peer"]
    assert degraded and degraded[0]["value"] >= 1

    integrity.reset_quarantine(1)
    assert integrity.quarantined_peers() == []


def test_verify_budget_widens_guarded_deadline(integrity_on,
                                               monkeypatch):
    """The consumer-side check runs INSIDE the watchdog deadline; a
    wire-SOL budget alone would time out every verified call on a fast
    slice, so guarded() must add the verification-cost term when
    integrity is armed — and exactly zero when it is not."""
    payload = 64 << 20
    budget = integrity.verify_budget_ms(payload, 4)
    assert budget > 100.0
    seen = {}

    def spy(op, thunk, deadline_ms, **kw):
        seen[op] = deadline_ms
        return thunk()

    monkeypatch.setattr(rz.watchdog, "call_with_deadline", spy)
    rz.guarded("all_gather", lambda: 1, payload_bytes=payload, ranks=4)()
    base = rz.deadline_ms("all_gather", payload_bytes=payload,
                          num_ranks=4)
    assert seen["all_gather"] == pytest.approx(base + budget)
    integrity.enable(False)
    assert integrity.verify_budget_ms(payload, 4) == 0.0
    rz.guarded("all_gather", lambda: 1, payload_bytes=payload, ranks=4)()
    assert seen["all_gather"] == pytest.approx(base)


def test_verify_reduce_tolerates_wire_dtype_accumulation():
    """Legitimate bf16 ring-accumulation rounding ((n-1) steps in the
    wire dtype) must NOT read as corruption — a deterministic false
    positive would permanently degrade a healthy op — while a real flip
    still lands orders of magnitude outside the scaled bound."""
    import jax.numpy as jnp

    n, m, r = 8, 16, 32
    rng = np.random.default_rng(11)
    x = rng.standard_normal((n * m, r)).astype(np.float32)
    import ml_dtypes

    bf16 = np.asarray(jnp.zeros((), jnp.bfloat16)).dtype
    # worst-case legitimate drift: every element off by (n-1) half-ulps
    exact = x.reshape(n, m, r).sum(0)
    eps = float(ml_dtypes.finfo(bf16).eps)
    drifted = (exact * (1.0 + (n - 1) * eps / 2)).astype(bf16)
    assert integrity.verify_reduce("all_reduce", x, drifted, n) is None
    flipped = exact.astype(bf16).copy()
    flipped[3, 4] = -flipped[3, 4] + 2 ** 4   # sign/exponent-scale flip
    assert integrity.verify_reduce("all_reduce", x, flipped, n) \
        is not None


def test_unattributable_corruption_never_quarantines(integrity_on):
    xs = np.ones((16, 4), np.float32)
    out = xs.reshape(4, 4, 4).sum(0)
    bad = out.copy()
    bad[0, 0] += 100.0
    d = integrity.verify_reduce("all_reduce", xs, bad, 4)
    assert d is not None and d.peer is None
    assert not integrity.note_corruption("all_reduce", d.peer)
    assert integrity.quarantined_peers() == []


def test_corrupt_result_acts_even_after_trace_time_firing():
    """Through a REAL kernel the trace-time hooks find the nth target
    first (fired=True, live_unsupported noted — they cannot act); the
    entry-level flip must still happen, exactly once."""
    scope = rz.FaultScope(
        rz.FaultSpec(rz.FaultKind.CORRUPT_PAYLOAD, rank=0, nth=0))
    assert scope.on_remote_copy(None, None, None, None, 0) == "corrupt"
    assert scope.fired
    out = scope.corrupt_result(np.zeros(8, np.float32))
    assert out.any(), "the live flip must act despite fired=True"
    out2 = scope.corrupt_result(out.copy())
    np.testing.assert_array_equal(out2, out)   # flips exactly once


def test_selftest_survives_zero_quarantine_threshold(monkeypatch):
    monkeypatch.setenv("TDT_QUARANTINE_THRESHOLD", "0")
    assert integrity.run_selftest() == []
    rz.policy._reset_state_for_tests()


def test_fold_pages_matches_fold_page():
    import jax.numpy as jnp

    from triton_distributed_tpu.models.kv_cache import PagedKVCache

    rng = np.random.default_rng(5)
    k = jnp.asarray(rng.standard_normal((2, 6, 1, 4, 8)).astype(np.float32))
    v = jnp.asarray(rng.standard_normal((2, 6, 1, 4, 8)).astype(np.float32))
    cache = PagedKVCache(k=k, v=v,
                         block_table=jnp.zeros((1, 6), jnp.int32),
                         seq_lens=jnp.zeros((1,), jnp.int32))
    batched = integrity.fold_pages(cache, [1, 3, 4])
    assert batched == {p: integrity.fold_page(cache, p) for p in (1, 3, 4)}
    assert integrity.fold_pages(cache, []) == {}


def test_live_fault_scope_injects_through_checked(integrity_on):
    """The LIVE corrupt_payload lever: a clean thunk inside a fault
    scope comes out flipped, and the consumer-side check catches it."""
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    scope = rz.FaultScope(
        rz.FaultSpec(rz.FaultKind.CORRUPT_PAYLOAD, rank=0, nth=5))
    checked = integrity.checked(
        "all_gather", lambda: x.copy(), ranks=4,
        verify=lambda out: integrity.verify_gather("all_gather", x, out, 4))
    with rz.scoped(scope):
        with pytest.raises(rz.PayloadCorruption) as ei:
            checked()
    assert scope.fired
    assert ei.value.diagnosis is not None
    assert ei.value.diagnosis.chunk


# ---------------------------------------------------------------------------
# EP fallbacks (ISSUE 7 satellite: the full ladder on all 8 entries)


def _ep_case(n=4, t=16, h=8, seed=0):
    rng = np.random.default_rng(seed)
    e_tot = 2 * n
    xs, sps = [], []
    for r in range(n):
        w = rng.random(e_tot)
        split = np.floor(w / w.sum() * t).astype(np.int32)
        split[0] += t - split.sum()
        tag = (r * 1000 + np.arange(t)).astype(np.float32)
        xs.append(np.broadcast_to(tag[:, None], (t, h)).copy())
        sps.append(split)
    return np.concatenate(xs), np.concatenate(sps)


class _MeshLike:
    def __init__(self, n, axis="ep"):
        self.shape = {axis: n}


def test_xla_ep_fallbacks_round_trip_and_zone_golden():
    from triton_distributed_tpu.comm.all_to_all import AllToAllConfig
    from triton_distributed_tpu.resilience.fallbacks import (
        xla_ep_combine, xla_ep_dispatch,
    )

    n, t, h = 4, 16, 8
    x, splits = _ep_case(n, t, h)
    mesh = _MeshLike(n)
    cfg = AllToAllConfig(chunk=8)
    recv, recv_splits = xla_ep_dispatch(x, splits, mesh, "ep", config=cfg)
    epr = splits.shape[0] // n // n
    sp = splits.reshape(n, n * epr)
    for dst in range(n):
        for src in range(n):
            cnt = sp[src, dst * epr:(dst + 1) * epr].sum()
            start = sp[src, :dst * epr].sum()
            want = src * 1000 + np.arange(start, start + cnt)
            np.testing.assert_array_equal(
                np.asarray(recv)[dst * n + src, :cnt, 0], want)
            np.testing.assert_array_equal(
                np.asarray(recv_splits)[dst * n + src],
                sp[src, dst * epr:(dst + 1) * epr])
    back = xla_ep_combine(recv, splits, mesh, "ep", token_dim=t,
                          config=cfg)
    np.testing.assert_allclose(np.asarray(back), x)
    # the zone verifiers pass the fallback's own layout (clean path)
    assert integrity.verify_ep_dispatch(
        "ep_dispatch", x, splits, (recv, recv_splits), n) is None
    assert integrity.verify_ep_combine(
        "ep_combine", recv, splits, back, n, t) is None


def test_ep_ladder_degrades_to_zone_fallback():
    """The satellite contract: a stalled EP dispatch now has a rung
    below the watchdog — the ladder serves the zone-layout fallback
    instead of propagating the timeout."""
    from triton_distributed_tpu.comm.all_to_all import AllToAllConfig
    from triton_distributed_tpu.resilience.fallbacks import xla_ep_dispatch

    n, t, h = 4, 16, 8
    x, splits = _ep_case(n, t, h, seed=3)
    mesh = _MeshLike(n)
    cfg = AllToAllConfig(chunk=8)
    rz.policy._reset_state_for_tests()

    def stuck():
        raise rz.CollectiveTimeoutError("ep_dispatch", 1.0)

    recv, recv_splits = rz.resilient_call(
        "ep_dispatch", stuck,
        fallback=lambda: xla_ep_dispatch(x, splits, mesh, "ep",
                                         config=cfg),
        policy=rz.RetryPolicy(max_retries=0, backoff_ms=0.0))
    assert np.asarray(recv).shape[0] == n * n
    assert np.asarray(recv_splits).shape == (n * n,
                                             splits.shape[0] // n // n)
    rz.policy._reset_state_for_tests()


# ---------------------------------------------------------------------------
# KV-pool audit: poison -> detect -> preemption-recompute recovery


def _expected_tokens(backend, req):
    return backend.expected_tokens(req)


def _run_poisoned(poison: bool, *, pool_pages=32):
    backend = serve.SimBackend(slots=3, page_size=4, pool_pages=pool_pages,
                               max_length=64)
    sched = serve.Scheduler(backend, serve.SchedulerConfig(
        kv_audit_interval_steps=2))
    reqs = [serve.Request(prompt=(11 + i, 12 + i, 13 + i, 14 + i, 15 + i),
                          max_new_tokens=9, priority=i)
            for i in range(3)]
    for r in reqs:
        sched.submit(r)
    fired = False
    for _ in range(400):
        res = sched.step()
        if poison and not fired:
            cand = next(
                (s for s in sched.slots
                 if s is not None and s.page_stamps
                 and s.request.state is serve.RequestState.DECODE), None)
            if cand is not None:
                page = int(cand.pages[max(cand.page_stamps)])
                sched.cache = dataclasses.replace(
                    sched.cache,
                    k=sched.cache.k.at[:, page].add(1000.0))
                fired = True
        if res.idle and (fired or not poison):
            break
    return backend, sched, reqs, fired


def test_kv_poison_recovery_matches_unpressured_run(integrity_on):
    """The acceptance pin: a poisoned-and-recovered run produces
    byte-identical tokens to an unpoisoned run — recovery through the
    preemption-recompute path is invisible in outputs."""
    b0, s0, clean_reqs, _ = _run_poisoned(False)
    b1, s1, poisoned_reqs, fired = _run_poisoned(True)
    assert fired
    assert s0.kv_corruptions == [] and s0.preemptions == 0
    assert s1.kv_corruptions, "the audit must name the poisoned page"
    assert s1.preemptions >= 1
    assert {"req_id", "page", "logical", "step"} <= \
        set(s1.kv_corruptions[0])
    for r in poisoned_reqs:
        assert r.state is serve.RequestState.DONE
        assert r.tokens == _expected_tokens(b1, r)
    assert {tuple(r.prompt): tuple(r.tokens) for r in clean_reqs} == \
        {tuple(r.prompt): tuple(r.tokens) for r in poisoned_reqs}
    assert s1.pool.used_pages == 0


def test_kv_audit_off_is_byte_identical_bookkeeping():
    """TDT_INTEGRITY unset: no stamps, no audits, no corruption
    entries, no kv_stamps carried — the scheduler path is untouched."""
    assert not integrity.enabled()
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=9,
                               max_length=48)
    sched = serve.Scheduler(backend)
    reqs = [serve.Request(prompt=(3, 4, 5, 6, 7, 8), max_new_tokens=8)
            for _ in range(3)]
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle(max_steps=400)
    assert sched.kv_corruptions == []
    assert all(s is None or not s.page_stamps for s in sched.slots)
    assert all(r.kv_stamps is None for r in reqs)


def test_verify_on_preempt_restore_fails_divergent_recompute(
        integrity_on):
    """A preempted request whose carried stamp does not match the
    recomputed page must FAIL with the corruption named — shipping
    either copy would ship bytes nobody can vouch for."""
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=7,
                               max_length=48)
    sched = serve.Scheduler(backend, serve.SchedulerConfig(
        kv_audit_interval_steps=1))
    victim = serve.Request(prompt=(5, 6, 7, 8, 9), max_new_tokens=12,
                           priority=0)
    other = serve.Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=12,
                          priority=1)
    sched.submit(victim)
    sched.submit(other)
    tampered = False
    for _ in range(400):
        sched.step()
        if victim.state is serve.RequestState.PREEMPTED and \
                victim.kv_stamps and not tampered:
            victim.kv_stamps = {j: s ^ 0xDEADBEEF
                                for j, s in victim.kv_stamps.items()}
            tampered = True
        if victim.done and other.done:
            break
    assert tampered, "the tight pool must have preempted the victim"
    assert victim.state is serve.RequestState.FAILED
    assert "PayloadCorruption" in victim.error
    assert other.state is serve.RequestState.DONE
    assert sched.pool.used_pages == 0


def test_repreemption_preserves_original_restore_stamps(integrity_on):
    """A second preemption DURING a restore prefill must not replace
    the original-write carry with stamps of the still-unverified
    recompute — every restore verifies against the original write."""
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=48)
    sched = serve.Scheduler(backend)
    req = serve.Request(prompt=(1, 2, 3, 4, 5), max_new_tokens=4)
    sched.submit(req)
    sched.step()    # admit + prefill
    i = next(k for k, s in enumerate(sched.slots) if s is not None)
    slot = sched.slots[i]
    assert slot.page_stamps      # audit stamped the full prompt page
    original_carry = {0: 123456}     # a pending, unverified carry
    req.kv_stamps = dict(original_carry)
    sched._preempt_slot(i)
    assert req.kv_stamps == original_carry


# ---------------------------------------------------------------------------
# eager queue-deadline sweep (ISSUE 7 satellite)


def test_submit_sweeps_expired_queue_entries():
    """Dead queued requests must not occupy depth against a live
    submit: without the eager sweep, a queue 'full' of expired entries
    sheds viable work and inflates the saturation gauges."""
    backend = serve.SimBackend(slots=1, page_size=4, pool_pages=16,
                               max_length=48)
    sched = serve.Scheduler(backend, serve.SchedulerConfig(
        max_queue_depth=2))
    # two requests whose deadline is already blown at submit time
    dead = [serve.Request(prompt=(1, 2), max_new_tokens=2,
                          deadline_ms=0.001) for _ in range(2)]
    now = 100.0
    for r in dead:
        assert sched.submit(r, now=now)
    assert sched.queue.depth == 2
    # a later live submit sweeps them instead of shedding itself
    live = serve.Request(prompt=(3, 4), max_new_tokens=2)
    assert sched.submit(live, now=now + 10.0)
    assert live.state is serve.RequestState.QUEUED
    assert sched.queue.depth == 1
    for r in dead:
        assert r.state is serve.RequestState.SHED
        assert "expired in queue" in r.shed_reason
    assert len(sched.shed) == 2
    sched.run_until_idle(max_steps=100)
    assert live.state is serve.RequestState.DONE


# ---------------------------------------------------------------------------
# CLI gate


def test_tdt_lint_integrity_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--integrity"],
        capture_output=True, text=True, timeout=560,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "integrity OK" in proc.stdout
    assert proc.stdout.count("DETECTED") >= 13   # 12 kernel + 1 sched


def test_entry_points_unwrapped_without_env(monkeypatch):
    """TDT_INTEGRITY unset => integrity.enabled() is False and the
    entry points never construct the checked wrapper (the byte-identity
    discipline all the env gates share)."""
    monkeypatch.delenv("TDT_INTEGRITY", raising=False)
    assert integrity.enable(None) is False
    assert not integrity.enabled()
    called = []
    monkeypatch.setattr(integrity, "checked",
                        lambda *a, **k: called.append(a) or a[1])
    # the entries guard with integrity.enabled() BEFORE touching
    # checked(); quarantine_blocks is inert too
    assert not integrity.quarantine_blocks(8)
    assert called == []
