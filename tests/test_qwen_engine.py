"""Qwen3 model + KV cache + Engine end-to-end (reference ``test_qwen.py`` /
engine serve-loop strategy): TP model equals the single-device model on the
same full weights, decode continues prefill exactly, engine generates."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.layers.tp_attn import TPAttn
from triton_distributed_tpu.layers.tp_mlp import TPMLP
from triton_distributed_tpu.models import (
    Engine,
    ModelConfig,
    Qwen3,
    QwenLayerParams,
    QwenParams,
    init_cache,
    sample_token,
)

CFG = ModelConfig(
    num_layers=2, hidden=64, intermediate=128, num_heads=4, num_kv_heads=2,
    head_dim=32, vocab=128, max_length=64, dtype=jnp.float32,
)


def _full_weights(key):
    c = CFG
    h, hk, d = c.num_heads, c.num_kv_heads, c.head_dim
    ws = []
    for li in range(c.num_layers):
        k = jax.random.fold_in(key, li)
        ks = jax.random.split(k, 7)
        ws.append(dict(
            wq=jax.random.normal(ks[0], (c.hidden, h * d), c.dtype) * 0.05,
            wk=jax.random.normal(ks[1], (c.hidden, hk * d), c.dtype) * 0.05,
            wv=jax.random.normal(ks[2], (c.hidden, hk * d), c.dtype) * 0.05,
            wo=jax.random.normal(ks[3], (h * d, c.hidden), c.dtype) * 0.05,
            gate=jax.random.normal(ks[4], (c.hidden, c.intermediate), c.dtype) * 0.05,
            up=jax.random.normal(ks[5], (c.hidden, c.intermediate), c.dtype) * 0.05,
            down=jax.random.normal(ks[6], (c.intermediate, c.hidden), c.dtype) * 0.05,
        ))
    ke, kl = jax.random.split(jax.random.fold_in(key, 99))
    emb = jax.random.normal(ke, (c.vocab, c.hidden), c.dtype) * 0.05
    lm = jax.random.normal(kl, (c.hidden, c.vocab), c.dtype) * 0.05
    return ws, emb, lm


def _params_on(mesh, ws, emb, lm):
    c = CFG
    attn_l = TPAttn(mesh, num_heads=c.num_heads, num_kv_heads=c.num_kv_heads,
                    head_dim=c.head_dim, rope_theta=c.rope_theta,
                    qk_norm_eps=c.rms_eps)
    mlp_l = TPMLP(mesh)
    qn = jnp.ones((c.head_dim,), c.dtype)
    layers = [
        QwenLayerParams(
            ln1=jnp.ones((c.hidden,), c.dtype),
            attn=attn_l.shard_params(w["wq"], w["wk"], w["wv"], w["wo"], qn, qn),
            ln2=jnp.ones((c.hidden,), c.dtype),
            mlp=mlp_l.shard_params(w["gate"], w["up"], w["down"]),
        )
        for w in ws
    ]
    return QwenParams(embed=emb, layers=layers,
                      final_norm=jnp.ones((c.hidden,), c.dtype), lm_head=lm)


def _mesh(n):
    return make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])


def _cache(mesh, b=1):
    return init_cache(mesh, CFG.num_layers, b, CFG.num_kv_heads,
                      CFG.max_length, CFG.head_dim, CFG.dtype)


def test_tp_model_matches_single_device():
    ws, emb, lm = _full_weights(jax.random.key(0))
    ids = jax.random.randint(jax.random.key(1), (1, 32), 0, CFG.vocab)

    logits = {}
    for n in (1, 2):
        mesh = _mesh(n)
        model = Qwen3(CFG, mesh)
        params = _params_on(mesh, ws, emb, lm)
        out, _ = model.prefill(params, _cache(mesh), ids)
        logits[n] = np.asarray(jax.device_get(out))
    assert np.allclose(logits[1], logits[2], atol=2e-4, rtol=2e-4), (
        np.abs(logits[1] - logits[2]).max()
    )


def test_decode_continues_prefill():
    """Logits from token-by-token decode match prefilling the longer
    sequence — cache correctness end to end."""
    n, s, extra = 2, 24, 8
    ws, emb, lm = _full_weights(jax.random.key(2))
    mesh = _mesh(n)
    model = Qwen3(CFG, mesh)
    params = _params_on(mesh, ws, emb, lm)
    ids = jax.random.randint(jax.random.key(3), (1, s + extra), 0, CFG.vocab)

    # full prefill over s+extra tokens: golden logits at every position
    full_logits, _ = model.prefill(params, _cache(mesh), ids)
    full_logits = np.asarray(jax.device_get(full_logits))

    # prefill s, then decode the remaining tokens one at a time
    cache = _cache(mesh)
    logits_p, cache = model.prefill(params, cache, ids[:, :s])
    got = [np.asarray(jax.device_get(logits_p))[:, -1]]
    for i in range(extra):
        logits_d, cache = model.decode(params, cache, ids[:, s + i])
        got.append(np.asarray(jax.device_get(logits_d)))
    assert int(cache.kv_len) == s + extra
    for i in range(extra + 1):
        want = full_logits[:, s - 1 + i]
        assert np.allclose(got[i], want, atol=5e-4, rtol=5e-4), (
            i, np.abs(got[i] - want).max()
        )


# 8 kv heads so the model shards over the full 8-mesh (CFG's 4 heads max
# out at tp=2); dims keep every decode-path divisibility: B=8 % 8 == 0
# takes gemm_ar's fused ring, B=3 exercises its fast-AR fallback
CFG8 = ModelConfig(
    num_layers=2, hidden=128, intermediate=256, num_heads=8, num_kv_heads=8,
    head_dim=32, vocab=128, max_length=64, dtype=jnp.float32,
)


@pytest.mark.parametrize("batch", [3, 8])
def test_decode_modes_logits_parity(mesh8, batch):
    """psum / ar / gemm_ar decode produce the same logits on the 8-mesh
    (the reference's set_fwd modes agree; ``e2e_dense.md`` check mode)."""
    mesh = mesh8
    params = Qwen3(CFG8, mesh).init(jax.random.key(11), scale=0.05)
    # B*S must divide the 8-way sequence sharding of prefill activations
    ids = jax.random.randint(jax.random.key(12), (batch, 16), 0, CFG8.vocab)
    step = jax.random.randint(jax.random.key(13), (batch,), 0, CFG8.vocab)

    logits = {}
    for mode in ("psum", "ar", "gemm_ar"):
        model = Qwen3(CFG8, mesh, decode_mode=mode)
        cache = init_cache(mesh, CFG8.num_layers, batch, CFG8.num_kv_heads,
                           CFG8.max_length, CFG8.head_dim, CFG8.dtype)
        # jit the steps: eager shard_map on the full 8-mesh starves the
        # interpret-mode client threads (minutes/step); compiled it's seconds
        _, cache = jax.jit(model.prefill)(params, cache, ids)
        out, cache = jax.jit(model.decode)(params, cache, step)
        logits[mode] = np.asarray(jax.device_get(out))
        assert int(cache.kv_len) == 17
    for mode in ("ar", "gemm_ar"):
        assert np.allclose(logits["psum"], logits[mode],
                           atol=2e-3, rtol=2e-3), (
            mode, np.abs(logits["psum"] - logits[mode]).max()
        )


def test_decode_mode_validation():
    with pytest.raises(ValueError):
        Qwen3(CFG, _mesh(1), decode_mode="nope")


def test_engine_decode_mode_switch():
    """Engine.set_decode_mode mid-stream: greedy continuations agree
    across the reduction implementations (reference engine swapping
    set_fwd between captures)."""
    mesh = _mesh(2)
    eng = Engine.build(CFG, mesh, key=jax.random.key(14), batch=2)
    ids = jax.random.randint(jax.random.key(15), (2, 8), 0, CFG.vocab)
    toks_psum = np.asarray(eng.generate(ids, 4))
    eng.set_decode_mode("ar")
    toks_ar = np.asarray(eng.generate(ids, 4))
    np.testing.assert_array_equal(toks_psum, toks_ar)
    eng.set_decode_mode("gemm_ar")
    toks_gar = np.asarray(eng.generate(ids, 4))
    np.testing.assert_array_equal(toks_psum, toks_gar)
    # the decode megakernel mode rides the same switch (contiguous
    # cache here: fused reductions, per-kernel attention)
    eng.set_decode_mode("fused")
    toks_fused = np.asarray(eng.generate(ids, 4))
    np.testing.assert_array_equal(toks_psum, toks_fused)


def test_engine_generate_greedy_deterministic():
    n = 2
    mesh = _mesh(n)
    eng = Engine.build(CFG, mesh, key=jax.random.key(4), batch=1)
    ids = jax.random.randint(jax.random.key(5), (1, 8), 0, CFG.vocab)
    out1 = np.asarray(jax.device_get(eng.generate(ids, gen_len=4)))

    eng2 = Engine.build(CFG, mesh, key=jax.random.key(4), batch=1)
    out2 = np.asarray(jax.device_get(eng2.generate(ids, gen_len=4)))
    assert out1.shape == (1, 4)
    np.testing.assert_array_equal(out1, out2)


def test_sample_token_top_p():
    logits = jnp.asarray([[0.0, 1.0, 10.0, -5.0]], jnp.float32)
    # greedy
    assert int(sample_token(logits, jax.random.key(0))[0]) == 2
    # top_p tight enough to keep only the argmax
    t = sample_token(logits, jax.random.key(1), temperature=1.0, top_p=0.5)
    assert int(t[0]) == 2


def test_engine_serve_reports_throughput():
    """serve = warmup + timed generate + stats (reference Engine.serve);
    tokens must equal a plain greedy generate."""
    n = 2
    mesh = _mesh(n)
    eng = Engine.build(CFG, mesh, key=jax.random.key(6), batch=1)
    ids = jax.random.randint(jax.random.key(7), (1, 8), 0, CFG.vocab)
    want = np.asarray(jax.device_get(eng.generate(ids, gen_len=4)))
    tokens, stats = eng.serve(ids, gen_len=4)
    np.testing.assert_array_equal(np.asarray(jax.device_get(tokens)), want)
    assert stats["prefill_ms"] > 0
    assert stats["decode_ms_per_token"] > 0
    assert stats["decode_tokens_per_s"] > 0


def test_engine_serve_rejects_overlength():
    n = 2
    mesh = _mesh(n)
    eng = Engine.build(CFG, mesh, key=jax.random.key(8), batch=1)
    ids = jax.random.randint(jax.random.key(9), (1, 8), 0, CFG.vocab)
    with pytest.raises(ValueError, match="max_length"):
        eng.serve(ids, gen_len=CFG.max_length)
