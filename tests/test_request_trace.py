"""Per-request distributed tracing + cross-tier SLO attribution
(ISSUE 14, ``obs.request_trace``).

Headless and model-free like the serve battery: every replay drives the
REAL scheduler/router over the deterministic ``serve.SimBackend``, so
the traces under test come from the production span call sites, not a
harness.  Pinned here: context propagation through preemption/recompute
and the handoff-to-re-prefill fallback, attributor exactness (phase
budgets sum to end-to-end latency, no silent gap), ring retention
bounds, zero behavior change with TDT_TRACE off, exemplar exclusion
under ``obs.suppress()``, the sketch exemplar slots, the
``/debug/trace`` endpoint battery, the queued-age high-water mark, and
the ``tdt_lint --trace`` / ``obs_report --request`` CLI hooks.
"""

import json
import os
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from triton_distributed_tpu import obs, resilience, serve
from triton_distributed_tpu.obs import request_trace as rtrace
from triton_distributed_tpu.obs.serve_stats import QuantileSketch

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def trace_on():
    """Enabled obs + trace plane with clean collector/ring state,
    restored after."""
    prev_obs = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    obs.tracing.clear()
    obs.serve_stats.STATS.reset()
    prev_trace = rtrace.enable(True)
    rtrace.RING.clear()
    yield
    rtrace.enable(prev_trace)
    rtrace.RING.clear()
    obs.enable(prev_obs)
    obs.REGISTRY.reset()
    obs.tracing.clear()
    obs.serve_stats.STATS.reset()


def _replay(seed=0, n=24, *, pool_pages=17, max_new=(4, 12),
            max_steps=20_000):
    backend = serve.SimBackend(slots=4, page_size=4,
                               pool_pages=pool_pages, max_length=64)
    sched = serve.Scheduler(backend, serve.SchedulerConfig(
        max_queue_depth=64))
    arrivals = serve.synthetic_trace(seed, n,
                                     mean_interarrival_steps=0.5,
                                     prompt_len=(2, 12), max_new=max_new)
    report = serve.replay(sched, arrivals, max_steps=max_steps)
    return sched, report


def _router_replay(faults=(), seed=0, n=24):
    resilience.reset_breaker(serve.HANDOFF_OP)
    pre = serve.Scheduler(
        serve.SimBackend(slots=4, page_size=4, pool_pages=33,
                         max_length=64),
        serve.SchedulerConfig(max_queue_depth=64, prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=4, page_size=4, pool_pages=49,
                         max_length=64),
        serve.SchedulerConfig(max_queue_depth=64))
    plane = serve.HandoffPlane(
        dcn_channel=serve.ModeledDCN(faults=list(faults), seed=seed))
    router = serve.DisaggRouter(pre, dec, plane=plane)
    arrivals = serve.synthetic_trace(seed, n, mean_interarrival_steps=0.5,
                                     prompt_len=(2, 12), max_new=(2, 10))
    pending = sorted(arrivals, key=lambda a: (a.step, a.request.req_id))
    idx = 0
    for _ in range(20_000):
        while idx < len(pending) and pending[idx].step <= pre.steps:
            router.submit(pending[idx].request)
            idx += 1
        if idx >= len(pending) and router.step().idle:
            break
        elif idx < len(pending):
            router.step()
    resilience.reset_breaker(serve.HANDOFF_OP)
    return router, [a.request for a in arrivals]


# ---------------------------------------------------------------------------
# the chain + attributor


def test_chain_gapless_and_attributor_sums(trace_on):
    """Every terminal request carries a gapless span chain whose phase
    budgets sum EXACTLY to its end-to-end latency — the no-silent-gap
    contract the lint gate rides."""
    sched, report = _replay()
    assert report.completed and not report.problems()
    for req in report.requests:
        tr = req.trace
        assert tr is not None and tr.closed
        assert rtrace.verify_chain(tr) == []
        att = rtrace.attribute_request(tr)
        total = sum(p["exposed_ms"] for p in att["phases"].values())
        assert att["gap_ms"] == pytest.approx(0.0, abs=1e-9)
        assert total == pytest.approx(att["e2e_ms"], abs=1e-6)
        assert att["dominant_phase"] in att["phases"]
    done = report.completed[0].trace
    att = rtrace.attribute_request(done)
    # a completed request passed through queue -> prefill -> decode
    assert {"queue", "prefill", "decode"} <= set(att["phases"])
    # TTFT decomposition sums to the trace's own TTFT
    assert att["ttft_ms"] is not None
    assert sum(att["ttft_phases"].values()) == \
        pytest.approx(att["ttft_ms"], abs=1e-6)


def test_propagation_through_preemption_recompute(trace_on):
    """A preempted request's ONE trace carries the preemption episode
    (pages tag), the recompute-marked second prefill, and still sums
    exactly — the thrash regime is where per-request attribution earns
    its keep."""
    sched, report = _replay(pool_pages=13, max_new=(6, 14))
    assert sched.preemptions >= 1
    preempted = [r for r in report.completed if r.preemptions]
    assert preempted, "the pressured replay never preempted a completer"
    for req in preempted:
        tr = req.trace
        assert rtrace.verify_chain(tr) == []
        names = [s.name for s in tr.spans]
        assert "preempted" in names
        pre_span = next(s for s in tr.spans if s.name == "preempted")
        assert pre_span.tags["pages"] >= 1
        # the recompute prefill is marked, and the chain stays ONE trace
        recompute = [s for s in tr.spans
                     if s.name == "prefill_chunk"
                     and s.tags.get("recompute")]
        assert recompute, names
        att = rtrace.attribute_request(tr)
        assert "preempted" in att["phases"]
        total = sum(p["exposed_ms"] for p in att["phases"].values())
        assert total == pytest.approx(att["e2e_ms"], abs=1e-6)


def test_handoff_reprefill_fallback_trace(trace_on):
    """The drop-faulted request's trace crosses both tiers on ONE chain
    and names every ladder rung: the retry annotations (reason strings
    from ``resilience.resilient_call``), the fallback, the re-prefill,
    then the decode-tier recompute."""
    faults = [serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 2)]
    router, reqs = _router_replay(faults)
    assert router.reprefills >= 1
    for rid in router.reprefill_ids:
        tr = next(r.trace for r in reqs if r.req_id == rid)
        assert rtrace.verify_chain(tr) == []
        assert tr.tiers() == ["prefill", "decode"]
        names = [e.name for e in tr.events]
        assert "retry" in names and "fallback" in names \
            and "reprefill" in names
        rung = next(e for e in tr.events if e.name == "retry")
        assert "dropped" in rung.tags["reason"]
        # the wire/verify split: per-attempt overlay events
        wires = [e for e in tr.events if e.name == "handoff_wire"]
        assert len(wires) >= 2            # original + retries
        # after the fallback, the decode tier re-queued and re-prefilled
        span_names = [s.name for s in tr.spans]
        i = span_names.index("handoff_transfer")
        assert "queue_wait" in span_names[i:] \
            and "prefill_chunk" in span_names[i:]
    # clean handoffs split wire from stamp-verify time
    handed = [r for r in reqs
              if r.trace is not None
              and any(s.name == "adopt" for s in r.trace.spans)]
    assert handed
    ev_names = [e.name for e in handed[0].trace.events]
    assert "handoff_wire" in ev_names and "stamp_verify" in ev_names


def test_ring_retention_bound(trace_on):
    """The ring keeps the most recent ``cap`` traces, oldest evicted."""
    ring = rtrace.TraceRing(cap=8)
    traces = []
    for i in range(20):
        tr = rtrace.TraceContext(i, "serve")
        tr.end("done")
        ring.retire(tr)
        traces.append(tr)
    assert len(ring) == 8
    assert ring.ids() == [t.trace_id for t in traces[-8:]]
    assert ring.get(traces[0].trace_id) is None
    assert ring.get(traces[-1].trace_id) is traces[-1]


def test_tdt_trace_off_is_byte_identical(trace_on):
    """With the plane off the scheduler behaves identically: same
    tokens, same outcomes, same step count — and no request carries a
    trace, nothing lands in the ring."""
    rtrace.enable(False)
    sched_off, rep_off = _replay(seed=3)
    assert all(r.trace is None for r in rep_off.requests)
    assert len(rtrace.RING) == 0
    rtrace.enable(True)
    sched_on, rep_on = _replay(seed=3)
    assert all(r.trace is not None for r in rep_on.requests)
    assert sched_on.steps == sched_off.steps
    assert [(r.state, tuple(r.tokens)) for r in rep_on.requests] == \
        [(r.state, tuple(r.tokens)) for r in rep_off.requests]


def test_exemplar_excluded_under_suppress(trace_on):
    """``obs.suppress()`` traffic (sweeps, warmups) mints no traces and
    feeds no exemplars — the ring and the p99 lookups describe REAL
    traffic only."""
    with obs.suppress():
        _replay(seed=5, n=8)
    assert len(rtrace.RING) == 0
    assert obs.serve_stats.STATS.ttft_ms.exemplar(0.99) is None
    # real traffic afterwards populates both
    _replay(seed=6, n=8)
    assert len(rtrace.RING) == 8
    ex = obs.serve_stats.STATS.ttft_ms.exemplar(0.99)
    assert ex is not None and rtrace.RING.get(ex) is not None


def test_sketch_exemplar_slots():
    """Unit: the p99 bucket returns the id of the observation that
    landed there; omitting exemplars keeps the sketch unchanged."""
    sk = QuantileSketch()
    for i in range(95):
        sk.observe(10.0 + 0.001 * i, exemplar=f"fast-{i}")
    for i in range(5):
        sk.observe(5000.0, exemplar=f"slow-{i}")
    # p99 rank (0.99 * 99 = 98.01) lands in the slow-tail bucket, whose
    # slot holds the LAST exemplar that landed there
    assert sk.exemplar(0.99) == "slow-4"
    assert sk.exemplar(0.5).startswith("fast-")
    assert sk.to_dict()["exemplars"]["p99"] == "slow-4"
    # merge carries exemplars; plain observations carry none
    other = QuantileSketch()
    other.observe(9999.0, exemplar="merged-tail")
    sk.merge(other)
    assert sk.exemplar(1.0) == "merged-tail"
    plain = QuantileSketch()
    plain.observe(1.0)
    assert plain.exemplar(0.99) is None
    assert "exemplars" not in plain.to_dict()


def test_sketch_exemplar_survives_bucket_collapse():
    sk = QuantileSketch(max_buckets=4)
    for i, v in enumerate((0.001, 0.01, 0.1, 1.0, 10.0, 100.0)):
        sk.observe(v, exemplar=f"e{i}")
    # collapses hit the SMALLEST keys; the tail exemplar survives
    assert sk.exemplar(1.0) == "e5"


# ---------------------------------------------------------------------------
# export surfaces


def _get(url: str):
    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_trace_endpoint_battery(trace_on):
    """/debug/trace listing, /debug/trace/<id> payload (spans + events
    + attribution), 404 for unknown ids, and the exemplar ids surfaced
    in /debug/serve."""
    from triton_distributed_tpu.obs import server as obs_server

    sched, report = _replay(n=8)
    srv = obs_server.start(port=0, engine=sched)
    try:
        code, body = _get(srv.url + "/debug/trace")
        assert code == 200
        listing = json.loads(body)
        assert listing["enabled"] and listing["retained"] == 8
        tid = listing["ids"][-1]
        code, body = _get(srv.url + f"/debug/trace/{tid}")
        assert code == 200
        tr = json.loads(body)
        assert tr["trace_id"] == tid and tr["state"] == "done"
        assert tr["spans"] and tr["attribution"]["gap_ms"] == 0.0
        total = sum(p["exposed_ms"]
                    for p in tr["attribution"]["phases"].values())
        assert total == pytest.approx(tr["attribution"]["e2e_ms"],
                                      abs=1e-6)
        code, body = _get(srv.url + "/debug/trace/nope")
        assert code == 404 and "not retained" in body
        code, body = _get(srv.url + "/debug/serve")
        assert code == 200
        dump = json.loads(body)
        ex = dump["trace"]["exemplars"]["ttft_ms_p99"]
        assert ex in listing["ids"]
        # the small fix: queued-age high-water rides the queue snapshot
        assert "queued_age_hw_s" in dump["scheduler"]["queue"]
        # 404 listing names the new endpoint
        code, body = _get(srv.url + "/nope")
        assert code == 404 and "/debug/trace" in body
    finally:
        obs_server.stop()


def test_waterfall_and_offline_round_trip(trace_on, tmp_path):
    """format_waterfall names every hop; export_traces -> load_traces
    -> attribute_request round-trips the offline debugging path the
    ``obs_report --request --trace-file`` CLI uses."""
    sched, report = _replay(n=6)
    tr = report.completed[0].trace
    text = rtrace.format_waterfall(tr)
    for name in ("queue_wait", "prefill_chunk", "decode_window",
                 "attribution:", "dominant="):
        assert name in text
    dump = tmp_path / "traces.json"
    rtrace.export_traces(str(dump))
    loaded = {t.trace_id: t for t in rtrace.load_traces(str(dump))}
    assert set(loaded) == set(rtrace.RING.ids())
    att0 = rtrace.attribute_request(tr)
    att1 = rtrace.attribute_request(loaded[tr.trace_id])
    assert att1["e2e_ms"] == pytest.approx(att0["e2e_ms"], abs=1e-9)
    assert att1["phases"].keys() == att0["phases"].keys()


def test_chrome_export_merges_with_process_spans(trace_on, tmp_path):
    """The request spans share the obs.tracing wall timebase, so
    trace_merge (the ts_offsets path included) folds request traces and
    the process span trace into one Chrome timeline."""
    from triton_distributed_tpu.obs import report as obs_report_mod
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    _replay(n=4)
    proc = tmp_path / "proc.json"
    reqs = tmp_path / "requests.json"
    obs.tracing.export(str(proc))
    rtrace.export_chrome(str(reqs))
    merged = tmp_path / "merged.json"
    merge_traces([str(proc), str(reqs)], [0, 0], str(merged),
                 ts_offsets=[0.0, 0.0])
    events = obs_report_mod.load_trace(str(merged))
    cats = {e.get("cat") for e in events}
    # scheduler ticks (satellite: serve/ now emits step spans), compute
    # spans and request spans coexist on one timeline
    assert {"step", "compute", "request"} <= cats
    steps = [e for e in events if e.get("cat") == "step"]
    assert any(e["name"] == "sched_step" for e in steps)
    req_ts = [e["ts"] for e in events if e.get("cat") == "request"]
    step_ts = [e["ts"] for e in steps]
    # one shared clock: request spans land inside the process span window
    assert min(step_ts) - 1e6 <= min(req_ts) <= max(step_ts) + 1e6


def test_obs_report_cli_request_waterfall(trace_on, tmp_path):
    sched, report = _replay(n=4)
    dump = tmp_path / "traces.json"
    rtrace.export_traces(str(dump))
    tid = report.completed[0].trace.trace_id
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--request", tid, "--trace-file", str(dump)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert tid in proc.stdout and "attribution:" in proc.stdout
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--request", "list", "--trace-file", str(dump)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0 and tid in proc.stdout


# ---------------------------------------------------------------------------
# the small fix + history direction


def test_queue_age_high_water_survives_expiry():
    """A starving low-priority request leaves its queued-age high-water
    mark even after deadline expiry sheds it — the evidence outlives
    the request."""
    q = serve.RequestQueue(max_depth=8)
    lo = serve.Request(prompt=(1, 2), max_new_tokens=2, priority=0,
                       deadline_ms=50.0)
    hi = serve.Request(prompt=(3, 4), max_new_tokens=2, priority=2)
    t0 = 100.0
    assert q.submit(lo, now=t0) and q.submit(hi, now=t0)
    q.expire_deadlines(now=t0 + 0.02)          # both still queued
    assert q.age_high_water_s[0] == pytest.approx(0.02)
    expired = q.expire_deadlines(now=t0 + 0.2)  # lo's deadline passed
    assert expired == [lo]
    snap = q.snapshot()
    # the mark recorded lo's final 200 ms of starvation BEFORE the shed
    assert snap["queued_age_hw_s"][0] == pytest.approx(0.2)
    assert snap["queued_age_hw_s"][2] == pytest.approx(0.2)
    # a preempted re-queue restarts ITS residency clock
    q.requeue_preempted(hi)


def test_history_classifies_trace_overhead_lower_is_better():
    from triton_distributed_tpu.obs.history import direction_for

    assert direction_for("trace_overhead_pct", "% over untraced") == \
        "lower"
    assert direction_for("trace_overhead_pct_disagg",
                         "% over untraced") == "lower"


def test_tdt_lint_trace_smoke():
    """The tier-1 CI hook (like the --serve / --handoff smokes): the
    seeded two-tier replay under TDT_TRACE with a transfer drop —
    gapless chains, attributor exactness, exemplar resolution, ladder
    rungs named."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--trace"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "trace OK" in proc.stdout
    assert "exemplar ->" in proc.stdout
