"""Gradients through the fused collective GEMMs (training support).

The custom VJPs ride the TP adjoint duality — AllGather's transpose is
ReduceScatter — so ``ag_gemm``'s backward runs ``gemm_rs`` and vice
versa, keeping the backward pass's collectives overlapped like the
forward's.  Goldens: ``jax.grad`` of the same global math in plain XLA.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.layers import TPMLP
from triton_distributed_tpu.ops import ag_gemm, gemm_rs


def _mesh(n):
    return make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])


@pytest.mark.parametrize("n", [2, 4])
def test_ag_gemm_grads_match_xla(n):
    mesh = _mesh(n)
    m, k, nn = 8 * n, 32, 16 * n
    rng = np.random.default_rng(n)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.3)
    a_s = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    # a non-uniform cotangent so dC exercises real structure
    w = jnp.asarray(rng.standard_normal((m, nn)).astype(np.float32))

    loss = jax.jit(lambda a, b: jnp.sum(ag_gemm(a, b, mesh) * w))
    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    ref = jax.jit(jax.grad(lambda a, b: jnp.sum((a @ b) * w),
                           argnums=(0, 1)))
    da_ref, db_ref = ref(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("n", [2, 4])
def test_gemm_rs_grads_match_xla(n):
    mesh = _mesh(n)
    m, k, nn = 8 * n, 16 * n, 32
    rng = np.random.default_rng(10 + n)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.3)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    w = jnp.asarray(rng.standard_normal((m, nn)).astype(np.float32))

    loss = jax.jit(lambda a, b: jnp.sum(gemm_rs(a, b, mesh) * w))
    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    ref = jax.jit(jax.grad(lambda a, b: jnp.sum((a @ b) * w),
                           argnums=(0, 1)))
    da_ref, db_ref = ref(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-3, rtol=1e-3)


def test_tp_mlp_training_step():
    """A full SGD step through the fused layer: loss -> grads (through
    AG-GEMM and GEMM-RS and their adjoints) -> update; grads match the
    rank-blocked XLA reference MLP."""
    n = 4
    mesh = _mesh(n)
    m, k, i = 8 * n, 32, 16 * n
    layer = TPMLP(mesh)
    params = layer.init(jax.random.key(0), k, i, dtype=jnp.float32,
                        scale=0.3)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    x_s = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))

    def loss_fused(p, x):
        y = layer.forward(p, x)
        return jnp.mean(y * y)

    def loss_ref(gu, dn, x):
        # the same rank-blocked math in plain XLA (bench.py baseline)
        t = jnp.matmul(x, gu).reshape(m, n, 2, i // n)
        h = (jax.nn.silu(t[:, :, 0]) * t[:, :, 1]).reshape(m, i)
        y = jnp.matmul(h, dn)
        return jnp.mean(y * y)

    grads = jax.jit(jax.grad(loss_fused))(params, x_s)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(
        jnp.asarray(np.asarray(params.gate_up)),
        jnp.asarray(np.asarray(params.down)), x,
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.gate_up)),
                               np.asarray(g_ref[0]), atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.down)),
                               np.asarray(g_ref[1]), atol=1e-4, rtol=1e-3)

    # the update step executes sharded end to end
    lr = 0.005
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    l0 = float(jax.jit(loss_fused)(params, x_s))
    l1 = float(jax.jit(loss_fused)(new_params, x_s))
    assert l1 < l0


@pytest.mark.parametrize("n", [2, 4])
def test_gemm_ar_grads_match_xla(n):
    """gemm_ar's adjoint is wire-free: replicated cotangent, two local
    GEMMs."""
    from triton_distributed_tpu.ops import gemm_ar

    mesh = _mesh(n)
    m, k, nn = 8 * n, 16 * n, 32
    rng = np.random.default_rng(20 + n)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.3)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    w = jnp.asarray(rng.standard_normal((m, nn)).astype(np.float32))

    loss = jax.jit(lambda a, b: jnp.sum(gemm_ar(a, b, mesh) * w))
    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    da_ref, db_ref = jax.jit(jax.grad(
        lambda a, b: jnp.sum((a @ b) * w), argnums=(0, 1)
    ))(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-3, rtol=1e-3)


def test_comm_collectives_differentiate():
    """all_gather / reduce_scatter / all_reduce under jax.grad vs their
    global-semantics references (identity resp. chunked sum)."""
    from triton_distributed_tpu.comm import all_gather, all_reduce, reduce_scatter
    from triton_distributed_tpu.comm.allreduce import AllReduceMethod

    n = 4
    mesh = _mesh(n)
    m, r = 8, 128
    rng = np.random.default_rng(30)
    x = jnp.asarray(rng.standard_normal((n * m, r)).astype(np.float32))
    w_ag = jnp.asarray(rng.standard_normal((n * m, r)).astype(np.float32))
    w_rs = jnp.asarray(rng.standard_normal((m, r)).astype(np.float32))
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))

    g = jax.jit(jax.grad(lambda x: jnp.sum(all_gather(x, mesh) * w_ag)))(xs)
    np.testing.assert_allclose(np.asarray(jax.device_get(g)),
                               np.asarray(w_ag), atol=1e-5)

    g = jax.jit(jax.grad(
        lambda x: jnp.sum(reduce_scatter(x, mesh) * w_rs)
    ))(xs)
    want = np.tile(np.asarray(w_rs), (n, 1))
    np.testing.assert_allclose(np.asarray(jax.device_get(g)), want,
                               atol=1e-5)

    for method in (AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT):
        g = jax.jit(jax.grad(
            lambda x: jnp.sum(all_reduce(x, mesh, method=method) * w_rs)
        ))(xs)
        np.testing.assert_allclose(np.asarray(jax.device_get(g)), want,
                                   atol=1e-5)


def test_grouped_matmul_grads_match_ragged():
    """Pallas forward, ragged_dot backward."""
    from triton_distributed_tpu.ops import GroupGemmConfig, grouped_matmul

    t, k, nn, e = 32, 16, 24, 3
    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.standard_normal((t, k)).astype(np.float32))
    w = jnp.asarray(rng.standard_normal((e, k, nn)).astype(np.float32))
    sp = jnp.asarray([10, 8, 14], jnp.int32)
    cfg = GroupGemmConfig(bm=8, bn=8, bk=8)
    cot = jnp.asarray(rng.standard_normal((t, nn)).astype(np.float32))

    loss = jax.jit(lambda x, w: jnp.sum(
        grouped_matmul(x, w, sp, config=cfg) * cot
    ))
    dx, dw = jax.grad(loss, argnums=(0, 1))(x, w)
    ref = jax.jit(jax.grad(
        lambda x, w: jnp.sum(jax.lax.ragged_dot(x, w, sp) * cot),
        argnums=(0, 1),
    ))
    dx_r, dw_r = ref(x, w)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_r), atol=1e-4)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_r), atol=1e-4)


def test_moe_tp_training_step():
    """Gradients through the full routed MoE TP path (route -> AG +
    grouped GEMM -> swiglu -> grouped GEMM + RS) vs the dense golden."""
    from triton_distributed_tpu.layers.moe import MoEMLP

    n = 2
    mesh = _mesh(n)
    t, hid, ffn, e, k = 8, 32, 8 * n, 2 * n, 2
    layer = MoEMLP(mesh, num_experts=e, top_k=k, swiglu=True)
    rng = np.random.default_rng(32)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    gate = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.3)
    up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.3)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.3)
    params = layer.shard_params_tp(
        router, layer.fuse_expert_gate_up(gate, up), w_dn
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))

    def loss_fused(p, x):
        y = layer.forward_tp(p, x)
        return jnp.mean(y * y)

    grads = jax.jit(jax.grad(loss_fused))(params, xs)
    # reference: dense per-token MoE in plain jnp on the same fused layout
    fused_gu = jnp.asarray(np.asarray(params.w_up))

    def loss_ref(w_up_f, w_dn_, x):
        probs = jax.nn.softmax(x @ router, axis=-1)
        top_w, top_e = jax.lax.top_k(probs, k)
        top_w = top_w / top_w.sum(-1, keepdims=True)
        f_loc = ffn // n
        out = jnp.zeros_like(x)
        for j in range(k):
            we = w_up_f[top_e[:, j]]              # (T, hid, 2ffn) blocked
            h = jnp.einsum("th,thf->tf", x, we)
            hb = h.reshape(-1, n, 2, f_loc)
            act = (jax.nn.silu(hb[:, :, 0]) * hb[:, :, 1]).reshape(-1, ffn)
            y = jnp.einsum("tf,tfh->th", act, w_dn_[top_e[:, j]])
            out = out + top_w[:, j:j + 1] * y
        return jnp.mean(out * out)

    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(
        fused_gu, jnp.asarray(np.asarray(params.w_dn)), x
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.w_up)),
                               np.asarray(g_ref[0]), atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.w_dn)),
                               np.asarray(g_ref[1]), atol=1e-4, rtol=1e-3)


def test_moe_ep_training_step():
    """Gradients through the EP path: the A2A dispatch/combine pair are
    each other's adjoints (a token permutation and its transpose), so the
    backward pass re-runs the opposite A2A."""
    from triton_distributed_tpu.comm.all_to_all import AllToAllConfig
    from triton_distributed_tpu.layers.moe import MoEMLP

    n = 4
    mesh = _mesh(n)
    t, hid, ffn, e, k = 8, 32, 16, 8, 2
    layer = MoEMLP(mesh, num_experts=e, top_k=k, swiglu=True)
    rng = np.random.default_rng(33)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    gate = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.3)
    up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.3)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.3)
    params_ep = layer.shard_params_ep(
        router, layer.fuse_expert_gate_up(gate, up, ep=True), w_dn
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    cfg = AllToAllConfig(chunk=8)

    def loss_ep(p, x):
        y = layer.forward_ep(p, x, a2a_config=cfg)
        return jnp.mean(y * y)

    grads = jax.jit(jax.grad(loss_ep))(params_ep, xs)

    # dense reference on unfused weights ([gate|up] plain concat under EP)
    def loss_ref(w_up_f, w_dn_, x):
        probs = jax.nn.softmax(x @ router, axis=-1)
        top_w, top_e = jax.lax.top_k(probs, k)
        top_w = top_w / top_w.sum(-1, keepdims=True)
        out = jnp.zeros_like(x)
        for j in range(k):
            we = w_up_f[top_e[:, j]]              # (T, hid, 2ffn)
            h = jnp.einsum("th,thf->tf", x, we)
            act = jax.nn.silu(h[:, :ffn]) * h[:, ffn:]
            y = jnp.einsum("tf,tfh->th", act, w_dn_[top_e[:, j]])
            out = out + top_w[:, j:j + 1] * y
        return jnp.mean(out * out)

    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(
        jnp.asarray(np.asarray(params_ep.w_up)),
        jnp.asarray(np.asarray(params_ep.w_dn)), x,
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.w_up)),
                               np.asarray(g_ref[0]), atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.w_dn)),
                               np.asarray(g_ref[1]), atol=1e-4, rtol=1e-3)


@pytest.mark.xfail(
    reason="jax.checkpoint cannot partial-eval the Pallas INTERPRETER's "
           "ordered-IO effects (the CPU simulation only; Mosaic-compiled "
           "kernels carry no such effect on real TPU)",
    raises=NotImplementedError, strict=True,
)
def test_remat_composes_with_fused_vjps():
    """jax.checkpoint around the fused layer (the HBM-for-FLOPs trade for
    long training graphs) must reproduce the unremat'd gradients — the
    custom VJPs replay their forwards under remat."""
    n = 2
    mesh = _mesh(n)
    m, k, i = 8 * n, 32, 16 * n
    layer = TPMLP(mesh)
    params = layer.init(jax.random.key(5), k, i, dtype=jnp.float32,
                        scale=0.3)
    x = jax.device_put(
        jnp.asarray(np.random.default_rng(6).standard_normal(
            (m, k)).astype(np.float32) * 0.3),
        NamedSharding(mesh, P(TP_AXIS, None)),
    )

    def loss(p, x):
        return jnp.mean(layer.forward(p, x) ** 2)

    def loss_remat(p, x):
        return jnp.mean(jax.checkpoint(layer.forward)(p, x) ** 2)

    g = jax.jit(jax.grad(loss))(params, x)
    gr = jax.jit(jax.grad(loss_remat))(params, x)
    for a, b in zip(jax.tree.leaves(g), jax.tree.leaves(gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-6)
