"""Gradients through the fused collective GEMMs (training support).

The custom VJPs ride the TP adjoint duality — AllGather's transpose is
ReduceScatter — so ``ag_gemm``'s backward runs ``gemm_rs`` and vice
versa, keeping the backward pass's collectives overlapped like the
forward's.  Goldens: ``jax.grad`` of the same global math in plain XLA.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.layers import TPMLP
from triton_distributed_tpu.ops import ag_gemm, gemm_rs


def _mesh(n):
    return make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])


@pytest.mark.parametrize("n", [2, 4])
def test_ag_gemm_grads_match_xla(n):
    mesh = _mesh(n)
    m, k, nn = 8 * n, 32, 16 * n
    rng = np.random.default_rng(n)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.3)
    a_s = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    # a non-uniform cotangent so dC exercises real structure
    w = jnp.asarray(rng.standard_normal((m, nn)).astype(np.float32))

    loss = jax.jit(lambda a, b: jnp.sum(ag_gemm(a, b, mesh) * w))
    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    ref = jax.jit(jax.grad(lambda a, b: jnp.sum((a @ b) * w),
                           argnums=(0, 1)))
    da_ref, db_ref = ref(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("n", [2, 4])
def test_gemm_rs_grads_match_xla(n):
    mesh = _mesh(n)
    m, k, nn = 8 * n, 16 * n, 32
    rng = np.random.default_rng(10 + n)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.3)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    w = jnp.asarray(rng.standard_normal((m, nn)).astype(np.float32))

    loss = jax.jit(lambda a, b: jnp.sum(gemm_rs(a, b, mesh) * w))
    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    ref = jax.jit(jax.grad(lambda a, b: jnp.sum((a @ b) * w),
                           argnums=(0, 1)))
    da_ref, db_ref = ref(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-3, rtol=1e-3)


def test_tp_mlp_training_step():
    """A full SGD step through the fused layer: loss -> grads (through
    AG-GEMM and GEMM-RS and their adjoints) -> update; grads match the
    rank-blocked XLA reference MLP."""
    n = 4
    mesh = _mesh(n)
    m, k, i = 8 * n, 32, 16 * n
    layer = TPMLP(mesh)
    params = layer.init(jax.random.key(0), k, i, dtype=jnp.float32,
                        scale=0.3)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    x_s = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))

    def loss_fused(p, x):
        y = layer.forward(p, x)
        return jnp.mean(y * y)

    def loss_ref(gu, dn, x):
        # the same rank-blocked math in plain XLA (bench.py baseline)
        t = jnp.matmul(x, gu).reshape(m, n, 2, i // n)
        h = (jax.nn.silu(t[:, :, 0]) * t[:, :, 1]).reshape(m, i)
        y = jnp.matmul(h, dn)
        return jnp.mean(y * y)

    grads = jax.jit(jax.grad(loss_fused))(params, x_s)
    g_ref = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(
        jnp.asarray(np.asarray(params.gate_up)),
        jnp.asarray(np.asarray(params.down)), x,
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.gate_up)),
                               np.asarray(g_ref[0]), atol=1e-4, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(grads.down)),
                               np.asarray(g_ref[1]), atol=1e-4, rtol=1e-3)

    # the update step executes sharded end to end
    lr = 0.005
    new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
    l0 = float(jax.jit(loss_fused)(params, x_s))
    l1 = float(jax.jit(loss_fused)(new_params, x_s))
    assert l1 < l0


@pytest.mark.parametrize("n", [2, 4])
def test_gemm_ar_grads_match_xla(n):
    """gemm_ar's adjoint is wire-free: replicated cotangent, two local
    GEMMs."""
    from triton_distributed_tpu.ops import gemm_ar

    mesh = _mesh(n)
    m, k, nn = 8 * n, 16 * n, 32
    rng = np.random.default_rng(20 + n)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.3)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    w = jnp.asarray(rng.standard_normal((m, nn)).astype(np.float32))

    loss = jax.jit(lambda a, b: jnp.sum(gemm_ar(a, b, mesh) * w))
    da, db = jax.grad(loss, argnums=(0, 1))(a_s, b_s)
    da_ref, db_ref = jax.jit(jax.grad(
        lambda a, b: jnp.sum((a @ b) * w), argnums=(0, 1)
    ))(a, b)
    np.testing.assert_allclose(np.asarray(jax.device_get(da)),
                               np.asarray(da_ref), atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(np.asarray(jax.device_get(db)),
                               np.asarray(db_ref), atol=1e-3, rtol=1e-3)
