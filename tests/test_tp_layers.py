"""TP_MLP / TP_Attn layers vs dense (unsharded) goldens — the analogue of
the reference's torch_fwd-vs-dist_triton_fwd layer tests
(``layers/nvidia/tp_mlp.py`` ``torch_fwd``)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh, shard
from triton_distributed_tpu.layers import TPAttn, TPMLP, rms_norm
from triton_distributed_tpu.ops.attention import flash_attention
from triton_distributed_tpu.ops.rope import apply_rope_at


def _mesh(n):
    return make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])


def _mlp_golden(x, g, u, d):
    h = jax.nn.silu(x @ g) * (x @ u)
    return (h @ d).astype(x.dtype)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_tp_mlp_forward(n):
    mesh = _mesh(n)
    layer = TPMLP(mesh)
    K, I, M = 128, 256, 16 * n * n  # M divisible by n (ag) and n*n (rs rows)
    kx, kw = jax.random.split(jax.random.key(0))
    g = jax.random.normal(kw, (K, I), jnp.float32) * 0.05
    u = jax.random.normal(jax.random.fold_in(kw, 1), (K, I), jnp.float32) * 0.05
    d = jax.random.normal(jax.random.fold_in(kw, 2), (I, K), jnp.float32) * 0.05
    params = layer.shard_params(g, u, d)
    x = jax.random.normal(kx, (M, K), jnp.float32) * 0.1
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward(params, xs)
    assert out.shape == (M, K)
    want = _mlp_golden(x, g, u, d)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-4, rtol=2e-4), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def test_tp_mlp_forward_ar(mesh8):
    layer = TPMLP(mesh8)
    K, I, M = 128, 256, 64
    kx, kw = jax.random.split(jax.random.key(1))
    g = jax.random.normal(kw, (K, I), jnp.float32) * 0.05
    u = jax.random.normal(jax.random.fold_in(kw, 1), (K, I), jnp.float32) * 0.05
    d = jax.random.normal(jax.random.fold_in(kw, 2), (I, K), jnp.float32) * 0.05
    params = layer.shard_params(g, u, d)
    x = jax.random.normal(kx, (M, K), jnp.float32) * 0.1
    out = layer.forward_ar(params, x)
    assert out.shape == (M, K)
    want = _mlp_golden(x, g, u, d)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-4, rtol=2e-4)


def test_tp_mlp_init_shapes(mesh8):
    layer = TPMLP(mesh8)
    params = layer.init(jax.random.key(2), hidden=128, intermediate=512)
    assert params.gate_up.shape == (128, 1024)
    assert params.down.shape == (512, 128)
    assert params.gate_up.sharding.spec == P(None, TP_AXIS)


def _attn_golden(x, wq, wk, wv, wo, h, hk, d, batch, theta,
                 qk_eps=None):
    m = x.shape[0]
    seq = m // batch
    q = (x @ wq).reshape(batch, seq, h, d).transpose(0, 2, 1, 3)
    k = (x @ wk).reshape(batch, seq, hk, d).transpose(0, 2, 1, 3)
    v = (x @ wv).reshape(batch, seq, hk, d).transpose(0, 2, 1, 3)
    if qk_eps is not None:
        q = rms_norm(q, jnp.ones((d,), q.dtype), qk_eps)
        k = rms_norm(k, jnp.ones((d,), k.dtype), qk_eps)
    pos = jnp.arange(seq)
    q = apply_rope_at(q, pos, theta=theta)
    k = apply_rope_at(k, pos, theta=theta)
    o = flash_attention(q, k, v, causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(m, h * d)
    return (o @ wo).astype(x.dtype)


@pytest.mark.parametrize("n,h,hk", [(2, 4, 2), (4, 8, 4), (8, 8, 8)])
def test_tp_attn_forward(n, h, hk):
    mesh = _mesh(n)
    K, d, batch = 128, 64, 1
    layer = TPAttn(mesh, num_heads=h, num_kv_heads=hk, head_dim=d)
    seq = 32 * n * n  # M=batch*seq divisible by n (ag) and n*n (rs rows)
    kx, kw = jax.random.split(jax.random.key(3))
    wq = jax.random.normal(kw, (K, h * d), jnp.float32) * 0.05
    wk = jax.random.normal(jax.random.fold_in(kw, 1), (K, hk * d), jnp.float32) * 0.05
    wv = jax.random.normal(jax.random.fold_in(kw, 2), (K, hk * d), jnp.float32) * 0.05
    wo = jax.random.normal(jax.random.fold_in(kw, 3), (h * d, K), jnp.float32) * 0.05
    params = layer.shard_params(wq, wk, wv, wo)
    x = jax.random.normal(kx, (batch * seq, K), jnp.float32) * 0.1
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward(params, xs, batch=batch)
    assert out.shape == x.shape
    want = _attn_golden(x, wq, wk, wv, wo, h, hk, d, batch, layer.rope_theta)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-4, rtol=2e-4), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def test_tp_attn_forward_ar_with_qk_norm(mesh8):
    n, K, d, batch = 8, 128, 64, 2
    h = hk = 8
    layer = TPAttn(mesh8, num_heads=h, num_kv_heads=hk, head_dim=d,
                   qk_norm_eps=1e-6)
    seq = 32
    kx, kw = jax.random.split(jax.random.key(4))
    wq = jax.random.normal(kw, (K, h * d), jnp.float32) * 0.05
    wk = jax.random.normal(jax.random.fold_in(kw, 1), (K, hk * d), jnp.float32) * 0.05
    wv = jax.random.normal(jax.random.fold_in(kw, 2), (K, hk * d), jnp.float32) * 0.05
    wo = jax.random.normal(jax.random.fold_in(kw, 3), (h * d, K), jnp.float32) * 0.05
    params = layer.shard_params(wq, wk, wv, wo,
                                jnp.ones((d,), jnp.float32),
                                jnp.ones((d,), jnp.float32))
    x = jax.random.normal(kx, (batch * seq, K), jnp.float32) * 0.1
    out = layer.forward_ar(params, x, batch=batch)
    want = _attn_golden(x, wq, wk, wv, wo, h, hk, d, batch, layer.rope_theta,
                        qk_eps=1e-6)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-4, rtol=2e-4)


def test_rms_norm_golden():
    x = jax.random.normal(jax.random.key(5), (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.key(6), (64,), jnp.float32)
    got = rms_norm(x, w, eps=1e-6)
    want = x / jnp.sqrt((x * x).mean(-1, keepdims=True) + 1e-6) * w
    assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5)


def test_tp_attn_varlen_packed():
    """A packed 2-sequence batch through TPAttn(segment_ids=...) equals
    running each sequence separately (per-segment RoPE restart + segment
    attention masking)."""
    import numpy as np

    n, h, hk, d = 2, 4, 2, 32
    hidden = 64
    lens = [24, 16]
    seq = sum(lens)                       # 40 packed rows, batch=1
    mesh = _mesh(n)
    layer = TPAttn(mesh, num_heads=h, num_kv_heads=hk, head_dim=d,
                   axis=TP_AXIS)
    params = layer.init(jax.random.key(20), hidden, dtype=jnp.float32,
                        scale=0.2)
    rng = np.random.default_rng(21)
    x = jnp.asarray(rng.standard_normal((seq, hidden)).astype(np.float32)
                    * 0.3)
    seg = np.zeros((1, seq), np.int32)
    seg[0, lens[0]:] = 1
    xs = shard(mesh, x, TP_AXIS, None)
    packed = layer.forward(params, xs, batch=1,
                           segment_ids=jnp.asarray(seg))
    packed = np.asarray(jax.device_get(packed))
    # golden: each sequence alone through the same layer (plain forward)
    start = 0
    for seg_len in lens:
        # segment lengths are chosen divisible by the mesh size (the
        # fused ops' M % n constraint); odd lengths would need padding
        piece = x[start:start + seg_len]
        alone = layer.forward(
            params, shard(mesh, piece, TP_AXIS, None), batch=1
        )
        np.testing.assert_allclose(
            packed[start:start + seg_len], np.asarray(jax.device_get(alone)),
            atol=2e-4, rtol=2e-4,
        )
        start += seg_len


def test_tp_attn_varlen_packed_ar_path():
    """The AR (replicated small-M) forward handles packed batches too."""
    import numpy as np

    n, h, hk, d, hidden = 2, 4, 2, 32, 64
    lens = [16, 8]
    seq = sum(lens)
    mesh = _mesh(n)
    layer = TPAttn(mesh, num_heads=h, num_kv_heads=hk, head_dim=d,
                   axis=TP_AXIS)
    params = layer.init(jax.random.key(22), hidden, dtype=jnp.float32,
                        scale=0.2)
    rng = np.random.default_rng(23)
    x = jnp.asarray(rng.standard_normal((seq, hidden)).astype(np.float32)
                    * 0.3)
    seg = np.zeros((1, seq), np.int32)
    seg[0, lens[0]:] = 1
    packed = np.asarray(jax.device_get(
        layer.forward_ar(params, x, batch=1, segment_ids=jnp.asarray(seg))
    ))
    start = 0
    for seg_len in lens:
        alone = layer.forward_ar(params, x[start:start + seg_len], batch=1)
        np.testing.assert_allclose(
            packed[start:start + seg_len],
            np.asarray(jax.device_get(alone)), atol=2e-4, rtol=2e-4,
        )
        start += seg_len
