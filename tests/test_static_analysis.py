"""tdt.analysis: the static protocol verifier (ISSUE 2).

CPU-only, no interpret mode: kernels are symbolically executed per rank
through the record mode in ``lang.primitives`` and the composed N-rank
traces checked for signal balance, deadlock freedom, write-overlap, and
collective divergence.  The shipped collective kernels must verify clean
at every rank count; the seeded-bad fixtures must each be flagged with
the violating semaphore/chunk named.
"""

import os
import subprocess
import sys

import pytest

from triton_distributed_tpu import analysis
from triton_distributed_tpu.analysis import fixtures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shipped kernels: the full registry must verify clean


@pytest.mark.parametrize("n", [2, 4, 8])
def test_shipped_kernels_clean(n):
    results = analysis.verify_all(ranks=(n,))
    assert results, "registry enumerated no kernel cases"
    bad = {c.name: [str(v) for v in vs] for c, vs in results if vs}
    assert not bad, bad


def test_registry_covers_required_families():
    """The ISSUE-2 matrix: every kernel builder family in comm/ and ops/."""
    names = {c.name for c in analysis.all_cases(ranks=(4,))}
    required = {
        "allgather/push_1shot", "allgather/ring_1d", "allgather/ring_bidir",
        "reduce_scatter/ring", "allreduce/one_shot", "allreduce/two_shot",
        "all_to_all/dispatch", "all_to_all/combine", "all_to_all/scheduled",
        "ag_gemm/unidir", "ag_gemm/bidir", "gemm_rs/ring", "gemm_ar/ring",
        "fused_mlp_ar/swiglu", "fused_mlp_ar/linear",
        # the two-level (ICI x DCN) family at the 2x2 layout (ISSUE 10);
        # 2x4 and 4x2 enumerate at n=8
        "hier_allgather/2x2", "hier_reduce_scatter/2x2",
        "hier_allreduce/2x2", "hier_a2a/2x2",
    }
    assert required <= names, required - names
    names8 = {c.name for c in analysis.all_cases(ranks=(8,))}
    assert {"hier_allreduce/2x4", "hier_allreduce/4x2",
            "hier_a2a/2x4", "hier_a2a/4x2"} <= names8


def test_fori_loop_patch_is_thread_scoped():
    """While one thread records, OTHER threads must still reach the real
    jax.lax.fori_loop (the patch dispatches on the thread-local recorder,
    so TDT_VERIFY verification cannot corrupt concurrent jax tracing)."""
    import threading

    import jax

    done = {}
    gate = threading.Barrier(2)

    def other_thread():
        gate.wait()
        done["val"] = int(jax.lax.fori_loop(0, 3, lambda i, v: v + i, 0))

    t = threading.Thread(target=other_thread)
    orig = jax.lax.fori_loop
    with analysis.recording((("tp", 2),), {"tp": 0}):
        assert jax.lax.fori_loop is not orig   # patched...
        t.start()
        gate.wait()                            # ...while the other runs
        t.join()
    assert done["val"] == 3
    assert jax.lax.fori_loop is orig


def test_start_false_rejected_in_record_mode():
    """An unstarted descriptor has no static issue point: modeling it at
    creation would credit semaphores for a copy that may never run, so
    record mode refuses loudly instead of verifying a false CLEAN."""
    from triton_distributed_tpu.analysis import FakeRef, FakeSem
    from triton_distributed_tpu.lang import primitives as dl

    with analysis.recording((("tp", 2),), {"tp": 0}):
        with pytest.raises(NotImplementedError, match="start=False"):
            dl.remote_copy(FakeRef("x", (4,)), FakeRef("y", (4,)),
                           FakeSem("s"), FakeSem("r"), 1, start=False)
        with pytest.raises(NotImplementedError, match="start=False"):
            dl.local_copy(FakeRef("x", (4,)), FakeRef("y", (4,)),
                          FakeSem("s"), start=False)


def test_record_mode_restores_state():
    """Recording must leave no trace: the thread-local recorder cleared and
    jax.lax.fori_loop unpatched, even after a kernel body raises."""
    import jax

    from triton_distributed_tpu.lang import primitives as dl

    orig_fori = jax.lax.fori_loop
    with pytest.raises(RuntimeError, match="boom"):
        with analysis.recording((("tp", 2),), {"tp": 0}):
            raise RuntimeError("boom")
    assert dl.active_recorder() is None
    assert jax.lax.fori_loop is orig_fori


# ---------------------------------------------------------------------------
# seeded-bad protocols: each defect class must be flagged, by name


def _violations(case_name, n=4):
    case = {c.name: c for c in fixtures.fixture_cases(n)}[case_name]
    return analysis.verify_case(case)


def test_missing_notify_flagged_as_signal_imbalance():
    vs = _violations("fixture/missing_notify")
    hits = [v for v in vs if v.check == "signal_balance"]
    assert hits, [str(v) for v in vs]
    # the message names the violating semaphore and the count mismatch
    assert any("ready" in v.message and "1" in v.message
               and "2" in v.message for v in hits)


def test_crossed_wait_flagged_as_deadlock_cycle():
    vs = _violations("fixture/crossed_wait")
    assert [v.check for v in vs] == ["deadlock"], [str(v) for v in vs]
    msg = vs[0].message
    assert "flag" in msg                       # the semaphore
    assert "wait-for cycle" in msg             # the cycle itself


def test_overlapping_destination_flagged_as_write_overlap():
    vs = _violations("fixture/overlapping_writes")
    hits = [v for v in vs if v.check == "write_overlap"]
    assert hits, [str(v) for v in vs]
    # names the destination buffer + chunk rows
    assert any("out[0:4" in v.message for v in hits)


def test_method_divergence_flagged():
    vs = _violations("fixture/diverged_method")
    assert [v.check for v in vs] == ["collective_divergence"]
    assert "one_shot" in vs[0].message and "two_shot" in vs[0].message


def test_fixture_selftest_battery():
    assert fixtures.run_selftest() == []


def test_hier_dropped_dcn_credit_flagged():
    """The ISSUE-10 two-level defect class: a DCN broadcast that consumes
    one fewer inter-slice arrival credit than the slices deliver must be
    flagged as a signal imbalance NAMING the dcn semaphore (the surplus
    credit would satisfy a future wait before its block landed)."""
    vs = _violations("fixture/hier_dropped_dcn_credit")
    hits = [v for v in vs if v.check == "signal_balance"]
    assert hits, [str(v) for v in vs]
    assert any("dcn_recv_sems" in v.message for v in hits), \
        [v.message for v in hits]


def test_unacked_slot_reuse_flagged():
    """The subtle case the vector-clock model exists for: two program-
    ordered sends into the SAME remote slot are unordered ON THE WIRE;
    only an ACK credit chain (the ring-RS protocol) orders the landings."""
    from jax.experimental import pallas as pl  # noqa: F401  (parity w/ kernels)

    from triton_distributed_tpu.analysis import FakeRef, FakeSem, analyze
    from triton_distributed_tpu.analysis.record import record_kernel
    from triton_distributed_tpu.lang import primitives as dl
    from triton_distributed_tpu.lang.primitives import Team

    n = 2
    team = Team((("tp", n),), "tp")

    def kernel(with_ack):
        _, right = team.neighbor_ranks()
        left, _ = team.neighbor_ranks()
        rid = team.device_id(right)
        x = FakeRef("x", (4, 8))
        slot = FakeRef("recv_slot", (4, 8))
        ss, rs = FakeSem("send_sem"), FakeSem("recv_sem")
        ack = FakeSem("ack", kind="regular")
        dl.remote_copy(x, slot, ss, rs, rid)
        # consume the FIRST arrival and credit its producer before the
        # second send (the ack chain), or skip the ack entirely
        dl.wait_recv(slot, rs)
        dl.notify(ack, team.device_id(left))
        if with_ack:
            dl.wait(ack, 1)
        dl.remote_copy(x, slot, ss, rs, rid)
        dl.wait_recv(slot, rs)
        dl.wait_send(x, ss)
        dl.wait_send(x, ss)
        if not with_ack:
            dl.wait(ack, 1)   # keep the credit balance identical

    def run(with_ack):
        traces, sigs = [], []
        for r in range(n):
            rec = record_kernel(lambda: kernel(with_ack), n=n, rank=r)
            traces.append(rec.events)
            sigs.append(rec.collapsed_signature())
        return analyze("unacked", n, traces, sigs, ["v"] * n)

    assert any(v.check == "write_overlap" for v in run(False))
    assert run(True) == []


# ---------------------------------------------------------------------------
# build hook + obs counters


def test_verify_build_hook(monkeypatch):
    from triton_distributed_tpu.analysis import registry as reg
    from triton_distributed_tpu.core import compilation

    monkeypatch.delenv("TDT_VERIFY", raising=False)
    assert not compilation.protocol_verify_enabled()
    compilation.verify_protocol("allgather", 4)   # off: no-op

    monkeypatch.setenv("TDT_VERIFY", "1")
    assert compilation.protocol_verify_enabled()
    monkeypatch.setattr(reg, "_VERIFIED", set())
    compilation.verify_protocol("allgather", 4)   # clean family passes
    assert ("allgather", 4, None) in reg._VERIFIED
    compilation.verify_protocol("ep_dispatch", 4)  # alias resolves
    assert ("all_to_all", 4, None) in reg._VERIFIED
    compilation.verify_protocol("allgather", 1)   # degenerate mesh: skip
    with pytest.raises(KeyError, match="unknown kernel family"):
        compilation.verify_protocol("nonexistent", 4)
    # the explore knob threads through: a bounded-DPOR verification is
    # memoized under its own depth (canonical and explored runs are
    # different facts)
    monkeypatch.setenv("TDT_VERIFY_EXPLORE", "1")
    compilation.verify_protocol("allgather", 4)
    assert ("allgather", 4, 1) in reg._VERIFIED
    monkeypatch.setenv("TDT_VERIFY_EXPLORE", "exact")
    assert compilation.explore_depth() == -1
    # any NEGATIVE integer means exact too (clamping to bound 0 would
    # silently weaken a gate the operator asked to be exhaustive)
    monkeypatch.setenv("TDT_VERIFY_EXPLORE", "-1")
    assert compilation.explore_depth() == -1
    monkeypatch.setenv("TDT_VERIFY_EXPLORE", "junk")
    with pytest.raises(ValueError, match="TDT_VERIFY_EXPLORE"):
        compilation.explore_depth()


def test_vmem_budget_env_is_loud_and_scoped(monkeypatch):
    """TDT_VMEM_BUDGET: malformed values raise (a silent 128 MiB
    fallback would green-light the lint against the wrong part), a
    lowered budget reaches the LINT, and the autotuner's pruning
    deliberately ignores it (the multi-process identical-candidates
    invariant must not depend on per-host env state)."""
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis import footprint as fpm
    from triton_distributed_tpu.core import compilation
    from triton_distributed_tpu.tune import autotuner as at

    monkeypatch.setenv("TDT_VMEM_BUDGET", "64 MiB")
    with pytest.raises(ValueError, match="TDT_VMEM_BUDGET"):
        compilation.vmem_budget_bytes()
    # 64 MiB physical: the 100 MiB-requesting VL tiles fail the LINT...
    monkeypatch.setenv("TDT_VMEM_BUDGET", str(64 * 2**20))
    vl_tile = (2048, 1024, 512, at.MATMUL_TILE_VL)
    dims = dict(m=8192, n=8192, k=8192, dtype=jnp.bfloat16)
    assert any("physical" in p
               for p in fpm.config_feasible("matmul", vl_tile, dims))
    # ...but pruning still keeps them (physical bound pinned to the
    # compile-time constant)
    kept = at.prune_infeasible("matmul", [at.XlaBackend(), vl_tile],
                               at.XlaBackend(), dims)
    assert vl_tile in kept


def test_obs_counters_record_checks_and_violations():
    from triton_distributed_tpu import obs

    obs.enable(True)
    obs.REGISTRY.reset()
    try:
        analysis.verify_case(analysis.cases_for("gemm_rs", 4)[0])
        bad = {c.name: c for c in fixtures.fixture_cases(2)}
        analysis.verify_case(bad["fixture/crossed_wait"])
        rows = {(r["name"], r["labels"].get("kernel"),
                 r["labels"].get("check")): r["value"]
                for r in obs.REGISTRY.snapshot()}
        assert rows[("verify_checks", "gemm_rs", "deadlock")] == 1
        assert rows[("verify_violations", "fixture", "deadlock")] >= 1
        assert ("verify_violations", "gemm_rs", "deadlock") not in rows
    finally:
        obs.REGISTRY.reset()
        obs.enable(None)   # restore the env-driven default


# ---------------------------------------------------------------------------
# the CLI (satellite: tier-1 shells the full lint matrix)


def _run_lint(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         *args],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )


def test_cli_full_matrix_clean():
    res = _run_lint()
    assert res.returncode == 0, res.stdout + res.stderr
    # 69 = the ISSUE-9-era 51 (pre-ISSUE-8 36 + fused_mlp_ar x {2,4,8} +
    # quantized wire variants x {2,4,8}) plus the ISSUE-10
    # all_to_all/scheduled variant x {2,4,8} and the hierarchical
    # two-level cases (4 families x the {2x2} layout at n=4 + 4 x the
    # {2x4, 4x2} layouts at n=8 = 12), plus the ISSUE-13 persistent
    # multi-layer decode chain x {2,4,8}
    assert "69 kernel cases" in res.stdout
    assert "0 violation(s)" in res.stdout


def test_cli_selftest():
    res = _run_lint("--selftest")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "selftest OK" in res.stdout


# ---------------------------------------------------------------------------
# ISSUE 15: the DPOR explorer (analysis.explore)


def _ev():
    from triton_distributed_tpu.analysis.events import NotifyEv, WaitEv

    return NotifyEv, WaitEv


def test_dpor_class_counts_hand_computed():
    """Equivalence-class counts pinned on cases small enough to count by
    hand — the reduction's exactness contract (sleep sets + singleton
    persistent sets must neither duplicate nor drop a class)."""
    from triton_distributed_tpu.analysis import explore as ex

    NotifyEv, WaitEv = _ev()
    s, t = ("s", None), ("t", None)
    # one producer, one consumer: every interleaving equivalent
    r = ex.explore("h1", 2, [[NotifyEv(s, 1, 1)], [WaitEv(s, 1, "count")]],
                   preemption_bound=None)
    assert (r.schedules, r.violations) == (1, [])
    # crossed produce/consume on two pools: still one class
    r = ex.explore("h2", 2, [
        [NotifyEv(s, 1, 1), WaitEv(t, 1, "count")],
        [NotifyEv(t, 0, 1), WaitEv(s, 1, "count")],
    ], preemption_bound=None)
    assert (r.schedules, r.violations) == (1, [])
    # TWO producers into one pool, consumed credit-by-credit: exactly
    # the multi-producer matching ambiguity -> 2 classes
    two = [[WaitEv(s, 1, "count"), WaitEv(s, 1, "count")],
           [NotifyEv(s, 0, 1)], [NotifyEv(s, 0, 1)]]
    r = ex.explore("h3", 3, two, preemption_bound=None)
    assert (r.schedules, r.violations) == (2, [])
    # same producers, ONE bulk wait: arrival order unobservable -> 1
    bulk = [[WaitEv(s, 2, "count")], [NotifyEv(s, 0, 1)],
            [NotifyEv(s, 0, 1)]]
    r = ex.explore("h4", 3, bulk, preemption_bound=None)
    assert (r.schedules, r.violations) == (1, [])


def test_dpor_finds_deadlock_with_blocked_waits_named():
    from triton_distributed_tpu.analysis import explore as ex

    NotifyEv, WaitEv = _ev()
    s = ("flag", None)
    r = ex.explore("dead", 2, [
        [WaitEv(s, 1, "count"), NotifyEv(s, 1, 1)],
        [WaitEv(s, 1, "count"), NotifyEv(s, 0, 1)],
    ], preemption_bound=None)
    assert [v.check for v in r.violations] == ["deadlock"]
    assert "flag" in r.violations[0].message
    assert r.witness is not None


def test_dpor_fixture_selftest_both_directions():
    """The ISSUE-15 acceptance pin: every order-dependent fixture PASSES
    the canonical schedule (all four checks) and FAILS under DPOR with
    the reused slot named — asserted in both directions by the
    selftest, and spot-checked here so a selftest regression cannot
    weaken the contract silently."""
    assert fixtures.run_dpor_selftest() == []
    for case in fixtures.dpor_fixture_cases(4):
        assert analysis.verify_case(case) == [], case.name
        res = analysis.explore_case(case)
        assert any(v.check == "write_overlap" for v in res.violations), \
            (case.name, [str(v) for v in res.violations])
        assert res.schedules >= 2        # the flipped class was reached


def test_dpor_also_flags_canonical_bad_fixtures():
    """The explorer is not a parallel universe: defects the canonical
    run already catches (deadlock, overlap visible on every schedule)
    are caught by DPOR too."""
    bad = {c.name: c for c in fixtures.fixture_cases(4)}
    res = analysis.explore_case(bad["fixture/crossed_wait"])
    assert any(v.check == "deadlock" for v in res.violations)
    res = analysis.explore_case(bad["fixture/overlapping_writes"])
    assert any(v.check == "write_overlap" for v in res.violations)


def test_dpor_registry_green_under_bounded_mode():
    """Every shipped kernel case at ranks {2, 4} verifies clean under
    the bounded explorer (the n=8 column rides the --dpor CLI smoke);
    under the reduction stack almost every case is ONE class — branch
    points exist only at multi-producer credit races."""
    results = analysis.explore_all((2, 4))
    assert results
    bad = {r.kernel: [str(v) for v in r.violations]
           for r in results if r.violations}
    assert not bad, bad
    # the single-producer protocols explore EXHAUSTIVELY (not capped)
    for r in results:
        if r.kernel in ("allgather/ring_1d", "gemm_rs/ring",
                        "persistent_decode/chain"):
            assert not r.pruned and r.schedules == 1, \
                (r.kernel, r.schedules, r.pruned)


def test_dpor_preemption_bound_and_caps_mark_pruned():
    from triton_distributed_tpu.analysis import explore as ex

    NotifyEv, WaitEv = _ev()
    s = ("s", None)
    two = [[WaitEv(s, 1, "count"), WaitEv(s, 1, "count")],
           [NotifyEv(s, 0, 1)], [NotifyEv(s, 0, 1)]]
    r = ex.explore("cap", 3, two, preemption_bound=None, max_schedules=1)
    assert r.schedules == 1 and r.pruned
    # bound 0 still explores free-choice reorderings (the fixtures'
    # flipped matchings are reachable without a single preemption)
    r = ex.explore("b0", 3, two, preemption_bound=0)
    assert r.schedules >= 1 and not r.violations


def test_explore_obs_counters():
    from triton_distributed_tpu import obs

    obs.enable(True)
    obs.REGISTRY.reset()
    try:
        analysis.explore_case(analysis.cases_for("gemm_rs", 4)[0])
        rows = {(r["name"], r["labels"].get("kernel")): r["value"]
                for r in obs.REGISTRY.snapshot()}
        assert rows[("explore_schedules", "gemm_rs")] == 1
        assert ("explore_pruned", "gemm_rs") not in rows
    finally:
        obs.REGISTRY.reset()
        obs.enable(None)


def test_verify_build_explore_knob_catches_dpor_fixture(monkeypatch):
    """TDT_VERIFY_EXPLORE end-to-end: a family whose cases pass the
    canonical checks but race under reordering builds fine at depth
    None and raises ProtocolViolationError when the explorer is armed."""
    from triton_distributed_tpu.analysis import registry as reg

    case = fixtures.dpor_fixture_cases(4)[0]
    monkeypatch.setattr(reg, "_VERIFIED", set())
    monkeypatch.setitem(reg._FAMILY_CASES, "dpor_fixture",
                        lambda n: [case])
    try:
        reg.maybe_verify_build("dpor_fixture", 4)            # canonical: ok
        with pytest.raises(analysis.ProtocolViolationError,
                           match="write_overlap"):
            reg.maybe_verify_build("dpor_fixture", 4, explore=2)
    finally:
        reg._FAMILY_CASES.pop("dpor_fixture", None)


# ---------------------------------------------------------------------------
# ISSUE 15: the footprint calculator (analysis.footprint)


def test_footprint_goldens_vs_known_scratch_shapes():
    """Byte-exact pins against the builders' scratch math: the (bm, bn)
    f32 accumulator plus the emit_pipeline double-buffered blocks."""
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis import footprint as fpm
    from triton_distributed_tpu.ops.gemm_rs import GemmRsConfig

    # matmul tile (512, 1792, 512) bf16: acc 512*1792*4 +
    # 2*(512*512 + 512*1792)*2 + 2*512*1792*2
    fp = fpm.matmul((512, 1792, 512), m=4096, n=4096, k=4096,
                    dtype=jnp.bfloat16)
    assert fp.vmem_bytes == 512 * 1792 * 4 \
        + 2 * (512 * 512 + 512 * 1792) * 2 + 2 * 512 * 1792 * 2
    # gemm_rs at its 2-rank serving shape: acc + matmul pipeline + the
    # travelling-partial add pipeline; HBM carries the 3 (2, m_loc, n)
    # ring slots; sems mirror the scratch list (2 dma pairs + 2 acks)
    cfg = GemmRsConfig().clip(64, 128, 64)
    fp = fpm.gemm_rs(cfg, m_loc=64, k_loc=128, n_dim=64, num_ranks=2,
                     dtype=jnp.float32)
    assert fp.hbm_scratch_bytes == 3 * 2 * 64 * 64 * 4
    assert (fp.dma_sems, fp.regular_sems) == (4, 2)
    assert fp.vmem_bytes == 64 * 64 * 4 \
        + 2 * (64 * 128 + 128 * 64) * 4 + 2 * 64 * 64 * 4 \
        + 2 * 3 * 64 * 64 * 4


def test_footprint_sem_counts_match_recorded_traces():
    """The independent cross-check the ISSUE names: semaphore counts
    derived from the RECORDED protocol traces equal the calculator's
    (recorded regular counts carry +1 where the kernel uses the implicit
    Mosaic collective-barrier semaphore, which no scratch list
    allocates)."""
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis import footprint as fpm
    from triton_distributed_tpu.comm.allreduce import AllReduceConfig
    from triton_distributed_tpu.ops.gemm_rs import GemmRsConfig

    cases = {c.name: c for c in analysis.all_cases(ranks=(4,))}
    dma, reg = fpm.sems_of_case(cases["gemm_rs/ring"])
    want = fpm.gemm_rs(GemmRsConfig(), m_loc=4, k_loc=8, n_dim=4,
                       num_ranks=4, dtype=jnp.float32)
    assert (dma, reg) == (want.dma_sems, want.regular_sems + 1)
    dma, reg = fpm.sems_of_case(cases["allreduce/two_shot"])
    want = fpm.allreduce(AllReduceConfig(), m=8, r=8, num_ranks=4,
                         dtype=jnp.float32)
    assert (dma, reg) == (want.dma_sems, want.regular_sems + 1)
    dma, reg = fpm.sems_of_case(cases["all_to_all/dispatch"])
    want = fpm.all_to_all(None, t=16, h=4, num_ranks=4,
                          dtype=jnp.float32)
    assert (dma, reg) == (want.dma_sems, want.regular_sems + 1)


def test_footprint_validation_and_budget_resolution():
    import jax.numpy as jnp

    from triton_distributed_tpu.analysis import footprint as fpm
    from triton_distributed_tpu.core import compilation

    # a tile tuple's optional 4th element is its requested budget
    assert fpm.budget_for((512, 512, 512)) == \
        compilation.MOSAIC_DEFAULT_VMEM_BYTES
    assert fpm.budget_for((512, 512, 512, 100 * 2**20)) == 100 * 2**20
    # an oversubscribing tile is named with both numbers
    fp = fpm.matmul((2048, 2048, 2048), m=8192, n=8192, k=8192,
                    dtype=jnp.bfloat16)
    problems = fpm.validate(fp, (2048, 2048, 2048), label="matmul")
    assert problems and "oversubscribes" in problems[0]
    # ...and a budget beyond physical VMEM is itself flagged
    problems = fpm.validate(fp, budget=512 * 2**20, label="matmul")
    assert any("physical" in p for p in problems)
    # persistent default: the ISSUE-15 lint found the old None default
    # unbuildable at serving dims — the shipped default now requests
    # the raised budget and must stay feasible there
    assert fpm.check_defaults() == []


def test_footprint_unknown_family_never_prunes():
    from triton_distributed_tpu.analysis import footprint as fpm

    assert fpm.config_feasible("no_such_family", (1, 1, 1), {}) == []


# ---------------------------------------------------------------------------
# ISSUE 15: the completeness lint (analysis.completeness)


def test_completeness_green_on_repo():
    from triton_distributed_tpu.analysis import completeness

    assert completeness.check() == []


def test_completeness_flags_missing_wiring(monkeypatch):
    """The golden is a tripwire, not documentation: removing a cost
    calculator or desyncing a collective_id fails with the family and
    the missing piece named."""
    from triton_distributed_tpu.analysis import completeness
    from triton_distributed_tpu.core import compilation
    from triton_distributed_tpu.obs import costs

    missing = dict(costs.FAMILY_COSTS)
    del missing["ag_gemm"]
    monkeypatch.setattr(costs, "FAMILY_COSTS", missing)
    problems = completeness.check()
    assert any("ag_gemm" in p and "FAMILY_COSTS" in p for p in problems)

    drifted = dict(compilation._COLLECTIVE_IDS)
    drifted["gemm_ar"] = 5                      # collides with ag_gemm
    monkeypatch.setattr(compilation, "_COLLECTIVE_IDS", drifted)
    problems = completeness.check()
    assert any("collective_id" in p and "gemm_ar" in p for p in problems)


def test_completeness_flags_unregistered_family(monkeypatch):
    from triton_distributed_tpu.analysis import completeness
    from triton_distributed_tpu.analysis import registry as reg

    monkeypatch.setattr(reg, "FAMILIES", (*reg.FAMILIES, "brand_new"))
    problems = completeness.check()
    assert any("brand_new" in p and "golden" in p for p in problems)


# ---------------------------------------------------------------------------
# ISSUE 15: the CLI legs (FAST_NODES smokes)


def test_tdt_lint_dpor_smoke():
    res = _run_lint("--dpor", "--ranks", "2,4")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "dpor OK" in res.stdout
    assert "fails under reordering" in res.stdout


def test_tdt_lint_completeness_smoke():
    res = _run_lint("--completeness")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "completeness OK" in res.stdout


def test_cli_dpor_full_registry_within_budget():
    """The acceptance bound: the FULL registry (ranks {2,4,8}, hier
    layouts included) verifies clean under bounded DPOR inside the lint
    time budget."""
    import time

    t0 = time.monotonic()
    res = _run_lint("--dpor")
    dt = time.monotonic() - t0
    assert res.returncode == 0, res.stdout + res.stderr
    assert "69 cases" in res.stdout
    assert dt < 120, f"--dpor took {dt:.0f}s — over the lint budget"


def test_explore_case_reuses_recorded_traces(monkeypatch):
    """A build-time verification with the explore knob armed records
    each case's N rank traces ONCE (review finding: verify + explore
    each recorded independently, doubling kernel-thunk execution)."""
    from triton_distributed_tpu.analysis import record as rec_mod
    from triton_distributed_tpu.analysis import registry as reg

    calls = []
    real = rec_mod.record_kernel

    def spy(thunk, **kw):
        calls.append(kw.get("rank"))
        return real(thunk, **kw)

    monkeypatch.setattr(reg, "record_kernel", spy)
    monkeypatch.setattr(reg, "_VERIFIED", set())
    reg.maybe_verify_build("gemm_rs", 2, explore=1)
    assert len(calls) == 2                # one recording pass, 2 ranks
    # and the shared-pass plumbing returns identical results
    case = analysis.cases_for("gemm_rs", 4)[0]
    recorded = analysis.record_case(case)
    assert analysis.verify_case(case, recorded=recorded) == []
    assert analysis.explore_case(case, recorded=recorded).violations == []


def test_cli_dpor_negative_bound_means_exact():
    """`--explore-bound -1` follows the TDT_VERIFY_EXPLORE convention
    (negative = exact) instead of silently running the WEAKEST bound
    while reporting success (review finding)."""
    res = _run_lint("--dpor", "--ranks", "2", "--kernel", "gemm_rs",
                    "--explore-bound", "-1")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "preemption bound exact" in res.stdout


def test_explorer_state_agrees_with_canonical_simulator():
    """TWO implementations of the credit-FIFO semantics exist — the
    canonical simulator (checks._simulate) and the explorer's
    backtrackable state (explore._State) — and they must never drift:
    replaying the canonical round-robin schedule through the explorer's
    state must reproduce the simulator's writes (regions, start clocks,
    transfer ids) and settle map BYTE-FOR-BYTE (review-pinned; the full
    registry sweep is the exhaustive version of this check and runs in
    the --dpor leg)."""
    from triton_distributed_tpu.analysis import checks
    from triton_distributed_tpu.analysis import explore as ex

    for fam in ("gemm_rs", "allreduce", "all_to_all", "fused_mlp_ar",
                "persistent_decode"):
        for case in analysis.cases_for(fam, 4):
            traces, _sigs, _variants = analysis.record_case(case)
            dead, writes, settle, _clocks = checks._simulate(
                case.name, case.n, traces)
            st = ex._State(case.n, traces,
                           ex._pool_table(case.n, traces))
            progress = True
            while progress:
                progress = False
                for r in range(case.n):
                    while st.enabled(r):
                        st.execute(r)
                        progress = True
            assert st.done() == (not dead), case.name
            key = lambda w: (w.owner, w.region, w.start, w.tid, w.writer)
            assert list(map(key, st.writes)) == list(map(key, writes)), \
                case.name
            assert st.settle == settle, case.name
