"""tdt.analysis: the static protocol verifier (ISSUE 2).

CPU-only, no interpret mode: kernels are symbolically executed per rank
through the record mode in ``lang.primitives`` and the composed N-rank
traces checked for signal balance, deadlock freedom, write-overlap, and
collective divergence.  The shipped collective kernels must verify clean
at every rank count; the seeded-bad fixtures must each be flagged with
the violating semaphore/chunk named.
"""

import os
import subprocess
import sys

import pytest

from triton_distributed_tpu import analysis
from triton_distributed_tpu.analysis import fixtures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shipped kernels: the full registry must verify clean


@pytest.mark.parametrize("n", [2, 4, 8])
def test_shipped_kernels_clean(n):
    results = analysis.verify_all(ranks=(n,))
    assert results, "registry enumerated no kernel cases"
    bad = {c.name: [str(v) for v in vs] for c, vs in results if vs}
    assert not bad, bad


def test_registry_covers_required_families():
    """The ISSUE-2 matrix: every kernel builder family in comm/ and ops/."""
    names = {c.name for c in analysis.all_cases(ranks=(4,))}
    required = {
        "allgather/push_1shot", "allgather/ring_1d", "allgather/ring_bidir",
        "reduce_scatter/ring", "allreduce/one_shot", "allreduce/two_shot",
        "all_to_all/dispatch", "all_to_all/combine", "all_to_all/scheduled",
        "ag_gemm/unidir", "ag_gemm/bidir", "gemm_rs/ring", "gemm_ar/ring",
        "fused_mlp_ar/swiglu", "fused_mlp_ar/linear",
        # the two-level (ICI x DCN) family at the 2x2 layout (ISSUE 10);
        # 2x4 and 4x2 enumerate at n=8
        "hier_allgather/2x2", "hier_reduce_scatter/2x2",
        "hier_allreduce/2x2", "hier_a2a/2x2",
    }
    assert required <= names, required - names
    names8 = {c.name for c in analysis.all_cases(ranks=(8,))}
    assert {"hier_allreduce/2x4", "hier_allreduce/4x2",
            "hier_a2a/2x4", "hier_a2a/4x2"} <= names8


def test_fori_loop_patch_is_thread_scoped():
    """While one thread records, OTHER threads must still reach the real
    jax.lax.fori_loop (the patch dispatches on the thread-local recorder,
    so TDT_VERIFY verification cannot corrupt concurrent jax tracing)."""
    import threading

    import jax

    done = {}
    gate = threading.Barrier(2)

    def other_thread():
        gate.wait()
        done["val"] = int(jax.lax.fori_loop(0, 3, lambda i, v: v + i, 0))

    t = threading.Thread(target=other_thread)
    orig = jax.lax.fori_loop
    with analysis.recording((("tp", 2),), {"tp": 0}):
        assert jax.lax.fori_loop is not orig   # patched...
        t.start()
        gate.wait()                            # ...while the other runs
        t.join()
    assert done["val"] == 3
    assert jax.lax.fori_loop is orig


def test_start_false_rejected_in_record_mode():
    """An unstarted descriptor has no static issue point: modeling it at
    creation would credit semaphores for a copy that may never run, so
    record mode refuses loudly instead of verifying a false CLEAN."""
    from triton_distributed_tpu.analysis import FakeRef, FakeSem
    from triton_distributed_tpu.lang import primitives as dl

    with analysis.recording((("tp", 2),), {"tp": 0}):
        with pytest.raises(NotImplementedError, match="start=False"):
            dl.remote_copy(FakeRef("x", (4,)), FakeRef("y", (4,)),
                           FakeSem("s"), FakeSem("r"), 1, start=False)
        with pytest.raises(NotImplementedError, match="start=False"):
            dl.local_copy(FakeRef("x", (4,)), FakeRef("y", (4,)),
                          FakeSem("s"), start=False)


def test_record_mode_restores_state():
    """Recording must leave no trace: the thread-local recorder cleared and
    jax.lax.fori_loop unpatched, even after a kernel body raises."""
    import jax

    from triton_distributed_tpu.lang import primitives as dl

    orig_fori = jax.lax.fori_loop
    with pytest.raises(RuntimeError, match="boom"):
        with analysis.recording((("tp", 2),), {"tp": 0}):
            raise RuntimeError("boom")
    assert dl.active_recorder() is None
    assert jax.lax.fori_loop is orig_fori


# ---------------------------------------------------------------------------
# seeded-bad protocols: each defect class must be flagged, by name


def _violations(case_name, n=4):
    case = {c.name: c for c in fixtures.fixture_cases(n)}[case_name]
    return analysis.verify_case(case)


def test_missing_notify_flagged_as_signal_imbalance():
    vs = _violations("fixture/missing_notify")
    hits = [v for v in vs if v.check == "signal_balance"]
    assert hits, [str(v) for v in vs]
    # the message names the violating semaphore and the count mismatch
    assert any("ready" in v.message and "1" in v.message
               and "2" in v.message for v in hits)


def test_crossed_wait_flagged_as_deadlock_cycle():
    vs = _violations("fixture/crossed_wait")
    assert [v.check for v in vs] == ["deadlock"], [str(v) for v in vs]
    msg = vs[0].message
    assert "flag" in msg                       # the semaphore
    assert "wait-for cycle" in msg             # the cycle itself


def test_overlapping_destination_flagged_as_write_overlap():
    vs = _violations("fixture/overlapping_writes")
    hits = [v for v in vs if v.check == "write_overlap"]
    assert hits, [str(v) for v in vs]
    # names the destination buffer + chunk rows
    assert any("out[0:4" in v.message for v in hits)


def test_method_divergence_flagged():
    vs = _violations("fixture/diverged_method")
    assert [v.check for v in vs] == ["collective_divergence"]
    assert "one_shot" in vs[0].message and "two_shot" in vs[0].message


def test_fixture_selftest_battery():
    assert fixtures.run_selftest() == []


def test_hier_dropped_dcn_credit_flagged():
    """The ISSUE-10 two-level defect class: a DCN broadcast that consumes
    one fewer inter-slice arrival credit than the slices deliver must be
    flagged as a signal imbalance NAMING the dcn semaphore (the surplus
    credit would satisfy a future wait before its block landed)."""
    vs = _violations("fixture/hier_dropped_dcn_credit")
    hits = [v for v in vs if v.check == "signal_balance"]
    assert hits, [str(v) for v in vs]
    assert any("dcn_recv_sems" in v.message for v in hits), \
        [v.message for v in hits]


def test_unacked_slot_reuse_flagged():
    """The subtle case the vector-clock model exists for: two program-
    ordered sends into the SAME remote slot are unordered ON THE WIRE;
    only an ACK credit chain (the ring-RS protocol) orders the landings."""
    from jax.experimental import pallas as pl  # noqa: F401  (parity w/ kernels)

    from triton_distributed_tpu.analysis import FakeRef, FakeSem, analyze
    from triton_distributed_tpu.analysis.record import record_kernel
    from triton_distributed_tpu.lang import primitives as dl
    from triton_distributed_tpu.lang.primitives import Team

    n = 2
    team = Team((("tp", n),), "tp")

    def kernel(with_ack):
        _, right = team.neighbor_ranks()
        left, _ = team.neighbor_ranks()
        rid = team.device_id(right)
        x = FakeRef("x", (4, 8))
        slot = FakeRef("recv_slot", (4, 8))
        ss, rs = FakeSem("send_sem"), FakeSem("recv_sem")
        ack = FakeSem("ack", kind="regular")
        dl.remote_copy(x, slot, ss, rs, rid)
        # consume the FIRST arrival and credit its producer before the
        # second send (the ack chain), or skip the ack entirely
        dl.wait_recv(slot, rs)
        dl.notify(ack, team.device_id(left))
        if with_ack:
            dl.wait(ack, 1)
        dl.remote_copy(x, slot, ss, rs, rid)
        dl.wait_recv(slot, rs)
        dl.wait_send(x, ss)
        dl.wait_send(x, ss)
        if not with_ack:
            dl.wait(ack, 1)   # keep the credit balance identical

    def run(with_ack):
        traces, sigs = [], []
        for r in range(n):
            rec = record_kernel(lambda: kernel(with_ack), n=n, rank=r)
            traces.append(rec.events)
            sigs.append(rec.collapsed_signature())
        return analyze("unacked", n, traces, sigs, ["v"] * n)

    assert any(v.check == "write_overlap" for v in run(False))
    assert run(True) == []


# ---------------------------------------------------------------------------
# build hook + obs counters


def test_verify_build_hook(monkeypatch):
    from triton_distributed_tpu.analysis import registry as reg
    from triton_distributed_tpu.core import compilation

    monkeypatch.delenv("TDT_VERIFY", raising=False)
    assert not compilation.protocol_verify_enabled()
    compilation.verify_protocol("allgather", 4)   # off: no-op

    monkeypatch.setenv("TDT_VERIFY", "1")
    assert compilation.protocol_verify_enabled()
    monkeypatch.setattr(reg, "_VERIFIED", set())
    compilation.verify_protocol("allgather", 4)   # clean family passes
    assert ("allgather", 4) in reg._VERIFIED
    compilation.verify_protocol("ep_dispatch", 4)  # alias resolves
    assert ("all_to_all", 4) in reg._VERIFIED
    compilation.verify_protocol("allgather", 1)   # degenerate mesh: skip
    with pytest.raises(KeyError, match="unknown kernel family"):
        compilation.verify_protocol("nonexistent", 4)


def test_obs_counters_record_checks_and_violations():
    from triton_distributed_tpu import obs

    obs.enable(True)
    obs.REGISTRY.reset()
    try:
        analysis.verify_case(analysis.cases_for("gemm_rs", 4)[0])
        bad = {c.name: c for c in fixtures.fixture_cases(2)}
        analysis.verify_case(bad["fixture/crossed_wait"])
        rows = {(r["name"], r["labels"].get("kernel"),
                 r["labels"].get("check")): r["value"]
                for r in obs.REGISTRY.snapshot()}
        assert rows[("verify_checks", "gemm_rs", "deadlock")] == 1
        assert rows[("verify_violations", "fixture", "deadlock")] >= 1
        assert ("verify_violations", "gemm_rs", "deadlock") not in rows
    finally:
        obs.REGISTRY.reset()
        obs.enable(None)   # restore the env-driven default


# ---------------------------------------------------------------------------
# the CLI (satellite: tier-1 shells the full lint matrix)


def _run_lint(*args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         *args],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )


def test_cli_full_matrix_clean():
    res = _run_lint()
    assert res.returncode == 0, res.stdout + res.stderr
    # 69 = the ISSUE-9-era 51 (pre-ISSUE-8 36 + fused_mlp_ar x {2,4,8} +
    # quantized wire variants x {2,4,8}) plus the ISSUE-10
    # all_to_all/scheduled variant x {2,4,8} and the hierarchical
    # two-level cases (4 families x the {2x2} layout at n=4 + 4 x the
    # {2x4, 4x2} layouts at n=8 = 12), plus the ISSUE-13 persistent
    # multi-layer decode chain x {2,4,8}
    assert "69 kernel cases" in res.stdout
    assert "0 violation(s)" in res.stdout


def test_cli_selftest():
    res = _run_lint("--selftest")
    assert res.returncode == 0, res.stdout + res.stderr
    assert "selftest OK" in res.stdout
