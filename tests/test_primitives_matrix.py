"""Exhaustive primitive-API matrix (VERDICT r4 next #3; reference
``test/nvidia/test_nvshmem_api.py:107-302`` — every device primitive
exercised against expected buffers, at multiple scopes, under reuse).

Complements ``test_lang_primitives.py`` (single-primitive goldens) with
the cross-product dimensions the reference matrix has: semaphore ARRAYS,
100-iteration reuse of one semaphore set, Team addressing exercised
INSIDE kernels on 2- and 3-axis meshes, and per-primitive cases whose
failure names the primitive (killing any one lowering breaks a named
test here or in test_lang_primitives.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import lang
from triton_distributed_tpu.core import compilation, mesh as mesh_lib
from triton_distributed_tpu.core.utils import assert_allclose
from triton_distributed_tpu.lang.primitives import Team


def _call(kernel_fn, out_shape, scratch_shapes, collective_id):
    def f(xs):
        return pl.pallas_call(
            kernel_fn,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=scratch_shapes,
            compiler_params=compilation.compiler_params(
                collective_id=collective_id
            ),
            interpret=compilation.interpret_mode(),
        )(xs)

    return f


# ---------------------------------------------------------------------------
# semaphore arrays


def test_regular_semaphore_array_per_slot_counts(mesh8):
    """A REGULAR semaphore ARRAY: each slot accumulates its own count —
    remote signals target (peer, slot) independently, and draining one
    slot leaves the others untouched (reference: signal arrays indexed
    per source rank, ``test_nvshmem_api.py`` signal ops)."""
    nslots = 4

    def kernel(x_ref, o_ref, sems):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        right = jax.lax.rem(me + 1, n)
        # signal each slot of the RIGHT neighbor with count slot+1
        def sig(i, _):
            lang.notify(sems.at[i], right, inc=i + 1)
            return 0

        jax.lax.fori_loop(0, nslots, sig, 0)

        def body(scratch, dma):
            scratch[:] = jnp.zeros_like(scratch)
            # drain in REVERSE slot order: counts are per-slot, so order
            # across slots cannot matter
            def drain(i, _):
                slot = nslots - 1 - i
                lang.wait(sems.at[slot], slot + 1)
                return 0

            jax.lax.fori_loop(0, nslots, drain, 0)
            scratch[0, 0] = 1.0
            lang.local_copy(scratch, o_ref, dma).wait()

        pl.run_scoped(body, pltpu.VMEM((1, 128), jnp.float32),
                      pltpu.SemaphoreType.DMA)

    x = jnp.zeros((8, 128), jnp.float32)
    g = compilation.jit_shard_map(
        _call(kernel, jax.ShapeDtypeStruct((1, 128), jnp.float32),
              [pltpu.SemaphoreType.REGULAR((nslots,))], 21),
        mesh8, in_specs=P("tp"), out_specs=P("tp"),
    )
    got = np.asarray(g(x))
    np.testing.assert_array_equal(got[:, 0], np.ones(8, np.float32))


def test_dma_semaphore_array_concurrent_transfers(mesh8):
    """A DMA semaphore ARRAY with two concurrent remote copies on
    different slots, drained out of order (reference: nbi puts on
    distinct completion signals)."""

    def kernel(x_ref, o_ref, send_sems, recv_sems):
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        a = lang.remote_copy(x_ref.at[pl.ds(0, 8)], o_ref.at[pl.ds(0, 8)],
                             send_sems.at[0], recv_sems.at[0], right)
        b = lang.remote_copy(x_ref.at[pl.ds(8, 8)], o_ref.at[pl.ds(8, 8)],
                             send_sems.at[1], recv_sems.at[1], right)
        del a
        b.wait()
        lang.wait_send(x_ref.at[pl.ds(0, 8)], send_sems.at[0])
        lang.wait_recv(o_ref.at[pl.ds(0, 8)], recv_sems.at[0])

    n = 8
    x = jnp.arange(n * 16 * 128, dtype=jnp.float32).reshape(n * 16, 128)
    g = compilation.jit_shard_map(
        _call(kernel, jax.ShapeDtypeStruct((16, 128), jnp.float32),
              [pltpu.SemaphoreType.DMA((2,)),
               pltpu.SemaphoreType.DMA((2,))], 22),
        mesh8, in_specs=P("tp"), out_specs=P("tp"),
    )
    out = g(x)
    expect = jnp.roll(x.reshape(n, 16, 128), 1, axis=0).reshape(n * 16, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


# ---------------------------------------------------------------------------
# 100-iteration reuse


def test_semaphore_reuse_100_rounds(mesh8):
    """One semaphore set reused for 100 notify/wait ring rounds inside a
    single kernel, then a data round whose correctness proves no residue
    (reference ``test_nvshmem_api.py`` iteration loops; the counting
    protocol must balance exactly at every round)."""
    rounds = 100

    def kernel(x_ref, o_ref, ready, send_sem, recv_sem):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        right = jax.lax.rem(me + 1, n)

        def rnd(i, _):
            # rank-dependent increment per round: any slot confusion or
            # residue shifts the expected exact count
            lang.notify(ready, right, inc=i + 1)
            lang.wait(ready, i + 1)
            return 0

        jax.lax.fori_loop(0, rounds, rnd, 0)
        _, right_id = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right_id).wait()
        lang.barrier_all("tp")

    n = 8
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    g = compilation.jit_shard_map(
        _call(kernel, jax.ShapeDtypeStruct((8, 128), jnp.float32),
              [pltpu.SemaphoreType.REGULAR, pltpu.SemaphoreType.DMA,
               pltpu.SemaphoreType.DMA], 23),
        mesh8, in_specs=P("tp"), out_specs=P("tp"),
    )
    out = g(x)
    expect = jnp.roll(x.reshape(n, 8, 128), 1, axis=0).reshape(n * 8, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


# ---------------------------------------------------------------------------
# Team addressing inside kernels, 2- and 3-axis meshes


def _team_ring_kernel(team):
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        lang.collective_prologue(team)
        me, n = team.rank(), team.size
        right = team.device_id(jax.lax.rem(me + 1, n))
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        lang.barrier_all(team)

    return kernel


@pytest.mark.parametrize("axes,team_axis", [
    ({"dp": 2, "tp": 4}, "tp"),
    ({"dp": 4, "tp": 2}, "dp"),
    ({"dp": 2, "tp": 2, "sp": 2}, "sp"),
    ({"dp": 2, "tp": 2, "sp": 2}, "tp"),
    ({"dp": 2, "tp": 2, "sp": 2}, "dp"),
])
def test_team_ring_on_multi_axis_mesh(axes, team_axis):
    """A ring push + round-safe barrier addressed through ``Team`` on a
    multi-axis mesh: every non-team coordinate must resolve to the
    calling device's own (reference team addressing; the collective
    rotates WITHIN each team and never leaks across sibling teams)."""
    mesh = mesh_lib.make_mesh(axes, devices=jax.devices()[:8])
    team = Team.of(mesh, team_axis)
    names = list(axes)
    sizes = [axes[a] for a in names]
    rows = 8
    x = jnp.arange(8 * rows * 128, dtype=jnp.float32).reshape(8 * rows, 128)

    g = compilation.jit_shard_map(
        _call(_team_ring_kernel(team),
              jax.ShapeDtypeStruct((rows, 128), jnp.float32),
              [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA], 24),
        mesh, in_specs=P(tuple(names)), out_specs=P(tuple(names)),
    )
    out = np.asarray(g(x)).reshape(*sizes, rows, 128)
    xs = np.asarray(x).reshape(*sizes, rows, 128)
    # each team rotates its members' shards by one along the team axis
    want = np.roll(xs, 1, axis=names.index(team_axis))
    np.testing.assert_array_equal(out, want)


# ---------------------------------------------------------------------------
# remaining vocabulary entries, each named


def test_symm_at_addresses_remote_copy(mesh8):
    """``symm_at`` IS the peer address on TPU: routing a remote_copy
    through it must land on that peer (the identity is the documented
    contract, so this is the case that breaks if it stops being one)."""

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        dst = lang.symm_at(jax.lax.rem(me + 2, n))   # rank+2 this time
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, dst).wait()
        lang.barrier_all("tp")

    n = 8
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    g = compilation.jit_shard_map(
        _call(kernel, jax.ShapeDtypeStruct((8, 128), jnp.float32),
              [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA], 25),
        mesh8, in_specs=P("tp"), out_specs=P("tp"),
    )
    out = g(x)
    expect = jnp.roll(x.reshape(n, 8, 128), 2, axis=0).reshape(n * 8, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


def test_consume_token_orders_and_passes_through(mesh8):
    """``consume_token`` returns its value unchanged (API-parity identity)
    and is usable at its reference call-site shape: gate a ref read on a
    wait's completion."""

    def kernel(x_ref, o_ref, ready, send_sem, recv_sem):
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        lang.notify(ready, jax.lax.rem(me + 1, n), inc=1)
        token = lang.wait(ready, 1)

        def body(scratch, dma):
            ref = lang.consume_token(o_ref, token)
            lang.local_copy(ref, scratch, dma).wait()
            scratch[:] = scratch[:] + 3.0
            lang.local_copy(scratch, ref, dma).wait()

        pl.run_scoped(body, pltpu.VMEM((8, 128), jnp.float32),
                      pltpu.SemaphoreType.DMA)

    n = 8
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    g = compilation.jit_shard_map(
        _call(kernel, jax.ShapeDtypeStruct((8, 128), jnp.float32),
              [pltpu.SemaphoreType.REGULAR, pltpu.SemaphoreType.DMA,
               pltpu.SemaphoreType.DMA], 26),
        mesh8, in_specs=P("tp"), out_specs=P("tp"),
    )
    out = g(x)
    expect = jnp.roll(x.reshape(n, 8, 128), 1, axis=0).reshape(n * 8, 128) + 3.0
    assert_allclose(out, expect, atol=0, rtol=0)
    # host-side identity contract
    assert lang.consume_token(5, None) == 5


def test_barrier_neighbors_ring(mesh8):
    """``barrier_neighbors`` (and collective_prologue(neighbors_only=True))
    synchronizes ring neighbors: the ring push that follows may only rely
    on neighbor arrival, which is exactly what it needs."""

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        lang.collective_prologue("tp", neighbors_only=True)
        _, right = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        lang.barrier_neighbors("tp")

    n = 8
    x = jnp.arange(n * 8 * 128, dtype=jnp.float32).reshape(n * 8, 128)
    g = compilation.jit_shard_map(
        _call(kernel, jax.ShapeDtypeStruct((8, 128), jnp.float32),
              [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA], 27),
        mesh8, in_specs=P("tp"), out_specs=P("tp"),
    )
    out = g(x)
    expect = jnp.roll(x.reshape(n, 8, 128), 1, axis=0).reshape(n * 8, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


def test_ring_src_rank_property():
    """``ring_src_rank`` pure math: after ``step`` forwarding hops in a +1
    ring, the arriving chunk originated ``step+1`` ranks to the left."""
    n = 8

    def body(_):
        me = lang.rank("tp")
        vals = jnp.stack([
            jnp.asarray(lang.ring_src_rank("tp", s), jnp.int32)
            for s in range(n)
        ])
        return vals.reshape(1, n)

    mesh = mesh_lib.tp_mesh(n)
    g = compilation.jit_shard_map(
        body, mesh, in_specs=P("tp"), out_specs=P("tp", None),
    )
    got = np.asarray(g(jnp.zeros((n,), jnp.float32)))
    for me in range(n):
        for s in range(n):
            assert got[me, s] == (me - s - 1) % n


def test_peek_reads_count_on_hardware():
    """``peek`` (semaphore_read) on REAL hardware: signal 3, peek reads 3,
    then drain — the one primitive interpret mode cannot run (VERDICT r4
    weak #7: previously zero executable coverage).  Skipped on CPU; run
    via ``python -m pytest tests/test_primitives_matrix.py -k peek`` on
    a TPU host (tests/conftest.py forces CPU for the suite, so this is
    exercised by scripts/run_hw_markers.py on the bench chip)."""
    if compilation.interpret_mode():
        pytest.skip("peek requires Mosaic lowering (real TPU)")

    def kernel(o_ref, counter, dma):
        lang.notify(counter, inc=3)
        def body(scratch):
            # broadcast: Mosaic rejects scalar stores to VMEM
            scratch[:] = jnp.broadcast_to(
                lang.peek(counter).astype(jnp.float32), (1, 128)
            )
            lang.local_copy(scratch, o_ref, dma).wait()
        pl.run_scoped(body, pltpu.VMEM((1, 128), jnp.float32))
        lang.wait(counter, 3)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, 128), jnp.float32),
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.SemaphoreType.REGULAR,
                        pltpu.SemaphoreType.DMA],
        compiler_params=compilation.compiler_params(collective=False),
        interpret=False,
    )()
    assert float(np.asarray(out)[0, 0]) == 3.0
