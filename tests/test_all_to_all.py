"""EP All-to-All dispatch/combine vs jnp permutation goldens (reference
``test_low_latency_a2a.py`` strategy: uneven splits, zero splits, round-trip
identity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.all_to_all import (
    AllToAllConfig,
    ep_combine,
    ep_dispatch,
)
from triton_distributed_tpu.core.mesh import EP_AXIS, make_mesh

CFG = AllToAllConfig(chunk=8)


def _mesh(n):
    return make_mesh({EP_AXIS: n}, devices=jax.devices()[:n])


def _make_case(n, t, h, e_tot, seed=0, uniform=False):
    """Per-rank sorted tokens + splits; returns (x, splits, expert_of_row).

    Rows are tagged so the test can track where each row lands: row value =
    (rank * 1000 + original_row_index) broadcast over H.
    """
    rng = np.random.default_rng(seed)
    xs, sps, experts = [], [], []
    for r in range(n):
        if uniform:
            split = np.full(e_tot, t // e_tot, np.int32)
        else:
            # uneven with zeros: distribute t rows over experts randomly
            w = rng.random(e_tot) * (rng.random(e_tot) > 0.3)
            if w.sum() == 0:
                w[0] = 1.0
            split = np.floor(w / w.sum() * t).astype(np.int32)
            split[0] += t - split.sum()
        assert split.sum() == t
        eid = np.repeat(np.arange(e_tot), split)
        tag = (r * 1000 + np.arange(t)).astype(np.float32)
        xs.append(np.broadcast_to(tag[:, None], (t, h)).copy())
        sps.append(split)
        experts.append(eid)
    return (
        jnp.asarray(np.concatenate(xs)),
        jnp.asarray(np.concatenate(sps)),
        experts,
    )


def _shard(mesh, x, splits):
    xs = jax.device_put(x, NamedSharding(mesh, P(EP_AXIS, None)))
    ss = jax.device_put(splits, NamedSharding(mesh, P(EP_AXIS)))
    return xs, ss


@pytest.mark.parametrize("n", [2, 4])
@pytest.mark.parametrize("uniform", [True, False])
def test_dispatch_places_rows_by_owner(n, uniform):
    t, h, e_tot = 32, 128, 2 * n
    epr = e_tot // n
    x, splits, experts = _make_case(n, t, h, e_tot, seed=n, uniform=uniform)
    mesh = _mesh(n)
    xs, ss = _shard(mesh, x, splits)
    recv, recv_splits = ep_dispatch(xs, ss, mesh, config=CFG)
    recv = np.asarray(jax.device_get(recv))
    recv_splits = np.asarray(jax.device_get(recv_splits))
    sp = np.asarray(splits).reshape(n, e_tot)
    for dst in range(n):
        for src in range(n):
            # rows rank src sent to rank dst: src's rows with experts owned
            # by dst, in sorted order
            cnt = sp[src, dst * epr:(dst + 1) * epr].sum()
            start = sp[src, :dst * epr].sum()
            want_tags = src * 1000 + np.arange(start, start + cnt)
            zone = recv[dst * n + src]
            got_tags = zone[:cnt, 0]
            np.testing.assert_array_equal(got_tags, want_tags.astype(np.float32))
            np.testing.assert_array_equal(
                recv_splits[dst * n + src],
                sp[src, dst * epr:(dst + 1) * epr],
            )


@pytest.mark.parametrize("n", [2, 4, 8])
def test_dispatch_combine_round_trip(n):
    """combine(dispatch(x)) == x — every row returns to its origin."""
    t, h, e_tot = 32, 128, 2 * n
    x, splits, _ = _make_case(n, t, h, e_tot, seed=10 + n)
    mesh = _mesh(n)
    xs, ss = _shard(mesh, x, splits)
    recv, _ = ep_dispatch(xs, ss, mesh, config=CFG)
    back = ep_combine(recv, ss, mesh, token_dim=t, config=CFG)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(back)), np.asarray(x)
    )


def test_combine_after_expert_compute():
    """An elementwise 'expert' applied in zone layout survives the return
    trip at the right rows (the MoE forward data flow)."""
    n, t, h, e_tot = 4, 32, 128, 8
    x, splits, _ = _make_case(n, t, h, e_tot, seed=3)
    mesh = _mesh(n)
    xs, ss = _shard(mesh, x, splits)
    recv, _ = ep_dispatch(xs, ss, mesh, config=CFG)
    processed = recv * 2.0 + 1.0
    back = ep_combine(processed, ss, mesh, token_dim=t, config=CFG)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(back)), np.asarray(x) * 2.0 + 1.0
    )


def test_dispatch_repeat_invocations():
    """Semaphore accounting leaves no residue across calls."""
    n, t, h, e_tot = 4, 16, 128, 8
    x, splits, _ = _make_case(n, t, h, e_tot, seed=4)
    mesh = _mesh(n)
    xs, ss = _shard(mesh, x, splits)
    r1, _ = ep_dispatch(xs, ss, mesh, config=CFG)
    r2, _ = ep_dispatch(xs, ss, mesh, config=CFG)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(r1)), np.asarray(jax.device_get(r2))
    )


def test_combine_direct_grad_zeroes_padding_rows():
    """Differentiating ep_combine directly must put ZERO cotangent on zone
    padding rows: dispatch's chunk-rounded DMAs drag neighboring rows into
    zone tails, so an unmasked adjoint would hand garbage gradients to any
    caller whose padding rows feed real computation downstream."""
    n, t, h, e_tot = 4, 32, 128, 8
    x, splits, _ = _make_case(n, t, h, e_tot, seed=11)
    mesh = _mesh(n)
    xs, ss = _shard(mesh, x, splits)
    recv, _ = ep_dispatch(xs, ss, mesh, config=CFG)

    def f(y):
        return (ep_combine(y, ss, mesh, token_dim=t, config=CFG) ** 2).sum()

    dy = np.asarray(jax.device_get(jax.grad(f)(recv)))
    # real rows carry 2*y; padding rows carry exactly zero
    sp = np.asarray(splits).reshape(n, n, e_tot // n)
    y_np = np.asarray(jax.device_get(recv))
    for dst in range(n):
        for src in range(n):
            cnt = sp[src, dst].sum()
            zone = dst * n + src
            np.testing.assert_allclose(
                dy[zone, :cnt], 2.0 * y_np[zone, :cnt], rtol=1e-5
            )
            np.testing.assert_array_equal(
                dy[zone, cnt:], np.zeros_like(dy[zone, cnt:])
            )


def test_dispatch_direct_grad_zeroes_padding_token_rows():
    """The mirror of the combine-grad property: with T = static worst case
    above the real token count, differentiating ep_dispatch must put ZERO
    cotangent on the padding token rows — combine's repack would otherwise
    clip them onto the last peer's zone tail and gather chunk spillover."""
    n, t, h, e_tot = 4, 32, 128, 8
    real = 19                      # real rows per rank; rows [19, 32) pad
    rng = np.random.default_rng(12)
    splits_np = []
    for _ in range(n):
        w = rng.random(e_tot)
        s = np.floor(w / w.sum() * real).astype(np.int32)
        s[0] += real - s.sum()
        splits_np.append(s)
    splits = jnp.asarray(np.concatenate(splits_np))
    x = jnp.asarray(rng.standard_normal((n * t, h)), jnp.float32)
    mesh = _mesh(n)
    xs, ss = _shard(mesh, x, splits)

    sp = np.asarray(splits).reshape(n, n, e_tot // n)
    zone_valid = sp.sum(-1).T.reshape(n * n)   # [dst*n + src] real rows

    def f(x_):
        recv, _ = ep_dispatch(x_, ss, mesh, config=CFG)
        rows = jnp.arange(recv.shape[1])
        mask = rows[None, :] < jnp.asarray(zone_valid)[:, None]
        return ((recv * mask[:, :, None]) ** 2).sum()

    dx = np.asarray(jax.device_get(jax.grad(f)(xs))).reshape(n, t, h)
    x_np = np.asarray(x).reshape(n, t, h)
    for r in range(n):
        np.testing.assert_allclose(dx[r, :real], 2.0 * x_np[r, :real],
                                   rtol=1e-5)
        np.testing.assert_array_equal(
            dx[r, real:], np.zeros_like(dx[r, real:])
        )


def test_single_rank_fallback():
    n, t, h, e_tot = 1, 16, 64, 4
    x, splits, _ = _make_case(n, t, h, e_tot, seed=5)
    mesh = _mesh(1)
    recv, recv_splits = ep_dispatch(x, splits, mesh, config=CFG)
    assert recv.shape == (1, t, h)
    back = ep_combine(recv, splits, mesh, token_dim=t, config=CFG)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_dispatch_combine_fp8_with_scales():
    """fp8 tokens + f32 per-token scales through dispatch/combine — the
    reference's headline low-latency A2A configuration (fp8 payload with
    scale sidecar, ``low_latency_all_to_all.py:36-120``).  The scale rides
    as an extra feature column, the TPU translation of the reference
    packing scales into the same message."""
    from triton_distributed_tpu.ops.moe_utils import dequantize, quantize_e4m3

    n, t, h, e_tot = 4, 16, 64, 8
    x, splits, _ = _make_case(n, t, h, e_tot, seed=9)
    mesh = _mesh(n)
    # quantize: per-row scale, payload in e4m3 (the packaged helper)
    x8, scale_j = quantize_e4m3(x)
    scale = np.asarray(scale_j)
    xs, ss = _shard(mesh, x8, splits)
    recv, _ = ep_dispatch(xs, ss, mesh, config=CFG)
    assert recv.dtype == jnp.float8_e4m3fn
    back = ep_combine(recv, ss, mesh, token_dim=t, config=CFG)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(back), np.float32),
        np.asarray(x8, np.float32),
    )
    # scales travel the same path (f32 payload, 1 feature column padded to
    # the 128-lane granule the kernels tile by)
    sc = jnp.asarray(np.broadcast_to(scale, (n * t, 128)).copy(), jnp.float32)
    scs = jax.device_put(sc, NamedSharding(mesh, P(EP_AXIS, None)))
    recv_sc, _ = ep_dispatch(scs, ss, mesh, config=CFG)
    back_sc = ep_combine(recv_sc, ss, mesh, token_dim=t, config=CFG)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(back_sc)), np.asarray(sc)
    )
    # dequantized round trip reproduces the original tokens to fp8 precision
    deq = np.asarray(dequantize(jnp.asarray(jax.device_get(back)),
                                jnp.asarray(scale), jnp.float32))
    np.testing.assert_allclose(deq, np.asarray(x), rtol=0.07, atol=0.5)
