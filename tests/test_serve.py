"""Continuous-batching scheduler on the paged KV cache (ISSUE 6).

Everything here is headless and model-free: the scheduler runs over the
deterministic ``serve.SimBackend``, which drives the REAL paged-cache
plumbing (``write_chunk_paged`` / ``append_paged`` / block tables /
the page free-list) with a seeded token automaton — so page
bookkeeping, preemption, isolation and telemetry are exercised for
real while the model's shard_map/Pallas paths (covered by the engine
tests where the platform supports them) stay out of the loop.  The
chunked-prefill model path (``Qwen3.prefill_chunk``) is plain jnp and
IS tested here, via chunk-invariance.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import obs, resilience, serve
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.models import (
    Engine,
    ModelConfig,
    PagePoolExhausted,
    Qwen3,
    init_serving_cache,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def obs_on():
    prev = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    obs.serve_stats.STATS.reset()
    yield obs
    obs.enable(prev)
    obs.REGISTRY.reset()
    obs.serve_stats.STATS.reset()


def _expected_tokens(backend: serve.SimBackend, req: serve.Request):
    """The golden for completed requests AND for the
    recompute-after-preemption contract (one home:
    ``SimBackend.expected_tokens``)."""
    return backend.expected_tokens(req)


# ---------------------------------------------------------------------------
# units: page pool + queue


def test_page_pool_alloc_free_deterministic():
    pool = serve.PagePool(8, page_size=4)     # pages 1..7 allocatable
    assert pool.capacity == 7
    a = pool.alloc(3)
    assert a == [1, 2, 3]                     # lowest-id-first
    b = pool.alloc(2)
    assert b == [4, 5]
    assert pool.free_pages == 2 and pool.occupancy() == 5 / 7
    pool.free(a)
    assert pool.alloc(3) == [1, 2, 3]         # returned pages re-sort
    with pytest.raises(PagePoolExhausted) as ei:
        pool.alloc(5)
    assert ei.value.needed == 5 and ei.value.available == 2
    assert pool.try_alloc(5) is None


def test_page_pool_double_free_and_foreign_free_raise():
    pool = serve.PagePool(6, page_size=4)
    a = pool.alloc(2)
    pool.free(a)
    with pytest.raises(ValueError, match="double free"):
        pool.free([a[0]])
    with pytest.raises(ValueError, match="outside the allocatable"):
        pool.free([serve.SCRAP_PAGE])
    assert serve.pages_needed(0, 4) == 0
    assert serve.pages_needed(1, 4) == 1
    assert serve.pages_needed(9, 4) == 3


def test_queue_bounds_shed_and_priority_order():
    q = serve.RequestQueue(max_depth=3)
    r_lo = serve.Request(prompt=(1,), max_new_tokens=1, priority=0)
    r_hi = serve.Request(prompt=(2,), max_new_tokens=1, priority=2)
    r_mid = serve.Request(prompt=(3,), max_new_tokens=1, priority=1)
    assert all(q.submit(r) for r in (r_lo, r_hi, r_mid))
    over = serve.Request(prompt=(4,), max_new_tokens=1)
    assert not q.submit(over)                 # bounded: shed, not buffered
    assert over.state is serve.RequestState.SHED
    assert "queue full" in over.shed_reason
    assert q.sheds == 1
    # preempted re-admission beats same-priority fresh arrivals
    r_pre = serve.Request(prompt=(5,), max_new_tokens=1, priority=1)
    r_pre.submitted_s = time.monotonic()
    q.requeue_preempted(r_pre)
    assert [q.pop().req_id for _ in range(4)] == \
        [r_hi.req_id, r_pre.req_id, r_mid.req_id, r_lo.req_id]


def test_queue_deadline_expiry_sheds():
    q = serve.RequestQueue(max_depth=4)
    fast = serve.Request(prompt=(1,), max_new_tokens=1, deadline_ms=1.0)
    slow = serve.Request(prompt=(2,), max_new_tokens=1)
    q.submit(fast)
    q.submit(slow)
    expired = q.expire_deadlines(now=time.monotonic() + 1.0)
    assert [r.req_id for r in expired] == [fast.req_id]
    assert fast.state is serve.RequestState.SHED
    assert "deadline" in fast.shed_reason
    assert q.depth == 1


# ---------------------------------------------------------------------------
# scheduler: drain, determinism, overcommit, preemption


def test_scheduler_drains_seeded_load_exactly():
    backend = serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                               max_length=48)
    sched = serve.Scheduler(backend)
    arrivals = serve.synthetic_trace(3, 14, mean_interarrival_steps=0.5,
                                     prompt_len=(2, 9), max_new=(2, 8))
    report = serve.replay(sched, arrivals, max_steps=2000)
    assert report.problems() == []
    assert len(report.completed) == 14
    assert report.leaked_pages == 0
    assert sched.pool.occupancy() == 0.0      # pool returns to empty
    for req in report.completed:
        assert req.tokens == _expected_tokens(backend, req)


def test_overcommit_2x_budget_completes_all_zero_leaks(obs_on):
    """The ISSUE 6 acceptance core: total page demand ~2x (actually
    >5x at peak concurrency 2x) the pool; every request completes via
    preemption, pool occupancy returns to 0, preemptions observable in
    serve_stats."""
    backend = serve.SimBackend(slots=3, page_size=4, pool_pages=10,
                               max_length=48)
    sched = serve.Scheduler(backend)
    arrivals = serve.synthetic_trace(7, 10, mean_interarrival_steps=0.0,
                                     prompt_len=(6, 12), max_new=(8, 16))
    demand = sum(serve.pages_needed(
        a.request.prompt_len + a.request.max_new_tokens, 4)
        for a in arrivals)
    assert demand >= 2 * sched.pool.capacity
    report = serve.replay(sched, arrivals, max_steps=5000)
    assert report.problems() == []
    assert len(report.completed) == 10 and not report.failed
    assert sched.preemptions > 0
    assert report.leaked_pages == 0 and sched.pool.occupancy() == 0.0
    snap = obs.serve_stats.STATS.snapshot()
    assert snap["preemptions_total"] == sched.preemptions
    assert snap["evicted_pages_total"] > 0
    # TTFT is once-per-REQUEST: preemption re-prefills must not add
    # samples (they would skew the p99 exactly in the thrash regime)
    assert snap["ttft_ms"]["count"] == 10
    assert snap["gauges"]["kv_pool_occupancy"] == 0.0
    # preempted requests recomputed deterministically from their prompts
    for req in report.completed:
        assert req.tokens == _expected_tokens(backend, req)
    assert max(r.preemptions for r in report.completed) > 0


def test_preemption_recompute_matches_unpressured_run():
    """Same trace, ample pool vs tight pool: identical final tokens —
    eviction + recompute is invisible in outputs."""
    def run(pool_pages):
        backend = serve.SimBackend(slots=3, page_size=4,
                                   pool_pages=pool_pages, max_length=48)
        sched = serve.Scheduler(backend)
        arrivals = serve.synthetic_trace(
            11, 8, mean_interarrival_steps=0.0, prompt_len=(4, 10),
            max_new=(6, 12))
        report = serve.replay(sched, arrivals, max_steps=5000)
        assert report.problems() == []
        return sched, {tuple(r.prompt): tuple(r.tokens)
                       for r in report.completed}

    ample_sched, ample = run(64)
    tight_sched, tight = run(9)
    assert ample_sched.preemptions == 0
    assert tight_sched.preemptions > 0
    assert ample == tight


def test_impossible_demand_sheds_typed():
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=6,
                               max_length=48)
    sched = serve.Scheduler(backend)
    too_big = serve.Request(prompt=tuple(range(10)), max_new_tokens=30)
    assert not sched.submit(too_big)          # 10 pages > capacity 5
    assert too_big.state is serve.RequestState.SHED
    assert "exceeds the pool capacity" in too_big.shed_reason
    too_long = serve.Request(prompt=tuple(range(40)), max_new_tokens=20)
    assert not sched.submit(too_long)
    assert "exceeds max_length" in too_long.shed_reason
    assert len(sched.shed) == 2


# ---------------------------------------------------------------------------
# robustness: isolation, deadlines, degradation


def test_rank_abort_mid_decode_isolates_victim_and_cache(obs_on):
    """A rank abort in a 3-request decode step fails exactly one
    sequence; the cohabitants complete with correct tokens AND their
    pool pages still hold exactly their token history — per-sequence
    isolation down to the bytes."""
    from triton_distributed_tpu.resilience.faults import RankAborted

    fired = []

    def hook(step):
        if step == 4 and not fired:
            fired.append(step)
            raise RankAborted(0, step)

    backend = serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                               max_length=48, step_hook=hook)
    sched = serve.Scheduler(backend)
    reqs = [serve.Request(prompt=(5 + i, 6 + i, 7 + i),
                          max_new_tokens=8, priority=i)
            for i in range(3)]
    for r in reqs:
        sched.submit(r)
    # remember the victim-designate's slot pages before the fault: the
    # lowest-priority request (priority 0) is the eviction policy's pick
    sched.run_until_idle(max_steps=200)
    assert fired
    victim, s1, s2 = reqs
    assert victim.state is serve.RequestState.FAILED
    assert "RankAborted" in victim.error
    for r in (s1, s2):
        assert r.state is serve.RequestState.DONE
        assert r.tokens == _expected_tokens(backend, r)
    assert sched.pool.occupancy() == 0.0


def test_survivor_cache_bytes_intact_after_abort():
    """Freeze the scheduler right after an aborted step (before the
    survivors finish) and materialize a survivor's pages: they must
    hold exactly prompt + generated-so-far token values."""
    from triton_distributed_tpu.resilience.faults import RankAborted

    def hook(step):
        if step == 3:
            raise RankAborted(1, step)

    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=32, step_hook=hook)
    sched = serve.Scheduler(backend)
    low = serve.Request(prompt=(9, 8, 7), max_new_tokens=10, priority=0)
    hi = serve.Request(prompt=(3, 4, 5, 6), max_new_tokens=10, priority=1)
    sched.submit(low)
    sched.submit(hi)
    while not sched.failed:
        sched.step()
    assert sched.failed[0] is low
    slot = next(s for s in sched.slots if s is not None)
    assert slot.request is hi
    pool = np.asarray(sched.cache.k[0])       # (P, Hk, ps, D)
    flat = np.concatenate([pool[p] for p in slot.pages], axis=1)[0, :, 0]
    want = list(hi.prompt) + hi.tokens[:-1]   # last token not yet written
    np.testing.assert_array_equal(flat[:len(want)],
                                  np.asarray(want, np.float32))
    sched.run_until_idle(max_steps=200)
    assert hi.state is serve.RequestState.DONE


def test_deadline_overrun_fails_only_the_deadline_carrier():
    """A straggling step past one request's deadline rides the PR-3
    watchdog: CollectiveTimeoutError, victim failed, cohabitants
    complete."""
    delay_s = 0.3

    def hook(step):
        if step == 2:
            time.sleep(delay_s)

    backend = serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                               max_length=64, step_hook=hook)
    sched = serve.Scheduler(backend, serve.SchedulerConfig(
        step_deadline_floor_ms=25.0))
    victim = serve.Request(prompt=(1, 2), max_new_tokens=20,
                           deadline_ms=120.0)
    others = [serve.Request(prompt=(3 + i, 4 + i), max_new_tokens=6)
              for i in range(2)]
    for r in (victim, *others):
        sched.submit(r)
    sched.run_until_idle(max_steps=400)
    # let the abandoned straggler finish its discarded step while the
    # runtime is alive (XLA teardown aborts on a zombie mid-op)
    time.sleep(delay_s + 0.1)
    assert victim.state is serve.RequestState.FAILED
    assert ("CollectiveTimeoutError" in victim.error
            or "deadline" in victim.error)
    for r in others:
        assert r.state is serve.RequestState.DONE
        assert r.tokens == _expected_tokens(backend, r)
    assert sched.pool.occupancy() == 0.0


def test_scheduler_fault_matrix_cells():
    """The ISSUE 6 fault-matrix satellite: every scheduler cell
    detected-or-survived with per-request isolation."""
    rows = resilience.run_scheduler_matrix(seed=0)
    assert {r["leg"] for r in rows} == {"abort", "slack", "overrun",
                                        "poison"}
    problems = resilience.verify_scheduler_matrix(rows)
    assert problems == [], problems
    outcomes = {r["leg"]: r["outcome"] for r in rows}
    assert outcomes["abort"] == "detected"
    assert outcomes["slack"] == "survived"
    assert outcomes["overrun"] == "detected"
    assert outcomes["poison"] == "detected"


def test_admission_governor_shrinks_and_recovers():
    gov = resilience.AdmissionGovernor(window_steps=4, thrash_threshold=2,
                                       recover_steps=2,
                                       breaker_op="test_gov_op")
    assert gov.slot_cap(8) == 8 and gov.headroom_pages() == 0
    gov.note_preemption()
    gov.note_step_ok()
    gov.note_preemption()
    gov.note_step_ok()                        # 2 preempts in window: level 1
    assert gov.level == 1
    assert gov.slot_cap(8) == 4 and gov.headroom_pages() == 1
    for _ in range(4):                        # clean steps decay it
        gov.note_step_ok()
    assert gov.level == 0 and gov.slot_cap(8) == 8
    # an open serve-step breaker forces max degradation regardless
    br = resilience.breaker("test_gov_op", threshold=1)
    br.record_failure()
    assert gov.degraded() and gov.slot_cap(8) == 1
    resilience.reset_breaker("test_gov_op")
    assert not gov.degraded()


def test_governor_thrash_shrinks_admission_live():
    """Under engineered thrash the scheduler's concurrent-slot cap
    drops below the slot count — degradation shrinks admission instead
    of failing requests (the resilience satellite)."""
    backend = serve.SimBackend(slots=4, page_size=4, pool_pages=9,
                               max_length=64)
    gov = resilience.AdmissionGovernor(window_steps=4, thrash_threshold=2,
                                       recover_steps=50,
                                       breaker_op="test_gov_live")
    sched = serve.Scheduler(backend, governor=gov)
    arrivals = serve.synthetic_trace(5, 12, mean_interarrival_steps=0.0,
                                     prompt_len=(6, 10), max_new=(10, 16))
    report = serve.replay(sched, arrivals, max_steps=8000)
    assert report.problems() == []
    assert sched.preemptions > 0
    assert gov.level > 0                      # thrash raised the level
    assert gov.slot_cap(4) < 4
    assert len(report.completed) == 12        # ...without failing anyone


# ---------------------------------------------------------------------------
# telemetry: healthz 503 <-> 200, /debug/serve


def _get(url: str):
    import urllib.error

    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_healthz_flips_503_under_saturation_then_200(obs_on):
    """The acceptance shape: sustained pool saturation answers 503 on
    /healthz (load-balancer backoff), flipping back to 200 as the
    backlog drains; /debug/serve exposes the scheduler state."""
    from triton_distributed_tpu.obs import server as obs_server

    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=7,
                               max_length=48)
    sched = serve.Scheduler(backend)
    srv = obs_server.start(port=0, engine=sched)
    try:
        arrivals = serve.synthetic_trace(
            13, 8, mean_interarrival_steps=0.0, prompt_len=(6, 10),
            max_new=(6, 10))
        for a in arrivals:
            sched.submit(a.request)
        saw_503 = False
        for _ in range(2000):
            res = sched.step()
            if sched.saturated_s() > 0 and not saw_503:
                code, body = _get(srv.url + "/healthz")
                assert code == 503
                assert json.loads(body)["status"] == "saturated"
                saw_503 = True
            if res.idle:
                break
        assert saw_503, "scheduler never reported saturation"
        code, body = _get(srv.url + "/healthz")
        assert code == 200
        snap = json.loads(body)
        assert snap["status"] == "ok"
        assert snap["scheduler"]["completed"] == 8
        assert snap["scheduler"]["pool"]["used_pages"] == 0
        code, body = _get(srv.url + "/debug/serve")
        assert code == 200
        dbg = json.loads(body)
        assert dbg["scheduler"]["queue"]["depth"] == 0
        assert dbg["serve_stats"]["preemptions_total"] \
            == sched.preemptions
        code, body = _get(srv.url + "/metrics")
        assert code == 200
        assert "serve_ttft_ms" in body
        assert "serve_preemptions_total" in body
    finally:
        obs_server.stop()


# ---------------------------------------------------------------------------
# chunked prefill (the plain-jnp model path) + engine validation


def _tiny_model():
    cfg = ModelConfig(
        num_layers=2, hidden=32, intermediate=64, num_heads=4,
        num_kv_heads=2, head_dim=8, vocab=64, max_length=32,
        dtype=jnp.float32,
    )
    mesh = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    model = Qwen3(cfg, mesh)
    params = model.init(jax.random.key(0), scale=0.05)
    return cfg, mesh, model, params


def _slot_cache(cfg, mesh):
    c = init_serving_cache(mesh, cfg.num_layers, 1, cfg.num_kv_heads,
                           cfg.max_length, cfg.head_dim, cfg.dtype,
                           page_size=4, pool_pages=12)
    return dataclasses.replace(
        c, block_table=c.block_table.at[0].set(
            jnp.arange(1, 9, dtype=jnp.int32)))


def test_prefill_chunk_is_chunking_invariant():
    """Chunk boundaries must not change logits or the written K/V —
    the correctness contract chunked admission rests on.  (The fused
    whole-prompt prefill parity is covered by the engine tests on
    platforms with shard_map.)"""
    cfg, mesh, model, params = _tiny_model()
    ids = jax.random.randint(jax.random.key(1), (1, 11), 0, cfg.vocab)

    whole = _slot_cache(cfg, mesh)
    logits_w, whole = model.prefill_chunk(params, whole, ids, 0)

    chunked = _slot_cache(cfg, mesh)
    _, chunked = model.prefill_chunk(params, chunked, ids[:, :5], 0)
    # final partial chunk right-padded and masked via true_len — the
    # one-executable contract the EngineBackend uses
    pad = jnp.concatenate([ids[:, 5:], jnp.zeros((1, 2), ids.dtype)],
                          axis=1)
    logits_c, chunked = model.prefill_chunk(params, chunked, pad, 5, 6)

    np.testing.assert_allclose(np.asarray(logits_w[0, 10]),
                               np.asarray(logits_c[0, 5]),
                               rtol=2e-5, atol=2e-5)
    assert int(chunked.seq_lens[0]) == 11

    def mat(c):
        g = np.asarray(c.k)[:, np.asarray(c.block_table)[0]]
        return g.transpose(0, 2, 1, 3, 4).reshape(
            cfg.num_layers, cfg.num_kv_heads, 32, cfg.head_dim)

    np.testing.assert_allclose(mat(whole)[:, :, :11],
                               mat(chunked)[:, :, :11],
                               rtol=1e-6, atol=1e-6)


def test_prefill_chunk_pads_spill_to_scrap_not_neighbors():
    """Pad positions past the slot's mapped pages land in the scrap
    page — never in another sequence's pages."""
    cfg, mesh, model, params = _tiny_model()
    c = init_serving_cache(mesh, cfg.num_layers, 2, cfg.num_kv_heads,
                           cfg.max_length, cfg.head_dim, cfg.dtype,
                           page_size=4, pool_pages=12)
    # slot 0 maps ONE page (4 positions); slot 1 owns pages 2..9
    table = c.block_table.at[0, 0].set(1)
    table = table.at[1].set(jnp.arange(2, 10, dtype=jnp.int32))
    c = dataclasses.replace(c, block_table=table)
    neighbor = np.asarray(c.k[:, 2:10]).copy()
    view = dataclasses.replace(c, block_table=c.block_table[0:1],
                               seq_lens=c.seq_lens[0:1])
    ids = jnp.zeros((1, 8), jnp.int32)        # 4 real slots + 4 spill
    _, view = model.prefill_chunk(params, view, ids, 0, 4)
    merged = dataclasses.replace(c, k=view.k, v=view.v)
    np.testing.assert_array_equal(np.asarray(merged.k[:, 2:10]), neighbor)


def test_engine_prefill_validates_batch_up_front():
    """ISSUE 6 satellite: the batch mismatch fails BEFORE tracing with
    both values named, instead of an opaque downstream shape error."""
    cfg, mesh, _, _ = _tiny_model()
    eng = Engine.build(cfg, mesh, key=jax.random.key(0), batch=2)
    with pytest.raises(ValueError, match="batch 3 does not match engine "
                                         "batch 2"):
        eng.prefill(jnp.zeros((3, 4), jnp.int32))
    with pytest.raises(ValueError, match="batch 1 does not match"):
        eng.serve(jnp.zeros((1, 4), jnp.int32), gen_len=2)


# ---------------------------------------------------------------------------
# CI wiring


def test_tdt_lint_serve_smoke():
    """The tier-1 CI hook (like the --timeline / --faults smokes): the
    seeded 64-request overload trace with fault injection, zero leaked
    pages, monotone drain, scheduler fault cells all
    detected-or-survived."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--serve"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "serve OK" in proc.stdout
    assert "DETECTED" in proc.stdout and "SURVIVED" in proc.stdout


# ---------------------------------------------------------------------------
# decode megakernel through the scheduler (ISSUE 8): the stateless-step
# interface means decode_mode="fused" swaps the whole decode hot path
# under the scheduler unchanged — proven by token-exact parity against
# the per-kernel chain UNDER POOL PRESSURE (preemption-recompute parity)


def _sched_tokens(decode_mode: str) -> dict:
    """Replay one seeded trace through the REAL scheduler over a real
    engine in ``decode_mode``, with a pool small enough to force
    preemption; returns {request id: tokens}."""
    cfg = ModelConfig(
        num_layers=2, hidden=64, intermediate=128, num_heads=4,
        num_kv_heads=2, head_dim=32, vocab=64, max_length=32,
        dtype=jnp.float32,
    )
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    eng = Engine.build(cfg, mesh, key=jax.random.key(7), batch=2,
                       cache_layout="paged", page_size=4,
                       decode_mode=decode_mode)
    sched = eng.scheduler(pool_pages=13, chunk_tokens=8)
    arrivals = serve.synthetic_trace(5, 6, mean_interarrival_steps=0.5,
                                     prompt_len=(2, 7), max_new=(2, 5))
    report = serve.replay(sched, arrivals, max_steps=4000)
    assert report.problems() == []
    assert len(report.completed) == 6
    assert sched.pool.occupancy() == 0.0
    return {id(r): tuple(r.tokens) for r in report.completed}, report


@pytest.mark.skipif(
    not __import__(
        "triton_distributed_tpu.core.compilation", fromlist=["x"]
    ).interpret_supported(),
    reason="jax build lacks shard_map/Pallas-interpret APIs",
)
def test_scheduler_fused_decode_mode_token_parity():
    _, rep_psum = _sched_tokens("psum")
    _, rep_fused = _sched_tokens("fused")
    toks_psum = sorted(tuple(r.tokens) for r in rep_psum.completed)
    toks_fused = sorted(tuple(r.tokens) for r in rep_fused.completed)
    assert toks_psum == toks_fused
    # the load genuinely pressured the pool (the parity above therefore
    # covers scheduling decisions made under pressure, preemption
    # recompute included when it fires)
    assert rep_fused.peak_pool_occupancy > 0.5
