"""Local attention kernels vs naive f32 goldens (reference per-kernel golden
strategy, SURVEY.md section 4): flash prefill (causal/full, GQA, soft-cap),
split-KV decode with state merging, RoPE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.attention import (
    decode_attention,
    decode_attention_state,
    flash_attention,
    merge_decode_states,
)
from triton_distributed_tpu.ops.rope import apply_rope_at


def _naive_attention(q, k, v, causal, sm_scale=None, soft_cap=0.0, kv_len=None):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    kf = jnp.repeat(k.astype(jnp.float32), g, axis=1)
    vf = jnp.repeat(v.astype(jnp.float32), g, axis=1)
    qf = q.astype(jnp.float32)
    scale = sm_scale if sm_scale is not None else d ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) * scale
    if soft_cap:
        s = jnp.tanh(s / soft_cap) * soft_cap
    skv = k.shape[2]
    if causal:
        mask = jnp.tril(jnp.ones((sq, skv), bool))
        s = jnp.where(mask, s, -jnp.inf)
    if kv_len is not None:
        s = jnp.where(jnp.arange(skv) < kv_len, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vf)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("h,hk", [(4, 4), (8, 2)])
def test_flash_attention_golden(causal, h, hk):
    b, s, d = 2, 256, 64
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, s, d), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    want = _naive_attention(q, k, v, causal)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5), (
        jnp.abs(out - want).max()
    )


def test_flash_attention_blocks_smaller_than_seq():
    """Multiple q and kv blocks exercise the online-softmax rescaling."""
    b, h, s, d = 1, 2, 512, 64
    kq, kk, kv = jax.random.split(jax.random.key(1), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, s, d), jnp.float32)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
    want = _naive_attention(q, k, v, True)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5)


def test_flash_attention_soft_cap_and_scale():
    b, h, s, d = 1, 2, 128, 64
    kq, kk, kv = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.float32)
    k = jax.random.normal(kk, (b, h, s, d), jnp.float32)
    v = jax.random.normal(kv, (b, h, s, d), jnp.float32)
    out = flash_attention(q, k, v, causal=False, sm_scale=0.2, soft_cap=30.0,
                          block_q=64, block_k=64)
    want = _naive_attention(q, k, v, False, sm_scale=0.2, soft_cap=30.0)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5)


def test_flash_attention_bf16():
    b, h, s, d = 1, 4, 256, 128
    kq, kk, kv = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(kq, (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(kk, (b, h, s, d), jnp.bfloat16)
    v = jax.random.normal(kv, (b, h, s, d), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    want = _naive_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    assert jnp.allclose(out.astype(jnp.float32), want, atol=5e-2, rtol=5e-2)


# ---------------------------------------------------------------------------
# split-KV decode


@pytest.mark.parametrize("n_split", [1, 4])
@pytest.mark.parametrize("h,hk", [(4, 4), (8, 2)])
def test_decode_attention_golden(n_split, h, hk):
    b, skv, d = 2, 512, 64
    kv_len = 300  # padded cache: positions >= kv_len masked
    kq, kk, kv = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, skv, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, skv, d), jnp.float32)
    out = decode_attention(q, k, v, kv_len, n_split=n_split)
    want = _naive_attention(
        q[:, :, None], k, v, causal=False, kv_len=kv_len
    )[:, :, 0]
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5), (
        jnp.abs(out - want).max()
    )


def test_default_decode_geometry_caps_vmem():
    """The jit-tracing resolve path returns the DEFAULT geometry
    unvalidated, so the default must always produce a compilable block:
    one split's KV slice is capped at _DECODE_BLOCK_BYTES (K + V
    double-buffered fit Mosaic's 16 MiB scoped default) as a BYTE budget
    — f32 or wide-head caches split earlier than bf16 d=128 — and splits
    divide the cache length exactly."""
    from triton_distributed_tpu.ops.attention import (
        _DECODE_BLOCK_BYTES, default_decode_geometry,
    )

    for s in (256, 1024, 2048, 8192, 12288, 16384, 131072, 6000):
        for d, isz in ((128, 2), (128, 4), (256, 2), (64, 4)):
            ns, bk = default_decode_geometry(s, d, isz)
            assert s % ns == 0, (s, d, isz, ns)
            sp = s // ns
            assert sp * d * isz <= max(_DECODE_BLOCK_BYTES, 256 * d * isz), (
                s, d, isz, ns
            )
            assert 1 <= bk <= sp, (s, d, isz, ns, bk)
    assert default_decode_geometry(8192) == (1, 2048)
    assert default_decode_geometry(131072) == (16, 2048)
    # f32 halves the row cap: an 8k f32 d=128 cache must split
    assert default_decode_geometry(8192, 128, 4) == (2, 2048)
    # prime-ish lengths over the cap raise with pad guidance instead of
    # degenerating to thousands of tiny grid steps
    with pytest.raises(ValueError, match="pad the cache"):
        default_decode_geometry(2 * 8209, 128, 2)


def test_decode_attention_long_cache_default():
    """config=None decode over a cache longer than one VMEM block: the
    default geometry splits instead of emitting an uncompilable
    (1, seq_kv) block (round-5 review finding)."""
    b, h, hk, skv, d = 1, 2, 1, 16384, 64
    lens = jnp.asarray([9000], jnp.int32)
    kq, kk, kv = jax.random.split(jax.random.key(21), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, skv, d), jnp.float32) * 0.1
    v = jax.random.normal(kv, (b, hk, skv, d), jnp.float32)
    out = jax.jit(lambda q, k, v: decode_attention(q, k, v, lens))(q, k, v)
    want = _naive_attention(
        q[:, :, None], k, v, causal=False, kv_len=9000
    )[:, :, 0]
    assert jnp.allclose(out, want, atol=2e-4, rtol=2e-4), (
        jnp.abs(out - want).max()
    )


def test_decode_attention_ragged_lengths():
    """(B,) per-sequence kv_len: each row masks at its OWN length — the
    contiguous cache's ragged-serving story (the paged kernel's lens
    semantics, on the flat layout)."""
    b, h, hk, skv, d = 3, 8, 4, 512, 64
    lens = jnp.asarray([300, 17, 512], jnp.int32)
    kq, kk, kv = jax.random.split(jax.random.key(14), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, skv, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, skv, d), jnp.float32)
    out = decode_attention(q, k, v, lens, n_split=4)
    for r in range(b):
        want = _naive_attention(
            q[r:r + 1, :, None], k[r:r + 1], v[r:r + 1], causal=False,
            kv_len=int(lens[r]),
        )[:, :, 0]
        assert jnp.allclose(out[r:r + 1], want, atol=2e-5, rtol=2e-5), (
            r, jnp.abs(out[r:r + 1] - want).max()
        )


def test_decode_attention_zero_length_rows():
    """A ragged row of length 0 (an empty/padding batch slot) returns
    ZEROS, not 0/0 NaN — realistic in padded serving batches."""
    b, h, hk, skv, d = 3, 4, 2, 256, 64
    lens = jnp.asarray([0, 100, 0], jnp.int32)
    kq, kk, kv = jax.random.split(jax.random.key(15), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, skv, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, skv, d), jnp.float32)
    out = decode_attention(q, k, v, lens, n_split=4)
    assert bool(jnp.isfinite(out).all())
    assert jnp.array_equal(out[0], jnp.zeros_like(out[0]))
    assert jnp.array_equal(out[2], jnp.zeros_like(out[2]))
    want = _naive_attention(
        q[1:2, :, None], k[1:2], v[1:2], causal=False, kv_len=100
    )[:, :, 0]
    assert jnp.allclose(out[1:2], want, atol=2e-5, rtol=2e-5)


def test_decode_state_merge_associative():
    """Merging per-split states equals single-split state — the invariant the
    distributed flash-decode rides (merge splits locally, then ranks)."""
    b, h, hk, skv, d = 1, 4, 2, 256, 64
    kq, kk, kv = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(kq, (b, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, hk, skv, d), jnp.float32)
    v = jax.random.normal(kv, (b, hk, skv, d), jnp.float32)
    num4, m4, l4 = decode_attention_state(q, k, v, skv, n_split=4)
    num, m, l = merge_decode_states(num4, m4, l4)
    out = (num[..., 0, :] / l[..., 0][..., None])
    want = decode_attention(q, k, v, skv, n_split=1)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5)


# ---------------------------------------------------------------------------
# RoPE


def test_rope_matches_complex_rotation():
    s, d = 64, 32
    x = jax.random.normal(jax.random.key(6), (2, 4, s, d), jnp.float32)
    pos = jnp.arange(s)
    got = apply_rope_at(x, pos, theta=10_000.0)
    # golden: complex multiply on (x1 + i x2)
    half = d // 2
    inv_freq = 1.0 / (10_000.0 ** (jnp.arange(half) / half))
    ang = pos[:, None] * inv_freq
    z = x[..., :half] + 1j * x[..., half:]
    zr = z * jnp.exp(1j * ang)
    want = jnp.concatenate([zr.real, zr.imag], axis=-1)
    assert jnp.allclose(got, want, atol=1e-5, rtol=1e-5)


def test_rope_preserves_norm_and_dtype():
    x = jax.random.normal(jax.random.key(7), (1, 2, 16, 64), jnp.bfloat16)
    got = apply_rope_at(x, jnp.arange(16))
    assert got.dtype == jnp.bfloat16
    n0 = jnp.linalg.norm(x.astype(jnp.float32), axis=-1)
    n1 = jnp.linalg.norm(got.astype(jnp.float32), axis=-1)
    assert jnp.allclose(n0, n1, atol=0.5, rtol=5e-2)


def test_rope_relative_property():
    """Scores depend only on relative distance: q_i . k_j after rope at
    (i, j) equals after rope at (i+t, j+t)."""
    d = 64
    q = jax.random.normal(jax.random.key(8), (1, 1, 1, d), jnp.float32)
    k = jax.random.normal(jax.random.key(9), (1, 1, 1, d), jnp.float32)
    def score(pq, pk):
        qr = apply_rope_at(q, jnp.array([pq]))
        kr = apply_rope_at(k, jnp.array([pk]))
        return (qr * kr).sum()
    assert jnp.allclose(score(5, 3), score(25, 23), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_varlen_segments(causal):
    """Packed variable-length batch via segment_ids (the reference's
    cu_seqlens varlen path): each packed sequence must match its own
    dense attention, and no probability mass leaks across the packing
    boundary or into the padding tail."""
    b, h, hk, s, d = 1, 4, 2, 64, 32
    lens = [24, 28]                       # packed; 12 rows of padding
    rng = np.random.default_rng(40)
    q = jnp.asarray(rng.standard_normal((b, h, s, d)).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.standard_normal((b, hk, s, d)).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.standard_normal((b, hk, s, d)).astype(np.float32) * 0.3)
    seg = np.full((b, s), 99, np.int32)   # sentinel for padding
    seg[0, :lens[0]] = 0
    seg[0, lens[0]:lens[0] + lens[1]] = 1
    out = flash_attention(q, k, v, causal=causal,
                          segment_ids=jnp.asarray(seg),
                          block_q=16, block_k=16)
    # golden: dense attention per segment
    start = 0
    for seg_len in lens:
        sl = slice(start, start + seg_len)
        want = _naive_attention(q[:, :, sl], k[:, :, sl], v[:, :, sl],
                                causal)
        np.testing.assert_allclose(
            np.asarray(out[:, :, sl], np.float32), np.asarray(want),
            atol=2e-5, rtol=2e-5,
        )
        start += seg_len


@pytest.mark.parametrize("ns,bk", [(1, 64), (4, 32), (2, 64)])
def test_decode_fused_matches_staged(ns, bk):
    """The fused single-kernel decode is numerically the 3-stage pipeline
    (split kernel -> merge -> normalize) it replaces, across split
    geometries and ragged lengths."""
    from triton_distributed_tpu.ops.attention import (
        decode_attention_fused, decode_attention_state,
        merge_decode_states, safe_normalize_decode,
    )

    b, h, hk, s, d = 3, 8, 4, 128, 32
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.standard_normal((b, h, d)).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.standard_normal((b, hk, s, d)).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.standard_normal((b, hk, s, d)).astype(np.float32) * 0.3)
    lens = jnp.asarray([s, 37, 0], jnp.int32)   # full, ragged, empty

    num, m, l = decode_attention_state(q, k, v, lens, n_split=ns, block_k=bk)
    num, _, l = merge_decode_states(num, m, l)
    want = safe_normalize_decode(num[..., 0, :], l[..., 0][..., None], q.dtype)
    got = decode_attention_fused(q, k, v, lens, n_split=ns, block_k=bk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
