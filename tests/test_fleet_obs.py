"""Fleet observability plane (ISSUE 19): cross-replica telemetry
federation, the control-decision ledger, and fleet-scope anomaly
detection.

Headless like the fleet tests: real ``FleetRouter`` fleets over
deterministic ``SimBackend`` replicas, the federation plane armed via
``obs.fleet_stats.enable`` / ``obs.decisions.enable`` (the in-process
spelling of ``TDT_FLEET_OBS=1``), and everything restored so the
plane stays byte-identically off for every other test.
"""

import json
import os
import random
import subprocess
import sys
import threading

import pytest

from triton_distributed_tpu import obs, resilience, serve
from triton_distributed_tpu.obs import decisions, fleet_stats, history
from triton_distributed_tpu.obs import request_trace as rtrace
from triton_distributed_tpu.obs.serve_stats import ServeStats
from triton_distributed_tpu.serve.fleet import replica_breaker_name

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_IDS = ("p0", "p1", "d0", "d1")


@pytest.fixture(autouse=True)
def _fresh_fleet_breakers():
    """The test fleets reuse replica ids; breakers are process-global
    sticky state (the ``test_fleet.py`` rule)."""
    for rid in _IDS:
        resilience.reset_breaker(replica_breaker_name(rid))
    resilience.reset_breaker(serve.HANDOFF_OP)
    yield
    for rid in _IDS:
        resilience.reset_breaker(replica_breaker_name(rid))
    resilience.reset_breaker(serve.HANDOFF_OP)


@pytest.fixture()
def fleet_obs_on(tmp_path):
    """Arm the whole plane — base obs, tracing, the decision ledger
    (persisted under tmp_path), the federation hook — and restore every
    singleton afterwards."""
    prev_obs = obs.enabled()
    obs.enable(True)
    prev_trace = rtrace.enable(True)
    rtrace.RING.clear()
    obs.serve_stats.STATS.reset()
    prev_dec = decisions.enabled()
    prev_fs = fleet_stats.enabled()
    decisions.enable(True)
    fleet_stats.enable(True)
    prev_led = decisions.install(
        decisions.DecisionLedger(cap=512, out_dir=str(tmp_path)))
    prev_fleet = fleet_stats.current()
    yield str(tmp_path)
    decisions.install(prev_led)
    decisions.enable(prev_dec)
    fleet_stats.install(prev_fleet)
    fleet_stats.enable(prev_fs)
    rtrace.RING.clear()
    rtrace.enable(prev_trace)
    obs.serve_stats.STATS.reset()
    obs.enable(prev_obs)


def _sched(*, prefill_only=False, slots=3, pool_pages=24,
           max_queue_depth=32):
    return serve.Scheduler(
        serve.SimBackend(slots=slots, page_size=4, pool_pages=pool_pages,
                         max_length=64),
        serve.SchedulerConfig(max_queue_depth=max_queue_depth,
                              prefill_only=prefill_only))


def _fleet(*, config=None, seed=1):
    replicas = [
        serve.Replica(rid, _sched(prefill_only=True), "prefill")
        for rid in ("p0", "p1")
    ] + [
        serve.Replica(rid, _sched(pool_pages=32), "decode")
        for rid in ("d0", "d1")
    ]
    plane = serve.HandoffPlane(dcn_channel=serve.ModeledDCN(seed=seed))
    return serve.FleetRouter(replicas, plane=plane, config=config)


def _load(n=6, seed=0, max_new=(4, 8)):
    rng = random.Random(seed)
    return [
        serve.Request(prompt=tuple(rng.randrange(1, 90)
                                   for _ in range(rng.randint(2, 6))),
                      max_new_tokens=rng.randint(*max_new))
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# the tee federation: merged == union, exactly


def test_tee_federation_merge_is_lossless():
    """The federation pin: per-replica tee sketches share the union's
    gamma, so merging the replica copies reconstructs the union stream
    bucket-for-bucket — count, sum, and every serving quantile EQUAL,
    not approximately equal."""
    union = ServeStats()
    fs = fleet_stats.FleetStats(union=union, record=False)
    a = fs.replica("p0", "prefill")
    b = fs.replica("p1", "prefill")
    rng = random.Random(3)
    for i in range(400):
        (a if i % 3 else b).observe_ttft(rng.uniform(1.0, 5000.0),
                                         exemplar=f"t-{i}")
        (b if i % 2 else a).request_completed(rng.uniform(5.0, 9000.0))
    for name in ("ttft_ms", "request_ms"):
        merged = fs.merged(name)
        ref = getattr(union, name)
        assert merged.count == ref.count == 400
        assert merged.sum == pytest.approx(ref.sum)
        for q in fleet_stats.SERVE_QUANTILES:
            assert merged.quantile(q) == ref.quantile(q)
    # the per-replica drill-down really is a partition of the union
    assert a.ttft_ms.count + b.ttft_ms.count == union.ttft_ms.count
    assert a.ttft_ms.count > 0 and b.ttft_ms.count > 0


def test_tee_rate_totals_partition_the_union():
    union = ServeStats()
    fs = fleet_stats.FleetStats(union=union, record=False)
    a = fs.replica("d0", "decode")
    b = fs.replica("d1", "decode")
    for _ in range(5):
        a.tokens.add(3.0)
        b.tokens.add(7.0)
    assert a.tokens.total == 15.0 and b.tokens.total == 35.0
    assert union.tokens.total == 50.0


def test_role_skew_flags_the_lagging_replica():
    union = ServeStats()
    fs = fleet_stats.FleetStats(union=union, record=False)
    a = fs.replica("p0", "prefill")
    b = fs.replica("p1", "prefill")
    for i in range(16):
        a.observe_ttft(10.0)
        b.observe_ttft(10.0)
    assert fs.role_skew() == pytest.approx(0.0)
    for i in range(16):
        b.observe_ttft(1000.0)
    assert fs.role_skew() > 5.0


# ---------------------------------------------------------------------------
# the decision ledger


def test_ledger_typed_ring_bound_and_jsonl_roundtrip(tmp_path):
    led = decisions.DecisionLedger(cap=8, out_dir=str(tmp_path))
    with pytest.raises(ValueError, match="unknown decision kind"):
        led.record("not_a_kind", step=0)
    for i in range(20):
        led.record("route", step=i, replica=f"p{i % 2}",
                   request_id=i, inputs={"load": i / 10.0,
                                         "role": "prefill"})
    assert led.total == 20 and len(led.tail()) == 8
    assert led.counts() == {"route": 20}
    # the ring is bounded; the JSONL segments keep the WHOLE stream
    disk = history.load_decision_records(str(tmp_path))
    assert [d["seq"] for d in disk] == list(range(20))
    # inputs verbatim through the round-trip
    rec = decisions.from_dict(disk[7])
    assert rec.kind == "route" and rec.replica == "p1"
    assert rec.inputs == {"load": 0.7, "role": "prefill"}


def test_load_decision_records_skips_garbage(tmp_path):
    p = tmp_path / "decisions_0000.jsonl"
    p.write_text('{"kind":"route","seq":0,"step":1}\n'
                 "\n"
                 "not json at all\n"
                 '{"no_kind_key": 1}\n'
                 '{"kind":"failover","seq":1,"step":2}\n')
    recs = history.load_decision_records(str(tmp_path))
    assert [d["kind"] for d in recs] == ["route", "failover"]


def test_suppressed_actuations_stay_out_of_the_ledger(fleet_obs_on):
    """Probe / warmup traffic drives the same actuation sites under
    ``obs.suppress()`` — the ledger must describe REAL control flow
    only."""
    assert decisions.record("route", step=1, replica="p0") is not None
    with obs.suppress():
        assert not decisions.enabled()
        assert decisions.record("route", step=2, replica="p0") is None
    led = decisions.ledger()
    assert led.total == 1 and led.tail()[0].step == 1


def test_concurrent_records_never_tear(fleet_obs_on):
    led = decisions.ledger()

    def spam(rid):
        for i in range(200):
            decisions.record("route", step=i, replica=rid)

    ts = [threading.Thread(target=spam, args=(f"p{i}",)) for i in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert led.total == 800
    assert led.counts() == {"route": 800}
    seqs = [r.seq for r in led.tail()]
    assert seqs == sorted(seqs)


# ---------------------------------------------------------------------------
# the armed fleet: every actuation ledgered, inputs verbatim


def test_armed_fleet_ledgers_every_admission(fleet_obs_on):
    router = _fleet()
    assert router.fleet_stats is not None          # attach() armed
    reqs = _load(6)
    for i, r in enumerate(reqs):
        router.submit(r, session=f"s{i % 2}")
    router.run_until_idle(max_steps=4000)
    led = decisions.ledger()
    counts = led.counts()
    admissions = sum(counts.get(k, 0) for k in
                     ("route", "affinity_hit", "affinity_redirect",
                      "shed"))
    assert admissions == len(reqs)
    # inputs verbatim: every admission names its target's role and load
    for kind in ("route", "affinity_hit", "affinity_redirect"):
        for rec in led.query(kind=kind):
            assert rec.inputs["role"] in ("prefill", "decode")
            assert "load" in rec.inputs
    # session affinity leaves its audit trail
    hits = led.query(kind="affinity_hit")
    assert all(r.session in ("s0", "s1") for r in hits)


def test_armed_fleet_loss_and_failover_ledgered(fleet_obs_on):
    router = _fleet(config=serve.FleetConfig(
        max_failovers_per_request=4, probe_interval_steps=1 << 30))
    reqs = _load(6)
    for r in reqs:
        router.submit(r)
    lost = False
    for _ in range(600):
        router.step()
        d0 = next(rep for rep in router.replicas
                  if rep.replica_id == "d0")
        if not lost and any(
                s is not None
                and s.request.state is serve.RequestState.DECODE
                for s in d0.scheduler.slots):
            router.lose_replica("d0", reason="test loss")
            lost = True
            break
    assert lost
    router.run_until_idle(max_steps=4000)
    led = decisions.ledger()
    counts = led.counts()
    assert counts.get("replica_lost") == 1
    (rec,) = led.query(kind="replica_lost")
    assert rec.replica == "d0"
    assert rec.inputs["reason"] == "test loss"
    assert counts.get("failover", 0) == router.failovers
    assert counts.get("reprefill", 0) == router.reprefills


def test_unarmed_fleet_is_byte_identical(fleet_obs_on):
    """The ``TDT_FLEET_OBS`` pin: with the plane off, ``attach``
    returns None and touches nothing — the schedulers keep the global
    ``STATS`` collector, no ledger grows, and a seeded replay produces
    token-for-token identical output."""
    def run():
        for rid in _IDS:
            resilience.reset_breaker(replica_breaker_name(rid))
        resilience.reset_breaker(serve.HANDOFF_OP)
        router = _fleet(seed=5)
        reqs = _load(6, seed=9)
        for r in reqs:
            router.submit(r)
        router.run_until_idle(max_steps=4000)
        return router, [tuple(r.tokens) for r in reqs]

    _, armed_tokens = run()
    led_total = decisions.ledger().total
    assert led_total > 0
    fleet_stats.enable(False)
    decisions.enable(False)
    router, off_tokens = run()
    assert router.fleet_stats is None
    for rep in router.replicas:
        assert rep.scheduler.stats is obs.serve_stats.STATS
    assert decisions.ledger().total == led_total   # nothing new
    assert off_tokens == armed_tokens


# ---------------------------------------------------------------------------
# fleet-scope anomaly detection


def _breach_bands():
    # any real decision activity breaches: the healthy edge is one
    # decision per 10 windows, lower-is-better
    band = history.healthy_band([0.0, 0.1], "lower")
    assert band is not None
    return {"fleet_decision_rate": band}


def test_anomaly_event_carries_window_decisions(fleet_obs_on):
    fs = fleet_stats.FleetStats(union=ServeStats(), window_steps=4,
                                bands=_breach_bands())
    rs = fs.replica("p0", "prefill")
    rs.observe_ttft(10.0, exemplar="t-anom-0")
    rs.union.request_ms.observe(20.0, exemplar="t-anom-0")
    decisions.record("quarantine_drain", step=2, replica="p0",
                     inputs={"why": "unit"})
    assert fs.on_step(3) == []                      # off-boundary
    events = fs.on_step(4)
    assert len(events) == 1
    e = events[0]
    assert e.metric == "fleet_decision_rate" and e.value > 0.0
    assert e.step_start == 0 and e.step_end == 4
    assert [d["kind"] for d in e.decisions] == ["quarantine_drain"]
    assert e.exemplar == "t-anom-0"
    assert "ledger decisions" in e.summary()
    # retained + surfaced as the WARNING fragment (never a status flip)
    frag = fs.health_fragment()
    assert frag["status"] == "warn" and frag["total"] == 1
    assert "fleet_decision_rate" in frag["anomalies"][0]
    snap = fs.snapshot()
    assert snap["anomalies"][0]["metric"] == "fleet_decision_rate"


def test_router_health_carries_fleet_obs_fragment(fleet_obs_on):
    router = _fleet()
    router.fleet_stats.window_steps = 4
    router.fleet_stats.bands = _breach_bands()
    reqs = _load(4)
    for r in reqs:
        router.submit(r)
    router.run_until_idle(max_steps=4000)
    snap = router.health()
    frag = snap.get("fleet_obs")
    assert frag is not None and frag["status"] == "warn"
    # drift warns; it never degrades the load-balancer contract
    assert snap["status"] == "ok"


def test_fleet_selftest_both_directions():
    assert fleet_stats.selftest(0) == []
    assert fleet_stats.selftest(7) == []


def test_direction_for_fleet_metrics():
    assert history.direction_for("fleet_decision_rate", "") == "lower"
    assert history.direction_for("fleet_role_skew", "") == "lower"
    assert history.direction_for("fleet_occupancy_spread", "") == "lower"
    assert history.direction_for("fleet_ttft_ms_p99", "ms") == "lower"
    assert history.direction_for("fleet_tokens_per_s", "") == "higher"
    assert history.direction_for("fleet_requests_total", "") == "higher"


def test_decision_coverage_golden_discharges():
    from triton_distributed_tpu.analysis import completeness

    assert completeness.check_decision_coverage() == []


# ---------------------------------------------------------------------------
# /debug/fleet + /metrics


def _get(url: str):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_fleet_endpoint(fleet_obs_on):
    from triton_distributed_tpu.obs import server as obs_server

    router = _fleet()
    reqs = _load(4)
    for r in reqs:
        router.submit(r)
    router.run_until_idle(max_steps=4000)
    srv = obs_server.start(port=0)
    try:
        code, body = _get(srv.url + "/debug/fleet")
        assert code == 200
        snap = json.loads(body)
        assert snap["fleet_stats"]["enabled"] is True
        assert set(snap["fleet_stats"]["replicas"]) == set(_IDS)
        assert snap["decisions"]["total"] == decisions.ledger().total
        assert snap["decisions"]["tail"]
        # ?n= clamps the ledger tail
        code, body = _get(srv.url + "/debug/fleet?n=1")
        assert code == 200
        assert len(json.loads(body)["decisions"]["tail"]) == 1
        # the endpoint is advertised in the 404 listing
        code, body = _get(srv.url + "/nope")
        assert code == 404 and "/debug/fleet" in body
        # /metrics grows the tdt_fleet_* series + the decision counters
        code, body = _get(srv.url + "/metrics")
        assert code == 200
        assert "tdt_fleet_request_ms" in body
        assert 'tdt_fleet_replica_request_ms_p99{replica="d0"' in body
        assert "tdt_fleet_decisions_total" in body
        # concurrent scrapes against live records never tear
        errs = []

        def scrape():
            try:
                for _ in range(10):
                    c, b = _get(srv.url + "/debug/fleet")
                    assert c == 200 and json.loads(b)["decisions"]
            except Exception as exc:   # pragma: no cover
                errs.append(exc)

        def churn():
            for i in range(200):
                decisions.record("route", step=1000 + i, replica="p0")

        ts = [threading.Thread(target=scrape),
              threading.Thread(target=churn)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert errs == []
    finally:
        obs_server.stop()


def test_debug_fleet_unarmed_stub():
    from triton_distributed_tpu.obs import server as obs_server

    srv = obs_server.start(port=0)
    try:
        code, body = _get(srv.url + "/debug/fleet")
        assert code == 200
        snap = json.loads(body)
        assert snap["fleet_stats"].get("hint")
        assert snap["decisions"]["enabled"] in (False, True)
    finally:
        obs_server.stop()


# ---------------------------------------------------------------------------
# the Chrome fleet timeline


def test_chrome_lanes_from_ledger_records(tmp_path):
    recs = [
        dict(seq=0, step=1, t_us=100.0, kind="quarantine_drain",
             replica="d1", inputs={}),
        dict(seq=1, step=2, t_us=150.0, kind="failover", replica="d0",
             request_id=7, inputs={"from": "d1"}),
        dict(seq=2, step=3, t_us=200.0, kind="quarantine_evict",
             replica="d1", inputs={}),
        dict(seq=3, step=9, t_us=400.0, kind="readmit", replica="d1",
             inputs={}),
        dict(seq=4, step=10, t_us=500.0, kind="replica_lost",
             replica="d0", inputs={}),
        # high-volume admission kinds are omitted from the lanes
        dict(seq=5, step=11, t_us=600.0, kind="route", replica="p0",
             inputs={}),
    ]
    evs = fleet_stats.to_chrome(recs, replica_order=("d0", "d1"))
    names = {e["name"] for e in evs}
    assert {"quarantine", "failover", "readmit", "lost",
            "process_name"} <= names
    assert "route" not in names
    # the quarantine span closes at the readmit; the lost span stays
    # open to the newest record
    quar = next(e for e in evs if e["name"] == "quarantine")
    assert quar["ph"] == "X" and quar["dur"] == pytest.approx(300.0)
    assert quar["args"]["end"] == "readmit"
    lost = next(e for e in evs if e["name"] == "lost")
    assert lost["args"]["end"] == "open"
    assert lost["dur"] == pytest.approx(100.0)
    # stable lane assignment: replica_order first
    lanes = {e["args"]["name"]: e["pid"] for e in evs
             if e["name"] == "process_name"}
    assert lanes["replica d0"] == 8000 and lanes["replica d1"] == 8001

    out = fleet_stats.export_chrome(str(tmp_path / "lanes.json"), recs)
    doc = json.loads(open(out).read())
    assert doc["displayTimeUnit"] == "ms" and doc["traceEvents"]


def test_export_fleet_timeline_merges_lanes_and_chains(fleet_obs_on,
                                                       tmp_path):
    router = _fleet()
    reqs = _load(4)
    for r in reqs:
        router.submit(r)
    router.run_until_idle(max_steps=4000)
    assert len(rtrace.RING) > 0
    # a clean replay ledgers only admission kinds (omitted from the
    # lanes by design) — seed the control-plane story the lanes exist
    # to show
    decisions.record("quarantine_drain", step=1, replica="d1",
                     inputs={"why": "timeline-test"})
    decisions.record("replica_lost", step=2, replica="d0",
                     inputs={"why": "timeline-test"})
    out = fleet_stats.export_fleet_timeline(str(tmp_path / "fleet.json"))
    doc = json.loads(open(out).read())
    evs = doc["traceEvents"]
    names = {e.get("name") for e in evs}
    assert "quarantine" in names and "lost" in names   # fleet lanes
    assert any(e.get("cat") == "fleet" for e in evs)
    assert any(e.get("cat") == "request" for e in evs)  # span chains


# ---------------------------------------------------------------------------
# CLI hooks


def test_obs_report_fleet_unarmed_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--fleet"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "not armed" in proc.stdout


def test_tdt_lint_fleetobs_smoke():
    """The tier-1 CI hook (like the --fleet smoke): the armed N=4
    replay with ledger/merge/coverage/selftest reconciliation."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--fleetobs"],
        capture_output=True, text=True, timeout=540,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fleetobs OK" in proc.stdout
    assert "exemplar ->" in proc.stdout
