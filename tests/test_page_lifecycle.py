"""Page-lifetime ownership model checking (ISSUE 17).

Covers the three layers of ``analysis.pages`` — the ownership state
machine, the recorder's interception at the REAL call sites, and the
page-footprint DPOR explorer (with hand-computed class counts) — plus
the refcounted ``PagePool.share``/``release`` substrate it certifies
(scrub refusal under live references pinned with a scrubber spy), the
seeded-bad fixtures both directions, TDT_VERIFY_PAGES inertness when
unset, and the ``tdt_lint --pages`` smoke.
"""

import os
import subprocess
import sys

import pytest

from triton_distributed_tpu import serve
from triton_distributed_tpu.analysis import fixtures, pages
from triton_distributed_tpu.analysis.pages import PageEvent, PageOp
from triton_distributed_tpu.resilience import integrity
from triton_distributed_tpu.serve import budget

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ev(actor, op, key, **meta):
    return PageEvent(actor, op, key, tuple(sorted(meta.items())))


def _checks(violations):
    return sorted({v.check for v in violations})


# ---------------------------------------------------------------------------
# the ownership state machine


def test_clean_lifecycle_is_quiet():
    evs = [
        _ev("serve", "alloc", "P1"), _ev("serve", "write", "P1"),
        _ev("serve", "seal", "P1"), _ev("serve", "stamp", "P1"),
        _ev("serve", "read", "P1"),
        _ev("serve", "free", "P1", scrub_pending=True),
        _ev("serve", "scrub", "P1"),
    ]
    assert pages.check_events(evs) == []


@pytest.mark.parametrize("evs,check,page", [
    # use-after-free: read of recycled storage
    ([_ev("a", "alloc", "U1"), _ev("a", "write", "U1"),
      _ev("a", "seal", "U1"), _ev("a", "free", "U1"),
      _ev("a", "read", "U1")], "use_after_free", "U1"),
    # read of a reserved, never-written page
    ([_ev("a", "alloc", "R1"), _ev("a", "read", "R1"),
      _ev("a", "free", "R1")], "read_before_stamp", "R1"),
    # double free / double alloc
    ([_ev("a", "alloc", "F1"), _ev("a", "free", "F1"),
      _ev("a", "free", "F1")], "double_free", "F1"),
    ([_ev("a", "alloc", "A1"), _ev("b", "alloc", "A1"),
      _ev("a", "free", "A1"), _ev("b", "free", "A1")],
     "double_alloc", "A1"),
    # stamped bytes never legally change
    ([_ev("a", "alloc", "S1"), _ev("a", "write", "S1"),
      _ev("a", "stamp", "S1"), _ev("a", "write", "S1"),
      _ev("a", "free", "S1")], "write_after_stamp", "S1"),
    # copy-on-write: no mutation under a share
    ([_ev("a", "alloc", "W1"), _ev("a", "write", "W1"),
      _ev("a", "seal", "W1"), _ev("b", "share", "W1"),
      _ev("a", "write", "W1"), _ev("a", "free", "W1"),
      _ev("b", "release", "W1")], "write_under_share", "W1"),
    # ABA: re-alloc before the pending poison-fill landed
    ([_ev("a", "alloc", "B1"), _ev("a", "write", "B1"),
      _ev("a", "seal", "B1"),
      _ev("a", "free", "B1", scrub_pending=True),
      _ev("a", "alloc", "B1"), _ev("a", "write", "B1"),
      _ev("a", "seal", "B1"), _ev("a", "free", "B1")],
     "reuse_before_scrub", "B1"),
    # poison-fill under a live reference
    ([_ev("a", "alloc", "L1"), _ev("a", "write", "L1"),
      _ev("a", "seal", "L1"), _ev("s", "scrub", "L1"),
      _ev("a", "free", "L1")], "scrub_under_live_reader", "L1"),
    # more releases than references
    ([_ev("a", "alloc", "N1"), _ev("a", "write", "N1"),
      _ev("a", "seal", "N1"), _ev("a", "release", "N1"),
      _ev("a", "release", "N1")], "refcount_underflow", "N1"),
    # implanted wire bytes sealed before stamp verification
    ([_ev("d", "alloc", "V1"), _ev("d", "implant", "V1"),
      _ev("d", "seal", "V1"), _ev("d", "free", "V1")],
     "adopt_before_stamp_verify", "V1"),
    # sharing a still-filling page serves a torn read
    ([_ev("a", "alloc", "T1"), _ev("a", "write", "T1"),
      _ev("b", "share", "T1"), _ev("a", "free", "T1"),
      _ev("b", "release", "T1")], "share_unsealed", "T1"),
    # leak: a terminal path failed to return the page
    ([_ev("a", "alloc", "K1"), _ev("a", "write", "K1")],
     "page_leak", "K1"),
])
def test_hazard_flagged_with_page_named(evs, check, page):
    vs = pages.check_events(evs)
    assert check in _checks(vs), _checks(vs)
    hit = next(v for v in vs if v.check == check)
    assert f"page {page}" in hit.message


def test_decode_reads_partially_filled_tail_page_legally():
    # decode attends over the FILLING tail page every step — the
    # read-before-stamp check must be narrowed to never-written pages
    evs = [
        _ev("serve", "alloc", "P1"), _ev("serve", "write", "P1"),
        _ev("serve", "read", "P1"), _ev("serve", "write", "P1"),
        _ev("serve", "seal", "P1"), _ev("serve", "free", "P1"),
    ]
    assert pages.check_events(evs) == []


def test_verified_implant_then_seal_is_quiet():
    evs = [
        _ev("decode", "alloc", "D1"), _ev("decode", "implant", "D1"),
        _ev("decode", "verify", "D1"), _ev("decode", "seal", "D1"),
        _ev("decode", "read", "D1"), _ev("decode", "free", "D1"),
    ]
    assert pages.check_events(evs) == []


def test_scrub_pending_free_then_scrub_is_quiet_and_terminal():
    # SCRUB_PENDING at end of trace is NOT a leak (the free committed);
    # but the next alloc before the scrub IS the ABA window
    evs = [
        _ev("a", "alloc", "P1"), _ev("a", "write", "P1"),
        _ev("a", "seal", "P1"),
        _ev("a", "free", "P1", scrub_pending=True),
    ]
    assert pages.check_events(evs) == []


# ---------------------------------------------------------------------------
# the refcounted share/release substrate (PagePool)


def test_refcount_share_release_and_scrub_refusal():
    scrubbed = []
    pool = serve.PagePool(8, page_size=4, scrubber=scrubbed.extend)
    a = pool.alloc(2)
    assert [pool.refcount(p) for p in a] == [1, 1]
    pool.share(a)
    assert [pool.refcount(p) for p in a] == [2, 2]
    assert pool.snapshot()["shared_pages"] == 2
    # first release: refs 2 -> 1, pages stay allocated, NOTHING scrubbed
    pool.free(a)
    assert scrubbed == []
    assert [pool.refcount(p) for p in a] == [1, 1]
    assert pool.used_pages == 2
    # last release: back to the free list, and only now the scrub
    pool.release(a)
    assert scrubbed == a
    assert [pool.refcount(p) for p in a] == [0, 0]
    assert pool.used_pages == 0
    # acquire is the share alias the radix cache will use
    b = pool.alloc(1)
    pool.acquire(b)
    assert pool.refcount(b[0]) == 2
    pool.free(b)
    pool.free(b)


def test_page_lifecycle_error_is_typed_and_names_the_page():
    pool = serve.PagePool(6, page_size=4)
    a = pool.alloc(1)
    pool.free(a)
    with pytest.raises(budget.PageLifecycleError) as ei:
        pool.free(a)
    assert ei.value.page == a[0]
    assert ei.value.transition == "FREE->free"
    assert isinstance(ei.value, ValueError)     # old callers keep working
    with pytest.raises(budget.PageLifecycleError) as ei:
        pool.share(a)
    assert ei.value.page == a[0]
    assert ei.value.transition == "FREE->share"
    with pytest.raises(budget.PageLifecycleError) as ei:
        pool.free([serve.SCRAP_PAGE])
    assert ei.value.page == serve.SCRAP_PAGE
    assert isinstance(ei.value, serve.PageLifecycleError)  # exported


def test_shared_page_survives_owner_free_with_content_intact():
    # the structural half of scrub-never-under-reader: with a poison
    # scrubber armed, an owner's free of a SHARED page must not poison
    # it — the last release does
    calls = []
    pool = serve.PagePool(8, page_size=4, scrubber=lambda ps: calls.append(
        list(ps)))
    a = pool.alloc(1)
    pool.share(a)
    pool.free(a)          # owner departs; radix still holds a ref
    assert calls == []
    pool.release(a)       # last reference -> scrub fires exactly once
    assert calls == [a]


# ---------------------------------------------------------------------------
# recorder interception at the real call sites


def test_recorder_intercepts_scheduler_lifecycle():
    prev = integrity.enable(True)
    try:
        backend = serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                                   max_length=48)
        sched = serve.Scheduler(backend)
        arrivals = serve.synthetic_trace(
            3, 10, mean_interarrival_steps=0.5, prompt_len=(2, 9),
            max_new=(2, 8))
        with pages.record() as rec:
            report = serve.replay(sched, arrivals, max_steps=2000)
    finally:
        integrity.enable(prev)
    assert report.problems() == []
    ops = {e.op for e in rec.events}
    # pool ops + scheduler prefill-write/seal + decode read/append +
    # audit stamps (integrity on) all funnel through the one hook
    assert {"alloc", "write", "seal", "read", "stamp",
            "free"} <= ops, ops
    assert pages.check_recorder(rec, label="sched_replay") == []
    # the pool is attributed to its owning scheduler's tier
    actors = {e.actor for e in rec.events}
    assert "serve" in actors


def test_recorder_intercepts_two_tier_handoff():
    pre = serve.Scheduler(
        serve.SimBackend(slots=3, page_size=4, pool_pages=24,
                         max_length=48),
        serve.SchedulerConfig(max_queue_depth=32, prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                         max_length=48),
        serve.SchedulerConfig(max_queue_depth=32))
    router = serve.DisaggRouter(pre, dec)
    reqs = [serve.Request(prompt=(5, 6, 7), max_new_tokens=4),
            serve.Request(prompt=(8, 9), max_new_tokens=3)]
    with pages.record() as rec:
        for r in reqs:
            router.submit(r)
        router.run_until_idle()
    assert router.leaked_pages() == 0
    ops = {e.op for e in rec.events}
    assert {"alloc", "extract", "implant", "free"} <= ops, ops
    assert pages.check_recorder(rec, label="two_tier") == []
    actors = {e.actor for e in rec.events}
    assert {"prefill", "decode"} <= actors, actors


def test_record_restores_previous_recorder():
    assert budget.lifecycle_recorder() is None
    with pages.record() as outer:
        assert budget.lifecycle_recorder() is outer
        with pages.record() as inner:
            assert budget.lifecycle_recorder() is inner
        assert budget.lifecycle_recorder() is outer
    assert budget.lifecycle_recorder() is None


# ---------------------------------------------------------------------------
# the page-footprint DPOR explorer


def test_dpor_hand_computed_class_counts():
    # two actors, two ops each, ALL on one page: every interleaving is
    # its own Mazurkiewicz class -> C(4, 2) = 6
    dep = {
        "a": [PageOp("alloc", "p1"), PageOp("free", "p1")],
        "b": [PageOp("alloc", "p1"), PageOp("free", "p1")],
    }
    res = pages.explore_pages("dep", dep)
    assert res.schedules == 6 and not res.pruned
    # ...and the race IS caught in the interleaved classes
    assert "double_alloc" in _checks(res.violations)
    # disjoint footprints: everything commutes -> ONE class, clean
    dis = {
        "a": [PageOp("alloc", "p1"), PageOp("free", "p1")],
        "b": [PageOp("alloc", "p2"), PageOp("free", "p2")],
    }
    res = pages.explore_pages("dis", dis)
    assert res.schedules == 1 and res.violations == []


def test_dpor_guard_tokens_enforce_happens_before():
    # the guarded consumer can never run first: one class, clean
    sc = {
        "prod": [PageOp("alloc", "p"), PageOp("write", "p"),
                 PageOp("seal", "p", token="done")],
        "cons": [PageOp("read", "p", guard=("done",)),
                 PageOp("free", "p")],
    }
    res = pages.explore_pages("guarded", sc)
    assert res.violations == []
    # a guard token nobody produces is a deadlock, named
    stuck = {
        "cons": [PageOp("read", "p", guard=("never",))],
    }
    res = pages.explore_pages("stuck", stuck)
    assert _checks(res.violations) == ["deadlock"]
    assert "never" in res.violations[0].message


def test_two_tier_scenarios_all_verify_clean():
    total = 0
    for name, sc in pages.two_tier_scenarios():
        res = pages.explore_pages(name, sc)
        assert res.violations == [], (name, [str(v) for v in
                                             res.violations])
        assert not res.pruned
        total += res.schedules
    # the sweep walks multiple genuine classes, not one serialization
    assert total > len(pages.two_tier_scenarios())


def test_shared_release_scenario_scrubs_only_after_last_release():
    # drop the scrub's guard on the owner's release: some schedule now
    # poisons under the radix cache's live reference — the exact bug
    # PagePool's refcounts (and the clean scenario's guards) prevent
    sc = dict(dict(pages.two_tier_scenarios())["pages/shared_release"])
    sc["scrubber"] = [PageOp("scrub", "D1", guard=("cache_released",))]
    res = pages.explore_pages("pages/shared_release_bad", sc)
    assert "scrub_under_live_reader" in _checks(res.violations)


# ---------------------------------------------------------------------------
# fixtures: both directions


def test_page_fixture_selftest_both_directions():
    problems = fixtures.run_page_selftest()
    assert problems == []


def test_each_page_fixture_names_page_and_transition():
    for name, sc in fixtures.page_fixture_cases():
        check, page = fixtures.PAGE_EXPECTED[name]
        res = pages.explore_pages(name, sc)
        assert check in _checks(res.violations), (name,
                                                  _checks(res.violations))
        hit = next(v for v in res.violations if v.check == check)
        assert f"page {page}" in hit.message
        assert "->" in hit.message          # the violating transition


# ---------------------------------------------------------------------------
# TDT_VERIFY_PAGES gate


def test_unset_env_is_inert(monkeypatch):
    monkeypatch.delenv("TDT_VERIFY_PAGES", raising=False)
    assert not pages.verify_pages_enabled()
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=48)
    sched = serve.Scheduler(backend)
    arrivals = serve.synthetic_trace(5, 4)
    report = serve.replay(sched, arrivals, max_steps=2000)
    assert report.problems() == []
    assert budget.lifecycle_recorder() is None


def test_env_armed_replay_records_and_passes(monkeypatch):
    monkeypatch.setenv("TDT_VERIFY_PAGES", "1")
    assert pages.verify_pages_enabled()
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=48)
    sched = serve.Scheduler(backend)
    arrivals = serve.synthetic_trace(5, 4)
    report = serve.replay(sched, arrivals, max_steps=2000)
    assert report.problems() == []
    # clean drain: the gate armed, checked, and raised nothing; it
    # disarmed on exit
    assert budget.lifecycle_recorder() is None


# ---------------------------------------------------------------------------
# lint smoke


def test_tdt_lint_pages_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--pages"],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"}, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "pages OK" in proc.stdout
