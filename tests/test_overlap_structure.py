"""Structural overlap assertions for the fused kernels (VERDICT round-1
weak #9: overlap quality must be validated somewhere wall-clock can't lie).

Wall-clock on the interpreted CPU mesh is meaningless, but the PROGRAM
ORDER of the kernel body is exactly the overlap contract: the fused
GEMM-RS must ISSUE the matmul of ring step s before BLOCKING on the
arrival of step s-1 (the matmul is what hides the wire), and AG-GEMM must
issue its gather pushes before consuming any chunk.  These tests trace the
kernels with instrumented primitives and assert that order.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.lang import primitives as dl
from triton_distributed_tpu.ops import blocks


@pytest.fixture
def trace_log(monkeypatch):
    """Record trace-time call order of DMA waits and matmul issues."""
    log = []

    real_wait_recv = dl.wait_recv
    real_remote_copy = dl.remote_copy
    real_mm = blocks.make_matmul_pipeline

    def wait_recv(*a, **k):
        log.append("wait_recv")
        return real_wait_recv(*a, **k)

    def remote_copy(*a, **k):
        log.append("send")
        return real_remote_copy(*a, **k)

    def make_matmul_pipeline(*a, **k):
        pipe = real_mm(*a, **k)

        def wrapped(*pa, **pk):
            log.append("mm")
            return pipe(*pa, **pk)

        return wrapped

    # the op modules call dl.<name> / blocks.<name> by attribute at trace
    # time, so patching the two source modules intercepts every kernel
    monkeypatch.setattr(dl, "wait_recv", wait_recv)
    monkeypatch.setattr(dl, "remote_copy", remote_copy)
    monkeypatch.setattr(blocks, "make_matmul_pipeline", make_matmul_pipeline)
    return log


def _run_gemm_rs(n, m, k, nn):
    from triton_distributed_tpu.ops.gemm_rs import gemm_rs

    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    a = jax.device_put(
        jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1,
        NamedSharding(mesh, P(None, TP_AXIS)),
    )
    b = jax.device_put(
        jax.random.normal(jax.random.key(1), (k, nn), jnp.float32) * 0.1,
        NamedSharding(mesh, P(TP_AXIS, None)),
    )
    return gemm_rs(a, b, mesh)


def test_gemm_rs_compute_issued_before_wire_wait(trace_log):
    """In every ring step, the NEXT chunk's matmul is issued before the
    kernel blocks on the PREVIOUS chunk's arrival — the compute-ahead-of-
    wire property (ops/gemm_rs.py docstring, point 3)."""
    # unique shape: the op builders lru-cache traced kernels, and a cached
    # build would bypass the instrumented primitives
    n = 4
    jax.block_until_ready(_run_gemm_rs(n, 4 * 24, 4 * 24, 128))
    assert trace_log, "kernel trace produced no events"
    # per kernel body: mm(step0), send, then per step s: mm BEFORE wait_recv
    first_wait = trace_log.index("wait_recv")
    mms_before_first_wait = trace_log[:first_wait].count("mm")
    # step 0's mm AND step 1's mm are both issued before the first blocking
    # wait on the wire
    assert mms_before_first_wait >= 2, trace_log
    # and every wait is preceded by at least as many mm issues as waits
    # completed (compute always runs ahead of the wire)
    mm_seen = wait_seen = 0
    for ev in trace_log:
        if ev == "mm":
            mm_seen += 1
        elif ev == "wait_recv":
            wait_seen += 1
            assert mm_seen > wait_seen, (
                f"wire wait #{wait_seen} issued with only {mm_seen} matmuls "
                f"ahead of it: {trace_log}"
            )


def test_ag_gemm_pushes_issued_before_consume(trace_log):
    """AG-GEMM issues its gather pushes before blocking on any chunk — the
    wire starts flowing before the consumer sits down."""
    from triton_distributed_tpu.ops.ag_gemm import ag_gemm

    n = 4
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    a = jax.device_put(
        jax.random.normal(jax.random.key(2), (4 * 24, 120), jnp.float32),
        NamedSharding(mesh, P(TP_AXIS, None)),
    )
    b = jax.device_put(
        jax.random.normal(jax.random.key(3), (120, 4 * 32), jnp.float32),
        NamedSharding(mesh, P(None, TP_AXIS)),
    )
    jax.block_until_ready(ag_gemm(a, b, mesh))
    assert trace_log, "kernel trace produced no events"
    first_wait = trace_log.index("wait_recv")
    sends_before_wait = trace_log[:first_wait].count("send")
    assert sends_before_wait >= 1, trace_log
