"""Pipeline-parallel forward vs sequential stage application."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import make_mesh
from triton_distributed_tpu.parallel.pipeline import pipeline_forward


def _stage(w, x):
    return jax.nn.silu(x @ w)


@pytest.mark.parametrize("n,micro", [(2, 2), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(n, micro):
    mesh = make_mesh({"pp": n}, devices=jax.devices()[:n])
    b, h = 16, 32
    key = jax.random.key(0)
    ws = jax.random.normal(key, (n, h, h), jnp.float32) * 0.5
    x = jax.random.normal(jax.random.fold_in(key, 1), (b, h), jnp.float32)
    ws_sharded = jax.device_put(ws, NamedSharding(mesh, P("pp", None, None)))

    got = pipeline_forward(_stage, ws_sharded, x, mesh, "pp",
                           num_microbatches=micro)
    want = np.asarray(x)
    for s in range(n):
        want = np.asarray(_stage(ws[s], jnp.asarray(want)))
    assert got.shape == x.shape
    np.testing.assert_allclose(np.asarray(jax.device_get(got)), want,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n,micro", [(2, 2), (4, 4)])
def test_pipeline_training_grads(n, micro):
    """A pipelined TRAINING step: jax.grad differentiates straight through
    the tick scan (ppermute's adjoint is the reverse hop), so per-stage
    parameter gradients match the unpipelined stack — pipeline-parallel
    training the reference does not have (SURVEY.md 2.5)."""
    mesh = make_mesh({"pp": n}, devices=jax.devices()[:n])
    b, h = 16, 32
    key = jax.random.key(7)
    ws = jax.random.normal(key, (n, h, h), jnp.float32) * 0.5
    x = jax.random.normal(jax.random.fold_in(key, 1), (b, h), jnp.float32)
    ws_sharded = jax.device_put(ws, NamedSharding(mesh, P("pp", None, None)))

    def loss_pp(w):
        y = pipeline_forward(_stage, w, x, mesh, "pp",
                             num_microbatches=micro)
        return jnp.mean(jnp.square(y))

    def loss_seq(w):
        y = x
        for s in range(n):
            y = _stage(w[s], y)
        return jnp.mean(jnp.square(y))

    g_pp = np.asarray(jax.device_get(jax.jit(jax.grad(loss_pp))(ws_sharded)))
    g_seq = np.asarray(jax.grad(loss_seq)(ws))
    np.testing.assert_allclose(g_pp, g_seq, rtol=1e-4, atol=1e-5)


def test_pipeline_single_stage_fallback():
    mesh = make_mesh({"pp": 1}, devices=jax.devices()[:1])
    ws = jax.random.normal(jax.random.key(2), (1, 8, 8), jnp.float32)
    x = jax.random.normal(jax.random.key(3), (4, 8), jnp.float32)
    got = pipeline_forward(_stage, ws, x, mesh, "pp")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_stage(ws[0], x)), rtol=1e-6
    )


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh({"pp": 2}, devices=jax.devices()[:2])
    ws = jax.random.normal(jax.random.key(4), (2, 8, 8), jnp.float32)
    x = jax.random.normal(jax.random.key(5), (5, 8), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_forward(_stage, ws, x, mesh, "pp", num_microbatches=3)
