"""Hierarchical multi-slice collectives (ISSUE 10): the headless half.

Everything here runs without a multi-device mesh (this CI container's
jax cannot execute collective kernels): the topology-scheduled chunk
order, the persisted slice topology, the per-wire-class cost/watchdog
pricing, the two-level protocol matrix, the seeded-bad inter-slice
fixture, the scheduled-A2A index math (merge/un-merge round trip), and
the single-slice delegation that numerically pins the hierarchical
entries to the flat ones.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import analysis, resilience
from triton_distributed_tpu.comm import hierarchical as hier
from triton_distributed_tpu.obs import costs
from triton_distributed_tpu.tools import calibrate, perf_model
from triton_distributed_tpu.tools.calibrate import LinkCalibration

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# schedule policy


@pytest.mark.parametrize("n", [2, 3, 4, 8])
def test_ici_schedule_is_farthest_first_permutation(n):
    s = hier.ici_schedule(n)
    assert sorted(s) == list(range(n))
    assert s[-1] == 0                       # self (no wire) last
    dists = [min(o, n - o) for o in s[:-1]]
    assert dists == sorted(dists, reverse=True), s


def test_chunk_schedule_dcn_first_on_cold_topology():
    # cold start (no calibration): the chip table says DCN << ICI, so
    # every inter-slice group precedes every intra-slice one
    s = hier.chunk_schedule(2, 4, LinkCalibration())
    k = sum(1 for g in s if g[0] != 0)
    assert all(g[0] != 0 for g in s[:k]), s
    assert s[-1] == (0, 0)
    assert len(s) == 8 and len(set(s)) == 8


def test_chunk_schedule_tracks_calibration():
    # a (synthetic) calibration measuring the ICI as the slower wire
    # must flip the class order — the schedule follows the topology
    # MODEL, not a hard-coded class
    flipped = hier.chunk_schedule(2, 4, LinkCalibration(
        ici_gbps=6.25, dcn_gbps=186.0, num_slices=2, chips_per_slice=4))
    k = sum(1 for g in flipped if g[0] == 0 and g != (0, 0))
    assert all(g[0] == 0 for g in flipped[:k]), flipped


def test_a2a_config_schedule_reaches_kernel():
    """The scheduled emission order is a verified protocol variant: the
    registry's all_to_all/scheduled case runs the REAL push kernel body
    with the farthest-first order at every rank count."""
    names = {c.name for c in analysis.all_cases(ranks=(4,))}
    assert "all_to_all/scheduled" in names
    case = {c.name: c for c in analysis.cases_for("all_to_all", 4)}[
        "all_to_all/scheduled"]
    assert analysis.verify_case(case) == []


# ---------------------------------------------------------------------------
# persisted slice topology + --json (satellite)


def test_link_calibration_persists_slice_topology(tmp_path, monkeypatch):
    monkeypatch.setenv("TDT_LINKCAL_CACHE", str(tmp_path / "linkcal.json"))
    calibrate.invalidate_cache()
    cal = LinkCalibration(ici_gbps=100.0, ici_hop_us=1.0, dcn_gbps=5.0,
                          dcn_hop_us=20.0, device_kind="test", n_devices=8,
                          num_slices=2, chips_per_slice=4)
    calibrate.save_calibration(cal)
    calibrate.invalidate_cache()
    loaded = calibrate.load_calibration()
    assert (loaded.num_slices, loaded.chips_per_slice) == (2, 4)
    assert calibrate.slice_topology() == (2, 4)
    calibrate.invalidate_cache()


def test_slice_topology_cold_start(tmp_path, monkeypatch):
    monkeypatch.setenv("TDT_LINKCAL_CACHE", str(tmp_path / "none.json"))
    calibrate.invalidate_cache()
    n_slices, chips = calibrate.slice_topology()
    assert n_slices >= 1 and chips >= 1
    calibrate.invalidate_cache()


def test_calibrate_main_json(monkeypatch, capsys):
    cal = LinkCalibration(ici_gbps=100.0, ici_hop_us=1.0,
                          device_kind="test", n_devices=4,
                          num_slices=1, chips_per_slice=4)
    monkeypatch.setattr(calibrate, "calibrate", lambda: cal)
    assert calibrate.main(["--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1                   # machine-readable: ONE object
    rec = json.loads(out[0])
    assert rec["num_slices"] == 1 and rec["chips_per_slice"] == 4
    assert "push_bytes_threshold" in rec and "path" in rec


# ---------------------------------------------------------------------------
# per-wire-class pricing (costs / perf_model / watchdog satellites)


def test_hier_ar_dcn_bytes_at_rs_ag_bound():
    """The acceptance bound: per-chip DCN bytes of the hierarchical AR
    == 1/n_in of the payload at n_out=2 (ring psum of the 1/n_in
    partial) — and always <= it."""
    m, r = 4096, 7168
    payload = m * r * 2
    for n_in, n_out in [(2, 2), (4, 2), (2, 4), (8, 2)]:
        _, dcn = hier.hier_ar_wire_bytes(m, r, jnp.bfloat16, n_in, n_out,
                                         "bf16")
        # the DCN hop reduces only the 1/n_in partial: psum ring =
        # 2(n_out-1)/n_out of it — at n_out=2 exactly the 1/n_in bound
        # the bench claims-gates, never more than 2/n_in
        assert dcn == 2 * (n_out - 1) * (payload // n_in) // n_out, \
            (n_in, n_out, dcn)
        if n_out == 2:
            assert dcn == payload // n_in


def test_sol_ms_charges_dcn_at_its_own_wire():
    """A cost whose bytes ride the DCN must price slower than the same
    bytes on ICI — the satellite's 'stop pricing every hop as ICI'."""
    c_ici = costs.KernelCost(flops=0, bytes_accessed=1 << 24,
                             wire_bytes=1 << 24)
    c_dcn = costs.KernelCost(flops=0, bytes_accessed=1 << 24,
                             dcn_bytes=1 << 24)
    assert costs.sol_ms(c_dcn, "TPU v5e") > 5 * costs.sol_ms(
        c_ici, "TPU v5e")


def test_hier_family_costs_registered():
    for fam in ("hier_all_gather", "hier_reduce_scatter",
                "hier_all_reduce", "hier_all_to_all"):
        assert fam in costs.FAMILY_COSTS
    c = costs.FAMILY_COSTS["hier_all_reduce"](
        m=4096, r=7168, n_in=4, n_out=2, dtype=jnp.bfloat16)
    assert c.dcn_bytes > 0 and c.wire_bytes > 0
    assert c.bytes_accessed >= c.wire_bytes + c.dcn_bytes


def test_watchdog_prices_each_level_its_own_wire():
    """The two-level deadline must exceed the ICI-only deadline for the
    same payload (the DCN hop is slower), and stay finite/monotone."""
    payload = 64 << 20
    flat = resilience.deadline_ms("all_reduce", payload_bytes=payload,
                                  num_ranks=8)
    two = resilience.deadline_ms("hier_all_reduce", payload_bytes=payload,
                                 num_ranks=8, topology=(2, 4))
    assert two > flat
    bigger = resilience.deadline_ms("hier_all_reduce",
                                    payload_bytes=2 * payload,
                                    num_ranks=8, topology=(2, 4))
    assert bigger > two
    a2a = resilience.deadline_ms("sched_ep_dispatch",
                                 payload_bytes=payload, num_ranks=8,
                                 topology=(2, 4))
    assert a2a > 0


def test_perf_model_two_level_terms():
    # the DCN term dominates exactly when its bytes/rate exceed ICI's
    ms = perf_model.hier_allgather_sol_ms(1 << 20, n_in=4, n_out=2)
    spec = perf_model.chip_spec("TPU v5e")
    t_ici = 3 * (1 << 20) / (spec.ici_gbps * 1e9) * 1e3
    t_dcn = 4 * (1 << 20) / (perf_model.dcn_gbps() * 1e9) * 1e3
    assert ms == pytest.approx(max(t_ici, t_dcn))


# ---------------------------------------------------------------------------
# two-level protocol matrix + fault cells


@pytest.mark.parametrize("n,layouts", [(4, ["2x2"]), (8, ["2x4", "4x2"])])
def test_hier_cases_verify_clean(n, layouts):
    results = analysis.verify_all(ranks=(n,), kernel_filter="hier_")
    names = {c.name for c, _ in results}
    for lay in layouts:
        for fam in ("hier_allgather", "hier_reduce_scatter",
                    "hier_allreduce", "hier_a2a"):
            assert f"{fam}/{lay}" in names
    bad = {c.name: [str(v) for v in vs] for c, vs in results if vs}
    assert not bad, bad


def test_hier_fault_cells_detected_or_survived():
    rows = resilience.run_matrix(
        seed=0, kernels=("hier_allreduce/2x2", "hier_a2a/2x2"), ranks=4)
    assert rows
    assert resilience.verify_matrix(rows, min_kernels_per_class=1) == []
    # the inter-slice credit class: at least one detection names a dcn
    # semaphore across the seeded sweep
    assert any("dcn" in s for r in rows for s in r["named"])


def test_dcn_ar_wire_arithmetic():
    # n_out=2: (n_out-1) packed < 2(n_out-1)/n_out bf16 -> quantized wins
    assert hier.dcn_ar_wire("auto", 7168, 2) == "fp8"
    # many slices: the one-shot exchange loses to the psum ring
    assert hier.dcn_ar_wire("auto", 7168, 8) == "bf16"
    assert hier.dcn_ar_wire("bf16", 7168, 2) == "bf16"


# ---------------------------------------------------------------------------
# scheduled-A2A index math (merge/un-merge round trip, pure host)


def test_merge_order_roundtrip():
    """Dispatch merges n_out expert-sorted groups into one run; combine
    inverts through argsort(order).  Simulated with labeled rows."""
    rng = np.random.default_rng(0)
    n_out, e, t = 3, 4, 10
    group_splits = rng.integers(0, 3, (n_out, e)).astype(np.int32)
    group_splits[group_splits.sum(axis=1) > t] = 1   # keep within t rows
    rows = np.full((n_out, t), -1, np.int64)         # -1 = padding
    label = 0
    eids = np.full((n_out, t), e, np.int64)
    for g in range(n_out):
        pos = 0
        for eid in range(e):
            for _ in range(int(group_splits[g, eid])):
                rows[g, pos] = label
                eids[g, pos] = eid
                label += 1
                pos += 1
    order = np.asarray(hier.merge_order(jnp.asarray(group_splits), t))
    merged = rows.reshape(-1)[order]
    merged_eids = eids.reshape(-1)[order]
    # expert-sorted, padding at the tail
    assert (np.diff(merged_eids) >= 0).all()
    real = int(group_splits.sum())
    assert (merged[:real] >= 0).all() and (merged[real:] == -1).all()
    # the combine-side inverse restores the original layout exactly
    inv = np.argsort(order, kind="stable")
    assert (merged[inv].reshape(n_out, t) == rows).all()


def test_per_slice_meta_matches_bruteforce():
    n, n_out = 4, 2
    e = 8                                   # global experts, epr = 2
    e_slice = e // n_out
    rng = np.random.default_rng(1)
    splits = rng.integers(0, 4, (e,)).astype(np.int32)
    per_slice, offs = hier.per_slice_meta(jnp.asarray(splits), n_out,
                                          e_slice)
    expect = splits.reshape(n_out, e_slice).sum(axis=1)
    assert (np.asarray(per_slice) == expect).all()
    assert (np.asarray(offs) == np.concatenate(
        [[0], np.cumsum(expect)[:-1]])).all()


# ---------------------------------------------------------------------------
# single-slice delegation (the flat-equivalence anchor)


def _mesh_1x1():
    from triton_distributed_tpu.core import mesh as mesh_lib

    return mesh_lib.make_mesh({"dcn": 1, "tp": 1})


def test_hier_entries_delegate_on_one_slice():
    """n_out == 1 routes to the flat single-level entries — the
    numerical pinning of the hierarchical semantics to the flat ones on
    an equivalent 1-slice mesh (at tp=1 both are the identity)."""
    mesh = _mesh_1x1()
    x = jnp.arange(32, dtype=jnp.float32).reshape(4, 8)
    assert (hier.hierarchical_all_gather(x, mesh, "tp", "dcn") == x).all()
    assert (hier.hierarchical_all_reduce(x, mesh, "tp", "dcn") == x).all()
    assert (hier.hierarchical_reduce_scatter(x, mesh, "tp", "dcn")
            == x).all()


def test_flat_entries_route_tuple_axis():
    from triton_distributed_tpu import comm

    mesh = _mesh_1x1()
    x = jnp.ones((4, 8), jnp.float32)
    assert (comm.all_gather(x, mesh, ("dcn", "tp")) == x).all()
    assert (comm.all_reduce(x, mesh, ("dcn", "tp")) == x).all()
    assert (comm.reduce_scatter(x, mesh, ("dcn", "tp")) == x).all()


def test_sched_ep_dispatch_delegates_on_one_slice():
    from triton_distributed_tpu import comm

    mesh = _mesh_1x1()
    t, h, e = 6, 8, 4
    x = jnp.arange(t * h, dtype=jnp.float32).reshape(t, h)
    splits = jnp.asarray([2, 1, 3, 0], jnp.int32)
    recv, recv_splits = hier.scheduled_ep_dispatch(
        x, splits, mesh, "tp", "dcn")
    flat_recv, flat_splits = comm.ep_dispatch(x, splits, mesh, "tp")
    assert (recv == flat_recv).all()
    assert (recv_splits == flat_splits).all()
    back = hier.scheduled_ep_combine(recv, splits, mesh, "tp", "dcn",
                                     token_dim=t)
    assert (back == x).all()


def test_slice_axes_detection():
    from triton_distributed_tpu.core import mesh as mesh_lib

    assert hier.slice_axes(_mesh_1x1()) is None      # dcn extent 1
    assert hier.slice_axes(mesh_lib.make_mesh({"tp": 1})) is None


def test_moe_dcn_axis_plumbs():
    from triton_distributed_tpu.layers.moe import MoEMLP

    mesh = _mesh_1x1()
    layer = MoEMLP(mesh, num_experts=4, dcn_axis="dcn")
    assert layer.n == 1
    assert layer._ep_spec == ("dcn", "tp")
    flat = MoEMLP(mesh, num_experts=4)
    assert flat._ep_spec == "tp"


# ---------------------------------------------------------------------------
# CLI


def test_cli_hier_gate():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--hier"],
        capture_output=True, text=True, cwd=REPO, timeout=600,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "hier OK" in res.stdout


def test_bench_hier_record():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "hier"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stdout + res.stderr
    rec = json.loads(res.stdout.strip().splitlines()[-1])
    assert rec["metric"].startswith("hier_ar_dcn_bytes_ratio")
    # the claims-gate bound: DCN bytes <= 1/slice_ranks payload + tol
    assert rec["value"] <= 1.02
    assert rec["ratio_bf16_psum"] == pytest.approx(1.0)
    assert rec["dcn_bytes"] <= rec["bound_bytes"] * 1.02
