"""MoE group-GEMM ops and routing utils vs dense-loop goldens (reference
``test_ag_group_gemm.py`` / ``test_moe_reduce_rs.py`` strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.ops.group_gemm import (
    GroupGemmConfig,
    ag_group_gemm,
    group_gemm,
    grouped_matmul,
    moe_reduce_rs,
)
from triton_distributed_tpu.ops.swizzle import grouped_tile_schedule
from triton_distributed_tpu.ops.moe_utils import (
    expert_block_permutation,
    flatten_topk,
    global_presort_index,
    sort_by_expert,
    topk_route,
    unsort_combine,
)


def _dense_group_golden(x_sorted, w, splits):
    """Loop-over-experts reference."""
    out = np.zeros((x_sorted.shape[0], w.shape[2]), np.float32)
    start = 0
    for e in range(w.shape[0]):
        c = int(splits[e])
        out[start:start + c] = np.asarray(x_sorted[start:start + c]) @ np.asarray(w[e])
        start += c
    return out


def test_group_gemm_golden():
    t, k, n_dim, e = 64, 32, 48, 4
    key = jax.random.key(0)
    splits = jnp.array([10, 0, 34, 20], jnp.int32)
    x = jax.random.normal(key, (t, k), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (e, k, n_dim), jnp.float32)
    got = group_gemm(x, w, splits)
    want = _dense_group_golden(x, w, splits)
    assert np.allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize(
    "splits",
    [
        [16, 16, 16, 16],   # block-aligned
        [10, 0, 30, 24],    # boundary-crossing + empty group
        [5, 3, 0, 20],      # trailing rows past all groups -> zero-filled
        [0, 0, 0, 0],       # fully empty
        [64, 0, 0, 0],      # one group takes everything
        [1, 1, 1, 1],       # many groups in one tile
    ],
)
def test_grouped_matmul_golden(splits):
    """Pallas tile-scheduled grouped matmul vs the dense loop, including
    the zero-fill of rows past ``sum(splits)`` (reference semantics: the
    aligned schedule of ``moe_ag_scatter_align_block_size`` never emits
    work for pad rows)."""
    t, k, n_dim, e = 64, 32, 48, 4
    key = jax.random.key(3)
    sp = jnp.asarray(splits, jnp.int32)
    x = jax.random.normal(key, (t, k), jnp.float32)
    w = jax.random.normal(jax.random.fold_in(key, 1), (e, k, n_dim),
                          jnp.float32)
    got = np.asarray(
        grouped_matmul(x, w, sp, config=GroupGemmConfig(bm=16, bn=16, bk=16))
    )
    want = _dense_group_golden(x, w, splits)
    assert np.allclose(got, want, atol=1e-4, rtol=1e-4)
    # rows past the last group must be exactly zero, not garbage
    tail = int(np.sum(splits))
    assert np.array_equal(got[tail:], np.zeros((t - tail, n_dim), np.float32))


def test_grouped_matmul_jit_and_dtype():
    """Traced splits (the layer path) and bf16 in/out."""
    t, k, n_dim, e = 32, 64, 32, 3
    key = jax.random.key(4)
    x = jax.random.normal(key, (t, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.fold_in(key, 1), (e, k, n_dim),
                          jnp.bfloat16)
    sp = jnp.asarray([8, 20, 4], jnp.int32)
    cfg = GroupGemmConfig(bm=8, bn=16, bk=16)
    f = jax.jit(lambda x, w, s: grouped_matmul(x, w, s, config=cfg))
    got = np.asarray(f(x, w, sp), np.float32)
    want = _dense_group_golden(x, w, np.asarray(sp))
    assert np.allclose(got, want, atol=0.1, rtol=0.1)


def test_grouped_tile_schedule_properties():
    """Every occupied row is claimed by exactly one slot of its tile, every
    tile has exactly one initializing slot, and pad slots are inert."""
    rng = np.random.default_rng(7)
    for _ in range(20):
        e = int(rng.integers(1, 6))
        bm = int(rng.choice([8, 16, 32]))
        nt = int(rng.integers(1, 6))
        t = nt * bm
        splits = rng.multinomial(
            int(rng.integers(0, t + 1)), np.ones(e) / e
        ).astype(np.int32)
        sched = jax.tree.map(
            np.asarray,
            grouped_tile_schedule(jnp.asarray(splits), t, bm),
        )
        num_slots = nt + e
        assert all(a.shape == (num_slots,) for a in sched)
        claimed = np.zeros(t, np.int32)
        for s in range(num_slots):
            lo, hi = sched.row_starts[s], sched.row_ends[s]
            tile = sched.tile_ids[s]
            assert 0 <= tile < nt
            if lo < hi:
                # slot rows live inside the slot's tile
                assert lo >= tile * bm and hi <= (tile + 1) * bm
                # and inside the slot's group's row range
                g = sched.group_ids[s]
                g_lo = splits[:g].sum()
                assert lo >= g_lo and hi <= g_lo + splits[g]
                claimed[lo:hi] += 1
        covered = int(splits.sum())
        assert np.array_equal(claimed[:covered], np.ones(covered, np.int32))
        assert np.array_equal(claimed[covered:], np.zeros(t - covered, np.int32))
        # exactly one initializer per tile, ordered tile-major
        init_tiles = sched.tile_ids[sched.is_first == 1]
        assert np.array_equal(np.sort(init_tiles), np.arange(nt))
        assert np.array_equal(sched.tile_ids, np.sort(sched.tile_ids))


def test_routing_sort_unsort_round_trip():
    t, h, e, k = 16, 8, 6, 2
    key = jax.random.key(1)
    x = jax.random.normal(key, (t, h), jnp.float32)
    logits = jax.random.normal(jax.random.fold_in(key, 1), (t, e), jnp.float32)
    eid, w = topk_route(logits, k)
    assert eid.shape == (t, k) and w.shape == (t, k)
    assert np.allclose(np.asarray(w.sum(-1)), 1.0, atol=1e-5)
    xr, eflat, wflat = flatten_topk(x, eid, w)
    xs, splits, unsort = sort_by_expert(xr, eflat, e)
    assert int(splits.sum()) == t * k
    # identity expert compute: combine must yield sum_k w_k * x = x
    out = unsort_combine(xs, unsort, wflat, k)
    assert np.allclose(np.asarray(out), np.asarray(x), atol=1e-5)


@pytest.mark.parametrize("n", [2, 4])
def test_ag_group_gemm_golden(n):
    t, kd, n_dim, e = 16, 32, 16 * n, 2 * n
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    rng = np.random.default_rng(n)
    # per-rank sorted tokens + splits
    xs, sps = [], []
    for r in range(n):
        w_ = rng.random(e)
        split = np.floor(w_ / w_.sum() * t).astype(np.int32)
        split[0] += t - split.sum()
        sps.append(split)
        xs.append(rng.standard_normal((t, kd)).astype(np.float32))
    x = jnp.asarray(np.concatenate(xs))
    splits = jnp.asarray(np.concatenate(sps))
    w = jnp.asarray(rng.standard_normal((e, kd, n_dim)).astype(np.float32))
    xg = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    sg = jax.device_put(splits, NamedSharding(mesh, P(TP_AXIS)))
    wg = jax.device_put(w, NamedSharding(mesh, P(None, None, TP_AXIS)))
    y, total_splits, perm = ag_group_gemm(xg, wg, sg, mesh)
    # golden: merge blocks to global expert order, dense loop
    perm_np = np.asarray(jax.device_get(perm))
    x_glob = np.concatenate(xs)[perm_np]
    want = _dense_group_golden(
        jnp.asarray(x_glob), w, np.asarray(jax.device_get(total_splits))
    )
    assert y.shape == (n * t, n_dim)
    assert np.allclose(np.asarray(jax.device_get(y)), want, atol=1e-3,
                       rtol=1e-3)


def test_ag_group_gemm_pallas_backend():
    """Forcing the tile-scheduled Pallas kernel through the distributed op
    (the real-TPU default) must match the ragged_dot path."""
    n = 2
    t, kd, n_dim, e = 16, 32, 32, 4
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    rng = np.random.default_rng(5)
    xs, sps = [], []
    for r in range(n):
        split = np.asarray([4, 0, 9, 3], np.int32)
        sps.append(split)
        xs.append(rng.standard_normal((t, kd)).astype(np.float32))
    x = jnp.asarray(np.concatenate(xs))
    splits = jnp.asarray(np.concatenate(sps))
    w = jnp.asarray(rng.standard_normal((e, kd, n_dim)).astype(np.float32))
    xg = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    sg = jax.device_put(splits, NamedSharding(mesh, P(TP_AXIS)))
    wg = jax.device_put(w, NamedSharding(mesh, P(None, None, TP_AXIS)))
    cfg = GroupGemmConfig(bm=8, bn=16, bk=16)
    y_pal, ts_pal, _ = ag_group_gemm(xg, wg, sg, mesh, config=cfg)
    y_rag, ts_rag, _ = ag_group_gemm(xg, wg, sg, mesh)
    assert np.array_equal(np.asarray(ts_pal), np.asarray(ts_rag))
    covered = int(np.asarray(ts_rag).sum())
    assert np.allclose(
        np.asarray(jax.device_get(y_pal))[:covered],
        np.asarray(jax.device_get(y_rag))[:covered],
        atol=1e-4, rtol=1e-4,
    )


@pytest.mark.parametrize("n", [2, 4])
def test_moe_forward_end_to_end(n):
    """Full MoE block: route -> sort -> AG+group-GEMM -> act ->
    group-GEMM+RS vs a dense per-token loop."""
    t, hid, ffn, e, k = 8, 32, 16 * n, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    rng = np.random.default_rng(20 + n)
    # tokens per rank (t), replicated routing computed per rank
    x_all, eid_all, wts_all = [], [], []
    for r in range(n):
        x_all.append(rng.standard_normal((t, hid)).astype(np.float32) * 0.3)
    w_up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    logits = rng.standard_normal((n * t, e)).astype(np.float32)

    # per-rank routing + sorting (host-side prep, same math every rank)
    xs_sorted, sps, unsorts, wflats = [], [], [], []
    for r in range(n):
        eid, wts = topk_route(jnp.asarray(logits[r * t:(r + 1) * t]), k)
        xr, eflat, wflat = flatten_topk(jnp.asarray(x_all[r]), eid, wts)
        xsr, split, unsort = sort_by_expert(xr, eflat, e)
        xs_sorted.append(np.asarray(xsr))
        sps.append(np.asarray(split))
        unsorts.append(np.asarray(unsort))
        wflats.append(np.asarray(wflat))
    x_sorted = jnp.asarray(np.concatenate(xs_sorted))     # (n*t*k, hid)
    splits = jnp.asarray(np.concatenate(sps))

    xg = jax.device_put(x_sorted, NamedSharding(mesh, P(TP_AXIS, None)))
    sg = jax.device_put(splits, NamedSharding(mesh, P(TP_AXIS)))
    wug = jax.device_put(w_up, NamedSharding(mesh, P(None, None, TP_AXIS)))
    wdg = jax.device_put(w_dn, NamedSharding(mesh, P(None, TP_AXIS, None)))

    h1, total_splits, perm = ag_group_gemm(xg, wug, sg, mesh)
    h1 = jax.nn.silu(h1)

    # compose block-merge + per-rank unsort into the pre-sort index; the
    # routing weights are already in pre-sort (rank-major) order
    presort = global_presort_index(perm, jnp.asarray(np.stack(unsorts)))
    wflat_glob = jnp.asarray(np.concatenate(wflats))
    out = moe_reduce_rs(h1, wdg, total_splits, presort, wflat_glob, k, mesh)
    assert out.shape == (n * t, hid)

    # dense golden per token
    got = np.asarray(jax.device_get(out))
    probs = jax.nn.softmax(jnp.asarray(logits), -1)
    top_w, top_e = jax.lax.top_k(probs, k)
    top_w = top_w / top_w.sum(-1, keepdims=True)
    x_cat = np.concatenate(x_all)
    for i in range(n * t):
        acc = np.zeros(hid, np.float32)
        for j in range(k):
            ee = int(top_e[i, j])
            hcol = jax.nn.silu(x_cat[i] @ np.asarray(w_up[ee]))
            acc += float(top_w[i, j]) * np.asarray(hcol @ np.asarray(w_dn[ee]))
        assert np.allclose(got[i], acc, atol=2e-3, rtol=2e-3), (
            i, np.abs(got[i] - acc).max()
        )


def test_expert_block_permutation():
    sp = jnp.asarray(np.array([[2, 1, 0], [1, 0, 2]], np.int32))
    perm, total = expert_block_permutation(sp, 3)
    assert list(np.asarray(total)) == [3, 1, 2]
    # block rows: r0 = [e0,e0,e1], r1 = [e0,e2,e2]; global expert order is
    # [r0e0, r0e0, r1e0, r0e1, r1e2, r1e2] -> indices [0,1,3,2,4,5]
    assert list(np.asarray(perm)) == [0, 1, 3, 2, 4, 5]


def test_grouped_matmul_fuzz_splits_and_tiles():
    """Randomized splits (zeros, unaligned boundaries, empty batches,
    partially-occupied rows) x tile shapes against ``lax.ragged_dot`` —
    the pad-elision schedule (frozen pad fetches, covers fast path,
    no-write pads) must be invisible at every boundary geometry."""
    from triton_distributed_tpu.ops.group_gemm import (
        GroupGemmConfig, grouped_matmul,
    )

    rng = np.random.default_rng(42)
    t, k, n_dim = 128, 64, 64
    x = jnp.asarray(rng.standard_normal((t, k)), jnp.float32)
    for trial in range(6):
        e = int(rng.integers(1, 7))
        w = jnp.asarray(rng.standard_normal((e, k, n_dim)), jnp.float32)
        occupied = int(rng.integers(0, t + 1))
        splits = rng.multinomial(occupied, np.ones(e) / e).astype(np.int32)
        s = jnp.asarray(splits)
        want = jax.lax.ragged_dot(x, w, s,
                                  precision=jax.lax.Precision.HIGHEST)
        bm = int(rng.choice([8, 16, 32, 64]))
        bn = int(rng.choice([16, 32, 64]))
        bk = int(rng.choice([16, 32, 64]))
        got = grouped_matmul(x, w, s, config=GroupGemmConfig(bm, bn, bk))
        occ = int(splits.sum())
        np.testing.assert_allclose(
            np.asarray(got[:occ]), np.asarray(want[:occ]),
            atol=2e-4, rtol=2e-4,
            err_msg=f"trial {trial}: e={e} splits={splits.tolist()} "
                    f"tiles=({bm},{bn},{bk})",
        )
        assert not np.any(np.asarray(got[occ:])), "trailing rows must be 0"
