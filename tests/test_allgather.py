"""AllGather kernels vs `jax.lax.all_gather` golden (reference test shape:
``test_fast_allgather.py`` / ``test_ag_small_msg.py`` — golden via
``torch.distributed.all_gather_into_tensor``)."""

import jax
import jax.numpy as jnp
import pytest

from triton_distributed_tpu.comm import AllGatherMethod, all_gather
from triton_distributed_tpu.core.mesh import TP_AXIS, shard
from triton_distributed_tpu.core.utils import assert_allclose, rand_tensor

METHODS = [
    AllGatherMethod.PUSH_1SHOT,
    AllGatherMethod.RING_1D,
    AllGatherMethod.RING_BIDIR,
]


@pytest.mark.parametrize("method", METHODS)
@pytest.mark.parametrize("shape,dtype", [
    ((16, 128), jnp.float32),
    ((64, 256), jnp.bfloat16),
])
def test_all_gather_matches_golden(mesh8, method, shape, dtype):
    x = rand_tensor(shape, dtype)
    xs = shard(mesh8, x, TP_AXIS)
    out = all_gather(xs, mesh8, TP_AXIS, method=method)
    assert out.shape == x.shape
    assert_allclose(out, x, name=f"allgather-{method.value}")


def test_all_gather_auto(mesh8):
    x = rand_tensor((32, 128), jnp.float32)
    out = all_gather(shard(mesh8, x, TP_AXIS), mesh8, TP_AXIS)
    assert_allclose(out, x, name="allgather-auto")


@pytest.mark.parametrize("method", METHODS)
def test_all_gather_multi_axis_mesh(method):
    """On a {"dp":2,"tp":4} mesh, tp-collectives must stay inside each dp
    replica: Team translates tp-rank -> logical device id, so dp row 1's
    pushes must land on devices 4-7, never 0-3."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.core.mesh import make_mesh

    mesh = make_mesh({"dp": 2, "tp": 4})
    x = rand_tensor((32, 128), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("tp")))
    out = all_gather(xs, mesh, "tp", method=method)
    assert_allclose(out, x, name=f"allgather-multiaxis-{method.value}")


def test_all_gather_single_device():
    from triton_distributed_tpu.core.mesh import make_mesh

    x = rand_tensor((8, 128), jnp.float32)
    m = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    assert all_gather(x, m, TP_AXIS) is x


def test_auto_method_selection():
    """Pin the size/rank heuristic (VERDICT round-1 weak #8: thresholds
    must be behavior-tested, not just asserted in comments)."""
    from triton_distributed_tpu.comm.allgather import (
        choose_method, resolve_method,
    )

    # tiny shards and 2-rank rings always take the one-shot push
    assert choose_method(4 * 1024, 8) == AllGatherMethod.PUSH_1SHOT
    assert choose_method(64 * 1024 * 1024, 2) == AllGatherMethod.PUSH_1SHOT
    # large shards ride the bidirectional ring
    assert choose_method(64 * 1024 * 1024, 8) == AllGatherMethod.RING_BIDIR
    # resolve: AUTO applies the heuristic from shape x dtype ...
    big = resolve_method(AllGatherMethod.AUTO, (4096, 4096), jnp.bfloat16, 8)
    assert big == AllGatherMethod.RING_BIDIR
    small = resolve_method(AllGatherMethod.AUTO, (128, 128), jnp.bfloat16, 8)
    assert small == AllGatherMethod.PUSH_1SHOT
    # ... and explicit choices pass through untouched
    assert resolve_method(
        AllGatherMethod.RING_1D, (4096, 4096), jnp.bfloat16, 8
    ) == AllGatherMethod.RING_1D
