"""MoE MLP layer (TP and EP strategies) vs a dense per-token golden —
the analogue of the reference's ep_a2a_layer / MoE layer tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.all_to_all import AllToAllConfig
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.layers.moe import MoEMLP


def _golden(x, router, w_up, w_dn, top_k):
    """Dense per-token reference with renormalized softmax top-k."""
    probs = jax.nn.softmax(x @ router, axis=-1)
    top_w, top_e = jax.lax.top_k(probs, top_k)
    top_w = top_w / top_w.sum(-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        for j in range(top_k):
            e = int(top_e[i, j])
            h = jax.nn.silu(x[i] @ w_up[e])
            out[i] += float(top_w[i, j]) * np.asarray(h @ w_dn[e])
    return out


def _setup(n, t, hid, ffn, e, seed):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    w_up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    return x, router, w_up, w_dn


@pytest.mark.parametrize("n", [2, 4])
def test_moe_tp_forward(n):
    t, hid, ffn, e, k = 8, 32, 16 * n, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=n)
    params = layer.shard_params_tp(router, w_up, w_dn)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_tp(params, xs)
    assert out.shape == x.shape
    want = _golden(x, router, w_up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3), (
        np.abs(np.asarray(jax.device_get(out)) - want).max()
    )


@pytest.mark.parametrize("n", [2, 4])
def test_moe_ep_forward(n):
    t, hid, ffn, e, k = 8, 32, 16, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=10 + n)
    params = layer.shard_params_ep(router, w_up, w_dn)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_ep(params, xs, a2a_config=AllToAllConfig(chunk=8))
    assert out.shape == x.shape
    want = _golden(x, router, w_up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3), (
        np.abs(np.asarray(jax.device_get(out)) - want).max()
    )


def test_moe_ep_fp8_wire_parity():
    """fp8_wire=True ships e4m3 + scale sidecars on BOTH A2A hops and must
    agree with the bf16 wire within fp8 quantization tolerance on the
    8-mesh (VERDICT next #7; reference production A2A configuration)."""
    n, t, hid, ffn, e, k = 8, 16, 128, 32, 16, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=77)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    cfg = AllToAllConfig(chunk=8)

    outs = {}
    for fp8 in (False, True):
        layer = MoEMLP(mesh, num_experts=e, top_k=k, fp8_wire=fp8)
        params = layer.shard_params_ep(router, w_up, w_dn)
        outs[fp8] = np.asarray(jax.device_get(
            layer.forward_ep(params, xs, a2a_config=cfg)
        ))
    # e4m3 has ~2 decimal digits; both hops quantize, so tolerance is a
    # few percent of the activations' scale
    np.testing.assert_allclose(outs[True], outs[False], rtol=0.12,
                               atol=0.12)
    # and the fp8 path still matches the dense golden loosely
    want = _golden(x, router, w_up, w_dn, k)
    np.testing.assert_allclose(outs[True], want, rtol=0.15, atol=0.15)


def test_moe_fp8_wire_auto_policy():
    """fp8_wire="auto" enables the codec by WIRE CLASS (VERDICT r4 next
    #8): off on ICI axes (the measured net win there is negative), on
    for DCN axes (named by convention or actually spanning processes).
    On an ICI mesh the auto forward must be BIT-identical to
    fp8_wire=False — the codec never ran."""
    from triton_distributed_tpu.core import mesh as mesh_lib

    n, t, hid, ffn, e, k = 4, 16, 128, 32, 8, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    # policy resolution per wire class
    assert MoEMLP(mesh, num_experts=e, fp8_wire="auto",
                  ).fp8_wire_enabled() is False          # single-host ICI
    dcn_mesh = make_mesh({"dcn_ep": 2, TP_AXIS: 2},
                         devices=jax.devices()[:4])
    assert mesh_lib.wire_class(dcn_mesh, "dcn_ep") == "dcn"
    assert MoEMLP(dcn_mesh, num_experts=e, axis="dcn_ep",
                  fp8_wire="auto").fp8_wire_enabled() is True
    assert MoEMLP(mesh, num_experts=e, fp8_wire=True).fp8_wire_enabled()
    with pytest.raises(ValueError, match="fp8_wire"):
        MoEMLP(mesh, num_experts=e, fp8_wire="always")

    # bit-identical to the bf16 wire on ICI (codec skipped, not merely
    # accurate)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=91)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    cfg = AllToAllConfig(chunk=8)
    outs = {}
    for wire in (False, "auto"):
        layer = MoEMLP(mesh, num_experts=e, top_k=k, fp8_wire=wire)
        params = layer.shard_params_ep(router, w_up, w_dn)
        outs[wire] = np.asarray(jax.device_get(
            layer.forward_ep(params, xs, a2a_config=cfg)
        ))
    np.testing.assert_array_equal(outs["auto"], outs[False])


def test_moe_ep_fp8_wire_gradients_flow():
    """The quantized wire must NOT freeze training: the u8 transport is
    custom-vjp'd with a straight-through estimator, so expert-weight
    gradients under fp8_wire=True stay close to the bf16-wire gradients
    (a bitcast path would silently return exact zeros)."""
    n, t, hid, ffn, e, k = 4, 8, 64, 32, 8, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=88)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    cfg = AllToAllConfig(chunk=8)

    grads = {}
    for fp8 in (False, True):
        layer = MoEMLP(mesh, num_experts=e, top_k=k, fp8_wire=fp8)
        params = layer.shard_params_ep(router, w_up, w_dn)

        def loss(p, x_):
            out = layer.forward_ep(p, x_, a2a_config=cfg)
            return jnp.mean(jnp.square(out.astype(jnp.float32)))

        g = jax.grad(loss)(params, xs)
        grads[fp8] = {
            "w_up": np.asarray(jax.device_get(g.w_up), np.float32),
            "w_dn": np.asarray(jax.device_get(g.w_dn), np.float32),
            "router": np.asarray(jax.device_get(g.router), np.float32),
        }
    for name in ("w_up", "w_dn", "router"):
        ref = grads[False][name]
        got = grads[True][name]
        assert np.abs(got).max() > 0, f"{name} gradient frozen under fp8"
        # straight-through: grads agree up to the fp8 forward error
        np.testing.assert_allclose(
            got, ref, atol=0.15 * np.abs(ref).max() + 1e-6, rtol=0.5,
        )


def test_moe_fp8_wire_bytes_halved():
    """The packed u8 wire message is ~half the bf16 payload bytes."""
    from triton_distributed_tpu.layers.moe import _FP8_SIDECAR, _pack_fp8

    h = 7168
    x = jnp.asarray(np.random.default_rng(0).standard_normal((4, h)),
                    jnp.bfloat16)
    packed = _pack_fp8(x)
    assert packed.dtype == jnp.uint8
    bf16_bytes = h * 2
    fp8_bytes = packed.shape[-1]
    assert fp8_bytes == h + _FP8_SIDECAR
    assert fp8_bytes / bf16_bytes < 0.51


def test_moe_model_fp8_wire_prefill_parity(mesh8):
    """Qwen3-MoE under EP serving with ``moe_fp8_wire`` produces logits
    within fp8 tolerance of the bf16-wire engine on the 8-mesh (VERDICT
    next #7 done criterion)."""
    import dataclasses

    from triton_distributed_tpu.models import ModelConfig, Qwen3, init_cache

    cfg = ModelConfig(
        num_layers=1, hidden=128, intermediate=256, num_heads=8,
        num_kv_heads=8, head_dim=32, vocab=128, max_length=64,
        dtype=jnp.float32, num_experts=8, top_k=2, moe_intermediate=32,
        moe_strategy="ep",
    )
    mesh = mesh8
    params = Qwen3(cfg, mesh).init(jax.random.key(41), scale=0.05)
    ids = jax.random.randint(jax.random.key(42), (2, 16), 0, cfg.vocab)

    logits = {}
    for fp8 in (False, True):
        model = Qwen3(dataclasses.replace(cfg, moe_fp8_wire=fp8), mesh)
        cache = init_cache(mesh, cfg.num_layers, 2, cfg.num_kv_heads,
                           cfg.max_length, cfg.head_dim, cfg.dtype)
        out, _ = jax.jit(model.prefill)(params, cache, ids)
        logits[fp8] = np.asarray(jax.device_get(out))
    diff = np.abs(logits[True] - logits[False]).max()
    scale = np.abs(logits[False]).max()
    assert diff <= 0.08 * scale + 1e-3, (diff, scale)


def test_moe_tp_ep_agree():
    """Both parallel strategies compute the same function."""
    n, t, hid, ffn, e, k = 4, 8, 32, 16, 8, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=99)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out_tp = layer.forward_tp(layer.shard_params_tp(router, w_up, w_dn), xs)
    out_ep = layer.forward_ep(layer.shard_params_ep(router, w_up, w_dn), xs,
                              a2a_config=AllToAllConfig(chunk=8))
    assert np.allclose(
        np.asarray(jax.device_get(out_tp)),
        np.asarray(jax.device_get(out_ep)), atol=2e-4, rtol=2e-4,
    )


def _golden_swiglu(x, router, gate, up, w_dn, top_k):
    """Dense per-token reference with SwiGLU experts."""
    probs = jax.nn.softmax(x @ router, axis=-1)
    top_w, top_e = jax.lax.top_k(probs, top_k)
    top_w = top_w / top_w.sum(-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        for j in range(top_k):
            e = int(top_e[i, j])
            h = jax.nn.silu(x[i] @ gate[e]) * (x[i] @ up[e])
            out[i] += float(top_w[i, j]) * np.asarray(h @ w_dn[e])
    return out


@pytest.mark.parametrize("n", [2, 4])
def test_moe_tp_forward_swiglu(n):
    """SwiGLU experts (Qwen3-MoE layout: fused rank-blocked [gate_r|up_r])
    through the TP path vs the dense gated golden."""
    t, hid, ffn, e, k = 8, 32, 8 * n, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k, swiglu=True)
    rng = np.random.default_rng(50 + n)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    gate = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    params = layer.shard_params_tp(
        router, layer.fuse_expert_gate_up(gate, up), w_dn
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_tp(params, xs)
    want = _golden_swiglu(x, router, gate, up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3)
    # the replicated (decode) path computes the same function
    out_rep = layer.forward_replicated(params, x)
    assert np.allclose(np.asarray(jax.device_get(out_rep)), want,
                       atol=2e-3, rtol=2e-3)


def test_moe_ep_forward_swiglu():
    """SwiGLU experts through the EP dispatch/combine path (plain [gate|up]
    fusing: experts sharded, F local)."""
    n, t, hid, ffn, e, k = 4, 8, 32, 16, 8, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k, swiglu=True, axis=TP_AXIS)
    rng = np.random.default_rng(60)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    gate = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    params = layer.shard_params_ep(
        router, layer.fuse_expert_gate_up(gate, up, ep=True), w_dn
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_ep(params, xs, a2a_config=AllToAllConfig(chunk=8))
    want = _golden_swiglu(x, router, gate, up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3)


def test_pack_fp8_pallas_kernel_matches_xla():
    """The fused one-pass Pallas pack must produce the same wire message
    as the XLA pack it replaces: same shape, same decoded values, zero
    sidecar padding.  (On real TPU the bytes are bit-identical —
    verified on-chip; CPU interpret mode fuses the divide+cast chain
    differently and may differ in the last f8/scale ulp, so this test
    holds the DECODED round-trip to that tolerance.)"""
    import numpy as np

    from triton_distributed_tpu.layers.moe import (
        _FP8_SIDECAR, _build_pack_fp8, _pack_fp8_xla, _unpack_fp8,
    )

    t, h = 256, 256
    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((t, h)) * 0.5, jnp.bfloat16
    )
    got = np.asarray(_build_pack_fp8(t, h)(x))
    want = np.asarray(_pack_fp8_xla(x))
    assert got.shape == want.shape == (t, h + _FP8_SIDECAR)
    # sidecar padding bytes beyond the 4 scale bytes are zero
    assert not np.any(got[:, h + 4:])
    dec_got = np.asarray(_unpack_fp8(jnp.asarray(got), h, jnp.float32))
    dec_want = np.asarray(_unpack_fp8(jnp.asarray(want), h, jnp.float32))
    # within one e4m3 quantum of each other (2^-3 relative at the row max)
    np.testing.assert_allclose(dec_got, dec_want, rtol=0.15, atol=1e-6)
    # and both round-trip the input to fp8 accuracy
    np.testing.assert_allclose(dec_got, np.asarray(x, np.float32),
                               rtol=0.1, atol=0.05)
    # zero-amplitude rows still produce a valid (tiny) scale, not NaN/inf
    x0 = jnp.zeros((t, h), jnp.bfloat16)
    back = _unpack_fp8(_build_pack_fp8(t, h)(x0), h, jnp.bfloat16)
    assert np.all(np.asarray(back) == 0)
