"""MoE MLP layer (TP and EP strategies) vs a dense per-token golden —
the analogue of the reference's ep_a2a_layer / MoE layer tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.all_to_all import AllToAllConfig
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.layers.moe import MoEMLP


def _golden(x, router, w_up, w_dn, top_k):
    """Dense per-token reference with renormalized softmax top-k."""
    probs = jax.nn.softmax(x @ router, axis=-1)
    top_w, top_e = jax.lax.top_k(probs, top_k)
    top_w = top_w / top_w.sum(-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        for j in range(top_k):
            e = int(top_e[i, j])
            h = jax.nn.silu(x[i] @ w_up[e])
            out[i] += float(top_w[i, j]) * np.asarray(h @ w_dn[e])
    return out


def _setup(n, t, hid, ffn, e, seed):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    w_up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    return x, router, w_up, w_dn


@pytest.mark.parametrize("n", [2, 4])
def test_moe_tp_forward(n):
    t, hid, ffn, e, k = 8, 32, 16 * n, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=n)
    params = layer.shard_params_tp(router, w_up, w_dn)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_tp(params, xs)
    assert out.shape == x.shape
    want = _golden(x, router, w_up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3), (
        np.abs(np.asarray(jax.device_get(out)) - want).max()
    )


@pytest.mark.parametrize("n", [2, 4])
def test_moe_ep_forward(n):
    t, hid, ffn, e, k = 8, 32, 16, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=10 + n)
    params = layer.shard_params_ep(router, w_up, w_dn)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_ep(params, xs, a2a_config=AllToAllConfig(chunk=8))
    assert out.shape == x.shape
    want = _golden(x, router, w_up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3), (
        np.abs(np.asarray(jax.device_get(out)) - want).max()
    )


def test_moe_tp_ep_agree():
    """Both parallel strategies compute the same function."""
    n, t, hid, ffn, e, k = 4, 8, 32, 16, 8, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k)
    x, router, w_up, w_dn = _setup(n, t, hid, ffn, e, seed=99)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out_tp = layer.forward_tp(layer.shard_params_tp(router, w_up, w_dn), xs)
    out_ep = layer.forward_ep(layer.shard_params_ep(router, w_up, w_dn), xs,
                              a2a_config=AllToAllConfig(chunk=8))
    assert np.allclose(
        np.asarray(jax.device_get(out_tp)),
        np.asarray(jax.device_get(out_ep)), atol=2e-4, rtol=2e-4,
    )


def _golden_swiglu(x, router, gate, up, w_dn, top_k):
    """Dense per-token reference with SwiGLU experts."""
    probs = jax.nn.softmax(x @ router, axis=-1)
    top_w, top_e = jax.lax.top_k(probs, top_k)
    top_w = top_w / top_w.sum(-1, keepdims=True)
    out = np.zeros_like(np.asarray(x))
    for i in range(x.shape[0]):
        for j in range(top_k):
            e = int(top_e[i, j])
            h = jax.nn.silu(x[i] @ gate[e]) * (x[i] @ up[e])
            out[i] += float(top_w[i, j]) * np.asarray(h @ w_dn[e])
    return out


@pytest.mark.parametrize("n", [2, 4])
def test_moe_tp_forward_swiglu(n):
    """SwiGLU experts (Qwen3-MoE layout: fused rank-blocked [gate_r|up_r])
    through the TP path vs the dense gated golden."""
    t, hid, ffn, e, k = 8, 32, 8 * n, 2 * n, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k, swiglu=True)
    rng = np.random.default_rng(50 + n)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    gate = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    params = layer.shard_params_tp(
        router, layer.fuse_expert_gate_up(gate, up), w_dn
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_tp(params, xs)
    want = _golden_swiglu(x, router, gate, up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3)
    # the replicated (decode) path computes the same function
    out_rep = layer.forward_replicated(params, x)
    assert np.allclose(np.asarray(jax.device_get(out_rep)), want,
                       atol=2e-3, rtol=2e-3)


def test_moe_ep_forward_swiglu():
    """SwiGLU experts through the EP dispatch/combine path (plain [gate|up]
    fusing: experts sharded, F local)."""
    n, t, hid, ffn, e, k = 4, 8, 32, 16, 8, 2
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    layer = MoEMLP(mesh, num_experts=e, top_k=k, swiglu=True, axis=TP_AXIS)
    rng = np.random.default_rng(60)
    x = jnp.asarray(rng.standard_normal((n * t, hid)).astype(np.float32) * 0.3)
    router = jnp.asarray(rng.standard_normal((hid, e)).astype(np.float32))
    gate = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    up = jnp.asarray(rng.standard_normal((e, hid, ffn)).astype(np.float32) * 0.1)
    w_dn = jnp.asarray(rng.standard_normal((e, ffn, hid)).astype(np.float32) * 0.1)
    params = layer.shard_params_ep(
        router, layer.fuse_expert_gate_up(gate, up, ep=True), w_dn
    )
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    out = layer.forward_ep(params, xs, a2a_config=AllToAllConfig(chunk=8))
    want = _golden_swiglu(x, router, gate, up, w_dn, k)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=2e-3, rtol=2e-3)
