"""The claims-vs-record loop: scripts/check_perf_claims.py must hold the
documented perf claims against the newest driver capture.

Round-5 restructure (VERDICT r4 next #1): the PRIMARY claims are
absolute throughput floors + physical ceilings (hard failures); ratio
spreads are secondary warnings.  These tests pin each behavior class.
"""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "check_perf_claims", os.path.join(REPO, "scripts", "check_perf_claims.py")
)
cpc = importlib.util.module_from_spec(spec)
spec.loader.exec_module(cpc)


def _line(**kw):
    rec = {"metric": "group_gemm_t8192_k7168_n2048_e8", "value": 150.0,
           "unit": "TFLOP/s", "vs_baseline": 1.05}
    rec.update(kw)
    return json.dumps(rec)


def test_repo_records_consistent():
    """The committed newest BENCH record satisfies the claims registry."""
    assert cpc.check(REPO) == 0


def test_no_floor_asserts_a_loss():
    """No PRIMARY claim may encode 'we might lose': floors are positive
    absolutes, and deterministic ratio claims sit above 1.0 (VERDICT r4
    weak #3 — a sub-1.0 lower bound cannot fail on regression)."""
    for prefix, claim in cpc.CLAIMS.items():
        floor = claim.get("floor")
        assert floor is None or floor > 0, prefix
        exact = claim.get("exact_ratio")
        if exact is not None:
            assert exact[0] >= 1.0, prefix
        # ratio spreads are secondary (warn-only); they are allowed to
        # document sub-1.0 observed draws, so no assertion on them here
        assert "floor" in claim or "value_max" in claim, (
            f"{prefix}: every metric needs a hard primary claim"
        )


def test_parses_driver_envelope(tmp_path):
    env = {"n": 9, "rc": 0, "tail": _line() + "\n"}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 0


def test_floor_violation_fails(tmp_path):
    (tmp_path / "BENCH_r09.json").write_text(_line(value=90.0) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_physical_ceiling_rejects_impossible_value(tmp_path):
    (tmp_path / "BENCH_r09.json").write_text(_line(value=260.0) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_impossible_baseline_fails_capture(tmp_path):
    """A baseline absolute above the chip's physical peak must fail the
    capture (the r04 '1,062 GB/s decode baseline on 819 GB/s HBM' class)."""
    rec = {"metric": "decode_attn_b8_h32_hk8_s8192_d128", "value": 750.0,
           "unit": "GB/s", "vs_baseline": 0.98, "baseline_value": 1062.0}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 1
    rec["baseline_value"] = 790.0
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 0


def test_ratio_spread_drift_warns_not_fails(tmp_path, capsys):
    (tmp_path / "BENCH_r09.json").write_text(_line(vs_baseline=0.5) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    assert "WARNING" in capsys.readouterr().out


def test_deterministic_ratio_drift_fails(tmp_path):
    rec = {"metric": "moe_ep_a2a_fp8_wire_bytes_h7168", "value": 7296,
           "unit": "bytes/token/hop", "vs_baseline": 1.90}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_missing_claimed_metric_fails_full_records(tmp_path):
    """A full-sweep record (bench_sweep_complete sentinel present)
    missing a binding claimed metric must fail: a crashed bench mode or
    a renamed metric would otherwise leave its claims silently
    unchecked.  Targeted records (no sentinel) are exempt, and a
    driver envelope with nonzero rc fails outright."""
    sentinel = json.dumps({"metric": "bench_sweep_complete", "value": 1,
                           "unit": "bool"})
    (tmp_path / "BENCH_r09.json").write_text(_line() + "\n" + sentinel + "\n")
    assert cpc.check(str(tmp_path)) == 1  # all other claims missing
    # a targeted record without the sentinel is exempt from completeness
    (tmp_path / "BENCH_r09.json").write_text(_line() + "\n")
    assert cpc.check(str(tmp_path)) == 0
    # sentinel value 0 = a mode crashed mid-sweep: hard failure
    crashed = json.dumps({"metric": "bench_sweep_complete", "value": 0,
                          "unit": "bool"})
    (tmp_path / "BENCH_r09.json").write_text(_line() + "\n" + crashed + "\n")
    assert cpc.check(str(tmp_path)) == 1
    # a driver envelope recording a nonzero bench exit code fails
    env = {"n": 9, "rc": 1, "tail": _line() + "\n"}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 1


def test_decode_claim_prefix_is_tp_agnostic(tmp_path):
    """bench.py emits qwen_decode_step_b{batch}_tp{ntp}_...: a multi-chip
    capture (tp>1) must satisfy the same claim rather than trip a
    spurious MISSING failure (ADVICE r5 low #2)."""
    for ntp in (1, 4, 8):
        rec = {"metric": f"qwen_decode_step_b128_tp{ntp}_psum_vs_ar",
               "value": 5.0, "unit": "ms/step (ar mode)",
               "vs_baseline": 1.05}
        (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
        assert cpc.check(str(tmp_path)) == 0, ntp
        rec["value"] = 25.0   # and the value_max claim still binds
        (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
        assert cpc.check(str(tmp_path)) == 1, ntp


def test_truncated_but_emitted_metric_warns_not_fails(tmp_path, capsys):
    """A healthy full-sweep capture whose HEAD lines were tail-truncated by
    the driver envelope must not read as 'bench mode crashed': the sweep
    sentinel records every emitted metric name, and a claim present there
    but absent from the surviving lines is a WARNING (value unchecked),
    while a name absent from BOTH still fails hard (ADVICE r5 medium #1)."""
    emitted = [p + "_suffix" for p in cpc.CLAIMS]
    sentinel = {"metric": "bench_sweep_complete", "value": 1, "unit": "bool",
                "emitted": emitted}
    body = _line() + "\n" + json.dumps(sentinel) + "\n"
    (tmp_path / "BENCH_r09.json").write_text(body)
    assert cpc.check(str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "tail-truncated" in out and "WARNING" in out
    # a claim missing from the emitted list too is still a hard failure
    sentinel["emitted"] = [p + "_suffix" for p in cpc.CLAIMS
                           if not p.startswith("flash_attn")]
    body = _line() + "\n" + json.dumps(sentinel) + "\n"
    (tmp_path / "BENCH_r09.json").write_text(body)
    assert cpc.check(str(tmp_path)) == 1


def test_legacy_truncated_envelope_warns_not_fails(tmp_path, capsys):
    """Pre-'emitted' full-sweep ENVELOPES (the committed BENCH_r05 shape:
    rc=0, sentinel=1, head lines truncated) warn instead of reporting a
    phantom crash; a raw (untruncated) record with the same legacy
    sentinel still fails hard on absence."""
    legacy = json.dumps({"metric": "bench_sweep_complete", "value": 1,
                         "unit": "bool"})
    body = _line() + "\n" + legacy + "\n"
    env = {"n": 9, "rc": 0, "tail": body}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 0
    assert "absent from the truncated envelope tail" in \
        capsys.readouterr().out
    (tmp_path / "BENCH_r09.json").write_text(body)   # raw: never truncated
    assert cpc.check(str(tmp_path)) == 1


def test_since_round_scopes_old_records(tmp_path):
    """A claim introduced in round N must not fail a round N-1 record."""
    line = _line(value=90.0)
    (tmp_path / "BENCH_r03.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 0
    (tmp_path / "BENCH_r04.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 1


# ---------------------------------------------------------------------------
# local-record plumbing (VERDICT r5 next #1): bench.py persists the full
# JSONL stream; the gate prefers it and treats the envelope tail as a
# fallback that fails loudly on detectable truncation


def test_local_record_preferred_over_envelope(tmp_path):
    """A committed BENCH_LOCAL_rNN.jsonl with round >= the envelope's is
    the gated record: a value the envelope truncated away still binds."""
    # envelope says 150 (passing); local record says 90 (floor breach):
    # the local record must win and fail the gate ON THE VALUE (sentinel
    # included so the failure comes from the floor check, not the
    # local-record completeness gate)
    sentinel = json.dumps({"metric": "bench_sweep_complete", "value": 1,
                           "unit": "bool", "emitted": list(cpc.CLAIMS)})
    env = {"n": 9, "rc": 0, "tail": _line(value=150.0) + "\n"}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 0
    (tmp_path / "BENCH_LOCAL_r09.jsonl").write_text(
        _line(value=90.0) + "\n" + sentinel + "\n")
    assert cpc.check(str(tmp_path)) == 1
    (tmp_path / "BENCH_LOCAL_r09.jsonl").write_text(
        _line(value=150.0) + "\n" + sentinel + "\n")
    assert cpc.check(str(tmp_path)) == 0   # same record, passing value
    (tmp_path / "BENCH_LOCAL_r09.jsonl").write_text(
        _line(value=90.0) + "\n" + sentinel + "\n")
    # an OLDER local record does not shadow a newer envelope
    (tmp_path / "BENCH_r10.json").write_text(
        json.dumps({"n": 10, "rc": 0, "tail": _line(value=150.0) + "\n"}))
    assert cpc.check(str(tmp_path)) == 0


def test_truncated_envelope_fails_loudly_without_local_record(
        tmp_path, capsys):
    """From round >= 6 (bench.py writes the local record), an envelope
    whose tail starts mid-line (detectable truncation) without a
    committed local record is a HARD failure, not a warning — the
    complete stream exists on the bench host and must be committed."""
    truncated_tail = '"value": 150.0, "unit": "TFLOP/s"}\n' + _line() + "\n"
    env = {"n": 9, "rc": 0, "tail": truncated_tail}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 1
    assert "truncated" in capsys.readouterr().out
    # the committed local record for the same round resolves it (a real
    # local record always ends with the auto sweep's sentinel)
    sentinel = json.dumps({"metric": "bench_sweep_complete", "value": 1,
                           "unit": "bool", "emitted": list(cpc.CLAIMS)})
    (tmp_path / "BENCH_LOCAL_r09.jsonl").write_text(
        _line() + "\n" + sentinel + "\n")
    assert cpc.check(str(tmp_path)) == 0
    # pre-round-6 envelopes (no local record ever existed) keep the
    # legacy warning path — the committed r05 shape must not turn red
    (tmp_path / "BENCH_r09.json").unlink()
    (tmp_path / "BENCH_LOCAL_r09.jsonl").unlink()
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"n": 5, "rc": 0, "tail": truncated_tail}))
    assert cpc.check(str(tmp_path)) == 0


def test_local_record_keeps_crash_gates(tmp_path, capsys):
    """Preferring the local record must not drop the crash gates: a
    local stream without the sweep sentinel is a sweep that died
    mid-run (bench.py only tees in `auto` mode, which always ends with
    the sentinel), and the same-round envelope's nonzero rc still
    binds."""
    # (a) local record without the sentinel: incomplete — hard failure
    (tmp_path / "BENCH_LOCAL_r09.jsonl").write_text(_line() + "\n")
    assert cpc.check(str(tmp_path)) == 1
    assert "no bench_sweep_complete sentinel" in capsys.readouterr().out
    # a healthy local stream (sentinel listing every claim as emitted ->
    # absence downgrades to truncation-free warnings is NOT possible for
    # raw records, so list them all as real lines): build a full record
    lines = [_line()]
    sentinel = {"metric": "bench_sweep_complete", "value": 1,
                "unit": "bool", "emitted": list(cpc.CLAIMS)}
    body = "\n".join(lines + [json.dumps(sentinel)]) + "\n"
    # (b) the same-round envelope's rc still binds even when the local
    # record itself carries a green sentinel
    (tmp_path / "BENCH_LOCAL_r09.jsonl").write_text(body)
    (tmp_path / "BENCH_r09.json").write_text(
        json.dumps({"n": 9, "rc": 137, "tail": ""}))
    assert cpc.check(str(tmp_path)) == 1
    assert "exit code 137" in capsys.readouterr().out


def test_floor_dip_with_passing_retry_warns_not_fails(tmp_path, capsys):
    """The gate owns the retry decision (ADVICE r5 low #3): bench.py
    publishes the FIRST draw plus ``retry_value``; a dip whose retry
    clears the floor is a transient-throttle warning, a double miss is
    a hard regression."""
    (tmp_path / "BENCH_r09.json").write_text(
        _line(value=90.0, retry_value=150.0, attempts=2) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "WARNING" in out and "retry" in out
    (tmp_path / "BENCH_r09.json").write_text(
        _line(value=90.0, retry_value=95.0, attempts=2) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_slice_gated_overlap_claim_binds_on_multi_device_records(tmp_path):
    """The overlap_collective >= 0.9-hidden claim (VERDICT r5 next #7)
    keys on the record's ``devices`` field: a synthetic multi-device
    capture is gated hard, a single-device record (or one without the
    field) is exempt — the first real slice run gates instead of merely
    logging."""
    def rec(value, devices):
        d = {"metric": "overlap_hidden_pct_ag_gemm_m4096_tp4",
             "value": value, "unit": "fraction of smaller phase hidden"}
        if devices is not None:
            d["devices"] = devices
        return json.dumps(d)

    # multi-device capture below the floor: hard failure
    (tmp_path / "BENCH_r09.json").write_text(rec(0.55, 4) + "\n")
    assert cpc.check(str(tmp_path)) == 1
    # multi-device capture meeting the target: green
    (tmp_path / "BENCH_r09.json").write_text(rec(0.93, 4) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    # single-device / field-less records are exempt (the tp=1 smoke
    # shape has no wire to hide)
    (tmp_path / "BENCH_r09.json").write_text(rec(0.55, 1) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    (tmp_path / "BENCH_r09.json").write_text(rec(0.55, None) + "\n")
    assert cpc.check(str(tmp_path)) == 0


def test_slice_decode_mode_ratio_binds_on_multi_device_records(tmp_path):
    """The decode-mode psum/ar ratio is informational at tp=1
    (definitional parity) but HARD on a slice: the fast-AR path losing
    to XLA's psum on a real mesh is a regression, not spread noise."""
    def rec(vb, devices):
        return json.dumps({
            "metric": f"qwen_decode_step_b128_tp{devices}_psum_vs_ar",
            "value": 5.0, "unit": "ms/step (ar mode)",
            "vs_baseline": vb, "devices": devices,
        })

    (tmp_path / "BENCH_r09.json").write_text(rec(0.80, 4) + "\n")
    assert cpc.check(str(tmp_path)) == 1
    (tmp_path / "BENCH_r09.json").write_text(rec(1.25, 4) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    # at one device the same ratio only warns (ratio_spread)
    (tmp_path / "BENCH_r09.json").write_text(rec(0.80, 1) + "\n")
    assert cpc.check(str(tmp_path)) == 0


def test_interpret_capture_exempt_from_hard_claims(tmp_path, capsys):
    """bench.py marks CPU-interpret captures (functional smoke, not
    timing) with ``interpret: true``; the gate warns instead of
    hard-failing simulated numbers — an 8-virtual-device interpret run
    of overlap_collective must not read as 'the distributed mode
    regressed'."""
    rec = {"metric": "overlap_hidden_pct_ag_gemm_m64_tp8", "value": 0.1,
           "unit": "fraction of smaller phase hidden", "devices": 8,
           "interpret": True}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "interpret-mode capture" in out and "WARNING" in out
    # the same numbers WITHOUT the marker still gate hard
    rec["interpret"] = False
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_slice_claim_completeness_keys_on_sentinel_devices(tmp_path):
    """A FULL-sweep record must carry the slice-gated metrics only when
    the sweep actually ran on a slice: the sentinel's ``devices`` field
    scopes the MISSING check."""
    body_lines = [_line()]
    emitted = [p + "_x" for p in cpc.CLAIMS
               if "overlap_hidden_pct_ag_gemm" not in p]

    def sentinel(devices):
        return json.dumps({"metric": "bench_sweep_complete", "value": 1,
                           "unit": "bool", "emitted": emitted,
                           "devices": devices})

    # single-chip sweep: the slice-only metric's absence is expected
    (tmp_path / "BENCH_r09.json").write_text(
        "\n".join(body_lines + [sentinel(1)]) + "\n")
    rc = cpc.check(str(tmp_path))
    assert rc == 0, "single-chip sweep must not MISS slice-only metrics"
    # multi-chip sweep: the same absence is a crashed/renamed bench mode
    (tmp_path / "BENCH_r09.json").write_text(
        "\n".join(body_lines + [sentinel(4)]) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_bench_emit_publishes_first_draw_and_tees_local_record(
        monkeypatch, capsys):
    """bench._emit symmetry + tee: the printed value is the first draw
    (never max-of-two), the retry rides along, and every line lands in
    the open local sink byte-identical to stdout."""
    import io

    bench = _load_bench()
    sink = io.StringIO()
    monkeypatch.setattr(bench, "_LOCAL_SINK", sink)
    monkeypatch.setattr(bench, "_EMITTED", [])
    draws = iter([90.0, 150.0])

    def fake_bench():
        return {"metric": "group_gemm_t8192_k7168_n2048_e8",
                "value": next(draws), "unit": "TFLOP/s"}

    bench._emit(fake_bench)
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["value"] == 90.0          # first draw published
    assert rec["retry_value"] == 150.0   # retry attached, not substituted
    assert rec["attempts"] == 2
    assert sink.getvalue().strip().splitlines()[-1] == line
    assert bench._EMITTED == ["group_gemm_t8192_k7168_n2048_e8"]


def test_bench_local_record_path_round_numbering(monkeypatch, tmp_path):
    """TDT_BENCH_LOCAL overrides the sink path; '0' disables the tee."""
    bench = _load_bench()
    target = tmp_path / "stream.jsonl"
    monkeypatch.setenv("TDT_BENCH_LOCAL", str(target))
    monkeypatch.setattr(bench, "_LOCAL_SINK", None)
    bench._open_local_record()
    try:
        assert bench._LOCAL_SINK is not None
        bench._record_line('{"metric": "x", "value": 1}')
    finally:
        bench._LOCAL_SINK.close()
        monkeypatch.setattr(bench, "_LOCAL_SINK", None)
    assert target.read_text() == '{"metric": "x", "value": 1}\n'
    monkeypatch.setenv("TDT_BENCH_LOCAL", "0")
    bench._open_local_record()
    assert bench._LOCAL_SINK is None


_BENCH_MODULE = None


def _load_bench():
    global _BENCH_MODULE
    if _BENCH_MODULE is None:
        spec = importlib.util.spec_from_file_location(
            "bench_under_test", os.path.join(REPO, "bench.py"))
        _BENCH_MODULE = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_BENCH_MODULE)
    return _BENCH_MODULE
