"""The claims-vs-record loop: scripts/check_perf_claims.py must hold the
documented perf ranges against the newest driver capture (VERDICT round-3
weak #2 — docstrings claiming 1.05x while the record said 0.84x)."""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "check_perf_claims", os.path.join(REPO, "scripts", "check_perf_claims.py")
)
cpc = importlib.util.module_from_spec(spec)
spec.loader.exec_module(cpc)


def test_repo_records_consistent():
    """Every committed BENCH record satisfies the claims registry."""
    assert cpc.check(REPO) == 0


def test_parses_driver_envelope(tmp_path):
    env = {"n": 9, "rc": 0, "tail": json.dumps(
        {"metric": "group_gemm_t8192_k7168_n2048_e8", "value": 1.0,
         "unit": "TFLOP/s", "vs_baseline": 1.01}) + "\n"}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 0


def test_flags_drifted_claim(tmp_path):
    line = json.dumps(
        {"metric": "group_gemm_t8192_k7168_n2048_e8", "value": 1.0,
         "unit": "TFLOP/s", "vs_baseline": 0.5})
    (tmp_path / "BENCH_r09.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_since_round_scopes_old_records(tmp_path):
    """A claim introduced in round N must not fail a round N-1 record."""
    line = json.dumps(
        {"metric": "group_gemm_t8192_k7168_n2048_e8", "value": 1.0,
         "unit": "TFLOP/s", "vs_baseline": 0.6})
    (tmp_path / "BENCH_r03.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 0
    (tmp_path / "BENCH_r04.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 1
