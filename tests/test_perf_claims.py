"""The claims-vs-record loop: scripts/check_perf_claims.py must hold the
documented perf claims against the newest driver capture.

Round-5 restructure (VERDICT r4 next #1): the PRIMARY claims are
absolute throughput floors + physical ceilings (hard failures); ratio
spreads are secondary warnings.  These tests pin each behavior class.
"""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "check_perf_claims", os.path.join(REPO, "scripts", "check_perf_claims.py")
)
cpc = importlib.util.module_from_spec(spec)
spec.loader.exec_module(cpc)


def _line(**kw):
    rec = {"metric": "group_gemm_t8192_k7168_n2048_e8", "value": 150.0,
           "unit": "TFLOP/s", "vs_baseline": 1.05}
    rec.update(kw)
    return json.dumps(rec)


def test_repo_records_consistent():
    """The committed newest BENCH record satisfies the claims registry."""
    assert cpc.check(REPO) == 0


def test_no_floor_asserts_a_loss():
    """No PRIMARY claim may encode 'we might lose': floors are positive
    absolutes, and deterministic ratio claims sit above 1.0 (VERDICT r4
    weak #3 — a sub-1.0 lower bound cannot fail on regression)."""
    for prefix, claim in cpc.CLAIMS.items():
        floor = claim.get("floor")
        assert floor is None or floor > 0, prefix
        exact = claim.get("exact_ratio")
        if exact is not None:
            assert exact[0] >= 1.0, prefix
        # ratio spreads are secondary (warn-only); they are allowed to
        # document sub-1.0 observed draws, so no assertion on them here
        assert "floor" in claim or "value_max" in claim, (
            f"{prefix}: every metric needs a hard primary claim"
        )


def test_parses_driver_envelope(tmp_path):
    env = {"n": 9, "rc": 0, "tail": _line() + "\n"}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 0


def test_floor_violation_fails(tmp_path):
    (tmp_path / "BENCH_r09.json").write_text(_line(value=90.0) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_physical_ceiling_rejects_impossible_value(tmp_path):
    (tmp_path / "BENCH_r09.json").write_text(_line(value=260.0) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_impossible_baseline_fails_capture(tmp_path):
    """A baseline absolute above the chip's physical peak must fail the
    capture (the r04 '1,062 GB/s decode baseline on 819 GB/s HBM' class)."""
    rec = {"metric": "decode_attn_b8_h32_hk8_s8192_d128", "value": 750.0,
           "unit": "GB/s", "vs_baseline": 0.98, "baseline_value": 1062.0}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 1
    rec["baseline_value"] = 790.0
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 0


def test_ratio_spread_drift_warns_not_fails(tmp_path, capsys):
    (tmp_path / "BENCH_r09.json").write_text(_line(vs_baseline=0.5) + "\n")
    assert cpc.check(str(tmp_path)) == 0
    assert "WARNING" in capsys.readouterr().out


def test_deterministic_ratio_drift_fails(tmp_path):
    rec = {"metric": "moe_ep_a2a_fp8_wire_bytes_h7168", "value": 7296,
           "unit": "bytes/token/hop", "vs_baseline": 1.90}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
    assert cpc.check(str(tmp_path)) == 1


def test_missing_claimed_metric_fails_full_records(tmp_path):
    """A full-sweep record (bench_sweep_complete sentinel present)
    missing a binding claimed metric must fail: a crashed bench mode or
    a renamed metric would otherwise leave its claims silently
    unchecked.  Targeted records (no sentinel) are exempt, and a
    driver envelope with nonzero rc fails outright."""
    sentinel = json.dumps({"metric": "bench_sweep_complete", "value": 1,
                           "unit": "bool"})
    (tmp_path / "BENCH_r09.json").write_text(_line() + "\n" + sentinel + "\n")
    assert cpc.check(str(tmp_path)) == 1  # all other claims missing
    # a targeted record without the sentinel is exempt from completeness
    (tmp_path / "BENCH_r09.json").write_text(_line() + "\n")
    assert cpc.check(str(tmp_path)) == 0
    # sentinel value 0 = a mode crashed mid-sweep: hard failure
    crashed = json.dumps({"metric": "bench_sweep_complete", "value": 0,
                          "unit": "bool"})
    (tmp_path / "BENCH_r09.json").write_text(_line() + "\n" + crashed + "\n")
    assert cpc.check(str(tmp_path)) == 1
    # a driver envelope recording a nonzero bench exit code fails
    env = {"n": 9, "rc": 1, "tail": _line() + "\n"}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 1


def test_decode_claim_prefix_is_tp_agnostic(tmp_path):
    """bench.py emits qwen_decode_step_b{batch}_tp{ntp}_...: a multi-chip
    capture (tp>1) must satisfy the same claim rather than trip a
    spurious MISSING failure (ADVICE r5 low #2)."""
    for ntp in (1, 4, 8):
        rec = {"metric": f"qwen_decode_step_b128_tp{ntp}_psum_vs_ar",
               "value": 5.0, "unit": "ms/step (ar mode)",
               "vs_baseline": 1.05}
        (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
        assert cpc.check(str(tmp_path)) == 0, ntp
        rec["value"] = 25.0   # and the value_max claim still binds
        (tmp_path / "BENCH_r09.json").write_text(json.dumps(rec) + "\n")
        assert cpc.check(str(tmp_path)) == 1, ntp


def test_truncated_but_emitted_metric_warns_not_fails(tmp_path, capsys):
    """A healthy full-sweep capture whose HEAD lines were tail-truncated by
    the driver envelope must not read as 'bench mode crashed': the sweep
    sentinel records every emitted metric name, and a claim present there
    but absent from the surviving lines is a WARNING (value unchecked),
    while a name absent from BOTH still fails hard (ADVICE r5 medium #1)."""
    emitted = [p + "_suffix" for p in cpc.CLAIMS]
    sentinel = {"metric": "bench_sweep_complete", "value": 1, "unit": "bool",
                "emitted": emitted}
    body = _line() + "\n" + json.dumps(sentinel) + "\n"
    (tmp_path / "BENCH_r09.json").write_text(body)
    assert cpc.check(str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "tail-truncated" in out and "WARNING" in out
    # a claim missing from the emitted list too is still a hard failure
    sentinel["emitted"] = [p + "_suffix" for p in cpc.CLAIMS
                           if not p.startswith("flash_attn")]
    body = _line() + "\n" + json.dumps(sentinel) + "\n"
    (tmp_path / "BENCH_r09.json").write_text(body)
    assert cpc.check(str(tmp_path)) == 1


def test_legacy_truncated_envelope_warns_not_fails(tmp_path, capsys):
    """Pre-'emitted' full-sweep ENVELOPES (the committed BENCH_r05 shape:
    rc=0, sentinel=1, head lines truncated) warn instead of reporting a
    phantom crash; a raw (untruncated) record with the same legacy
    sentinel still fails hard on absence."""
    legacy = json.dumps({"metric": "bench_sweep_complete", "value": 1,
                         "unit": "bool"})
    body = _line() + "\n" + legacy + "\n"
    env = {"n": 9, "rc": 0, "tail": body}
    (tmp_path / "BENCH_r09.json").write_text(json.dumps(env))
    assert cpc.check(str(tmp_path)) == 0
    assert "absent from the truncated envelope tail" in \
        capsys.readouterr().out
    (tmp_path / "BENCH_r09.json").write_text(body)   # raw: never truncated
    assert cpc.check(str(tmp_path)) == 1


def test_since_round_scopes_old_records(tmp_path):
    """A claim introduced in round N must not fail a round N-1 record."""
    line = _line(value=90.0)
    (tmp_path / "BENCH_r03.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 0
    (tmp_path / "BENCH_r04.json").write_text(line + "\n")
    assert cpc.check(str(tmp_path)) == 1
