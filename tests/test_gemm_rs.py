"""GEMM-RS vs golden `matmul + psum-scatter` (reference ``test_gemm_rs.py``)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh, shard
from triton_distributed_tpu.core.utils import assert_allclose, rand_tensor
from triton_distributed_tpu.ops import GemmRsConfig, gemm_rs


def _golden(a, b):
    return jnp.dot(a.astype(jnp.float32), b.astype(jnp.float32))


@pytest.mark.parametrize("m,k,n,dtype", [
    (64, 256, 128, jnp.float32),
    (128, 512, 256, jnp.bfloat16),
])
def test_gemm_rs_matches_golden(mesh8, m, k, n, dtype):
    a = rand_tensor((m, k), dtype, scale=0.1)
    b = rand_tensor((k, n), dtype, scale=0.1)
    a_s = shard(mesh8, a, None, TP_AXIS)
    b_s = shard(mesh8, b, TP_AXIS)
    c = gemm_rs(a_s, b_s, mesh8, TP_AXIS,
                config=GemmRsConfig(bm=8, bn=64, bk=32))
    assert c.shape == (m, n)
    tol = 1e-3 if dtype == jnp.float32 else 1e-1
    assert_allclose(c.astype(jnp.float32), _golden(a, b).astype(c.dtype),
                    atol=tol, rtol=tol, name="gemm_rs")


def test_gemm_rs_repeat(mesh8):
    a = rand_tensor((64, 256), jnp.float32, scale=0.1)
    b = rand_tensor((256, 128), jnp.float32, scale=0.1)
    a_s = shard(mesh8, a, None, TP_AXIS)
    b_s = shard(mesh8, b, TP_AXIS)
    cfg = GemmRsConfig(bm=8, bn=64, bk=32)
    c1 = gemm_rs(a_s, b_s, mesh8, TP_AXIS, config=cfg)
    c2 = gemm_rs(a_s, b_s, mesh8, TP_AXIS, config=cfg)
    assert_allclose(c1, c2, atol=0, rtol=0, name="gemm_rs-repeat")


@pytest.mark.parametrize("nranks", [2, 3])
def test_gemm_rs_small_rings(nranks):
    mesh = make_mesh({TP_AXIS: nranks}, devices=jax.devices()[:nranks])
    m, k, n = 12 * nranks, 16 * nranks, 128
    a = rand_tensor((m, k), jnp.float32, scale=0.1)
    b = rand_tensor((k, n), jnp.float32, scale=0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS)))
    c = gemm_rs(a_s, b_s, mesh, TP_AXIS)
    assert_allclose(c, _golden(a, b).astype(c.dtype), atol=1e-3, rtol=1e-3,
                    name=f"gemm_rs-{nranks}")


def test_gemm_rs_single_device():
    mesh1 = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    a = rand_tensor((16, 128), jnp.float32)
    b = rand_tensor((128, 128), jnp.float32)
    c = gemm_rs(a, b, mesh1, TP_AXIS)
    assert_allclose(c, _golden(a, b).astype(c.dtype), atol=1e-4, rtol=1e-4)
