"""Golden tests for the local blocked Pallas matmul (`ops/matmul.py`).

Mirrors the reference's per-kernel golden strategy (SURVEY.md section 4):
compare against XLA's own jnp.matmul with f32 accumulation across shapes
that exercise block clipping (non-multiples of the default tiles) and both
dtypes the framework cares about.
"""

import jax
import jax.numpy as jnp
import pytest

from triton_distributed_tpu.ops.matmul import matmul


def _golden(a, b, out_dtype):
    return jnp.matmul(a, b, preferred_element_type=jnp.float32).astype(out_dtype)


@pytest.mark.parametrize(
    "m,k,n",
    [
        (256, 256, 256),     # single tile after clipping
        (1024, 512, 1024),   # multi-tile, exact multiples
        (384, 640, 896),     # forces clip_block on every dim
    ],
)
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_matmul_golden(m, k, n, dtype):
    ka, kb = jax.random.split(jax.random.key(0))
    a = jax.random.normal(ka, (m, k), dtype=dtype)
    b = jax.random.normal(kb, (k, n), dtype=dtype)
    got = matmul(a, b)
    want = _golden(a, b, dtype)
    assert got.dtype == want.dtype and got.shape == want.shape
    # identical f32 accumulation order is not guaranteed; tolerances scaled
    # for bf16 inputs at k<=640
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    assert jnp.allclose(got.astype(jnp.float32), want.astype(jnp.float32),
                        rtol=tol, atol=tol * 8)


def test_matmul_out_dtype():
    ka, kb = jax.random.split(jax.random.key(1))
    a = jax.random.normal(ka, (256, 256), dtype=jnp.bfloat16)
    b = jax.random.normal(kb, (256, 256), dtype=jnp.bfloat16)
    got = matmul(a, b, out_dtype=jnp.float32)
    assert got.dtype == jnp.float32
    want = _golden(a, b, jnp.float32)
    assert jnp.allclose(got, want, rtol=2e-2, atol=1e-1)


def test_matmul_shape_mismatch():
    a = jnp.zeros((128, 64), jnp.float32)
    b = jnp.zeros((128, 64), jnp.float32)
    with pytest.raises(ValueError, match="inner dims mismatch"):
        matmul(a, b)
