"""Bucketed AOT serving (VERDICT r4 next #4; reference
``tools/compile_aot.py:61-130`` signature spaces + ``link_all:470``
dispatcher): ``Engine.precompile(buckets)`` AOT-compiles prefill per
prompt-length bucket (+ the decode step), serializes next to the
weights, and a second process serves through the deserialized
executables with ZERO retraces."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.core import mesh as mesh_lib
from triton_distributed_tpu.models import Engine, ModelConfig


def _cfg():
    return ModelConfig(
        num_layers=2, hidden=128, intermediate=256, num_heads=8,
        num_kv_heads=8, head_dim=16, vocab=256, max_length=64,
        dtype=jnp.float32,
    )


def _engine(batch=2):
    mesh = mesh_lib.tp_mesh()
    return Engine.build(_cfg(), mesh, key=jax.random.key(3), batch=batch)


def _poison_jit_paths(eng):
    """Any trace/compile after AOT loading is a dispatch bug: poison the
    jitted fallbacks so touching them fails the test loudly — this is
    the compile-count hook (count must be zero, so any call raises)."""
    def boom(*a, **k):
        raise AssertionError("jit path invoked — AOT dispatch retraced")

    eng._prefill = boom
    eng._decode = boom


def test_precompile_serve_matches_jit_path():
    """Bucketed prefill (padded + traced true_len) is EXACT for every
    prompt length <= the bucket: logits and subsequent greedy decode
    match the unbucketed jit path."""
    eng = _engine()
    # length 8 divides the tp=8 token dim, so the UNBUCKETED jit path can
    # produce the reference; the bucketed path pads it to 16 (true_len 8)
    ids8 = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (2, 8)), jnp.int32
    )
    ids16 = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, (2, 16)), jnp.int32
    )
    ref8 = np.asarray(eng.generate(ids8, 5))
    ref16 = np.asarray(eng.generate(ids16, 5))

    eng.precompile([16, 32])
    _poison_jit_paths(eng)
    got8 = np.asarray(eng.generate(ids8, 5))     # pads 8 -> bucket 16
    got16 = np.asarray(eng.generate(ids16, 5))   # exact-fit bucket
    np.testing.assert_array_equal(got8, ref8)
    np.testing.assert_array_equal(got16, ref16)
    # bucketing also UNLOCKS lengths the raw path cannot run (M % tp):
    ids9 = jnp.asarray(
        np.random.default_rng(2).integers(0, 256, (2, 9)), jnp.int32
    )
    assert eng.generate(ids9, 3).shape == (2, 3)


def test_second_process_serves_with_zero_retraces():
    """The serialized bundle restores in a fresh Engine (the second
    process: same topology, no shared jit caches) and serves entirely
    through the deserialized executables — the jitted paths are poisoned,
    so a single retrace anywhere fails.

    Hardware-only: interpret-mode Pallas kernels lower to
    ``xla_ffi_python_cpu_callback`` custom calls, which XLA cannot
    serialize — on the CPU suite this skips, and the case runs on the
    real chip via ``scripts/run_hw_markers.py`` (the in-process dispatch
    mechanics are covered everywhere by the other tests here)."""
    from triton_distributed_tpu.core import compilation

    if compilation.interpret_mode():
        pytest.skip("executable serialization needs real-TPU lowering "
                    "(interpret kernels embed python callbacks)")
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        eng = _engine()
        manifest = eng.precompile([16], save_dir=d)
        import os

        assert os.path.exists(os.path.join(d, "aot_manifest.json"))
        ids = jnp.asarray(
            np.random.default_rng(2).integers(0, 256, (2, 12)), jnp.int32
        )
        want = np.asarray(eng.generate(ids, 4))

        eng2 = _engine()
        got_manifest = eng2.load_precompiled(d)
        assert got_manifest["buckets"] == manifest["buckets"] == [16]
        _poison_jit_paths(eng2)
        got = np.asarray(eng2.generate(ids, 4))
        np.testing.assert_array_equal(got, want)


def test_zero_traces_after_precompile():
    """The compile-count hook, counted directly: after precompile, serving
    bucketed prompts runs ZERO traces of the model's prefill/decode
    python (every trace executes the python body; the counter freezing
    proves dispatch never reaches a tracer)."""
    import dataclasses

    eng = _engine()
    counts = {"prefill": 0, "decode": 0}
    orig_prefill, orig_decode = eng.model.prefill, eng.model.decode
    object.__setattr__(
        eng.model, "prefill",
        lambda *a, **k: (counts.__setitem__("prefill", counts["prefill"] + 1),
                         orig_prefill(*a, **k))[1],
    )
    object.__setattr__(
        eng.model, "decode",
        lambda *a, **k: (counts.__setitem__("decode", counts["decode"] + 1),
                         orig_decode(*a, **k))[1],
    )
    # rebuild the jit wrappers over the counting fns, then precompile
    eng._prefill = jax.jit(eng.model.prefill, donate_argnums=(1,))
    eng._decode = jax.jit(eng.model.decode, donate_argnums=(1,))
    eng.precompile([16])
    frozen = dict(counts)
    assert frozen["prefill"] >= 1 and frozen["decode"] >= 1
    ids = jnp.asarray(
        np.random.default_rng(5).integers(0, 256, (2, 10)), jnp.int32
    )
    eng.generate(ids, 4)
    eng.generate(ids[:, :6], 3)
    assert counts == frozen, (counts, frozen)


def test_prompt_longer_than_buckets_falls_back_to_jit():
    eng = _engine()
    eng.precompile([8])
    ids = jnp.asarray(
        np.random.default_rng(3).integers(0, 256, (2, 20)), jnp.int32
    )
    # no poison: the fallback is the jit path, which must still work
    toks = eng.generate(ids, 3)
    assert toks.shape == (2, 3)


def test_precompile_validates(tmp_path):
    import json

    eng = _engine()
    with pytest.raises(ValueError, match="max_length"):
        eng.precompile([4096])
    with pytest.raises(ValueError, match="buckets"):
        eng.precompile([])
    # the batch check reads the manifest before touching any executable,
    # so it is testable without hardware serialization
    (tmp_path / "aot_manifest.json").write_text(json.dumps(
        {"buckets": [16], "batch": 2, "max_length": 64, "vocab": 256,
         "decode_mode": "psum"}
    ))
    other = _engine(batch=3)
    with pytest.raises(ValueError, match="batch"):
        other.load_precompiled(str(tmp_path))


def test_arch_fingerprint_rejects_different_model():
    """A bundle from a DIFFERENT model architecture or mesh topology must
    fail at load with an error naming the differing fields, even when the
    coarse manifest fields (batch/vocab/max_length) coincide (ADVICE r5
    low #4).  Pure manifest logic — no executables needed."""
    import dataclasses
    import json

    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import engine as engine_mod

    cfg = _cfg()
    mesh = mesh_lib.tp_mesh()
    fp = engine_mod.arch_fingerprint(cfg, mesh, "tp")
    # fingerprints are manifest-JSON-safe and stable across a round trip
    fp_rt = json.loads(json.dumps(fp))
    engine_mod.check_arch({"arch": fp_rt}, fp)      # identical: passes
    engine_mod.check_arch({}, fp)                   # legacy bundle: passes

    # same vocab/max_length, different heads/hidden — the coincident-
    # manifest case the fingerprint exists for
    other = dataclasses.replace(cfg, num_heads=4, hidden=256)
    fp2 = engine_mod.arch_fingerprint(other, mesh, "tp")
    with pytest.raises(ValueError, match="num_heads"):
        engine_mod.check_arch({"arch": fp_rt}, fp2)
    with pytest.raises(ValueError, match="hidden"):
        engine_mod.check_arch({"arch": fp_rt}, fp2)

    # a different tp axis size is a topology mismatch
    fp3 = dict(fp, mesh={"tp": 2})
    with pytest.raises(ValueError, match="mesh"):
        engine_mod.check_arch({"arch": fp_rt}, fp3)
