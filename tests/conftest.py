"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Reference tests require N physical GPUs under torchrun (SURVEY.md section 4);
here every distributed test runs on one host, with Pallas kernels executing
under TPU interpret mode (simulated DMA/semaphores).
"""

from triton_distributed_tpu.core.platform import force_cpu

# Must run before any JAX backend is created (safe here: conftest is imported
# before test modules). Overrides the container sitecustomize's force-selected
# TPU platform as well.
force_cpu(8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from triton_distributed_tpu.core import mesh as mesh_lib

    return mesh_lib.tp_mesh(8)
