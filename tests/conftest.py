"""Test harness: force a virtual CPU mesh before JAX initializes.

Reference tests require N physical GPUs under torchrun (SURVEY.md section 4);
here every distributed test runs on one host, with Pallas kernels executing
under TPU interpret mode (simulated DMA/semaphores).

12 devices = the widest test mesh (8) + 4 spares; spare devices keep spare
XLA client threads so interpret-mode collective kernels can't starve at full
mesh occupancy (see ``core.platform.force_cpu``).
"""

import os
import tempfile

from triton_distributed_tpu.core.platform import force_cpu, SPARE_VIRTUAL_DEVICES

# Must run before any JAX backend is created (safe here: conftest is imported
# before test modules). Overrides the container sitecustomize's force-selected
# TPU platform as well.
force_cpu(8 + SPARE_VIRTUAL_DEVICES)

# Hermetic link calibration: choose_method reads the persisted
# calibration (tools/calibrate.py), and a real slice's linkcal.json in
# the developer's ~/.cache must not leak into threshold assertions.
# Tests that WANT a calibration set TDT_LINKCAL_CACHE themselves.
os.environ.setdefault(
    "TDT_LINKCAL_CACHE",
    os.path.join(tempfile.mkdtemp(prefix="tdt-test-linkcal-"),
                 "linkcal.json"),
)

import pytest  # noqa: E402


def _serialize_interpret_teardown() -> None:
    """Durable workaround for the single-process full-suite abort
    (VERDICT r5 weak #2; root cause + rationale in docs/robustness.md
    "Interpreter teardown abort"): the Pallas TPU interpreter keeps
    per-kernel shared-memory state in module-global maps, and its
    cleanup (``_clean_up_shared_memory``) can race a concurrently
    finishing interpret kernel's device threads when many engine-heavy
    tests churn kernels in one process — observed as a non-deterministic
    fatal abort at different suite positions, while the same files pass
    in isolation.  Serializing every cleanup under one lock (and turning
    a teardown exception into a warning — the state is being discarded
    anyway) removes the race without sharding the suite.  Probed
    defensively: the symbol does not exist on every jax version (this
    container's 0.4.37 has no mosaic interpret module at all)."""
    import functools
    import importlib
    import threading

    lock = threading.Lock()
    for modname in ("jax._src.pallas.mosaic.interpret",
                    "jax._src.pallas.mosaic.interpret.interpret_pallas_call"):
        try:
            mod = importlib.import_module(modname)
        except Exception:
            continue
        for attr in dir(mod):
            if "clean_up_shared_memory" not in attr:
                continue
            orig = getattr(mod, attr)
            if not callable(orig) or getattr(orig, "_tdt_serialized", False):
                continue

            def guarded(*a, __orig=orig, **k):
                with lock:
                    try:
                        return __orig(*a, **k)
                    except Exception as e:  # discarded state: warn, don't die
                        import warnings

                        warnings.warn(
                            f"suppressed interpret teardown error: {e!r}")
                        return None

            guarded._tdt_serialized = True
            guarded = functools.wraps(orig)(guarded)
            setattr(mod, attr, guarded)


_serialize_interpret_teardown()

# The `-m fast` smoke tier (VERDICT r4 next #9): ONE cheap test per op
# family, kept under ~3 minutes total on the 1-CPU container so a
# wall-clock-limited runner still produces a real signal instead of a
# timeout masking failures.  Curated by nodeid (not per-file markers) so
# the whole tier is auditable in one place; pytest_collection_modifyitems
# below raises UsageError on full-suite runs if a listed id stops
# collecting (rename/delete rot cannot silently shrink the tier).
FAST_NODES = frozenset((
    "tests/test_matmul.py::test_matmul_golden[float32-256-256-256]",
    "tests/test_attention.py::test_flash_attention_golden[4-4-True]",
    "tests/test_attention.py::test_decode_attention_golden[4-4-4]",
    "tests/test_lang_primitives.py::test_ring_push",
    "tests/test_lang_primitives.py::test_notify_wait_producer_consumer",
    "tests/test_allgather.py::test_all_gather_matches_golden"
    "[shape0-float32-AllGatherMethod.RING_1D]",
    "tests/test_reduce_scatter.py::test_reduce_scatter_matches_golden"
    "[64-128-float32]",
    "tests/test_allreduce.py::test_all_reduce_matches_golden"
    "[64-128-float32-AllReduceMethod.ONE_SHOT]",
    "tests/test_allreduce.py::test_gemm_ar_matches_golden[64-128-128]",
    "tests/test_ag_gemm.py::test_ag_gemm_matches_golden[64-128-256-float32]",
    "tests/test_gemm_rs.py::test_gemm_rs_matches_golden[64-256-128-float32]",
    "tests/test_all_to_all.py::test_dispatch_combine_round_trip[2]",
    "tests/test_group_gemm.py::test_grouped_matmul_golden[splits0]",
    "tests/test_flash_decode.py::test_sp_flash_decode_matches_full[4-4-2]",
    "tests/test_sp_attention.py::test_sp_attention_matches_flash[True-2]",
    "tests/test_tp_layers.py::test_tp_mlp_forward[2]",
    "tests/test_moe_layer.py::test_moe_ep_forward[2]",
    "tests/test_pipeline.py::test_pipeline_matches_sequential[2-2]",
    "tests/test_paged_cache.py::test_paged_decode_matches_contiguous[False]",
    "tests/test_qwen_engine.py::test_engine_generate_greedy_deterministic",
    "tests/test_race_detection.py::test_all_gather_race_free",
    "tests/test_overlap_structure.py::test_gemm_rs_compute_issued_before_wire_wait",
    "tests/test_tools.py::test_aot_round_trip",
    "tests/test_loader_checkpoint.py::test_safetensors_round_trip[True]",
    "tests/test_perf_claims.py::test_repo_records_consistent",
    "tests/test_autotuner.py::test_picks_fastest_candidate",
    "tests/test_obs.py::test_tdt_lint_timeline_smoke",
    "tests/test_obs.py::test_tdt_lint_profile_smoke",
    "tests/test_obs.py::test_bench_history_check_repo_green",
    "tests/test_obs.py::test_telemetry_endpoints_during_live_decode",
    "tests/test_serve.py::test_tdt_lint_serve_smoke",
    "tests/test_serve.py::test_overcommit_2x_budget_completes_all_zero_leaks",
    "tests/test_serve.py::test_healthz_flips_503_under_saturation_then_200",
    "tests/test_integrity.py::test_matrix_corruption_cells_all_detected",
    "tests/test_integrity.py::test_kv_poison_recovery_matches_unpressured_run",
    "tests/test_fused_decode.py::"
    "test_fused_mlp_ar_protocol_clean[swiglu-4]",
    "tests/test_fused_decode.py::test_fused_fault_cells_detected_or_survived",
    "tests/test_fused_decode.py::test_decode_writeback_copy_count",
    "tests/test_handoff.py::test_tdt_lint_handoff_smoke",
    "tests/test_fleet.py::test_tdt_lint_fleet_smoke",
    "tests/test_fleet_obs.py::test_tdt_lint_fleetobs_smoke",
    "tests/test_diff.py::test_tdt_lint_regress_smoke",
    "tests/test_request_trace.py::test_tdt_lint_trace_smoke",
    "tests/test_persistent_decode.py::test_persistent_protocol_clean[4]",
    "tests/test_static_analysis.py::test_tdt_lint_dpor_smoke",
    "tests/test_static_analysis.py::test_tdt_lint_completeness_smoke",
    "tests/test_page_lifecycle.py::test_tdt_lint_pages_smoke",
    "tests/test_page_lifecycle.py::"
    "test_refcount_share_release_and_scrub_refusal",
    "tests/test_page_lifecycle.py::"
    "test_page_fixture_selftest_both_directions",
    "tests/test_persistent_decode.py::"
    "test_window_token_parity_under_pressure[4]",
    "tests/test_persistent_decode.py::test_bundle_equals_single_steps_tp1",
))


def pytest_collection_modifyitems(config, items):
    collected = set()
    for item in items:
        collected.add(item.nodeid)
        if item.nodeid in FAST_NODES:
            item.add_marker(pytest.mark.fast)
    # full-suite collections must resolve every fast node: a renamed or
    # DELETED test silently shrinking the smoke tier is exactly the class
    # of rot a curated list risks.  Partial runs skip the check; only
    # files the invocation EXPLICITLY --ignore'd (the CI shards) are
    # exempt — a deleted file is not ignored, so its nodes still flag.
    if len({i.fspath for i in items}) >= 20:
        import os

        ignored = {
            os.path.abspath(str(p))
            for p in (config.getoption("ignore", default=None) or [])
        }
        root = str(config.rootpath)
        missing = {
            n for n in FAST_NODES - collected
            if os.path.abspath(os.path.join(root, n.split("::", 1)[0]))
            not in ignored
        }
        if missing:
            raise pytest.UsageError(
                f"tests/conftest.py FAST_NODES lists tests that no longer "
                f"collect: {sorted(missing)}"
            )


def pytest_terminal_summary(terminalreporter):
    """Make skipped HF-parity convention checks LOUD (VERDICT weak #6):
    a run whose model conventions were not validated against the
    canonical Hugging Face implementation must say so in the summary,
    not hide in the 's' column."""
    skipped = [
        r for r in terminalreporter.stats.get("skipped", [])
        if "test_hf_parity" in str(getattr(r, "nodeid", ""))
    ]
    if skipped:
        terminalreporter.write_line(
            f"WARNING: {len(skipped)} HF-parity convention check(s) "
            f"SKIPPED (torch/transformers not installed) — prefill/decode "
            f"logits were NOT validated against the canonical HF "
            f"implementation this run.  The HF CI shard must set "
            f"TDT_REQUIRE_HF_PARITY=1 so a broken provision step fails "
            f"instead of skipping.",
            yellow=True,
        )


@pytest.fixture(scope="session")
def mesh8():
    from triton_distributed_tpu.core import mesh as mesh_lib

    return mesh_lib.tp_mesh(8)
