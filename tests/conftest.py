"""Test harness: force a virtual CPU mesh before JAX initializes.

Reference tests require N physical GPUs under torchrun (SURVEY.md section 4);
here every distributed test runs on one host, with Pallas kernels executing
under TPU interpret mode (simulated DMA/semaphores).

10 devices = the widest test mesh (8) + 2 spares; spare devices keep spare
XLA client threads so interpret-mode collective kernels can't starve at full
mesh occupancy (see ``core.platform.force_cpu``).
"""

from triton_distributed_tpu.core.platform import force_cpu, SPARE_VIRTUAL_DEVICES

# Must run before any JAX backend is created (safe here: conftest is imported
# before test modules). Overrides the container sitecustomize's force-selected
# TPU platform as well.
force_cpu(8 + SPARE_VIRTUAL_DEVICES)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from triton_distributed_tpu.core import mesh as mesh_lib

    return mesh_lib.tp_mesh(8)
