"""AllReduce kernels (one-shot push, fused two-shot) and fused GEMM+AR vs
stacked-sum goldens (reference ``test_allreduce.py`` /
``kernels/nvidia/allreduce.py``)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import (
    AllReduceConfig,
    AllReduceMethod,
    all_reduce,
)
from triton_distributed_tpu.comm.allreduce import choose_method
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh, shard
from triton_distributed_tpu.core.utils import assert_allclose, rand_tensor
from triton_distributed_tpu.ops import gemm_ar

CFG = AllReduceConfig(bm=8, bn=128)


def _golden(x, n):
    m = x.shape[0] // n
    return x.reshape(n, m, x.shape[1]).astype(jnp.float32).sum(0)


@pytest.mark.parametrize("method", [
    AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
])
@pytest.mark.parametrize("m,r,dtype", [
    (64, 128, jnp.float32),
    (128, 256, jnp.bfloat16),
])
def test_all_reduce_matches_golden(mesh8, method, m, r, dtype):
    n = 8
    x = rand_tensor((n * m, r), dtype, scale=0.1)
    xs = shard(mesh8, x, TP_AXIS)
    out = all_reduce(xs, mesh8, TP_AXIS, method=method, config=CFG)
    assert out.shape == (m, r)
    tol = 1e-4 if dtype == jnp.float32 else 1e-1
    assert_allclose(out.astype(jnp.float32), _golden(x, n),
                    atol=tol, rtol=tol, name=f"allreduce-{method.value}")


@pytest.mark.parametrize("method", [
    AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
])
def test_all_reduce_repeat(mesh8, method):
    """Second in-process invocation: drains must leave no semaphore residue."""
    n, m, r = 8, 64, 128
    x = rand_tensor((n * m, r), jnp.float32, scale=0.1)
    xs = shard(mesh8, x, TP_AXIS)
    out1 = all_reduce(xs, mesh8, TP_AXIS, method=method, config=CFG)
    out2 = all_reduce(xs, mesh8, TP_AXIS, method=method, config=CFG)
    assert_allclose(out1, out2, atol=0, rtol=0, name="ar-repeat")


@pytest.mark.parametrize("nring", [2, 3, 4])
@pytest.mark.parametrize("method", [
    AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
])
def test_all_reduce_small_rings(nring, method):
    """n in {2,3,4} exercises every drain-accounting branch."""
    mesh = make_mesh({TP_AXIS: nring}, devices=jax.devices()[:nring])
    m = 16 * nring  # divisible by nring (two-shot chunks) and sublane-aligned
    x = rand_tensor((nring * m, 128), jnp.float32, scale=0.1)
    xs = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS)))
    out = all_reduce(xs, mesh, TP_AXIS, method=method, config=CFG)
    assert_allclose(out.astype(jnp.float32), _golden(x, nring),
                    atol=1e-4, rtol=1e-4, name=f"ar-n{nring}")


def test_all_reduce_auto_select():
    # tiny -> one-shot; big -> two-shot; n<=2 always one-shot
    assert choose_method(4 * 1024, 8) == AllReduceMethod.ONE_SHOT
    assert choose_method(64 * 1024 * 1024, 8) == AllReduceMethod.TWO_SHOT
    assert choose_method(64 * 1024 * 1024, 2) == AllReduceMethod.ONE_SHOT


def test_all_reduce_single_rank():
    mesh1 = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    x = rand_tensor((32, 128), jnp.float32)
    assert_allclose(all_reduce(x, mesh1, TP_AXIS), x, atol=0, rtol=0)


# ---------------------------------------------------------------------------
# fused GEMM + AllReduce


def _gemm_golden(a, b):
    return jnp.matmul(a.astype(jnp.float32), b.astype(jnp.float32))


@pytest.mark.parametrize("m,k,n_dim", [(64, 128, 128), (128, 256, 256)])
def test_gemm_ar_matches_golden(mesh8, m, k, n_dim):
    a = rand_tensor((m, k), jnp.float32, scale=0.1)
    b = rand_tensor((k, n_dim), jnp.float32, scale=0.1)
    a_s = shard(mesh8, a, None, TP_AXIS)
    b_s = shard(mesh8, b, TP_AXIS, None)
    out = gemm_ar(a_s, b_s, mesh8, TP_AXIS)
    assert out.shape == (m, n_dim)
    assert_allclose(out.astype(jnp.float32), _gemm_golden(a, b),
                    atol=1e-3, rtol=1e-3, name="gemm_ar")


@pytest.mark.parametrize("nring", [2, 3])
def test_gemm_ar_small_rings(nring):
    mesh = make_mesh({TP_AXIS: nring}, devices=jax.devices()[:nring])
    m, k, n_dim = 16 * nring, 32 * nring, 128
    a = rand_tensor((m, k), jnp.float32, scale=0.1)
    b = rand_tensor((k, n_dim), jnp.float32, scale=0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    out = gemm_ar(a_s, b_s, mesh, TP_AXIS)
    assert_allclose(out.astype(jnp.float32), _gemm_golden(a, b),
                    atol=1e-3, rtol=1e-3, name=f"gemm_ar-n{nring}")


def test_gemm_ar_repeat(mesh8):
    m, k, n_dim = 64, 128, 128
    a = shard(mesh8, rand_tensor((m, k), jnp.float32, scale=0.1), None, TP_AXIS)
    b = shard(mesh8, rand_tensor((k, n_dim), jnp.float32, scale=0.1), TP_AXIS, None)
    out1 = gemm_ar(a, b, mesh8, TP_AXIS)
    out2 = gemm_ar(a, b, mesh8, TP_AXIS)
    assert_allclose(out1, out2, atol=0, rtol=0, name="gemm_ar-repeat")
