"""Stress + straggler + fault-tolerance coverage (reference
``test/stress/stress_test_ag_gemm.py`` randomized shapes and the
straggler options of ``allreduce.py:146``): randomized-shape sweeps of
the fused ops (also under the interpret-mode race detector), a
host-callback-injected straggler rank that must not deadlock or corrupt
any collective, and the ``tdt.resilience`` fault-injection matrix —
every injected fault class is either DETECTED (timeout naming the
offending semaphore/chunk) or SURVIVED via degraded fallback with
numerically correct results (VERDICT r5 missing #5)."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import all_gather, all_reduce
from triton_distributed_tpu.comm.allreduce import AllReduceConfig, AllReduceMethod
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.ops import ag_gemm, gemm_rs


from triton_distributed_tpu.core.compilation import interpret_supported

# the container's jax 0.4.37 lacks the interpret APIs — the seed's
# pre-existing failure class; capability-gated tests skip cleanly
# instead of adding to it
requires_interpret = pytest.mark.skipif(
    not interpret_supported(),
    reason="jax lacks pallas TPU interpret APIs (InterpretParams/"
           "CompilerParams/shard_map)",
)


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh({TP_AXIS: 4}, devices=jax.devices()[:4])


def _straggle(x, mesh, lagger: int = 0, ms: float = 30.0):
    """Delay one rank's entry into whatever consumes ``x`` next (reference
    ``sleep_async`` straggler injection): a host callback sleeps on the
    lagging rank, and its result is data-woven into the output."""
    def local(x_loc):
        r = jax.lax.axis_index(TP_AXIS)

        def cb(rv):
            if int(rv) == lagger:
                time.sleep(ms / 1e3)
            return np.zeros((), np.float32)

        tok = jax.pure_callback(
            cb, jax.ShapeDtypeStruct((), jnp.float32), r
        )
        return x_loc + tok.astype(x_loc.dtype)

    return jax.shard_map(
        local, mesh=mesh, in_specs=P(TP_AXIS, None),
        out_specs=P(TP_AXIS, None),
    )(x)


@pytest.mark.parametrize("seed", [0, 1, 2])
@requires_interpret
def test_ag_gemm_randomized_shapes(mesh4, seed):
    rng = np.random.default_rng(seed)
    n = 4
    m = 8 * n * int(rng.integers(1, 4))
    k = 128 * int(rng.integers(1, 3))
    nn = n * 64 * int(rng.integers(1, 3))
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.1)
    a_s = jax.device_put(a, NamedSharding(mesh4, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh4, P(None, TP_AXIS)))
    out = ag_gemm(a_s, b_s, mesh4)
    want = np.asarray(a) @ np.asarray(b)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("lagger", [0, 2])
@requires_interpret
def test_all_gather_with_straggler(mesh4, lagger):
    n, m, r = 4, 32, 128
    x = jnp.asarray(
        np.random.default_rng(7).standard_normal((n * m, r)).astype(np.float32)
    )
    xs = jax.device_put(x, NamedSharding(mesh4, P(TP_AXIS, None)))
    delayed = _straggle(xs, mesh4, lagger=lagger)
    out = jax.block_until_ready(all_gather(delayed, mesh4))
    assert np.allclose(np.asarray(jax.device_get(out)), np.asarray(x))


@requires_interpret
def test_all_reduce_with_straggler(mesh4):
    n, m, r = 4, 32, 128
    x = jnp.asarray(
        np.random.default_rng(8).standard_normal((n * m, r)).astype(np.float32)
        * 0.1
    )
    xs = jax.device_put(x, NamedSharding(mesh4, P(TP_AXIS, None)))
    delayed = _straggle(xs, mesh4, lagger=1)
    out = jax.block_until_ready(all_reduce(
        delayed, mesh4, method=AllReduceMethod.TWO_SHOT,
        config=AllReduceConfig(bm=8, bn=128),
    ))
    want = np.asarray(x).reshape(n, m, r).sum(0)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=1e-4, rtol=1e-4)


@requires_interpret
def test_gemm_rs_repeated_pressure(mesh4):
    """Back-to-back fused invocations (semaphore reuse under load)."""
    n, m, k, nn = 4, 64, 128, 128
    rng = np.random.default_rng(9)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.1)
    a_s = jax.device_put(a, NamedSharding(mesh4, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh4, P(TP_AXIS, None)))
    outs = [jax.device_get(gemm_rs(a_s, b_s, mesh4)) for _ in range(5)]
    for o in outs[1:]:
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(o))


@requires_interpret
def test_ep_a2a_with_straggler(mesh4):
    """A lagging rank through dispatch AND combine: the parity-slot
    semaphore protocol must absorb the skew without deadlock or
    corruption (reference: straggler injection through the A2A path)."""
    from triton_distributed_tpu.comm.all_to_all import (
        AllToAllConfig, ep_combine, ep_dispatch,
    )

    n, t, h, e = 4, 16, 128, 8
    rng = np.random.default_rng(10)
    # per-rank expert-sorted rows with uneven splits
    xs_l, sps = [], []
    for r in range(n):
        w = rng.random(e)
        split = np.floor(w / w.sum() * t).astype(np.int32)
        split[0] += t - split.sum()
        xs_l.append(rng.standard_normal((t, h)).astype(np.float32))
        sps.append(split)
    x = jnp.asarray(np.concatenate(xs_l))
    splits = jnp.asarray(np.concatenate(sps))
    xg = jax.device_put(x, NamedSharding(mesh4, P(TP_AXIS, None)))
    sg = jax.device_put(splits, NamedSharding(mesh4, P(TP_AXIS)))
    cfg = AllToAllConfig(chunk=8)
    delayed = _straggle(xg, mesh4, lagger=3)
    recv, _ = ep_dispatch(delayed, sg, mesh4, TP_AXIS, config=cfg)
    back = jax.block_until_ready(
        ep_combine(recv, sg, mesh4, TP_AXIS, token_dim=t, config=cfg)
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(back)),
                               np.asarray(x), atol=1e-5)
    # and again immediately: slot parity must have drained clean
    recv2, _ = ep_dispatch(xg, sg, mesh4, TP_AXIS, config=cfg)
    back2 = jax.block_until_ready(
        ep_combine(recv2, sg, mesh4, TP_AXIS, token_dim=t, config=cfg)
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(back2)),
                               np.asarray(x), atol=1e-5)


# ---------------------------------------------------------------------------
# randomized breadth sweeps under the race detector (VERDICT r5 missing
# #5): (M, K, N) / dtype / mesh-width randomized per seed, every fused
# op checked against its numpy golden with interpret-mode race
# detection armed — an unsynchronized write in any sampled shape class
# fails the run, not just the few hand-picked shapes


@pytest.fixture
def race_detector():
    from triton_distributed_tpu.core import compilation

    compilation.enable_race_detection(True)
    yield
    compilation.enable_race_detection(False)


def _sweep_mesh(rng):
    n = int(rng.choice([2, 4]))
    return n, make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])


@requires_interpret
@pytest.mark.parametrize("seed", [10, 11, 12])
def test_ag_gemm_sweep_race_detected(race_detector, seed):
    rng = np.random.default_rng(seed)
    n, mesh = _sweep_mesh(rng)
    dtype = jnp.float32 if rng.integers(2) else jnp.bfloat16
    m = 8 * n * int(rng.integers(1, 4))
    k = 128 * int(rng.integers(1, 3))
    nn = n * 64 * int(rng.integers(1, 3))
    a = jnp.asarray(rng.standard_normal((m, k)) * 0.1, dtype)
    b = jnp.asarray(rng.standard_normal((k, nn)) * 0.1, dtype)
    a_s = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    out = jax.block_until_ready(ag_gemm(a_s, b_s, mesh))
    want = np.asarray(a.astype(jnp.float32)) @ np.asarray(
        b.astype(jnp.float32))
    tol = 1e-3 if dtype == jnp.float32 else 5e-2
    assert np.allclose(np.asarray(out.astype(jnp.float32)), want,
                       atol=tol, rtol=tol * 10)


@requires_interpret
@pytest.mark.parametrize("seed", [20, 21, 22])
def test_gemm_rs_gemm_ar_sweep_race_detected(race_detector, seed):
    from triton_distributed_tpu.ops import gemm_ar

    rng = np.random.default_rng(seed)
    n, mesh = _sweep_mesh(rng)
    m = 8 * n * int(rng.integers(1, 4))
    k = n * 64 * int(rng.integers(1, 3))
    nn = 128 * int(rng.integers(1, 3))
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    want = np.asarray(a) @ np.asarray(b)
    out_rs = jax.block_until_ready(gemm_rs(a_s, b_s, mesh))
    assert np.allclose(np.asarray(jax.device_get(out_rs)), want,
                       atol=1e-3, rtol=1e-3)
    out_ar = jax.block_until_ready(gemm_ar(a_s, b_s, mesh))
    assert np.allclose(np.asarray(jax.device_get(out_ar)), want,
                       atol=1e-3, rtol=1e-3)


@requires_interpret
@pytest.mark.parametrize("seed", [30, 31])
def test_ep_a2a_sweep_race_detected(race_detector, seed):
    """Randomized uneven splits through dispatch+combine round trips."""
    from triton_distributed_tpu.comm.all_to_all import (
        AllToAllConfig, ep_combine, ep_dispatch,
    )

    rng = np.random.default_rng(seed)
    n, mesh = _sweep_mesh(rng)
    t = 8 * int(rng.integers(1, 4))
    h = 128 * int(rng.integers(1, 3))
    e = n * int(rng.integers(1, 3))
    xs_l, sps = [], []
    for _ in range(n):
        w = rng.random(e) + 1e-3
        split = np.floor(w / w.sum() * t).astype(np.int32)
        split[0] += t - split.sum()
        xs_l.append(rng.standard_normal((t, h)).astype(np.float32))
        sps.append(split)
    x = jnp.asarray(np.concatenate(xs_l))
    splits = jnp.asarray(np.concatenate(sps))
    xg = jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))
    sg = jax.device_put(splits, NamedSharding(mesh, P(TP_AXIS)))
    cfg = AllToAllConfig(chunk=8)
    recv, _ = ep_dispatch(xg, sg, mesh, TP_AXIS, config=cfg)
    back = jax.block_until_ready(
        ep_combine(recv, sg, mesh, TP_AXIS, token_dim=t, config=cfg))
    np.testing.assert_allclose(np.asarray(jax.device_get(back)),
                               np.asarray(x), atol=1e-5)


# ---------------------------------------------------------------------------
# fault-injection matrix (tdt.resilience; CPU-only — runs everywhere):
# each fault class x guarded kernel is DETECTED with the offending
# semaphore named, or SURVIVED; detected faults then ride the policy
# ladder to a numerically-correct degraded fallback, and the obs
# counters reflect the injected counts (ISSUE 3 acceptance)


def test_fault_injection_matrix_detected_or_survived():
    from triton_distributed_tpu import resilience as rz

    rows = rz.run_matrix(seed=0)
    problems = rz.verify_matrix(rows)
    assert not problems, problems
    by_fault = {}
    for row in rows:
        by_fault.setdefault(row["fault"], []).append(row)
    # all five classes present, each across >= 3 kernels
    assert set(by_fault) == {k.value for k in rz.FAULT_KINDS}
    for fault, fr in by_fault.items():
        assert len(fr) >= 3, (fault, len(fr))
    # the must-detect classes name the pending semaphore/chunk
    for kind in rz.matrix.MUST_DETECT:
        for row in by_fault[kind.value]:
            assert row["outcome"] == "detected", row
            assert row["named"], row


def test_fault_matrix_counters_reflect_injections():
    from triton_distributed_tpu import obs
    from triton_distributed_tpu import resilience as rz

    obs.REGISTRY.reset()
    obs.enable(True)
    try:
        rows = rz.run_matrix(seed=1)
    finally:
        obs.enable(None)
    injected = sum(
        r["value"] for r in obs.REGISTRY.snapshot()
        if r["name"] == "resilience_faults_injected")
    timeouts = sum(
        r["value"] for r in obs.REGISTRY.snapshot()
        if r["name"] == "resilience_timeouts")
    assert injected == len(rows)
    assert timeouts == sum(
        1 for r in rows
        if r["outcome"] == "detected"
        and ("stalled" in r["detail"] or "deadline" in r["detail"]))
    obs.REGISTRY.reset()


def test_detected_fault_survives_via_degraded_fallback():
    """The ladder bottom: a fused kernel that times out (replayed from
    the bounded simulator) degrades to the XLA-equivalent fallback and
    the result matches the fault-free golden exactly."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu import resilience as rz
    from triton_distributed_tpu.analysis.registry import all_cases

    case = next(c for c in all_cases(ranks=(4,))
                if c.name == "reduce_scatter/ring")
    ft = rz.record_faulty_case(
        case, rz.FaultSpec(rz.FaultKind.DROP_NOTIFY, rank=1, nth=0))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 8, 16)).astype(np.float32)
    golden = x.sum(0)

    def fused():
        # the fused kernel is stalled: the simulator proves it and
        # raises the SAME CollectiveTimeoutError the live watchdog would
        rz.run_bounded(ft, deadline_ticks=1000)
        raise AssertionError("stalled protocol cannot complete")

    def fallback():
        return golden.copy()

    obs.REGISTRY.reset()
    obs.enable(True)
    rz.policy._reset_state_for_tests()
    try:
        policy = rz.RetryPolicy(max_retries=1, backoff_ms=0.0)
        out = rz.resilient_call("reduce_scatter", fused,
                                fallback=fallback, policy=policy)
    finally:
        obs.enable(None)
    np.testing.assert_array_equal(out, golden)
    rows = {r["name"]: r["value"] for r in obs.REGISTRY.snapshot()
            if r["name"].startswith("resilience_")}
    assert rows.get("resilience_retries") == 1
    assert rows.get("resilience_degraded_calls") == 1
    obs.REGISTRY.reset()
    rz.policy._reset_state_for_tests()
