"""Stress + straggler coverage (reference ``test/stress/stress_test_ag_gemm.py``
randomized shapes and the straggler options of ``allreduce.py:146``):
randomized-shape sweeps of the fused ops, and a host-callback-injected
straggler rank that must not deadlock or corrupt any collective."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import all_gather, all_reduce
from triton_distributed_tpu.comm.allreduce import AllReduceConfig, AllReduceMethod
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.ops import ag_gemm, gemm_rs


@pytest.fixture(scope="module")
def mesh4():
    return make_mesh({TP_AXIS: 4}, devices=jax.devices()[:4])


def _straggle(x, mesh, lagger: int = 0, ms: float = 30.0):
    """Delay one rank's entry into whatever consumes ``x`` next (reference
    ``sleep_async`` straggler injection): a host callback sleeps on the
    lagging rank, and its result is data-woven into the output."""
    def local(x_loc):
        r = jax.lax.axis_index(TP_AXIS)

        def cb(rv):
            if int(rv) == lagger:
                time.sleep(ms / 1e3)
            return np.zeros((), np.float32)

        tok = jax.pure_callback(
            cb, jax.ShapeDtypeStruct((), jnp.float32), r
        )
        return x_loc + tok.astype(x_loc.dtype)

    return jax.shard_map(
        local, mesh=mesh, in_specs=P(TP_AXIS, None),
        out_specs=P(TP_AXIS, None),
    )(x)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_ag_gemm_randomized_shapes(mesh4, seed):
    rng = np.random.default_rng(seed)
    n = 4
    m = 8 * n * int(rng.integers(1, 4))
    k = 128 * int(rng.integers(1, 3))
    nn = n * 64 * int(rng.integers(1, 3))
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.1)
    a_s = jax.device_put(a, NamedSharding(mesh4, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh4, P(None, TP_AXIS)))
    out = ag_gemm(a_s, b_s, mesh4)
    want = np.asarray(a) @ np.asarray(b)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=1e-3, rtol=1e-3)


@pytest.mark.parametrize("lagger", [0, 2])
def test_all_gather_with_straggler(mesh4, lagger):
    n, m, r = 4, 32, 128
    x = jnp.asarray(
        np.random.default_rng(7).standard_normal((n * m, r)).astype(np.float32)
    )
    xs = jax.device_put(x, NamedSharding(mesh4, P(TP_AXIS, None)))
    delayed = _straggle(xs, mesh4, lagger=lagger)
    out = jax.block_until_ready(all_gather(delayed, mesh4))
    assert np.allclose(np.asarray(jax.device_get(out)), np.asarray(x))


def test_all_reduce_with_straggler(mesh4):
    n, m, r = 4, 32, 128
    x = jnp.asarray(
        np.random.default_rng(8).standard_normal((n * m, r)).astype(np.float32)
        * 0.1
    )
    xs = jax.device_put(x, NamedSharding(mesh4, P(TP_AXIS, None)))
    delayed = _straggle(xs, mesh4, lagger=1)
    out = jax.block_until_ready(all_reduce(
        delayed, mesh4, method=AllReduceMethod.TWO_SHOT,
        config=AllReduceConfig(bm=8, bn=128),
    ))
    want = np.asarray(x).reshape(n, m, r).sum(0)
    assert np.allclose(np.asarray(jax.device_get(out)), want,
                       atol=1e-4, rtol=1e-4)


def test_gemm_rs_repeated_pressure(mesh4):
    """Back-to-back fused invocations (semaphore reuse under load)."""
    n, m, k, nn = 4, 64, 128, 128
    rng = np.random.default_rng(9)
    a = jnp.asarray(rng.standard_normal((m, k)).astype(np.float32) * 0.1)
    b = jnp.asarray(rng.standard_normal((k, nn)).astype(np.float32) * 0.1)
    a_s = jax.device_put(a, NamedSharding(mesh4, P(None, TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh4, P(TP_AXIS, None)))
    outs = [jax.device_get(gemm_rs(a_s, b_s, mesh4)) for _ in range(5)]
    for o in outs[1:]:
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(o))


def test_ep_a2a_with_straggler(mesh4):
    """A lagging rank through dispatch AND combine: the parity-slot
    semaphore protocol must absorb the skew without deadlock or
    corruption (reference: straggler injection through the A2A path)."""
    from triton_distributed_tpu.comm.all_to_all import (
        AllToAllConfig, ep_combine, ep_dispatch,
    )

    n, t, h, e = 4, 16, 128, 8
    rng = np.random.default_rng(10)
    # per-rank expert-sorted rows with uneven splits
    xs_l, sps = [], []
    for r in range(n):
        w = rng.random(e)
        split = np.floor(w / w.sum() * t).astype(np.int32)
        split[0] += t - split.sum()
        xs_l.append(rng.standard_normal((t, h)).astype(np.float32))
        sps.append(split)
    x = jnp.asarray(np.concatenate(xs_l))
    splits = jnp.asarray(np.concatenate(sps))
    xg = jax.device_put(x, NamedSharding(mesh4, P(TP_AXIS, None)))
    sg = jax.device_put(splits, NamedSharding(mesh4, P(TP_AXIS)))
    cfg = AllToAllConfig(chunk=8)
    delayed = _straggle(xg, mesh4, lagger=3)
    recv, _ = ep_dispatch(delayed, sg, mesh4, TP_AXIS, config=cfg)
    back = jax.block_until_ready(
        ep_combine(recv, sg, mesh4, TP_AXIS, token_dim=t, config=cfg)
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(back)),
                               np.asarray(x), atol=1e-5)
    # and again immediately: slot parity must have drained clean
    recv2, _ = ep_dispatch(xg, sg, mesh4, TP_AXIS, config=cfg)
    back2 = jax.block_until_ready(
        ep_combine(recv2, sg, mesh4, TP_AXIS, token_dim=t, config=cfg)
    )
    np.testing.assert_allclose(np.asarray(jax.device_get(back2)),
                               np.asarray(x), atol=1e-5)
