"""Regression forensics (ISSUE 20): differential root-cause
attribution between comparable captures — the ``obs.diff`` engine, its
exactness contract, the ``/debug/diff`` endpoint's scrape safety, and
the CI gate wiring.

Headless like the profiler tests: real flight-ring captures replayed
through the REAL ``ContinuousProfiler`` under the deterministic model
clock, everything armed in-process and restored after.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu.obs import anomaly, continuous, diff, flight
from triton_distributed_tpu.obs import fleet_stats, history
from triton_distributed_tpu.obs import request_trace as rtrace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def profiler_on():
    """Armed flight ring + continuous profiler, restored after (the
    anomaly-selftest harness shape)."""
    prev_obs = obs.enabled()
    obs.enable(True)
    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    flight.enable(True)
    continuous.enable(True)
    flight.clear()
    obs.serve_stats.STATS.reset()
    yield
    flight.clear()
    continuous.reset()
    flight.enable(prev_flight)
    continuous.enable(prev_prof)
    obs.enable(prev_obs)


def _window_of(streams, *, tier="decode"):
    """One rotated window from a recorded capture through the REAL
    profiler path (fresh instance — no global install)."""
    prof = continuous.ContinuousProfiler(window_steps=1, out_dir="")
    flight.clear()
    flight.feed_streams("allgather", streams)
    prof.on_step(tier, 1)
    return prof.last_window()


# ---------------------------------------------------------------------------
# the exactness contract on a planted regression


def test_diff_windows_exactness_on_planted_regression(profiler_on):
    """The acceptance pin: per-term deltas plus the residual sum to the
    total metric delta EXACTLY (floating point equality, the gap_ms
    discipline), and the ranked #1 term names the injected family."""
    _, streams = flight.record_family("allgather", 2)
    healthy = _window_of(streams)
    bad = _window_of(anomaly._inflate_wire(streams, 1 << 16))
    assert healthy["totals"]["episodes"] and bad["totals"]["episodes"]

    d = diff.diff_windows(healthy, bad, metric="exposed_ms")
    total = d["total_delta"]
    assert total > 0.0                     # the inflation grew exposure
    terms = d["terms"]
    assert terms, "planted regression attributed nothing"
    # the additive identity holds EXACTLY — residual is defined as
    # total - sum(kept), so this is a floating-point equality, not a
    # tolerance check
    assert sum(t["delta"] for t in terms) + d["residual"] == total
    assert d["exact"], d["residual"]
    assert abs(d["residual"]) <= diff.EXACT_TOL_PER_TERM * max(
        1, len(terms))
    top = terms[0]
    assert top["family"] == "allgather"
    assert top["phase"] == "decode"        # tier IS the phase axis
    assert top["stall"] is not None        # (sem, chunk, peer) triple
    assert top["delta"] == max(t["delta"] for t in terms)
    # ranked: descending |delta|
    mags = [abs(t["delta"]) for t in terms]
    assert mags == sorted(mags, reverse=True)
    # pct_of_total is consistent with the term's share
    assert top["pct_of_total"] == pytest.approx(
        100.0 * top["delta"] / total, abs=0.11)

    # identical captures rank nothing and close exactly
    same = diff.diff_windows(healthy, healthy)
    assert same["terms"] == []
    assert same["residual"] == 0.0 and same["exact"]


def test_diff_cohorts_exactness_and_gap_discipline(profiler_on):
    """Cohort pairing: per-phase exposed deltas (plus the chain-gap
    term) sum to the mean end-to-end delta exactly, and the slow
    cohort's extra decode time ranks first with a resolving exemplar."""
    prev = rtrace.enable(True)
    rtrace.RING.clear()
    try:
        fast = diff._synthetic_trace("req-fast", 10.0)
        slow = diff._synthetic_trace("req-slow", 90.0)
        d = diff.diff_cohorts([fast], [slow], label_a="p50",
                              label_b="p99")
        assert d["terms"]
        assert sum(t["delta"] for t in d["terms"]) + d["residual"] \
            == d["total_delta"]
        assert d["exact"]
        assert d["terms"][0]["phase"] == "decode"
        assert d["exemplar"] == "req-slow"
        # empty cohorts are a caller error, not a silent zero
        with pytest.raises(ValueError):
            diff.diff_cohorts([], [slow], label_a="a", label_b="b")
    finally:
        rtrace.RING.clear()
        rtrace.enable(prev)


def test_rounds_attribution_in_history_warnings():
    """`bench_history` WARN lines carry the round-over-round
    co-regression note (history.analyze -> diff.rounds_attribution)."""
    rounds = history.load_rounds(REPO)
    assert len(rounds) >= 2
    trs = history.analyze(rounds)
    # committed rounds are currently warning-free; pin the attribution
    # path directly on the last two rounds instead
    a, b = rounds[-2], rounds[-1]
    d = diff.diff_rounds(a, b)
    assert d["terms"], "adjacent committed rounds diff to nothing"
    worse = [t for t in d["terms"] if t["drift_pct"] > 0]
    if worse:
        note = diff.rounds_attribution(
            trs, worse[0]["metric"], min_drift=0.0)
        assert note is None or "co-regressed" in note
    # and any warning that DOES exist already carries its note
    for tr in trs.values():
        for w in tr.warnings:
            assert "WARN" in w or w  # annotated strings stay strings


# ---------------------------------------------------------------------------
# /debug/diff: concurrent scrape during window rotation (tear test)


def test_debug_diff_scrape_during_rotation(profiler_on):
    """Satellite 4a: /debug/diff payloads stay internally consistent
    (json-serializable, schema-complete) while windows rotate and
    anomaly events are being replaced underneath the scrapers."""
    from triton_distributed_tpu.obs import server as obs_server

    _, streams = flight.record_family("allgather", 2)
    bad = anomaly._inflate_wire(streams, 1 << 16)

    prof = continuous.ContinuousProfiler(window_steps=1, out_dir="")
    prev_installed = continuous.install(prof)
    # a band the inflated replay breaches on every rotation
    healthy = _window_of(streams)
    v = healthy["totals"]["exposed_ms"]
    det = anomaly.AnomalyDetector(
        {"exposed_ms": history.healthy_band([v, v], "lower")},
        record=True)   # /debug/diff serves the RECORDED event stream
    anomaly.set_detector(det)
    srv = obs_server.start(port=0)
    failures: list[str] = []
    payloads: list[dict] = []
    stop = threading.Event()

    def scrape():
        import urllib.request

        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                        srv.url + "/debug/diff", timeout=10) as r:
                    snap = json.loads(r.read().decode())
            except Exception as e:      # noqa: BLE001 — collected
                failures.append(repr(e))
                return
            if not snap.get("enabled"):
                failures.append(f"disabled mid-run: {snap}")
                return
            ev = snap.get("anomaly")
            if ev is not None:
                dd = ev.get("diff")
                if dd is not None:
                    # schema-complete, never a torn mix
                    need = {"kind", "terms", "residual", "exact",
                            "summary"}
                    if not need <= set(dd):
                        failures.append(
                            f"torn diff keys: {sorted(dd)}")
                        return
                payloads.append(snap)

    threads = [threading.Thread(target=scrape) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        for step in range(1, 26):
            # healthy and inflated windows alternate: baselines rotate
            # in and out underneath the scrapers
            src = streams if step % 2 else bad
            flight.clear()
            flight.feed_streams("allgather", src)
            prof.on_step("decode", step)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        obs_server.stop()
        anomaly.set_detector(None)
        anomaly.clear()
        continuous.install(prev_installed)
    assert not failures, failures[:3]
    assert payloads, "scrapers never saw an attributed anomaly"
    # at least one scrape caught a full attribution with terms
    assert any((p.get("diff") or {}).get("terms") for p in payloads)


# ---------------------------------------------------------------------------
# fleet merge: exemplars survive the union


def test_fleet_merge_preserves_exemplar_trace_ids():
    """Satellite 4b: a p99 exemplar observed on ONE replica's tee
    sketch survives the ReplicaStats union merge — diff_replicas can
    always name a resolving trace id at fleet scope."""
    fs = fleet_stats.FleetStats()
    r0 = fs.replica("r0", "decode")
    r1 = fs.replica("r1", "decode")
    for i in range(50):
        r0.request_ms.observe(10.0 + i * 0.01)
        r1.request_ms.observe(12.0 + i * 0.01)
    for _ in range(3):   # a real tail: the p99 bucket IS the slow one
        r1.request_ms.observe(500.0, exemplar="req-tail-exemplar")
    merged = fs.merged("request_ms")
    assert merged.exemplar(0.99) == "req-tail-exemplar"
    d = diff.diff_replicas(r0, r1)
    assert d["terms"]
    top = d["terms"][0]
    assert top["metric"] == "request_ms_p99"
    assert top["exemplar"] == "req-tail-exemplar"
    assert top["delta"] > 0


# ---------------------------------------------------------------------------
# CI gate wiring


def test_direction_coverage_clean():
    """Satellite 2: every bench metric classifies under a named
    DIRECTION_RULES row; no dead rules; no dead allowlist rows."""
    from triton_distributed_tpu.analysis import completeness

    assert completeness.check_direction_coverage() == []
    # the golden table IS direction_for: spot-pin both halves
    assert history.classify_direction(
        "profile_overhead_pct", "% over unprofiled") == \
        ("overhead-tax", "lower")
    assert history.classify_direction(
        "diff_overhead_pct", "% over undiffed profiling") == \
        ("overhead-tax", "lower")
    assert history.classify_direction(
        "flash_attn_b1_h32_s4096_d128", "TFLOP/s") == \
        ("throughput-default", "higher")


def test_tdt_lint_regress_smoke():
    """The CI gate wiring (ISSUE 20 satellite): the seeded
    both-direction forensics selftest plus the direction-coverage
    golden, as `tdt_lint --all` leg 18 runs it."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--regress"],
        capture_output=True, text=True, timeout=540,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "regress OK" in proc.stdout
    assert "exemplar" in proc.stdout
