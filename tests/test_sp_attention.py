"""Ring (sequence-parallel) attention vs single-device flash golden
(reference ``test_sp_ag_attention`` strategy)."""

import jax
import numpy as np
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import SP_AXIS, make_mesh
from triton_distributed_tpu.ops.attention import (
    flash_attention,
    flash_attention_chunk,
    finalize_attention_state,
    init_attention_state,
)
from triton_distributed_tpu.ops.sp_attention import (
    hierarchical_sp_attention,
    sp_attention,
)


def _inputs(b, h, hk, s, d, key=0, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, hk, s, d), dtype)
    v = jax.random.normal(kv, (b, hk, s, d), dtype)
    return q, k, v


def _mesh(n):
    return make_mesh({SP_AXIS: n}, devices=jax.devices()[:n])


def _shard(mesh, *xs):
    spec = NamedSharding(mesh, P(None, None, SP_AXIS, None))
    return tuple(jax.device_put(x, spec) for x in xs)


def test_chunk_state_equals_full_attention():
    """Folding KV chunks sequentially must reproduce one-shot flash."""
    b, h, s, d, c = 1, 2, 256, 64, 4
    q, k, v = _inputs(b, h, h, s, d)
    sc = s // c
    state = init_attention_state(b, h, s, d)
    for j in range(c):
        state = flash_attention_chunk(
            q, k[:, :, j * sc:(j + 1) * sc], v[:, :, j * sc:(j + 1) * sc],
            state, q_offset=0, kv_offset=j * sc,
            causal=True, block_q=64, block_k=64,
        )
    got = finalize_attention_state(state, q.dtype)
    want = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert jnp.allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_sp_attention_matches_flash(n, causal):
    b, h, s, d = 1, 4, 512, 64
    q, k, v = _inputs(b, h, h, s, d, key=1)
    mesh = _mesh(n)
    qs, ks, vs = _shard(mesh, q, k, v)
    out = sp_attention(qs, ks, vs, mesh, causal=causal,
                       block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    assert out.shape == q.shape
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def test_sp_attention_gqa():
    n, b, h, hk, s, d = 4, 1, 8, 2, 512, 64
    q, k, v = _inputs(b, h, hk, s, d, key=2)
    mesh = _mesh(n)
    spec_q = NamedSharding(mesh, P(None, None, SP_AXIS, None))
    qs = jax.device_put(q, spec_q)
    ks, vs = _shard(mesh, k, v)
    out = sp_attention(qs, ks, vs, mesh, causal=True,
                       block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5)


def test_sp_attention_bf16():
    n, b, h, s, d = 4, 1, 4, 512, 128
    q, k, v = _inputs(b, h, h, s, d, key=3, dtype=jnp.bfloat16)
    mesh = _mesh(n)
    qs, ks, vs = _shard(mesh, q, k, v)
    out = sp_attention(qs, ks, vs, mesh, causal=True)
    want = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    assert jnp.allclose(
        jax.device_get(out).astype(jnp.float32),
        want.astype(jnp.float32), atol=5e-2, rtol=5e-2,
    )


def test_sp_attention_single_rank_fallback():
    b, h, s, d = 1, 2, 256, 64
    q, k, v = _inputs(b, h, h, s, d, key=4)
    mesh = _mesh(1)
    out = sp_attention(q, k, v, mesh, causal=True, block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    assert jnp.allclose(out, want, atol=0, rtol=0)


@pytest.mark.parametrize("n", [2, 4])
@pytest.mark.parametrize("causal", [True, False])
def test_sp_attention_varlen_segments(n, causal):
    """PACKED variable-length batches through the ring: segment ids rotate
    alongside the KV chunks, and every position attends only within its
    own segment — the reference SP attention's cu_seqlens support
    (``sp_ag_attention_intra_node.py`` varlen path)."""
    b, h, s, d = 1, 4, 512, 64
    q, k, v = _inputs(b, h, h, s, d, key=9)
    # three packed sequences of uneven length (cu_seqlens 0, 200, 344, 512)
    segs = jnp.asarray(
        np.repeat([0, 1, 2], [200, 144, 168])[None, :], jnp.int32
    )
    mesh = _mesh(n)
    qs, ks, vs = _shard(mesh, q, k, v)
    segs_s = jax.device_put(segs, NamedSharding(mesh, P(None, SP_AXIS)))
    out = sp_attention(qs, ks, vs, mesh, causal=causal, block_q=64,
                       block_k=64, segment_ids=segs_s)
    want = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                           segment_ids=segs)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def _mesh2(n_out, n_in):
    devs = jax.devices()[: n_out * n_in]
    return jax.sharding.Mesh(
        np.array(devs).reshape(n_out, n_in), ("dcn", "ici")
    )


@pytest.mark.parametrize("n_out,n_in", [(2, 4), (2, 2), (4, 2)])
@pytest.mark.parametrize("causal", [True, False])
def test_hierarchical_sp_attention_matches_flash(n_out, n_in, causal):
    """Inner-ICI ring x outer-DCN superchunk hops == single-device flash
    (VERDICT next #6; reference ``sp_ag_attention_inter_node.py:115-192``)."""
    b, h, s, d = 1, 4, 512, 64
    q, k, v = _inputs(b, h, h, s, d, key=7)
    mesh = _mesh2(n_out, n_in)
    spec = NamedSharding(mesh, P(None, None, ("dcn", "ici"), None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = hierarchical_sp_attention(
        qs, ks, vs, mesh, "ici", "dcn", causal=causal,
        block_q=64, block_k=64,
    )
    want = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    assert out.shape == q.shape
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def test_hierarchical_sp_attention_gqa_and_outer1():
    b, h, hk, s, d = 1, 8, 2, 256, 64
    q, k, v = _inputs(b, h, hk, s, d, key=8)
    mesh = _mesh2(2, 2)
    spec = NamedSharding(mesh, P(None, None, ("dcn", "ici"), None))
    spec_kv = spec
    qs = jax.device_put(q, spec)
    ks, vs = jax.device_put(k, spec_kv), jax.device_put(v, spec_kv)
    out = hierarchical_sp_attention(qs, ks, vs, mesh, "ici", "dcn",
                                    causal=True, block_q=64, block_k=64)
    want = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5)

    # n_out == 1 degenerates to the flat ICI ring
    mesh1 = _mesh2(1, 4)
    spec1 = NamedSharding(mesh1, P(None, None, ("dcn", "ici"), None))
    qs, ks, vs = (jax.device_put(x, spec1) for x in (q, k, v))
    out1 = hierarchical_sp_attention(qs, ks, vs, mesh1, "ici", "dcn",
                                     causal=True, block_q=64, block_k=64)
    assert jnp.allclose(jax.device_get(out1), want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n_out,n_in", [(2, 2), (2, 4)])
@pytest.mark.parametrize("causal", [True, False])
def test_hierarchical_sp_attention_varlen_segments(n_out, n_in, causal):
    """PACKED variable-length batches through the 2-level ring (VERDICT
    next #5): segment ids ride the inner ICI rotations AND the outer DCN
    hops with their chunks, matching the reference inter-node varlen path
    (``sp_ag_attention_inter_node.py:56,328``).  Golden: single-device
    packed ``flash_attention``."""
    b, h, s, d = 1, 4, 512, 64
    q, k, v = _inputs(b, h, h, s, d, key=11)
    # three packed sequences of uneven length (cu_seqlens 0, 200, 344, 512)
    segs = jnp.asarray(
        np.repeat([0, 1, 2], [200, 144, 168])[None, :], jnp.int32
    )
    mesh = _mesh2(n_out, n_in)
    spec = NamedSharding(mesh, P(None, None, ("dcn", "ici"), None))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    segs_s = jax.device_put(
        segs, NamedSharding(mesh, P(None, ("dcn", "ici")))
    )
    out = hierarchical_sp_attention(
        qs, ks, vs, mesh, "ici", "dcn", causal=causal,
        block_q=64, block_k=64, segment_ids=segs_s,
    )
    want = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                           segment_ids=segs)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def test_hierarchical_sp_attention_varlen_outer1_fallback():
    """n_out == 1 varlen degenerates to the flat ring's varlen path."""
    b, h, s, d = 1, 2, 256, 64
    q, k, v = _inputs(b, h, h, s, d, key=12)
    segs = jnp.asarray(np.repeat([0, 1], [100, 156])[None, :], jnp.int32)
    mesh1 = _mesh2(1, 4)
    spec1 = NamedSharding(mesh1, P(None, None, ("dcn", "ici"), None))
    qs, ks, vs = (jax.device_put(x, spec1) for x in (q, k, v))
    segs_s = jax.device_put(
        segs, NamedSharding(mesh1, P(None, ("dcn", "ici")))
    )
    out = hierarchical_sp_attention(qs, ks, vs, mesh1, "ici", "dcn",
                                    causal=True, block_q=64, block_k=64,
                                    segment_ids=segs_s)
    want = flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                           segment_ids=segs)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5)
