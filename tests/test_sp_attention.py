"""Ring (sequence-parallel) attention vs single-device flash golden
(reference ``test_sp_ag_attention`` strategy)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import SP_AXIS, make_mesh
from triton_distributed_tpu.ops.attention import (
    flash_attention,
    flash_attention_chunk,
    finalize_attention_state,
    init_attention_state,
)
from triton_distributed_tpu.ops.sp_attention import sp_attention


def _inputs(b, h, hk, s, d, key=0, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(kq, (b, h, s, d), dtype)
    k = jax.random.normal(kk, (b, hk, s, d), dtype)
    v = jax.random.normal(kv, (b, hk, s, d), dtype)
    return q, k, v


def _mesh(n):
    return make_mesh({SP_AXIS: n}, devices=jax.devices()[:n])


def _shard(mesh, *xs):
    spec = NamedSharding(mesh, P(None, None, SP_AXIS, None))
    return tuple(jax.device_put(x, spec) for x in xs)


def test_chunk_state_equals_full_attention():
    """Folding KV chunks sequentially must reproduce one-shot flash."""
    b, h, s, d, c = 1, 2, 256, 64, 4
    q, k, v = _inputs(b, h, h, s, d)
    sc = s // c
    state = init_attention_state(b, h, s, d)
    for j in range(c):
        state = flash_attention_chunk(
            q, k[:, :, j * sc:(j + 1) * sc], v[:, :, j * sc:(j + 1) * sc],
            state, q_offset=0, kv_offset=j * sc,
            causal=True, block_q=64, block_k=64,
        )
    got = finalize_attention_state(state, q.dtype)
    want = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    assert jnp.allclose(got, want, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_sp_attention_matches_flash(n, causal):
    b, h, s, d = 1, 4, 512, 64
    q, k, v = _inputs(b, h, h, s, d, key=1)
    mesh = _mesh(n)
    qs, ks, vs = _shard(mesh, q, k, v)
    out = sp_attention(qs, ks, vs, mesh, causal=causal,
                       block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    assert out.shape == q.shape
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5), (
        jnp.abs(jax.device_get(out) - want).max()
    )


def test_sp_attention_gqa():
    n, b, h, hk, s, d = 4, 1, 8, 2, 512, 64
    q, k, v = _inputs(b, h, hk, s, d, key=2)
    mesh = _mesh(n)
    spec_q = NamedSharding(mesh, P(None, None, SP_AXIS, None))
    qs = jax.device_put(q, spec_q)
    ks, vs = _shard(mesh, k, v)
    out = sp_attention(qs, ks, vs, mesh, causal=True,
                       block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    assert jnp.allclose(jax.device_get(out), want, atol=2e-5, rtol=2e-5)


def test_sp_attention_bf16():
    n, b, h, s, d = 4, 1, 4, 512, 128
    q, k, v = _inputs(b, h, h, s, d, key=3, dtype=jnp.bfloat16)
    mesh = _mesh(n)
    qs, ks, vs = _shard(mesh, q, k, v)
    out = sp_attention(qs, ks, vs, mesh, causal=True)
    want = flash_attention(q, k, v, causal=True)
    assert out.dtype == jnp.bfloat16
    assert jnp.allclose(
        jax.device_get(out).astype(jnp.float32),
        want.astype(jnp.float32), atol=5e-2, rtol=5e-2,
    )


def test_sp_attention_single_rank_fallback():
    b, h, s, d = 1, 2, 256, 64
    q, k, v = _inputs(b, h, h, s, d, key=4)
    mesh = _mesh(1)
    out = sp_attention(q, k, v, mesh, causal=True, block_q=128, block_k=128)
    want = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    assert jnp.allclose(out, want, atol=0, rtol=0)
