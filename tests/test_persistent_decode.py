"""Persistent serving megakernel (ISSUE 13, ``ops.persistent_decode``):
protocol coverage of the chained multi-layer loop (2L ring reductions on
ONE re-armed semaphore set), fault cells naming the inter-layer
semaphore, the <= 2 dispatches-per-bundle structure, config-hoist and
AOT-bucket serving plumbing, scheduler window parity — and, on the
``n == 1`` pure-XLA reference path that runs on ANY jax build, real
numerics: bundle-vs-stepwise token/pool parity and a golden against the
independent ``prefill_chunk`` implementation."""

import dataclasses
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import analysis, serve
from triton_distributed_tpu import resilience as rz
from triton_distributed_tpu.analysis import registry
from triton_distributed_tpu.analysis.record import record_kernel
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.models import Engine, ModelConfig, Qwen3
from triton_distributed_tpu.models.kv_cache import init_paged_cache
from triton_distributed_tpu.models.qwen import (
    DECODE_MODES,
    stack_decode_params,
)
from triton_distributed_tpu.ops import persistent_decode as pdm
from triton_distributed_tpu.ops.persistent_decode import (
    PersistentDecodeConfig,
    persistent_decode_candidates,
)


def _mesh(n=1):
    return make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])


CFG = ModelConfig(num_layers=2, hidden=32, intermediate=64, num_heads=4,
                  num_kv_heads=2, head_dim=8, vocab=64, max_length=32,
                  dtype=jnp.float32, qk_norm=True)


def _model(n=1, mode="persistent", cfg=CFG):
    return Qwen3(cfg, _mesh(n), decode_mode=mode)


def _cache(mesh, batch, cfg=CFG, **kw):
    return init_paged_cache(mesh, cfg.num_layers, batch, cfg.num_kv_heads,
                            cfg.max_length, cfg.head_dim, cfg.dtype,
                            page_size=8, **kw)


# ---------------------------------------------------------------------------
# protocol coverage (headless: record mode, no pallas, no shard_map)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_persistent_protocol_clean(n):
    """The WHOLE chained multi-layer body — L layers x (attention cell +
    two column-ring AllReduce instances) on one shared semaphore set —
    passes all four static checks at every registry rank count."""
    (case,) = registry.cases_for("persistent_decode", n)
    assert registry.verify_case(case) == []


def test_persistent_chain_structure():
    """Structural evidence of the fusion: ONE recorded body holds every
    stage of every layer.  Ring traffic: 2 layers x 2 AR instances x
    2(n-1) forwards; exactly ONE entry barrier (instance boundaries are
    in-kernel ACK waits, never kernel re-entries); one attention-staging
    local copy per layer; the compute glue (rmsnorm / matmul / swiglu /
    add / attn_decode / copy) all present in the same trace."""
    n = 4
    (case,) = registry.cases_for("persistent_decode", n)
    label, thunk = case.make(0)
    assert label == "chain"
    rec = record_kernel(thunk, n=n, rank=0)
    sig = rec.signature
    layers, instances = 2, 4
    assert sig.count("remote_copy") == instances * 2 * (n - 1)
    assert sig.count("barrier_neighbors") == 1
    assert sig.count("local_copy") == layers       # attn_vm -> attn_hbm
    for kind in ("compute:rmsnorm", "compute:matmul", "compute:swiglu",
                 "compute:add", "compute:attn_decode", "compute:copy"):
        assert kind in sig, kind
    # the chain order: attention precedes the first ring forward, and
    # the final writeback copy is the LAST compute
    assert sig.index("compute:attn_decode") < sig.index("remote_copy")
    assert sig[::-1].index("compute:copy") < sig[::-1].index("remote_copy")


def test_persistent_family_in_default_matrix():
    names = {c.name for c in analysis.all_cases(ranks=(4,))}
    assert "persistent_decode/chain" in names


def test_persistent_fault_cells_name_interlayer_semaphores():
    """Every fault class lands a verdict on the chain; must-detect
    classes name the pending semaphore, and at least one detection names
    the SHARED re-armed set (ack/recv/ag) — the inter-layer edge."""
    rows = rz.run_persistent_cells(seed=0)
    assert rows, "no persistent cells ran"
    kinds = {r["fault"] for r in rows}
    assert {"drop_notify", "stale_credit", "rank_abort",
            "corrupt_payload"} <= kinds
    for row in rows:
        assert row["outcome"] in ("detected", "survived"), row
        if row["fault"] in {k.value for k in rz.matrix.MUST_DETECT}:
            assert row["outcome"] == "detected", row
            assert row["named"], row
    chain = ("ack_sems", "recv_sems", "ag_recv_sems")
    assert any(any(s in nm for s in chain)
               for r in rows if r["outcome"] == "detected"
               for nm in r["named"])
    assert rz.verify_matrix(rows, min_kernels_per_class=1) == []


def test_persistent_watchdog_deadline_and_static_diagnosis():
    from triton_distributed_tpu.resilience import watchdog

    d = watchdog.deadline_ms("persistent_decode", payload_bytes=1 << 22,
                             num_ranks=4)
    assert 0 < d < float("inf")
    diag = watchdog.protocol_pending("persistent_decode", 4)
    assert diag is not None
    sems = diag.semaphores()
    assert any("recv_sems" in s or "ack_sems" in s for s in sems), sems


def test_persistent_costs_registered():
    from triton_distributed_tpu.obs import costs

    c = costs.FAMILY_COSTS["persistent_decode"](
        4, 8, 2048, 16, 8, 4096, 128, 512, 4, jnp.bfloat16)
    assert c.flops > 0 and c.bytes_accessed > 0
    assert c.wire_bytes > 0                  # 2L chained reductions
    assert c.transcendentals > 0             # softmax + rope + silu
    assert costs.sol_ms(c) > 0
    # composes linearly in L: the chain is L of the PR-8 layer
    c1 = costs.FAMILY_COSTS["persistent_decode"](
        1, 8, 2048, 16, 8, 4096, 128, 512, 4, jnp.bfloat16)
    assert c.flops == 4 * c1.flops


def test_persistent_candidates_default_first_and_deduped():
    cands = persistent_decode_candidates(8, 512, 512)
    assert cands[0] == PersistentDecodeConfig(
        bm=8, bn=512, bk=512, bf=512)
    assert len(cands) == len(set(cands))
    tiny = persistent_decode_candidates(1, 64, 16)
    assert all(c.bm == 1 for c in tiny)


def test_persistent_mode_registered_and_scoped():
    assert "persistent" in DECODE_MODES
    m = _model()
    assert m.decode_mode == "persistent"
    cache = _cache(_mesh(), 2)
    assert m._persistent_ok(cache)
    # int8 pools are out of scope (in-kernel append cannot re-encode a
    # page scale): the router falls back, the entry refuses loudly
    qcache = _cache(_mesh(), 2, kv_dtype="int8")
    assert not m._persistent_ok(qcache)
    params = m.init(jax.random.key(0), scale=0.05)
    sp = stack_decode_params(params)
    with pytest.raises(NotImplementedError, match="int8"):
        pdm.persistent_decode_step(
            jnp.zeros((2, CFG.hidden), CFG.dtype), sp, qcache.k, qcache.v,
            qcache.block_table, qcache.seq_lens, _mesh())


def test_stack_decode_params_shapes():
    m = _model()
    params = m.init(jax.random.key(1), scale=0.05)
    sp = stack_decode_params(params)
    L, K, D = CFG.num_layers, CFG.hidden, CFG.head_dim
    assert sp.ln1.shape == (L, K) and sp.ln2.shape == (L, K)
    assert sp.wqkv.shape == (
        L, K, (CFG.num_heads + 2 * CFG.num_kv_heads) * D)
    assert sp.q_norm.shape == (L, D) and sp.k_norm.shape == (L, D)
    assert sp.wo.shape == (L, CFG.num_heads * D, K)
    assert sp.gate_up.shape == (L, K, 2 * CFG.intermediate)
    assert sp.down.shape == (L, CFG.intermediate, K)


# ---------------------------------------------------------------------------
# dispatch accounting (headless: the <= 2 per-bundle structure)


def test_bundle_harness_adds_exactly_one_dispatch(monkeypatch):
    """With the megakernel stubbed to contribute ZERO launch-shaped
    equations, the traced step bundle (embed gather + lax.scan + final
    norm + lm_head + argmax feedback) counts exactly ONE dispatch — the
    lm_head GEMM.  The module builds exactly one pallas_call, so the
    real bundle is <= 2 per token window (the
    decode_dispatches_per_bundle claim, measured live on slices)."""
    m = _model()
    params = m.init(jax.random.key(0), scale=0.05)
    cache = _cache(_mesh(), 2)
    tok = jnp.zeros((2,), jnp.int32)
    monkeypatch.setattr(
        pdm, "persistent_decode_step",
        lambda x, sp, pk, pv, table, lens, mesh, axis=TP_AXIS, **kw:
        (x, pk, pv))
    assert pdm.count_bundle_dispatches(m, params, cache, tok, 4) == 1
    with open(pdm.__file__) as f:
        assert f.read().count("pl.pallas_call(") == 1


def test_decode_bundle_scan_counts_body_once():
    """The generic bundle harness: a step whose body is one dot counts
    ONE dispatch regardless of the step count — lax.scan, not an
    unrolled loop, so the bundle's jaxpr stays O(1) in steps."""
    from triton_distributed_tpu.ops.fused_decode import (
        count_jaxpr_dispatches,
    )

    w = jnp.zeros((8, 8), jnp.float32)

    def step(carry, tok):
        logits = jnp.dot(carry, w)
        return logits, carry

    for steps in (1, 4, 16):
        n = count_jaxpr_dispatches(
            lambda c, t: pdm.decode_bundle(step, c, t, steps),
            jnp.zeros((2, 8)), jnp.zeros((2,), jnp.int32))
        assert n == 1, (steps, n)


# ---------------------------------------------------------------------------
# real numerics on the n == 1 reference path (runs on ANY jax build)


def test_bundle_equals_single_steps_tp1():
    """The acceptance parity at model level: N single ``decode`` steps
    == one N-step ``decode_multi`` bundle — tokens, ragged lengths and
    the page pools byte-compare."""
    mesh = _mesh()
    m = _model()
    params = m.init(jax.random.key(0), scale=0.05)
    cache = _cache(mesh, 3)
    ids = jax.random.randint(jax.random.key(1), (3, 5), 0, CFG.vocab)
    logits, cache = jax.jit(m.prefill_chunk)(
        params, cache, ids, jnp.int32(0), jnp.int32(5))
    tok = jnp.argmax(logits[:, 4], -1).astype(jnp.int32)

    c1, t = cache, tok
    singles = []
    for _ in range(3):
        lg, c1 = jax.jit(m.decode)(params, c1, t)
        t = jnp.argmax(lg, -1).astype(jnp.int32)
        singles.append(t)
    toks2, c2 = jax.jit(m.decode_multi, static_argnums=3)(
        params, cache, tok, 3)
    assert bool((jnp.stack(singles) == toks2).all())
    np.testing.assert_array_equal(np.asarray(c1.seq_lens),
                                  np.asarray(c2.seq_lens))
    np.testing.assert_array_equal(np.asarray(c1.k), np.asarray(c2.k))
    np.testing.assert_array_equal(np.asarray(c1.v), np.asarray(c2.v))


def test_reference_step_matches_prefill_chunk_golden():
    """The persistent reference (append + block-table attention + MLP)
    against the INDEPENDENT plain-jnp chunked-prefill implementation:
    prefill 5 then persistent-decode token #6 must equal prefilling all
    6 in one chunk — logits at the step position and the full pools."""
    mesh = _mesh()
    m = _model()
    params = m.init(jax.random.key(0), scale=0.05)
    rng = np.random.default_rng(7)
    prompt = jnp.asarray(rng.integers(0, CFG.vocab, (2, 6)), jnp.int32)

    cA = _cache(mesh, 2)
    _, cA = jax.jit(m.prefill_chunk)(params, cA, prompt[:, :5],
                                     jnp.int32(0), jnp.int32(5))
    logitsA, cA = jax.jit(m.decode)(params, cA, prompt[:, 5])

    cB = _cache(mesh, 2)
    lgB, cB = jax.jit(m.prefill_chunk)(params, cB, prompt, jnp.int32(0),
                                       jnp.int32(6))
    assert np.allclose(np.asarray(logitsA), np.asarray(lgB[:, 5]),
                       atol=1e-4, rtol=1e-4)
    assert np.allclose(np.asarray(cA.k), np.asarray(cB.k), atol=1e-5)
    assert np.allclose(np.asarray(cA.v), np.asarray(cB.v), atol=1e-5)


# ---------------------------------------------------------------------------
# config hoist + AOT bucket set (tp=2 on the virtual mesh; the
# megakernel entry is stubbed — its protocol is covered above, the
# plumbing under test here is the serving path around it)


def _stub_entry(captured):
    def stub(x, sp, pk, pv, table, lens, mesh, axis=TP_AXIS, **kw):
        captured.append(kw.get("config"))
        return x, pk, pv

    return stub


def _engine2(**kw):
    return Engine.build(CFG, _mesh(2), key=jax.random.key(0), batch=2,
                        decode_mode="persistent", cache_layout="paged",
                        page_size=8, **kw)


def test_config_hoist_resolved_once_and_threaded(monkeypatch):
    """The ISSUE-13 autotuner hoist: a winner planted in the tuner cache
    before backend construction is adopted at __init__ (one consult per
    backend, not per dispatch) and reaches the TRACED bundle —
    ``resolve_config`` is never consulted again from inside
    ``decode_multi``."""
    from triton_distributed_tpu.core import platform
    from triton_distributed_tpu.tune import autotuner as at

    eng = _engine2()
    n = 2
    c = CFG
    winner = persistent_decode_candidates(
        2, c.intermediate // n, c.hidden // n)[1]
    key = pdm.persistent_config_key(
        c.num_layers, 2, c.hidden, c.intermediate, c.num_kv_heads, 8,
        c.max_length // 8, c.head_dim, n, jnp.dtype(c.dtype))
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=os.devnull))
    at._GLOBAL._resolved[("persistent_decode", tuple(map(str, key)))] = \
        winner

    from triton_distributed_tpu.serve import EngineBackend

    backend = EngineBackend(eng, pool_pages=13, steps_per_dispatch=3)
    assert backend.steps_per_dispatch == 3
    assert backend._persistent_cfg == winner

    captured = []
    monkeypatch.setattr(pdm, "persistent_decode_step",
                        _stub_entry(captured))
    resolves = []
    orig_resolve = at.resolve_config

    def spy_resolve(*a, **k):
        resolves.append(a[0])
        return orig_resolve(*a, **k)

    monkeypatch.setattr(at, "resolve_config", spy_resolve)
    cache = backend.make_cache()
    cache, toks = backend.decode_multi(cache, np.zeros(2, np.int32), 3)
    assert toks.shape == (3, 2)
    assert captured and all(cfg == winner for cfg in captured)
    assert "persistent_decode" not in resolves   # hoisted: zero consults
    del platform


def test_precompile_decode_bucket_set(monkeypatch):
    """The AOT bucket set: ``precompile_decode`` pre-compiles the
    (batch, steps-bucket) grid, windowed dispatches run the compiled
    executables, and serializing on a CPU (interpret) build refuses
    loudly like ``Engine.precompile``."""
    monkeypatch.setattr(pdm, "persistent_decode_step", _stub_entry([]))
    eng = _engine2()

    from triton_distributed_tpu.serve import EngineBackend

    backend = EngineBackend(eng, pool_pages=13, steps_per_dispatch=4)
    manifest = backend.precompile_decode(steps_buckets=(2,))
    assert manifest["steps_buckets"] == [1, 2, 4]
    assert manifest["batch"] == 2
    assert manifest["decode_mode"] == "persistent"
    assert "arch" in manifest and set(backend._decode_exec) == {1, 2, 4}
    cache = backend.make_cache()
    cache, toks = backend.decode_multi(cache, np.zeros(2, np.int32), 4)
    assert toks.shape == (4, 2)
    with pytest.raises(RuntimeError, match="interpret"):
        backend.precompile_decode(save_dir="/tmp/never-written")


def test_load_precompiled_decode_rejects_mismatch(tmp_path, monkeypatch):
    """The manifest rides the PR-2 arch-fingerprint discipline: a bundle
    for a different backend geometry fails at load with the field
    named, BEFORE any executable is touched."""
    monkeypatch.setattr(pdm, "persistent_decode_step", _stub_entry([]))
    eng = _engine2()

    from triton_distributed_tpu.serve import EngineBackend

    backend = EngineBackend(eng, pool_pages=13, steps_per_dispatch=2)
    manifest = backend.precompile_decode()
    with open(tmp_path / EngineBackend._MANIFEST, "w") as f:
        json.dump(manifest, f)
    other = EngineBackend(eng, pool_pages=13, steps_per_dispatch=2,
                          chunk_tokens=32)
    with pytest.raises(ValueError, match="chunk_tokens"):
        other.load_precompiled_decode(str(tmp_path))


# ---------------------------------------------------------------------------
# scheduler windows (headless: SimBackend over the real paged plumbing)


def _window_run(spd, *, seed=3, pool_pages=17, hook=None):
    backend = serve.SimBackend(slots=4, page_size=4,
                               pool_pages=pool_pages, max_length=64,
                               steps_per_dispatch=spd, step_hook=hook)
    sched = serve.Scheduler(backend,
                            serve.SchedulerConfig(max_queue_depth=64))
    arrivals = serve.synthetic_trace(seed, 24, mean_interarrival_steps=0.5,
                                     prompt_len=(2, 12), max_new=(4, 12))
    report = serve.replay(sched, arrivals, max_steps=20_000)
    return sched, report


@pytest.mark.parametrize("spd", [2, 4])
def test_window_token_parity_under_pressure(spd):
    """The acceptance pin: N-step windowed dispatch vs N single steps
    under the REAL scheduler on a pool-pressured trace — identical
    completion sets and token streams (membership changes land between
    windows, preemption re-queues cleanly), zero leaked pages, and
    strictly fewer dispatch windows."""
    s1, r1 = _window_run(1)
    sw, rw = _window_run(spd)
    for s, r in ((s1, r1), (sw, rw)):
        assert r.problems() == []
        assert r.leaked_pages == 0
        assert all(q.tokens == s.backend.expected_tokens(q)
                   for q in r.completed)
    assert sw.preemptions >= 1          # the pressure actually preempted
    assert len(rw.completed) == len(r1.completed) == 24
    assert sorted(tuple(q.tokens) for q in r1.completed) == \
        sorted(tuple(q.tokens) for q in rw.completed)
    assert sw.decode_windows < s1.decode_windows


def test_window_clipped_to_finish_boundary():
    """A window never runs past a member's last token: one request with
    2 decode steps on an 8-step knob completes in ONE window of exactly
    its remaining length."""
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=64, steps_per_dispatch=8)
    sched = serve.Scheduler(backend, serve.SchedulerConfig())
    req = serve.Request(prompt=(5,), max_new_tokens=3)
    sched.submit(req)
    sched.run_until_idle()
    assert req.tokens == backend.expected_tokens(req)
    assert len(req.tokens) == 3
    assert sched.decode_windows == 1    # prefill token + ONE 2-step window
    assert sched.pool.used_pages == 0


def test_midwindow_abort_discards_window_and_isolates():
    """A rank abort at an INNER step of a window: the whole window is
    discarded (non-donated cache), exactly one victim fails with the
    fault named, cohabitants complete with token parity from the intact
    pre-window state, zero pages leak."""
    from triton_distributed_tpu.resilience.faults import RankAborted

    class Inject:
        fired = 0

        def __call__(self, step):
            # step counts INNER steps: 9 lands mid-window at spd=4
            if step == 9 and not self.fired:
                self.fired = 1
                raise RankAborted(1, step)

    inj = Inject()
    sched, report = _window_run(4, pool_pages=33, hook=inj)
    assert inj.fired
    assert report.leaked_pages == 0
    assert report.problems() == []
    assert len(report.failed) == 1
    assert "RankAborted" in (report.failed[0].error or "")
    assert all(q.tokens == sched.backend.expected_tokens(q)
               for q in report.completed)


def test_engine_scheduler_threads_the_knob():
    eng = Engine.build(CFG, _mesh(), key=jax.random.key(0), batch=2,
                       decode_mode="persistent", cache_layout="paged",
                       page_size=8)
    sched = eng.scheduler(pool_pages=13, chunk_tokens=8,
                          steps_per_dispatch=3)
    assert sched.backend.steps_per_dispatch == 3
    # n == 1: the reference path needs no kernel config (hoist is a
    # no-op, not an error)
    assert sched.backend._persistent_cfg is None


def test_scheduler_engine_backend_tp1_window_parity():
    """The REAL model end to end on this container (tp=1 reference
    path): the scheduler + EngineBackend serve the same requests to the
    same tokens whether decode runs step-by-step or in 3-step windows,
    with zero leaked pages."""
    def run(spd):
        eng = Engine.build(CFG, _mesh(), key=jax.random.key(0), batch=3,
                           decode_mode="persistent", cache_layout="paged",
                           page_size=8)
        sched = eng.scheduler(pool_pages=13, chunk_tokens=8,
                              steps_per_dispatch=spd)
        arrivals = serve.synthetic_trace(5, 6, mean_interarrival_steps=0.7,
                                         prompt_len=(2, 6), max_new=(2, 5))
        report = serve.replay(sched, arrivals, max_steps=5000)
        assert report.problems() == []
        assert report.leaked_pages == 0
        assert len(report.completed) == 6
        return sorted(tuple(r.tokens) for r in report.completed)

    assert run(1) == run(3)


# ---------------------------------------------------------------------------
# numerical parity of the REAL megakernel (needs shard_map + Pallas
# interpret: capability-gated, like the PR-8 parity battery)

from triton_distributed_tpu.core.compilation import (  # noqa: E402
    interpret_supported,
)

needs_interpret = pytest.mark.skipif(
    not interpret_supported(),
    reason="jax build lacks shard_map/Pallas-interpret APIs",
)

CFG8 = ModelConfig(
    num_layers=2, hidden=128, intermediate=256, num_heads=8, num_kv_heads=8,
    head_dim=32, vocab=128, max_length=64, dtype=jnp.float32,
)


@needs_interpret
@pytest.mark.parametrize("batch", [3, 8])
def test_persistent_decode_logits_parity_paged(mesh8, batch):
    """decode_mode="persistent" (ONE megakernel for all layers) matches
    the per-kernel psum chain on the paged cache — logits AND the full
    page pools after the step."""
    mesh = mesh8
    params = Qwen3(CFG8, mesh).init(jax.random.key(21), scale=0.05)
    ids = jax.random.randint(jax.random.key(22), (batch, 16), 0,
                             CFG8.vocab)
    step = jax.random.randint(jax.random.key(23), (batch,), 0, CFG8.vocab)

    out = {}
    for mode in ("psum", "persistent"):
        model = Qwen3(CFG8, mesh, decode_mode=mode)
        cache = init_paged_cache(mesh, CFG8.num_layers, batch,
                                 CFG8.num_kv_heads, CFG8.max_length,
                                 CFG8.head_dim, CFG8.dtype, page_size=16)
        _, cache = jax.jit(model.prefill)(params, cache, ids)
        logits, cache = jax.jit(model.decode)(params, cache, step)
        out[mode] = (np.asarray(jax.device_get(logits)),
                     np.asarray(jax.device_get(cache.k)),
                     np.asarray(jax.device_get(cache.v)))
        assert int(cache.seq_lens[0]) == 17
    for got, want, what in zip(out["persistent"], out["psum"],
                               ("logits", "pool_k", "pool_v")):
        assert np.allclose(got, want, atol=2e-3, rtol=2e-3), (
            what, np.abs(got - want).max())


@needs_interpret
def test_persistent_bundle_dispatches_on_slice(mesh8):
    """The acceptance number, measured on the traced jaxpr: the
    persistent step bundle issues <= 2 dispatch-shaped equations — the
    megakernel and the lm_head GEMM — vs 2/layer for the chain."""
    batch = 8
    params = Qwen3(CFG8, mesh8).init(jax.random.key(41), scale=0.05)
    cache = init_paged_cache(mesh8, CFG8.num_layers, batch,
                             CFG8.num_kv_heads, CFG8.max_length,
                             CFG8.head_dim, CFG8.dtype, page_size=16)
    tok = jnp.zeros((batch,), jnp.int32)
    model = Qwen3(CFG8, mesh8, decode_mode="persistent")
    assert pdm.count_bundle_dispatches(model, params, cache, tok, 4) <= 2


# ---------------------------------------------------------------------------
# CI smoke


def test_tdt_lint_persistent_smoke():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "tdt_lint.py"),
         "--persistent"],
        capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "persistent OK" in res.stdout


def test_persistent_resolve_paths_share_the_pruned_candidate_list():
    """All three persistent resolve paths (transparent step, fresh tune,
    EngineBackend hoist) consume ONE pruned sweep: at serving dims the
    default-budget (vmem_limit=None) variant is statically unbuildable
    (~28 MiB streamed weights vs the 16 MiB Mosaic default) and must be
    pruned BEFORE any compile/measure — and pruning must happen in the
    shared helper so the candidates digest (the winner-cache key) stays
    common (review finding on ISSUE 15)."""
    import jax.numpy as jnp

    from triton_distributed_tpu.ops import persistent_decode as pdm

    serving = pdm.persistent_candidates_pruned(
        24, 8, 2048, 6144, 16, 8, 16, 128, 8, jnp.bfloat16)
    assert serving, "pruning emptied the sweep"
    assert all(c.vmem_limit is not None for c in serving), serving
    # tiny dims: the None variant fits 16 MiB and stays in the sweep
    tiny = pdm.persistent_candidates_pruned(
        2, 2, 64, 128, 4, 2, 8, 16, 2, jnp.float32)
    assert any(c.vmem_limit is None for c in tiny), tiny
