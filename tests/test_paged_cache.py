"""Paged (block-table) KV cache and decode: kernel vs contiguous golden
under randomized page maps + RAGGED per-sequence lengths, the SP paged
decode on the mesh, pool write helpers, and engine serving on the paged
layout (reference ``flash_decode.py:587-720`` ``gqa_fwd_batch_decode`` with
``block_table``; ``sp_flash_decode_layer.py:83-108``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.core.mesh import SP_AXIS, TP_AXIS, make_mesh
from triton_distributed_tpu.models import (
    Engine,
    ModelConfig,
    Qwen3,
    append_paged,
    init_cache,
    init_paged_cache,
    write_prefill_paged,
)
from triton_distributed_tpu.ops.attention import (
    decode_attention,
    paged_decode_attention,
)
from triton_distributed_tpu.ops.flash_decode import sp_paged_flash_decode


def _materialize(pool, table, b):
    """(P, Hkv, ps, D) pool + (B, mp) table -> (B, Hkv, mp*ps, D)."""
    gathered = np.asarray(pool)[np.asarray(table)]    # (B, mp, Hkv, ps, D)
    return np.ascontiguousarray(
        gathered.transpose(0, 2, 1, 3, 4)
    ).reshape(b, pool.shape[1], -1, pool.shape[3])


@pytest.mark.parametrize("ragged", [False, True])
def test_paged_decode_matches_contiguous(ragged):
    rng = np.random.default_rng(0)
    b, h, hk, d, ps, mp = 4, 8, 4, 64, 16, 8
    pool_pages = b * mp + 3                       # spare pages stay unused
    lens = (np.asarray([100, 37, 1, 128]) if ragged
            else np.full(b, 96)).astype(np.int32)
    table = rng.permutation(pool_pages)[: b * mp].reshape(b, mp).astype(
        np.int32
    )
    pool_k = rng.standard_normal((pool_pages, hk, ps, d)).astype(np.float32)
    pool_v = rng.standard_normal((pool_pages, hk, ps, d)).astype(np.float32)
    q = rng.standard_normal((b, h, d)).astype(np.float32)

    out = np.asarray(paged_decode_attention(
        jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
        jnp.asarray(table), jnp.asarray(lens),
    ))
    kc = _materialize(pool_k, table, b)
    vc = _materialize(pool_v, table, b)
    for bi in range(b):
        want = decode_attention(
            jnp.asarray(q[bi:bi + 1]), jnp.asarray(kc[bi:bi + 1]),
            jnp.asarray(vc[bi:bi + 1]), int(lens[bi]),
        )
        np.testing.assert_allclose(out[bi], np.asarray(want)[0],
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("n", [2, 8])
def test_sp_paged_decode_matches_single_device(n):
    """Sequence-sharded paged decode == full-cache decode_attention, with a
    randomized per-rank page map and ragged lengths spanning rank
    boundaries."""
    rng = np.random.default_rng(1 + n)
    b, h, hk, d, ps, mp_loc = 2, 4, 2, 64, 8, 4
    s_loc = ps * mp_loc
    p_loc = b * mp_loc
    mesh = make_mesh({SP_AXIS: n}, devices=jax.devices()[:n])
    # lengths: one seq ends mid-rank-0, one spans into the last rank
    lens = np.asarray([ps + 3, (n - 1) * s_loc + 5], np.int32)

    tables = np.stack([
        rng.permutation(p_loc).reshape(b, mp_loc) for _ in range(n)
    ]).astype(np.int32)                               # (n, B, mp_loc)
    pool_k = rng.standard_normal((n * p_loc, hk, ps, d)).astype(np.float32)
    pool_v = rng.standard_normal((n * p_loc, hk, ps, d)).astype(np.float32)
    q = rng.standard_normal((b, h, d)).astype(np.float32)

    got = np.asarray(sp_paged_flash_decode(
        jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
        jnp.asarray(tables), jnp.asarray(lens), mesh,
    ))

    # golden: stitch each rank's materialized slice into the global cache
    kc = np.concatenate([
        _materialize(pool_k[r * p_loc:(r + 1) * p_loc], tables[r], b)
        for r in range(n)
    ], axis=2)
    vc = np.concatenate([
        _materialize(pool_v[r * p_loc:(r + 1) * p_loc], tables[r], b)
        for r in range(n)
    ], axis=2)
    for bi in range(b):
        want = decode_attention(
            jnp.asarray(q[bi:bi + 1]), jnp.asarray(kc[bi:bi + 1]),
            jnp.asarray(vc[bi:bi + 1]), int(lens[bi]),
        )
        np.testing.assert_allclose(got[bi], np.asarray(want)[0],
                                   rtol=2e-5, atol=2e-5)


def test_pool_write_helpers_round_trip():
    """write_prefill_paged + append_paged land every token where the
    contiguous cache puts it (including a partial trailing page and ragged
    appends)."""
    rng = np.random.default_rng(3)
    mesh = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    L, b, hk, ps, max_len, d = 2, 3, 2, 8, 64, 16
    s = 21                                           # partial last page
    cache = init_paged_cache(mesh, L, b, hk, max_len, d, jnp.float32,
                             page_size=ps, key=jax.random.key(0))
    k_new = rng.standard_normal((b, hk, s, d)).astype(np.float32)
    v_new = rng.standard_normal((b, hk, s, d)).astype(np.float32)
    for layer in range(L):
        cache = write_prefill_paged(cache, layer, jnp.asarray(k_new) + layer,
                                    jnp.asarray(v_new) + layer)
    from triton_distributed_tpu.models import with_length

    cache = with_length(cache, s)
    # ragged appends: advance seq 1 by two tokens, others by one
    toks = rng.standard_normal((3, b, hk, d)).astype(np.float32)
    import dataclasses

    cache = append_paged(cache, 0, jnp.asarray(toks[0]), jnp.asarray(toks[0]))
    cache = dataclasses.replace(
        cache, seq_lens=cache.seq_lens + jnp.asarray([0, 1, 0], jnp.int32)
    )
    cache = append_paged(cache, 0, jnp.asarray(toks[1]), jnp.asarray(toks[1]))

    got = _materialize(np.asarray(cache.k[0]), cache.block_table, b)
    for bi in range(b):
        np.testing.assert_array_equal(got[bi, :, :s], k_new[bi])
    # the appends: seq 1's first append went to position s+1's slot? no —
    # append writes at seq_lens[b]: first append at s for all, second at
    # s for seqs 0/2 (overwrite) and s+1 for seq 1
    np.testing.assert_array_equal(got[0, :, s], toks[1][0])
    np.testing.assert_array_equal(got[1, :, s], toks[0][1])
    np.testing.assert_array_equal(got[1, :, s + 1], toks[1][1])
    np.testing.assert_array_equal(got[2, :, s], toks[1][2])


def test_append_paged_exhaustion_raises_typed():
    """ISSUE 6 satellite: a sequence outgrowing its block table raises
    PagePoolExhausted (naming the sequences) on the eager path instead
    of silently scattering into a clamped — i.e. WRONG — page."""
    import dataclasses

    from triton_distributed_tpu.models import PagePoolExhausted

    mesh = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    cache = init_paged_cache(mesh, 1, 2, 2, 16, 8, jnp.float32,
                             page_size=4)
    tok = jnp.ones((2, 2, 8), jnp.float32)
    # at the limit: 16 positions of capacity, seq 1 already at 16
    cache = dataclasses.replace(
        cache, seq_lens=jnp.asarray([3, 16], jnp.int32))
    with pytest.raises(PagePoolExhausted) as ei:
        append_paged(cache, 0, tok, tok)
    assert ei.value.sequences == (1,)
    assert "outgrown" in str(ei.value)
    # in range: both sequences write fine
    cache = dataclasses.replace(
        cache, seq_lens=jnp.asarray([3, 15], jnp.int32))
    append_paged(cache, 0, tok, tok)


@pytest.mark.parametrize("n", [1, 2])
def test_paged_engine_matches_contiguous(n):
    """Greedy generation on the paged engine equals the contiguous engine
    token for token (same weights, same prompts)."""
    cfg = ModelConfig(
        num_layers=2, hidden=64, intermediate=128, num_heads=4,
        num_kv_heads=2, head_dim=32, vocab=128, max_length=64,
        dtype=jnp.float32,
    )
    mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
    ids = jax.random.randint(jax.random.key(21), (2, 16), 0, cfg.vocab)
    eng_c = Engine.build(cfg, mesh, key=jax.random.key(20), batch=2)
    eng_p = Engine.build(cfg, mesh, key=jax.random.key(20), batch=2,
                         cache_layout="paged", page_size=16)
    toks_c = np.asarray(eng_c.generate(ids, 6))
    toks_p = np.asarray(eng_p.generate(ids, 6))
    np.testing.assert_array_equal(toks_c, toks_p)


def test_paged_engine_with_ar_decode_mode():
    """The feature matrix composes: paged cache x fast-AR decode mode
    produce the same greedy tokens as the contiguous psum engine."""
    cfg = ModelConfig(
        num_layers=2, hidden=64, intermediate=128, num_heads=4,
        num_kv_heads=2, head_dim=32, vocab=128, max_length=64,
        dtype=jnp.float32,
    )
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    ids = jax.random.randint(jax.random.key(51), (2, 12), 0, cfg.vocab)
    base = Engine.build(cfg, mesh, key=jax.random.key(50), batch=2)
    combo = Engine.build(cfg, mesh, key=jax.random.key(50), batch=2,
                         cache_layout="paged", page_size=16,
                         decode_mode="ar")
    np.testing.assert_array_equal(
        np.asarray(base.generate(ids, 5)),
        np.asarray(combo.generate(ids, 5)),
    )


def test_paged_model_ragged_decode():
    """Ragged serving: two sequences at different lengths decode in one
    batch and each matches its own single-sequence contiguous decode."""
    cfg = ModelConfig(
        num_layers=2, hidden=64, intermediate=128, num_heads=4,
        num_kv_heads=2, head_dim=32, vocab=128, max_length=64,
        dtype=jnp.float32,
    )
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(cfg, mesh)
    params = model.init(jax.random.key(30), scale=0.05)
    s0, s1 = 24, 10

    # paged batch: prefill the longer prompt for both rows, then set ragged
    # lengths so row 1 only keeps its first s1 positions
    ids = jax.random.randint(jax.random.key(31), (2, s0), 0, cfg.vocab)
    cache = init_paged_cache(mesh, cfg.num_layers, 2, cfg.num_kv_heads,
                             cfg.max_length, cfg.head_dim, cfg.dtype,
                             page_size=8, key=jax.random.key(32))
    _, cache = jax.jit(model.prefill)(params, cache, ids)
    import dataclasses

    cache = dataclasses.replace(
        cache, seq_lens=jnp.asarray([s0, s1], jnp.int32)
    )
    step = jax.random.randint(jax.random.key(33), (2,), 0, cfg.vocab)
    logits, cache = jax.jit(model.decode)(params, cache, step)
    logits = np.asarray(logits)
    assert np.array_equal(np.asarray(cache.seq_lens), [s0 + 1, s1 + 1])

    # goldens: each row alone on a contiguous cache of its true length
    for row, s in ((0, s0), (1, s1)):
        ids_r = ids[row:row + 1, :s]
        cache_r = init_cache(mesh, cfg.num_layers, 1, cfg.num_kv_heads,
                             cfg.max_length, cfg.head_dim, cfg.dtype)
        _, cache_r = jax.jit(model.prefill)(params, cache_r, ids_r)
        want, _ = jax.jit(model.decode)(params, cache_r, step[row:row + 1])
        np.testing.assert_allclose(logits[row], np.asarray(want)[0],
                                   rtol=2e-4, atol=2e-4)
