"""Disaggregated prefill/decode serving and the KV-handoff plane
(ISSUE 12).

Headless like the scheduler tests: two REAL schedulers over
deterministic ``SimBackend``s, the real paged-cache plumbing on both
tiers, and the ``ModeledDCN`` transport (priority wire model + seeded
fault plan) in between — so page bookkeeping, stamp verification, the
transfer ladder, the re-prefill fallback and the colocation shed are
exercised end to end without hardware.
"""

import dataclasses
import os
import random
import subprocess
import sys

import numpy as np
import pytest

from triton_distributed_tpu import obs, resilience, serve
from triton_distributed_tpu.comm import dcn
from triton_distributed_tpu.resilience import integrity
from triton_distributed_tpu.serve import handoff as handoff_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def obs_on():
    prev = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    obs.serve_stats.STATS.reset()
    yield obs
    obs.enable(prev)
    obs.REGISTRY.reset()
    obs.serve_stats.STATS.reset()


@pytest.fixture()
def integrity_on():
    prev = integrity._ENABLED
    integrity.enable(True)
    yield integrity
    integrity.enable(prev)


@pytest.fixture(autouse=True)
def _fresh_handoff_breaker():
    """The handoff breaker is process-global ladder state: no test may
    inherit (or donate) an open breaker."""
    resilience.reset_breaker(serve.HANDOFF_OP)
    yield
    resilience.reset_breaker(serve.HANDOFF_OP)


def _two_tier(*, faults=(), seed=1, decode_slots=3, decode_pool=32,
              prefill_pool=24, plane_cfg=None, router_cfg=None):
    pre = serve.Scheduler(
        serve.SimBackend(slots=3, page_size=4, pool_pages=prefill_pool,
                         max_length=48),
        serve.SchedulerConfig(max_queue_depth=32, prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=decode_slots, page_size=4,
                         pool_pages=decode_pool, max_length=48),
        serve.SchedulerConfig(max_queue_depth=32))
    plane = serve.HandoffPlane(
        dcn_channel=serve.ModeledDCN(faults=faults, seed=seed),
        config=plane_cfg)
    return serve.DisaggRouter(pre, dec, plane=plane, config=router_cfg)


def _submit_load(router, n=6, seed=0, max_new=(3, 8)):
    rng = random.Random(seed)
    reqs = [
        serve.Request(prompt=tuple(rng.randrange(1, 90)
                                   for _ in range(rng.randint(2, 6))),
                      max_new_tokens=rng.randint(*max_new))
        for _ in range(n)
    ]
    for r in reqs:
        assert router.submit(r)
    return reqs


def _assert_all_done_with_parity(router, reqs):
    backend = router.prefill.backend
    for r in reqs:
        assert r.state is serve.RequestState.DONE, (r.req_id, r.state,
                                                    r.error)
        assert r.tokens == backend.expected_tokens(r), r.req_id
    assert router.leaked_pages() == 0


# ---------------------------------------------------------------------------
# the priority-classed DCN wire model


def test_priority_wire_latency_preempts_bulk():
    """FAST's discipline at the port: a LATENCY send queued behind a
    multi-chunk bulk stream waits at most ONE chunk's serialization,
    never the stream."""
    wire = dcn.PriorityDCNWire(gbps=1.0, hop_us=0.0,
                               chunk_bytes=1 << 20)
    bulk_ms = wire.send(64 << 20, priority=dcn.BULK)   # 64 chunks
    lat_ms = wire.send(1 << 20, priority=dcn.LATENCY)
    chunk_ms = (1 << 20) / 1e9 * 1e3
    assert lat_ms < bulk_ms
    # wait component bounded by one chunk residual
    assert lat_ms <= 2 * chunk_ms + 1e-9
    # the same transfer WITHOUT priority queues behind the whole stream
    tail_ms = wire.send(1 << 20, priority=dcn.BULK)
    assert tail_ms > 64 * chunk_ms


def test_priority_wire_fifo_within_class_and_tick():
    wire = dcn.PriorityDCNWire(gbps=1.0, hop_us=0.0)
    a = wire.send(1 << 20, priority=dcn.LATENCY)
    b = wire.send(1 << 20, priority=dcn.LATENCY)
    assert b > a                         # FIFO within the class
    assert wire.backlog_ms(dcn.LATENCY) > 0
    wire.tick(1e9)
    assert wire.backlog_ms(dcn.LATENCY) == 0.0
    assert wire.backlog_ms(dcn.BULK) == 0.0
    with pytest.raises(ValueError):
        wire.send(1, priority=7)


def test_priority_wire_tick_drains_latency_first():
    wire = dcn.PriorityDCNWire(gbps=1.0, hop_us=0.0)
    wire.send(2 << 20, priority=dcn.BULK)
    wire.send(2 << 20, priority=dcn.LATENCY)
    one_chunk_ms = (2 << 20) / 1e9 * 1e3
    wire.tick(one_chunk_ms)
    assert wire.backlog_ms(dcn.LATENCY) == 0.0
    assert wire.backlog_ms(dcn.BULK) == pytest.approx(one_chunk_ms)


# ---------------------------------------------------------------------------
# payload: extract / verify / implant


def _prefilled_pair(kv_dtype=None, prompt=(5, 9, 14, 3, 7)):
    """One request prefilled on a producer scheduler; a fresh consumer
    scheduler of the same geometry."""
    pre = serve.Scheduler(
        serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                         max_length=32, kv_dtype=kv_dtype),
        serve.SchedulerConfig(max_queue_depth=8, prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                         max_length=32, kv_dtype=kv_dtype),
        serve.SchedulerConfig(max_queue_depth=8))
    req = serve.Request(prompt=prompt, max_new_tokens=4)
    pre.submit(req)
    for _ in range(20):
        pre.step()
        if pre.handoff_ready():
            break
    assert pre.handoff_ready()
    return pre, dec, req


@pytest.mark.parametrize("wire_dtype", ["raw", "int8"])
def test_extract_implant_round_trip(wire_dtype):
    pre, dec, req = _prefilled_pair()
    i = pre.handoff_ready()[0]
    slot = pre.slots[i]
    payload = handoff_mod.extract_payload(
        pre.cache, slot.pages, req, slot.next_token,
        wire_dtype=wire_dtype)
    assert payload.wire == wire_dtype
    assert payload.n_pages == serve.pages_needed(req.prompt_len, 4)
    assert handoff_mod.verify_payload(payload) is None
    ok = dec.adopt_prefilled(
        req, lambda c, p: handoff_mod.implant_payload(c, p, payload),
        length=payload.prompt_len, next_token=payload.first_token)
    assert ok
    j = next(k for k, s in enumerate(dec.slots) if s is not None)
    src = np.asarray(pre.cache.k[:, [int(p) for p in slot.pages[
        :payload.n_pages]]])
    dst = np.asarray(dec.cache.k[:, [int(p) for p in dec.slots[j].pages[
        :payload.n_pages]]])
    if wire_dtype == "raw":
        np.testing.assert_array_equal(src, dst)
    else:
        # int8 wire: round-trip bounded by the codec's per-row envelope
        from triton_distributed_tpu.lang import quant

        bound = float(np.abs(src).max()) * quant.rel_error_bound("int8")
        assert float(np.abs(src - dst).max()) <= bound + 1e-6


def test_extract_auto_wire_consults_codec_economics():
    pre, _, req = _prefilled_pair()
    i = pre.handoff_ready()[0]
    slot = pre.slots[i]
    payload = handoff_mod.extract_payload(
        pre.cache, slot.pages, req, slot.next_token, wire_dtype="auto")
    from triton_distributed_tpu.tools import calibrate

    row_width = int(np.prod(payload.page_shape))
    want = "int8" if calibrate.codec_pays("dcn", row_width) else "raw"
    assert payload.wire == want


def test_int8_pool_ships_pages_and_sidecars_verbatim():
    pre, dec, req = _prefilled_pair(kv_dtype="int8")
    i = pre.handoff_ready()[0]
    slot = pre.slots[i]
    payload = handoff_mod.extract_payload(
        pre.cache, slot.pages, req, slot.next_token, wire_dtype="auto")
    assert payload.wire == "pool"
    assert payload.k.dtype == np.int8 and payload.k_scale is not None
    ok = dec.adopt_prefilled(
        req, lambda c, p: handoff_mod.implant_payload(c, p, payload),
        length=payload.prompt_len, next_token=payload.first_token)
    assert ok
    j = next(k for k, s in enumerate(dec.slots) if s is not None)
    pids_src = [int(p) for p in slot.pages[:payload.n_pages]]
    pids_dst = [int(p) for p in dec.slots[j].pages[:payload.n_pages]]
    np.testing.assert_array_equal(
        np.asarray(pre.cache.k[:, pids_src]),
        np.asarray(dec.cache.k[:, pids_dst]))
    np.testing.assert_array_equal(
        np.asarray(pre.cache.k_scale[:, pids_src]),
        np.asarray(dec.cache.k_scale[:, pids_dst]))


def test_verify_payload_names_corrupt_page():
    pre, _, req = _prefilled_pair()
    i = pre.handoff_ready()[0]
    slot = pre.slots[i]
    payload = handoff_mod.extract_payload(
        pre.cache, slot.pages, req, slot.next_token, wire_dtype="raw")
    bad = payload.copy()
    pg = np.ascontiguousarray(bad.k[:, 1])
    pg.view(np.uint8).reshape(-1)[3] ^= 0xFF
    bad.k[:, 1] = pg
    diag = handoff_mod.verify_payload(bad)
    assert diag is not None
    assert diag.chunk == "page[1]"
    assert "stamp" in diag.note


def test_verify_payload_flags_stale_stamp_sidecar():
    pre, _, req = _prefilled_pair()
    i = pre.handoff_ready()[0]
    slot = pre.slots[i]
    payload = handoff_mod.extract_payload(
        pre.cache, slot.pages, req, slot.next_token, wire_dtype="raw")
    stale = payload.copy()
    stale.stamps = {j: (s ^ 0xDEAD) & 0xFFFFFFFF
                    for j, s in stale.stamps.items()}
    assert handoff_mod.verify_payload(stale) is not None
    missing = payload.copy()
    missing.stamps = {0: payload.stamps[0]}   # sidecar from a SHORTER
    diag = handoff_mod.verify_payload(missing)  # previous transfer
    assert diag is not None and "sidecar" in diag.note


# ---------------------------------------------------------------------------
# the transfer ladder (plane level)


def _payload_for_plane():
    pre, _, req = _prefilled_pair()
    i = pre.handoff_ready()[0]
    slot = pre.slots[i]
    return handoff_mod.extract_payload(
        pre.cache, slot.pages, req, slot.next_token, wire_dtype="raw")


def test_plane_clean_transfer_delivers(obs_on):
    plane = serve.HandoffPlane(dcn_channel=serve.ModeledDCN())
    out = plane.transfer(_payload_for_plane())
    assert out is not None
    assert plane.delivered == 1 and plane.retries == 0
    assert plane.handoff_ms and plane.handoff_ms[0] > 0
    snap = obs.serve_stats.STATS.snapshot()
    assert snap["handoff_ms"]["count"] == 1
    assert snap["handoff_pages_total"] == out.n_pages


def test_plane_retry_recovers_first_attempt_corruption():
    plane = serve.HandoffPlane(dcn_channel=serve.ModeledDCN(
        faults=[serve.WireFault(serve.HandoffFault.CORRUPT_PAGE, 0,
                                attempts=1)]))
    out = plane.transfer(_payload_for_plane())
    assert out is not None
    assert plane.retries == 1
    assert plane.corruptions and "page[" in plane.corruptions[0]["chunk"]
    # the retried payload that landed is byte-clean
    assert handoff_mod.verify_payload(out) is None


def test_plane_drop_exhausts_ladder_to_none():
    plane = serve.HandoffPlane(dcn_channel=serve.ModeledDCN(
        faults=[serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 0)]))
    assert plane.transfer(_payload_for_plane()) is None
    assert plane.exhausted == 1
    assert plane.retries == plane.cfg.max_retries
    assert plane.dcn.drops == plane.cfg.max_retries + 1


def test_plane_breaker_opens_and_short_circuits():
    """Three ladder-bottom failures open the sticky handoff breaker;
    the next transfer goes straight to the re-prefill cue WITHOUT
    touching the wire, and /healthz would report the op degraded."""
    plane = serve.HandoffPlane(dcn_channel=serve.ModeledDCN(
        faults=[serve.WireFault(serve.HandoffFault.TRANSFER_DROP, t)
                for t in range(3)]))
    for _ in range(3):
        assert plane.transfer(_payload_for_plane()) is None
    assert resilience.breaker(serve.HANDOFF_OP).open
    attempts_before = plane.dcn.transfers
    assert plane.transfer(_payload_for_plane()) is None
    assert plane.dcn.transfers == attempts_before   # wire never touched
    snap = resilience.health_snapshot()
    assert serve.HANDOFF_OP in snap["degraded_ops"]


# ---------------------------------------------------------------------------
# the router: end-to-end two-tier behavior


def test_disagg_happy_path_parity_and_zero_leaks():
    router = _two_tier()
    reqs = _submit_load(router)
    router.run_until_idle(max_steps=2000)
    _assert_all_done_with_parity(router, reqs)
    assert router.handoffs > 0
    assert router.reprefills == 0
    # decode work actually ran on the decode tier
    assert len(router.decode.completed) == router.handoffs


def test_handoff_ttft_observed_once_per_request(obs_on):
    router = _two_tier()
    reqs = _submit_load(router, n=4)
    router.run_until_idle(max_steps=2000)
    _assert_all_done_with_parity(router, reqs)
    snap = obs.serve_stats.STATS.snapshot()
    assert snap["ttft_ms"]["count"] == len(reqs)


def test_drop_rides_ladder_to_reprefill_on_decode_tier():
    router = _two_tier(faults=[
        serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 1)])
    reqs = _submit_load(router)
    router.run_until_idle(max_steps=4000)
    _assert_all_done_with_parity(router, reqs)
    assert router.reprefills == 1
    assert router.plane.exhausted == 1
    # the re-prefilled request completed on the DECODE tier
    rid = next(iter(router.reprefill_ids))
    assert any(r.req_id == rid for r in router.decode.completed)


def test_prefill_abort_mid_handoff_reprefills():
    router = _two_tier(faults=[
        serve.WireFault(serve.HandoffFault.PREFILL_ABORT, 1)])
    reqs = _submit_load(router)
    router.run_until_idle(max_steps=4000)
    _assert_all_done_with_parity(router, reqs)
    assert router.aborts == 1 and router.reprefills == 1


def test_decode_saturation_sheds_to_colocated():
    router = _two_tier(decode_slots=1, decode_pool=3)
    reqs = _submit_load(router)
    router.run_until_idle(max_steps=4000)
    _assert_all_done_with_parity(router, reqs)
    assert router.colocated > 0
    # the colocated requests decoded on the PREFILL tier
    assert len(router.prefill.completed) == router.colocated


def test_router_submit_load_balances_on_queue_pressure():
    """The telemetry-driven routing: a pressured prefill tier with a
    healthy decode tier routes fresh submits COLOCATED to the decode
    tier (queue-depth gauge as the signal)."""
    router = _two_tier(router_cfg=serve.RouterConfig(queue_pressure=0.2))
    rng = random.Random(3)
    for _ in range(10):
        router.submit(serve.Request(
            prompt=tuple(rng.randrange(1, 90) for _ in range(4)),
            max_new_tokens=4))
    # prefill queue crossed 0.2 * 32 ≈ 6: later submits landed on the
    # decode tier directly
    assert router.decode.queue.depth > 0


def test_router_requires_prefill_only_tier():
    sched = serve.Scheduler(
        serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                         max_length=32),
        serve.SchedulerConfig())
    with pytest.raises(ValueError, match="prefill_only"):
        serve.DisaggRouter(sched, sched)


def test_router_rejects_mismatched_page_geometry():
    """Mismatched tier page shapes must fail FAST at construction — not
    crash the first handoff with a raw shape error mid-step."""
    pre = serve.Scheduler(
        serve.SimBackend(slots=2, page_size=16, pool_pages=8,
                         max_length=32),
        serve.SchedulerConfig(prefill_only=True))
    dec = serve.Scheduler(
        serve.SimBackend(slots=2, page_size=8, pool_pages=16,
                         max_length=32),
        serve.SchedulerConfig())
    with pytest.raises(ValueError, match="page geometries differ"):
        serve.DisaggRouter(pre, dec)


def test_mixed_kv_dtype_tiers_handoff_and_reprefill(integrity_on):
    """A float-pool prefill tier feeding an int8-pool decode tier: the
    implant requantizes, and the re-prefill fallback must NOT carry the
    producer's f32 pool stamps (the int8 recompute is byte-different by
    design — carrying them would fail every re-prefill spuriously)."""
    pre = serve.Scheduler(
        serve.SimBackend(slots=3, page_size=4, pool_pages=24,
                         max_length=48),
        serve.SchedulerConfig(max_queue_depth=32, prefill_only=True,
                              kv_audit_interval_steps=1))
    dec = serve.Scheduler(
        serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                         max_length=48, kv_dtype="int8"),
        serve.SchedulerConfig(max_queue_depth=32))
    router = serve.DisaggRouter(pre, dec, plane=serve.HandoffPlane(
        dcn_channel=serve.ModeledDCN(faults=[
            serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 1)])))
    assert not router._stamp_carry_ok
    reqs = _submit_load(router, n=4, seed=9)
    router.run_until_idle(max_steps=4000)
    _assert_all_done_with_parity(router, reqs)
    assert router.reprefills == 1
    rid = next(iter(router.reprefill_ids))
    victim = next(r for r in reqs if r.req_id == rid)
    assert victim.state is serve.RequestState.DONE
    assert victim.kv_stamps is None      # never carried cross-layout


def test_router_health_aggregates_tiers():
    # decode tier small enough that queued work blocks on PAGES (the
    # saturation latch) while every request still eventually fits
    router = _two_tier(decode_slots=3, decode_pool=5)
    snap = router.health()
    assert snap["status"] == "ok"
    assert set(snap["tiers"]) == {"prefill", "decode"}
    # force decode-tier saturation: queued work it cannot admit
    rng = random.Random(5)
    for _ in range(4):
        router.decode.submit(serve.Request(
            prompt=tuple(rng.randrange(1, 90) for _ in range(4)),
            max_new_tokens=2))
    router.decode.step()
    snap = router.health()
    assert snap["status"] == "saturated"
    assert snap["saturated_tiers"] == ["decode"]
    # drain: flips back
    router.run_until_idle(max_steps=2000)
    assert router.health()["status"] == "ok"


# ---------------------------------------------------------------------------
# the re-prefill carry: recompute verified like a preemption restore


def test_reprefill_carries_stamps_and_verifies(integrity_on):
    router = _two_tier(faults=[
        serve.WireFault(serve.HandoffFault.TRANSFER_DROP, 1)])
    # audit every step so prompt pages are stamped by handoff time
    router.prefill.cfg.kv_audit_interval_steps = 1
    reqs = _submit_load(router, n=4, seed=7)
    router.run_until_idle(max_steps=4000)
    _assert_all_done_with_parity(router, reqs)
    assert router.reprefills == 1
    rid = next(iter(router.reprefill_ids))
    victim = next(r for r in reqs if r.req_id == rid)
    # the carry was consumed by a SUCCESSFUL restore verification
    assert victim.kv_stamps is None


def test_reprefill_divergent_recompute_fails_named(integrity_on):
    """A poisoned carry (the producer's stamps do not match the
    recompute) must FAIL the request with the page named — neither copy
    can be trusted."""
    dec = serve.Scheduler(
        serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                         max_length=32),
        serve.SchedulerConfig(max_queue_depth=8))
    req = serve.Request(prompt=(5, 9, 14, 3, 7), max_new_tokens=4)
    req.kv_stamps = {0: 0xBAD}   # a stamp the recompute cannot match
    dec.submit(req)
    for _ in range(40):
        if dec.step().idle:
            break
    assert req.state is serve.RequestState.FAILED
    assert "PayloadCorruption" in req.error and "stamp" in req.error


# ---------------------------------------------------------------------------
# TDT_SCRUB_PAGES: poison-fill on free (ISSUE 12 satellite)


def test_scrub_pages_poisons_recycled_pages(monkeypatch):
    """With TDT_SCRUB_PAGES=1 a completed request's freed pages read
    the POISON pattern, not the previous tenant's token history — any
    stale-read bug (a handoff mapping a recycled page included) trips
    deterministically."""
    from triton_distributed_tpu.serve import budget

    monkeypatch.setenv("TDT_SCRUB_PAGES", "1")
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=32)
    sched = serve.Scheduler(backend, serve.SchedulerConfig())
    req = serve.Request(prompt=(5, 9, 14, 3), max_new_tokens=3)
    sched.submit(req)
    pages = None
    for _ in range(40):
        for s in sched.slots:
            if s is not None and s.request is req:
                pages = [int(p) for p in s.pages]
        if sched.step().idle:
            break
    assert req.state is serve.RequestState.DONE and pages
    # read BEFORE rewrite: every recycled page holds the poison
    got = np.asarray(sched.cache.k[:, pages])
    assert np.all(got == budget.POISON_FLOAT), got
    # and NOT the token history the previous tenant wrote
    assert not np.any(np.isin(got, np.asarray(req.prompt, np.float32)))


def test_scrub_disabled_keeps_stale_bytes(monkeypatch):
    """The contrast pin: without the flag, freed pages keep the
    previous tenant's bytes — exactly the hazard the poison surfaces."""
    monkeypatch.delenv("TDT_SCRUB_PAGES", raising=False)
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=32)
    sched = serve.Scheduler(backend, serve.SchedulerConfig())
    req = serve.Request(prompt=(5, 9, 14, 3), max_new_tokens=3)
    sched.submit(req)
    pages = None
    for _ in range(40):
        for s in sched.slots:
            if s is not None and s.request is req:
                pages = [int(p) for p in s.pages]
        if sched.step().idle:
            break
    assert req.state is serve.RequestState.DONE and pages
    got = np.asarray(sched.cache.k[:, pages])
    assert np.any(np.isin(got, np.asarray(req.prompt, np.float32)))


def test_scrub_int8_pool_uses_int8_poison(monkeypatch):
    from triton_distributed_tpu.serve import budget

    monkeypatch.setenv("TDT_SCRUB_PAGES", "1")
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=32, kv_dtype="int8")
    sched = serve.Scheduler(backend, serve.SchedulerConfig())
    req = serve.Request(prompt=(5, 9, 14, 3), max_new_tokens=3)
    sched.submit(req)
    pages = None
    for _ in range(40):
        for s in sched.slots:
            if s is not None and s.request is req:
                pages = [int(p) for p in s.pages]
        if sched.step().idle:
            break
    assert req.state is serve.RequestState.DONE and pages
    got = np.asarray(sched.cache.k[:, pages])
    assert got.dtype == np.int8
    assert np.all(got == budget.POISON_INT8)


# ---------------------------------------------------------------------------
# the fault matrix's handoff cells + CI smoke


def test_handoff_matrix_cells_all_classified():
    rows = resilience.run_handoff_matrix(seed=0)
    assert {r["fault"] for r in rows} == \
        {k.value for k in serve.HANDOFF_FAULT_KINDS}
    assert resilience.verify_handoff_matrix(rows) == []
    for row in rows:
        want = "survived" if row["fault"] == "decode_saturated" \
            else "detected"
        assert row["outcome"] == want, row


def test_verify_handoff_matrix_flags_missing_class():
    rows = resilience.run_handoff_matrix(seed=0)
    problems = resilience.verify_handoff_matrix(
        [r for r in rows if r["fault"] != "stale_stamp"])
    assert any("stale_stamp" in p for p in problems)


def test_tdt_lint_handoff_smoke():
    """The tier-1 CI hook (like the --serve / --integrity smokes): the
    seeded two-tier replay with a drop, a corrupt page and a prefill
    abort injected, plus the handoff fault cells."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--handoff"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "handoff OK" in proc.stdout
    assert "DETECTED" in proc.stdout and "SURVIVED" in proc.stdout
