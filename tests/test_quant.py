"""Low-precision wire and KV (ISSUE 9): codec round-trip envelopes,
error-feedback convergence, the quantized KV-cache layout, capacity
math, the quantized-variant protocol/fault coverage, and the
calibrate-driven wire policy.

Everything here is CPU-safe (no shard_map, no compiled Pallas): the
codec and cache paths are pure jnp, the protocol/fault legs run the
record-mode verifier, and the multi-rank wire paths are covered by the
static verifier at ranks {2,4,8} (kernel parity itself is pinned by the
capability-gated mesh tests like every collective)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.core.mesh import make_mesh
from triton_distributed_tpu.lang import quant


def _mesh1():
    return make_mesh({"tp": 1}, devices=jax.devices()[:1])


# ---------------------------------------------------------------------------
# codec round-trip property tests


def _edge_rows(h: int, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return np.stack([
        rng.standard_normal(h) * 3.0,                 # generic
        rng.standard_normal(h) * 40.0,                # large dynamic range
        -np.abs(rng.standard_normal(h)) - 0.5,        # all-negative
        rng.standard_normal(h) * 1e-30,               # denormal-range
        np.zeros(h),                                  # absmax-zero
        np.where(np.arange(h) == 3, 7.0, 1e-6),       # one dominant spike
    ]).astype(np.float32)


@pytest.mark.parametrize("wire_dtype", ["fp8", "int8"])
@pytest.mark.parametrize("h", [16, 128, 1000])
def test_codec_roundtrip_error_envelope(wire_dtype, h):
    """Every row class round-trips inside the documented envelope
    (``abs_error_bound`` = rel bound x row absmax + the SCALE_EPS
    floor), including all-negative, denormal, and absmax-zero rows."""
    rows = _edge_rows(h)
    back = np.asarray(quant.roundtrip_rows(
        jnp.asarray(rows), wire_dtype, out_dtype=jnp.float32))
    absmax = np.abs(rows).max(axis=-1, keepdims=True)
    tol = np.asarray(quant.abs_error_bound(absmax, wire_dtype))
    err = np.abs(back - rows)
    assert (err <= tol * (1 + 1e-5)).all(), (
        f"{wire_dtype}: max err {err.max():.3g} vs tol {tol.max():.3g}")
    # the absmax-zero row must round-trip exactly
    np.testing.assert_array_equal(back[4], 0.0)


@pytest.mark.parametrize("wire_dtype", ["fp8", "int8"])
def test_pack_unpack_wire_message(wire_dtype):
    """The one-message wire layout: H payload bytes + a 128-lane sidecar
    whose first 4 bytes are the row's f32 scale; unpack == the bare
    codec round-trip."""
    h = 96
    rows = _edge_rows(h, seed=1)
    x = jnp.asarray(rows)
    packed = quant.pack_rows(x, wire_dtype)
    assert packed.shape == (rows.shape[0], h + quant.SIDECAR)
    assert packed.dtype == jnp.uint8
    pk = np.asarray(packed)
    # sidecar bytes past the scale are zero padding
    assert (pk[:, h + 4:] == 0).all()
    # the embedded scale is the quantizer's scale, byte-exact
    _, scale = quant.quantize_rows(x, wire_dtype)
    embedded = pk[:, h:h + 4].copy().view(np.float32)[:, 0]
    np.testing.assert_array_equal(embedded,
                                  np.asarray(scale, np.float32)[:, 0])
    # decoded equivalence with the bare round-trip
    back = np.asarray(quant.unpack_rows(packed, h, wire_dtype,
                                        jnp.float32))
    want = np.asarray(quant.roundtrip_rows(x, wire_dtype,
                                           out_dtype=jnp.float32))
    np.testing.assert_allclose(back, want, atol=1e-7)


def test_packed_width_and_wire_ratio():
    assert quant.packed_width(7168, "fp8") == 7168 + 128
    assert quant.packed_width(7168, "bf16") == 2 * 7168
    # the claims-gate floor: quantized moves <= 0.55x the bf16 bytes at
    # serving widths
    assert quant.wire_ratio(7168, "fp8") <= 0.55
    assert quant.wire_ratio(1024, "int8") <= 0.57


# ---------------------------------------------------------------------------
# error feedback: repeated quantized reductions must not drift


@pytest.mark.parametrize("wire_dtype", ["fp8", "int8"])
def test_ar_error_feedback_convergence(wire_dtype):
    """Chained quantized reductions WITH error feedback keep the running
    mean of outputs converging to the exact sum (the EF residual cancels
    the codec's bias), while the per-call error never exceeds one codec
    envelope — over N calls the EF mean error must shrink well below
    the no-EF mean error."""
    rng = np.random.default_rng(3)
    n, m, r = 4, 8, 32
    parts = jnp.asarray(rng.standard_normal((n, m, r)) * 2.0, jnp.float32)
    exact = np.asarray(parts, np.float64).sum(axis=0)

    def reduce_once(p, residuals):
        q, scale, new_res = quant.ef_quantize_rows(p, wire_dtype,
                                                   residuals)
        deq = quant.dequantize_rows(q, scale, jnp.float32)
        return np.asarray(deq, np.float64).sum(axis=0), new_res

    n_iter = 64
    res = jnp.zeros((n, m, r), jnp.float32)
    acc_ef = np.zeros((m, r))
    acc_plain = np.zeros((m, r))
    for _ in range(n_iter):
        out_ef, res = reduce_once(parts, res)
        out_plain, _ = reduce_once(parts, None)
        acc_ef += out_ef
        acc_plain += out_plain
        # bounded drift per call: n partials, each inside one envelope
        bound = n * float(quant.abs_error_bound(
            float(jnp.max(jnp.abs(parts))), wire_dtype))
        # EF folds the residual in, so the instantaneous error can reach
        # ~2x the envelope; it must stay bounded, not grow with t
        assert np.abs(out_ef - exact).max() <= 2.5 * bound
    err_ef = np.abs(acc_ef / n_iter - exact).max()
    err_plain = np.abs(acc_plain / n_iter - exact).max()
    # the plain codec's bias is deterministic (same inputs -> same
    # rounding); EF's time-average converges toward exact
    assert err_ef <= max(0.25 * err_plain, 1e-4), (
        f"EF mean err {err_ef:.2e} vs plain {err_plain:.2e}")


def test_quantized_all_reduce_error_feedback_api():
    """The EF option on the quantized AR entry: residual in, (out,
    residual) out; repeated calls stay bounded (the n==1 path runs the
    same codec semantics the mesh path ships)."""
    from triton_distributed_tpu.comm import quantized_all_reduce

    mesh = _mesh1()
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((8, 32)), jnp.float32)
    res = jnp.zeros_like(x)
    outs = []
    for _ in range(16):
        out, res = quantized_all_reduce(x, mesh, "tp", wire_dtype="int8",
                                        residual=res)
        outs.append(np.asarray(out, np.float64))
    exact = np.asarray(x, np.float64)
    mean_err = np.abs(np.mean(outs, axis=0) - exact).max()
    one_err = np.abs(outs[0] - exact).max()
    assert mean_err <= max(0.25 * one_err, 1e-5)
    # without residual: plain value return
    out = quantized_all_reduce(x, mesh, "tp", wire_dtype="int8")
    assert out.shape == x.shape


# ---------------------------------------------------------------------------
# the eager entries' wire_dtype plumbing (degenerate mesh)


def test_quantized_entries_degenerate_mesh():
    from triton_distributed_tpu.comm import (
        quantized_all_gather,
        quantized_reduce_scatter,
    )

    mesh = _mesh1()
    x = jnp.asarray(np.random.default_rng(0).standard_normal((16, 64)),
                    jnp.float32)
    for wd in ("fp8", "int8"):
        got = quantized_all_gather(x, mesh, "tp", wire_dtype=wd)
        want = quant.roundtrip_rows(x, wd)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
        got = quantized_reduce_scatter(x, mesh, "tp", wire_dtype=wd)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
    # bf16 wire_dtype on the public entries is the identity path
    from triton_distributed_tpu.comm import all_gather, all_reduce

    np.testing.assert_array_equal(
        np.asarray(all_gather(x, mesh, "tp", wire_dtype="fp8")),
        np.asarray(x))   # n == 1: no wire, no codec
    np.testing.assert_array_equal(
        np.asarray(all_reduce(x, mesh, "tp", wire_dtype="int8")),
        np.asarray(x))


# ---------------------------------------------------------------------------
# quantized KV cache


def _mk_cache(kv_dtype, *, layers=2, batch=3, heads=4, max_len=64,
              page_size=8, head_dim=16):
    from triton_distributed_tpu.models import kv_cache as kvc

    return kvc.init_paged_cache(
        _mesh1(), layers, batch, heads, max_len, head_dim, jnp.float32,
        page_size=page_size, kv_dtype=kv_dtype,
        key=jax.random.key(7),   # fragmented page map, like a real pool
    )


def _dense(cache, layer):
    from triton_distributed_tpu.models import kv_cache as kvc

    k, v = kvc.layer_pool(cache, layer, jnp.float32)
    b, mp = cache.block_table.shape
    hk, ps, d = k.shape[1], cache.page_size, k.shape[-1]
    kd = k[cache.block_table].transpose(0, 2, 1, 3, 4).reshape(
        b, hk, mp * ps, d)
    vd = v[cache.block_table].transpose(0, 2, 1, 3, 4).reshape(
        b, hk, mp * ps, d)
    return np.asarray(kd), np.asarray(vd)


def test_quantized_cache_init_layout():
    c = _mk_cache("int8")
    assert c.quantized
    assert c.k.dtype == jnp.int8 and c.v.dtype == jnp.int8
    assert c.k_scale.shape == (2, 3 * 8, 4)
    assert c.k_scale.dtype == jnp.float32
    bf = _mk_cache(None)
    assert not bf.quantized and bf.k_scale is None


def test_quantized_prefill_append_chunk_roundtrip():
    """write_prefill_paged + append_paged + write_chunk_paged on the
    int8 layout land within the int8 page envelope of the bf16 truth."""
    from triton_distributed_tpu.models import kv_cache as kvc

    rng = np.random.default_rng(2)
    b, hk, d = 3, 4, 16
    k0 = jnp.asarray(rng.standard_normal((b, hk, 20, d)), jnp.float32)
    v0 = jnp.asarray(rng.standard_normal((b, hk, 20, d)), jnp.float32)
    kt = jnp.asarray(rng.standard_normal((b, hk, d)) * 3.0, jnp.float32)
    kch = jnp.asarray(rng.standard_normal((b, hk, 5, d)), jnp.float32)

    c = _mk_cache("int8")
    c = kvc.write_prefill_paged(c, 0, k0, v0)
    c = kvc.with_length(c, 20)
    c = kvc.append_paged(c, 0, kt, kt)           # unaligned position 20
    c = kvc.write_chunk_paged(c, 0, kch, kch, 21)  # unaligned chunk
    kd, vd = _dense(c, 0)

    def tol(n_trips):
        # per-(page, head) scales: each write to a partially-filled page
        # dequant-merge-requants it (the documented layout), so a row
        # written then requantized n-1 times carries n half-step errors,
        # each bounded by the envelope at the page's absmax <= the
        # global absmax of everything ever merged onto it.
        am = float(max(np.abs(k0).max(), np.abs(kt).max(),
                       np.abs(kch).max()))
        return n_trips * float(quant.abs_error_bound(am, "int8")) * (
            1 + 1e-5)

    # page_size=8: positions 16-19 share page 2 with the append (pos 20)
    # and the chunk head (21-23) -> 3 round-trips; pos 20 is requantized
    # once by the chunk -> 2; positions 0-15 are never touched again.
    assert np.abs(kd[:, :, :16] - np.asarray(k0)[:, :, :16]).max() <= tol(1)
    assert np.abs(kd[:, :, 16:20] - np.asarray(k0)[:, :, 16:]).max() <= tol(3)
    assert np.abs(kd[:, :, 20] - np.asarray(kt)).max() <= tol(2)
    assert np.abs(kd[:, :, 21:26] - np.asarray(kch)).max() <= tol(1)


def test_quantized_chunk_write_traced_start():
    """One jitted executable serves every chunk position (the serving
    scheduler's retrace-freedom contract) on the quantized layout."""
    from triton_distributed_tpu.models import kv_cache as kvc

    rng = np.random.default_rng(4)
    b, hk, d, s = 2, 2, 8, 6
    c = _mk_cache("int8", batch=b, heads=hk, head_dim=d)
    ch1 = jnp.asarray(rng.standard_normal((b, hk, s, d)), jnp.float32)
    ch2 = jnp.asarray(rng.standard_normal((b, hk, s, d)), jnp.float32)

    write = jax.jit(lambda cache, k, v, start: kvc.write_chunk_paged(
        cache, 0, k, v, start))
    c = write(c, ch1, ch1, jnp.int32(0))
    c = write(c, ch2, ch2, jnp.int32(s))
    kd, _ = _dense(c, 0)
    want = np.concatenate([np.asarray(ch1), np.asarray(ch2)], axis=2)
    bound = float(quant.abs_error_bound(float(np.abs(want).max()),
                                        "int8"))
    assert np.abs(kd[:, :, :2 * s] - want).max() <= bound * (1 + 1e-5)


def test_append_layer_quantized_matches_append_paged():
    """The layer-slice quantized append (the decode shard_map body and
    the megakernel's post-kernel scatter) matches the stacked-cache
    append exactly."""
    from triton_distributed_tpu.models import kv_cache as kvc

    rng = np.random.default_rng(6)
    b, hk, d = 3, 4, 16
    k0 = jnp.asarray(rng.standard_normal((b, hk, 16, d)), jnp.float32)
    tok = jnp.asarray(rng.standard_normal((b, hk, d)), jnp.float32)
    c = _mk_cache("int8")
    c = kvc.write_prefill_paged(c, 0, k0, k0)
    c = kvc.with_length(c, 16)

    via_cache = kvc.append_paged(c, 0, tok, tok)
    pk, pv, ksc, vsc = kvc.append_layer_quantized(
        c.k[0], c.v[0], c.k_scale[0], c.v_scale[0],
        c.block_table, c.seq_lens, tok, tok)
    np.testing.assert_array_equal(np.asarray(via_cache.k[0]),
                                  np.asarray(pk))
    np.testing.assert_array_equal(np.asarray(via_cache.k_scale[0]),
                                  np.asarray(ksc))


def test_kv_page_bytes_capacity_math():
    """The ISSUE-9 capacity claim at serving geometry: int8 pages cost
    <= 0.55x the bf16 bytes, so one byte budget holds >= 1.8x pages."""
    from triton_distributed_tpu.models.kv_cache import kv_page_bytes

    bf16 = kv_page_bytes(4, 8, 64, 128, jnp.bfloat16, None)
    int8 = kv_page_bytes(4, 8, 64, 128, jnp.bfloat16, "int8")
    assert int8 / bf16 <= 0.55
    assert bf16 // int8 >= 1 and (10 * bf16) // int8 >= 18  # >= 1.8x pages


def test_dequantize_pool_and_serving_cache():
    from triton_distributed_tpu.models import kv_cache as kvc

    c = kvc.init_serving_cache(_mesh1(), 2, 4, 2, 64, 8, jnp.float32,
                               page_size=8, kv_dtype="int8")
    assert c.quantized and c.k.dtype == jnp.int8
    deq = kvc.dequantize_pool(c, jnp.float32)
    assert not deq.quantized and deq.k.dtype == jnp.float32
    # scrap-page layout preserved
    assert int(c.block_table.max()) == 0


def test_engine_rejects_contiguous_kv_dtype():
    from triton_distributed_tpu.models import Engine, ModelConfig

    cfg = ModelConfig(num_layers=1, hidden=64, intermediate=128,
                      num_heads=4, num_kv_heads=2, head_dim=16, vocab=64,
                      max_length=32)
    with pytest.raises(ValueError, match="paged"):
        Engine.build(cfg, _mesh1(), key=jax.random.key(0),
                     cache_layout="contiguous", kv_dtype="int8")


# ---------------------------------------------------------------------------
# serving over the int8 cache (the real scheduler, headless)


def test_scheduler_int8_cache_tokens_and_pages():
    """The continuous-batching scheduler over an int8 pool: tokens are
    IDENTICAL to the bf16 run (the Sim rule is KV-independent — this
    pins the cache plumbing, not the model), pages dequantize to the
    token history within the int8 envelope, zero pages leak."""
    from triton_distributed_tpu import serve
    from triton_distributed_tpu.models import kv_cache as kvc

    def run(kv_dtype):
        backend = serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                                   max_length=64, kv_dtype=kv_dtype)
        sched = serve.Scheduler(backend, serve.SchedulerConfig())
        reqs = [serve.Request(prompt=tuple(range(1, 7 + i)),
                              max_new_tokens=6) for i in range(3)]
        for r in reqs:
            sched.submit(r)
        sched.run_until_idle()
        assert sched.pool.used_pages == 0
        return sched, reqs

    sched_q, reqs_q = run("int8")
    _, reqs_f = run(None)
    for rq, rf in zip(reqs_q, reqs_f):
        assert rq.state is serve.RequestState.DONE
        assert rq.tokens == rf.tokens
    assert sched_q.cache.quantized


# ---------------------------------------------------------------------------
# protocol / fault / lint coverage


def test_quant_registry_cases_verify_clean():
    from triton_distributed_tpu import analysis

    results = analysis.verify_all(ranks=(2, 4, 8), kernel_filter="quant")
    assert len(results) == 9          # 3 variants x 3 rank counts
    for case, violations in results:
        assert not violations, f"{case.name}: {violations}"


def test_quant_corruption_cells_detected_and_named():
    from triton_distributed_tpu import resilience

    rows = resilience.run_quant_cells(seed=0)
    assert len(rows) == 6             # 3 kernels x 2 corruption classes
    for row in rows:
        assert row["outcome"] == "detected", row
        assert row["named"], row
    assert not resilience.verify_matrix(
        rows, kinds=resilience.CORRUPTION_KINDS)


def test_quant_selftest_battery_clean():
    from triton_distributed_tpu.resilience import integrity

    assert integrity.run_quant_selftest() == []


def test_scale_sidecar_poison_is_checksum_caught():
    """A flipped scale-sidecar byte: fold32 moves (wire checksum catches
    it) AND the dequant error explodes past the codec envelope (parity
    tolerance could never absorb it — the checksum is the guard)."""
    from triton_distributed_tpu.resilience.integrity import fold32

    h = 64
    x = jnp.asarray(_edge_rows(h)[0][None], jnp.float32)
    packed = np.asarray(quant.pack_rows(x, "fp8"))
    poisoned = packed.copy()
    poisoned[0, h + 3] ^= 0x14       # exponent bits of the f32 scale
    assert fold32(packed) != fold32(poisoned)
    good = np.asarray(quant.unpack_rows(jnp.asarray(packed), h, "fp8",
                                        jnp.float32))
    bad = np.asarray(quant.unpack_rows(jnp.asarray(poisoned), h, "fp8",
                                       jnp.float32))
    delta = np.abs(bad - good).max()
    envelope = float(quant.abs_error_bound(float(np.abs(good).max()),
                                           "fp8"))
    assert not np.isfinite(delta) or delta > 10 * envelope


def test_integrity_fold_page_covers_scales():
    """The KV-pool audit stamp must move when ONLY a scale flips (at-rest
    scale corruption poisons a whole (page, head) block on dequant)."""
    from triton_distributed_tpu.resilience import integrity

    c = _mk_cache("int8")
    before = integrity.fold_page(c, 1)
    poisoned = dataclasses.replace(
        c, k_scale=c.k_scale.at[0, 1, 0].multiply(4.0))
    assert integrity.fold_page(poisoned, 1) != before


def test_verify_reduce_q_clean_and_catches():
    from triton_distributed_tpu.resilience import integrity

    rng = np.random.default_rng(9)
    n, m_loc, r = 4, 4, 16
    parts = rng.standard_normal((n, n * m_loc, r)).astype(np.float32)
    golden = np.asarray(quant.reduce_roundtrip(
        jnp.asarray(parts.reshape(n, n, m_loc, r)), "fp8",
        out_dtype=jnp.float32)).reshape(n * m_loc, r)
    x = parts.reshape(n * n * m_loc, r)
    assert integrity.verify_reduce_q("rs_fp8", x, golden, n, "fp8") is None
    bad = golden.copy()
    bad[0, 0] += 50.0
    diag = integrity.verify_reduce_q("rs_fp8", x, bad, n, "fp8")
    assert diag is not None and diag.kind == "payload"


# ---------------------------------------------------------------------------
# MoE wire policy (satellites): shared codec + calibrate-driven auto


def test_moe_consumes_shared_codec():
    """The MoE layer's historic names are aliases of the shared module —
    no duplicate pack/unpack body remains."""
    import inspect

    from triton_distributed_tpu.layers import moe

    assert moe._FP8_SIDECAR == quant.SIDECAR
    src = inspect.getsource(moe)
    assert "bitcast_convert_type" not in src     # the duplicate is gone
    x = jnp.asarray(_edge_rows(64)[:2], jnp.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(moe._pack_fp8(x)), np.asarray(quant.pack_rows(x, "fp8")))


def test_fp8_wire_auto_fast_wire_stays_off():
    """The missing fast-wire case (satellite): on an ICI-class axis the
    "auto" codec resolves OFF — through the calibrate-threshold policy,
    not a hard-coded class rule."""
    from triton_distributed_tpu.layers.moe import MoEMLP

    mesh = _mesh1()
    layer = MoEMLP(mesh=mesh, num_experts=4, top_k=2, axis="tp",
                   fp8_wire="auto")
    assert layer.fp8_wire_enabled() is False
    # a DCN-named axis resolves ON with the cold-start economics
    dcn_mesh = make_mesh({"dcn_ep": 1}, devices=jax.devices()[:1])
    dcn_layer = MoEMLP(mesh=dcn_mesh, num_experts=4, top_k=2,
                       axis="dcn_ep", fp8_wire="auto")
    assert dcn_layer.fp8_wire_enabled() is True


def test_codec_pays_reads_calibration(tmp_path, monkeypatch):
    """The wire-class decision reads tools/calibrate thresholds: a
    persisted calibration that makes the ICI wire SLOW flips the ICI
    decision on, and a fast-DCN calibration flips DCN off — the policy
    follows the measurement, not the axis name."""
    import json

    from triton_distributed_tpu.tools import calibrate

    path = tmp_path / "linkcal.json"
    monkeypatch.setenv("TDT_LINKCAL_CACHE", str(path))
    calibrate.invalidate_cache()
    try:
        assert calibrate.codec_pays("ici") is False   # cold start
        assert calibrate.codec_pays("dcn") is True
        path.write_text(json.dumps({
            "ici_gbps": 2.0, "ici_hop_us": 1.0,
            "dcn_gbps": 500.0, "dcn_hop_us": 5.0,
            "device_kind": "test", "n_devices": 8}))
        calibrate.invalidate_cache()
        assert calibrate.codec_pays("ici") is True    # slow wire: pays
        assert calibrate.codec_pays("dcn") is False   # fast wire: off
    finally:
        calibrate.invalidate_cache()


# ---------------------------------------------------------------------------
# bench records (deterministic legs)


def test_bench_wire_and_kv_quant_records():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "_bench_quant", os.path.join(os.path.dirname(__file__), "..",
                                     "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    rec = bench.bench_wire_bytes()
    assert rec["value"] >= 1.82                   # the claims-gate floor
    assert rec["static_ratio"] == pytest.approx(14336 / 7296, rel=1e-4)
    par = bench.bench_wire_parity()
    assert par["value"] <= 1.05                   # inside the envelope
    kvq = bench.bench_serve_kv_quant()
    assert kvq["value"] >= 1.8                    # the acceptance number
    assert kvq["page_bytes_int8"] / kvq["page_bytes_bf16"] <= 0.55


# ---------------------------------------------------------------------------
# capability-gated mesh tests: quantized KV decode token-parity against
# the bf16 pool (the acceptance gate — these pin the KERNEL side the
# CPU-safe cache tests above cannot reach; skipped where the jax build
# lacks the shard_map/Pallas-interpret APIs, like every mesh test)

from triton_distributed_tpu.core import compilation  # noqa: E402

needs_interpret = pytest.mark.skipif(
    not compilation.interpret_supported(),
    reason="jax build lacks shard_map/Pallas-interpret APIs",
)


@needs_interpret
def test_quantized_paged_decode_kernel_parity():
    """The int8 page-streaming decode == attention over the MATERIALIZED
    dequantized pool (tight: same values, fusion only), and stays within
    the derived envelope of the original full-precision pool."""
    from triton_distributed_tpu.models import kv_cache as kvc
    from triton_distributed_tpu.ops.attention import paged_decode_attention

    rng = np.random.default_rng(7)
    b, h, hk, d, ps, mp = 2, 8, 4, 64, 16, 4
    p = b * mp
    lens = jnp.asarray([37, 61], jnp.int32)
    table = jnp.asarray(
        rng.permutation(p).reshape(b, mp).astype(np.int32))
    pool_k = jnp.asarray(rng.standard_normal((p, hk, ps, d)), jnp.float32)
    pool_v = jnp.asarray(rng.standard_normal((p, hk, ps, d)), jnp.float32)
    q = jnp.asarray(rng.standard_normal((b, h, d)), jnp.float32)

    qk, sk = kvc._quantize_pages(pool_k)
    qv, sv = kvc._quantize_pages(pool_v)
    out_q = np.asarray(paged_decode_attention(
        q, qk, qv, table, lens, k_scale=sk, v_scale=sv))

    # (1) kernel parity: fused in-loop dequant vs the dequantized pool
    want = np.asarray(paged_decode_attention(
        q, kvc._dequantize_pages(qk, sk), kvc._dequantize_pages(qv, sv),
        table, lens))
    np.testing.assert_allclose(out_q, want, rtol=2e-5, atol=2e-5)

    # (2) envelope parity vs the ORIGINAL pool: V-side error is a convex
    # combination of per-element codec errors (<= env_v); K-side error
    # perturbs each score by <= sm_scale * sum|q| * env_k, and softmax
    # weight L1 sensitivity is <= 2*max|dS|, scaled by max|V|
    env_k = float(quant.abs_error_bound(
        float(jnp.abs(pool_k).max()), "int8"))
    env_v = float(quant.abs_error_bound(
        float(jnp.abs(pool_v).max()), "int8"))
    sm_scale = d ** -0.5
    ds = sm_scale * float(jnp.abs(q).sum(-1).max()) * env_k
    bound = env_v + 2.0 * ds * float(jnp.abs(pool_v).max())
    base = np.asarray(paged_decode_attention(q, pool_k, pool_v, table,
                                             lens))
    assert np.abs(out_q - base).max() <= bound


@needs_interpret
def test_qwen_paged_decode_int8_token_parity():
    """End-to-end decode on the int8 cache vs the SAME model on the bf16
    pool: the logits stay inside an envelope-scaled band, and where the
    full-precision greedy choice is decisive (top-2 gap beyond the
    band), the quantized pool picks the SAME token."""
    import dataclasses as _dc

    from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
    from triton_distributed_tpu.models import (ModelConfig, Qwen3,
                                               init_paged_cache)

    cfg = ModelConfig(
        num_layers=2, hidden=64, intermediate=128, num_heads=4,
        num_kv_heads=2, head_dim=32, vocab=128, max_length=64,
        dtype=jnp.float32,
    )
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(cfg, mesh)
    params = model.init(jax.random.key(40), scale=0.05)
    ids = jax.random.randint(jax.random.key(41), (2, 20), 0, cfg.vocab)
    step = jax.random.randint(jax.random.key(42), (2,), 0, cfg.vocab)

    def run(kv_dtype):
        cache = init_paged_cache(
            mesh, cfg.num_layers, 2, cfg.num_kv_heads, cfg.max_length,
            cfg.head_dim, cfg.dtype, page_size=8,
            key=jax.random.key(43), kv_dtype=kv_dtype)
        _, cache = jax.jit(model.prefill)(params, cache, ids)
        logits, cache = jax.jit(model.decode)(params, cache, step)
        return np.asarray(logits), cache

    logits_b, _ = run(None)
    logits_q, cache_q = run("int8")
    assert cache_q.quantized and cache_q.k.dtype == jnp.int8

    # dtype-scaled band: two layers of int8 KV noise through the model;
    # 64x the bare codec envelope of the logit magnitude is a loose
    # sanity band that still catches a dropped/misapplied scale (those
    # move logits by the 127/absmax encoding factor, orders of magnitude
    # outside it)
    band = 64.0 * quant.rel_error_bound("int8") * (
        float(np.abs(logits_b).max()) + 1.0)
    assert np.abs(logits_q - logits_b).max() <= band

    top2 = np.sort(logits_b, axis=-1)[:, -2:]
    decisive = (top2[:, 1] - top2[:, 0]) > 2.0 * band
    tok_b = logits_b.argmax(-1)
    tok_q = logits_q.argmax(-1)
    assert np.array_equal(tok_b[decisive], tok_q[decisive])


def test_quantized_writes_ignore_stale_recycled_page_bytes():
    """A recycled page carries the previous tenant's bytes
    (``serve.budget.PagePool.free`` does not scrub): the quantized
    merge must NOT fold those into the (page, head) absmax — a stale
    large value would inflate the scale and crush the new tenant's
    precision.  Covers append_paged, append_layer_quantized (via the
    exact-match contract), and write_chunk_paged."""
    from triton_distributed_tpu.models import kv_cache as kvc

    # simulate recycling: every pool page holds a large-magnitude
    # tenant's bytes (|K| ~ 127 at scale 1.0)
    def poison(c):
        return dataclasses.replace(
            c,
            k=jnp.full_like(c.k, 127), v=jnp.full_like(c.v, 127),
            k_scale=jnp.full_like(c.k_scale, 1.0),
            v_scale=jnp.full_like(c.v_scale, 1.0))

    b, hk, d = 3, 4, 16
    small = 0.01
    tol = float(quant.abs_error_bound(small, "int8")) * (1 + 1e-5)

    # append into a FRESH (stale) page: pos 8 = page 1 slot 0
    c = poison(_mk_cache("int8"))
    c = kvc.with_length(c, 8)
    tok = jnp.full((b, hk, d), small, jnp.float32)
    c = kvc.append_paged(c, 0, tok, tok)
    kd, _ = _dense(c, 0)
    assert np.abs(kd[:, :, 8] - small).max() <= tol

    # chunk write into stale pages: positions [9, 21) span pages 1-2
    ch = jnp.full((b, hk, 12, d), small, jnp.float32)
    c = kvc.write_chunk_paged(c, 0, ch, ch, 9)
    kd, _ = _dense(c, 0)
    # the earlier appended token requantized once more (page 1 touched)
    assert np.abs(kd[:, :, 8] - small).max() <= 2 * tol
    assert np.abs(kd[:, :, 9:21] - small).max() <= tol
