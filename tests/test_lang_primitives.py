"""Primitive-level tests (reference analogue: test_distributed_wait.py,
test_notify.py, test_nvshmem_api.py — SURVEY.md section 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.core import compilation, mesh as mesh_lib
from triton_distributed_tpu.core.utils import assert_allclose
from triton_distributed_tpu import lang


def _run(mesh, kernel_fn, x, out_shape, scratch_shapes, collective_id=7):
    def f(xs):
        return pl.pallas_call(
            kernel_fn,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=scratch_shapes,
            compiler_params=compilation.compiler_params(collective_id=collective_id),
            interpret=compilation.interpret_mode(),
        )(xs)

    g = compilation.jit_shard_map(f, mesh, in_specs=P("tp"), out_specs=P("tp"))
    return g(x)


def test_ring_push(mesh8):
    """Each device pushes its shard to its right neighbor (putmem_signal)."""
    n = 8
    shape = (8, 128)

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        copy = lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right)
        copy.wait()

    x = jnp.arange(n * shape[0] * shape[1], dtype=jnp.float32).reshape(n * shape[0], shape[1])
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((shape[0], shape[1]), jnp.float32),
        [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
    )
    expect = jnp.roll(x.reshape(n, *shape), 1, axis=0).reshape(n * shape[0], shape[1])
    assert_allclose(out, expect, atol=0, rtol=0)


def test_notify_wait_producer_consumer(mesh8):
    """Producer rank pushes data + notifies; consumer waits then reads
    (tutorial-01 equivalent: the reference's producer-consumer queue)."""

    def kernel(x_ref, o_ref, ready_sem, send_sem, recv_sem):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        # push data into neighbor's output buffer (completion sems consumed),
        # then notify the consumer with a REGULAR semaphore — the dl.notify /
        # dl.wait pair of the reference, decoupled from the DMA itself.
        copy = lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, dst)
        copy.wait()
        lang.notify(ready_sem, dst, inc=1)
        # consumer side: wait for the producer's notify, then scale the data.
        lang.wait(ready_sem, 1)

        def scale(scratch, sem):
            lang.local_copy(o_ref, scratch, sem).wait()
            scratch[:] = scratch[:] * 2.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(scale, pltpu.VMEM((8, 128), jnp.float32), pltpu.SemaphoreType.DMA)

    x = jnp.tile(jnp.arange(8, dtype=jnp.float32)[:, None], (8, 128))
    x = (x + jnp.repeat(jnp.arange(8, dtype=jnp.float32), 8)[:, None])  # rank-dependent
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((8, 128), jnp.float32),
        [pltpu.SemaphoreType.REGULAR, pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
    )
    expect = 2.0 * jnp.roll(x.reshape(8, 8, 128), 1, axis=0).reshape(64, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


def test_barrier_all(mesh8):
    """barrier_all: no rank proceeds until all arrive (smoke: completes, and
    post-barrier remote reads see pre-barrier writes)."""

    def kernel(x_ref, o_ref, send_sem, recv_sem, bar):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        # everyone pushes to right neighbor, then a full barrier, then doubles
        _, right = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        lang.barrier_all("tp", bar)

        def scale(scratch, sem):
            lang.local_copy(o_ref, scratch, sem).wait()
            scratch[:] = scratch[:] + 1.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(scale, pltpu.VMEM((8, 128), jnp.float32), pltpu.SemaphoreType.DMA)

    x = jnp.ones((64, 128), jnp.float32)
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((8, 128), jnp.float32),
        [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.REGULAR],
    )
    assert_allclose(out, jnp.full((64, 128), 2.0, jnp.float32), atol=0, rtol=0)


def test_rank_num_ranks(mesh8):
    def kernel(x_ref, o_ref):
        def body(scratch, sem):
            scratch[:] = jnp.zeros_like(scratch)
            scratch[0, 0] = lang.rank("tp").astype(jnp.float32)
            scratch[0, 1] = jnp.float32(lang.num_ranks("tp"))
            lang.local_copy(scratch, o_ref, sem).wait()
        pl.run_scoped(body, pltpu.VMEM((1, 128), jnp.float32), pltpu.SemaphoreType.DMA)

    x = jnp.zeros((8, 128), jnp.float32)
    out = _run(mesh8, kernel, x, jax.ShapeDtypeStruct((1, 128), jnp.float32), [])
    got = np.asarray(out)
    for r in range(8):
        assert got[r, 0] == r
        assert got[r, 1] == 8
