"""Primitive-level tests (reference analogue: test_distributed_wait.py,
test_notify.py, test_nvshmem_api.py — SURVEY.md section 4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.core import compilation, mesh as mesh_lib
from triton_distributed_tpu.core.utils import assert_allclose
from triton_distributed_tpu import lang


def _run(mesh, kernel_fn, x, out_shape, scratch_shapes, collective_id=7):
    def f(xs):
        return pl.pallas_call(
            kernel_fn,
            out_shape=out_shape,
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=scratch_shapes,
            compiler_params=compilation.compiler_params(collective_id=collective_id),
            interpret=compilation.interpret_mode(),
        )(xs)

    g = compilation.jit_shard_map(f, mesh, in_specs=P("tp"), out_specs=P("tp"))
    return g(x)


def test_ring_push(mesh8):
    """Each device pushes its shard to its right neighbor (putmem_signal)."""
    n = 8
    shape = (8, 128)

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        copy = lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right)
        copy.wait()

    x = jnp.arange(n * shape[0] * shape[1], dtype=jnp.float32).reshape(n * shape[0], shape[1])
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((shape[0], shape[1]), jnp.float32),
        [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
    )
    expect = jnp.roll(x.reshape(n, *shape), 1, axis=0).reshape(n * shape[0], shape[1])
    assert_allclose(out, expect, atol=0, rtol=0)


def test_notify_wait_producer_consumer(mesh8):
    """Producer rank pushes data + notifies; consumer waits then reads
    (tutorial-01 equivalent: the reference's producer-consumer queue)."""

    def kernel(x_ref, o_ref, ready_sem, send_sem, recv_sem):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        # push data into neighbor's output buffer (completion sems consumed),
        # then notify the consumer with a REGULAR semaphore — the dl.notify /
        # dl.wait pair of the reference, decoupled from the DMA itself.
        copy = lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, dst)
        copy.wait()
        lang.notify(ready_sem, dst, inc=1)
        # consumer side: wait for the producer's notify, then scale the data.
        lang.wait(ready_sem, 1)

        def scale(scratch, sem):
            lang.local_copy(o_ref, scratch, sem).wait()
            scratch[:] = scratch[:] * 2.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(scale, pltpu.VMEM((8, 128), jnp.float32), pltpu.SemaphoreType.DMA)

    x = jnp.tile(jnp.arange(8, dtype=jnp.float32)[:, None], (8, 128))
    x = (x + jnp.repeat(jnp.arange(8, dtype=jnp.float32), 8)[:, None])  # rank-dependent
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((8, 128), jnp.float32),
        [pltpu.SemaphoreType.REGULAR, pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
    )
    expect = 2.0 * jnp.roll(x.reshape(8, 8, 128), 1, axis=0).reshape(64, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


def test_barrier_all(mesh8):
    """barrier_all: no rank proceeds until all arrive (smoke: completes, and
    post-barrier remote reads see pre-barrier writes)."""

    def kernel(x_ref, o_ref, send_sem, recv_sem, bar):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")
        # everyone pushes to right neighbor, then a full barrier, then doubles
        _, right = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        lang.barrier_all("tp", bar)

        def scale(scratch, sem):
            lang.local_copy(o_ref, scratch, sem).wait()
            scratch[:] = scratch[:] + 1.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(scale, pltpu.VMEM((8, 128), jnp.float32), pltpu.SemaphoreType.DMA)

    x = jnp.ones((64, 128), jnp.float32)
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((8, 128), jnp.float32),
        [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.REGULAR],
    )
    assert_allclose(out, jnp.full((64, 128), 2.0, jnp.float32), atol=0, rtol=0)


@pytest.fixture
def race_detection():
    compilation.enable_race_detection(True)
    yield
    compilation.enable_race_detection(False)


def test_team_device_id_3axis_mesh():
    """Team.device_id translates axis ranks to linearized logical ids on a
    3-axis mesh, for teams over the OUTER, MIDDLE, and INNER axis
    (reference ``test_nvshmem_api.py`` team addressing; VERDICT next #8).
    Only the team axis's coordinate is substituted — all others are the
    calling device's own."""
    from triton_distributed_tpu.lang.primitives import Team

    mesh = mesh_lib.make_mesh({"dp": 2, "tp": 2, "sp": 2},
                              devices=jax.devices()[:8])

    def check(axis):
        team = Team.of(mesh, axis)
        n_ax = mesh.shape[axis]

        def body(_):
            ids = jnp.stack([
                jnp.asarray(team.device_id(r), jnp.int32)
                for r in range(n_ax)
            ])
            return ids.reshape(1, 1, 1, n_ax)

        out = compilation.jit_shard_map(
            body, mesh,
            in_specs=P("dp", "tp", "sp"),
            out_specs=P("dp", "tp", "sp", None),
        )(jnp.zeros((2, 2, 2), jnp.float32))
        got = np.asarray(out)                    # (2, 2, 2, n_ax)
        for a in range(2):
            for b in range(2):
                for c in range(2):
                    for r in range(n_ax):
                        coord = {"dp": a, "tp": b, "sp": c}
                        coord[axis] = r
                        want = (coord["dp"] * 2 + coord["tp"]) * 2 + coord["sp"]
                        assert got[a, b, c, r] == want, (axis, a, b, c, r)

    for axis in ("dp", "tp", "sp"):
        check(axis)


def test_barrier_all_reuse_across_kernel_families(mesh8):
    """The per-family global barrier semaphores leave no residue when two
    DIFFERENT kernel families (distinct collective_ids) run barrier_all
    repeatedly inside ONE jitted program (reference
    ``test_nvshmem_api.py:107-302`` exercising barriers between other API
    calls; VERDICT next #8)."""
    n, shape = 8, (8, 128)

    def kern_a(x_ref, o_ref, send_sem, recv_sem):
        # family A: barrier -> ring push -> barrier -> +1
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        lang.barrier_all("tp")

        def bump(scratch, sem):
            lang.local_copy(o_ref, scratch, sem).wait()
            scratch[:] = scratch[:] + 1.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(bump, pltpu.VMEM(shape, jnp.float32),
                      pltpu.SemaphoreType.DMA)

    def kern_b(x_ref, o_ref, ready, send_sem, recv_sem):
        # family B: push LEFT, notify/wait handshake, barrier, x2
        lang.collective_prologue("tp")
        left, _ = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, left).wait()
        lang.notify(ready, left)
        lang.wait(ready, 1)
        lang.barrier_all("tp")

        def dbl(scratch, sem):
            lang.local_copy(o_ref, scratch, sem).wait()
            scratch[:] = scratch[:] * 2.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(dbl, pltpu.VMEM(shape, jnp.float32),
                      pltpu.SemaphoreType.DMA)

    def a(xs):
        return pl.pallas_call(
            kern_a,
            out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
            compiler_params=compilation.compiler_params(collective_id=11),
            interpret=compilation.interpret_mode(),
        )(xs)

    def b(xs):
        return pl.pallas_call(
            kern_b,
            out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
            out_specs=pl.BlockSpec(memory_space=pl.ANY),
            scratch_shapes=[pltpu.SemaphoreType.REGULAR,
                            pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
            compiler_params=compilation.compiler_params(collective_id=12),
            interpret=compilation.interpret_mode(),
        )(xs)

    def prog(xs):
        # A -> B -> A again: both families' barrier semaphores are reused
        # within one program, interleaved with each other's collectives
        return a(b(a(xs)))

    g = compilation.jit_shard_map(prog, mesh8, in_specs=P("tp"),
                                  out_specs=P("tp"))
    x = jnp.arange(n * shape[0] * shape[1], dtype=jnp.float32).reshape(
        n * shape[0], shape[1]
    )
    out = np.asarray(jax.device_get(g(x)))
    # A: roll right then +1; B: roll left then x2; A again
    xr = np.asarray(x).reshape(n, *shape)
    want = np.roll(xr, 1, axis=0) + 1.0
    want = np.roll(want, -1, axis=0) * 2.0
    want = np.roll(want, 1, axis=0) + 1.0
    np.testing.assert_array_equal(out.reshape(n, *shape), want)


def test_interleaved_wait_send_counting(mesh8):
    """Two outstanding remote_copies of DIFFERENT shapes on the SAME send
    semaphore, drained in the OPPOSITE order they were issued: the
    byte-counting drain must match per-transfer sizes regardless of order
    (reference ``nvshmem_quiet`` with multiple nbi puts in flight;
    VERDICT next #8)."""
    n = 8

    def kernel(x_ref, o_ref, send_sem, recv_small, recv_big):
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        # small (8, 128) rows [0, 8) and big (16, 128) rows [8, 24),
        # both in flight on one send semaphore
        small = lang.remote_copy(
            x_ref.at[pl.ds(0, 8)], o_ref.at[pl.ds(0, 8)],
            send_sem, recv_small, right,
        )
        big = lang.remote_copy(
            x_ref.at[pl.ds(8, 16)], o_ref.at[pl.ds(8, 16)],
            send_sem, recv_big, right,
        )
        del small, big
        # drain sends in REVERSED issue order
        lang.wait_send(x_ref.at[pl.ds(8, 16)], send_sem)
        lang.wait_send(x_ref.at[pl.ds(0, 8)], send_sem)
        lang.wait_recv(o_ref.at[pl.ds(0, 8)], recv_small)
        lang.wait_recv(o_ref.at[pl.ds(8, 16)], recv_big)

    x = jnp.arange(n * 24 * 128, dtype=jnp.float32).reshape(n * 24, 128)
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((24, 128), jnp.float32),
        [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA,
         pltpu.SemaphoreType.DMA],
        collective_id=13,
    )
    expect = jnp.roll(x.reshape(n, 24, 128), 1, axis=0).reshape(n * 24, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


def test_semaphore_count_observability(mesh8):
    """Counting semantics peek would observe, proven through exact-valued
    wait round-trips (``peek`` itself is Mosaic-only — see its docstring):
    increments accumulate (1 + 2 then wait(3) passes), a drained semaphore
    holds zero residue (a fresh 1-round-trip after the drain), and
    aggregated remote arrivals are consumable as one exact count
    (reference ``signal_wait_until`` counting; VERDICT next #8)."""

    def kernel(x_ref, o_ref, counter, arrived, done):
        lang.collective_prologue("tp")
        me = lang.rank("tp")
        n = lang.num_ranks("tp")

        def body(scratch, sem):
            scratch[:] = jnp.zeros_like(scratch)
            # accumulation: two signals of different increments sum
            lang.notify(counter, inc=1)
            lang.notify(counter, inc=2)
            lang.wait(counter, 3)                # passes iff count == 3
            # zero residue: a fresh 1-round-trip must balance exactly
            lang.notify(counter, inc=1)
            lang.wait(counter, 1)
            scratch[0, 0] = 1.0                  # reached = both held
            # aggregated remote arrivals: everyone signals rank 0 with
            # rank-dependent increments; rank 0 consumes the exact sum
            lang.notify(arrived, 0, inc=me + 1)

            @pl.when(me == 0)
            def _():
                lang.wait(arrived, n * (n + 1) // 2)
                scratch[0, 1] = 1.0

                def release(i, _):
                    lang.notify(done, i + 1, inc=1)
                    return 0

                jax.lax.fori_loop(0, n - 1, release, 0)

            @pl.when(me != 0)
            def _():
                lang.wait(done, 1)
                scratch[0, 1] = 1.0

            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(body, pltpu.VMEM((1, 128), jnp.float32),
                      pltpu.SemaphoreType.DMA)

    x = jnp.zeros((8, 128), jnp.float32)
    out = _run(
        mesh8, kernel, x, jax.ShapeDtypeStruct((1, 128), jnp.float32),
        [pltpu.SemaphoreType.REGULAR, pltpu.SemaphoreType.REGULAR,
         pltpu.SemaphoreType.REGULAR],
        collective_id=14,
    )
    got = np.asarray(out)
    np.testing.assert_array_equal(got[:, :2],
                                  np.ones((8, 2), np.float32))


def test_peek_interpret_rule_lower_bound():
    """``peek``'s interpret-mode rule (VERDICT weak #5): under simulation
    (the CPU backend) the non-blocking read returns the pessimistic
    lower bound 0 — "nothing arrived yet" — instead of raising from the
    missing semaphore_read lowering.  A polling protocol must already
    handle 0 by falling through to its blocking wait, so the
    approximation preserves correctness; it can never fabricate a count
    that lets a wait-free consumer run ahead of its data."""
    from triton_distributed_tpu.core import platform

    if not platform.on_cpu():
        pytest.skip("real-TPU run: peek reads the live count there "
                    "(scripts/run_hw_markers.py)")
    got = lang.peek(object())   # any sem-shaped arg: the rule is static
    assert got.dtype == jnp.int32
    assert int(got) == 0


@pytest.mark.skipif(not compilation.interpret_supported(),
                    reason="interpret-mode kernels need "
                           "InterpretParams/shard_map on this jax")
def test_peek_interpret_rule_in_kernel(mesh8):
    """The same rule inside a simulated kernel: a signalled semaphore
    peeks as 0 (lower bound), and the signal is still consumable by an
    exact-valued blocking wait afterwards — peek neither consumed nor
    fabricated credits."""

    def kernel(x_ref, o_ref, counter):
        def body(scratch, sem):
            scratch[:] = jnp.zeros_like(scratch)
            lang.notify(counter, inc=3)
            # non-blocking approximation: reads the 0 lower bound
            scratch[0, 0] = lang.peek(counter).astype(jnp.float32) + 7.0
            lang.wait(counter, 3)        # the 3 credits are all still there
            scratch[0, 1] = 1.0
            lang.local_copy(scratch, o_ref, sem).wait()

        pl.run_scoped(body, pltpu.VMEM((1, 128), jnp.float32),
                      pltpu.SemaphoreType.DMA)

    x = jnp.zeros((8, 128), jnp.float32)
    out = _run(
        mesh8, kernel, x, jax.ShapeDtypeStruct((1, 128), jnp.float32),
        [pltpu.SemaphoreType.REGULAR], collective_id=16,
    )
    got = np.asarray(out)
    np.testing.assert_array_equal(got[:, 0], np.full((8,), 7.0, np.float32))
    np.testing.assert_array_equal(got[:, 1], np.ones((8,), np.float32))


def test_peek_record_mode_still_refuses():
    """Record mode keeps raising: a polling protocol has no static
    wait-for structure the verifier could check (unchanged contract)."""
    from triton_distributed_tpu.analysis.record import recording

    with recording((("tp", 2),), {"tp": 0}):
        with pytest.raises(NotImplementedError, match="peek"):
            lang.peek(object())


def test_primitives_green_under_race_detection(race_detection, mesh8):
    """The new primitive patterns stay race-free under the interpret-mode
    vector-clock detector (VERDICT next #8 done criterion)."""
    n = 8

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        lang.collective_prologue("tp")
        _, right = lang.ring_neighbors("tp")
        lang.remote_copy(x_ref, o_ref, send_sem, recv_sem, right).wait()
        lang.barrier_all("tp")

    # unique shape so the call isn't an lru-cached non-detecting build
    x = jnp.arange(n * 16 * 128, dtype=jnp.float32).reshape(n * 16, 128)
    out = _run(
        mesh8, kernel, x,
        jax.ShapeDtypeStruct((16, 128), jnp.float32),
        [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        collective_id=15,
    )
    expect = jnp.roll(x.reshape(n, 16, 128), 1, axis=0).reshape(n * 16, 128)
    assert_allclose(out, expect, atol=0, rtol=0)


def test_rank_num_ranks(mesh8):
    def kernel(x_ref, o_ref):
        def body(scratch, sem):
            scratch[:] = jnp.zeros_like(scratch)
            scratch[0, 0] = lang.rank("tp").astype(jnp.float32)
            scratch[0, 1] = jnp.float32(lang.num_ranks("tp"))
            lang.local_copy(scratch, o_ref, sem).wait()
        pl.run_scoped(body, pltpu.VMEM((1, 128), jnp.float32), pltpu.SemaphoreType.DMA)

    x = jnp.zeros((8, 128), jnp.float32)
    out = _run(mesh8, kernel, x, jax.ShapeDtypeStruct((1, 128), jnp.float32), [])
    got = np.asarray(out)
    for r in range(8):
        assert got[r, 0] == r
        assert got[r, 1] == 8
