"""Driver entry points stay green: ``entry()`` compiles and runs, and the
multichip dryrun completes quickly on the virtual mesh (VERDICT.md round-1
gate: MULTICHIP must be self-bootstrapping and finish in well under 60 s)."""

import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root for __graft_entry__

import __graft_entry__ as graft  # noqa: E402


def test_entry_jits_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1, 128, 1024)
    assert bool(jnp.isfinite(out.astype(jnp.float32)).all())


def test_dryrun_body_8_devices():
    t0 = time.time()
    graft._dryrun_body(8)
    # ~50 s alone on a loaded CI box; the bound guards against the round-1
    # never-finishes regression, not normal scheduling jitter
    assert time.time() - t0 < 180, "dryrun(8) must not hang"


def test_dryrun_body_2_devices():
    graft._dryrun_body(2)


def test_dryrun_multichip_inline_path():
    # with a 10-device platform, dryrun_multichip(4) takes the inline path
    graft.dryrun_multichip(4)
