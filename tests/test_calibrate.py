"""Link calibration -> method choice (VERDICT r4 next #5: measured
crossovers, with the pinned constants demoted to cold-start defaults)."""

import json

import pytest

from triton_distributed_tpu.comm.allgather import AllGatherMethod
from triton_distributed_tpu.comm.allgather import choose_method as ag_choose
from triton_distributed_tpu.comm.allreduce import AllReduceMethod
from triton_distributed_tpu.comm.allreduce import choose_method as ar_choose
from triton_distributed_tpu.tools import calibrate as cal


@pytest.fixture
def cal_path(tmp_path, monkeypatch):
    p = tmp_path / "linkcal.json"
    monkeypatch.setenv("TDT_LINKCAL_CACHE", str(p))
    cal.invalidate_cache()
    yield p
    cal.invalidate_cache()


def _plant(path, **kw):
    path.write_text(json.dumps(
        cal.LinkCalibration(**kw).to_json()
    ))
    cal.invalidate_cache()


def test_cold_start_uses_pinned_defaults(cal_path):
    assert cal.load_calibration() is None
    assert cal.push_bytes_threshold() == cal.DEFAULT_PUSH_BYTES
    assert cal.one_shot_bytes_threshold() == cal.DEFAULT_ONE_SHOT_BYTES
    assert ag_choose(cal.DEFAULT_PUSH_BYTES, 8) == AllGatherMethod.PUSH_1SHOT
    assert ag_choose(cal.DEFAULT_PUSH_BYTES + 1, 8) == AllGatherMethod.RING_BIDIR
    assert ar_choose(cal.DEFAULT_ONE_SHOT_BYTES, 8) == AllReduceMethod.ONE_SHOT
    assert ar_choose(cal.DEFAULT_ONE_SHOT_BYTES + 1, 8) == AllReduceMethod.TWO_SHOT


def test_crossover_moves_with_calibration(cal_path):
    """The VERDICT done-criterion: the SAME (bytes, ranks) question gets a
    different method when the measured link characteristics change."""
    probe = 1 * 2**20  # 1 MiB shard: ring under the cold defaults
    assert ag_choose(probe, 8) == AllGatherMethod.RING_BIDIR
    assert ar_choose(probe, 8) == AllReduceMethod.TWO_SHOT

    # a high-latency link (10 us hops at 186 GB/s -> ~1.86 MB BDP) makes
    # latency dominance reach further: the 1 MiB shard flips to one-shot
    _plant(cal_path, ici_gbps=186.0, ici_hop_us=10.0,
           device_kind="TPU v5e", n_devices=8)
    assert cal.push_bytes_threshold() == int(186e9 * 10e-6)
    assert ag_choose(probe, 8) == AllGatherMethod.PUSH_1SHOT
    assert ar_choose(probe, 8) == AllReduceMethod.ONE_SHOT

    # an ultra-low-latency link shrinks the push window below 64 KiB
    _plant(cal_path, ici_gbps=186.0, ici_hop_us=0.3,
           device_kind="TPU v5e", n_devices=8)
    assert ag_choose(64 * 1024, 8) == AllGatherMethod.RING_BIDIR
    assert ar_choose(256 * 1024, 8) == AllReduceMethod.TWO_SHOT


def test_save_load_round_trip(cal_path):
    c = cal.LinkCalibration(ici_gbps=123.4, ici_hop_us=1.5,
                            dcn_gbps=6.1, dcn_hop_us=12.0,
                            device_kind="TPU v5e", n_devices=16)
    cal.save_calibration(c)
    assert cal_path.exists()
    cal.invalidate_cache()
    assert cal.load_calibration() == c


def test_corrupt_calibration_falls_back(cal_path):
    cal_path.write_text("{not json")
    cal.invalidate_cache()
    assert cal.load_calibration() is None
    assert cal.push_bytes_threshold() == cal.DEFAULT_PUSH_BYTES


def test_fit_latency_bandwidth_recovers_synthetic_link():
    # t = 2 us + S / (100 GB/s)
    sizes = [64e3, 512e3, 2e6, 8e6]
    times = [2e-6 + s / 100e9 for s in sizes]
    hop_us, gbps = cal.fit_latency_bandwidth(sizes, times)
    assert abs(hop_us - 2.0) < 1e-6
    assert abs(gbps - 100.0) < 1e-6
    with pytest.raises(ValueError, match="non-physical"):
        cal.fit_latency_bandwidth(sizes, list(reversed(times)))


def test_measure_smoke_on_virtual_mesh(cal_path):
    """End-to-end measure path on the CPU mesh (force=True: simulator
    numbers, asserted only for shape/positivity, never persisted)."""
    got = cal.calibrate(save=False, force=True,
                        sizes_bytes=(64 * 1024, 256 * 1024, 1 * 2**20))
    assert got.ici_gbps is not None and got.ici_gbps > 0
    assert got.ici_hop_us is not None and got.ici_hop_us >= 0
    assert got.n_devices >= 8
    assert not cal_path.exists()


def test_refuses_interpret_measure_without_force(cal_path):
    from triton_distributed_tpu.core import compilation

    if not compilation.interpret_mode():
        pytest.skip("real hardware: measuring is legitimate")
    with pytest.raises(RuntimeError, match="interpret"):
        cal.calibrate(save=False)
