"""Collective kernels under the interpret-mode race detector — the
framework's stand-in for the reference's compute-sanitizer runs
(SURVEY.md section 5; ``core.compilation.enable_race_detection``).

Shapes here are deliberately unique: the op builders lru-cache compiled
calls, and a cached call would keep the interpret params it was built
with — a fresh shape forces a rebuild under detect_races=True.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm import all_gather, all_reduce, reduce_scatter
from triton_distributed_tpu.comm.allreduce import AllReduceConfig, AllReduceMethod
from triton_distributed_tpu.comm.reduce_scatter import ReduceScatterConfig
from triton_distributed_tpu.core import compilation
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.core.utils import rand_tensor


@pytest.fixture
def race_detection():
    compilation.enable_race_detection(True)
    yield
    compilation.enable_race_detection(False)


@pytest.fixture
def mesh4():
    return make_mesh({TP_AXIS: 4}, devices=jax.devices()[:4])


def _shard(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P(TP_AXIS, None)))


def test_all_gather_race_free(race_detection, mesh4):
    x = rand_tensor((4 * 40, 128), jnp.float32)  # unique shape: rebuild
    out = jax.block_until_ready(all_gather(_shard(mesh4, x), mesh4))
    assert out.shape == x.shape


def test_reduce_scatter_race_free(race_detection, mesh4):
    x = rand_tensor((4 * 32, 128), jnp.float32, scale=0.1)
    out = jax.block_until_ready(reduce_scatter(
        _shard(mesh4, x), mesh4, config=ReduceScatterConfig(bm=8, bn=128)
    ))
    assert out.shape == (32, 128)


@pytest.mark.parametrize("method", [
    AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
])
def test_all_reduce_race_free(race_detection, mesh4, method):
    x = rand_tensor((4 * 32, 128), jnp.float32, scale=0.1)
    out = jax.block_until_ready(all_reduce(
        _shard(mesh4, x), mesh4, method=method,
        config=AllReduceConfig(bm=8, bn=128),
    ))
    assert out.shape == (32, 128)
