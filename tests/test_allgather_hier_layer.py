"""Hierarchical (2-level) AllGather and the persistent double-buffered AG
layer (reference ``allgather.py:442-601`` 2D AG;
``low_latency_allgather_layer.py:30``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.allgather import hierarchical_all_gather
from triton_distributed_tpu.core.mesh import make_mesh
from triton_distributed_tpu.layers.allgather_layer import AllGatherLayer


@pytest.mark.parametrize("n_out,n_in", [(2, 4), (2, 2), (4, 2)])
def test_hierarchical_all_gather_matches_flat(n_out, n_in):
    n = n_out * n_in
    mesh = make_mesh({"dcn": n_out, "ici": n_in},
                     devices=jax.devices()[:n])
    m, r = 16, 128
    x = jax.random.normal(jax.random.key(0), (n * m, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    assert out.shape == x.shape
    # flat golden: the gather must reproduce global rank order
    assert np.allclose(np.asarray(jax.device_get(out)), np.asarray(x))


def test_hierarchical_single_outer_falls_back():
    mesh = make_mesh({"dcn": 1, "ici": 4}, devices=jax.devices()[:4])
    m, r = 8, 128
    x = jax.random.normal(jax.random.key(1), (4 * m, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    assert np.allclose(np.asarray(jax.device_get(out)), np.asarray(x))


def test_allgather_layer_double_buffer():
    n, m, r = 4, 16, 128
    mesh = make_mesh({"tp": n}, devices=jax.devices()[:n])
    layer = AllGatherLayer(mesh, local_rows=m, trailing=(r,),
                           dtype=jnp.float32, axis="tp")
    x1 = jax.random.normal(jax.random.key(2), (n * m, r), jnp.float32)
    x2 = jax.random.normal(jax.random.key(3), (n * m, r), jnp.float32)
    s = NamedSharding(mesh, P("tp", None))
    out1 = layer(jax.device_put(x1, s))
    np.testing.assert_allclose(np.asarray(jax.device_get(out1)),
                               np.asarray(x1))
    out2 = layer(jax.device_put(x2, s))
    np.testing.assert_allclose(np.asarray(jax.device_get(out2)),
                               np.asarray(x2))
    # the double-buffer guarantee: call k's output survives call k+1
    np.testing.assert_allclose(np.asarray(jax.device_get(out1)),
                               np.asarray(x1))
    # and a third call recycles slot 0 in place
    x3 = jax.random.normal(jax.random.key(4), (n * m, r), jnp.float32)
    out3 = layer(jax.device_put(x3, s))
    np.testing.assert_allclose(np.asarray(jax.device_get(out3)),
                               np.asarray(x3))
