"""Hierarchical (2-level) collectives and the persistent double-buffered AG
layer (reference ``allgather.py:442-601`` 2D AG; 2D RS
``reduce_scatter.py:688-882``; ``low_latency_allgather_layer.py:30``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.comm.allgather import hierarchical_all_gather
from triton_distributed_tpu.comm.allreduce import hierarchical_all_reduce
from triton_distributed_tpu.comm.reduce_scatter import (
    hierarchical_reduce_scatter,
)
from triton_distributed_tpu.core.mesh import make_mesh
from triton_distributed_tpu.layers.allgather_layer import AllGatherLayer


@pytest.mark.parametrize("n_out,n_in", [(2, 4), (2, 2), (4, 2)])
def test_hierarchical_all_gather_matches_flat(n_out, n_in):
    n = n_out * n_in
    mesh = make_mesh({"dcn": n_out, "ici": n_in},
                     devices=jax.devices()[:n])
    m, r = 16, 128
    x = jax.random.normal(jax.random.key(0), (n * m, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    assert out.shape == x.shape
    # flat golden: the gather must reproduce global rank order
    assert np.allclose(np.asarray(jax.device_get(out)), np.asarray(x))


def test_hierarchical_single_outer_falls_back():
    mesh = make_mesh({"dcn": 1, "ici": 4}, devices=jax.devices()[:4])
    m, r = 8, 128
    x = jax.random.normal(jax.random.key(1), (4 * m, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_gather(xs, mesh, "ici", "dcn")
    assert np.allclose(np.asarray(jax.device_get(out)), np.asarray(x))


@pytest.mark.parametrize("n_out,n_in", [(2, 4), (2, 2), (4, 2)])
def test_hierarchical_reduce_scatter_matches_flat(n_out, n_in):
    """Output must match a flat RS over the combined outer-major axis:
    global block g of the sum lands on global rank g."""
    n = n_out * n_in
    mesh = make_mesh({"dcn": n_out, "ici": n_in}, devices=jax.devices()[:n])
    mp, r = 2 * n, 128   # per-device partial rows, divisible by N
    x = jax.random.normal(jax.random.key(5), (n * mp, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_reduce_scatter(xs, mesh, "ici", "dcn")
    want = np.asarray(x).reshape(n, mp, r).sum(0)
    assert out.shape == (mp, r)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                               rtol=1e-5, atol=1e-5)


def test_hierarchical_reduce_scatter_single_outer_falls_back():
    mesh = make_mesh({"dcn": 1, "ici": 4}, devices=jax.devices()[:4])
    mp, r = 8, 128
    x = jax.random.normal(jax.random.key(6), (4 * mp, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_reduce_scatter(xs, mesh, "ici", "dcn")
    want = np.asarray(x).reshape(4, mp, r).sum(0)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n_out,n_in", [(2, 4), (2, 2), (4, 2)])
def test_hierarchical_all_reduce_matches_sum(n_out, n_in):
    n = n_out * n_in
    mesh = make_mesh({"dcn": n_out, "ici": n_in}, devices=jax.devices()[:n])
    m, r = 2 * n_in, 128   # per-device partial rows, divisible by n_in
    x = jax.random.normal(jax.random.key(7), (n * m, r), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(("dcn", "ici"), None)))
    out = hierarchical_all_reduce(xs, mesh, "ici", "dcn")
    want = np.asarray(x).reshape(n, m, r).sum(0)
    assert out.shape == (m, r)
    np.testing.assert_allclose(np.asarray(jax.device_get(out)), want,
                               rtol=1e-5, atol=1e-5)
    # repeat invocation: ring drains must leave the semaphores balanced
    out2 = hierarchical_all_reduce(xs, mesh, "ici", "dcn")
    np.testing.assert_allclose(np.asarray(jax.device_get(out2)), want,
                               rtol=1e-5, atol=1e-5)


def test_allgather_layer_double_buffer():
    n, m, r = 4, 16, 128
    mesh = make_mesh({"tp": n}, devices=jax.devices()[:n])
    layer = AllGatherLayer(mesh, local_rows=m, trailing=(r,),
                           dtype=jnp.float32, axis="tp")
    x1 = jax.random.normal(jax.random.key(2), (n * m, r), jnp.float32)
    x2 = jax.random.normal(jax.random.key(3), (n * m, r), jnp.float32)
    s = NamedSharding(mesh, P("tp", None))
    out1 = layer(jax.device_put(x1, s))
    np.testing.assert_allclose(np.asarray(jax.device_get(out1)),
                               np.asarray(x1))
    out2 = layer(jax.device_put(x2, s))
    np.testing.assert_allclose(np.asarray(jax.device_get(out2)),
                               np.asarray(x2))
    # the double-buffer guarantee: call k's output survives call k+1
    np.testing.assert_allclose(np.asarray(jax.device_get(out1)),
                               np.asarray(x1))
    # and a third call recycles slot 0 in place
    x3 = jax.random.normal(jax.random.key(4), (n * m, r), jnp.float32)
    out3 = layer(jax.device_put(x3, s))
    np.testing.assert_allclose(np.asarray(jax.device_get(out3)),
                               np.asarray(x3))
