"""HF state-dict loading and checkpoint round trip (reference weight
ingest ``models/qwen.py:147-165``; checkpointing is a capability the
reference lacks)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.models import ModelConfig, Qwen3, init_cache
from triton_distributed_tpu.models.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from triton_distributed_tpu.models.loader import load_qwen_state_dict

CFG = ModelConfig(
    num_layers=2, hidden=64, intermediate=128, num_heads=4, num_kv_heads=2,
    head_dim=32, vocab=128, max_length=64, dtype=jnp.float32,
)


def _synthetic_state_dict(rng):
    """A HF-Qwen3-shaped state dict of numpy arrays (out_features first,
    as torch stores linear weights)."""
    c = CFG
    h, hk, d = c.num_heads, c.num_kv_heads, c.head_dim
    sd = {
        "model.embed_tokens.weight":
            rng.standard_normal((c.vocab, c.hidden)).astype(np.float32) * 0.05,
        "model.norm.weight": np.ones(c.hidden, np.float32),
        "lm_head.weight":
            rng.standard_normal((c.vocab, c.hidden)).astype(np.float32) * 0.05,
    }
    for i in range(c.num_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = np.ones(c.hidden, np.float32)
        sd[p + "post_attention_layernorm.weight"] = np.ones(c.hidden, np.float32)
        sd[p + "self_attn.q_proj.weight"] = \
            rng.standard_normal((h * d, c.hidden)).astype(np.float32) * 0.05
        sd[p + "self_attn.k_proj.weight"] = \
            rng.standard_normal((hk * d, c.hidden)).astype(np.float32) * 0.05
        sd[p + "self_attn.v_proj.weight"] = \
            rng.standard_normal((hk * d, c.hidden)).astype(np.float32) * 0.05
        sd[p + "self_attn.o_proj.weight"] = \
            rng.standard_normal((c.hidden, h * d)).astype(np.float32) * 0.05
        sd[p + "self_attn.q_norm.weight"] = np.ones(d, np.float32)
        sd[p + "self_attn.k_norm.weight"] = np.ones(d, np.float32)
        sd[p + "mlp.gate_proj.weight"] = \
            rng.standard_normal((c.intermediate, c.hidden)).astype(np.float32) * 0.05
        sd[p + "mlp.up_proj.weight"] = \
            rng.standard_normal((c.intermediate, c.hidden)).astype(np.float32) * 0.05
        sd[p + "mlp.down_proj.weight"] = \
            rng.standard_normal((c.hidden, c.intermediate)).astype(np.float32) * 0.05
    return sd


def _cache(mesh):
    return init_cache(mesh, CFG.num_layers, 1, CFG.num_kv_heads,
                      CFG.max_length, CFG.head_dim, CFG.dtype)


def test_loaded_weights_agree_across_tp():
    """The SAME state dict loaded at tp=1 and tp=2 gives identical logits —
    the sharded fused layouts reproduce the dense weights."""
    sd = _synthetic_state_dict(np.random.default_rng(0))
    ids = jax.random.randint(jax.random.key(1), (1, 32), 0, CFG.vocab)
    logits = {}
    for n in (1, 2):
        mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
        model = Qwen3(CFG, mesh)
        params = load_qwen_state_dict(model, sd)
        out, _ = model.prefill(params, _cache(mesh), ids)
        logits[n] = np.asarray(jax.device_get(out))
    assert np.allclose(logits[1], logits[2], atol=2e-4, rtol=2e-4)


def test_loader_accepts_torch_tensors():
    torch = pytest.importorskip("torch")
    sd = {
        k: torch.from_numpy(v)
        for k, v in _synthetic_state_dict(np.random.default_rng(1)).items()
    }
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(CFG, mesh)
    params = load_qwen_state_dict(model, sd)
    assert params.embed.shape == (CFG.vocab, CFG.hidden)


def test_tied_embeddings_fallback():
    sd = _synthetic_state_dict(np.random.default_rng(2))
    del sd["lm_head.weight"]
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    params = load_qwen_state_dict(Qwen3(CFG, mesh), sd)
    np.testing.assert_array_equal(
        np.asarray(params.lm_head), np.asarray(params.embed).T
    )


def test_checkpoint_round_trip(tmp_path):
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(CFG, mesh)
    params = model.init(jax.random.key(3))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path, params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding
