"""HF state-dict loading and checkpoint round trip (reference weight
ingest ``models/qwen.py:147-165``; checkpointing is a capability the
reference lacks)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.models import ModelConfig, Qwen3, init_cache
from triton_distributed_tpu.models.checkpoint import (
    load_checkpoint,
    save_checkpoint,
)
from triton_distributed_tpu.models.loader import load_qwen_state_dict

CFG = ModelConfig(
    num_layers=2, hidden=64, intermediate=128, num_heads=4, num_kv_heads=2,
    head_dim=32, vocab=128, max_length=64, dtype=jnp.float32,
)


def _synthetic_state_dict(rng):
    """A HF-Qwen3-shaped state dict of numpy arrays (out_features first,
    as torch stores linear weights)."""
    c = CFG
    h, hk, d = c.num_heads, c.num_kv_heads, c.head_dim
    sd = {
        "model.embed_tokens.weight":
            rng.standard_normal((c.vocab, c.hidden)).astype(np.float32) * 0.05,
        "model.norm.weight": np.ones(c.hidden, np.float32),
        "lm_head.weight":
            rng.standard_normal((c.vocab, c.hidden)).astype(np.float32) * 0.05,
    }
    for i in range(c.num_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = np.ones(c.hidden, np.float32)
        sd[p + "post_attention_layernorm.weight"] = np.ones(c.hidden, np.float32)
        sd[p + "self_attn.q_proj.weight"] = \
            rng.standard_normal((h * d, c.hidden)).astype(np.float32) * 0.05
        sd[p + "self_attn.k_proj.weight"] = \
            rng.standard_normal((hk * d, c.hidden)).astype(np.float32) * 0.05
        sd[p + "self_attn.v_proj.weight"] = \
            rng.standard_normal((hk * d, c.hidden)).astype(np.float32) * 0.05
        sd[p + "self_attn.o_proj.weight"] = \
            rng.standard_normal((c.hidden, h * d)).astype(np.float32) * 0.05
        sd[p + "self_attn.q_norm.weight"] = np.ones(d, np.float32)
        sd[p + "self_attn.k_norm.weight"] = np.ones(d, np.float32)
        sd[p + "mlp.gate_proj.weight"] = \
            rng.standard_normal((c.intermediate, c.hidden)).astype(np.float32) * 0.05
        sd[p + "mlp.up_proj.weight"] = \
            rng.standard_normal((c.intermediate, c.hidden)).astype(np.float32) * 0.05
        sd[p + "mlp.down_proj.weight"] = \
            rng.standard_normal((c.hidden, c.intermediate)).astype(np.float32) * 0.05
    return sd


def _cache(mesh):
    return init_cache(mesh, CFG.num_layers, 1, CFG.num_kv_heads,
                      CFG.max_length, CFG.head_dim, CFG.dtype)


def test_loaded_weights_agree_across_tp():
    """The SAME state dict loaded at tp=1 and tp=2 gives identical logits —
    the sharded fused layouts reproduce the dense weights."""
    sd = _synthetic_state_dict(np.random.default_rng(0))
    ids = jax.random.randint(jax.random.key(1), (1, 32), 0, CFG.vocab)
    logits = {}
    for n in (1, 2):
        mesh = make_mesh({TP_AXIS: n}, devices=jax.devices()[:n])
        model = Qwen3(CFG, mesh)
        params = load_qwen_state_dict(model, sd)
        out, _ = model.prefill(params, _cache(mesh), ids)
        logits[n] = np.asarray(jax.device_get(out))
    assert np.allclose(logits[1], logits[2], atol=2e-4, rtol=2e-4)


def test_loader_accepts_torch_tensors():
    torch = pytest.importorskip("torch")
    sd = {
        k: torch.from_numpy(v)
        for k, v in _synthetic_state_dict(np.random.default_rng(1)).items()
    }
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(CFG, mesh)
    params = load_qwen_state_dict(model, sd)
    assert params.embed.shape == (CFG.vocab, CFG.hidden)


def test_tied_embeddings_fallback():
    sd = _synthetic_state_dict(np.random.default_rng(2))
    del sd["lm_head.weight"]
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    params = load_qwen_state_dict(Qwen3(CFG, mesh), sd)
    np.testing.assert_array_equal(
        np.asarray(params.lm_head), np.asarray(params.embed).T
    )


def _mixed_arrays(rng):
    import ml_dtypes

    return {
        "a/f32": rng.standard_normal((4, 6)).astype(np.float32),
        "b.bf16": rng.standard_normal((3, 5)).astype(ml_dtypes.bfloat16),
        "c f16": rng.standard_normal((8,)).astype(np.float16),
        "d\"quoted\\name": np.arange(12, dtype=np.int32).reshape(3, 4),
        "e_unicode_é中": np.asarray([True, False, True]),
        # non-BMP name: the writer must emit raw UTF-8 (not surrogate-pair
        # escapes, which the native reader rejects by design)
        "e_nonbmp_𝛼": np.asarray([1.0, 2.0], np.float32),
        "f_scalar": np.asarray(2.5, np.float32),
        "g_empty": np.zeros((0, 4), np.int64),
    }


@pytest.mark.parametrize("native", [True, False])
def test_safetensors_round_trip(tmp_path, native):
    """Writer -> both readers (native C++ mmap and numpy fallback) across
    dtypes, escaped/unicode names, scalars, and empty tensors."""
    from triton_distributed_tpu.models.safetensors_io import (
        SafetensorsFile, save_safetensors,
    )

    arrays = _mixed_arrays(np.random.default_rng(3))
    path = str(tmp_path / "w.safetensors")
    save_safetensors(arrays, path, metadata={"format": "pt"})
    sf = SafetensorsFile(path, native=native)
    assert set(sf) == set(arrays)
    for name, want in arrays.items():
        got = sf[name]
        assert got.dtype == want.dtype and got.shape == want.shape
        np.testing.assert_array_equal(np.asarray(got), want)


def test_safetensors_matches_reference_library(tmp_path):
    """Our writer's files parse identically under the upstream
    ``safetensors`` library, and our readers parse its files."""
    st = pytest.importorskip("safetensors.numpy")
    from triton_distributed_tpu.models.safetensors_io import (
        SafetensorsFile, save_safetensors,
    )

    arrays = {
        k: v for k, v in _mixed_arrays(np.random.default_rng(4)).items()
        # upstream numpy backend has no bf16; cross-check the rest
        if v.dtype == np.float32 or v.dtype == np.int32
    }
    ours = str(tmp_path / "ours.safetensors")
    save_safetensors(arrays, ours)
    theirs_view = st.load_file(ours)
    for name, want in arrays.items():
        np.testing.assert_array_equal(theirs_view[name], want)

    theirs = str(tmp_path / "theirs.safetensors")
    st.save_file(arrays, theirs)
    for native in (True, False):
        sf = SafetensorsFile(theirs, native=native)
        for name, want in arrays.items():
            np.testing.assert_array_equal(np.asarray(sf[name]), want)


def test_safetensors_corrupt_header(tmp_path):
    from triton_distributed_tpu.models.safetensors_io import SafetensorsFile

    path = str(tmp_path / "bad.safetensors")
    with open(path, "wb") as f:
        f.write((10**9).to_bytes(8, "little"))  # header longer than file
        f.write(b"garbage")
    for native in (True, False):
        with pytest.raises(Exception):
            SafetensorsFile(path, native=native)


def _write_raw_safetensors(path, header: dict, payload: bytes):
    import json

    hjson = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(len(hjson).to_bytes(8, "little"))
        f.write(hjson)
        f.write(payload)


@pytest.mark.parametrize("native", [True, False])
@pytest.mark.parametrize(
    "offsets,shape",
    [
        ((0, 64), (4,)),        # byte range disagrees with shape product
        ((0, 1024), (256,)),    # end beyond the data section
        ((32, 16), (4,)),       # end before begin
        # count = 2**62 + 4, so count * 4 wraps 64 bits to exactly 16:
        # the consistency check must not be fooled by the wrapped product
        ((0, 16), (4, 2**60 + 1)),
    ],
)
def test_safetensors_rejects_inconsistent_offsets(tmp_path, native,
                                                  offsets, shape):
    """Both readers reject malformed data_offsets identically (the numpy
    fallback must not clamp through slicing or accept overlaps)."""
    from triton_distributed_tpu.models.safetensors_io import SafetensorsFile

    path = str(tmp_path / "bad_offsets.safetensors")
    _write_raw_safetensors(
        path,
        {"t": {"dtype": "F32", "shape": list(shape),
               "data_offsets": list(offsets)}},
        b"\x00" * 64,
    )
    with pytest.raises(ValueError):
        SafetensorsFile(path, native=native)


@pytest.mark.parametrize("native", [True, False])
def test_safetensors_zero_element_huge_dim_parity(tmp_path, native):
    """A zero-element tensor with a huge sibling dimension is consistent
    (count = 0, empty byte range) — BOTH readers must accept it; the native
    overflow guard must not trip on the prefix product."""
    from triton_distributed_tpu.models.safetensors_io import SafetensorsFile

    path = str(tmp_path / "zero_dim.safetensors")
    _write_raw_safetensors(
        path,
        {"t": {"dtype": "F32", "shape": [2**40, 0],
               "data_offsets": [0, 0]}},
        b"",
    )
    sf = SafetensorsFile(path, native=native)
    assert sf["t"].size == 0 and sf["t"].shape == (2**40, 0)


def test_load_state_dict_sharded_index(tmp_path):
    """HF-style sharded checkpoint: two .safetensors files + index.json."""
    from triton_distributed_tpu.models.safetensors_io import (
        load_state_dict, save_safetensors,
    )

    rng = np.random.default_rng(5)
    s1 = {"layer.0.w": rng.standard_normal((4, 4)).astype(np.float32)}
    s2 = {"layer.1.w": rng.standard_normal((2, 3)).astype(np.float32)}
    save_safetensors(s1, str(tmp_path / "model-00001-of-00002.safetensors"))
    save_safetensors(s2, str(tmp_path / "model-00002-of-00002.safetensors"))
    index = {
        "weight_map": {
            "layer.0.w": "model-00001-of-00002.safetensors",
            "layer.1.w": "model-00002-of-00002.safetensors",
        }
    }
    with open(tmp_path / "model.safetensors.index.json", "w") as f:
        import json

        json.dump(index, f)
    # via the index file, and via the directory (which finds the index)
    for target in (str(tmp_path / "model.safetensors.index.json"),
                   str(tmp_path)):
        sd = load_state_dict(target)
        assert set(sd) == {"layer.0.w", "layer.1.w"}
        np.testing.assert_array_equal(np.asarray(sd["layer.0.w"]),
                                      s1["layer.0.w"])
        np.testing.assert_array_equal(np.asarray(sd["layer.1.w"]),
                                      s2["layer.1.w"])


def test_load_qwen_from_safetensors(tmp_path):
    """File-level weight ingest lands in the same sharded params as the
    in-memory state dict path."""
    from triton_distributed_tpu.models.loader import (
        load_qwen_from_safetensors,
    )
    from triton_distributed_tpu.models.safetensors_io import save_safetensors

    sd = _synthetic_state_dict(np.random.default_rng(6))
    path = str(tmp_path / "qwen.safetensors")
    save_safetensors(sd, path)
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(CFG, mesh)
    from_file = load_qwen_from_safetensors(model, path)
    from_dict = load_qwen_state_dict(model, sd)
    for a, b in zip(jax.tree.leaves(from_file), jax.tree.leaves(from_dict)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_round_trip(tmp_path):
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(CFG, mesh)
    params = model.init(jax.random.key(3))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path, params)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding


def test_load_moe_from_safetensors(tmp_path):
    """MoE checkpoint through the file-level path: synthetic HF Qwen3-MoE
    state dict -> safetensors -> loader, equal to the in-memory path."""
    import dataclasses

    from triton_distributed_tpu.models.loader import (
        load_qwen_from_safetensors,
    )
    from triton_distributed_tpu.models.safetensors_io import save_safetensors

    cfg = dataclasses.replace(CFG, num_experts=4, top_k=2,
                              moe_intermediate=16)
    rng = np.random.default_rng(11)
    sd = _synthetic_state_dict(rng)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}.mlp."
        for k in ("gate_proj.weight", "up_proj.weight", "down_proj.weight"):
            del sd[p + k]
        sd[p + "gate.weight"] = rng.standard_normal(
            (cfg.num_experts, cfg.hidden)).astype(np.float32) * 0.05
        for j in range(cfg.num_experts):
            ep = p + f"experts.{j}."
            sd[ep + "gate_proj.weight"] = rng.standard_normal(
                (cfg.moe_intermediate, cfg.hidden)).astype(np.float32) * 0.05
            sd[ep + "up_proj.weight"] = rng.standard_normal(
                (cfg.moe_intermediate, cfg.hidden)).astype(np.float32) * 0.05
            sd[ep + "down_proj.weight"] = rng.standard_normal(
                (cfg.hidden, cfg.moe_intermediate)).astype(np.float32) * 0.05

    path = str(tmp_path / "moe.safetensors")
    save_safetensors(sd, path)
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    model = Qwen3(cfg, mesh)
    from_file = load_qwen_from_safetensors(model, path)
    from_dict = load_qwen_state_dict(model, sd)
    for a, b in zip(jax.tree.leaves(from_file), jax.tree.leaves(from_dict)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the loaded model runs
    cache = init_cache(mesh, cfg.num_layers, 1, cfg.num_kv_heads,
                       cfg.max_length, cfg.head_dim, cfg.dtype)
    ids = jax.random.randint(jax.random.key(12), (1, 8), 0, cfg.vocab)
    logits, _ = model.prefill(from_file, cache, ids)
    assert bool(jnp.isfinite(logits).all())
