"""Fleet tier: N-replica serving with replica-loss failover,
per-replica quarantine, and SLO-driven rebalance (ISSUE 18).

Headless like the router tests: N REAL schedulers over deterministic
``SimBackend``s (prefill- and decode-role pools), the real paged-cache
plumbing on every replica, and the ``ModeledDCN`` transport in between
— so admission routing, the replica breakers, drain-before-evict
quarantine, probe readmission, loss failover (original clock + gapless
trace chain carried) and the rebalance actuator are exercised end to
end without hardware.
"""

import os
import random
import subprocess
import sys
import time

import pytest

from triton_distributed_tpu import obs, resilience, serve
from triton_distributed_tpu.obs import request_trace as rtrace
from triton_distributed_tpu.resilience import matrix as rmatrix
from triton_distributed_tpu.resilience.faults import RankAborted
from triton_distributed_tpu.serve.fleet import replica_breaker_name

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_IDS = ("p0", "p1", "d0", "d1")


@pytest.fixture(autouse=True)
def _fresh_fleet_breakers():
    """Replica breakers are process-global sticky state keyed by id —
    and the test fleets reuse ids — so no test may inherit (or donate)
    an open breaker."""
    for rid in _IDS:
        resilience.reset_breaker(replica_breaker_name(rid))
    resilience.reset_breaker(serve.HANDOFF_OP)
    yield
    for rid in _IDS:
        resilience.reset_breaker(replica_breaker_name(rid))
    resilience.reset_breaker(serve.HANDOFF_OP)


@pytest.fixture()
def trace_on():
    prev_obs = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    obs.serve_stats.STATS.reset()
    prev_trace = rtrace.enable(True)
    rtrace.RING.clear()
    yield
    rtrace.enable(prev_trace)
    rtrace.RING.clear()
    obs.enable(prev_obs)
    obs.REGISTRY.reset()
    obs.serve_stats.STATS.reset()


def _sched(*, prefill_only=False, slots=3, pool_pages=24, hook=None,
           max_queue_depth=32):
    return serve.Scheduler(
        serve.SimBackend(slots=slots, page_size=4, pool_pages=pool_pages,
                         max_length=64, step_hook=hook),
        serve.SchedulerConfig(max_queue_depth=max_queue_depth,
                              prefill_only=prefill_only))


def _fleet(*, hooks=None, config=None, decode_pool=32, seed=1):
    hooks = hooks or {}
    replicas = [
        serve.Replica(rid, _sched(prefill_only=True,
                                  hook=hooks.get(rid)), "prefill")
        for rid in ("p0", "p1")
    ] + [
        serve.Replica(rid, _sched(pool_pages=decode_pool,
                                  hook=hooks.get(rid)), "decode")
        for rid in ("d0", "d1")
    ]
    plane = serve.HandoffPlane(dcn_channel=serve.ModeledDCN(seed=seed))
    return serve.FleetRouter(replicas, plane=plane, config=config)


def _load(n=6, seed=0, max_new=(4, 8)):
    rng = random.Random(seed)
    return [
        serve.Request(prompt=tuple(rng.randrange(1, 90)
                                   for _ in range(rng.randint(2, 6))),
                      max_new_tokens=rng.randint(*max_new))
        for _ in range(n)
    ]


class _Flap:
    """Decode-step hook raising ``RankAborted`` while the backend step
    counter is inside the window — a flapping replica."""

    def __init__(self, first, last):
        self.first, self.last, self.fired = first, last, 0

    def __call__(self, step):
        if self.first <= step <= self.last:
            self.fired += 1
            raise RankAborted(0, step)


# ---------------------------------------------------------------------------
# construction validation


def test_duplicate_replica_ids_rejected():
    reps = [serve.Replica("a", _sched(prefill_only=True), "prefill"),
            serve.Replica("a", _sched(), "decode")]
    with pytest.raises(ValueError, match="duplicate replica id"):
        serve.FleetRouter(reps)


def test_role_must_match_prefill_only():
    reps = [serve.Replica("a", _sched(prefill_only=False), "prefill"),
            serve.Replica("b", _sched(), "decode")]
    with pytest.raises(ValueError, match="prefill_only"):
        serve.FleetRouter(reps)


def test_each_role_required():
    reps = [serve.Replica("a", _sched(prefill_only=True), "prefill")]
    with pytest.raises(ValueError, match="at least one 'decode'"):
        serve.FleetRouter(reps)


def test_page_geometry_must_match():
    bad = serve.Scheduler(
        serve.SimBackend(slots=3, page_size=8, pool_pages=24,
                         max_length=64),
        serve.SchedulerConfig(max_queue_depth=32))
    reps = [serve.Replica("a", _sched(prefill_only=True), "prefill"),
            serve.Replica("b", bad, "decode")]
    with pytest.raises(ValueError, match="page geometry"):
        serve.FleetRouter(reps)


# ---------------------------------------------------------------------------
# routing + affinity


def test_clean_fleet_drains_with_parity_and_zero_leaks():
    router = _fleet()
    reqs = _load(8)
    for r in reqs:
        router.submit(r)
    router.run_until_idle(max_steps=4000)
    backend = router.replicas[0].scheduler.backend
    assert all(r.state is serve.RequestState.DONE for r in reqs)
    assert all(r.tokens == backend.expected_tokens(r) for r in reqs)
    assert router.leaked_pages() == 0
    assert router.handoffs > 0          # the disaggregated path ran


def test_submit_routes_to_least_loaded_prefill():
    router = _fleet()
    # preload p0 so p1 is strictly less loaded
    for r in _load(3, seed=7):
        router._by_id["p0"].scheduler.submit(r)
    req = _load(1, seed=8)[0]
    assert router.submit(req)
    p1 = router._by_id["p1"].scheduler
    assert any(req is q for q in [p1.queue.pop()])


def test_session_affinity_sticks_and_follows_failover():
    router = _fleet()
    first = _load(1, seed=3)[0]
    assert router.submit(first, session="tenant-a")
    home = router._affinity["tenant-a"]
    router.run_until_idle(max_steps=2000)
    # after the handoff the session's pages live on the decode replica
    moved_home = router._affinity["tenant-a"]
    assert router._by_id[moved_home].role == "decode"
    second = _load(1, seed=4)[0]
    assert router.submit(second, session="tenant-a")
    assert home is not None  # affinity was recorded at admission


def test_fleet_shed_when_no_replica_admits():
    router = _fleet()
    for rep in router.replicas:
        rep.draining = True
    req = _load(1)[0]
    assert not router.submit(req)
    assert req.state is serve.RequestState.SHED
    assert "no admitting replica" in req.shed_reason


# ---------------------------------------------------------------------------
# replica loss mid-decode: failover ladder, original clock, zero leaks


def test_replica_loss_mid_decode_reprefills_on_survivor():
    router = _fleet()
    reqs = _load(8, max_new=(6, 10))
    for r in reqs:
        router.submit(r)
    lost = None
    for _ in range(400):
        router.step()
        d0 = router._by_id["d0"]
        if any(s is not None
               and s.request.state is serve.RequestState.DECODE
               for s in d0.scheduler.slots):
            before = {r.req_id: r.submitted_s for r in reqs}
            moved = router.lose_replica("d0", reason="test loss")
            lost = ("d0", moved, before)
            break
    assert lost is not None, "no mid-decode resident to lose"
    _, moved, before = lost
    assert moved, "the lost replica had residents"
    router.run_until_idle(max_steps=4000)
    backend = router.replicas[0].scheduler.backend
    assert all(r.state is serve.RequestState.DONE for r in reqs)
    assert all(r.tokens == backend.expected_tokens(r) for r in reqs)
    # the ORIGINAL submit clock survived the failover resubmit
    for r in reqs:
        if r.req_id in moved:
            assert r.submitted_s == before[r.req_id]
    # zero leaked pages on EVERY replica, the lost one included
    for rep in router.replicas:
        assert rep.scheduler.pool.used_pages == 0, rep.replica_id
    assert router.lost_replicas == ["d0"]
    # a lost replica is terminal: probes never readmit it
    with pytest.raises(ValueError, match="LOST"):
        router.readmit("d0")


# ---------------------------------------------------------------------------
# satellite 2: failover resubmit extends the SAME gapless trace chain


def test_failover_trace_chain_gapless_with_resubmit_tag(trace_on):
    inj = _Flap(2, 6)
    router = _fleet(hooks={"d0": inj},
                    config=serve.FleetConfig(
                        flap_threshold=100,   # no quarantine: pure failover
                        probe_interval_steps=1 << 30))
    req = _load(1, seed=5, max_new=(6, 8))[0]
    t_submit = time.monotonic()
    router.submit(req)
    router.run_until_idle(max_steps=4000)
    assert req.state is serve.RequestState.DONE
    assert inj.fired >= 1, "the decode fault never landed"
    tr = req.trace
    assert tr is not None and tr.closed
    # one chain, no gap: every span closes where the next opens, the
    # failed hop's spans included
    assert rtrace.verify_chain(tr) == []
    # the resubmit's queue_wait is tagged, and the failover annotation
    # names the replica it left
    assert any(s.name == "queue_wait" and s.tags.get("resubmit")
               for s in tr.spans)
    assert any(e.name == "failover" and e.tier == "d0"
               for e in tr.events)
    # the original clock survived: the terminal latency covers the
    # WHOLE life including the failed replica's time
    assert req.submitted_s <= t_submit + 0.5
    assert req.finished_s is not None
    assert req.finished_s > req.submitted_s


# ---------------------------------------------------------------------------
# flap -> sticky breaker -> drain-before-evict -> probe readmission


def test_flap_walks_quarantine_with_drain_before_evict():
    inj = _Flap(2, 12)
    router = _fleet(hooks={"d1": inj},
                    config=serve.FleetConfig(
                        flap_threshold=3,
                        probe_interval_steps=1 << 30))
    reqs = _load(10, max_new=(6, 10))
    for r in reqs:
        router.submit(r)
    d1 = router._by_id["d1"]
    saw_draining = False
    for _ in range(4000):
        res = router.step()
        if d1.draining and not d1.evicted:
            saw_draining = True
            # draining refuses NEW admission but keeps stepping
            assert not router._admitting(d1)
            assert router._steppable(d1)
        if res.idle:
            break
    assert inj.fired >= 3
    assert saw_draining, "the breaker never opened into a drain"
    assert d1.evicted and d1.quarantined and not d1.lost
    assert resilience.breaker(replica_breaker_name("d1")).open
    assert router.quarantined_history == ["d1"]
    backend = router.replicas[0].scheduler.backend
    assert all(r.state is serve.RequestState.DONE for r in reqs)
    assert all(r.tokens == backend.expected_tokens(r) for r in reqs)
    for rep in router.replicas:
        assert rep.scheduler.pool.used_pages == 0, rep.replica_id


def test_probe_readmission_after_flap_clears():
    inj = _Flap(2, 9)
    router = _fleet(hooks={"d1": inj},
                    config=serve.FleetConfig(
                        flap_threshold=3,
                        probe_interval_steps=8,
                        readmit_probe_successes=2))
    reqs = _load(10, max_new=(6, 10))
    for r in reqs:
        router.submit(r)
    for _ in range(4000):
        router.step()
        if router.readmissions:
            break
    assert router.readmissions == ["d1"]
    d1 = router._by_id["d1"]
    assert "d1" in router.quarantined_history   # it DID quarantine
    assert not d1.evicted and not d1.draining
    assert router._admitting(d1)
    assert not resilience.breaker(replica_breaker_name("d1")).open
    router.run_until_idle(max_steps=4000)
    assert all(r.state is serve.RequestState.DONE for r in reqs)
    assert router.leaked_pages() == 0


# ---------------------------------------------------------------------------
# SLO-driven rebalance: attribution -> membership conversion


def test_rebalance_converts_prefill_replica_under_decode_demand(trace_on):
    rng = random.Random(0)
    row = rmatrix._fleet_rebalance_cell(rng)
    assert row["outcome"] == "survived", row["detail"]
    assert row["rebalances"], "no membership conversion recorded"
    rb = row["rebalances"][0]
    assert (rb["from"], rb["to"]) == ("prefill", "decode")
    # the convergence pin: within the claims gate's ceiling
    assert rb["convergence_steps"] <= 512
    assert row["pages_leaked"] == 0


def test_rebalance_never_empties_the_donor_role():
    router = _fleet(config=serve.FleetConfig(rebalance_interval_steps=1,
                                             rebalance_sustain=1))
    # force-drain p1 so only ONE admitting prefill donor remains
    router._by_id["p1"].draining = True
    router._dom_role = "decode"
    router._dom_count = 5
    # directly exercise the donor guard: one admitting prefill replica
    # must never be recruited away
    router.steps = router.cfg.rebalance_interval_steps
    router._rebalance_tick()
    assert router._recruit is None
    assert router._by_id["p0"].role == "prefill"


# ---------------------------------------------------------------------------
# satellite 1: health aggregation over N named replicas


def test_health_snapshot_carries_quarantined_replicas():
    br = resilience.breaker(replica_breaker_name("d1"), 1)
    br.record_failure()
    snap = resilience.health_snapshot()
    assert "d1" in snap["quarantined_replicas"]
    resilience.reset_breaker(replica_breaker_name("d1"))
    assert "d1" not in \
        resilience.health_snapshot()["quarantined_replicas"]


def test_fleet_health_names_replicas_and_roles():
    router = _fleet()
    snap = router.health()
    assert snap["status"] == "ok"
    assert set(snap["replicas"]) == set(_IDS)
    assert snap["unavailable_roles"] == []
    assert snap["saturated_replicas"] == []
    assert snap["fleet"]["roles"] == {"prefill": 2, "decode": 2}


def test_fleet_health_unavailable_when_role_empty():
    router = _fleet()
    router.lose_replica("d0", reason="test")
    router.lose_replica("d1", reason="test")
    snap = router.health()
    assert snap["status"] == "unavailable"
    assert snap["unavailable_roles"] == ["decode"]


def test_fleet_health_saturated_replica_named():
    router = _fleet()
    d0 = router._by_id["d0"].scheduler
    d0._saturated_since = time.monotonic() - 1.0
    snap = router.health()
    assert "d0" in snap["saturated_replicas"]
    assert snap["status"] == "saturated"


# ---------------------------------------------------------------------------
# satellite 3: FLEET_GOLDEN <-> FleetFault both directions


def test_fleet_golden_matches_live_enum_both_directions():
    live = {f.value for f in serve.FleetFault}
    assert set(rmatrix.FLEET_GOLDEN) == live
    from triton_distributed_tpu.analysis import completeness

    assert completeness.check_fleet_coverage() == []


def test_fleet_coverage_flags_drift(monkeypatch):
    from triton_distributed_tpu.analysis import completeness

    golden = dict(rmatrix.FLEET_GOLDEN)
    removed = next(iter(golden))
    trimmed = {k: v for k, v in golden.items() if k != removed}
    trimmed["ghost_fault"] = {"leg": "x", "outcome": "survived"}
    monkeypatch.setattr(rmatrix, "FLEET_GOLDEN", trimmed)
    problems = completeness.check_fleet_coverage()
    assert any(removed in p and "no FLEET_GOLDEN" in p
               for p in problems)
    assert any("ghost_fault" in p and "no longer exists" in p
               for p in problems)


def test_verify_fleet_matrix_flags_missing_cell():
    rows = [{"kernel": "serve/fleet", "fault": f, "leg": g["leg"],
             "fired": True, "outcome": g["outcome"], "named": ["x"],
             "replica": "x", "pages_leaked": 0,
             "pages_leaked_by_replica": {}, "lifecycle_events": 1,
             "lifecycle_violations": [], "detail": ""}
            for f, g in rmatrix.FLEET_GOLDEN.items()]
    assert rmatrix.verify_fleet_matrix(rows) == []
    problems = rmatrix.verify_fleet_matrix(rows[1:])
    assert any(rows[0]["fault"] in p for p in problems)
    # wrong outcome flagged
    flipped = [dict(r) for r in rows]
    flipped[0]["outcome"] = ("survived"
                             if rows[0]["outcome"] == "detected"
                             else "detected")
    assert any("expected" in p
               for p in rmatrix.verify_fleet_matrix(flipped))


# ---------------------------------------------------------------------------
# the trend sentinel classifies the fleet metrics


def test_history_direction_for_fleet_metrics():
    from triton_distributed_tpu.obs import history

    assert history.direction_for(
        "fleet_ttft_ms_p99_under_loss", "ms") == "lower"
    assert history.direction_for(
        "fleet_rebalance_convergence_steps", "steps") == "lower"


# ---------------------------------------------------------------------------
# the CI hook


def test_tdt_lint_fleet_smoke():
    """The tier-1 CI hook (like the --handoff / --serve smokes): the
    seeded N=4 replay with one replica lost and one flapping, plus the
    fleet fault cells."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--fleet"],
        capture_output=True, text=True, timeout=540,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fleet OK" in proc.stdout
    assert "DETECTED" in proc.stdout and "SURVIVED" in proc.stdout
