"""Decode megakernel (ISSUE 8, ``ops.fused_decode``): protocol coverage of
the semaphore-chained fused MLP+AllReduce, fault-matrix cells, dispatch
accounting, the rebuild-once KV writeback, and — where this jax build can
run shard_map/interpret kernels — numerical parity of
``decode_mode="fused"`` against the per-kernel reference chain."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu import analysis
from triton_distributed_tpu.analysis import registry
from triton_distributed_tpu.core.compilation import interpret_supported
from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
from triton_distributed_tpu.models import ModelConfig, Qwen3
from triton_distributed_tpu.models.kv_cache import (
    init_paged_cache,
    replace_layer_slices,
)
from triton_distributed_tpu.models.qwen import DECODE_MODES
from triton_distributed_tpu.ops.fused_decode import (
    DISPATCH_PRIMS,
    FusedMlpConfig,
    count_jaxpr_dispatches,
    fused_mlp_candidates,
)


def _mesh1():
    return make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])


# ---------------------------------------------------------------------------
# protocol coverage (headless: record mode, no pallas, no shard_map)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("variant", ["swiglu", "linear"])
def test_fused_mlp_ar_protocol_clean(n, variant):
    """The semaphore-chained MLP/o-proj + two-shot-AR kernel passes all
    four static checks (signal balance, deadlock freedom, write overlap,
    divergence) at every registry rank count."""
    case = next(c for c in registry.cases_for("fused_mlp_ar", n)
                if c.name.endswith(variant))
    assert registry.verify_case(case) == []


def test_fused_mlp_ar_chains_gemm_into_ring():
    """Structural evidence of the fusion: ONE recorded kernel body holds
    the up-projection GEMMs, the SwiGLU fold, the down-proj chunk GEMMs
    AND the ring's remote copies/acks — no host boundary between the
    compute and the reduction."""
    from triton_distributed_tpu.analysis.record import record_kernel

    case = next(c for c in registry.cases_for("fused_mlp_ar", 4)
                if c.name.endswith("swiglu"))
    label, thunk = case.make(0)
    assert label == "swiglu"
    rec = record_kernel(thunk, n=4, rank=0)
    sig = rec.signature
    assert "compute:swiglu" in sig
    assert "compute:matmul" in sig
    assert "compute:add" in sig
    assert "remote_copy" in sig
    # the ring work happens AFTER the fused prologue in the same body
    assert sig.index("compute:swiglu") < sig.index("remote_copy")
    # phase 1 forwards n-1 partials, phase 2 forwards n-1 reduced chunks
    assert sig.count("remote_copy") == 2 * (4 - 1)


def test_fused_family_in_default_matrix():
    names = {c.name for c in analysis.all_cases(ranks=(4,))}
    assert {"fused_mlp_ar/swiglu", "fused_mlp_ar/linear"} <= names


def test_fused_fault_cells_detected_or_survived():
    """Every fault class lands a verdict on the fused kernel, and the
    must-detect classes name the pending semaphore/chunk."""
    from triton_distributed_tpu import resilience as rz

    rows = rz.run_matrix(seed=0, kernels=("fused_mlp_ar/swiglu",))
    assert rows, "no fused cells ran"
    kinds = {r["fault"] for r in rows}
    assert {"drop_notify", "stale_credit", "rank_abort",
            "corrupt_payload"} <= kinds
    for row in rows:
        assert row["outcome"] in ("detected", "survived"), row
        if row["fault"] in {k.value for k in rz.matrix.MUST_DETECT}:
            assert row["outcome"] == "detected", row
            assert row["named"], row


def test_fused_watchdog_has_deadline_and_static_diagnosis():
    """The resilience ladder prices the fused family like any other
    collective: a finite SOL-derived deadline and a static wait-structure
    diagnosis naming its semaphores."""
    from triton_distributed_tpu.resilience import watchdog

    d = watchdog.deadline_ms("fused_mlp_ar", payload_bytes=1 << 20,
                             num_ranks=4)
    assert 0 < d < float("inf")
    diag = watchdog.protocol_pending("fused_mlp_ar", 4)
    assert diag is not None
    sems = diag.semaphores()
    assert any("recv_sems" in s or "ack_sems" in s for s in sems), sems


def test_fused_costs_registered():
    """obs.costs carries both megakernel families — the one flop/byte
    truth for Mosaic cost estimates, watchdog deadlines and the
    timeline."""
    from triton_distributed_tpu.obs import costs

    attn = costs.FAMILY_COSTS["fused_attn_decode"](
        8, 2048, 16, 8, 4096, 128, jnp.bfloat16)
    mlp = costs.FAMILY_COSTS["fused_mlp_ar"](
        8, 2048, 512, 2048, 4, jnp.bfloat16)
    assert attn.flops > 0 and attn.bytes_accessed > 0
    assert attn.transcendentals > 0          # softmax + rope
    assert mlp.flops > 0 and mlp.wire_bytes > 0
    assert mlp.transcendentals > 0           # the silu exp
    lin = costs.FAMILY_COSTS["fused_mlp_ar"](
        8, 512, 512, 2048, 4, jnp.bfloat16, swiglu=False)
    assert lin.transcendentals == 0
    assert costs.sol_ms(mlp) > 0


def test_fused_mlp_candidates_default_first_and_deduped():
    cands = fused_mlp_candidates(8, 512, 512)
    assert cands[0] == FusedMlpConfig().clip(8, 512, 512)
    assert len(cands) == len(set(cands))
    # B=1 decode: every bm clips to the whole-row tile, sweep collapses
    tiny = fused_mlp_candidates(1, 512, 256)
    assert all(c.bm == 1 for c in tiny)


def test_fused_decode_mode_registered():
    assert "fused" in DECODE_MODES
    cfg = ModelConfig(num_layers=1, hidden=64, intermediate=128,
                      num_heads=4, num_kv_heads=2, head_dim=32, vocab=64,
                      max_length=32, dtype=jnp.float32)
    model = Qwen3(cfg, _mesh1(), decode_mode="fused")
    assert model.decode_mode == "fused"
    with pytest.raises(ValueError):
        Qwen3(cfg, _mesh1(), decode_mode="megakernel")


# ---------------------------------------------------------------------------
# dispatch accounting (headless: jaxpr walking, tracing only)


def test_dispatch_counter_counts_launch_shaped_eqns():
    from jax.experimental import pallas as pl

    def pk(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2

    f = pl.pallas_call(
        pk, out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32))

    def fn(x, w):
        y = f(x)                                        # 1 pallas_call
        z = jnp.dot(y, w)                               # 1 dot_general
        return jax.lax.dynamic_update_slice(            # 1 update
            z, jnp.zeros((1, 128)), (0, 0))

    x = jnp.zeros((8, 128))
    w = jnp.zeros((128, 128))
    assert count_jaxpr_dispatches(fn, x, w) == 3
    # elementwise chains are NOT dispatches (they fuse)
    assert count_jaxpr_dispatches(lambda a: jnp.tanh(a) + 1, x) == 0
    assert "pallas_call" in DISPATCH_PRIMS


def test_dispatch_counter_descends_into_jitted_calls():
    def inner(x, w):
        return jnp.dot(x, w)

    def fn(x, w):
        return jax.jit(inner)(x, w) + jax.jit(inner)(x, w)

    x = jnp.zeros((8, 8))
    assert count_jaxpr_dispatches(fn, x, x) == 2


# ---------------------------------------------------------------------------
# rebuild-once KV writeback (satellite: the per-layer full-pool copy fix)


def _tiny_cache(layers=3):
    return init_paged_cache(_mesh1(), layers, 2, 1, 16, 8, jnp.float32,
                            page_size=4)


def test_replace_layer_slices_values_and_validation():
    cache = _tiny_cache()
    ks = [jnp.full(cache.k.shape[1:], i, jnp.float32) for i in range(3)]
    vs = [jnp.full(cache.v.shape[1:], 10 + i, jnp.float32)
          for i in range(3)]
    c2 = replace_layer_slices(cache, ks, vs)
    assert np.allclose(np.asarray(c2.k[1]), 1.0)
    assert np.allclose(np.asarray(c2.v[2]), 12.0)
    assert c2.k.dtype == cache.k.dtype
    with pytest.raises(ValueError, match="one slice per layer"):
        replace_layer_slices(cache, ks[:2], vs)


def _count_prims(fn, *args):
    jaxpr = jax.make_jaxpr(fn)(*args)
    counts = {}

    def walk(jx):
        for eqn in jx.eqns:
            counts[eqn.primitive.name] = \
                counts.get(eqn.primitive.name, 0) + 1
            for v in eqn.params.values():
                from triton_distributed_tpu.ops.fused_decode import \
                    _sub_jaxprs

                for sub in _sub_jaxprs(v):
                    walk(sub)

    walk(jaxpr.jaxpr)
    return counts


def test_decode_writeback_copy_count():
    """The decode-loop writeback contract: threading per-layer slices and
    rebuilding once eliminates every full-pool ``dynamic_update_slice``
    (L whole-pool copies per step on unfused paths) in favour of exactly
    one stacked materialization per pool."""
    layers = 3
    cache = _tiny_cache(layers)
    ks = [jnp.zeros(cache.k.shape[1:], jnp.float32) for _ in range(layers)]

    def old_pattern(cache, ks, vs):
        # what Qwen3._attn_decode* used to do, once per layer
        for li in range(layers):
            cache = dataclasses.replace(
                cache,
                k=jax.lax.dynamic_update_slice(
                    cache.k, ks[li][None], (li, 0, 0, 0, 0)),
                v=jax.lax.dynamic_update_slice(
                    cache.v, vs[li][None], (li, 0, 0, 0, 0)),
            )
        return cache

    def new_pattern(cache, ks, vs):
        return replace_layer_slices(cache, list(ks), list(vs))

    old = _count_prims(old_pattern, cache, ks, ks)
    new = _count_prims(new_pattern, cache, ks, ks)
    assert old.get("dynamic_update_slice", 0) == 2 * layers
    assert new.get("dynamic_update_slice", 0) == 0
    assert new.get("concatenate", 0) == 2        # one stack per pool


def test_qwen_decode_has_no_full_pool_update():
    """Source-level pin that the model's decode loop threads slices: the
    decode path must not contain a stacked-pool ``dynamic_update_slice``
    writeback (the jaxpr-level pin needs shard_map; the source pin holds
    on every jax build)."""
    import inspect

    from triton_distributed_tpu.models import qwen

    src = inspect.getsource(qwen.Qwen3.decode)
    assert "replace_layer_slices" in src
    for fn in (qwen.Qwen3._attn_decode, qwen.Qwen3._attn_decode_paged,
               qwen.Qwen3._attn_decode_paged_fused):
        assert "dynamic_update_slice(\n                cache.k" \
            not in inspect.getsource(fn)
        assert "return self._row_parallel_reduce(out, p.wo), k_l, v_l" \
            in inspect.getsource(fn)


# ---------------------------------------------------------------------------
# degraded-fallback shape (headless: pure function inspection)


def test_xla_fused_mlp_ar_fallback_registered():
    from triton_distributed_tpu.resilience import fallbacks

    assert callable(fallbacks.xla_fused_mlp_ar)


# ---------------------------------------------------------------------------
# review fixes (headless: stubbed builder, no shard_map): the jitted
# decode step must consult the autotuner winner cache, and the fused
# entries must ride the TDT_INTEGRITY consumer-side check like every
# other guarded collective


class _StubMesh:
    """Hashable stand-in carrying only what the entries read headlessly
    (``mesh.shape[axis]``); the kernel builder is monkeypatched out."""

    def __init__(self, n):
        self.shape = {TP_AXIS: n}


def _stub_builder(captured):
    def build(mesh, axis, b, k_in, k_loc, n_dim, swiglu, dtype, out_dtype,
              cfg):
        captured.append(cfg)
        n = mesh.shape[axis]
        return lambda *a: jnp.zeros((n * b, n_dim // n), out_dtype)

    return build


def test_fused_mlp_config_resolves_under_tracing(tmp_path, monkeypatch):
    """The serving path is ``jax.jit(model.decode)`` — x is ALWAYS a
    tracer there, so config resolution must consult the winner cache
    under tracing (resolve_config's contract) or a bench/warmup crown
    never reaches production; pinned after a review catch where the
    traced path silently ran the default config."""
    from triton_distributed_tpu.core import platform
    from triton_distributed_tpu.ops import fused_decode as fd
    from triton_distributed_tpu.tune import autotuner as at

    monkeypatch.setattr(at, "_GLOBAL",
                        at.Autotuner(path=str(tmp_path / "w.json")))
    monkeypatch.setenv("TDT_AUTOTUNE", "0")   # never measure in this test

    n, b, k_in = 2, 3, 8
    f_dim = n_dim = 2048                      # big enough that tile
    k_loc, cn = f_dim // n, n_dim // n        # candidates stay distinct
    cands = fd.fused_mlp_candidates(b, k_loc, cn)
    winner = next(c for c in cands[1:])       # a NON-default candidate
    key = (b, k_in, k_loc, n_dim, n, "float32", platform.device_kind())
    at._GLOBAL._resolved[("fused_mlp_ar", tuple(map(str, key)))] = winner

    captured = []
    monkeypatch.setattr(fd, "_build_fused_mlp_ar", _stub_builder(captured))
    mesh = _StubMesh(n)
    x = jnp.zeros((b, k_in), jnp.float32)
    gate_up = jnp.zeros((k_in, 2 * f_dim), jnp.float32)
    down = jnp.zeros((f_dim, n_dim), jnp.float32)

    out = jax.jit(
        lambda x, gu, dn: fd.fused_mlp_ar(x, gu, dn, mesh))(x, gate_up,
                                                            down)
    assert out.shape == (b, n_dim)
    assert captured and captured[-1] == winner


def test_fused_entries_wrap_integrity_checked(monkeypatch):
    """With TDT_INTEGRITY armed, both fused entries route their core
    through ``integrity.checked`` like the other guarded collectives —
    otherwise a flipped ring chunk on the fused decode path would produce
    wrong logits with no PayloadCorruption, no counter, no quarantine."""
    from triton_distributed_tpu.ops import fused_decode as fd
    from triton_distributed_tpu.resilience import integrity

    calls = []

    def spy_checked(op, thunk, verify=None, *, ranks=None):
        calls.append((op, ranks, callable(verify)))
        return thunk

    monkeypatch.setattr(integrity, "enabled", lambda: True)
    monkeypatch.setattr(integrity, "checked", spy_checked)
    monkeypatch.setattr(fd, "_build_fused_mlp_ar", _stub_builder([]))

    n = 2
    mesh = _StubMesh(n)
    x = jnp.zeros((2, 4), jnp.float32)
    gate_up = jnp.zeros((4, 16), jnp.float32)
    down = jnp.zeros((8, 8), jnp.float32)
    fd.fused_mlp_ar(x, gate_up, down, mesh, config=FusedMlpConfig())
    h = jnp.zeros((2, 8), jnp.float32)
    w = jnp.zeros((8, 8), jnp.float32)
    fd.fused_linear_ar(h, w, mesh, config=FusedMlpConfig())
    assert calls == [("fused_mlp_ar", n, True),
                     ("fused_linear_ar", n, True)]


def test_fused_mlp_integrity_verify_math():
    """The host act mirror reproduces the kernel's rank-blocked
    ``[gate_r | up_r]`` SwiGLU (so ``act @ down`` IS the allreduced
    product), and the Freivalds check passes the clean result while
    catching a planted flip with the row named."""
    from triton_distributed_tpu.ops.fused_decode import _mlp_act_host
    from triton_distributed_tpu.resilience import integrity

    rng = np.random.default_rng(3)
    n, b, k_in, f_dim, k_out = 2, 3, 8, 8, 8
    x = jnp.asarray(rng.standard_normal((b, k_in)), jnp.float32)
    gate_up = jnp.asarray(rng.standard_normal((k_in, 2 * f_dim)),
                          jnp.float32)
    down = jnp.asarray(rng.standard_normal((f_dim, k_out)), jnp.float32)

    act = np.asarray(_mlp_act_host(x, gate_up, n, jnp.float32))
    fh = f_dim // n
    gu = np.asarray(gate_up)
    gates = np.concatenate(
        [gu[:, r * 2 * fh:r * 2 * fh + fh] for r in range(n)], axis=1)
    ups = np.concatenate(
        [gu[:, r * 2 * fh + fh:(r + 1) * 2 * fh] for r in range(n)], axis=1)
    g = np.asarray(x) @ gates
    ref = (g / (1 + np.exp(-g))) * (np.asarray(x) @ ups)
    np.testing.assert_allclose(act, ref, rtol=1e-5, atol=1e-5)

    out = act @ np.asarray(down)        # what a clean AllReduce returns
    assert integrity.verify_gemm("fused_mlp_ar", act, down, out) is None
    bad = out.copy()
    bad[1, 2] += 25.0
    diag = integrity.verify_gemm("fused_mlp_ar", act, down, bad)
    assert diag is not None and diag.chunk == "out[1, :]"


# ---------------------------------------------------------------------------
# numerical parity (needs shard_map + pallas interpret: capability-gated)

CFG8 = ModelConfig(
    num_layers=2, hidden=128, intermediate=256, num_heads=8, num_kv_heads=8,
    head_dim=32, vocab=128, max_length=64, dtype=jnp.float32,
)

needs_interpret = pytest.mark.skipif(
    not interpret_supported(),
    reason="jax build lacks shard_map/Pallas-interpret APIs",
)


def _paged_cache8(mesh, batch):
    return init_paged_cache(mesh, CFG8.num_layers, batch,
                            CFG8.num_kv_heads, CFG8.max_length,
                            CFG8.head_dim, CFG8.dtype, page_size=16)


@needs_interpret
@pytest.mark.parametrize("batch", [3, 8])
def test_fused_decode_logits_parity_paged(mesh8, batch):
    """decode_mode="fused" (attention megakernel + semaphore-chained
    reductions) matches the per-kernel psum chain on the paged cache —
    logits AND the full page pools after the step."""
    mesh = mesh8
    params = Qwen3(CFG8, mesh).init(jax.random.key(21), scale=0.05)
    ids = jax.random.randint(jax.random.key(22), (batch, 16), 0, CFG8.vocab)
    step = jax.random.randint(jax.random.key(23), (batch,), 0, CFG8.vocab)

    out = {}
    for mode in ("psum", "fused"):
        model = Qwen3(CFG8, mesh, decode_mode=mode)
        cache = _paged_cache8(mesh, batch)
        _, cache = jax.jit(model.prefill)(params, cache, ids)
        logits, cache = jax.jit(model.decode)(params, cache, step)
        out[mode] = (np.asarray(jax.device_get(logits)),
                     np.asarray(jax.device_get(cache.k)),
                     np.asarray(jax.device_get(cache.v)))
        assert int(cache.seq_lens[0]) == 17
    for got, want, what in zip(out["fused"], out["psum"],
                               ("logits", "pool_k", "pool_v")):
        assert np.allclose(got, want, atol=2e-3, rtol=2e-3), (
            what, np.abs(got - want).max())


@needs_interpret
def test_fused_decode_logits_parity_contiguous(mesh8):
    """On a contiguous cache fused mode keeps the per-kernel attention
    and fuses the reductions only — logits still match psum exactly
    within tolerance."""
    from triton_distributed_tpu.models import init_cache

    mesh = mesh8
    batch = 8
    params = Qwen3(CFG8, mesh).init(jax.random.key(31), scale=0.05)
    ids = jax.random.randint(jax.random.key(32), (batch, 16), 0, CFG8.vocab)
    step = jax.random.randint(jax.random.key(33), (batch,), 0, CFG8.vocab)
    logits = {}
    for mode in ("psum", "fused"):
        model = Qwen3(CFG8, mesh, decode_mode=mode)
        cache = init_cache(mesh, CFG8.num_layers, batch, CFG8.num_kv_heads,
                           CFG8.max_length, CFG8.head_dim, CFG8.dtype)
        _, cache = jax.jit(model.prefill)(params, cache, ids)
        out, cache = jax.jit(model.decode)(params, cache, step)
        logits[mode] = np.asarray(jax.device_get(out))
        assert int(cache.kv_len) == 17
    assert np.allclose(logits["psum"], logits["fused"],
                       atol=2e-3, rtol=2e-3), (
        np.abs(logits["psum"] - logits["fused"]).max())


@needs_interpret
def test_fused_dispatch_reduction_on_slice(mesh8):
    """The acceptance number: on a TP slice the fused chain issues <= half
    the per-kernel chain's dispatches per decode step."""
    from triton_distributed_tpu.ops import count_decode_dispatches

    batch = 8
    params = Qwen3(CFG8, mesh8).init(jax.random.key(41), scale=0.05)
    cache = _paged_cache8(mesh8, batch)
    tok = jnp.zeros((batch,), jnp.int32)
    counts = {
        mode: count_decode_dispatches(
            Qwen3(CFG8, mesh8, decode_mode=mode), params, cache, tok)
        for mode in ("psum", "fused")
    }
    assert counts["fused"] > 0
    assert counts["psum"] >= 2 * counts["fused"], counts


@needs_interpret
def test_xla_fused_mlp_ar_fallback_golden(mesh8):
    """The degraded fallback equals the plain replicated formula."""
    from triton_distributed_tpu.resilience import fallbacks

    k = jax.random.key(51)
    x = jax.random.normal(k, (4, 64), jnp.float32)
    gu = jax.random.normal(jax.random.fold_in(k, 1), (64, 256),
                           jnp.float32) * 0.1
    dn = jax.random.normal(jax.random.fold_in(k, 2), (128, 64),
                           jnp.float32) * 0.1
    got = fallbacks.xla_fused_mlp_ar(x, gu, dn, mesh8, "tp")
    # reference on the rank-blocked [gate_r | up_r] layout
    n = 8
    f_loc = 128 // n
    t = jnp.dot(x, gu).reshape(4, n, 2, f_loc)
    act = (jax.nn.silu(t[:, :, 0]) * t[:, :, 1]).reshape(4, 128)
    want = jnp.dot(act, dn)
    assert np.allclose(np.asarray(got), np.asarray(want),
                       atol=1e-4, rtol=1e-4)
