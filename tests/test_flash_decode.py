"""Distributed flash-decode vs full-cache single-device golden (reference
``test_flash_decode.py`` strategy: split-KV + inter-rank combine must equal
plain attention)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import SP_AXIS, make_mesh
from triton_distributed_tpu.ops.attention import decode_attention
from triton_distributed_tpu.ops.flash_decode import sp_flash_decode


def _inputs(b, h, hk, s, d, key=0, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(kq, (b, h, d), dtype)
    k = jax.random.normal(kk, (b, hk, s, d), dtype)
    v = jax.random.normal(kv, (b, hk, s, d), dtype)
    return q, k, v


def _mesh(n):
    return make_mesh({SP_AXIS: n}, devices=jax.devices()[:n])


def _shard_cache(mesh, k, v):
    spec = NamedSharding(mesh, P(None, None, SP_AXIS, None))
    return jax.device_put(k, spec), jax.device_put(v, spec)


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("h,hk", [(4, 4), (8, 2)])
def test_sp_flash_decode_matches_full(n, h, hk):
    b, s, d = 2, 512, 64
    kv_len = 500
    q, k, v = _inputs(b, h, hk, s, d)
    mesh = _mesh(n)
    ks, vs = _shard_cache(mesh, k, v)
    out = sp_flash_decode(q, ks, vs, kv_len, mesh)
    want = decode_attention(q, k, v, kv_len)
    assert out.shape == (b, h, d)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5), (
        jnp.abs(out - want).max()
    )


def test_sp_flash_decode_ragged_lengths():
    """(B,) ragged lengths through the sequence-sharded decode: per-rank
    clipping happens per sequence, including sequences that end before a
    rank's slice begins."""
    n, b, h, hk, s, d = 4, 3, 8, 2, 512, 64
    lens = jnp.asarray([500, 90, 260], jnp.int32)  # spans 4 / 1 / 3 ranks
    q, k, v = _inputs(b, h, hk, s, d)
    mesh = _mesh(n)
    ks, vs = _shard_cache(mesh, k, v)
    out = sp_flash_decode(q, ks, vs, lens, mesh)
    want = decode_attention(q, k, v, lens)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5), (
        jnp.abs(out - want).max()
    )


def test_sp_flash_decode_short_cache_empty_ranks():
    """kv_len inside the first shard: later ranks are fully masked and must
    drop out of the merge (zero-denominator guard)."""
    n, b, h, hk, s, d = 4, 1, 4, 2, 512, 64
    kv_len = 100  # < s/n = 128: ranks 1..3 hold no valid positions
    q, k, v = _inputs(b, h, hk, s, d, key=1)
    mesh = _mesh(n)
    ks, vs = _shard_cache(mesh, k, v)
    out = sp_flash_decode(q, ks, vs, kv_len, mesh)
    want = decode_attention(q, k, v, kv_len)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5)


def test_sp_flash_decode_with_splits():
    """Local splits and cross-rank merge compose (associativity)."""
    n, b, h, hk, s, d = 4, 1, 8, 2, 1024, 64
    kv_len = 700
    q, k, v = _inputs(b, h, hk, s, d, key=2)
    mesh = _mesh(n)
    ks, vs = _shard_cache(mesh, k, v)
    out = sp_flash_decode(q, ks, vs, kv_len, mesh, n_split=2)
    want = decode_attention(q, k, v, kv_len)
    assert jnp.allclose(out, want, atol=2e-5, rtol=2e-5)


def test_sp_flash_decode_bf16():
    n, b, h, hk, s, d = 4, 1, 4, 4, 512, 128
    q, k, v = _inputs(b, h, hk, s, d, key=3, dtype=jnp.bfloat16)
    mesh = _mesh(n)
    ks, vs = _shard_cache(mesh, k, v)
    out = sp_flash_decode(q, ks, vs, s, mesh)
    want = decode_attention(q, k, v, s)
    assert out.dtype == jnp.bfloat16
    assert jnp.allclose(out.astype(jnp.float32), want.astype(jnp.float32),
                        atol=5e-2, rtol=5e-2)


def test_sp_flash_decode_single_rank_fallback():
    b, h, hk, s, d = 1, 4, 4, 256, 64
    q, k, v = _inputs(b, h, hk, s, d, key=4)
    mesh = _mesh(1)
    out = sp_flash_decode(q, k, v, 200, mesh)
    want = decode_attention(q, k, v, 200)
    assert jnp.allclose(out, want, atol=0, rtol=0)
