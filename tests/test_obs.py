"""Observability subsystem (ISSUE 1): registry semantics, histogram
bucketing, JSONL/Prometheus round trips, span tracing + merged-trace
overlap report, instrumentation hooks, and the ``obs_report --selftest``
CLI."""

import json
import os
import subprocess
import sys
import threading
import types

import jax
import jax.numpy as jnp
import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu.obs import report
from triton_distributed_tpu.obs.registry import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def obs_on():
    """Enabled obs with a clean registry/trace buffer, restored after."""
    prev = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    obs.tracing.clear()
    yield obs
    obs.REGISTRY.reset()
    obs.tracing.clear()
    obs.enable(prev)


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_gauge_semantics():
    r = Registry()
    c = r.counter("reqs", op="ag")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # get-or-create: same identity for same (name, labels)
    assert r.counter("reqs", op="ag") is c
    # distinct labels -> distinct series
    assert r.counter("reqs", op="rs") is not c
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("occ")
    g.set(0.5)
    g.add(0.25)
    assert g.value == 0.75


def test_histogram_bucketing_and_quantiles():
    r = Registry()
    h = r.histogram("lat_ms", (1.0, 10.0, 100.0))
    for v in (0.5, 0.9, 5.0, 50.0, 500.0):
        h.observe(v)
    # cumulative bucket counts: <=1: 2, <=10: 3, <=100: 4, +Inf: 5
    row = h.row()
    assert row["counts"] == [2, 3, 4]
    assert row["count"] == 5
    assert row["sum"] == pytest.approx(556.4)
    assert row["min"] == 0.5 and row["max"] == 500.0
    assert h.quantile(0.5) == 10.0      # 3rd of 5 lands in the <=10 bucket
    assert h.quantile(1.0) == 500.0     # +Inf bucket reports observed max
    with pytest.raises(ValueError):
        r.histogram("bad", (3.0, 1.0))


def test_registry_thread_safety():
    r = Registry()
    def work():
        for _ in range(1000):
            r.counter("n").inc()
            r.histogram("h", (1.0,)).observe(0.5)
    ts = [threading.Thread(target=work) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert r.counter("n").value == 4000
    assert r.histogram("h", (1.0,)).count == 4000


def test_snapshot_sorted_and_reset():
    r = Registry()
    r.counter("b").inc()
    r.counter("a", x="2").inc()
    r.counter("a", x="1").inc()
    names = [(row["name"], row["labels"]) for row in r.snapshot()]
    assert names == [("a", {"x": "1"}), ("a", {"x": "2"}), ("b", {})]
    r.reset()
    assert r.snapshot() == []


# ---------------------------------------------------------------------------
# exporters


def _populate(r: Registry):
    r.counter("comm_calls", op="ag", method="ring_1d").inc(3)
    r.gauge("tokens_per_s").set(123.5)
    h = r.histogram("lat_ms", (1.0, 10.0))
    h.observe(0.5)
    h.observe(20.0)


def test_jsonl_round_trip(tmp_path):
    r = Registry()
    _populate(r)
    p = str(tmp_path / "m.jsonl")
    n = obs.write_jsonl(r, p, extra={"run": "t1"})
    assert n == 3
    obs.write_jsonl(r, p)  # append a second snapshot
    rows = obs.read_jsonl(p)
    assert len(rows) == 6
    first = {row["name"]: row for row in rows[:3]}
    assert first["comm_calls"]["value"] == 3
    assert first["comm_calls"]["labels"] == {"op": "ag", "method": "ring_1d"}
    assert first["comm_calls"]["run"] == "t1"
    assert first["lat_ms"]["counts"] == [1, 1]
    assert first["lat_ms"]["count"] == 2
    # one append shares one ts; the second append has a later ts
    assert len({row["ts"] for row in rows[:3]}) == 1
    assert rows[3]["ts"] >= rows[0]["ts"]


def test_prometheus_round_trip():
    r = Registry()
    _populate(r)
    text = obs.to_prometheus(r)
    assert "# TYPE comm_calls_total counter" in text
    got = obs.parse_prometheus(text)
    assert got['comm_calls_total{method="ring_1d",op="ag"}'] == 3.0
    assert got["tokens_per_s"] == 123.5
    assert got['lat_ms_bucket{le="1"}'] == 1.0
    assert got['lat_ms_bucket{le="10"}'] == 1.0
    assert got['lat_ms_bucket{le="+Inf"}'] == 2.0
    assert got["lat_ms_count"] == 2.0
    assert got["lat_ms_sum"] == pytest.approx(20.5)


def test_summary_table():
    r = Registry()
    _populate(r)
    t = obs.summary_table(r)
    assert "comm_calls" in t and "ring_1d" in t and "lat_ms" in t
    assert obs.summary_table(Registry()).startswith("(no metrics")


# ---------------------------------------------------------------------------
# enable gating


def test_disabled_is_noop():
    obs.enable(False)
    obs.REGISTRY.reset()
    obs.tracing.clear()
    try:
        obs.record_collective("ag", payload_bytes=1, wire_bytes=1, chunks=1,
                              method="m")
        obs.observe_timer("t", 1.0)
        with obs.span("s", "step"):
            pass
        assert obs.REGISTRY.snapshot() == []
        assert obs.tracing.events() == []
    finally:
        obs.enable(None)  # restore the env-derived default


def test_env_flag(monkeypatch):
    monkeypatch.setenv("TDT_OBS", "1")
    assert obs.enable(None) is True
    monkeypatch.setenv("TDT_OBS", "0")
    assert obs.enable(None) is False
    monkeypatch.delenv("TDT_OBS", raising=False)
    obs.enable(None)


# ---------------------------------------------------------------------------
# tracing + overlap report


def test_span_records_chrome_events(obs_on, tmp_path):
    with obs.span("decode_step", "step", idx=0):
        with obs.span("mlp", "compute"):
            pass
    evs = obs.tracing.events()
    assert [e["name"] for e in evs] == ["mlp", "decode_step"]  # exit order
    step = evs[1]
    assert step["ph"] == "X" and step["cat"] == "step"
    assert step["args"] == {"idx": 0}
    p = obs.tracing.export(str(tmp_path / "t.json"), clear_buffer=True)
    assert obs.tracing.events() == []
    trace = json.load(open(p))
    assert list(trace.keys()) == ["displayTimeUnit", "traceEvents"]
    assert len(trace["traceEvents"]) == 2


def test_overlap_report_two_rank_merge(obs_on, tmp_path):
    """Two per-process span exports merged into one timeline produce the
    per-step overlap table (the 2-process decode workflow, simulated by
    exporting the buffer twice and merging under two rank offsets)."""
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    with obs.span("decode_step", "step"):
        with obs.span("mlp", "compute"):
            with obs.span("all_gather", "comm"):
                pass  # comm fully inside compute -> overlap 1.0
    r0 = obs.tracing.export(str(tmp_path / "r0.json"), clear_buffer=True)
    with obs.span("decode_step", "step"):
        with obs.span("all_reduce", "comm"):
            pass  # comm with no compute -> overlap 0.0
    r1 = obs.tracing.export(str(tmp_path / "r1.json"), clear_buffer=True)

    merged = str(tmp_path / "merged.json")
    merge_traces([r0, r1], [0, 1], merged)
    rows = report.overlap_report(report.load_trace(merged))
    assert [r["rank"] for r in rows] == [0, 1]
    assert rows[0]["overlap"] == pytest.approx(1.0)
    assert rows[1]["overlap"] == pytest.approx(0.0)
    agg = report.aggregate(rows)
    assert agg["steps_with_comm"] == 2
    assert agg["mean_overlap"] == pytest.approx(0.5)
    table = report.format_report(rows)
    assert "overlap" in table and "mean overlap: 0.500" in table


def test_obs_report_cli_selftest():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    assert "selftest OK" in proc.stdout
    assert "decode_step" in proc.stdout


def test_obs_report_cli_on_files(obs_on, tmp_path):
    with obs.span("decode_step", "step"):
        with obs.span("all_gather", "comm"):
            pass
    r0 = obs.tracing.export(str(tmp_path / "r0.json"), clear_buffer=True)
    out_json = str(tmp_path / "rep.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         r0, "--json", out_json],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.load(open(out_json))
    assert rep["aggregate"]["steps"] == 1
    assert rep["rows"][0]["overlap"] == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# instrumentation hooks


def test_timer_and_perf_func_route_into_registry(obs_on, capsys):
    from triton_distributed_tpu.core.utils import perf_func, timer

    with timer("unit_block"):
        pass
    assert "unit_block" in capsys.readouterr().out  # print behavior kept
    _, ms = perf_func(lambda: jnp.zeros((8,)), iters=1, warmup_iters=1,
                      name="zeros")
    assert ms > 0
    h = obs.REGISTRY.histogram("timer_ms", name="unit_block")
    assert h.count == 1
    h2 = obs.REGISTRY.histogram("timer_ms", name="perf_func/zeros")
    assert h2.count == 1 and h2.sum == pytest.approx(ms)


def test_record_collective_metrics(obs_on):
    obs.record_collective("all_gather", payload_bytes=1024, wire_bytes=7168,
                          chunks=7, method="ring_1d")
    obs.record_collective("all_gather", payload_bytes=1024, wire_bytes=7168,
                          chunks=7, method="ring_1d")
    c = obs.REGISTRY.counter("comm_calls", op="all_gather", method="ring_1d")
    assert c.value == 2
    assert obs.REGISTRY.counter("comm_wire_bytes", op="all_gather",
                                method="ring_1d").value == 2 * 7168
    assert obs.REGISTRY.histogram("comm_payload_bytes_hist",
                                  op="all_gather").count == 2


def test_all_gather_entry_instrumented(obs_on, mesh8):
    """The eager all_gather entry records bytes/chunks/method and a comm
    span before dispatching the kernel."""
    from triton_distributed_tpu.comm import AllGatherMethod, all_gather
    from triton_distributed_tpu.core.mesh import TP_AXIS, shard
    from triton_distributed_tpu.core.utils import rand_tensor

    x = rand_tensor((16, 128), jnp.float32)
    xs = shard(mesh8, x, TP_AXIS)
    try:
        all_gather(xs, mesh8, TP_AXIS, method=AllGatherMethod.RING_1D)
    except AttributeError:
        # the kernel layer needs newer jax APIs (shard_map /
        # pltpu.CompilerParams); the instrumentation at the entry point
        # must still have recorded before dispatch, which is what the
        # asserts below check either way
        pass
    shard_bytes = (16 // 8) * 128 * 4
    assert obs.REGISTRY.counter("comm_calls", op="all_gather",
                                method="ring_1d").value == 1
    assert obs.REGISTRY.counter("comm_payload_bytes", op="all_gather",
                                method="ring_1d").value == shard_bytes
    assert obs.REGISTRY.counter("comm_wire_bytes", op="all_gather",
                                method="ring_1d").value == shard_bytes * 7
    assert obs.REGISTRY.counter("comm_chunks", op="all_gather",
                                method="ring_1d").value == 7


def test_autotuner_records_search_metrics(obs_on, tmp_path):
    from triton_distributed_tpu.tune.autotuner import Autotuner

    t = Autotuner(path=str(tmp_path / "cache.json"))
    f1 = jax.jit(lambda: jnp.zeros((32, 32)) + 1)
    f2 = jax.jit(lambda: jnp.zeros((32, 32)) + 2)
    mk = lambda c: f1 if c == "a" else f2  # noqa: E731
    t.tune("unit_op", ("k",), ["a", "b"], mk, iters=1)
    t.tune("unit_op", ("k",), ["a", "b"], mk, iters=1)  # mem-cache hit
    r = obs.REGISTRY
    assert r.counter("autotune_searches", name="unit_op").value == 1
    assert r.counter("autotune_candidates_tried", name="unit_op").value == 2
    assert r.counter("autotune_cache_hits", name="unit_op",
                     source="mem").value == 1
    assert r.gauge("autotune_last_search_s", name="unit_op").value > 0
    assert r.histogram("autotune_winner_ms", name="unit_op").count == 1
    # the sweep also dropped a timeline marker
    assert any(e["name"] == "autotune" for e in obs.tracing.events())


def test_engine_serve_metrics_recorded(obs_on):
    """The serve-loop recorder lands latency histograms + occupancy
    gauges (exercised directly; the full engine needs the TPU-interpret
    stack)."""
    from triton_distributed_tpu.models.engine import Engine

    eng = types.SimpleNamespace(
        batch=2,
        model=types.SimpleNamespace(
            config=types.SimpleNamespace(max_length=64)),
    )
    stats = {"prefill_ms": 12.0, "decode_ms_per_token": 3.0,
             "decode_tokens_per_s": 666.0}
    Engine._record_serve_metrics(eng, 8, 16, stats)
    r = obs.REGISTRY
    assert r.histogram("engine_prefill_ms").count == 1
    assert r.histogram("engine_decode_ms_per_token").sum == pytest.approx(3.0)
    assert r.gauge("engine_decode_tokens_per_s").value == 666.0
    assert r.counter("engine_tokens_generated").value == 2 * 16
    assert r.gauge("kv_cache_seq_occupancy").value == pytest.approx(24 / 64)


def test_disabled_overhead_smoke(obs_on):
    """The disabled fast path must stay allocation-free and near-free:
    span() returns the shared null context and record_collective returns
    before touching the registry (the < 1% bench.py acceptance bar rides
    on this shape, not on a timing assert that would flake in CI)."""
    obs.enable(False)
    s1 = obs.span("x", "step")
    s2 = obs.span("y", "comm")
    assert s1 is s2  # the one shared nullcontext: no per-call allocation
    import timeit

    t_obs = timeit.timeit(lambda: obs.span("x", "step"), number=10_000)
    assert t_obs < 0.5  # ~50 us/call ceiling: catches accidental work only


def test_suppress_blocks_recording(obs_on):
    with obs.suppress():
        assert not obs.enabled()
        obs.record_collective("ghost", payload_bytes=1, wire_bytes=1,
                              chunks=1, method="m")
        obs.observe_timer("ghost", 1.0)
        with obs.span("ghost", "step"):
            pass
    assert obs.enabled()
    assert obs.REGISTRY.snapshot() == []
    assert obs.tracing.events() == []


def test_autotune_sweep_traffic_is_suppressed(obs_on, tmp_path):
    """Measurement thunks re-enter instrumented entry points hundreds of
    times; none of that may count as real comm traffic (only the
    autotuner's own search metrics land)."""
    from triton_distributed_tpu.tune.autotuner import Autotuner

    def make_thunk(cand):
        def thunk():
            # stands in for an instrumented comm entry point the sweep
            # would re-invoke (e.g. all_gather in the ag_method sweep)
            obs.record_collective("all_gather", payload_bytes=1024,
                                  wire_bytes=1024, chunks=1, method=cand)
            with obs.span("all_gather", "comm"):
                return jnp.zeros((8,))
        return thunk

    t = Autotuner(path=str(tmp_path / "cache.json"))
    t.tune("sweep_op", ("k",), ["a", "b"], make_thunk, iters=1)
    rows = obs.REGISTRY.snapshot()
    assert not any(r["name"].startswith("comm_") for r in rows), rows
    assert not any(e.get("cat") == "comm" for e in obs.tracing.events())
    assert obs.REGISTRY.counter("autotune_searches", name="sweep_op").value == 1


def test_prometheus_large_counter_exact():
    """Large byte counters must survive the exposition exactly (%g's 6
    significant digits silently truncated them)."""
    r = Registry()
    r.counter("comm_payload_bytes", op="ag").inc(123_456_789)
    r.gauge("big").set(987_654_321.0)
    got = obs.parse_prometheus(obs.to_prometheus(r))
    assert got['comm_payload_bytes_total{op="ag"}'] == 123_456_789.0
    assert got["big"] == 987_654_321.0
