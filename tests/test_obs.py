"""Observability subsystem (ISSUE 1): registry semantics, histogram
bucketing, JSONL/Prometheus round trips, span tracing + merged-trace
overlap report, instrumentation hooks, and the ``obs_report --selftest``
CLI."""

import json
import os
import subprocess
import sys
import threading
import types

import jax
import jax.numpy as jnp
import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu.obs import report
from triton_distributed_tpu.obs.registry import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def obs_on():
    """Enabled obs with a clean registry/trace buffer/serve-stats
    collector, restored after."""
    prev = obs.enabled()
    obs.enable(True)
    obs.REGISTRY.reset()
    obs.tracing.clear()
    obs.serve_stats.STATS.reset()
    yield obs
    obs.REGISTRY.reset()
    obs.tracing.clear()
    obs.serve_stats.STATS.reset()
    obs.enable(prev)


# ---------------------------------------------------------------------------
# registry semantics


def test_counter_gauge_semantics():
    r = Registry()
    c = r.counter("reqs", op="ag")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    # get-or-create: same identity for same (name, labels)
    assert r.counter("reqs", op="ag") is c
    # distinct labels -> distinct series
    assert r.counter("reqs", op="rs") is not c
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("occ")
    g.set(0.5)
    g.add(0.25)
    assert g.value == 0.75


def test_histogram_bucketing_and_quantiles():
    r = Registry()
    h = r.histogram("lat_ms", (1.0, 10.0, 100.0))
    for v in (0.5, 0.9, 5.0, 50.0, 500.0):
        h.observe(v)
    # cumulative bucket counts: <=1: 2, <=10: 3, <=100: 4, +Inf: 5
    row = h.row()
    assert row["counts"] == [2, 3, 4]
    assert row["count"] == 5
    assert row["sum"] == pytest.approx(556.4)
    assert row["min"] == 0.5 and row["max"] == 500.0
    assert h.quantile(0.5) == 10.0      # 3rd of 5 lands in the <=10 bucket
    assert h.quantile(1.0) == 500.0     # +Inf bucket reports observed max
    with pytest.raises(ValueError):
        r.histogram("bad", (3.0, 1.0))


def test_registry_thread_safety():
    r = Registry()
    def work():
        for _ in range(1000):
            r.counter("n").inc()
            r.histogram("h", (1.0,)).observe(0.5)
    ts = [threading.Thread(target=work) for _ in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert r.counter("n").value == 4000
    assert r.histogram("h", (1.0,)).count == 4000


def test_snapshot_sorted_and_reset():
    r = Registry()
    r.counter("b").inc()
    r.counter("a", x="2").inc()
    r.counter("a", x="1").inc()
    names = [(row["name"], row["labels"]) for row in r.snapshot()]
    assert names == [("a", {"x": "1"}), ("a", {"x": "2"}), ("b", {})]
    r.reset()
    assert r.snapshot() == []


# ---------------------------------------------------------------------------
# exporters


def _populate(r: Registry):
    r.counter("comm_calls", op="ag", method="ring_1d").inc(3)
    r.gauge("tokens_per_s").set(123.5)
    h = r.histogram("lat_ms", (1.0, 10.0))
    h.observe(0.5)
    h.observe(20.0)


def test_jsonl_round_trip(tmp_path):
    r = Registry()
    _populate(r)
    p = str(tmp_path / "m.jsonl")
    n = obs.write_jsonl(r, p, extra={"run": "t1"})
    assert n == 3
    obs.write_jsonl(r, p)  # append a second snapshot
    rows = obs.read_jsonl(p)
    assert len(rows) == 6
    first = {row["name"]: row for row in rows[:3]}
    assert first["comm_calls"]["value"] == 3
    assert first["comm_calls"]["labels"] == {"op": "ag", "method": "ring_1d"}
    assert first["comm_calls"]["run"] == "t1"
    assert first["lat_ms"]["counts"] == [1, 1]
    assert first["lat_ms"]["count"] == 2
    # one append shares one ts; the second append has a later ts
    assert len({row["ts"] for row in rows[:3]}) == 1
    assert rows[3]["ts"] >= rows[0]["ts"]


def test_prometheus_round_trip():
    r = Registry()
    _populate(r)
    text = obs.to_prometheus(r)
    assert "# TYPE comm_calls_total counter" in text
    got = obs.parse_prometheus(text)
    assert got['comm_calls_total{method="ring_1d",op="ag"}'] == 3.0
    assert got["tokens_per_s"] == 123.5
    assert got['lat_ms_bucket{le="1"}'] == 1.0
    assert got['lat_ms_bucket{le="10"}'] == 1.0
    assert got['lat_ms_bucket{le="+Inf"}'] == 2.0
    assert got["lat_ms_count"] == 2.0
    assert got["lat_ms_sum"] == pytest.approx(20.5)


def test_summary_table():
    r = Registry()
    _populate(r)
    t = obs.summary_table(r)
    assert "comm_calls" in t and "ring_1d" in t and "lat_ms" in t
    assert obs.summary_table(Registry()).startswith("(no metrics")


# ---------------------------------------------------------------------------
# enable gating


def test_disabled_is_noop():
    obs.enable(False)
    obs.REGISTRY.reset()
    obs.tracing.clear()
    try:
        obs.record_collective("ag", payload_bytes=1, wire_bytes=1, chunks=1,
                              method="m")
        obs.observe_timer("t", 1.0)
        with obs.span("s", "step"):
            pass
        assert obs.REGISTRY.snapshot() == []
        assert obs.tracing.events() == []
    finally:
        obs.enable(None)  # restore the env-derived default


def test_env_flag(monkeypatch):
    monkeypatch.setenv("TDT_OBS", "1")
    assert obs.enable(None) is True
    monkeypatch.setenv("TDT_OBS", "0")
    assert obs.enable(None) is False
    monkeypatch.delenv("TDT_OBS", raising=False)
    obs.enable(None)


# ---------------------------------------------------------------------------
# tracing + overlap report


def test_span_records_chrome_events(obs_on, tmp_path):
    with obs.span("decode_step", "step", idx=0):
        with obs.span("mlp", "compute"):
            pass
    evs = obs.tracing.events()
    assert [e["name"] for e in evs] == ["mlp", "decode_step"]  # exit order
    step = evs[1]
    assert step["ph"] == "X" and step["cat"] == "step"
    assert step["args"] == {"idx": 0}
    p = obs.tracing.export(str(tmp_path / "t.json"), clear_buffer=True)
    assert obs.tracing.events() == []
    trace = json.load(open(p))
    assert list(trace.keys()) == ["displayTimeUnit", "traceEvents"]
    assert len(trace["traceEvents"]) == 2


def test_overlap_report_two_rank_merge(obs_on, tmp_path):
    """Two per-process span exports merged into one timeline produce the
    per-step overlap table (the 2-process decode workflow, simulated by
    exporting the buffer twice and merging under two rank offsets)."""
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    with obs.span("decode_step", "step"):
        with obs.span("mlp", "compute"):
            with obs.span("all_gather", "comm"):
                pass  # comm fully inside compute -> overlap 1.0
    r0 = obs.tracing.export(str(tmp_path / "r0.json"), clear_buffer=True)
    with obs.span("decode_step", "step"):
        with obs.span("all_reduce", "comm"):
            pass  # comm with no compute -> overlap 0.0
    r1 = obs.tracing.export(str(tmp_path / "r1.json"), clear_buffer=True)

    merged = str(tmp_path / "merged.json")
    merge_traces([r0, r1], [0, 1], merged)
    rows = report.overlap_report(report.load_trace(merged))
    assert [r["rank"] for r in rows] == [0, 1]
    assert rows[0]["overlap"] == pytest.approx(1.0)
    assert rows[1]["overlap"] == pytest.approx(0.0)
    agg = report.aggregate(rows)
    assert agg["steps_with_comm"] == 2
    assert agg["mean_overlap"] == pytest.approx(0.5)
    table = report.format_report(rows)
    assert "overlap" in table and "mean overlap: 0.500" in table


def test_obs_report_cli_selftest():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--selftest"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    assert "selftest OK" in proc.stdout
    assert "decode_step" in proc.stdout


def test_obs_report_cli_on_files(obs_on, tmp_path):
    with obs.span("decode_step", "step"):
        with obs.span("all_gather", "comm"):
            pass
    r0 = obs.tracing.export(str(tmp_path / "r0.json"), clear_buffer=True)
    out_json = str(tmp_path / "rep.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         r0, "--json", out_json],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.load(open(out_json))
    assert rep["aggregate"]["steps"] == 1
    assert rep["rows"][0]["overlap"] == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# instrumentation hooks


def test_timer_and_perf_func_route_into_registry(obs_on, capsys):
    from triton_distributed_tpu.core.utils import perf_func, timer

    with timer("unit_block"):
        pass
    assert "unit_block" in capsys.readouterr().out  # print behavior kept
    _, ms = perf_func(lambda: jnp.zeros((8,)), iters=1, warmup_iters=1,
                      name="zeros")
    assert ms > 0
    h = obs.REGISTRY.histogram("timer_ms", name="unit_block")
    assert h.count == 1
    h2 = obs.REGISTRY.histogram("timer_ms", name="perf_func/zeros")
    assert h2.count == 1 and h2.sum == pytest.approx(ms)


def test_record_collective_metrics(obs_on):
    obs.record_collective("all_gather", payload_bytes=1024, wire_bytes=7168,
                          chunks=7, method="ring_1d")
    obs.record_collective("all_gather", payload_bytes=1024, wire_bytes=7168,
                          chunks=7, method="ring_1d")
    c = obs.REGISTRY.counter("comm_calls", op="all_gather", method="ring_1d")
    assert c.value == 2
    assert obs.REGISTRY.counter("comm_wire_bytes", op="all_gather",
                                method="ring_1d").value == 2 * 7168
    assert obs.REGISTRY.histogram("comm_payload_bytes_hist",
                                  op="all_gather").count == 2


def test_all_gather_entry_instrumented(obs_on, mesh8):
    """The eager all_gather entry records bytes/chunks/method and a comm
    span before dispatching the kernel."""
    from triton_distributed_tpu.comm import AllGatherMethod, all_gather
    from triton_distributed_tpu.core.mesh import TP_AXIS, shard
    from triton_distributed_tpu.core.utils import rand_tensor

    x = rand_tensor((16, 128), jnp.float32)
    xs = shard(mesh8, x, TP_AXIS)
    try:
        all_gather(xs, mesh8, TP_AXIS, method=AllGatherMethod.RING_1D)
    except AttributeError:
        # the kernel layer needs newer jax APIs (shard_map /
        # pltpu.CompilerParams); the instrumentation at the entry point
        # must still have recorded before dispatch, which is what the
        # asserts below check either way
        pass
    shard_bytes = (16 // 8) * 128 * 4
    assert obs.REGISTRY.counter("comm_calls", op="all_gather",
                                method="ring_1d").value == 1
    assert obs.REGISTRY.counter("comm_payload_bytes", op="all_gather",
                                method="ring_1d").value == shard_bytes
    assert obs.REGISTRY.counter("comm_wire_bytes", op="all_gather",
                                method="ring_1d").value == shard_bytes * 7
    assert obs.REGISTRY.counter("comm_chunks", op="all_gather",
                                method="ring_1d").value == 7


def test_autotuner_records_search_metrics(obs_on, tmp_path):
    from triton_distributed_tpu.tune.autotuner import Autotuner

    t = Autotuner(path=str(tmp_path / "cache.json"))
    f1 = jax.jit(lambda: jnp.zeros((32, 32)) + 1)
    f2 = jax.jit(lambda: jnp.zeros((32, 32)) + 2)
    mk = lambda c: f1 if c == "a" else f2  # noqa: E731
    t.tune("unit_op", ("k",), ["a", "b"], mk, iters=1)
    t.tune("unit_op", ("k",), ["a", "b"], mk, iters=1)  # mem-cache hit
    r = obs.REGISTRY
    assert r.counter("autotune_searches", name="unit_op").value == 1
    assert r.counter("autotune_candidates_tried", name="unit_op").value == 2
    assert r.counter("autotune_cache_hits", name="unit_op",
                     source="mem").value == 1
    assert r.gauge("autotune_last_search_s", name="unit_op").value > 0
    assert r.histogram("autotune_winner_ms", name="unit_op").count == 1
    # the sweep also dropped a timeline marker
    assert any(e["name"] == "autotune" for e in obs.tracing.events())


def test_engine_serve_metrics_recorded(obs_on):
    """The serve-loop recorder lands latency histograms + occupancy
    gauges (exercised directly; the full engine needs the TPU-interpret
    stack)."""
    from triton_distributed_tpu.models.engine import Engine

    eng = types.SimpleNamespace(
        batch=2,
        model=types.SimpleNamespace(
            config=types.SimpleNamespace(max_length=64)),
    )
    stats = {"prefill_ms": 12.0, "decode_ms_per_token": 3.0,
             "decode_tokens_per_s": 666.0}
    Engine._record_serve_metrics(eng, 8, 16, stats)
    r = obs.REGISTRY
    assert r.histogram("engine_prefill_ms").count == 1
    assert r.histogram("engine_decode_ms_per_token").sum == pytest.approx(3.0)
    assert r.gauge("engine_decode_tokens_per_s").value == 666.0
    assert r.counter("engine_tokens_generated").value == 2 * 16
    assert r.gauge("kv_cache_seq_occupancy").value == pytest.approx(24 / 64)


def test_disabled_overhead_smoke(obs_on):
    """The disabled fast path must stay allocation-free and near-free:
    span() returns the shared null context and record_collective returns
    before touching the registry (the < 1% bench.py acceptance bar rides
    on this shape, not on a timing assert that would flake in CI)."""
    obs.enable(False)
    s1 = obs.span("x", "step")
    s2 = obs.span("y", "comm")
    assert s1 is s2  # the one shared nullcontext: no per-call allocation
    import timeit

    t_obs = timeit.timeit(lambda: obs.span("x", "step"), number=10_000)
    assert t_obs < 0.5  # ~50 us/call ceiling: catches accidental work only


def test_suppress_blocks_recording(obs_on):
    with obs.suppress():
        assert not obs.enabled()
        obs.record_collective("ghost", payload_bytes=1, wire_bytes=1,
                              chunks=1, method="m")
        obs.observe_timer("ghost", 1.0)
        with obs.span("ghost", "step"):
            pass
    assert obs.enabled()
    assert obs.REGISTRY.snapshot() == []
    assert obs.tracing.events() == []


def test_autotune_sweep_traffic_is_suppressed(obs_on, tmp_path):
    """Measurement thunks re-enter instrumented entry points hundreds of
    times; none of that may count as real comm traffic (only the
    autotuner's own search metrics land)."""
    from triton_distributed_tpu.tune.autotuner import Autotuner

    def make_thunk(cand):
        def thunk():
            # stands in for an instrumented comm entry point the sweep
            # would re-invoke (e.g. all_gather in the ag_method sweep)
            obs.record_collective("all_gather", payload_bytes=1024,
                                  wire_bytes=1024, chunks=1, method=cand)
            with obs.span("all_gather", "comm"):
                return jnp.zeros((8,))
        return thunk

    t = Autotuner(path=str(tmp_path / "cache.json"))
    t.tune("sweep_op", ("k",), ["a", "b"], make_thunk, iters=1)
    rows = obs.REGISTRY.snapshot()
    assert not any(r["name"].startswith("comm_") for r in rows), rows
    assert not any(e.get("cat") == "comm" for e in obs.tracing.events())
    assert obs.REGISTRY.counter("autotune_searches", name="sweep_op").value == 1


def test_prometheus_large_counter_exact():
    """Large byte counters must survive the exposition exactly (%g's 6
    significant digits silently truncated them)."""
    r = Registry()
    r.counter("comm_payload_bytes", op="ag").inc(123_456_789)
    r.gauge("big").set(987_654_321.0)
    got = obs.parse_prometheus(obs.to_prometheus(r))
    assert got['comm_payload_bytes_total{op="ag"}'] == 123_456_789.0
    assert got["big"] == 987_654_321.0


# ---------------------------------------------------------------------------
# flight recorder (ISSUE 4): primitive-level capture, ring retention,
# timeout dumps


from triton_distributed_tpu.obs import costs, flight, timeline  # noqa: E402


@pytest.fixture()
def flight_on():
    """Enabled flight ring, cleared before and after, state restored."""
    prev = flight.enabled()
    flight.enable(True)
    flight.clear()
    yield flight
    flight.clear()
    flight.enable(prev)


def test_flight_disabled_is_noop():
    flight.enable(False)
    try:
        flight.clear()
        assert flight.active() is None
        flight.mark_step(1)
        flight.mark_collective("all_gather", payload_bytes=8, ranks=2)
        assert flight.recent() == []
    finally:
        flight.enable(None)


def test_flight_capture_records_primitive_stream():
    """A recorded registry case yields one per-rank stream whose events
    carry the (semaphore, chunk, peer) identity of every primitive —
    the raw material of the timeline reconstruction."""
    name, streams = flight.record_family("allgather", 2, variant="ring_1d")
    assert name == "allgather/ring_1d" and len(streams) == 2
    for rank, evs in enumerate(streams):
        assert evs, "empty stream"
        assert all(e.rank == rank for e in evs)
        kinds = [e.kind for e in evs]
        assert "barrier" in kinds and "remote_copy" in kinds \
            and "wait_recv" in kinds
    copies = [e for e in streams[0] if e.kind == "remote_copy"]
    assert copies[0].sem and copies[0].sem.startswith("recv_sems")
    assert copies[0].sem2 is not None          # send side kept for drains
    assert copies[0].chunk and copies[0].chunk.startswith("out[")
    assert copies[0].peer == 1                 # rank 0's right neighbor
    assert copies[0].elems > 0
    # JSON round trip preserves the stream exactly
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".json") as f:
        flight.save_streams(name, streams, f.name)
        name2, streams2 = flight.load_streams(f.name)
    assert name2 == name
    assert [[e.to_dict() for e in s] for s in streams2] == \
        [[e.to_dict() for e in s] for s in streams]


def test_flight_ring_step_retention(flight_on, monkeypatch):
    """The global ring keeps the last TDT_FLIGHT_STEPS serving steps:
    events tagged with older steps are pruned at each step mark."""
    monkeypatch.setenv("TDT_FLIGHT_STEPS", "2")
    for step in range(1, 6):
        flight.mark_step(step)
        flight.mark_collective("all_reduce", payload_bytes=step, ranks=2)
    steps = {e.step for e in flight.recent()}
    assert steps == {4, 5}, steps


def test_flight_ring_captures_live_primitives(flight_on):
    """With the ring armed (no thread capture), primitives report into
    the global ring BEFORE dispatching — the trace-time stream a live
    timeout dump shows (the pltpu dispatch itself needs a kernel
    context and is allowed to fail here)."""
    from triton_distributed_tpu.lang import primitives as dl

    try:
        dl.notify(object(), None, inc=1)
    except Exception:
        pass   # no kernel context: only the flight hook's view matters
    kinds = [e.kind for e in flight.recent()]
    assert kinds == ["notify"]


def test_flight_ring_honors_obs_suppress(flight_on):
    """Measurement sweeps (autotune candidates, serve warmup) run under
    obs.suppress(); the flight ring must stay silent there — a timeout
    dump shows the serving protocol's history, not hundreds of sweep
    markers."""
    with obs.suppress():
        assert flight.active() is None
        flight.mark_step(1)
        flight.mark_collective("all_gather", payload_bytes=8, ranks=2)
    assert flight.recent() == []
    flight.mark_collective("all_gather", payload_bytes=8, ranks=2)
    assert len(flight.recent()) == 1
    # an explicitly-installed capture is the record harness, not live
    # traffic: it keeps recording under suppression
    with obs.suppress():
        with flight.capture(0) as cap:
            assert flight.active() is cap


def test_record_family_rejects_unknown_variant():
    with pytest.raises(ValueError, match="unidr"):
        flight.record_family("ag_gemm", 2, variant="unidr")


def test_watchdog_timeout_attaches_flight_events(flight_on):
    """A CollectiveTimeoutError raised while the ring is armed carries
    the recent flight history in its diagnosis (the acceptance shape:
    not just 'it timed out' but 'this is what the protocol was doing')."""
    import time

    from triton_distributed_tpu import resilience

    flight.mark_step(1)
    flight.mark_collective("all_gather", payload_bytes=64, ranks=4)
    with pytest.raises(resilience.CollectiveTimeoutError) as ei:
        resilience.call_with_deadline(
            "all_gather", lambda: time.sleep(1.0), 20.0)
    diag = ei.value.diagnosis
    assert diag is not None and diag.flight
    assert any("all_gather" in line for line in diag.flight)
    assert "recent flight events" in str(ei.value)


def test_engine_mark_failed_dumps_flight(flight_on):
    """Failed-step isolation dumps the ring: health() and the error note
    carry the last flight lines."""
    from triton_distributed_tpu.models.engine import Engine

    eng = types.SimpleNamespace(
        _failed_requests=0, _last_failure=None, _last_flight=(),
        _abandoned_threads=set(), _fence_lock=threading.Lock(),
        cache=None,
    )
    flight.mark_step(1)
    flight.mark_collective("gemm_rs", payload_bytes=128, ranks=2)
    err = RuntimeError("boom")
    Engine._mark_failed(eng, err)
    assert eng._failed_requests == 1
    assert any("gemm_rs" in line for line in eng._last_flight)
    if hasattr(err, "__notes__"):
        assert any("flight recorder" in n for n in err.__notes__)


# ---------------------------------------------------------------------------
# timeline reconstruction (ISSUE 4): golden cross-rank merge, clock
# alignment, truncated-ring recovery


def test_timeline_golden_4rank_ag_gemm():
    """Golden cross-rank merge of a recorded 4-rank AG-GEMM trace
    (deterministic record mode): the reconstruction completes, is
    exactly symmetric across the ring, attributes every recv stall to a
    named (semaphore, chunk, peer) triple with the correct ring
    topology, and two recordings reconstruct identically."""
    name, streams = flight.record_family("ag_gemm", 4, variant="unidir")
    tl = timeline.reconstruct(streams, kernel=name)
    assert tl.n == 4 and not tl.stalled
    assert tl.critical_us > 0 and 0 < tl.pct_sol <= 1.0
    assert tl.skew_us == pytest.approx(0.0, abs=1e-9)
    # symmetric ring: identical per-rank totals
    for field in ("compute_us", "wire_us", "exposed_us", "finish_us"):
        vals = [getattr(r, field) for r in tl.rows]
        assert max(vals) - min(vals) < 1e-9, (field, vals)
    recv_waits = [w for w in tl.waits if w.kind == "wait_recv"
                  and w.sem.startswith("recv_sems")]
    # 3 forwarded chunks per rank on the unidirectional ring
    assert len(recv_waits) == 12
    for w in recv_waits:
        assert w.sem and w.chunk and w.chunk.startswith("ag[")
        # chunks always arrive from the LEFT ring neighbor
        assert w.source == (w.rank - 1) % 4
        assert w.exposed_us > 0
    assert timeline.check_balanced(tl) == []
    # deterministic: a second recording reconstructs identically
    _, streams2 = flight.record_family("ag_gemm", 4, variant="unidir")
    tl2 = timeline.reconstruct(streams2, kernel=name)
    assert tl2.critical_us == pytest.approx(tl.critical_us)
    assert [dataclasses_asdict(w) for w in tl2.waits] == \
        [dataclasses_asdict(w) for w in tl.waits]


def dataclasses_asdict(w):
    import dataclasses

    return dataclasses.asdict(w)


def test_timeline_chrome_export_has_flow_arrows():
    name, streams = flight.record_family("allgather", 2, variant="ring_1d")
    tl = timeline.reconstruct(streams, kernel=name)
    evs = timeline.to_chrome(tl)
    phases = {e["ph"] for e in evs}
    assert "X" in phases and "s" in phases and "f" in phases
    starts = [e for e in evs if e["ph"] == "s"]
    ends = [e for e in evs if e["ph"] == "f"]
    assert {e["id"] for e in starts} == {e["id"] for e in ends}
    assert all(e["cat"] == "stall" for e in starts)


def test_timeline_clock_alignment():
    """align_clocks recovers known per-rank clock offsets from the
    hub-barrier events, and apply_offsets + trace-merge ts_offsets put
    the lanes on one clock."""
    name, streams = flight.record_family("allgather", 2, variant="ring_1d")
    # ranks are recorded sequentially, so their clocks already differ;
    # an EXTRA known skew must shift the recovered offset by exactly it
    base = timeline.align_clocks(streams)
    skewed = timeline.apply_offsets(streams, [0.0, 1234.5])
    offs = timeline.align_clocks(skewed)
    assert offs[0] == pytest.approx(0.0)
    assert offs[1] == pytest.approx(base[1] - 1234.5)
    realigned = timeline.apply_offsets(skewed, offs)
    b0 = [e.t_us for e in realigned[0] if e.kind == "barrier"]
    b1 = [e.t_us for e in realigned[1] if e.kind == "barrier"]
    assert b0 == pytest.approx(b1)


def test_timeline_truncated_ring_recovery():
    """A partially-retained ring buffer (oldest events dropped) must
    reconstruct as far as credits allow and name the unreplayable tail
    instead of raising — the dump-at-failure path cannot crash."""
    name, streams = flight.record_family("ag_gemm", 4, variant="unidir")
    streams[2] = streams[2][: len(streams[2]) // 3]
    tl = timeline.reconstruct(streams, kernel=name)
    assert tl.stalled
    assert tl.pending and any("rank" in p and "need" in p
                              for p in tl.pending)
    # the table still renders, flagged as partial
    table = timeline.format_table(tl)
    assert "PARTIAL RECONSTRUCTION" in table


def test_trace_merge_ts_offsets(obs_on, tmp_path):
    """merge_traces(ts_offsets=...) shifts each input's timestamps (the
    clock-alignment hook for per-process span exports)."""
    from triton_distributed_tpu.tools.trace_merge import merge_traces

    with obs.span("decode_step", "step"):
        pass
    r0 = obs.tracing.export(str(tmp_path / "r0.json"), clear_buffer=True)
    with obs.span("decode_step", "step"):
        pass
    r1 = obs.tracing.export(str(tmp_path / "r1.json"), clear_buffer=True)
    plain = report.load_trace(merge_traces(
        [r0, r1], [0, 1], str(tmp_path / "plain.json")))
    shifted = report.load_trace(merge_traces(
        [r0, r1], [0, 1], str(tmp_path / "shifted.json"),
        ts_offsets=[0.0, 500.0]))
    assert shifted[0]["ts"] == plain[0]["ts"]
    assert shifted[1]["ts"] == plain[1]["ts"] + 500.0


# ---------------------------------------------------------------------------
# kernel cost attribution (ISSUE 4): one flop/byte source


def test_costs_shared_with_perf_model():
    """tools.perf_model reads its GEMM roofline from obs.costs — the
    watchdog deadline and the kernel cost_estimate can never disagree."""
    from triton_distributed_tpu.tools import perf_model

    c = costs.matmul(512, 256, 128, jnp.bfloat16)
    assert c.flops == 2 * 512 * 256 * 128
    assert c.bytes_accessed == 2 * (512 * 128 + 128 * 256 + 512 * 256)
    assert perf_model.gemm_sol_ms(512, 256, 128, jnp.bfloat16) == \
        pytest.approx(costs.sol_ms(c))
    # the fused families all resolve through the shared registry
    for fam in ("ag_gemm", "gemm_rs", "gemm_ar"):
        ms = perf_model.fused_sol_ms(
            fam, m_loc=128, **({"k": 256} if fam == "ag_gemm"
                               else {"k_loc": 256}),
            **({"n_loc": 128} if fam == "ag_gemm" else {"n_dim": 128}),
            num_ranks=4, dtype=jnp.bfloat16)
        assert ms > 0


def test_costs_pallas_estimate_values():
    """pallas_cost carries the exact counts into pl.CostEstimate (when
    this jax has it)."""
    from jax.experimental import pallas as pl

    c = costs.flash_attention(1, 2, 64, 64, 32, True, jnp.bfloat16)
    est = costs.pallas_cost(c)
    if not hasattr(pl, "CostEstimate"):
        assert est is None
        return
    assert est.flops == c.flops
    assert est.bytes_accessed == c.bytes_accessed
    assert est.transcendentals == c.transcendentals
    assert c.transcendentals == 1 * 2 * 64 * 64 // 2   # causal halves


def test_fused_builders_carry_cost_estimates():
    """Every fused collective kernel builder passes an obs.costs-sourced
    cost_estimate to pallas_call (acceptance criterion).  Checked
    statically — building a kernel needs newer jax than this container
    may have."""
    import importlib
    import inspect

    # importlib on purpose: the ops package re-exports functions over
    # the submodule names, so ``import ...ops.ag_gemm as m`` binds the
    # FUNCTION on 3.7+ import semantics
    a2a_mod = importlib.import_module("triton_distributed_tpu.comm.all_to_all")
    ag_mod = importlib.import_module("triton_distributed_tpu.ops.ag_gemm")
    attn_mod = importlib.import_module("triton_distributed_tpu.ops.attention")
    fd_mod = importlib.import_module("triton_distributed_tpu.ops.fused_decode")
    gar_mod = importlib.import_module("triton_distributed_tpu.ops.gemm_ar")
    grs_mod = importlib.import_module("triton_distributed_tpu.ops.gemm_rs")
    mm_mod = importlib.import_module("triton_distributed_tpu.ops.matmul")

    for mod, builders in (
        (ag_mod, ["_build_ag_gemm"]),
        (grs_mod, ["_build_gemm_rs"]),
        (gar_mod, ["_build_gemm_ar"]),
        (mm_mod, ["_build_matmul"]),
        (a2a_mod, ["_make_push_call"]),
        (attn_mod, ["_build_flash_attention", "_build_attn_chunk",
                    "_build_decode", "_build_decode_fused",
                    "_build_paged_decode"]),
        (fd_mod, ["_build_fused_attn", "_build_fused_mlp_ar"]),
    ):
        for name in builders:
            fn = getattr(mod, name)
            fn = getattr(fn, "__wrapped__", fn)   # unwrap lru_cache
            src = inspect.getsource(fn)
            assert "cost_estimate=costs.pallas_cost(" in src, \
                f"{mod.__name__}.{name} lacks an obs.costs cost_estimate"


# ---------------------------------------------------------------------------
# CLI smokes: obs_report --timeline and tdt_lint --timeline (tier-1 gate)


def test_obs_report_cli_timeline(tmp_path):
    out_json = str(tmp_path / "tl.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         "--timeline", "ag_gemm", "--ranks", "4", "--variant", "unidir",
         "--json", out_json],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    for col in ("compute_us", "wire_us", "exposed_us", "finish_us",
                "pct_sol", "wait attribution"):
        assert col in proc.stdout, (col, proc.stdout)
    rep = json.load(open(out_json))
    assert rep["ranks"] == 4 and not rep["stalled"]
    assert rep["waits"] and all(
        w["sem"] and w["source"] is not None for w in rep["waits"]
        if w["kind"] == "wait_recv")


def test_tdt_lint_timeline_smoke():
    """The headless flight-timeline regression gate: record a 2-rank AG,
    reconstruct, assert balanced attribution (tier-1 wiring for the
    ISSUE 4 CI satellite)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--timeline"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "timeline OK" in proc.stdout
    assert "allgather/ring_1d" in proc.stdout


# ---------------------------------------------------------------------------
# live telemetry plane (ISSUE 5): quantile sketches, windowed rates,
# HTTP endpoints


def test_sketch_quantile_error_bound():
    """DDSketch-style log buckets guarantee RELATIVE quantile error <=
    alpha — pinned against a heavy-tailed known distribution."""
    import random

    from triton_distributed_tpu.obs.serve_stats import QuantileSketch

    rng = random.Random(0)
    values = [rng.lognormvariate(1.0, 1.5) for _ in range(20_000)]
    sk = QuantileSketch(alpha=0.01)
    for v in values:
        sk.observe(v)
    values.sort()
    for q in (0.5, 0.9, 0.99):
        true = values[int(q * (len(values) - 1))]
        est = sk.quantile(q)
        assert abs(est - true) / true <= 0.0101, (q, est, true)
    assert sk.count == 20_000
    assert sk.quantile(0.0) <= sk.quantile(1.0) == pytest.approx(
        values[-1])


def test_sketch_zero_and_empty_and_merge():
    from triton_distributed_tpu.obs.serve_stats import QuantileSketch

    sk = QuantileSketch()
    assert sk.quantile(0.5) == 0.0          # empty
    sk.observe(0.0)
    sk.observe(-1.0)
    assert sk.quantile(0.5) <= 0.0          # zero bucket dominates
    a, b = QuantileSketch(), QuantileSketch()
    for v in (1.0, 2.0, 4.0):
        a.observe(v)
    for v in (8.0, 16.0):
        b.observe(v)
    a.merge(b)
    assert a.count == 5
    assert a.quantile(1.0) == pytest.approx(16.0)
    with pytest.raises(ValueError):
        a.merge(QuantileSketch(alpha=0.05))


def test_windowed_rate_slides():
    from triton_distributed_tpu.obs.serve_stats import WindowedRate

    r = WindowedRate(window_s=10.0)
    r.add(5.0, now=100.2)
    r.add(5.0, now=101.7)
    assert r.rate(now=102.0) == pytest.approx(1.0)   # 10 units / 10 s
    assert r.rate(now=120.0) == 0.0                  # burst decayed out
    assert r.total == 10.0                           # lifetime counter


def test_serve_stats_request_flow(obs_on):
    """The collector's request lifecycle: queue depth, latency sketches,
    windowed token rate, prometheus rendering."""
    st = obs.serve_stats.STATS
    st.request_begin()
    assert st.queue_depth == 1
    st.observe_request(prompt_len=8, gen_len=17,
                       stats={"prefill_ms": 10.0,
                              "decode_ms_per_token": 2.0})
    st.request_end()
    assert st.queue_depth == 0
    snap = st.snapshot()
    assert snap["request_ms"]["count"] == 1
    # request = prefill + per-token * decode_steps = 10 + 2*16 = 42 ms
    assert snap["request_ms"]["quantiles"]["p50"] == pytest.approx(
        42.0, rel=0.02)
    assert snap["tokens_total"] == 17.0
    assert snap["requests_total"] == 1.0
    text = st.to_prometheus()
    assert 'serve_request_ms{quantile="0.5"}' in text
    assert "serve_queue_depth 0.0" in text
    assert "serve_request_ms_count 1" in text


def test_record_collective_feeds_wire_window(obs_on):
    obs.record_collective("all_gather", payload_bytes=1 << 20,
                          wire_bytes=3 << 20, chunks=3, method="ring")
    snap = obs.serve_stats.STATS.snapshot()
    assert snap["wire_bytes_per_s_window"]["all_gather"] > 0
    # suppressed traffic must not land in the live window either
    obs.serve_stats.STATS.reset()
    with obs.suppress():
        obs.record_collective("all_gather", payload_bytes=1, wire_bytes=1,
                              chunks=1, method="ring")
    assert obs.serve_stats.STATS.snapshot()["wire_bytes_per_s_window"] \
        == {}


def test_engine_serve_metrics_feed_serve_stats(obs_on):
    """The engine recorder feeds the live plane alongside the registry
    (same stub-engine harness as test_engine_serve_metrics_recorded)."""
    from triton_distributed_tpu.models.engine import Engine

    eng = types.SimpleNamespace(
        batch=2,
        model=types.SimpleNamespace(
            config=types.SimpleNamespace(max_length=64)),
    )
    stats = {"prefill_ms": 12.0, "decode_ms_per_token": 3.0,
             "decode_tokens_per_s": 666.0}
    Engine._record_serve_metrics(eng, 8, 16, stats)
    snap = obs.serve_stats.STATS.snapshot()
    assert snap["prefill_ms"]["count"] == 1
    assert snap["decode_ms_per_token"]["quantiles"]["p50"] == \
        pytest.approx(3.0, rel=0.02)
    # the token window carries the BATCH factor, matching the registry's
    # engine_tokens_generated accounting (2 sequences x 16 tokens)
    assert snap["tokens_total"] == 2 * 16
    assert snap["gauges"]["kv_cache_seq_occupancy"] == \
        pytest.approx(24 / 64)


def _get(url: str):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_telemetry_server_endpoints(obs_on):
    """Scrape /metrics, /healthz (incl. the 503-on-tripped-breaker
    contract), /debug/flight, /debug/timeline, and 404 handling against
    a live server."""
    from triton_distributed_tpu.obs import server as obs_server
    from triton_distributed_tpu.resilience import policy

    obs.serve_stats.STATS.observe_request(
        prompt_len=4, gen_len=8,
        stats={"prefill_ms": 5.0, "decode_ms_per_token": 1.0})
    obs.counter("comm_calls", op="ag", method="ring").inc()
    srv = obs_server.start(port=0)
    try:
        code, body = _get(srv.url + "/metrics")
        assert code == 200
        assert "comm_calls_total" in body          # registry exposition
        assert 'serve_request_ms{quantile="0.5"}' in body  # live plane
        code, body = _get(srv.url + "/healthz")
        assert code == 200
        snap = json.loads(body)
        assert snap["status"] == "ok" and snap["degraded_ops"] == []
        assert "serve_stats" not in snap           # no engine registered
        # a tripped breaker flips the load-balancer contract to 503
        policy.breaker("unit_op", threshold=1).record_failure()
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        snap = json.loads(body)
        assert snap["status"] == "degraded"
        assert snap["degraded_ops"] == ["unit_op"]
        code, body = _get(srv.url + "/debug/flight")
        assert code == 200
        assert "events" in json.loads(body)
        code, body = _get(srv.url + "/debug/timeline")
        assert code == 200
        assert "error" not in json.loads(body)
        code, body = _get(srv.url + "/nope")
        assert code == 404
        assert "/metrics" in body                  # endpoint listing
    finally:
        obs_server.stop()
        policy._reset_state_for_tests()


def test_telemetry_two_tier_healthz_aggregation(obs_on):
    """ISSUE 12 satellite: /healthz against a DisaggRouter aggregates
    BOTH schedulers — 503 while EITHER tier is saturated or any breaker
    is open, flipping back to 200 as each drains independently."""
    from triton_distributed_tpu import serve
    from triton_distributed_tpu.obs import server as obs_server
    from triton_distributed_tpu.resilience import policy

    def tier(prefill_only):
        return serve.Scheduler(
            serve.SimBackend(slots=3, page_size=4, pool_pages=5,
                             max_length=32),
            serve.SchedulerConfig(max_queue_depth=16,
                                  prefill_only=prefill_only))

    pre, dec = tier(True), tier(False)
    router = serve.DisaggRouter(pre, dec)
    srv = obs_server.start(port=0, engine=router)
    try:
        assert _get(srv.url + "/healthz")[0] == 200
        # saturate the PREFILL tier: queued work blocked on pages
        for _ in range(4):
            pre.submit(serve.Request(prompt=(1, 2, 3, 4),
                                     max_new_tokens=2))
        pre.step()
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        snap = json.loads(body)
        assert snap["status"] == "saturated"
        assert snap["saturated_tiers"] == ["prefill"]
        # saturate the DECODE tier too (colocated direct submits)
        for _ in range(4):
            dec.submit(serve.Request(prompt=(5, 6, 7, 8),
                                     max_new_tokens=2))
        dec.step()
        snap = json.loads(_get(srv.url + "/healthz")[1])
        assert set(snap["saturated_tiers"]) == {"prefill", "decode"}
        # drain the decode tier ALONE: still 503 — the prefill tier
        # holds the aggregate down independently
        for _ in range(300):
            if dec.step().idle:
                break
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        assert json.loads(body)["saturated_tiers"] == ["prefill"]
        # drain the rest through the router: flips back to 200
        router.run_until_idle(max_steps=2000)
        code, body = _get(srv.url + "/healthz")
        assert code == 200
        assert json.loads(body)["saturated_tiers"] == []
        # an open breaker ANYWHERE still answers 503 through the
        # aggregate (the resilience snapshot is the base layer)
        policy.breaker("unit_tier_op", threshold=1).record_failure()
        code, body = _get(srv.url + "/healthz")
        assert code == 503
        assert json.loads(body)["status"] == "degraded"
        # /debug/serve shows both tiers through the router's debug_state
        code, body = _get(srv.url + "/debug/serve")
        assert code == 200
        dump = json.loads(body)
        assert set(dump["scheduler"]["tiers"]) == {"prefill", "decode"}
    finally:
        obs_server.stop()
        policy._reset_state_for_tests()


def test_telemetry_server_env_gate_and_engine_release(monkeypatch):
    """TDT_OBS_HTTP unset -> maybe_start is a no-op (the PR-4-identical
    path); set -> the engine-registered server backs /healthz and
    Engine-owned release stops it."""
    from triton_distributed_tpu.obs import server as obs_server

    monkeypatch.delenv("TDT_OBS_HTTP", raising=False)
    assert obs_server.port_from_env() is None
    assert obs_server.maybe_start() is None
    assert obs_server.running() is None

    class _FakeEngine:
        def health(self):
            return {"status": "degraded", "engine": {"fake": True}}

    eng = _FakeEngine()
    monkeypatch.setenv("TDT_OBS_HTTP", "0")   # 0 = ephemeral port
    srv = obs_server.maybe_start(eng)
    try:
        assert srv is not None and obs_server.running() is srv
        code, body = _get(srv.url + "/healthz")
        assert code == 503                    # the ENGINE's health payload
        assert json.loads(body)["engine"]["fake"] is True
        # another engine's close() must not stop this engine's plane
        obs_server.release(object())
        assert obs_server.running() is srv
        obs_server.release(eng)
        assert obs_server.running() is None
    finally:
        obs_server.stop()


class _TinyServeModel:
    """A model-shaped stub so the REAL ``Engine`` (cache init, jitted
    prefill/decode, serve loop, telemetry, health) runs on any jax build
    — the full Qwen layers need Pallas/shard_map APIs this container's
    jax may lack, and those paths are capability-gated elsewhere."""

    def __init__(self, mesh, config):
        self.mesh = mesh
        self.axis = "tp"
        self.decode_mode = "psum"
        self.config = config

    def prefill(self, params, cache, ids, true_len=None):
        logits = jax.nn.one_hot(
            (ids + 1) % self.config.vocab, self.config.vocab, dtype=jnp.float32
        ) + params["w"]
        return logits, jax.tree.map(lambda x: x + 0, cache)

    def decode(self, params, cache, tok):
        logits = jax.nn.one_hot(
            (tok + 1) % self.config.vocab, self.config.vocab,
            dtype=jnp.float32) + params["w"]
        return logits, jax.tree.map(lambda x: x + 0, cache)


def test_telemetry_endpoints_during_live_decode(obs_on):
    """The acceptance shape: with the plane up, a SERVING engine answers
    /metrics, /healthz, and /debug/flight while a request is mid-decode
    — verified deterministically by scraping from inside the decode
    step (the serve loop is blocked in engine code at that instant; the
    daemon-threaded server answers concurrently)."""
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig
    from triton_distributed_tpu.obs import server as obs_server

    cfg = ModelConfig(
        num_layers=1, hidden=8, intermediate=16, num_heads=1,
        num_kv_heads=1, head_dim=8, vocab=32, max_length=32,
        dtype=jnp.float32,
    )
    model = _TinyServeModel(mesh_lib.tp_mesh(1), cfg)
    eng = Engine(model, {"w": jnp.zeros((), jnp.float32)}, batch=1)
    srv = obs_server.start(port=0, engine=eng)
    seen: dict = {}
    orig = eng.decode_step

    def hooked(tok):
        # obs.enabled() is False during the suppressed warmup: the scrape
        # below therefore happens inside the TIMED decode loop
        if obs.enabled() and not seen:
            seen["metrics"] = _get(srv.url + "/metrics")
            seen["healthz"] = _get(srv.url + "/healthz")
            seen["flight"] = _get(srv.url + "/debug/flight")
        return orig(tok)

    eng.decode_step = hooked
    try:
        ids = jnp.zeros((1, 4), jnp.int32)
        _, stats = eng.serve(ids, gen_len=6)
        assert seen, "decode loop never ran with telemetry enabled"
        code, body = seen["metrics"]
        assert code == 200 and "serve_queue_depth 1.0" in body
        code, body = seen["healthz"]
        assert code == 200
        snap = json.loads(body)
        assert snap["status"] == "ok"
        assert snap["serve_stats"]["queue_depth"] == 1
        assert seen["flight"][0] == 200
        # after the request: the latency sketches hold it
        code, body = _get(srv.url + "/metrics")
        assert code == 200 and "serve_request_ms_count 1" in body
        assert eng.health()["serve_stats"]["request_ms"]["count"] == 1
    finally:
        eng.close()                            # engine-owned stop
        assert obs_server.running() is None


# ---------------------------------------------------------------------------
# perf-trajectory regression sentinel (obs.history / bench_history CLI)


def _hist_round(tmp_path, rnd: int, lines: list[dict], *, local=False,
                envelope_tail=None):
    recs = "\n".join(json.dumps(r) for r in lines) + "\n"
    if local:
        (tmp_path / f"BENCH_LOCAL_r{rnd:02d}.jsonl").write_text(recs)
        if envelope_tail is not None:
            (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(
                json.dumps({"n": rnd, "rc": 0, "tail": envelope_tail}))
    else:
        (tmp_path / f"BENCH_r{rnd:02d}.json").write_text(recs)


def _toy(value, **kw):
    return {"metric": "toy_tflops", "value": value, "unit": "TFLOP/s",
            **kw}


def test_history_flags_three_round_monotonic_decline(tmp_path):
    from triton_distributed_tpu.obs import history

    for rnd, v in enumerate((100.0, 97.0, 90.0, 80.0), start=1):
        _hist_round(tmp_path, rnd, [_toy(v)])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    warns = history.all_warnings(trs)
    assert any("3-round monotonic decline" in w for w in warns), warns
    assert any("below" in w or "outside" in w for w in warns)
    # the same magnitudes RISING never warn (direction-aware)
    for p in tmp_path.glob("BENCH_r*.json"):
        p.unlink()
    for rnd, v in enumerate((80.0, 90.0, 97.0, 100.0), start=1):
        _hist_round(tmp_path, rnd, [_toy(v)])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    assert history.all_warnings(trs) == []


def test_history_lower_is_better_direction(tmp_path):
    """ms-unit metrics decline UPWARD: a rising latency trajectory warns,
    a falling one does not."""
    from triton_distributed_tpu.obs import history

    for rnd, v in enumerate((5.0, 5.5, 6.2, 7.0), start=1):
        _hist_round(tmp_path, rnd, [{
            "metric": "toy_step", "value": v, "unit": "ms/step (ar mode)",
        }])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    assert any("monotonic decline" in w
               for w in history.all_warnings(trs))


def test_history_handoff_metric_directions():
    """ISSUE 12 satellite: the trend sentinel classifies the handoff
    metrics — latency/retry growth is the regression, pages/s rides the
    throughput default."""
    from triton_distributed_tpu.obs import history

    assert history.direction_for("handoff_ms_p99", "ms") == "lower"
    assert history.direction_for("serve_disagg_ttft_ms_p99", "ms") \
        == "lower"
    assert history.direction_for("handoff_retries", "count") == "lower"
    assert history.direction_for("handoff_pages_per_s", "pages/s") \
        == "higher"
    # the rule is substring-shaped on purpose: any *_failures count is
    # failure pressure
    assert history.direction_for("engine_failed_requests", "count") \
        == "lower"


def test_history_handoff_retries_growth_warns(tmp_path):
    """Synthetic decline fixtures: retry GROWTH warns (lower-is-better
    count), pages/s decline warns (throughput), and the same retry
    trajectory falling never warns."""
    from triton_distributed_tpu.obs import history

    for rnd, (r, pps) in enumerate(
            ((2.0, 100.0), (4.0, 90.0), (6.0, 80.0), (8.0, 70.0)),
            start=1):
        _hist_round(tmp_path, rnd, [
            {"metric": "handoff_retries", "value": r, "unit": "count"},
            {"metric": "handoff_pages_per_s", "value": pps,
             "unit": "pages/s"},
        ])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    warns = history.all_warnings(trs)
    assert any("handoff_retries" in w and "decline" in w
               for w in warns), warns
    assert any("handoff_pages_per_s" in w and "decline" in w
               for w in warns), warns
    for p in tmp_path.glob("BENCH_r*.json"):
        p.unlink()
    for rnd, r in enumerate((8.0, 6.0, 4.0, 2.0), start=1):
        _hist_round(tmp_path, rnd, [
            {"metric": "handoff_retries", "value": r, "unit": "count"},
        ])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    assert history.all_warnings(trs) == []


def test_history_below_band_retry_reports_transient(tmp_path):
    from triton_distributed_tpu.obs import history

    values = (100.0, 102.0, 101.0)
    for rnd, v in enumerate(values, start=1):
        _hist_round(tmp_path, rnd, [_toy(v)])
    _hist_round(tmp_path, 4, [_toy(85.0, retry_value=101.0)])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    warns = history.all_warnings(trs)
    assert any("transient throttle" in w for w in warns), warns
    # without the passing retry the same draw is a regression finding
    _hist_round(tmp_path, 4, [_toy(85.0)])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    warns = history.all_warnings(trs)
    assert any("healthy band" in w for w in warns), warns
    # interpret-mode captures never enter the trajectory
    _hist_round(tmp_path, 4, [_toy(1.0, interpret=True)])
    trs = history.analyze(history.load_rounds(str(tmp_path)))
    assert [d.round for d in trs["toy_tflops"].draws] == [1, 2, 3]


def test_history_consistency_problems(tmp_path):
    from triton_distributed_tpu.obs import history

    # (a) local stream disagreeing with its same-round envelope
    _hist_round(tmp_path, 1, [_toy(100.0)], local=True,
                envelope_tail=json.dumps(_toy(150.0)) + "\n")
    problems = history.consistency_problems(
        history.load_rounds(str(tmp_path)))
    assert any("disagrees" in p for p in problems), problems
    # (b) local sentinel lists an emitted metric whose line is missing
    (tmp_path / "BENCH_r01.json").unlink()
    _hist_round(tmp_path, 1, [
        _toy(100.0),
        {"metric": "bench_sweep_complete", "value": 1, "unit": "bool",
         "emitted": ["toy_tflops", "ghost_metric"]},
    ], local=True)
    problems = history.consistency_problems(
        history.load_rounds(str(tmp_path)))
    assert any("ghost_metric" in p for p in problems), problems
    # (c) a round-id stamp contradicting the committed filename
    (tmp_path / "BENCH_LOCAL_r01.jsonl").unlink()
    _hist_round(tmp_path, 2, [_toy(100.0, round=7)])
    problems = history.consistency_problems(
        history.load_rounds(str(tmp_path)))
    assert any("renamed or mixed" in p for p in problems), problems
    # (d) a crashed sweep sentinel
    _hist_round(tmp_path, 3, [
        _toy(90.0),
        {"metric": "bench_sweep_complete", "value": 0, "unit": "bool"},
    ])
    problems = history.consistency_problems(
        history.load_rounds(str(tmp_path)))
    assert any("crashed mid-sweep" in p for p in problems)


def test_bench_history_check_repo_green():
    """Tier-1 smoke (the CI satellite): the committed r01-r05 records
    are internally consistent and the sentinel exits green."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_history.py"),
         "--check"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bench history check OK" in proc.stdout
    assert "PROBLEM" not in proc.stdout


def test_bench_history_cli_flags_synthetic_decline(tmp_path):
    """The acceptance fixture: a synthetic 3-round decline is flagged
    (WARN, exit 0) and --strict turns it into a failure."""
    for rnd, v in enumerate((100.0, 97.0, 90.0, 80.0), start=1):
        _hist_round(tmp_path, rnd, [_toy(v)])
    cmd = [sys.executable, os.path.join(REPO, "scripts",
                                        "bench_history.py"),
           str(tmp_path), "--check"]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "monotonic decline" in proc.stdout
    proc = subprocess.run(cmd + ["--strict"], capture_output=True,
                          text=True, timeout=120,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1
    # an internally inconsistent round fails --check without --strict
    _hist_round(tmp_path, 5, [_toy(100.0)], local=True,
                envelope_tail=json.dumps(_toy(50.0)) + "\n")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 1
    assert "PROBLEM" in proc.stdout


def test_tdt_lint_history_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--history"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "bench history check OK" in proc.stdout


def test_check_perf_claims_trend_hook():
    """--trend rides along the claims gate: trajectory output appears
    next to the floor verdicts without changing the verdict."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_perf_claims.py"), "--trend"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "trend:" in proc.stdout
    assert "satisfy their primary claims" in proc.stdout


# ---------------------------------------------------------------------------
# continuous overlap profiler (ISSUE 16): incremental drain, windowed
# rollups, on-disk time-series, anomaly detection, HTTP surface


@pytest.fixture()
def profile_on(obs_on):
    """Armed flight ring + continuous profiler with a fresh unpersisted
    profiler installed, everything restored after."""
    from triton_distributed_tpu.obs import anomaly, continuous, flight

    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    flight.enable(True)
    continuous.enable(True)
    flight.clear()
    prev_installed = continuous.install(
        continuous.ContinuousProfiler(window_steps=2, out_dir=""))
    yield continuous
    continuous.install(prev_installed)
    anomaly.clear()
    flight.clear()
    continuous.enable(prev_prof)
    flight.enable(prev_flight)


def test_profile_disarmed_hook_is_noop():
    """TDT_PROFILE unset: the step hook must neither instantiate a
    profiler nor touch the ring — byte-identical serve behavior is the
    acceptance criterion, and no-profiler-object is its observable."""
    from triton_distributed_tpu import serve
    from triton_distributed_tpu.obs import continuous

    assert not continuous.enabled()
    continuous.reset()
    continuous.on_step("decode", 1)
    assert continuous.profiler() is None
    assert continuous.to_prometheus() == ""
    # a real scheduler replay with the hook wired in leaves it None too
    backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                               max_length=32)
    sched = serve.Scheduler(backend)
    arrivals = serve.synthetic_trace(1, 4, mean_interarrival_steps=0.5,
                                     prompt_len=(2, 6), max_new=(2, 4))
    report = serve.replay(sched, arrivals, max_steps=2000)
    assert report.problems() == []
    assert continuous.profiler() is None


def test_profile_rollup_agrees_with_offline_timeline(profile_on):
    """The acceptance pin: live per-family rollups from the incremental
    drain must agree with the OFFLINE timeline reconstructor on the
    same capture — same code path, exact float equality on the raw
    Rollup, not the rounded to_dict."""
    from triton_distributed_tpu.obs import continuous, flight, timeline

    _, streams = flight.record_family("allgather", 2)
    prof = continuous.ContinuousProfiler(window_steps=1, out_dir="")
    flight.clear()
    flight.feed_streams("allgather", streams)
    prof.on_step("decode", 1)
    rollups = prof.lifetime_rollups()
    assert ("allgather", "n2", "decode") in rollups, sorted(rollups)
    live = rollups[("allgather", "n2", "decode")]
    off = timeline.reconstruct(streams, kernel="allgather")
    assert live.exposed_us == sum(r.exposed_us for r in off.rows)
    assert live.compute_us == sum(r.compute_us for r in off.rows)
    assert live.critical_us == off.critical_us
    assert live.sol_us == off.sol_us
    assert live.skew_us == off.skew_us
    assert live.pct_sol == off.pct_sol
    # the stall aggregation keeps the (sem, chunk, peer) attribution
    sem, chunk, peer, exposed = live.dominant_stall()
    assert sem and exposed > 0
    assert any(w.sem == sem and w.chunk == chunk and w.source == peer
               for w in off.waits)


def test_profile_incremental_drain_and_rotation(profile_on):
    """The drain is incremental (an identity cursor — each event
    ingested exactly once) and windows rotate at window_steps with the
    gauges/sketches fed."""
    from triton_distributed_tpu.obs import continuous, flight

    _, streams = flight.record_family("allgather", 2)
    prof = continuous.profiler()
    flight.clear()
    flight.feed_streams("allgather", streams)
    prof.on_step("decode", 1)           # drains the episode, no window yet
    assert prof.last_window() is None
    flight.feed_streams("allgather", streams)
    prof.on_step("decode", 2)           # second boundary -> rotate (ws=2)
    win = prof.last_window()
    assert win is not None and win["window"] == 0
    assert win["steps"] == 2 and win["window_steps"] == 2
    [r] = win["rollups"]
    assert (r["family"], r["topology"], r["tier"]) == \
        ("allgather", "n2", "decode")
    assert r["episodes"] == 2           # both feeds, each counted ONCE
    assert win["totals"]["episodes"] == 2
    # the serve_stats plane carries the window
    snap = obs.serve_stats.STATS.snapshot()
    assert snap["gauges"]["profile_windows"] == 1.0
    assert "tdt_profile_windows_total 1" in continuous.to_prometheus()
    # an idle window (no new events) still rotates, with empty rollups
    prof.on_step("decode", 3)
    prof.on_step("decode", 4)
    win2 = prof.last_window()
    assert win2["window"] == 1 and win2["rollups"] == []


def test_profile_scheduler_replay_rotates_windows(profile_on, tmp_path):
    """The serve hook end-to-end: an armed seeded replay through the
    REAL scheduler rotates windows and persists the time-series, and
    ``obs.history`` parses the segments back."""
    from triton_distributed_tpu import serve
    from triton_distributed_tpu.obs import continuous, history

    continuous.install(continuous.ContinuousProfiler(
        window_steps=4, out_dir=str(tmp_path)))
    backend = serve.SimBackend(slots=3, page_size=4, pool_pages=32,
                               max_length=48)
    sched = serve.Scheduler(backend)
    arrivals = serve.synthetic_trace(3, 14, mean_interarrival_steps=0.5,
                                     prompt_len=(2, 9), max_new=(2, 8))
    report = serve.replay(sched, arrivals, max_steps=2000)
    assert report.problems() == []
    prof = continuous.profiler()
    snap = prof.snapshot()
    assert snap["windows_total"] >= 2
    wins = history.load_profile_windows(str(tmp_path))
    assert len(wins) == snap["windows_total"]
    assert [w["window"] for w in wins] == \
        sorted(w["window"] for w in wins)
    series = history.profile_series(wins, "exposed_ms")
    assert len(series) == len(wins)
    assert all(isinstance(v, float) for v in series)


def test_profile_segments_bounded(profile_on, tmp_path, monkeypatch):
    """The on-disk time-series is bounded BY CONSTRUCTION: segments
    rotate at the size cap and only the newest MAX_SEGMENTS survive."""
    from triton_distributed_tpu.obs import continuous, flight

    monkeypatch.setattr(continuous, "SEGMENT_MAX_BYTES", 512)
    _, streams = flight.record_family("allgather", 2)
    prof = continuous.ContinuousProfiler(window_steps=1,
                                         out_dir=str(tmp_path))
    for step in range(1, 41):
        flight.feed_streams("allgather", streams)
        prof.on_step("decode", step)
    segs = sorted(tmp_path.glob("profile_*.jsonl"))
    assert 1 <= len(segs) <= continuous.MAX_SEGMENTS
    assert all(s.stat().st_size <= 512 + 4096 for s in segs)
    # the newest window is in the newest segment (pruning drops OLD)
    from triton_distributed_tpu.obs import history

    wins = history.load_profile_windows(str(tmp_path))
    assert wins and wins[-1]["window"] == 39


def test_band_shared_implementation_pins_analyze(tmp_path):
    """Satellite 1: ONE band implementation.  ``healthy_band`` /
    ``Band.breach`` must agree exactly with ``analyze``'s below-band
    warning predicate, both directions, on the same synthetic rounds."""
    from triton_distributed_tpu.obs import history

    def run(values):
        for rnd, v in enumerate(values, start=1):
            _hist_round(tmp_path, rnd, [_toy(v)])
        trs = history.analyze(history.load_rounds(str(tmp_path)))
        warned = any("outside" in w or "band" in w
                     for w in history.all_warnings(trs))
        band = history.healthy_band([float(v) for v in values[:-1]],
                                    "higher")
        return warned, band.breach(float(values[-1])) is not None

    warned, breached = run((100.0, 102.0, 98.0, 80.0))
    assert warned and breached
    for p in tmp_path.glob("BENCH_*"):
        p.unlink()
    warned, breached = run((100.0, 102.0, 98.0, 99.0))
    assert not warned and not breached
    # bands_for: the same Band from committed draws by metric name
    for p in tmp_path.glob("BENCH_*"):
        p.unlink()
    for rnd, v in enumerate((100.0, 102.0, 98.0), start=1):
        _hist_round(tmp_path, rnd, [_toy(v)])
    band = history.bands_for("toy_tflops", root=str(tmp_path))
    assert band == history.healthy_band([100.0, 102.0, 98.0], "higher")


def test_anomaly_selftest_both_directions():
    """Tier-1 wiring for the acceptance criterion: the clean replay is
    quiet, the seeded regression is caught with the stall triple and
    exemplar named."""
    from triton_distributed_tpu.obs import anomaly

    prev = obs.enabled()
    obs.enable(True)
    try:
        assert anomaly.selftest() == []
    finally:
        anomaly.clear()
        obs.serve_stats.STATS.reset()
        obs.enable(prev)


def test_anomaly_event_surfaces_in_health(profile_on):
    """A breaching window is a WARNING on the health surface — the
    `profile` fragment appears, the status (and therefore the /healthz
    code) stays ok, and the governor takes the advisory."""
    from triton_distributed_tpu import resilience, serve
    from triton_distributed_tpu.obs import anomaly, continuous, flight
    from triton_distributed_tpu.obs import history

    _, streams = flight.record_family("allgather", 2)
    band = history.healthy_band([1e-6, 2e-6], "lower")  # everything breaches
    anomaly.set_detector(anomaly.AnomalyDetector({"exposed_ms": band}))
    try:
        backend = serve.SimBackend(slots=2, page_size=4, pool_pages=16,
                                   max_length=32)
        sched = serve.Scheduler(backend)
        prof = continuous.ContinuousProfiler(window_steps=1, out_dir="")
        continuous.install(prof)
        flight.clear()
        flight.feed_streams("allgather", streams)
        sched.step()                      # the hook drains + rotates
        assert prof.snapshot()["anomalies_total"] == 1
        [ev] = anomaly.current()
        assert ev.metric == "exposed_ms" and ev.stall is not None
        assert ev.excerpt                  # flight-ring excerpt attached
        # health(): warning fragment, status stays ok
        snap = sched.health()
        assert snap["status"] == "ok"
        assert snap["profile"]["status"] == "warn"
        assert any("exposed_ms" in s for s in snap["profile"]["anomalies"])
        assert resilience.health_snapshot()["profile"]["total"] == 1
        # the governor counted the advisory
        assert sched.governor.snapshot()["advisories"] == 1
        # a later healthy window CLEARS the warning state
        anomaly.set_detector(anomaly.AnomalyDetector({}))
        sched.step()
        assert anomaly.current() == []
        assert "profile" not in sched.health()
    finally:
        anomaly.set_detector(None)


def test_governor_advisory_needs_recurrence():
    """One advisory does nothing; recurring advisories within the
    window degrade admission exactly like preemption thrash."""
    from triton_distributed_tpu.resilience.policy import AdmissionGovernor

    g = AdmissionGovernor()
    g.note_advisory()
    g.note_step_ok()
    assert g.level == 0
    for _ in range(3):
        g.note_advisory()
        g.note_step_ok()
    assert g.level == 1
    assert g.snapshot()["advisories"] == 4


def test_debug_endpoints_bounded_and_profile_surface(profile_on):
    """Satellite 2: /debug/flight and /debug/timeline are ring-TAIL
    bounded with ?n= clamping; armed /debug/timeline serves the
    profiler's window instead of re-reconstructing; /debug/profile
    answers in both disarmed and armed states."""
    from triton_distributed_tpu.obs import continuous, flight
    from triton_distributed_tpu.obs import server as obs_server

    srv = obs_server.start(port=0)
    try:
        for _ in range(600):
            flight.mark_collective("allgather", payload_bytes=64,
                                   ranks=2, method="ring")
        code, body = _get(srv.url + "/debug/flight")
        assert code == 200
        d = json.loads(body)
        assert d["n"] == 256 and len(d["events"]) == 256
        code, body = _get(srv.url + "/debug/flight?n=10")
        assert json.loads(body)["n"] == 10
        code, body = _get(srv.url + "/debug/flight?n=999999")
        assert json.loads(body)["n"] == obs_server.FLIGHT_DUMP_MAX
        code, body = _get(srv.url + "/debug/flight?n=garbage")
        assert code == 200 and json.loads(body)["n"] == 256
        # armed but windowless: timeline falls back to the ring tail
        code, body = _get(srv.url + "/debug/timeline?n=50")
        d = json.loads(body)
        assert code == 200 and d["source"] == "ring" and d["n"] == 50
        # /debug/profile before any step boundary: armed stub
        code, body = _get(srv.url + "/debug/profile")
        d = json.loads(body)
        assert code == 200 and d["enabled"] and d["windows_total"] == 0
        # rotate a window; timeline flips to the profiler snapshot
        _, streams = flight.record_family("allgather", 2)
        flight.clear()
        flight.feed_streams("allgather", streams)
        prof = continuous.profiler()
        prof.on_step("decode", 1)
        prof.on_step("decode", 2)
        code, body = _get(srv.url + "/debug/timeline")
        d = json.loads(body)
        assert code == 200 and d["source"] == "profiler"
        assert d["window"]["rollups"]
        code, body = _get(srv.url + "/debug/profile")
        d = json.loads(body)
        assert d["windows_total"] == 1
        assert d["last_window"]["totals"]["episodes"] == 1
        code, body = _get(srv.url + "/metrics")
        assert "tdt_profile_windows_total 1" in body
        assert 'tdt_profile_overlap_hidden_pct{family="allgather"' in body
        code, body = _get(srv.url + "/nope")
        assert code == 404 and "/debug/profile" in body
        # disarmed: stub, and timeline back to the ring path
        continuous.enable(False)
        code, body = _get(srv.url + "/debug/profile")
        d = json.loads(body)
        assert code == 200 and d["enabled"] is False
        code, body = _get(srv.url + "/debug/timeline")
        assert json.loads(body)["source"] == "ring"
    finally:
        obs_server.stop()


def test_profile_scrape_during_window_rotation(profile_on):
    """Satellite 3: /metrics and /debug/profile scraped from threads
    WHILE windows rotate — every response parses (no torn snapshot),
    no 500s, and the final window count matches the rotations driven
    (no dropped window)."""
    from triton_distributed_tpu.obs import continuous, flight
    from triton_distributed_tpu.obs import server as obs_server

    _, streams = flight.record_family("allgather", 2)
    prof = continuous.ContinuousProfiler(window_steps=1, out_dir="")
    continuous.install(prof)
    srv = obs_server.start(port=0)
    stop = threading.Event()
    failures: list = []
    seen_windows: list = []

    def scraper():
        while not stop.is_set():
            code, body = _get(srv.url + "/metrics")
            if code != 200:
                failures.append(("metrics", code, body))
            code, body = _get(srv.url + "/debug/profile")
            if code != 200:
                failures.append(("profile", code, body))
                continue
            snap = json.loads(body)     # raises on a torn payload
            if snap.get("last_window"):
                w = snap["last_window"]
                # a published window is immutable and self-consistent
                if len(w["rollups"]) != len(set(
                        (r["family"], r["topology"], r["tier"])
                        for r in w["rollups"])):
                    failures.append(("dup rollup", w))
                seen_windows.append(w["window"])

    threads = [threading.Thread(target=scraper) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        rotations = 25
        for step in range(1, rotations + 1):
            flight.feed_streams("allgather", streams)
            prof.on_step("decode", step)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        obs_server.stop()
    assert not failures, failures[:3]
    assert prof.snapshot()["windows_total"] == rotations
    # scrapers observed monotone window ids (no rollback, no tear)
    for ws in seen_windows:
        assert 0 <= ws < rotations


def test_telemetry_profile_during_live_decode(obs_on):
    """Satellite 3, the PR-5 harness shape: with the profiler armed and
    a rotated window, /metrics and /debug/profile answer from INSIDE a
    live decode step without dropping the window."""
    from triton_distributed_tpu.core import mesh as mesh_lib
    from triton_distributed_tpu.models import Engine, ModelConfig
    from triton_distributed_tpu.obs import continuous, flight
    from triton_distributed_tpu.obs import server as obs_server

    prev_flight = flight.enabled()
    prev_prof = continuous.enabled()
    flight.enable(True)
    continuous.enable(True)
    flight.clear()
    prof = continuous.ContinuousProfiler(window_steps=1, out_dir="")
    prev_installed = continuous.install(prof)
    cfg = ModelConfig(
        num_layers=1, hidden=8, intermediate=16, num_heads=1,
        num_kv_heads=1, head_dim=8, vocab=32, max_length=32,
        dtype=jnp.float32,
    )
    model = _TinyServeModel(mesh_lib.tp_mesh(1), cfg)
    eng = Engine(model, {"w": jnp.zeros((), jnp.float32)}, batch=1)
    srv = obs_server.start(port=0, engine=eng)
    try:
        _, streams = flight.record_family("allgather", 2)
        flight.clear()
        flight.feed_streams("allgather", streams)
        prof.on_step("decode", 1)       # one completed window pre-serve
        seen: dict = {}
        orig = eng.decode_step

        def hooked(tok):
            if obs.enabled() and not seen:
                seen["metrics"] = _get(srv.url + "/metrics")
                seen["profile"] = _get(srv.url + "/debug/profile")
                seen["timeline"] = _get(srv.url + "/debug/timeline")
            return orig(tok)

        eng.decode_step = hooked
        ids = jnp.zeros((1, 4), jnp.int32)
        eng.serve(ids, gen_len=6)
        assert seen, "decode loop never ran with telemetry enabled"
        code, body = seen["metrics"]
        assert code == 200 and "tdt_profile_windows_total 1" in body
        code, body = seen["profile"]
        assert code == 200
        snap = json.loads(body)
        assert snap["enabled"] and snap["windows_total"] == 1
        assert snap["last_window"]["rollups"]
        code, body = seen["timeline"]
        assert code == 200 and json.loads(body)["source"] == "profiler"
        # the window survived the serve (not dropped by live traffic)
        assert prof.snapshot()["windows_total"] == 1
    finally:
        eng.close()
        continuous.install(prev_installed)
        flight.clear()
        continuous.enable(prev_prof)
        flight.enable(prev_flight)


def test_tdt_lint_profile_smoke():
    """The CI gate wiring (ISSUE 16 satellite): armed two-tier replay
    rotates windows, per-family rollups reconcile against the offline
    timeline, anomaly selftest passes both directions."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "tdt_lint.py"),
         "--profile"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "profile OK" in proc.stdout
    assert "windows rotated" in proc.stdout
