"""Multi-process (simulated multi-host) rendezvous and cross-host
collectives: two local processes bootstrap through
``platform.initialize_distributed`` (the reference's torchrun + NCCL +
NVSHMEM-UID bring-up, ``utils.py:174-200``, collapsed into
``jax.distributed``) and run collectives over a 2-host x 4-device mesh.

Scope: the DCN (cross-process) layer — XLA collectives over Gloo — plus
the mesh/axis conventions, which is exactly what crosses hosts in
production (SURVEY.md section 5: device-initiated DMA is ICI-only).  The
Pallas ICI kernels are interpreted per-process and covered by the
single-process suite; the interpreter's simulated semaphores cannot span
a process boundary, so the hierarchical ops' inner level is out of scope
here by design."""

import os
import subprocess
import sys

import pytest

_CHILD = r"""
import sys
proc_id = int(sys.argv[1])
from triton_distributed_tpu.core.platform import force_cpu, initialize_distributed
force_cpu(6)

ctx = initialize_distributed(
    coordinator_address=f"127.0.0.1:{sys.argv[2]}",
    num_processes=2, process_id=proc_id,
)
assert ctx.world == 2 and ctx.rank == proc_id, (ctx.rank, ctx.world)
assert len(ctx.local_devices) == 6 and len(ctx.devices) == 12

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.compilation import jit_shard_map
from triton_distributed_tpu.core.mesh import is_dcn_axis

assert is_dcn_axis("dcn")

# 2 hosts x 4 devices (2 spare local devices stay out of the mesh)
devs = np.array(jax.devices()).reshape(2, 6)[:, :4]
mesh = Mesh(devs, ("dcn", "ici"))
n, m, r = 8, 16, 128
x_global = np.arange(n * m * r, dtype=np.float32).reshape(n * m, r) / 1e3
spec = NamedSharding(mesh, P(("dcn", "ici"), None))
xs = jax.make_array_from_callback(
    x_global.shape, spec, lambda idx: x_global[idx]
)

# two-level all-gather: inner over ici, outer over dcn (the XLA layer the
# hierarchical ops place above their Pallas rings)
def body(x):
    x = jax.lax.all_gather(x, "ici", tiled=True)
    return jax.lax.all_gather(x, "dcn", tiled=True)

out = jit_shard_map(
    body, mesh, in_specs=P(("dcn", "ici"), None), out_specs=P(None, None)
)(xs)
for shard in out.addressable_shards:
    np.testing.assert_allclose(np.asarray(shard.data), x_global)

# cross-host psum_scatter + ppermute (the DCN verbs the reduce side uses)
def rs_body(x):
    part = jax.lax.psum(x, "ici")
    part = jax.lax.psum_scatter(part, "dcn", scatter_dimension=0, tiled=True)
    # rotate the scattered chunks around the dcn ring and back
    return jax.lax.ppermute(part, "dcn", [(0, 1), (1, 0)])

rs = jit_shard_map(
    rs_body, mesh, in_specs=P(("dcn", "ici"), None), out_specs=P("dcn", None)
)(xs)
want_sum = x_global.reshape(n, m, r).sum(0)
got = np.concatenate(
    [np.asarray(s.data) for s in rs.addressable_shards[:1]]
)
# after the rotation, host h holds the OTHER host's scattered half
half = m // 2
other = (proc_id + 1) % 2
np.testing.assert_allclose(
    got, want_sum[other * half:(other + 1) * half], rtol=1e-5, atol=1e-5
)
print(f"proc {proc_id} multihost collectives ok", flush=True)

# link calibration's DCN branch + cross-process agreement: both procs
# must compute the IDENTICAL (mean) numbers or per-host thresholds could
# steer choose_method into mismatched collective methods across hosts
import os as _os, tempfile as _tf
_os.environ["TDT_LINKCAL_CACHE"] = _os.path.join(_tf.mkdtemp(), "cal.json")
from triton_distributed_tpu.tools import calibrate as _cal
got = _cal.calibrate(mesh=mesh, force=True, save=False,
                     sizes_bytes=(65536, 262144, 1048576))
assert got.ici_gbps and got.ici_gbps > 0, got
assert got.dcn_gbps is not None and got.dcn_gbps > 0, got
print(f"proc {proc_id} dcn calibration "
      f"ici={got.ici_gbps:.4f}/{got.ici_hop_us:.4f} "
      f"dcn={got.dcn_gbps:.4f}/{got.dcn_hop_us:.4f} ok", flush=True)
"""


def _free_port() -> int:
    """OS-assigned ephemeral port (bind to 0, read, close) — a fixed
    pid-derived port can collide with concurrent test processes or an
    unrelated listener."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_children(port: int, env: dict, cwd: str):
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", "-c", _CHILD, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=cwd,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


@pytest.mark.skipif(os.environ.get("TDT_SKIP_MULTIPROC") == "1",
                    reason="multi-process run disabled")
def test_two_process_bootstrap_and_dcn_collectives(tmp_path):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # children set their own platform
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    for attempt in range(2):
        procs, outs = _run_children(_free_port(), env, str(tmp_path))
        if all(p.returncode == 0 for p in procs):
            break
        # retry (once, on a fresh port) ONLY when the failure looks like a
        # racer grabbing the probed port between close and the coordinator's
        # bind — a genuine bootstrap regression should report immediately
        # with its own first-attempt logs
        bind_race = any(
            "address already in use" in out.lower()
            or "failed to bind" in out.lower()
            for out in outs
        )
        if not bind_race:
            break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}:\n{out[-4000:]}"
        assert f"proc {i} multihost collectives ok" in out, out[-2000:]
    # the agreed calibration numbers must be IDENTICAL on both processes
    # (the printed line carries them to 4 decimals)
    import re

    cals = [
        re.search(r"dcn calibration (.*) ok", out).group(1) for out in outs
    ]
    assert cals[0] == cals[1], cals
