"""Contextual autotuner: winner selection, failure skipping, persistence
(reference ``autotuner.py`` behavior)."""

import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tune import Autotuner, tuned_matmul


def test_picks_fastest_candidate(tmp_path):
    tuner = Autotuner(path=str(tmp_path / "cache.json"))
    calls = []

    def make_thunk(c):
        def thunk():
            calls.append(c)
            time.sleep(0.002 * c)  # candidate value = its cost
            return jnp.zeros(())
        return thunk

    res = tuner.tune("toy", ("k",), [3, 1, 2], make_thunk, iters=2)
    assert res.config == 1
    assert not res.from_cache
    # second call: memory cache, no re-timing
    n_calls = len(calls)
    res2 = tuner.tune("toy", ("k",), [3, 1, 2], make_thunk, iters=2)
    assert res2.config == 1 and res2.from_cache
    assert len(calls) == n_calls


def test_failing_candidates_skipped(tmp_path):
    tuner = Autotuner(path=str(tmp_path / "cache.json"))

    def make_thunk(c):
        if c == "bad":
            def boom():
                raise ValueError("invalid tile")
            return boom
        return lambda: jnp.zeros(())

    res = tuner.tune("toy", ("k2",), ["bad", "good"], make_thunk, iters=1)
    assert res.config == "good"

    with pytest.raises(RuntimeError, match="every candidate failed"):
        tuner.tune("toy", ("k3",), ["bad"], make_thunk, iters=1)


def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "cache.json")
    t1 = Autotuner(path=path)
    t1.tune("toy", ("k",), [10, 1], lambda c: (lambda: time.sleep(0.001 * c)),
            iters=1)
    with open(path) as f:
        disk = json.load(f)
    assert list(disk.values()) == [1]

    # a fresh tuner (new process analogue) reuses the persisted winner
    timed = []
    t2 = Autotuner(path=path)
    res = t2.tune("toy", ("k",), [10, 1],
                  lambda c: (lambda: timed.append(c)), iters=1)
    assert res.config == 1 and res.from_cache and not timed


def test_tuned_matmul_correct():
    import jax

    a = jax.random.normal(jax.random.key(0), (256, 128), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (128, 256), jnp.float32)
    got = tuned_matmul(a, b)
    want = jnp.matmul(a, b)
    assert np.allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                       rtol=1e-4)
