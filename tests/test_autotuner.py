"""Contextual autotuner: winner selection, failure skipping, persistence
(reference ``autotuner.py`` behavior)."""

import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tune import Autotuner, tuned_matmul


def test_picks_fastest_candidate(tmp_path):
    tuner = Autotuner(path=str(tmp_path / "cache.json"))
    calls = []

    def make_thunk(c):
        def thunk():
            calls.append(c)
            time.sleep(0.002 * c)  # candidate value = its cost
            return jnp.zeros(())
        return thunk

    res = tuner.tune("toy", ("k",), [3, 1, 2], make_thunk, iters=2)
    assert res.config == 1
    assert not res.from_cache
    # second call: memory cache, no re-timing
    n_calls = len(calls)
    res2 = tuner.tune("toy", ("k",), [3, 1, 2], make_thunk, iters=2)
    assert res2.config == 1 and res2.from_cache
    assert len(calls) == n_calls


def test_failing_candidates_skipped(tmp_path):
    tuner = Autotuner(path=str(tmp_path / "cache.json"))

    def make_thunk(c):
        if c.startswith("bad"):
            def boom():
                raise ValueError("invalid tile")
            return boom
        return lambda: jnp.zeros(())

    res = tuner.tune("toy", ("k2",), ["bad", "good"], make_thunk, iters=1)
    assert res.config == "good"

    with pytest.raises(RuntimeError, match="every candidate failed"):
        tuner.tune("toy", ("k3",), ["bad", "bad2"], make_thunk, iters=1)

    # a single candidate short-circuits without measuring (nothing to pick)
    probed = []
    res1 = tuner.tune("toy", ("k4",), ["only"],
                      lambda c: (lambda: probed.append(c)), iters=1)
    assert res1.config == "only" and res1.from_cache and not probed


def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "cache.json")
    t1 = Autotuner(path=path)
    t1.tune("toy", ("k",), [10, 1], lambda c: (lambda: time.sleep(0.001 * c)),
            iters=1)
    with open(path) as f:
        disk = json.load(f)
    assert list(disk.values()) == [1]

    # a fresh tuner (new process analogue) reuses the persisted winner
    timed = []
    t2 = Autotuner(path=path)
    res = t2.tune("toy", ("k",), [10, 1],
                  lambda c: (lambda: timed.append(c)), iters=1)
    assert res.config == 1 and res.from_cache and not timed


def test_tuned_matmul_correct(tmp_path, monkeypatch):
    import jax

    from triton_distributed_tpu.tune import autotuner as at

    # fresh global tuner: the module-level one memoizes the user's REAL
    # disk cache on first load, which would leak into/out of this test
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "m.json")))

    a = jax.random.normal(jax.random.key(0), (256, 128), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (128, 256), jnp.float32)
    got = tuned_matmul(a, b)
    want = jnp.matmul(a, b)
    assert np.allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                       rtol=1e-4)


def test_transparent_matmul_uses_cached_winner(tmp_path, monkeypatch):
    """With config=None, ops consult the persisted winner cache — a prior
    tuned run teaches later (including jit'd) calls with zero code change;
    with no cache entry under tracing/interpret, the default backend
    (XLA dispatch) holds and no Pallas kernel is built (VERDICT next #5,
    round-4 backend dispatch)."""
    import jax

    from triton_distributed_tpu.ops import matmul as mm
    from triton_distributed_tpu.tune import autotuner as at

    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "w.json")))
    monkeypatch.setenv("TDT_AUTOTUNE", "0")   # never measure in this test

    built = []
    real_build = mm._build_matmul

    def spy(m, n, k, bm, bn, bk, dtype, out_dtype, vmem_limit=None):
        built.append((bm, bn, bk))
        return real_build(m, n, k, bm, bn, bk, dtype, out_dtype, vmem_limit)

    monkeypatch.setattr(mm, "_build_matmul", spy)

    # shape chosen so exactly one big-tile Pallas candidate survives the
    # size filter (the round-4 candidate list is VL big tiles only)
    m, n, k = 512, 2048, 1024
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (k, n), jnp.float32)

    # no cache entry: XLA-dispatch default — correct result, no Pallas build
    want = np.asarray(jnp.matmul(a, b))
    got = mm.matmul(a, b)
    assert not built
    assert np.allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)

    # plant a PALLAS winner and check both eager and traced calls pick it
    # up from disk
    cands = at.matmul_backend_candidates(m, n, k)
    target4 = (512, 2048, 1024, at.MATMUL_TILE_VL)
    target = target4[:3]
    idx = cands.index(target4)
    key = ("matmul", (m, n, k, str(a.dtype), at.platform.device_kind()))
    at._GLOBAL._load_disk()[at._cache_key(key[0], key[1], cands)] = idx
    at._GLOBAL._save_disk()
    # fresh tuner (new process analogue) reads the planted winner from disk
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "w.json")))

    got = mm.matmul(a, b)                             # eager
    assert built[-1] == target
    assert np.allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)

    jax.jit(lambda a, b: mm.matmul(a, b))(a, b)       # traced: same winner
    assert built[-1] == target

    # plant the XLA-dispatch winner: eager dispatches (no Pallas build),
    # traced inlines the plain dot — both numerically identical.  (Flag
    # variants are excluded from default sweeps — see
    # XLA_VMEM_SWEEP_KIB — so the dispatch candidate is XlaBackend(0).)
    built.clear()
    at._GLOBAL._load_disk()[at._cache_key(key[0], key[1], cands)] = (
        cands.index(at.XlaBackend(0))
    )
    at._GLOBAL._save_disk()
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "w.json")))
    got = mm.matmul(a, b)
    got_jit = jax.jit(lambda a, b: mm.matmul(a, b))(a, b)
    assert not built
    assert np.allclose(np.asarray(got), want, atol=1e-4, rtol=1e-4)
    assert np.allclose(np.asarray(got_jit), want, atol=1e-4, rtol=1e-4)


def test_transparent_ag_gemm_cache_consult(tmp_path, monkeypatch):
    """config=None on the fused collective consults the same cache keys the
    explicit tuned_ag_gemm writes, including under jit tracing."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    import sys

    import triton_distributed_tpu.ops.ag_gemm  # noqa: F401

    from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
    from triton_distributed_tpu.tune import autotuner as at
    from triton_distributed_tpu.tune import tuned_ag_gemm

    agg = sys.modules["triton_distributed_tpu.ops.ag_gemm"]

    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "c.json")))
    mesh = make_mesh({TP_AXIS: 2}, devices=jax.devices()[:2])
    m, k, n = 64, 96, 80
    a = jax.device_put(
        jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1,
        NamedSharding(mesh, P(TP_AXIS, None)))
    b = jax.device_put(
        jax.random.normal(jax.random.key(1), (k, n), jnp.float32) * 0.1,
        NamedSharding(mesh, P(None, TP_AXIS)))

    built = []
    real_build = agg._build_ag_gemm

    def spy(mesh_, axis_, m_, k_, n_, dt, odt, cfg, bidir):
        built.append(cfg)
        return real_build(mesh_, axis_, m_, k_, n_, dt, odt, cfg, bidir)

    monkeypatch.setattr(agg, "_build_ag_gemm", spy)

    tuned_ag_gemm(a, b, mesh, TP_AXIS)        # measures, persists winner
    winner = built[-1]

    built.clear()
    out = jax.jit(
        lambda a, b: agg.ag_gemm(a, b, mesh, TP_AXIS)
    )(a, b)                                    # traced, config=None
    assert built and built[-1] == winner
    want = np.asarray(jax.device_get(a)) @ np.asarray(jax.device_get(b))
    assert np.allclose(np.asarray(jax.device_get(out)), want, atol=1e-3,
                       rtol=1e-3)


def test_tuned_collective_ops_correct(tmp_path, monkeypatch):
    """tuned_ag_gemm / tuned_gemm_rs sweep real collective invocations and
    return correct results with the winning config."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
    from triton_distributed_tpu.tune import autotuner as at
    from triton_distributed_tpu.tune import tuned_ag_gemm, tuned_gemm_rs

    # fresh global tuner with an isolated cache file: the module-level one
    # memoizes whatever disk cache it loaded first
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "c.json")))
    mesh = make_mesh({TP_AXIS: 4}, devices=jax.devices()[:4])
    m, k, n = 4 * 24, 96, 4 * 40
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(1), (k, n), jnp.float32) * 0.1
    a_ag = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_ag = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    out = tuned_ag_gemm(a_ag, b_ag, mesh, TP_AXIS)
    want = np.asarray(a) @ np.asarray(b)
    assert np.allclose(np.asarray(jax.device_get(out)), want, atol=1e-3,
                       rtol=1e-3)

    a_rs = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_rs = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    out2 = tuned_gemm_rs(a_rs, b_rs, mesh, TP_AXIS)
    assert np.allclose(np.asarray(jax.device_get(out2)), want, atol=1e-3,
                       rtol=1e-3)


def test_sol_fraction_reported(tmp_path):
    """A perf_model estimate turns the winner's time into a SOL fraction
    on fresh tunes (reference: its perf models feed the autotuner)."""
    tuner = Autotuner(path=str(tmp_path / "sol.json"))

    def make_thunk(c):
        def thunk():
            time.sleep(0.001)
            return jnp.zeros(())
        return thunk

    res = tuner.tune("toy_sol", ("k",), [1, 2], make_thunk, iters=2,
                     sol_ms=0.5)
    assert res.sol_fraction is not None and 0 < res.sol_fraction <= 1.5
    # cached result carries no fresh measurement -> no fraction
    res2 = tuner.tune("toy_sol", ("k",), [1, 2], make_thunk, iters=2,
                      sol_ms=0.5)
    assert res2.from_cache and res2.sol_fraction is None


def test_fresh_fine_margin_crown_not_persisted(tmp_path, monkeypatch):
    """A fresh crown that clears only the fine FRESH margins must stay
    process-local: the shared disk cache hands winners to later
    processes WITHOUT re-measurement, so only wins clearing the full
    conservative margin may persist (the round-3 inherited-chip-state
    regression class)."""
    from triton_distributed_tpu.tune import autotuner as at

    def run(times_by_candidate, conf_times=None):
        tuner = Autotuner(path=str(tmp_path / f"c{len(times_by_candidate)}.json"))

        def fake_measure(thunks, iters, rounds=5, target_window_s=0.15):
            return {i: times_by_candidate[i] for i in thunks}

        def fake_samples(thunks, iters, rounds, target_window_s=None):
            # the confirmation pass maps {0: challenger, 1: baseline};
            # this test's sweep has baseline=candidate 0, challenger=
            # candidate 1 — synthesize consistent (slope, raw) samples
            src = conf_times or times_by_candidate
            seq = {0: [(src[1] / 1e3, src[1] / 1e3)] * rounds,
                   1: [(src[0] / 1e3, src[0] / 1e3)] * rounds}
            return {i: seq[i] for i in thunks}

        monkeypatch.setattr(tuner, "_measure_interleaved", fake_measure)
        monkeypatch.setattr(at, "interleaved_time_samples", fake_samples)
        res = tuner.tune(
            "toy", ("k",), [0, 1],
            lambda c: (lambda: jnp.zeros(())),
            baseline_index=0, margin=0.08, fresh=True,
        )
        disk = json.loads(
            (tmp_path / f"c{len(times_by_candidate)}.json").read_text()
        ) if (tmp_path / f"c{len(times_by_candidate)}.json").exists() else {}
        return res.config, disk

    # challenger wins by ~3% (> fine 1.5%, < full 8%): crowned for this
    # process, NOT written to disk
    cfg, disk = run({0: 1.00, 1: 0.97})
    assert cfg == 1
    assert disk == {}

    # challenger wins by 20% (> full margin): crowned AND persisted
    cfg, disk = run({0: 1.00, 1: 0.80, 2: 0.80})
    assert cfg == 1
    assert list(disk.values()) == [1]


def test_fresh_fine_margin_crown_demotes_stale_disk_winner(tmp_path,
                                                          monkeypatch):
    """A fine-margin fresh crown that CONTRADICTS a previously persisted
    winner must delete the stale disk entry (not merely skip writing its
    own): the measurement that crowned the disk entry is now refuted, and
    later processes must fall back to the default rather than inherit it
    (ADVICE r4: a demoted winner lingering on disk)."""
    from triton_distributed_tpu.tune import autotuner as at

    path = tmp_path / "cache.json"
    tuner = Autotuner(path=str(path))

    # persist a full-margin winner (index 2) for the key first
    def fake_measure_seed(thunks, iters, rounds=5, target_window_s=0.15):
        return {i: {0: 1.00, 1: 1.00, 2: 0.70}[i] for i in thunks}

    monkeypatch.setattr(tuner, "_measure_interleaved", fake_measure_seed)

    conf = {"challenger": 0.70e-3}  # confirmation gap, mutated per phase

    def fake_samples(thunks, iters, rounds, target_window_s=None):
        # confirmation maps {0: challenger, 1: baseline}
        return {0: [(conf["challenger"], conf["challenger"])] * rounds,
                1: [(1.00e-3, 1.00e-3)] * rounds}

    monkeypatch.setattr(at, "interleaved_time_samples", fake_samples)
    res = tuner.tune("toy", ("k",), [0, 1, 2],
                     lambda c: (lambda: jnp.zeros(())),
                     baseline_index=0, margin=0.08, fresh=True)
    assert res.config == 2
    assert list(json.loads(path.read_text()).values()) == [2]

    # a later fresh tune (fresh chip state) now crowns index 1, but only
    # by the fine margin: process-local crown + stale entry dropped
    conf["challenger"] = 0.97e-3
    def fake_measure_demote(thunks, iters, rounds=5, target_window_s=0.15):
        return {i: {0: 1.00, 1: 0.97, 2: 1.10}[i] for i in thunks}

    tuner2 = Autotuner(path=str(path))
    monkeypatch.setattr(tuner2, "_measure_interleaved", fake_measure_demote)
    res2 = tuner2.tune("toy", ("k",), [0, 1, 2],
                       lambda c: (lambda: jnp.zeros(())),
                       baseline_index=0, margin=0.08, fresh=True)
    assert res2.config == 1
    assert json.loads(path.read_text()) == {}


# ---------------------------------------------------------------------------
# collective configs under the tuner (VERDICT r5 next #5): candidate
# sweeps, config=None wiring, interpret-pinned defaults, cache consult


def test_collective_tile_candidates_clip_and_dedupe():
    from triton_distributed_tpu.comm.allreduce import AllReduceConfig
    from triton_distributed_tpu.comm.reduce_scatter import (
        ReduceScatterConfig,
    )
    from triton_distributed_tpu.tune.autotuner import (
        collective_tile_candidates,
    )

    cands = collective_tile_candidates(AllReduceConfig, 4096, 4096)
    assert cands[0] == AllReduceConfig(256, 512)   # default-first baseline
    assert len(cands) == len(set(cands)) > 1
    # tiny problems collapse every tiling onto one clipped config
    small = collective_tile_candidates(ReduceScatterConfig, 8, 128)
    assert len(small) == len(set(small))
    assert all(c.bm <= 8 and c.bn <= 128 for c in small)


def test_a2a_chunk_candidates_clamp_and_dedupe():
    from triton_distributed_tpu.comm.all_to_all import AllToAllConfig
    from triton_distributed_tpu.tune.autotuner import a2a_chunk_candidates

    cands = a2a_chunk_candidates(AllToAllConfig, 1024)
    assert cands[0] == AllToAllConfig(128)         # default leads
    assert {c.chunk for c in cands} == {128, 64, 256, 512}
    # a 50-row problem clamps every chunk onto round_up(50, 8) = 56
    tiny = a2a_chunk_candidates(AllToAllConfig, 50)
    assert [c.chunk for c in tiny] == [56]


def _spy_resolve(monkeypatch):
    """Replace the shared resolve_config with a recorder returning the
    default — proves the comm entry points route config=None through the
    tuner machinery (the same hook the GEMM ops use)."""
    from triton_distributed_tpu.tune import autotuner

    calls = []

    def fake(name, key, candidates, default, make_thunk, *, tracing,
             **kw):
        calls.append((name, tuple(key), list(candidates), default,
                      tracing))
        return default

    monkeypatch.setattr(autotuner, "resolve_config", fake)
    return calls


def test_all_reduce_config_none_routes_through_tuner(monkeypatch):
    import jax.numpy as jnp

    from triton_distributed_tpu.comm import allreduce as ar
    from triton_distributed_tpu.core import mesh as mesh_lib

    calls = _spy_resolve(monkeypatch)
    seen = {}
    monkeypatch.setattr(
        ar, "_all_reduce_core",
        lambda mesh, axis, method, out_dtype, cfg, x: seen.setdefault(
            "cfg", cfg) or x[: x.shape[0] // 2])
    mesh = mesh_lib.tp_mesh(2)
    x = jnp.ones((512, 512), jnp.float32)
    ar.all_reduce(x, mesh, "tp")
    names = [c[0] for c in calls]
    assert "ar_cfg" in names                     # the new config sweep
    name, key, cands, default, tracing = calls[names.index("ar_cfg")]
    assert default == ar.AllReduceConfig(256, 512).clip(256, 512)
    assert default in cands and tracing is False
    assert seen["cfg"] == default                # interpret-pinned default


def test_reduce_scatter_config_none_routes_through_tuner(monkeypatch):
    import importlib

    import jax.numpy as jnp

    # the comm package re-exports the FUNCTION under the submodule's
    # name; reach the module itself for monkeypatching
    rs = importlib.import_module(
        "triton_distributed_tpu.comm.reduce_scatter")
    from triton_distributed_tpu.core import mesh as mesh_lib

    calls = _spy_resolve(monkeypatch)
    seen = {}
    monkeypatch.setattr(
        rs, "_reduce_scatter_core",
        lambda mesh, axis, cfg, x: seen.setdefault("cfg", cfg)
        or x[: x.shape[0] // 4])
    mesh = mesh_lib.tp_mesh(2)
    x = jnp.ones((64, 128), jnp.float32)
    rs.reduce_scatter(x, mesh, "tp")
    assert [c[0] for c in calls] == ["rs_cfg"]
    _, _, cands, default, _ = calls[0]
    assert default == rs.ReduceScatterConfig(256, 512).clip(16, 128)
    assert seen["cfg"] == default


def test_ep_dispatch_config_none_routes_through_tuner(monkeypatch):
    import jax.numpy as jnp

    from triton_distributed_tpu.comm import all_to_all as a2a
    from triton_distributed_tpu.core import mesh as mesh_lib

    calls = _spy_resolve(monkeypatch)
    sentinel = ("recv", "splits")
    monkeypatch.setattr(a2a, "_ep_dispatch_diff",
                        lambda mesh, axis, cfg, x, splits: sentinel)
    mesh = mesh_lib.tp_mesh(2)
    x = jnp.ones((2 * 256, 16), jnp.bfloat16)
    splits = jnp.asarray([128, 128, 64, 192], jnp.int32)
    out = a2a.ep_dispatch(x, splits, mesh, "tp")
    assert out == sentinel
    assert [c[0] for c in calls] == ["ep_dispatch_cfg"]
    _, key, cands, default, _ = calls[0]
    assert default == a2a.AllToAllConfig(128)
    assert key[0] == 256                         # per-rank token rows


def test_all_reduce_config_consults_planted_winner(monkeypatch):
    """A winner in the tuner's resolved cache is picked up by a later
    config=None call — the 'consult the winner cache like the GEMM ops
    do' acceptance, exercised through the real resolve_config."""
    import jax.numpy as jnp

    from triton_distributed_tpu.comm import allreduce as ar
    from triton_distributed_tpu.core import mesh as mesh_lib, platform
    from triton_distributed_tpu.tune import autotuner

    seen = {}
    monkeypatch.setattr(
        ar, "_all_reduce_core",
        lambda mesh, axis, method, out_dtype, cfg, x: seen.setdefault(
            "cfg", cfg) or x[: x.shape[0] // 2])
    mesh = mesh_lib.tp_mesh(2)
    x = jnp.ones((512, 512), jnp.float32)   # 512 KiB partial -> one_shot
    winner = ar.AllReduceConfig(128, 512)
    # the contextual key carries the axis's wire class (ISSUE 10): a
    # winner crowned on the ICI torus must not be found for a DCN edge
    key = (256, 512, "float32", 2, "one_shot",
           mesh_lib.wire_class(mesh, "tp"), platform.device_kind())
    rk = ("ar_cfg", tuple(map(str, key)))
    monkeypatch.setitem(autotuner._GLOBAL._resolved, rk, winner)
    # pin the method so the planted key is the one consulted
    ar.all_reduce(x, mesh, "tp", method=ar.AllReduceMethod.ONE_SHOT)
    assert seen["cfg"] == winner


# ---------------------------------------------------------------------------
# ISSUE 15: static footprint pruning (candidates dropped BEFORE measuring)


def test_prune_infeasible_drops_oversubscribing_tiles():
    """An infeasible tile never reaches the measurement phase: the
    (2048, 2048, 2048) bf16 working set (~48 MiB) cannot build under
    the 16 MiB default budget, so the pruner drops it, counts it on
    ``footprint_rejections``, and keeps the default, the XLA dispatch
    candidate, and every feasible tile."""
    import jax.numpy as jnp

    from triton_distributed_tpu import obs
    from triton_distributed_tpu.tune import autotuner as at

    default = at.XlaBackend()
    cands = [default, (512, 512, 512), (2048, 2048, 2048),
             (2048, 2048, 2048, at.MATMUL_TILE_VL)]
    obs.enable(True)
    obs.REGISTRY.reset()
    try:
        kept = at.prune_infeasible(
            "matmul", cands, default,
            dict(m=8192, n=8192, k=8192, dtype=jnp.bfloat16))
        rows = {(r["name"], r["labels"].get("name")): r["value"]
                for r in obs.REGISTRY.snapshot()}
        assert rows[("footprint_rejections", "matmul")] == 1
    finally:
        obs.REGISTRY.reset()
        obs.enable(None)
    # the bare big tile is gone; the SAME tile under its raised budget
    # survives (the budget is part of the candidate)
    assert kept == [default, (512, 512, 512),
                    (2048, 2048, 2048, at.MATMUL_TILE_VL)]


def test_prune_infeasible_never_drops_default_or_unknown():
    import jax.numpy as jnp

    from triton_distributed_tpu.tune import autotuner as at

    # an infeasible DEFAULT passes through (the completeness lint owns
    # flagging it; the sweep must keep its baseline)
    bad_default = (2048, 2048, 2048)
    kept = at.prune_infeasible(
        "matmul", [bad_default], bad_default,
        dict(m=8192, n=8192, k=8192, dtype=jnp.bfloat16))
    assert kept == [bad_default]
    # unknown families never prune (no false positives)
    kept = at.prune_infeasible("no_such_family", [(9, 9, 9)], None, {})
    assert kept == [(9, 9, 9)]


def test_resolve_gemm_like_prunes_before_resolve(monkeypatch):
    """The spy the ISSUE asks for: resolve_gemm_like hands
    resolve_config a candidate list ALREADY pruned of statically
    infeasible tiles — the tuner cannot spend a compile or a timing
    slot on them, and on multi-process sweeps a doomed per-rank build
    (fatal by contract) is avoided."""
    import jax

    from triton_distributed_tpu.ops.gemm_rs import GemmRsConfig
    from triton_distributed_tpu.tune import autotuner as at

    infeasible = (2048, 2048, 2048)
    monkeypatch.setattr(
        at, "matmul_tile_candidates",
        lambda m, n, k: [(256, 256, 256), infeasible])
    seen = {}

    def spy_resolve(name, key, candidates, default, make_thunk, **kw):
        seen["cands"] = list(candidates)
        return default

    monkeypatch.setattr(at, "resolve_config", spy_resolve)
    mesh = __import__(
        "triton_distributed_tpu.core.mesh", fromlist=["tp_mesh"]
    ).tp_mesh(1)
    a = jax.numpy.ones((8192, 8192), jax.numpy.bfloat16)
    b = jax.numpy.ones((8192, 8192), jax.numpy.bfloat16)
    at.resolve_gemm_like("gemm_rs", lambda *a_, **k_: None, GemmRsConfig,
                         at.GEMM_RS_CAND_DIMS, GemmRsConfig(), a, b,
                         mesh, "tp", {})
    tiles = [(c.bm, c.bn, c.bk) for c in seen["cands"]
             if isinstance(c, GemmRsConfig)]
    assert (256, 256, 256) in tiles
    assert infeasible not in tiles


def test_gemm_like_footprint_dims_mapping():
    import jax.numpy as jnp

    from triton_distributed_tpu.tune import autotuner as at

    d = at._gemm_like_footprint_dims("ag_gemm", 512, 1024, 2048, 4,
                                     jnp.bfloat16)
    assert (d["m_loc"], d["k"], d["n_loc"]) == (128, 2048, 256)
    d = at._gemm_like_footprint_dims("gemm_rs", 512, 1024, 2048, 4,
                                     jnp.bfloat16)
    assert (d["m_loc"], d["k_loc"], d["n_dim"]) == (128, 512, 1024)


def test_all_matmul_resolve_paths_share_the_pruned_candidate_list(
        monkeypatch):
    """The winner cache is keyed by a digest of the candidate LIST, so
    the transparent ``matmul(config=None)`` path, ``matmul_callable``,
    and the measuring ``_matmul_resolve`` must all consume the SAME
    pruned list — a one-sided prune would silently split the cache the
    moment anything is pruned (review finding on this PR)."""
    import jax.numpy as jnp

    from triton_distributed_tpu.ops import matmul as mm
    from triton_distributed_tpu.tune import autotuner as at

    # plant an infeasible tile so pruning actually changes the list
    monkeypatch.setattr(
        at, "matmul_backend_candidates",
        lambda m, n, k: [at.XlaBackend(), (512, 512, 512),
                         (2048, 2048, 2048)])
    monkeypatch.setenv("TDT_AUTOTUNE", "0")
    seen = []
    real = at.resolve_config

    def spy(name, key, candidates, default, make_thunk, **kw):
        seen.append(list(candidates))
        return default

    monkeypatch.setattr(at, "resolve_config", spy)
    m = n = k = 8192
    a = jnp.ones((m, k), jnp.bfloat16)
    b = jnp.ones((k, n), jnp.bfloat16)
    mm.matmul(a, b)                                   # transparent path
    mm.matmul_callable(a, b)                          # hot-loop path
    monkeypatch.setattr(at, "resolve_config", real)
    pruned = at.matmul_candidates_pruned(m, n, k, a.dtype)
    assert (2048, 2048, 2048) not in pruned
    assert seen[0] == seen[1] == pruned


def test_fused_mlp_resolve_paths_share_the_pruned_candidate_list(
        monkeypatch):
    import jax.numpy as jnp

    from triton_distributed_tpu.ops import fused_decode as fd
    from triton_distributed_tpu.tune import autotuner as at

    seen = []

    def spy(name, key, candidates, default, make_thunk, **kw):
        seen.append(list(candidates))
        return default

    monkeypatch.setattr(at, "resolve_config", spy)
    fd._resolve_fused_mlp("fused_mlp_ar", 8, 2048, 768, 2048, 8,
                          jnp.bfloat16, lambda c: None, tracing=True)
    assert seen[0] == at.fused_mlp_candidates_pruned(
        8, 2048, 768, 2048, 8, jnp.bfloat16)
