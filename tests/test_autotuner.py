"""Contextual autotuner: winner selection, failure skipping, persistence
(reference ``autotuner.py`` behavior)."""

import json
import time

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tune import Autotuner, tuned_matmul


def test_picks_fastest_candidate(tmp_path):
    tuner = Autotuner(path=str(tmp_path / "cache.json"))
    calls = []

    def make_thunk(c):
        def thunk():
            calls.append(c)
            time.sleep(0.002 * c)  # candidate value = its cost
            return jnp.zeros(())
        return thunk

    res = tuner.tune("toy", ("k",), [3, 1, 2], make_thunk, iters=2)
    assert res.config == 1
    assert not res.from_cache
    # second call: memory cache, no re-timing
    n_calls = len(calls)
    res2 = tuner.tune("toy", ("k",), [3, 1, 2], make_thunk, iters=2)
    assert res2.config == 1 and res2.from_cache
    assert len(calls) == n_calls


def test_failing_candidates_skipped(tmp_path):
    tuner = Autotuner(path=str(tmp_path / "cache.json"))

    def make_thunk(c):
        if c.startswith("bad"):
            def boom():
                raise ValueError("invalid tile")
            return boom
        return lambda: jnp.zeros(())

    res = tuner.tune("toy", ("k2",), ["bad", "good"], make_thunk, iters=1)
    assert res.config == "good"

    with pytest.raises(RuntimeError, match="every candidate failed"):
        tuner.tune("toy", ("k3",), ["bad", "bad2"], make_thunk, iters=1)

    # a single candidate short-circuits without measuring (nothing to pick)
    probed = []
    res1 = tuner.tune("toy", ("k4",), ["only"],
                      lambda c: (lambda: probed.append(c)), iters=1)
    assert res1.config == "only" and res1.from_cache and not probed


def test_persistence_round_trip(tmp_path):
    path = str(tmp_path / "cache.json")
    t1 = Autotuner(path=path)
    t1.tune("toy", ("k",), [10, 1], lambda c: (lambda: time.sleep(0.001 * c)),
            iters=1)
    with open(path) as f:
        disk = json.load(f)
    assert list(disk.values()) == [1]

    # a fresh tuner (new process analogue) reuses the persisted winner
    timed = []
    t2 = Autotuner(path=path)
    res = t2.tune("toy", ("k",), [10, 1],
                  lambda c: (lambda: timed.append(c)), iters=1)
    assert res.config == 1 and res.from_cache and not timed


def test_tuned_matmul_correct(tmp_path, monkeypatch):
    import jax

    from triton_distributed_tpu.tune import autotuner as at

    # fresh global tuner: the module-level one memoizes the user's REAL
    # disk cache on first load, which would leak into/out of this test
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "m.json")))

    a = jax.random.normal(jax.random.key(0), (256, 128), jnp.float32)
    b = jax.random.normal(jax.random.key(1), (128, 256), jnp.float32)
    got = tuned_matmul(a, b)
    want = jnp.matmul(a, b)
    assert np.allclose(np.asarray(got), np.asarray(want), atol=1e-4,
                       rtol=1e-4)


def test_tuned_collective_ops_correct(tmp_path, monkeypatch):
    """tuned_ag_gemm / tuned_gemm_rs sweep real collective invocations and
    return correct results with the winning config."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh
    from triton_distributed_tpu.tune import autotuner as at
    from triton_distributed_tpu.tune import tuned_ag_gemm, tuned_gemm_rs

    # fresh global tuner with an isolated cache file: the module-level one
    # memoizes whatever disk cache it loaded first
    monkeypatch.setattr(at, "_GLOBAL", at.Autotuner(path=str(tmp_path / "c.json")))
    mesh = make_mesh({TP_AXIS: 4}, devices=jax.devices()[:4])
    m, k, n = 4 * 24, 96, 4 * 40
    a = jax.random.normal(jax.random.key(0), (m, k), jnp.float32) * 0.1
    b = jax.random.normal(jax.random.key(1), (k, n), jnp.float32) * 0.1
    a_ag = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_ag = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    out = tuned_ag_gemm(a_ag, b_ag, mesh, TP_AXIS)
    want = np.asarray(a) @ np.asarray(b)
    assert np.allclose(np.asarray(jax.device_get(out)), want, atol=1e-3,
                       rtol=1e-3)

    a_rs = jax.device_put(a, NamedSharding(mesh, P(None, TP_AXIS)))
    b_rs = jax.device_put(b, NamedSharding(mesh, P(TP_AXIS, None)))
    out2 = tuned_gemm_rs(a_rs, b_rs, mesh, TP_AXIS)
    assert np.allclose(np.asarray(jax.device_get(out2)), want, atol=1e-3,
                       rtol=1e-3)


def test_sol_fraction_reported(tmp_path):
    """A perf_model estimate turns the winner's time into a SOL fraction
    on fresh tunes (reference: its perf models feed the autotuner)."""
    tuner = Autotuner(path=str(tmp_path / "sol.json"))

    def make_thunk(c):
        def thunk():
            time.sleep(0.001)
            return jnp.zeros(())
        return thunk

    res = tuner.tune("toy_sol", ("k",), [1, 2], make_thunk, iters=2,
                     sol_ms=0.5)
    assert res.sol_fraction is not None and 0 < res.sol_fraction <= 1.5
    # cached result carries no fresh measurement -> no fraction
    res2 = tuner.tune("toy_sol", ("k",), [1, 2], make_thunk, iters=2,
                      sol_ms=0.5)
    assert res2.from_cache and res2.sol_fraction is None
