"""AG-GEMM vs golden `all_gather + matmul` (reference ``test_ag_gemm.py``:
golden via torch.distributed all_gather_into_tensor + torch.matmul)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.core.mesh import TP_AXIS, make_mesh, shard
from triton_distributed_tpu.core.utils import assert_allclose, rand_tensor
from triton_distributed_tpu.ops import AgGemmConfig, ag_gemm


def _golden(a, b):
    return jnp.dot(
        a.astype(jnp.float32), b.astype(jnp.float32)
    )


@pytest.mark.parametrize("m,k,n,dtype", [
    (64, 128, 256, jnp.float32),
    (128, 256, 512, jnp.bfloat16),
])
def test_ag_gemm_matches_golden(mesh8, m, k, n, dtype):
    a = rand_tensor((m, k), dtype, scale=0.1)
    b = rand_tensor((k, n), dtype, scale=0.1)
    a_s = shard(mesh8, a, TP_AXIS)
    b_s = shard(mesh8, b, None, TP_AXIS)
    c = ag_gemm(a_s, b_s, mesh8, TP_AXIS,
                config=AgGemmConfig(bm=32, bn=64, bk=64))
    assert c.shape == (m, n)
    tol = 1e-4 if dtype == jnp.float32 else 5e-2
    assert_allclose(c.astype(jnp.float32), _golden(a, b).astype(c.dtype),
                    atol=tol, rtol=tol, name="ag_gemm")


def test_ag_gemm_return_gathered(mesh8):
    a = rand_tensor((64, 128), jnp.float32, scale=0.1)
    b = rand_tensor((128, 256), jnp.float32, scale=0.1)
    c, ag = ag_gemm(shard(mesh8, a, TP_AXIS), shard(mesh8, b, None, TP_AXIS),
                    mesh8, TP_AXIS, config=AgGemmConfig(bm=8, bn=128, bk=128),
                    return_gathered=True)
    assert_allclose(ag, a, atol=0, rtol=0, name="gathered_a")
    assert_allclose(c, _golden(a, b).astype(c.dtype), atol=1e-4, rtol=1e-4,
                    name="c")


@pytest.mark.parametrize("nranks", [2, 3])
def test_ag_gemm_small_rings(nranks):
    # 2 ranks is the exact shape of the interpret-mode occupancy deadlock
    # found in round 1 (VERDICT.md weak #2): keep it covered.
    mesh = make_mesh({TP_AXIS: nranks}, devices=jax.devices()[:nranks])
    m, k, n = 16 * nranks, 128, 128 * nranks
    a = rand_tensor((m, k), jnp.float32, scale=0.1)
    b = rand_tensor((k, n), jnp.float32, scale=0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    c = ag_gemm(a_s, b_s, mesh, TP_AXIS)
    assert_allclose(c, _golden(a, b).astype(c.dtype), atol=1e-3, rtol=1e-3,
                    name=f"ag_gemm-{nranks}")


def test_ag_gemm_single_device():
    mesh1 = make_mesh({TP_AXIS: 1}, devices=jax.devices()[:1])
    a = rand_tensor((16, 128), jnp.float32)
    b = rand_tensor((128, 128), jnp.float32)
    c = ag_gemm(a, b, mesh1, TP_AXIS)
    assert_allclose(c, _golden(a, b).astype(c.dtype), atol=1e-4, rtol=1e-4)


def test_ag_gemm_multi_axis():
    mesh = make_mesh({"dp": 2, "tp": 4})
    a = rand_tensor((64, 128), jnp.float32, scale=0.1)
    b = rand_tensor((128, 256), jnp.float32, scale=0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P("tp")))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, "tp")))
    c = ag_gemm(a_s, b_s, mesh, "tp", config=AgGemmConfig(bm=16, bn=64, bk=64))
    assert_allclose(c, _golden(a, b).astype(c.dtype), atol=1e-4, rtol=1e-4,
                    name="ag_gemm-multiaxis")


@pytest.mark.parametrize("nring", [3, 4, 8])
def test_ag_gemm_bidir_golden(nring):
    """Bidirectional fused ring (both ICI directions) vs dense golden."""
    mesh = make_mesh({TP_AXIS: nring}, devices=jax.devices()[:nring])
    m, k, nn = 8 * nring, 64, 16 * nring
    a = rand_tensor((m, k), jnp.float32, scale=0.1)
    b = rand_tensor((k, nn), jnp.float32, scale=0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    out = ag_gemm(a_s, b_s, mesh, bidir=True)
    golden = jnp.matmul(a.astype(jnp.float32), b.astype(jnp.float32))
    assert_allclose(out.astype(jnp.float32), golden, atol=1e-3, rtol=1e-3,
                    name=f"ag_gemm-bidir-n{nring}")


def test_ag_gemm_bidir_repeat_and_matches_uni():
    """Repeat invocations drain cleanly and both ring directions agree."""
    nring = 4
    mesh = make_mesh({TP_AXIS: nring}, devices=jax.devices()[:nring])
    m, k, nn = 8 * nring, 64, 16 * nring
    a = rand_tensor((m, k), jnp.float32, scale=0.1)
    b = rand_tensor((k, nn), jnp.float32, scale=0.1)
    a_s = jax.device_put(a, NamedSharding(mesh, P(TP_AXIS, None)))
    b_s = jax.device_put(b, NamedSharding(mesh, P(None, TP_AXIS)))
    o1 = ag_gemm(a_s, b_s, mesh, bidir=True)
    o2 = ag_gemm(a_s, b_s, mesh, bidir=True)
    o_uni = ag_gemm(a_s, b_s, mesh, bidir=False)
    assert_allclose(o1, o2, atol=0, rtol=0, name="bidir-repeat")
    assert_allclose(o1, o_uni, atol=1e-5, rtol=1e-5, name="bidir-vs-uni")
