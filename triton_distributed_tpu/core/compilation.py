"""Kernel compilation policy: one switch between real-TPU Mosaic compilation
and CPU interpret-mode simulation.

The reference ships an entire codegen backend per vendor
(``backends/nvidia/backend/compiler.py:355-736`` stages ttir->ttgir->llir->ptx
->cubin and links NVSHMEM bitcode).  On TPU that whole layer collapses into
Pallas -> Mosaic, so the only policy left is *how* a kernel is executed:

- on TPU: compiled by Mosaic (optionally with a VMEM limit / cost estimate);
- on CPU: executed under TPU interpret mode, which simulates HBM/VMEM,
  local+remote DMA, and semaphores — this is what makes every distributed
  test runnable on a laptop-style 8-device virtual mesh (a capability the
  reference lacks: its tests require N physical GPUs, SURVEY.md section 4).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
from jax.experimental.pallas import tpu as pltpu

from . import platform


def interpret_params(
    *,
    detect_races: bool = False,
    dma_execution_mode: str = "eager",
) -> pltpu.InterpretParams:
    return pltpu.InterpretParams(
        detect_races=detect_races,
        dma_execution_mode=dma_execution_mode,
    )


_race_detection = {"enabled": False}

_shims_installed = {"done": False}


def _install_interpret_shims() -> None:
    """Make ``pltpu.emit_pipeline`` usable under CPU interpret mode.

    The pipeline emitter asks the backend for the TPU generation to pick VMEM
    tilings; on the CPU backend there is no TPU, so we pin a v5-class answer
    (tilings are a performance detail — interpret mode only checks
    semantics).  Scoped to the CPU backend; on real TPU nothing is touched.
    """
    if _shims_installed["done"] or not platform.on_cpu():
        return
    from jax._src.pallas.mosaic import pipeline as _mosaic_pipeline

    # fail loudly if a jax upgrade moves the symbol (a silent no-op here
    # would surface as an unrelated backend-query error inside emit_pipeline)
    assert hasattr(_mosaic_pipeline, "_get_tpu_generation"), (
        "jax internals changed: update core.compilation._install_interpret_shims"
    )
    _mosaic_pipeline._get_tpu_generation = lambda: 5
    _shims_installed["done"] = True


def enable_race_detection(on: bool = True) -> None:
    """Globally enable interpret-mode race detection for subsequent kernels.

    TPU-native stand-in for the reference's reliance on external
    ``compute-sanitizer`` (SURVEY.md section 5): the Pallas interpreter's
    vector-clock race detector flags unsynchronized accesses to the same
    buffer across devices/cores.
    """
    _race_detection["enabled"] = bool(on)


def protocol_verify_enabled() -> bool:
    """Whether the build-time static protocol gate is on (``TDT_VERIFY=1``).

    The second, CPU-only half of the correctness policy next to interpret
    -mode race detection: when enabled, every collective kernel builder
    runs the ``tdt.analysis`` verifier (signal balance / deadlock freedom /
    write-overlap / divergence, docs/static_analysis.md) for its family at
    its rank count BEFORE constructing the pallas_call, and a violation
    raises instead of compiling a broken protocol."""
    from .utils import env_flag

    return env_flag("TDT_VERIFY")


def explore_depth() -> int | None:
    """The ``TDT_VERIFY`` explore-depth knob, ``TDT_VERIFY_EXPLORE``:
    unset/``0`` = canonical verification only (None); an integer ``N`` =
    additionally model-check every schedule class under the DPOR
    explorer with a preemption bound of N (``analysis.explore``);
    ``exact`` = exhaustive (no bound, encoded as -1).  The canonical
    run is sound for deadlock; the explorer closes the multi-producer
    credit-matching gap (docs/static_analysis.md "Schedule
    exhaustiveness")."""
    import os

    raw = os.environ.get("TDT_VERIFY_EXPLORE", "").strip().lower()
    if raw in ("", "0", "off", "no", "false"):
        return None
    if raw == "exact":
        return -1
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"TDT_VERIFY_EXPLORE={raw!r}: expected an integer preemption "
            f"bound, 'exact', or unset") from None
    # any negative means exact — the -1 encoding maybe_verify_build
    # documents (clamping to bound 0 would silently WEAKEN a gate the
    # operator asked to be exhaustive)
    return -1 if v < 0 else v


def verify_protocol(family: str, num_ranks: int) -> None:
    """Build-time hook the collective op builders call: no-op unless
    ``TDT_VERIFY=1`` (one env read + int compare), else delegates to
    ``analysis.registry.maybe_verify_build`` (memoized per family x ranks;
    raises ``analysis.ProtocolViolationError`` on violation).  With
    ``TDT_VERIFY_EXPLORE`` set, the schedule-exhaustive explorer runs on
    top of the canonical checks at that preemption depth."""
    if num_ranks < 2 or not protocol_verify_enabled():
        return
    from ..analysis import maybe_verify_build

    maybe_verify_build(family, num_ranks, explore=explore_depth())


# Physical VMEM per TensorCore (v5-class parts: 128 MiB) and Mosaic's
# DEFAULT scoped-VMEM compile budget (16 MiB — what a kernel gets unless
# its pallas_call raises ``vmem_limit_bytes``, see ``ops.group_gemm``/
# ``ops.matmul``).  The static footprint lint (``analysis.footprint``)
# validates tile working sets against these; ``TDT_VMEM_BUDGET`` (bytes)
# overrides the physical number for other parts.
VMEM_BYTES = 128 * 2**20
MOSAIC_DEFAULT_VMEM_BYTES = 16 * 2**20


def vmem_budget_bytes() -> int:
    import os

    raw = os.environ.get("TDT_VMEM_BUDGET", "")
    if not raw:
        return VMEM_BYTES
    try:
        return int(raw)
    except ValueError:
        # silently falling back would green-light the lint against the
        # wrong part's budget — the masking failure the PRUNED-marker
        # discipline exists to prevent
        raise ValueError(
            f"TDT_VMEM_BUDGET={raw!r}: expected bytes as an integer"
        ) from None


def interpret_mode() -> pltpu.InterpretParams | bool:
    """The value to pass as ``pallas_call(..., interpret=...)``.

    False on real TPU (compile with Mosaic); InterpretParams on CPU.
    """
    if platform.on_cpu():
        _install_interpret_shims()
        return interpret_params(detect_races=_race_detection["enabled"])
    return False


# Stable collective_id per kernel family: Mosaic keys the global barrier
# semaphore by collective_id, so two different collectives in flight must not
# share one (the reference's analogue is distinct symmetric flag arrays per
# op context).  The registry is FIXED, not first-call-ordered: every process
# of a multi-host program must agree on family -> id regardless of which
# kernels it happens to trace first.
_COLLECTIVE_IDS: dict[str, int] = {
    "test": 0,
    "allgather": 1,
    "reduce_scatter": 2,
    "allreduce": 3,
    "all_to_all": 4,
    "ag_gemm": 5,
    "gemm_rs": 6,
    "ag_group_gemm": 7,
    "moe_reduce_rs": 8,
    "flash_decode": 9,
    "sp_ag_attention": 10,
    "ep_dispatch": 11,
    "ep_combine": 12,
    "barrier": 13,
    "gemm_ar": 14,
    "tutorial": 15,   # user-authored kernels in tutorials/ share one family
    "fused_mlp_ar": 16,   # decode megakernel reductions (ops/fused_decode)
    # the persistent multi-layer decode loop (ops/persistent_decode):
    # all 2L chained ring reductions live in ONE kernel, one family
    "persistent_decode": 17,
}


def collective_id(family: str) -> int:
    try:
        return _COLLECTIVE_IDS[family]
    except KeyError:
        raise KeyError(
            f"unknown collective family {family!r}; register it in "
            "core.compilation._COLLECTIVE_IDS (ids must be identical on "
            "every process)"
        ) from None


def xla_gemm_options(scoped_vmem_kib: int = 0) -> dict:
    """Per-computation XLA compile options for XLA-backend GEMM dispatch.

    The second half of the compile policy: ops with an XLA backend
    candidate (``ops.matmul``, ``ops.group_gemm``) are compiled as their
    own jitted computation with a tuned scoped-VMEM budget.  Measured on
    the v5e (interleaved per-round ratios vs default-flag XLA): raising
    ``xla_tpu_scoped_vmem_limit_kib`` from the 16 MB default lets XLA pick
    deeper GEMM tilings — 1.8-2.1x at 4096^3 bf16, 1.05-2.4x at
    8192x2048x7168, 1.12-1.64x for ``lax.ragged_dot`` at the MoE bench
    shape, parity-to-1.05x at 7168^3 (already at 95%+ of peak).  The
    per-shape choice is the autotuner's, not a global flag flip: a raised
    scoped budget can regress other fusions, so it is applied only to the
    dispatched GEMM computation itself (``scoped_vmem_kib=0`` = default
    flags).  On the CPU (interpret) backend the TPU flag does not exist:
    a planted/simulated XlaBackend winner degrades to default flags.
    """
    if not scoped_vmem_kib or platform.on_cpu():
        return {}
    return {"xla_tpu_scoped_vmem_limit_kib": int(scoped_vmem_kib)}


def compiler_params(
    *,
    collective: bool = True,
    collective_id: int = 0,
    vmem_limit_bytes: int | None = None,
    dimension_semantics: tuple[str, ...] | None = None,
) -> pltpu.CompilerParams:
    kw: dict[str, Any] = dict(has_side_effects=collective)
    if collective:
        kw["collective_id"] = collective_id
    if vmem_limit_bytes is not None:
        kw["vmem_limit_bytes"] = vmem_limit_bytes
    if dimension_semantics is not None:
        kw["dimension_semantics"] = dimension_semantics
    return pltpu.CompilerParams(**kw)


def jit_shard_map(fn, mesh, in_specs, out_specs, *, static_argnums=(), donate_argnums=()):
    """``jax.jit(jax.shard_map(fn))`` with the conventions all our collective
    kernels need (check_vma off: Pallas outputs have no vma annotations)."""
    mapped = jax.shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )
    return jax.jit(mapped, static_argnums=static_argnums, donate_argnums=donate_argnums)


def aot_compile(jitted, *example_args, **example_kwargs):
    """Ahead-of-time compile (reference: the 1.7k-LoC AOT C toolchain
    ``tools/compile_aot.py`` + ``triton_aot_runtime.cc``).  Delegates to
    ``tools.aot`` — the one home of the AOT path, including serialization."""
    from ..tools.aot import aot_compile as _aot

    return _aot(jitted, *example_args, **example_kwargs)


def reset_interpret_state() -> None:
    """Reset interpreter shared state after an exception inside a kernel."""
    try:
        from jax._src.pallas.mosaic.interpret import interpret_pallas_call as _ipc

        _ipc.reset_tpu_interpret_mode_state()  # type: ignore[attr-defined]
    except Exception:
        pass


@functools.cache
def supports_remote_dma() -> bool:
    """Whether device-to-device Pallas RDMA is available (multi-device mesh)."""
    return jax.device_count() > 1 or platform.on_cpu()


def interpret_supported() -> bool:
    """Whether this jax build carries the APIs the interpret-mode path
    needs (``pltpu.InterpretParams``/``CompilerParams``, ``jax.shard_map``).
    Older builds (e.g. 0.4.37) lack them; capability-gated tests use this
    one probe instead of per-file hasattr copies."""
    return (hasattr(pltpu, "InterpretParams")
            and hasattr(pltpu, "CompilerParams")
            and hasattr(jax, "shard_map"))
