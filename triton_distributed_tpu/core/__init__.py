from . import compilation, mesh, platform, symm, utils
