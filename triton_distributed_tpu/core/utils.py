"""Host-side utilities: tensor factories, comparison, timing, printing.

TPU-native counterpart of the reference's ``python/triton_dist/utils.py``
grab-bag: ``_make_tensor`` (:217), ``assert_allclose`` (:865-894),
``perf_func`` (:269-281), ``dist_print`` (:284).
"""

from __future__ import annotations

import contextlib
import functools
import os
import sys
import time
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from . import platform


def env_flag(name: str) -> bool:
    """The one truthy-env-flag convention for feature gates (``TDT_OBS``,
    ``TDT_VERIFY``): unset/empty/0/off/false/no mean OFF, anything else ON."""
    return os.environ.get(name, "").lower() not in ("", "0", "off", "false",
                                                    "no")


def rand_tensor(
    shape: tuple[int, ...],
    dtype=jnp.bfloat16,
    *,
    key: jax.Array | None = None,
    scale: float = 1.0,
) -> jax.Array:
    """Random test tensor (reference ``_make_tensor``): normal data scaled to
    keep bf16 matmuls in a numerically friendly range."""
    if key is None:
        # Derive a fresh key from the process-wide seed + a counter.
        rand_tensor._counter += 1  # type: ignore[attr-defined]
        key = jax.random.fold_in(platform.base_key(), rand_tensor._counter)  # type: ignore[attr-defined]
    x = jax.random.normal(key, shape, dtype=jnp.float32) * scale
    return x.astype(dtype)


rand_tensor._counter = 0  # type: ignore[attr-defined]


def assert_allclose(
    actual,
    expected,
    *,
    atol: float = 1e-2,
    rtol: float = 1e-2,
    max_mismatch_report: int = 10,
    name: str = "tensor",
) -> None:
    """Comparison with a rich mismatch dump (reference ``utils.py:865-894``)."""
    a = np.asarray(jax.device_get(actual), dtype=np.float64)
    e = np.asarray(jax.device_get(expected), dtype=np.float64)
    if a.shape != e.shape:
        raise AssertionError(f"{name}: shape mismatch {a.shape} vs {e.shape}")
    err = np.abs(a - e)
    tol = atol + rtol * np.abs(e)
    # NaN-safe: treat any non-finite error (NaN/inf in actual or expected
    # disagreement) as a mismatch — `err > tol` alone is False for NaN.
    bad = ~(err <= tol)
    if bad.any():
        idxs = np.argwhere(bad)
        n_bad = len(idxs)
        lines = [
            f"{name}: {n_bad}/{a.size} mismatched "
            f"({100.0 * n_bad / a.size:.3f}%), atol={atol} rtol={rtol}",
            f"  max abs err {err.max():.6g} at {tuple(np.unravel_index(err.argmax(), a.shape))}",
        ]
        for i in idxs[:max_mismatch_report]:
            t = tuple(i)
            lines.append(f"  [{t}] actual={a[t]:.6g} expected={e[t]:.6g} err={err[t]:.6g}")
        raise AssertionError("\n".join(lines))


def dist_print(*args, rank: int | None = None, allowed_ranks: Iterable[int] | None = None, **kw):
    """Per-process serialized printing (reference ``dist_print``).

    In JAX's SPMD model there is one Python process per host (not per device),
    so this filters by process index rather than device rank.
    """
    me = jax.process_index()
    if rank is not None and me != rank:
        return
    if allowed_ranks is not None and me not in set(allowed_ranks):
        return
    print(f"[proc {me}/{jax.process_count()}]", *args, **kw)
    sys.stdout.flush()


def sync(x) -> None:
    """Force device completion of ``x``.

    ``jax.block_until_ready`` alone is not trustworthy on tunneled device
    backends (observed on the axon TPU tunnel: it returns immediately); a
    one-element ``device_get`` genuinely round-trips.  One tiny fetch is done
    per addressable shard of every leaf so every participating device's queue
    is drained, not just device 0's.  Costs fixed host<->device latency —
    cancel it with slope timing (``perf_func``).
    """
    jax.block_until_ready(x)
    for leaf in jax.tree_util.tree_leaves(x):
        if hasattr(leaf, "addressable_shards"):
            for s in leaf.addressable_shards:
                jax.device_get(s.data.reshape(-1)[:1])
        elif hasattr(leaf, "reshape"):
            jax.device_get(leaf.reshape(-1)[:1])
        else:
            jax.device_get(leaf)


def timed_run(func: Callable[[], object], k: int) -> float:
    """Wall seconds for k back-to-back calls of ``func`` ended by one
    :func:`sync` — the building block of slope timing (``perf_func`` and
    ``bench.py`` both difference two of these to cancel the sync cost)."""
    t0 = time.perf_counter()
    o = None
    for _ in range(k):
        o = func()
    sync(o)
    return time.perf_counter() - t0


def interleaved_time_samples(
    thunks: dict,
    iters: int,
    rounds: int,
    target_window_s: float | None = None,
    abba: bool = True,
) -> dict:
    """Per-thunk ``(slope_dt, raw_dt)`` second/iter samples over
    INTERLEAVED rounds — the shared measurement core of ``bench.py`` and
    ``tune.autotuner``.

    Thunks timed back to back within a round share the chip's thermal and
    clock state, so cross-thunk ranking survives the drift that makes
    sequential per-thunk timing unreliable; the order alternates between
    rounds so a monotonic drift biases no thunk.

    Two estimators per sample, for two different consumers:

    - ``slope_dt`` — the slope between a 1-iter and a (1+k)-iter
      :func:`timed_run`, cancelling the fixed sync/tunnel cost:
      UNBIASED per-iter time, the right basis for absolute TFLOP/s.
      But the two extra 1-iter calibrations inject independent noise
      into every sample: even a thunk timed against ITSELF shows +-3%
      interleaved-median ratio spread (round-4 measurement).
    - ``raw_dt`` — the (1+k)-iter window divided by 1+k, sync cost
      included.  Biased HIGH as an absolute, but in a cross-thunk RATIO
      the shared fixed cost is common mode: near-tie ratios read 1.0
      almost exactly, and a true gap is understated by only
      ~sync/window (~10% of the gap at 0.4 s windows) — the right
      basis for ratios and for crowning decisions.

    With ``target_window_s``, each thunk's trip count is RE-calibrated
    every round from its latest raw per-iter time, holding every
    thunk's window at that duration as the chip's clock drifts — equal
    window durations are what make the raw estimator's fixed-cost share
    common mode (a one-time round-0 calibration let windows drift apart
    and a literal self-vs-self pair drew 0.85; the trip cap is high
    enough that sub-0.1 ms thunks still reach a 0.4 s window).  Callers
    warm thunks up first, apply their own non-positive-sample policy,
    and should DROP round 0 of the raw samples (taken before the
    window calibration, so its sync share is not yet equalized).
    ``abba=False`` skips the doubled windows for slope-only callers.
    """
    samples = {name: [] for name in thunks}
    trips = {name: iters for name in thunks}
    for r in range(rounds):
        order = list(thunks.items())
        if r % 2:
            order.reverse()
        if abba and len(order) == 2 and r > 0:
            # two-thunk rounds run the ABBA scheme: windows at times
            # 0,t,2t,3t give each thunk the same MEAN timestamp
            # (0+3t == t+2t), so a LINEAR thermal/clock drift across the
            # round cancels exactly in the raw ratio — the chip
            # oscillates on second timescales, and adjacent single
            # windows were capturing the oscillation as a phantom 5%
            # engine difference.  (Round 0 keeps the simple order while
            # trip counts calibrate.)
            (na, fa), (nb, fb) = order
            ka, kb = trips[na], trips[nb]
            # the 1-iter slope calibrations sit ADJACENT to the long
            # window they are differenced against (a1/cal_a, b2/cal_b) so
            # slope absolutes see ~one window of thermal drift, not the
            # whole round; placed symmetrically (after a1 and after b2)
            # the two equal-length calibrations shift both engines' mean
            # window timestamps by the same amount, preserving the
            # linear-drift cancellation of the raw ABBA ratio
            a1 = timed_run(fa, 1 + ka)
            cal_a = timed_run(fa, 1)
            b1 = timed_run(fb, 1 + kb)
            b2 = timed_run(fb, 1 + kb)
            cal_b = timed_run(fb, 1)
            a2 = timed_run(fa, 1 + ka)
            slope_a = (a1 - cal_a) / ka
            slope_b = (b2 - cal_b) / kb
            raw_a = (a1 + a2) / (2 * (1 + ka))
            raw_b = (b1 + b2) / (2 * (1 + kb))
            samples[na].append((slope_a, raw_a))
            samples[nb].append((slope_b, raw_b))
            if target_window_s:
                # RE-calibrate trips every round (see the docstring's
                # equal-window rationale)
                for nm, raw_dt in ((na, raw_a), (nb, raw_b)):
                    if raw_dt > 0:
                        trips[nm] = max(iters, min(
                            int(target_window_s / raw_dt), 8192))
            continue
        for name, thunk in order:
            k = trips[name]
            t_long = timed_run(thunk, 1 + k)
            dt = (t_long - timed_run(thunk, 1)) / k
            raw_dt = t_long / (1 + k)
            samples[name].append((dt, raw_dt))
            if target_window_s and raw_dt > 0:
                # every round, not just round 0 (see the ABBA branch) —
                # and from the RAW per-iter time: the slope dt's
                # independent calibration noise can read tiny-positive
                # and explode the trip count to the cap
                trips[name] = max(iters,
                                  min(int(target_window_s / raw_dt), 8192))
    return samples


def interleaved_slope_samples(
    thunks: dict,
    iters: int,
    rounds: int,
    target_window_s: float | None = None,
) -> dict:
    """The slope halves of :func:`interleaved_time_samples` (the
    original protocol; kept for callers that only need absolutes —
    ``abba=False`` skips the ratio-oriented doubled windows)."""
    both = interleaved_time_samples(thunks, iters, rounds, target_window_s,
                                    abba=False)
    return {name: [s for s, _ in xs] for name, xs in both.items()}


def perf_func(
    func: Callable[[], object],
    iters: int = 16,
    warmup_iters: int = 3,
    *,
    name: str | None = None,
) -> tuple[object, float]:
    """Wall-clock timing of a device thunk, returning (last_output, ms/iter).
    When observability is on (``TDT_OBS=1``) each call also lands one
    sample in the ``timer_ms{name="perf_func/<name>"}`` histogram.

    Reference ``perf_func`` (``utils.py:269-281``) uses CUDA events; here the
    per-iteration time is the two-point slope between a 1-iteration and a
    (1+iters)-iteration run, each ended by one :func:`sync` — the fixed
    sync/tunnel overhead cancels, surviving backends where async dispatch
    can't be flushed precisely.
    """
    out = func()
    for _ in range(warmup_iters - 1):
        out = func()
    sync(out)

    run = functools.partial(timed_run, func)
    t1 = min(run(1), run(1))
    t2 = min(run(1 + iters), run(1 + iters))
    dt = max(t2 - t1, 1e-9) / max(iters, 1)
    ms = dt * 1e3
    from .. import obs

    if obs.enabled():
        # existing benches populate telemetry for free: one histogram
        # sample per perf_func call, keyed by the caller's name for the
        # thunk (or the thunk's own name when anonymous)
        label = name or getattr(func, "__qualname__", None) \
            or getattr(func, "__name__", "<thunk>")
        obs.observe_timer(f"perf_func/{label}", ms)
    return out, ms


@contextlib.contextmanager
def timer(name: str = ""):
    t0 = time.perf_counter()
    yield
    ms = (time.perf_counter() - t0) * 1e3
    dist_print(f"{name}: {ms:.3f} ms", rank=0)
    from .. import obs

    if obs.enabled():
        obs.observe_timer(name or "<anonymous>", ms)


def process_mean(values) -> list[float]:
    """Cross-process elementwise mean of a small float vector — identical
    on every process (the agreement primitive behind the autotuner's
    rank-synced winner choice and the link calibration's persisted
    numbers; divergent per-host values feeding method choice would
    launch MISMATCHED collectives across hosts).  Single-process: the
    values unchanged."""
    if jax.process_count() == 1:
        return [float(v) for v in values]
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        jnp.asarray(list(values), jnp.float32)
    )
    return [float(v) for v in
            np.asarray(gathered).reshape(-1, len(list(values))).mean(axis=0)]


def sleep_async(ms: float):
    """Straggler injection (reference ``utils.py:1010`` ``sleep_async``): a
    host-side delay a test can insert on one rank to simulate skew.  Device-
    side delay injection lives in the straggler option of allreduce."""
    time.sleep(ms / 1e3)


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


def clip_block(block: int, dim: int) -> int:
    """Largest sublane-aligned divisor of ``dim`` that is <= ``block`` — used
    to normalize tile-size configs to a problem.

    Prefers divisors that are multiples of the TPU sublane granule (8) so
    the tile stays legal for Mosaic's lane tiling on real hardware.  A
    *partial* unaligned tile (dim >= 8 with no aligned divisor) raises:
    CPU interpret mode would accept it silently and the misalignment would
    only surface as a mis-tiled kernel on real TPU — pad the operand to the
    8-row granule instead.  A single whole-dim tile (b == dim) is safe at
    any size: Mosaic pads a full dim to the granule."""
    b = min(block, dim)
    if dim >= 8:
        for cand in range(b, 7, -1):
            if dim % cand == 0 and cand % 8 == 0:
                return cand
    while dim % b:
        b -= 1
    if dim >= 8 and b < dim:
        raise ValueError(
            f"tile size {block} would clip to non-sublane-aligned {b} for "
            f"dim {dim} (no divisor that is a multiple of 8 and <= "
            f"{block}); pad the dimension to a multiple of 8"
        )
    return b


def round_up(a: int, b: int) -> int:
    return cdiv(a, b) * b
