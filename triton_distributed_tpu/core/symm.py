"""Symmetric buffers: the TPU answer to the NVSHMEM symmetric heap.

Reference model (``utils.py:122-171``): every rank allocates an identically
shaped tensor on a symmetric heap; any rank can address any peer's copy by
(rank, offset) (``nvshmem_create_tensor`` / ``get_peer_tensor``), and signal
flags live in separate symmetric u64 arrays.

TPU model: under `shard_map` every device executes the same program over its
own shard.  An array sharded so that each device holds the same local shape
IS a symmetric buffer: Pallas remote DMA addresses a peer's shard by logical
device id (``lang.primitives.remote_copy``), which is exactly ``symm_at``.
Signals are Pallas semaphores scoped to a kernel, or tiny int32 symmetric
arrays when a flag must persist across kernels.

Because Pallas semaphores do not outlive a kernel invocation, the reference's
"producer kernel signals, consumer kernel waits" split becomes either (a) one
fused kernel containing both sides (our default — see ``ops/``), or (b) a
persistent int32 flag array updated/polled by separate kernels (used by the
double-buffered layers, e.g. ``layers/allgather_layer.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass
class SymmetricBuffer:
    """A per-device identically-shaped workspace + its mesh placement.

    ``data`` is a global array whose per-device shard has shape
    ``local_shape``; kernel code addresses peers' shards via remote DMA.
    """

    data: jax.Array
    mesh: Mesh
    axis: str
    local_shape: tuple[int, ...]

    @property
    def num_ranks(self) -> int:
        return self.mesh.shape[self.axis]


def symm_buffer(
    mesh: Mesh,
    axis: str,
    local_shape: Sequence[int],
    dtype=jnp.bfloat16,
    *,
    fill: float | int = 0,
) -> SymmetricBuffer:
    """Allocate a symmetric workspace: every device along ``axis`` holds a
    ``local_shape`` shard (reference: ``nvshmem_create_tensor``)."""
    local_shape = tuple(int(d) for d in local_shape)
    n = mesh.shape[axis]
    global_shape = (local_shape[0] * n, *local_shape[1:])
    spec = [None] * len(local_shape)
    spec[0] = axis
    arr = jnp.full(global_shape, fill, dtype=dtype)
    arr = jax.device_put(arr, NamedSharding(mesh, P(*spec)))
    return SymmetricBuffer(data=arr, mesh=mesh, axis=axis, local_shape=local_shape)


def symm_signal(mesh: Mesh, axis: str, n_flags: int = 1) -> SymmetricBuffer:
    """Persistent int32 signal flags, one row of ``n_flags`` per device
    (reference: symmetric u64 signal arrays, ``nvshmem_create_tensor`` with
    dtype uint64).  Values are counts, matching TPU counting-semaphore
    semantics rather than arbitrary magic values (SURVEY.md section 7,
    "Semaphore semantics mismatch")."""
    return symm_buffer(mesh, axis, (1, n_flags), dtype=jnp.int32, fill=0)
