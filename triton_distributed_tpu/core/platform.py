"""Platform/bootstrap layer: backend selection, distributed init, device query.

TPU-native replacement for the reference's runtime bring-up
(``python/triton_dist/utils.py:174-200`` ``initialize_distributed``: torchrun
env -> NCCL process group -> NVSHMEM UID init).  On TPU a single call to
:func:`initialize_distributed` covers all three: `jax.distributed.initialize`
is the rendezvous, XLA's SPMD runtime is the communication backend, and the
"symmetric heap" is simply the identically-shaped per-device shards of arrays
laid out by `jax.sharding` (see ``core/symm.py``).

This module also owns the CPU-simulation story (SURVEY.md section 4): any test
can run on a virtual N-device CPU mesh, in which case Pallas kernels execute
under TPU interpret mode (``core/compilation.py``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Sequence

import jax
import numpy as np

_DEFAULT_VIRTUAL_DEVICES = 8

# Spare virtual devices to request beyond the widest mesh (see force_cpu):
# spare devices = spare XLA client threads = interpret-mode kernels can make
# progress even when every mesh device's thread is blocked in a wait.
# 4 (round 5; was 2): programs mixing many compiled callback kernels with
# effects tokens (the AOT-serving engine tests) starved a 2-spare pool —
# observed as a worker-thread SIGABRT with every thread parked in the
# interpreter's _clean_up_shared_memory while the main thread sharded
# effect tokens; 4 spares runs the same programs reliably.
SPARE_VIRTUAL_DEVICES = 4

_initialized = False


def force_cpu(num_devices: int = _DEFAULT_VIRTUAL_DEVICES) -> None:
    """Force the CPU backend with ``num_devices`` virtual devices.

    Must be called before any JAX backend is initialized.  Note: a plain
    ``JAX_PLATFORMS=cpu`` env var is not sufficient in environments whose
    sitecustomize force-selects a platform via ``jax.config``; we therefore
    set the config explicitly as well.

    IMPORTANT — request MORE devices than the widest mesh you will build
    (see ``SPARE_VIRTUAL_DEVICES``).  The XLA CPU
    client's execution thread pool is sized by the device count; an
    interpret-mode collective kernel occupies one pool thread per mesh
    device while blocked in a semaphore wait, and kernel progress (buffer
    allocation's device-to-host copies, async dispatch of producer
    computations) needs at least one FREE pool thread.  A mesh at exact
    platform occupancy can therefore deadlock — observed as threads parked
    in ``semaphore_wait`` and ``_allocate_buffer``.  ``make_mesh`` leaves
    extra devices idle, so over-provisioning is always safe.
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    want = f"--xla_force_host_platform_device_count={num_devices}"
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", want, flags
        )
    else:
        flags = (flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = flags
    jax.config.update("jax_platforms", "cpu")


def backend() -> str:
    return jax.default_backend()


def on_cpu() -> bool:
    return backend() == "cpu"


def on_tpu() -> bool:
    # The "axon" platform is a tunneled TPU PJRT plugin; treat it as TPU.
    return backend() in ("tpu", "axon")


def is_multichip() -> bool:
    return jax.device_count() > 1


@dataclasses.dataclass(frozen=True)
class DistContext:
    """Handle returned by :func:`initialize_distributed`.

    Plays the role of the reference's ``TP_GROUP`` (a torch ProcessGroup): a
    value tests thread through to ops.  On TPU the actual communicator is the
    mesh + XLA runtime, so this only carries identity/topology facts.
    """

    rank: int                 # process index (multi-host), not device index
    world: int                # number of processes
    devices: tuple[jax.Device, ...]
    local_devices: tuple[jax.Device, ...]

    @property
    def num_devices(self) -> int:
        return len(self.devices)


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    seed: int | None = 42,
) -> DistContext:
    """Bring up the distributed runtime.

    Single-host (including the CPU-simulated mesh and the single-chip case):
    a no-op beyond seeding.  Multi-host (a real pod slice or multi-host CPU
    rendezvous): calls ``jax.distributed.initialize``, which replaces both the
    NCCL bootstrap and the NVSHMEM UID exchange of the reference.

    Environment variables honored (mirroring torchrun-style launches):
    ``COORDINATOR_ADDRESS``, ``NUM_PROCESSES``, ``PROCESS_ID``.
    """
    global _initialized

    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if num_processes is None and "NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["NUM_PROCESSES"])
    if process_id is None and "PROCESS_ID" in os.environ:
        process_id = int(os.environ["PROCESS_ID"])

    # _initialized tracks only the multi-host runtime: a prior single-host
    # call (e.g. for seeding) must not swallow a later real rendezvous.
    if coordinator_address and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True

    if seed is not None:
        init_seed(seed)

    return DistContext(
        rank=jax.process_index(),
        world=jax.process_count(),
        devices=tuple(jax.devices()),
        local_devices=tuple(jax.local_devices()),
    )


def finalize_distributed() -> None:
    """Tear down the multi-host runtime (reference: ``utils.py:153-155``)."""
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False


_seed_state: dict[str, int] = {"seed": 42}


def init_seed(seed: int) -> None:
    """Deterministic seeding (reference: ``utils.py:75-94`` ``init_seed``).

    JAX PRNG is already deterministic and functional; we keep a process-wide
    base seed so helpers like ``rand_tensor`` can derive per-call keys, and
    seed numpy for host-side shuffles.
    """
    _seed_state["seed"] = int(seed)
    np.random.seed(seed)


def base_key() -> jax.Array:
    return jax.random.key(_seed_state["seed"])


def device_kind() -> str:
    d = jax.devices()[0]
    return getattr(d, "device_kind", d.platform)


def topology_summary() -> dict:
    """Topology probe (reference: NVLink/PCIe/NUMA probes ``utils.py:587-862``).

    On TPU the relevant facts are the mesh-relevant ones: device count, chip
    kind, process count, and (when available) the physical coords that tell
    you which axes ride ICI vs DCN.
    """
    devs = jax.devices()
    info: dict = {
        "backend": backend(),
        "num_devices": len(devs),
        "num_processes": jax.process_count(),
        "device_kind": device_kind(),
    }
    coords = []
    for d in devs:
        coords.append(getattr(d, "coords", None))
    if any(c is not None for c in coords):
        info["coords"] = coords
    return info


def devices_array(shape: Sequence[int] | None = None) -> np.ndarray:
    """Device grid for building a Mesh; defaults to a 1-D grid of all devices."""
    devs = np.array(jax.devices())
    if shape is not None:
        devs = devs.reshape(tuple(shape))
    return devs
