"""Mesh construction and axis conventions.

The reference derives its communicator structure from torch process groups
(``utils.py:190`` builds one TP group over all ranks) plus NVSHMEM teams, and
encodes intra/inter-node hierarchy in a ``CommScope`` enum
(``DistributedAttrDefs.td:45``).  On TPU the equivalent object is a
`jax.sharding.Mesh`: axes over ICI within a slice, an outer axis over DCN for
multi-slice.  This module standardizes axis names so kernels, layers, and
models agree:

- ``tp``: tensor parallel (ICI, innermost — highest-bandwidth axis)
- ``ep``: expert parallel (may alias tp for inference MoE)
- ``sp``: sequence/context parallel
- ``dp``: data parallel (outermost; may ride DCN across slices)
- ``pp``: pipeline parallel (not in the reference's scope; provided for mesh
  completeness so users can lay out their own schedules)
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

TP_AXIS = "tp"
EP_AXIS = "ep"
SP_AXIS = "sp"
DP_AXIS = "dp"
PP_AXIS = "pp"

# Intra-slice axes ride ICI; inter-slice axes ride DCN. Mirrors the
# reference's CommScope{GPU, INTRA_NODE, INTER_NODE} distinction.  An axis
# literally named "dcn" (or "dcn_*" — the convention the hierarchical
# tutorials/tests use for the outer level) is always inter-slice.
ICI_AXES = (TP_AXIS, EP_AXIS, SP_AXIS)
DCN_AXES = (DP_AXIS, PP_AXIS, "dcn")


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh from named axis sizes, e.g. ``{"dp": 2, "tp": 4}``.

    Axis order in the mapping is the device-grid order (outermost first).
    Defaults to a 1-D ``tp`` mesh over all devices — the reference's default
    "one TP group over WORLD_SIZE" shape.
    """
    from . import platform

    explicit_devices = devices is not None
    devs = np.array(devices if devices is not None else jax.devices())
    if axis_sizes is None:
        n = devs.size
        if not explicit_devices and platform.on_cpu():
            # On the virtual CPU platform, a default-sized mesh leaves the
            # spare devices idle (see below); callers wanting all devices
            # pass explicit sizes.
            n = max(1, n - platform.SPARE_VIRTUAL_DEVICES)
        axis_sizes = {TP_AXIS: n}
    names = tuple(axis_sizes.keys())
    sizes = tuple(int(s) for s in axis_sizes.values())
    total = int(np.prod(sizes))
    if total > devs.size:
        raise ValueError(
            f"mesh axes {dict(axis_sizes)} require {total} devices, "
            f"have {devs.size}"
        )
    if total < devs.size and (explicit_devices or not platform.on_cpu()):
        # An explicitly passed device list must be covered exactly (a
        # mismatch means a typo'd axis map, and silently shrinking a test's
        # ring would mask the bugs it exists to catch).  On real hardware
        # the same applies to the default list: a smaller-than-world mesh
        # on multi-host would silently exclude some processes' devices.
        raise ValueError(
            f"mesh axes {dict(axis_sizes)} cover {total} of {devs.size} "
            f"devices; pass an explicit `devices=` slice of exactly "
            f"{total} to build a sub-mesh deliberately"
        )
    # CPU backend with the default device list: extra devices beyond the
    # mesh are deliberately allowed and left idle — spare devices keep
    # spare XLA client threads, which interpret-mode collective kernels
    # need to make progress when every mesh device's execution thread
    # blocks in a semaphore wait (exact-occupancy starvation; see
    # platform.force_cpu).
    return Mesh(devs[:total].reshape(sizes), names)


def tp_mesh(tp: int | None = None) -> Mesh:
    # tp=None routes through make_mesh's default sizing so the CPU
    # platform's spare-device subtraction applies (deadlock avoidance).
    return make_mesh({TP_AXIS: tp} if tp is not None else None)


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard(mesh: Mesh, x: jax.Array, *spec) -> jax.Array:
    """Place ``x`` with the given PartitionSpec on the mesh."""
    return jax.device_put(x, sharding(mesh, *spec))


def is_dcn_axis(axis: str) -> bool:
    """Whether collectives over this axis are expected to cross DCN.

    Used by ops to choose hierarchical algorithms (Pallas RDMA over ICI,
    XLA collectives over DCN) — the TPU analogue of the reference's 2D/3D
    intra+inter-node kernel hierarchies (``allgather.py:442-601``).
    """
    return axis in DCN_AXES or axis.startswith("dcn_")


def axis_spans_processes(mesh: Mesh, axis: str) -> bool:
    """Whether stepping along ``axis`` crosses process (host) boundaries —
    the topological test for DCN hops, independent of axis naming."""
    import numpy as np

    ax = list(mesh.axis_names).index(axis)
    n = mesh.devices.shape[ax]
    devs = np.moveaxis(mesh.devices, ax, 0).reshape(n, -1)
    procs = np.asarray(
        [[d.process_index for d in row] for row in devs]
    )
    return bool((procs != procs[:1]).any())


import functools as _functools


@_functools.lru_cache(maxsize=None)
def wire_class(mesh: Mesh, axis: str) -> str:
    """"dcn" when hops along ``axis`` ride the cross-slice network (by
    naming convention OR by actually spanning processes), else "ici".
    The policy input for wire-cost decisions (e.g. the MoE fp8 wire
    codec, whose measured net win is positive on DCN and negative on
    ICI — BENCH r04 ``net_us_per_token_hop_*``).  Memoized per (mesh,
    axis): it sits on every collective's contextual-key path (ISSUE 10)
    and the process-spanning probe is an O(devices) Python scan of a
    quantity that never changes for a live mesh."""
    if is_dcn_axis(axis) or axis_spans_processes(mesh, axis):
        return "dcn"
    return "ici"
