"""triton_distributed_tpu: a TPU-native compute–communication overlapping
framework (JAX / XLA / Pallas / pjit).

Brand-new implementation of the capabilities of Triton-distributed
(ByteDance Seed) for TPU: tile-granular signal/wait primitives woven into
Pallas kernels, a library of overlapped collectives and distributed
attention/MoE ops, tensor-/expert-/sequence-parallel layers, and an
end-to-end Qwen3-style inference engine — all designed for the TPU execution
model (MXU, VMEM pipelines, ICI remote DMA, XLA SPMD) rather than translated
from the reference's CUDA/NVSHMEM architecture.

Layer map (vs SURVEY.md section 1):

- ``core``     runtime bring-up, mesh, symmetric buffers, test/perf utils
- ``lang``     the distributed primitive vocabulary used inside kernels
- ``comm``     collectives as fused Pallas kernels (AG, RS, AR, A2A)
- ``ops``      overlapped compute kernels (AG-GEMM, GEMM-RS, MoE, attention)
- ``layers``   TP/EP/SP layers as functional pytree modules
- ``models``   model configs, KV cache, Qwen3, inference engine
- ``parallel`` shard_map/pjit conventions and sharding rules
- ``tune``     contextual autotuner
- ``tools``    profiling, AOT serialization, perf (SOL) models
- ``obs``      runtime observability: metrics registry, span tracing,
               exporters, overlap-efficiency reporting (``TDT_OBS=1``)
- ``analysis`` static protocol verifier for the collective kernels:
               signal balance / deadlock freedom / write-overlap /
               divergence, no hardware or interpret mode needed
               (``TDT_VERIFY=1`` build gate, ``scripts/tdt_lint.py``)
- ``resilience`` runtime fault tolerance: primitives-level fault
               injection, bounded-wait watchdog with named-semaphore
               timeout diagnoses, retry/degrade/circuit-breaker ladder
               (``TDT_RESILIENCE=1`` runtime gate,
               ``scripts/tdt_lint.py --faults``)

(host-side helpers live in ``core.utils``; there is deliberately no
separate ``utils`` package)
"""

__version__ = "0.1.0"

from . import core
from .core import mesh as mesh_lib
from .core.platform import (
    initialize_distributed,
    finalize_distributed,
    force_cpu,
    init_seed,
)
from .core.mesh import make_mesh, tp_mesh, TP_AXIS, EP_AXIS, SP_AXIS, DP_AXIS, PP_AXIS
from .core.utils import assert_allclose, dist_print, perf_func, rand_tensor
from .core.symm import symm_buffer, symm_signal, SymmetricBuffer
from .layers import TPAttn, TPAttnParams, TPMLP, TPMLPParams, rms_norm
from . import obs
from . import analysis
from . import resilience
from . import serve
