"""Priority-classed DCN transfers: the shared slow wire as a scheduled
resource.

The disaggregated serving topology (``serve.router``) puts two traffic
classes on the SAME inter-slice DCN port: latency-critical KV-handoff
transfers (a decode slot is idle until its pages arrive) and bulk
streams (chunked-prefill shipments, hierarchical-collective phases,
checkpoint traffic).  FIFO sharing is exactly the failure FAST names
(PAPERS.md, "FAST: An Efficient Scheduler for All-to-All GPU
Communication"): a latency-critical transfer queued behind a multi-MB
bulk stream pays the whole stream's serialization.  The discipline here
is FAST's, applied at the port: two strict-priority classes with
CHUNK-granular preemption — bulk streams are emitted in bounded chunks,
and a :data:`LATENCY` transfer arriving mid-stream waits at most the
residual of the chunk currently on the wire, never the stream.

On this container the port is MODELED (:class:`PriorityDCNWire` — a
deterministic queueing model priced from the calibrated link table,
``tools.calibrate``), which is what the handoff plane's fault matrix
and the ``bench.py serve_disagg`` smoke run against; the class
constants and the ``send()`` contract are the interface a real
multi-slice transport implements, and the slice-gated bench claims arm
on the first real capture (the PR-10 pattern).
"""

from __future__ import annotations

import threading

# the two wire classes.  LATENCY preempts BULK at chunk granularity;
# within a class the port is FIFO.
LATENCY = 0
BULK = 1

# bulk streams are emitted in bounded chunks so a latency-class arrival
# waits at most one chunk's serialization (the preemption grain)
DEFAULT_CHUNK_BYTES = 1 << 20


def dcn_wire_ms(nbytes: int, *, gbps: float | None = None,
                hop_us: float | None = None) -> float:
    """Serialization + hop time for one DCN transfer, from the measured
    link calibration when one exists (``tools.calibrate``), else the
    documented defaults — the same rate the watchdog's SOL pricing
    reads."""
    from ..tools import calibrate, perf_model

    if gbps is None:
        gbps = perf_model.dcn_gbps()
    if hop_us is None:
        cal = calibrate.load_calibration()
        hop_us = cal.dcn_hop_us if cal is not None and cal.dcn_hop_us \
            else 20.0
    return nbytes / (gbps * 1e9) * 1e3 + hop_us / 1e3


class PriorityDCNWire:
    """Deterministic queueing model of ONE shared DCN port with two
    strict-priority classes.

    State is two per-class backlogs (milliseconds of serialization
    already committed to the wire); ``send`` returns the modeled
    completion latency of the new transfer — queue wait + its own
    serialization + the hop — and adds its serialization to the class
    backlog.  ``tick(ms)`` drains the backlogs as modeled time passes
    (latency class first: it owns the port).  The preemption contract:

    - a :data:`LATENCY` send waits for the latency backlog ahead of it
      plus AT MOST one chunk's residual of the bulk stream (the chunk
      currently on the wire finishes; the rest of the stream yields);
    - a :data:`BULK` send waits for everything.

    Thread-safe; deterministic (no wall clock — the router advances the
    model with its own step cadence, so seeded replays reproduce).
    """

    def __init__(self, *, gbps: float | None = None,
                 hop_us: float | None = None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        from ..tools import calibrate, perf_model

        self.gbps = float(gbps) if gbps else perf_model.dcn_gbps()
        if hop_us is None:
            cal = calibrate.load_calibration()
            hop_us = cal.dcn_hop_us if cal is not None and cal.dcn_hop_us \
                else 20.0
        self.hop_us = float(hop_us)
        self.chunk_bytes = int(chunk_bytes)
        self._lock = threading.Lock()
        self._backlog_ms = {LATENCY: 0.0, BULK: 0.0}
        self.sent_bytes = {LATENCY: 0, BULK: 0}
        self.sends = {LATENCY: 0, BULK: 0}

    def _ser_ms(self, nbytes: int) -> float:
        return nbytes / (self.gbps * 1e9) * 1e3

    def send(self, nbytes: int, *, priority: int = BULK) -> float:
        """Enqueue one transfer; returns its modeled completion latency
        in ms (queue wait + serialization + hop)."""
        if priority not in (LATENCY, BULK):
            raise ValueError(f"unknown priority class {priority!r}")
        if nbytes < 0:
            raise ValueError(f"negative payload {nbytes}")
        ser = self._ser_ms(nbytes)
        hop = self.hop_us / 1e3
        with self._lock:
            if priority == LATENCY:
                wait = self._backlog_ms[LATENCY] + min(
                    self._backlog_ms[BULK], self._ser_ms(self.chunk_bytes))
            else:
                wait = self._backlog_ms[LATENCY] + self._backlog_ms[BULK]
            self._backlog_ms[priority] += ser
            self.sent_bytes[priority] += int(nbytes)
            self.sends[priority] += 1
        return wait + ser + hop

    def tick(self, ms: float) -> None:
        """Advance the model clock: ``ms`` of wire time drains the
        backlogs, latency class first (strict priority)."""
        if ms <= 0:
            return
        with self._lock:
            take = min(ms, self._backlog_ms[LATENCY])
            self._backlog_ms[LATENCY] -= take
            self._backlog_ms[BULK] = max(
                0.0, self._backlog_ms[BULK] - (ms - take))

    def backlog_ms(self, priority: int) -> float:
        with self._lock:
            return self._backlog_ms[priority]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "gbps": self.gbps,
                "chunk_bytes": self.chunk_bytes,
                "backlog_ms": dict(self._backlog_ms),
                "sent_bytes": dict(self.sent_bytes),
                "sends": dict(self.sends),
            }
