"""AllGather collectives as Pallas TPU kernels.

TPU-native re-design of the reference's AllGather zoo
(``python/triton_dist/kernels/nvidia/allgather.py:46-601`` — copy-engine
full-mesh push/pull, 1D ring, 2D ring; ``low_latency_allgather.py:47-994`` —
device push kernels with LL flag-in-data protocol, multimem).  On TPU:

- the copy-engine producer stream becomes in-kernel async remote DMA chains;
- LL flag-in-data packing becomes DMA completion semaphores (no flags woven
  into payload — the DMA system signals per-transfer);
- multimem/NVLS broadcast has no ICI equivalent; the bidirectional ring uses
  both ICI directions for full bisection bandwidth instead;
- method auto-selection by message size mirrors
  ``get_auto_all_gather_method`` (``allgather.py:57``).

All variants gather dim 0.  Each kernel is written to be *consumable at chunk
granularity*: received chunks land directly in their final offset of the
output buffer and are individually gated by a per-chunk DMA semaphore — the
property the fused AG-GEMM consumer (``ops/ag_gemm.py``) relies on, exactly
like the reference consumer GEMM waits on per-rank flags
(``allgather_gemm.py:146-215``).
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import TP_AXIS
from ..lang import primitives as dl
from ..lang.primitives import Team
from . import ring
from .ring import chunk as _chunk


class AllGatherMethod(enum.Enum):
    """Mirrors the reference's ``AllGatherMethod`` enum (``allgather.py:46``);
    TPU has no intra/inter-node split at this level (DCN hierarchy lives in
    ``hierarchical_all_gather``)."""

    AUTO = "auto"
    PUSH_1SHOT = "push_1shot"   # full-mesh push: lowest latency, small msgs
    RING_1D = "ring_1d"         # unidirectional ring: simple, one ICI dir
    RING_BIDIR = "ring_bidir"   # bidirectional ring: full bisection bandwidth


# One-shot push beats the ring below the link's bandwidth-delay product.
# The crossover comes from ``tools.calibrate`` when a calibration run has
# measured the live topology (reference probes NICs the same way,
# ``comm_perf_model.py:92-129``); its cold-start default is the 256 KiB
# "MTU-ish" constant rounds 1-4 pinned by reasoning (the reference
# switches methods by size the same way, ``allgather.py:57-78``).


def choose_method(nbytes_per_shard: int, num_ranks: int) -> AllGatherMethod:
    from ..tools import calibrate

    if num_ranks <= 2:
        return AllGatherMethod.PUSH_1SHOT
    if nbytes_per_shard <= calibrate.push_bytes_threshold():
        return AllGatherMethod.PUSH_1SHOT
    return AllGatherMethod.RING_BIDIR


def _wait_recv_chunk(out_ref, recv_sems, chunk_idx, m):
    dl.wait_recv(_chunk(out_ref, chunk_idx, m), recv_sems.at[chunk_idx])


def _wait_send(out_ref, send_sem, chunk_idx, m):
    dl.wait_send(_chunk(out_ref, chunk_idx, m), send_sem)


def _ag_push_kernel(team: Team, m, x_ref, out_ref, local_sem, send_sem, recv_sems):
    """One-shot full-mesh push (reference ``All2All_IntraNode`` copy-engine
    path ``allgather.py:81-139`` and NVSHMEM broadcast push kernels in
    ``low_latency_allgather.py``): every rank RDMAs its shard into all peers'
    output at its own offset, then waits for all n-1 incoming shards."""
    me, n = team.rank(), team.size
    # own shard into place (async local DMA; overlaps the barrier)
    local = dl.local_copy(x_ref, _chunk(out_ref, me, m), local_sem)
    dl.collective_prologue(team)
    local.wait()
    # push to every peer (static loop; ICI routes concurrently)
    for off in range(1, n):
        dst = jax.lax.rem(me + off, n)
        dl.remote_copy(
            _chunk(out_ref, me, m),
            _chunk(out_ref, me, m),
            send_sem,
            recv_sems.at[me],
            team.device_id(dst),
        )
    for off in range(1, n):
        src = jax.lax.rem(me + n - off, n)
        _wait_recv_chunk(out_ref, recv_sems, src, m)
    for _ in range(n - 1):
        _wait_send(out_ref, send_sem, me, m)


def _ag_ring_kernel(team: Team, m, x_ref, out_ref, local_sem, send_sem, recv_sems):
    """Unidirectional ring (reference ``Ring1D_IntraNode``,
    ``allgather.py:141-200``): each step forwards the chunk received last step
    to the right neighbor; n-1 steps, each chunk takes rank-distance hops."""
    me, n = team.rank(), team.size
    _, right = team.neighbor_ranks()
    right_id = team.device_id(right)
    local = dl.local_copy(x_ref, _chunk(out_ref, me, m), local_sem)
    dl.collective_prologue(team, neighbors_only=True)
    local.wait()
    ring.ag_ring_phase(team, out_ref, m, send_sem, recv_sems, right_id)
    ring.ag_ring_drain(team, out_ref, m, send_sem)


def _ag_ring_bidir_kernel(
    team: Team, m, x_ref, out_ref, local_sem, send_sems, recv_sems
):
    """Bidirectional ring: clockwise stream carries ceil((n-1)/2) chunks,
    counter-clockwise floor((n-1)/2), using both ICI directions — the TPU
    answer to the reference's NUMA-aware 2D ring (``allgather.py:203-260``),
    where the hierarchy exists to use both NVLink directions/planes.
    Schedule + drain live in ``ring.bidir_ring_phase`` (shared with the
    fused AG-GEMM's bidir variant)."""
    me, n = team.rank(), team.size
    local = dl.local_copy(x_ref, _chunk(out_ref, me, m), local_sem)
    dl.collective_prologue(team, neighbors_only=True)
    local.wait()
    ring.bidir_ring_phase(team, out_ref, m, send_sems, recv_sems)
    ring.bidir_ring_drain(team, out_ref, m, send_sems)


_KERNELS = {
    AllGatherMethod.PUSH_1SHOT: (_ag_push_kernel, False),
    AllGatherMethod.RING_1D: (_ag_ring_kernel, False),
    AllGatherMethod.RING_BIDIR: (_ag_ring_bidir_kernel, True),
}


def resolve_method(
    method: AllGatherMethod,
    shard_shape: tuple[int, ...],
    dtype,
    num_ranks: int,
) -> AllGatherMethod:
    """Resolve AUTO to a concrete method from per-shard bytes — the ONE
    home of the size heuristic (used by the flat entry, the hierarchical
    entry, and the persistent layer)."""
    if method != AllGatherMethod.AUTO:
        return method
    nbytes = int(jnp.dtype(dtype).itemsize)
    for d in shard_shape:
        nbytes *= d
    return choose_method(nbytes, num_ranks)


def _build_ag_call(
    mesh: Mesh,
    axis: str,
    method: AllGatherMethod,
    shard_shape: tuple[int, ...],
    dtype: jnp.dtype,
):
    """The bare per-device Pallas call (no shard_map wrapper) — reused by
    the flat and hierarchical entries.  (The persistent layer builds its
    own variant with a workspace-aliased output; it shares the kernel
    bodies via ``_KERNELS``.)"""
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("allgather", n)   # TDT_VERIFY=1 static gate
    m_local = shard_shape[0]
    kern, two_send_sems = _KERNELS[method]
    kernel = functools.partial(kern, team, m_local)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * m_local, *shard_shape[1:]), dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),                       # local copy
            pltpu.SemaphoreType.DMA((2,)) if two_send_sems
            else pltpu.SemaphoreType.DMA(()),                  # send(s)
            pltpu.SemaphoreType.DMA((n,)),                     # per-chunk recv
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("allgather"),
        ),
        interpret=compilation.interpret_mode(),
    )


@functools.lru_cache(maxsize=None)
def _build_all_gather(
    mesh: Mesh,
    axis: str,
    method: AllGatherMethod,
    shard_shape: tuple[int, ...],
    dtype: jnp.dtype,
):
    """Build + jit the collective once per (mesh, axis, method, shape, dtype).

    Cached so steady-state calls hit the jit cache instead of re-tracing
    (jax.jit caches by function identity; a fresh closure every call would
    recompile every call)."""
    call = _build_ag_call(mesh, axis, method, shard_shape, dtype)
    ndim = len(shard_shape)
    return compilation.jit_shard_map(
        call, mesh,
        in_specs=P(axis, *([None] * (ndim - 1))),
        out_specs=P(*([None] * ndim)),
    )


def hierarchical_all_gather(
    x: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    method: AllGatherMethod = AllGatherMethod.AUTO,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Two-level AllGather (ICI Pallas ring per slice + DCN XLA gather).
    Canonical implementation: ``comm.hierarchical`` (ISSUE 10 — the
    observe/survive-wrapped, DCN-wire-codec-composing entry); this name
    stays importable here for the historic call sites."""
    from .hierarchical import hierarchical_all_gather as _hier

    return _hier(x, mesh, inner_axis, outer_axis, method=method,
                 wire_dtype=wire_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _all_gather_core(mesh, axis, method, x):
    shard_shape = (x.shape[0] // mesh.shape[axis], *x.shape[1:])
    fn = _build_all_gather(mesh, axis, method, shard_shape,
                           jnp.dtype(x.dtype))
    return fn(x)


def _ag_fwd(mesh, axis, method, x):
    return _all_gather_core(mesh, axis, method, x), jnp.zeros((0,), x.dtype)


def _ag_bwd(mesh, axis, method, wit, dout):
    # In GLOBAL semantics the gather is the identity (it only changes the
    # sharding from P(axis) to replicated), so the adjoint is the
    # identity too; XLA turns the replicated-to-sharded cotangent into a
    # local slice.  (The per-device RS-adjoint picture lives inside the
    # fused ops' VJPs, which compute global matmul adjoints.)
    return (dout.astype(wit.dtype),)


_all_gather_core.defvjp(_ag_fwd, _ag_bwd)


def all_gather(
    x: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    method: AllGatherMethod = AllGatherMethod.AUTO,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Gather dim 0 of ``x`` (sharded over ``axis``) on every device.

    Entry point mirroring the reference's host-side dispatchers
    (``allgather.py`` / ``fast_allgather``).  Returns the replicated gathered
    array; golden equivalent is ``jax.lax.all_gather(..., tiled=True)``.
    Differentiable: in global semantics the gather only changes sharding,
    so the adjoint is the identity (the ring-RS adjoints live inside the
    fused ops' VJPs).

    ``wire_dtype``: "bf16" (ship the payload as-is), "int8"/"fp8" (pack
    per-row quantized payload + scale sidecar into one u8 message —
    ``comm.quantized``), or "auto" (the contextual tuner picks per
    shape/ranks/WIRE CLASS; bf16 is the never-lose baseline).

    ``axis`` may be a 2-tuple ``(outer, inner)`` (outermost first) on a
    2D multi-slice mesh: the call routes to the hierarchical entry
    (``comm.hierarchical`` — ICI ring per slice, DCN gather across).
    """
    if isinstance(axis, (tuple, list)):
        from . import hierarchical

        outer_axis, inner_axis = axis
        return hierarchical.hierarchical_all_gather(
            x, mesh, inner_axis, outer_axis, method=method,
            wire_dtype=wire_dtype)
    n = mesh.shape[axis]
    if n == 1:
        return x
    if wire_dtype != "bf16":
        from ..tune.autotuner import is_tracer as _q_is_tracer
        from . import quantized as _q

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "ag_wire", (tuple(x.shape), str(x.dtype)), mesh, axis,
                lambda wd: (lambda: all_gather(x, mesh, axis,
                                               method=method,
                                               wire_dtype=wd)),
                tracing=_q_is_tracer(x),
            )
        if wire_dtype != "bf16":
            return _q.quantized_all_gather(
                x, mesh, axis, wire_dtype=wire_dtype, method=method)

    m_total = x.shape[0]
    if m_total % n:
        raise ValueError(f"dim0 {m_total} not divisible by {axis}={n}")
    m_local = m_total // n
    shard_shape = (m_local, *x.shape[1:])

    if method == AllGatherMethod.AUTO:
        # the size threshold is only a default: when the contextual tuner
        # may measure (eager, real hardware), the method choice itself is
        # tuner-resolved per shape class (VERDICT weak #7: thresholds are
        # MTU-ish constants a measurement should replace).  The key
        # carries the axis's WIRE CLASS (ISSUE 10): a method crowned on
        # the ICI torus must never leak onto a DCN edge.
        from ..core import mesh as mesh_lib, platform
        from ..tune.autotuner import is_tracer, resolve_config

        cands = [AllGatherMethod.PUSH_1SHOT, AllGatherMethod.RING_BIDIR,
                 AllGatherMethod.RING_1D]
        method = resolve_config(
            "ag_method",
            (shard_shape, str(x.dtype), n, mesh_lib.wire_class(mesh, axis),
             platform.device_kind()),
            cands, resolve_method(method, shard_shape, x.dtype, n),
            lambda mth: (lambda: all_gather(x, mesh, axis, method=mth)),
            tracing=is_tracer(x),
        )
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer as _is_tracer

    import math

    shard_bytes = math.prod(shard_shape) * jnp.dtype(x.dtype).itemsize
    core = lambda: _all_gather_core(mesh, axis, method, x)  # noqa: E731
    # eager calls only for both wrappers: a traced call runs this Python
    # once, at trace time — obs would record one phantom sample per
    # compile, and a host-side watchdog cannot bound a traced subcall
    eager = not _is_tracer(x)
    if eager and resilience.integrity.enabled():
        # consumer-side checksum verification (TDT_INTEGRITY=1,
        # docs/robustness.md "Data integrity"): AG delivers shards
        # verbatim, so the per-chunk fold is byte-exact and a mismatch
        # names its producing peer (quarantine-attributable)
        core = resilience.integrity.checked(
            "all_gather", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_gather(
                "all_gather", x, out, n))
    if eager and resilience.enabled():
        core = resilience.guarded(
            "all_gather", core, family="allgather", ranks=n,
            payload_bytes=shard_bytes,
            fallback=lambda: resilience.fallbacks.xla_all_gather(
                x, mesh, axis),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        # every method moves each shard through n-1 per-rank hops
        return obs.comm_call(
            "all_gather", core,
            payload_bytes=shard_bytes, wire_bytes=shard_bytes * (n - 1),
            chunks=n - 1, method=method.value, ranks=n,
        )
    return core()
