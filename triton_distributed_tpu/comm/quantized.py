"""Quantized collective payloads: int8/fp8 wire variants of AG/RS/AR/A2A.

The low-precision plane of ISSUE 9 (ROADMAP item 4): bytes on the wire
are the congestion currency of large-scale collectives (the
lightweight-NoC-collective payload-size argument, arXiv:2603.26438), and
the reference ships fp8 A2A payloads as a production optimization
(SURVEY section 7).  This module generalizes the MoE layer's one-off
codec into first-class collective variants:

- **Quantize at the producer, dequantize at the consumer**: every
  variant packs rows into the shared one-message wire format
  (``lang.quant.pack_rows`` — payload bytes + f32 scale sidecar riding
  the SAME chunk) on the sending rank and dequantizes on arrival.  No
  full-precision payload ever crosses the wire.
- **AG / A2A** ride the existing Pallas collective entries on the
  PACKED u8 array — so the integrity plane folds the *quantized* wire
  bytes, the resilience ladder guards the real transfer, and the obs
  wire-byte counters record what actually moved (a flipped sidecar byte
  is a checksum mismatch like any payload byte).
- **RS / AR** cannot reduce quantized payloads in the ring (int8 sums
  overflow; e4m3 sums round) — they use the ONE-SHOT exchange shape
  instead: each rank packs its n chunk-contributions, an equal-split
  all-to-all lands every rank's chunk ``j`` on rank ``j``, and the
  consumer dequantizes and reduces the n partials in f32.  AR appends a
  quantized AG of the reduced chunk (the two-shot shape with both hops
  quantized).  Each chunk crosses the wire once per direction — the
  same 2(n-1)/n wire volume class as the bf16 two-shot, at half the
  bytes per element.
- **Error feedback** (the AR option): the quantization residual of each
  rank's contribution is returned to the caller and folded into the
  NEXT call's input, so chained quantized reductions do not drift
  (``lang.quant.ef_quantize_rows``).

Gradients: the packed u8 wire is an integer path whose cotangent would
be float0 — every entry here is custom-vjp'd with the straight-through
estimator (backward = the transport adjoint at full precision, ignoring
quantization error), the treatment ``layers.moe`` pioneered and now
consumes from here (one home for the STE custom-vjp machinery).

The ``wire_dtype`` axis is autotuner-selectable: the eager comm entries
accept ``wire_dtype="auto"`` and resolve {bf16, int8, fp8} per
(shape, ranks, WIRE CLASS) through :func:`resolve_wire_dtype` — the
winner is measured per topology, so an ICI torus (where the codec's
compute rarely pays) and a DCN edge (where it clearly does) crown
independently.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import TP_AXIS
from ..lang import quant

WIRE_DTYPES = quant.WIRE_DTYPES


def resolve_wire_dtype(name: str, shape_key: tuple, mesh: Mesh, axis: str,
                       make_thunk, *, tracing: bool) -> str:
    """The ``wire_dtype="auto"`` hook of the comm entries: {bf16, int8,
    fp8} through the contextual autotuner, keyed on shape AND the axis's
    wire class — a winner crowned on the ICI torus must never leak onto
    a DCN edge (ROADMAP item 3's contextual-key extension).  bf16 is
    the never-lose baseline the margins protect."""
    from ..core import mesh as mesh_lib, platform
    from ..tune.autotuner import resolve_config

    return resolve_config(
        name,
        (*shape_key, mesh.shape[axis], mesh_lib.wire_class(mesh, axis),
         platform.device_kind()),
        list(WIRE_DTYPES), "bf16", make_thunk, tracing=tracing,
    )


# ---------------------------------------------------------------------------
# quantized AllGather: pack -> u8 AG (Pallas ring/push) -> unpack


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _q_all_gather(mesh, axis, wire_dtype, method, x):
    from .allgather import all_gather

    h = x.shape[-1]
    packed = quant.pack_rows(x, wire_dtype)
    # the inner entry is the REAL wire: integrity folds the quantized
    # bytes, resilience guards the u8 transfer, obs counts u8 wire bytes
    gathered = all_gather(packed, mesh, axis, method=method)
    return quant.unpack_rows(gathered, h, wire_dtype, x.dtype)


def _q_ag_fwd(mesh, axis, wire_dtype, method, x):
    return _q_all_gather(mesh, axis, wire_dtype, method, x), \
        jnp.zeros((0,), x.dtype)


def _q_ag_bwd(mesh, axis, wire_dtype, method, wit, dout):
    # straight-through: in global semantics the gather is the identity
    # (sharding change only), and STE ignores the quantization error
    return (dout.astype(wit.dtype),)


_q_all_gather.defvjp(_q_ag_fwd, _q_ag_bwd)


def quantized_all_gather(
    x: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    wire_dtype: str = "fp8",
    method=None,
) -> jax.Array:
    """AllGather with a quantized wire: each rank's shard is packed
    (payload + scale sidecar in one u8 message), gathered through the
    Pallas collective, and dequantized on arrival.  Golden:
    ``quant.roundtrip_rows`` of each shard, gathered.  Differentiable
    (straight-through)."""
    if not quant.is_quantized(wire_dtype):
        from .allgather import AllGatherMethod, all_gather

        return all_gather(x, mesh, axis,
                          method=method or AllGatherMethod.AUTO)
    if mesh.shape[axis] == 1:
        return quant.roundtrip_rows(x, wire_dtype)
    from .allgather import AllGatherMethod

    return _q_all_gather(mesh, axis, wire_dtype,
                         method or AllGatherMethod.AUTO, x)


# ---------------------------------------------------------------------------
# quantized ReduceScatter / AllReduce: one-shot packed exchange +
# f32 consumer reduce (+ quantized AG return hop for AR)


@functools.lru_cache(maxsize=None)
def _build_q_rs(mesh: Mesh, axis: str, m_loc: int, r: int,
                wire_dtype: str, in_dtype, out_dtype):
    n = mesh.shape[axis]

    def local(x_loc):                       # (n*m_loc, r) local partial
        chunks = x_loc.reshape(n, m_loc, r)
        packed = quant.pack_rows(chunks, wire_dtype)   # (n, m_loc, w) u8
        # equal-split exchange: chunk j of every rank lands on rank j —
        # scale sidecars ride the same message as their payload rows
        recv = jax.lax.all_to_all(packed, axis, 0, 0)
        deq = quant.unpack_rows(recv, r, wire_dtype, jnp.float32)
        return deq.sum(axis=0).astype(out_dtype)       # (m_loc, r)

    return compilation.jit_shard_map(
        local, mesh, in_specs=P(axis, None), out_specs=P(axis, None))


@functools.lru_cache(maxsize=None)
def _build_q_ar(mesh: Mesh, axis: str, m_loc: int, r: int,
                wire_dtype: str, in_dtype, out_dtype, with_residual: bool):
    n = mesh.shape[axis]

    def exchange(q, scale):
        # ship EXACTLY the (q, scale) the residual was accounted
        # against (lang.quant.pack_quantized — the one sidecar home),
        # reduce the dequantized partials, then the quantized AG return
        # hop reassembles the full (n*m_loc, r) result on every rank
        recv = jax.lax.all_to_all(quant.pack_quantized(q, scale),
                                  axis, 0, 0)
        red = quant.unpack_rows(recv, r, wire_dtype, jnp.float32)
        red = red.sum(axis=0).astype(out_dtype)        # (m_loc, r)
        back = quant.pack_rows(red, wire_dtype)
        gathered = jax.lax.all_gather(back, axis, tiled=True)
        return quant.unpack_rows(gathered, r, wire_dtype, out_dtype)

    if with_residual:
        def local(x_loc, res_loc):
            q, scale, new_res = quant.ef_quantize_rows(
                x_loc.reshape(n, m_loc, r), wire_dtype,
                res_loc.reshape(n, m_loc, r))
            return exchange(q, scale), new_res.reshape(n * m_loc, r)

        return compilation.jit_shard_map(
            local, mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=(P(None, None), P(axis, None)))

    # the hot non-EF path (gemm_ar / fused_mlp_ar decode): no residual
    # input, no residual materialized
    def local_plain(x_loc):
        q, scale = quant.quantize_rows(
            x_loc.reshape(n, m_loc, r).astype(jnp.float32), wire_dtype)
        return exchange(q, scale)

    return compilation.jit_shard_map(
        local_plain, mesh,
        in_specs=P(axis, None), out_specs=P(None, None))


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _q_reduce_scatter(mesh, axis, wire_dtype, out_dtype, x):
    n = mesh.shape[axis]
    m_loc = x.shape[0] // (n * n)
    fn = _build_q_rs(mesh, axis, m_loc, x.shape[1], wire_dtype,
                     jnp.dtype(x.dtype), out_dtype)
    return fn(x)


def _q_rs_fwd(mesh, axis, wire_dtype, out_dtype, x):
    return _q_reduce_scatter(mesh, axis, wire_dtype, out_dtype, x), \
        jnp.zeros((0,), x.dtype)


def _q_rs_bwd(mesh, axis, wire_dtype, out_dtype, wit, dout):
    # straight-through: out = sum of stacked partials -> broadcast back
    n = mesh.shape[axis]
    return (jnp.tile(dout, (n, 1)).astype(wit.dtype),)


_q_reduce_scatter.defvjp(_q_rs_fwd, _q_rs_bwd)


def quantized_reduce_scatter(
    x: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    wire_dtype: str = "fp8",
    out_dtype=None,
) -> jax.Array:
    """ReduceScatter with a quantized wire (one-shot packed exchange;
    see module docstring).  Same contract as ``comm.reduce_scatter``:
    ``x`` global (n*M, R) stacked partials, returns (M, R) sharded.
    Golden: ``quant.reduce_roundtrip`` of the stacked chunk partials,
    scattered.  Differentiable (straight-through)."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    n = mesh.shape[axis]
    if not quant.is_quantized(wire_dtype):
        from .reduce_scatter import reduce_scatter

        return reduce_scatter(x, mesh, axis).astype(out_dtype)
    m_stack = x.shape[0]
    if m_stack % n or (m_stack // n) % n:
        raise ValueError(
            f"dim0 {m_stack} must be divisible by {axis}^2 = {n * n}")
    if n == 1:
        return quant.roundtrip_rows(x, wire_dtype, out_dtype=out_dtype)

    def make_verify(integrity):
        return lambda out: integrity.verify_reduce_q(
            f"reduce_scatter_{wire_dtype}", x, out, n, wire_dtype)

    return _wrapped(
        "reduce_scatter", mesh, axis, wire_dtype, x,
        lambda: _q_reduce_scatter(mesh, axis, wire_dtype, out_dtype, x),
        make_verify=make_verify,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _q_all_reduce(mesh, axis, wire_dtype, out_dtype, x, res):
    n = mesh.shape[axis]
    m_loc = x.shape[0] // (n * n)
    fn = _build_q_ar(mesh, axis, m_loc, x.shape[1], wire_dtype,
                     jnp.dtype(x.dtype), out_dtype, True)
    return fn(x, res)


def _q_ar_fwd(mesh, axis, wire_dtype, out_dtype, x, res):
    out = _q_all_reduce(mesh, axis, wire_dtype, out_dtype, x, res)
    return out, jnp.zeros((0,), x.dtype)


def _q_ar_bwd(mesh, axis, wire_dtype, out_dtype, wit, cots):
    dout, _ = cots          # residual cotangent is dropped (carried state)
    n = mesh.shape[axis]
    dx = jnp.tile(dout, (n, 1)).astype(wit.dtype)
    return dx, jnp.zeros_like(dx, dtype=jnp.float32)


_q_all_reduce.defvjp(_q_ar_fwd, _q_ar_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _q_all_reduce_plain(mesh, axis, wire_dtype, out_dtype, x):
    # the hot non-EF path: no residual input or output rides shard_map
    n = mesh.shape[axis]
    m_loc = x.shape[0] // (n * n)
    fn = _build_q_ar(mesh, axis, m_loc, x.shape[1], wire_dtype,
                     jnp.dtype(x.dtype), out_dtype, False)
    return fn(x)


def _q_arp_fwd(mesh, axis, wire_dtype, out_dtype, x):
    return _q_all_reduce_plain(mesh, axis, wire_dtype, out_dtype, x), \
        jnp.zeros((0,), x.dtype)


def _q_arp_bwd(mesh, axis, wire_dtype, out_dtype, wit, dout):
    n = mesh.shape[axis]
    return (jnp.tile(dout, (n, 1)).astype(wit.dtype),)


_q_all_reduce_plain.defvjp(_q_arp_fwd, _q_arp_bwd)


def quantized_all_reduce(
    x: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    wire_dtype: str = "fp8",
    out_dtype=None,
    residual: jax.Array | None = None,
):
    """AllReduce with both hops quantized (packed exchange + packed AG
    return), and the ERROR-FEEDBACK option: pass ``residual`` (zeros,
    or the residual a previous call returned) and the call returns
    ``(out, new_residual)`` — folding the residual into the next call's
    input bounds the drift of repeated quantized reductions
    (``lang.quant.ef_quantize_rows``; pinned by the convergence test).
    Without ``residual`` the call returns ``out`` alone.

    Contract matches ``comm.all_reduce``: ``x`` global (n*M, R) stacked
    partials, out (M, R) replicated."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    n = mesh.shape[axis]
    with_res = residual is not None
    if not quant.is_quantized(wire_dtype):
        from .allreduce import all_reduce

        if with_res:
            # the exact wire still owes the carry accumulated by earlier
            # quantized calls: fold it in; what the input-dtype cast
            # cannot represent stays in the residual (the EF invariant)
            xf = x.astype(jnp.float32) + residual.astype(jnp.float32)
            xr = xf.astype(x.dtype)
            return (all_reduce(xr, mesh, axis, out_dtype=out_dtype),
                    xf - xr.astype(jnp.float32))
        return all_reduce(x, mesh, axis, out_dtype=out_dtype)
    if n == 1:
        if with_res:
            xc = x.astype(jnp.float32) + residual.astype(jnp.float32)
            out = quant.roundtrip_rows(xc, wire_dtype, out_dtype=out_dtype)
            return out, xc - out.astype(jnp.float32)
        return quant.roundtrip_rows(x, wire_dtype, out_dtype=out_dtype)
    m_stack = x.shape[0]
    if m_stack % n or (m_stack // n) % n:
        raise ValueError(
            f"dim0 {m_stack} must be divisible by {axis}^2 = {n * n}")

    def make_verify(integrity):
        return lambda out: integrity.verify_reduce_q(
            f"all_reduce_{wire_dtype}", x,
            out[0] if with_res else out, n, wire_dtype,
            residual=residual if with_res else None, two_hop=True)

    if with_res:
        return _wrapped(
            "all_reduce", mesh, axis, wire_dtype, x,
            lambda: _q_all_reduce(mesh, axis, wire_dtype, out_dtype,
                                  x, residual),
            make_verify=make_verify,
        )
    return _wrapped(
        "all_reduce", mesh, axis, wire_dtype, x,
        lambda: _q_all_reduce_plain(mesh, axis, wire_dtype, out_dtype, x),
        make_verify=make_verify,
    )


def _wrapped(op: str, mesh, axis, wire_dtype, x, core, *, make_verify):
    """The shared eager instrumentation of the XLA-exchange quantized
    variants (RS/AR — whose wire is ``lax.all_to_all`` inside the
    shard_map, invisible to the Pallas entries' wrappers): obs wire-byte
    accounting of the PACKED bytes, and consumer-side integrity
    verification against the codec-aware golden
    (``integrity.verify_reduce_q``).  ``make_verify(integrity)`` builds
    the verifier lazily so the disabled path never imports it."""
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer

    n = mesh.shape[axis]
    m_stack, r = x.shape
    m_loc = m_stack // (n * n)
    w = quant.packed_width(r, wire_dtype)
    chunk_bytes = m_loc * w
    eager = not is_tracer(x)
    if eager and resilience.integrity.enabled():
        core = resilience.integrity.checked(
            f"{op}_{wire_dtype}", core, ranks=n,
            verify=make_verify(resilience.integrity))
    if eager and (obs.enabled() or obs.flight.enabled()):
        wire = (n - 1) * chunk_bytes
        if op == "all_reduce":
            wire *= 2          # packed exchange + packed AG return hop
        return obs.comm_call(
            op, core,
            payload_bytes=m_loc * n * r * jnp.dtype(x.dtype).itemsize,
            wire_bytes=wire, chunks=2 * (n - 1) if op == "all_reduce"
            else n - 1,
            method=f"oneshot_{wire_dtype}", ranks=n,
        )
    return core()


# ---------------------------------------------------------------------------
# stacked partial GEMM: the producer half the quantized fused-GEMM
# compositions share (gemm_rs / gemm_ar / fused_mlp_ar with a quantized
# wire compute their local partial, then reduce through the quantized
# exchange above — the tuner decides per shape whether the halved wire
# beats the bf16 ring's compute overlap)


@functools.lru_cache(maxsize=None)
def _build_partial_gemm(mesh: Mesh, axis: str, m: int, k_loc: int,
                        n_dim: int, dtype, out_dtype):
    def local(a_loc, b_loc):
        return jnp.dot(a_loc, b_loc,
                       preferred_element_type=jnp.float32).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None))


def stacked_partial_gemm(a: jax.Array, b: jax.Array, mesh: Mesh,
                         axis: str, out_dtype=None) -> jax.Array:
    """Per-rank partial of a K-parallel GEMM, stacked: ``a`` (M, K)
    sharded dim 1, ``b`` (K, N) sharded dim 0 -> global (n*M, N) where
    rank r's block is its partial addend — exactly the input contract of
    :func:`quantized_reduce_scatter` / :func:`quantized_all_reduce`."""
    n = mesh.shape[axis]
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    fn = _build_partial_gemm(mesh, axis, a.shape[0], a.shape[1] // n,
                             b.shape[1], jnp.dtype(a.dtype), out_dtype)
    return fn(a, b)


# ---------------------------------------------------------------------------
# quantized EP all-to-all transports (the STE custom-vjp home — moved
# from layers/moe.py, generalized over wire dtypes)

# The u8 wire is an integer path — its cotangent is float0, which would
# silently FREEZE every gradient crossing the A2A.  The transports are
# therefore custom-vjp'd with a straight-through estimator: forward
# ships the quantized message, backward pulls the cotangent through the
# exact (padding-masked) permutation adjoint at FULL precision,
# ignoring the quantization error — the standard STE treatment of
# fake-quant wires.


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def quantized_ep_dispatch(mesh, axis, cfg, h, wire_dtype, x, splits):
    """EP dispatch with a quantized wire: pack -> ``ep_dispatch`` (the
    real Pallas A2A on the u8 message — integrity/obs see the quantized
    bytes) -> dequantize into the model dtype.  Straight-through
    backward (the padding-masked combine adjoint)."""
    from .all_to_all import ep_dispatch

    recv_u8, recv_splits = ep_dispatch(
        quant.pack_rows(x, wire_dtype), splits, mesh, axis, config=cfg
    )
    return quant.unpack_rows(recv_u8, h, wire_dtype, x.dtype), recv_splits


def _q_dispatch_fwd(mesh, axis, cfg, h, wire_dtype, x, splits):
    out = quantized_ep_dispatch(mesh, axis, cfg, h, wire_dtype, x, splits)
    return out, (splits, x.shape[0] // mesh.shape[axis],
                 jnp.zeros((0,), x.dtype))


def _q_dispatch_bwd(mesh, axis, cfg, h, wire_dtype, res, cots):
    import numpy as np

    from .all_to_all import ep_dispatch_adjoint

    splits, t_loc, wit = res
    d_recv, _ = cots
    dx = ep_dispatch_adjoint(d_recv.astype(wit.dtype), splits, mesh, axis,
                             token_dim=t_loc, config=cfg)
    return dx, np.zeros(splits.shape, dtype=jax.dtypes.float0)


quantized_ep_dispatch.defvjp(_q_dispatch_fwd, _q_dispatch_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5))
def quantized_ep_combine(mesh, axis, cfg, h, wire_dtype, token_dim, y,
                         splits):
    """EP combine with a quantized wire (see
    :func:`quantized_ep_dispatch`)."""
    from .all_to_all import ep_combine

    back_u8 = ep_combine(quant.pack_rows(y, wire_dtype), splits, mesh,
                         axis, token_dim=token_dim, config=cfg)
    return quant.unpack_rows(back_u8, h, wire_dtype, y.dtype)


def _q_combine_fwd(mesh, axis, cfg, h, wire_dtype, token_dim, y, splits):
    return quantized_ep_combine(
        mesh, axis, cfg, h, wire_dtype, token_dim, y, splits
    ), (splits, jnp.zeros((0,), y.dtype))


def _q_combine_bwd(mesh, axis, cfg, h, wire_dtype, token_dim, res, dback):
    import numpy as np

    from .all_to_all import ep_combine_adjoint

    splits, wit = res
    dy = ep_combine_adjoint(dback.astype(wit.dtype), splits, mesh, axis,
                            config=cfg)
    return dy, np.zeros(splits.shape, dtype=jax.dtypes.float0)


quantized_ep_combine.defvjp(_q_combine_fwd, _q_combine_bwd)
