"""ReduceScatter as a ring Pallas kernel.

Reference: ``python/triton_dist/kernels/nvidia/reduce_scatter.py`` (ring
reduce with TMA/non-TMA paths ``:688-882``, 2D intra+inter hierarchy,
``ReduceScatter2DContext:46``).  TPU design: a single ring kernel — at step
``s`` each device adds its local contribution to the partial sum received
from the left and forwards it right, so every chunk visits all ranks once
(bandwidth-optimal).  Double-buffered receive slots are protected by an
ACK credit protocol (a regular semaphore signalled back to the producer
after consumption) — the role the reference's per-tile barrier/flag arrays
play for its copy-engine path.

Semantics (functional): input global shape ``(n*M, R)`` over ``axis`` — each
device's shard is its (M, R) partial addend; output global ``(M, R)`` sharded
over ``axis`` — device r holds rows ``r*M/n:(r+1)*M/n`` of the element-wise
sum of all n partials.  Golden: ``x.reshape(n, M, R).sum(0)`` scattered.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from ..ops import blocks
from . import ring


@dataclasses.dataclass(frozen=True)
class ReduceScatterConfig:
    bm: int = 256   # add-pipeline tile rows
    bn: int = 512   # add-pipeline tile cols

    def clip(self, m_loc: int, r: int) -> "ReduceScatterConfig":
        return ReduceScatterConfig(
            bm=clip_block(self.bm, m_loc), bn=clip_block(self.bn, r)
        )


def _rs_ring_kernel(
    team: Team,
    m_loc: int,
    r_dim: int,
    cfg: ReduceScatterConfig,
    x_ref,       # (n*m_loc, r) local partial addends           [ANY]
    out_ref,     # (m_loc, r) reduced chunk                     [ANY]
    recv_buf,    # (2, m_loc, r) incoming partial slots         [HBM scratch]
    send_buf,    # (2, m_loc, r) outgoing accumulated slots     [HBM scratch]
    send_sems,   # (2,) per-slot send completion: a single byte-counting
                 # semaphore could be satisfied by a DIFFERENT send's bytes,
                 # voiding the slot-reuse guarantee; per-parity sems make
                 # each wait match the send it protects
    recv_sems,   # (2,) per-slot arrival
    ack_sems,    # (2,) per-slot consumption credits (REGULAR)
):
    me, n = team.rank(), team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)

    add = blocks.make_add_pipeline(m_loc, r_dim, cfg.bm, cfg.bn)

    def x_chunk(c):
        return x_ref.at[pl.ds(c * m_loc, m_loc)]

    dl.collective_prologue(team, neighbors_only=True)

    # step 0: our raw contribution to the chunk that travels farthest
    j0 = jax.lax.rem(me + n - 1, n)
    dl.remote_copy(x_chunk(j0), recv_buf.at[0], send_sems.at[0],
                   recv_sems.at[0], right_id)

    for s in range(1, n):
        j = jax.lax.rem(me + n - s - 1, n)   # chunk being accumulated here
        slot_in = (s - 1) % 2
        dl.wait_recv(recv_buf.at[slot_in], recv_sems.at[slot_in])
        last = s == n - 1
        if last:
            add(recv_buf.at[slot_in], x_chunk(j), out_ref)
        else:
            slot_out = s % 2
            if s >= 2:
                # local reuse: the step s-2 send from this slot must be done
                dl.wait_send(send_buf.at[slot_out], send_sems.at[slot_out])
                # remote reuse: right must have consumed what we sent into
                # its recv slot_out two steps ago
                dl.wait(ack_sems.at[slot_out], 1)
            add(recv_buf.at[slot_in], x_chunk(j), send_buf.at[slot_out])
            dl.remote_copy(send_buf.at[slot_out], recv_buf.at[slot_out],
                           send_sems.at[slot_out], recv_sems.at[slot_out],
                           right_id)
        # credit the producer: its send slot_in payload is consumed
        dl.notify(ack_sems.at[slot_in], left_id)

    # Drain so repeated invocations start balanced: per send parity exactly
    # one send is unawaited in-loop (two when n==2 collapses to parity 0
    # only), and the credits for the last two sends are outstanding.
    dl.wait_send(send_buf.at[0], send_sems.at[0])
    if n > 2:
        dl.wait_send(send_buf.at[1], send_sems.at[1])
    ring.rs_ack_drain(ack_sems, n)


def _build_rs_call(
    mesh: Mesh,
    axis: str,
    m_loc: int,
    r_dim: int,
    dtype: jnp.dtype,
    cfg: ReduceScatterConfig,
):
    """The bare per-device ring kernel: (n*m_loc, r) stacked partials in,
    (m_loc, r) reduced chunk out.  Must run inside a shard_map over
    ``axis`` (used directly by the hierarchical paths here and in
    ``allreduce``)."""
    team = Team.of(mesh, axis)
    compilation.verify_protocol("reduce_scatter", team.size)
    kernel = functools.partial(_rs_ring_kernel, team, m_loc, r_dim, cfg)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m_loc, r_dim), dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.HBM((2, m_loc, r_dim), dtype),
            pltpu.HBM((2, m_loc, r_dim), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("reduce_scatter"),
        ),
        interpret=compilation.interpret_mode(),
    )


@functools.lru_cache(maxsize=None)
def _build_reduce_scatter(
    mesh: Mesh,
    axis: str,
    m_loc: int,
    r_dim: int,
    dtype: jnp.dtype,
    cfg: ReduceScatterConfig,
):
    call = _build_rs_call(mesh, axis, m_loc, r_dim, dtype, cfg)
    return compilation.jit_shard_map(
        call, mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )


def hierarchical_reduce_scatter(
    x: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    config: ReduceScatterConfig | None = None,
) -> jax.Array:
    """Two-level ReduceScatter (ICI ring per slice + DCN ``psum_scatter``).
    Canonical implementation: ``comm.hierarchical`` (ISSUE 10); this name
    stays importable here for the historic call sites."""
    from .hierarchical import hierarchical_reduce_scatter as _hier

    return _hier(x, mesh, inner_axis, outer_axis, config=config)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _reduce_scatter_core(mesh, axis, cfg, x):
    n = mesh.shape[axis]
    fn = _build_reduce_scatter(
        mesh, axis, x.shape[0] // (n * n), x.shape[1], jnp.dtype(x.dtype),
        cfg,
    )
    return fn(x)


def _rs_fwd(mesh, axis, cfg, x):
    return _reduce_scatter_core(mesh, axis, cfg, x), jnp.zeros((0,), x.dtype)


def _rs_bwd(mesh, axis, cfg, wit, dout):
    # global semantics: out = x.reshape(n, M, R).sum(0) -> the adjoint
    # broadcasts the cotangent back over the n stacked partials
    n = mesh.shape[axis]
    return (jnp.tile(dout, (n, 1)).astype(wit.dtype),)


_reduce_scatter_core.defvjp(_rs_fwd, _rs_bwd)


def reduce_scatter(
    x: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: ReduceScatterConfig | None = None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Ring reduce-scatter over ``axis`` (reference host entry
    ``reduce_scatter.py:688-882``).

    ``x``: global ``(n*M, R)``, device r's shard = its (M, R) partial addend.
    Returns global ``(M, R)`` sharded over ``axis``: the element-wise sum,
    row-chunk r on device r.  Golden: ``x.reshape(n, M, R).sum(0)``.

    ``wire_dtype``: "bf16" (this ring), "int8"/"fp8" (the quantized
    one-shot exchange — ``comm.quantized.quantized_reduce_scatter``:
    quantize at the producer chunk, dequantize + f32-reduce at the
    consumer), or "auto" (tuner-resolved per shape/ranks/wire class).

    ``axis`` may be a 2-tuple ``(outer, inner)`` on a 2D multi-slice
    mesh: routes to ``comm.hierarchical``.
    """
    if isinstance(axis, (tuple, list)):
        from . import hierarchical

        outer_axis, inner_axis = axis
        return hierarchical.hierarchical_reduce_scatter(
            x, mesh, inner_axis, outer_axis, config=config)
    n = mesh.shape[axis]
    m_stack = x.shape[0]
    if m_stack % n:
        raise ValueError(f"dim0 {m_stack} not divisible by {axis}={n}")
    m_partial = m_stack // n          # per-device partial row count
    if n == 1:
        return x
    if wire_dtype != "bf16":
        from ..tune.autotuner import is_tracer as _q_is_tracer
        from . import quantized as _q

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "rs_wire", (tuple(x.shape), str(x.dtype)), mesh, axis,
                lambda wd: (lambda: reduce_scatter(x, mesh, axis,
                                                   config=config,
                                                   wire_dtype=wd)),
                tracing=_q_is_tracer(x),
            )
        if wire_dtype != "bf16":
            return _q.quantized_reduce_scatter(
                x, mesh, axis, wire_dtype=wire_dtype)
    if m_partial % n:
        raise ValueError(
            f"partial rows {m_partial} not divisible by {axis}={n}"
        )
    m_loc = m_partial // n            # output rows per device
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer

    if config is None:
        # add-pipeline tiles through the contextual tuner (VERDICT r5
        # next #5) — cached winner / measured / interpret-pinned
        # default, exactly like the GEMM ops' config=None path; the key
        # carries the axis's wire class (ISSUE 10) so winners cannot
        # leak across topologies
        from ..core import mesh as mesh_lib, platform
        from ..tune.autotuner import (
            collective_tile_candidates, resolve_config,
        )

        config = resolve_config(
            "rs_cfg",
            (m_partial, x.shape[1], str(x.dtype), n,
             mesh_lib.wire_class(mesh, axis), platform.device_kind()),
            collective_tile_candidates(ReduceScatterConfig, m_loc,
                                       x.shape[1]),
            ReduceScatterConfig().clip(m_loc, x.shape[1]),
            lambda c: (lambda: reduce_scatter(x, mesh, axis, config=c)),
            tracing=is_tracer(x),
        )
    cfg = config.clip(m_loc, x.shape[1])
    chunk_bytes = m_loc * x.shape[1] * jnp.dtype(x.dtype).itemsize
    core = lambda: _reduce_scatter_core(mesh, axis, cfg, x)  # noqa: E731
    eager = not is_tracer(x)  # eager calls only (see all_gather)
    if eager and resilience.integrity.enabled():
        # consumer-side re-reduction check (TDT_INTEGRITY=1): reductions
        # mix every peer's bytes, so a mismatch is detected-but-
        # unattributable (ladder yes, quarantine no)
        core = resilience.integrity.checked(
            "reduce_scatter", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_reduce(
                "reduce_scatter", x, out, n))
    if eager and resilience.enabled():
        core = resilience.guarded(
            "reduce_scatter", core, family="reduce_scatter", ranks=n,
            payload_bytes=chunk_bytes * n,
            fallback=lambda: resilience.fallbacks.xla_reduce_scatter(
                x, mesh, axis),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        return obs.comm_call(
            "reduce_scatter", core,
            payload_bytes=chunk_bytes * n,
            # ring: n-1 hops, each carrying one m_loc-row chunk
            wire_bytes=chunk_bytes * (n - 1), chunks=n - 1,
            method="ring", ranks=n,
        )
    return core()
